"""Legacy ``raft::spatial::knn`` API surface.

Reference: ``spatial/knn/knn.cuh`` (``knn_merge_parts`` :55, ``select_k``
:125, ``brute_force_knn`` :196) and ``spatial/knn/ann.cuh``
(``approx_knn_build_index`` / ``approx_knn_search`` — the runtime-
dispatched ANN entry points that route IVF-Flat/IVF-PQ/IVF-SQ through
FAISS in ``detail/ann_quantized.cuh:67-160``). Thin forwards over the
primary :mod:`raft_tpu.neighbors` implementations."""

from __future__ import annotations

from typing import Tuple, Union

import jax

from raft_tpu.neighbors.brute_force import (brute_force_knn, knn,
                                            knn_merge_parts)
from raft_tpu.neighbors.selection import select_k
from raft_tpu.neighbors import ivf_flat, ivf_pq

__all__ = [
    "brute_force_knn", "knn", "knn_merge_parts", "select_k",
    "approx_knn_build_index", "approx_knn_search",
]

_ANNIndex = Union[ivf_flat.Index, ivf_pq.Index]


def approx_knn_build_index(
    dataset,
    params: Union[ivf_flat.IndexParams, ivf_pq.IndexParams],
    res=None,
) -> _ANNIndex:
    """Build an ANN index, dispatching on the parameter struct's type —
    the role of the reference's ``knnIndexParam`` dynamic casts
    (``ann_quantized.cuh:78-103``)."""
    if isinstance(params, ivf_flat.IndexParams):
        return ivf_flat.build(dataset, params, res=res)
    if isinstance(params, ivf_pq.IndexParams):
        return ivf_pq.build(dataset, params, seed=0, res=res)
    raise TypeError(
        f"approx_knn_build_index: unknown params type {type(params).__name__}"
        " (want ivf_flat.IndexParams or ivf_pq.IndexParams)")


def approx_knn_search(
    index: _ANNIndex,
    queries,
    k: int,
    params: Union[ivf_flat.SearchParams, ivf_pq.SearchParams, None] = None,
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """Search a built ANN index (reference ``ann_quantized.cuh:106-160``)."""
    if isinstance(index, ivf_flat.Index):
        return ivf_flat.search(index, queries, k,
                               params or ivf_flat.SearchParams(), res=res)
    if isinstance(index, ivf_pq.Index):
        return ivf_pq.search(index, queries, k,
                             params or ivf_pq.SearchParams(), res=res)
    raise TypeError(
        f"approx_knn_search: unknown index type {type(index).__name__}")
