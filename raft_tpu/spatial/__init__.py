"""Legacy ``spatial`` namespace (reference ``raft/spatial/knn/**`` — the
older public API kept for cuML; ``raft::neighbors`` forwards into it,
SURVEY.md §2.7 "Legacy spatial::knn API"). Here the direction is
reversed: :mod:`raft_tpu.neighbors` is primary and this package
forwards, so downstream code written against either namespace works."""

from raft_tpu.spatial import knn

__all__ = ["knn"]
