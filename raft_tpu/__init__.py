"""raft_tpu — TPU-native reusable accelerated functions and tools.

A ground-up JAX/XLA/Pallas re-design of the capability surface of RAPIDS RAFT
(reference: /root/reference, branch-22.12 era): dense & sparse linear algebra,
pairwise distances, k-NN / ANN indexes (brute-force, IVF-Flat, IVF-PQ),
clustering, solvers, statistics, random generation, and multi-host
communicator infrastructure — built TPU-first:

  * MXU-shaped matmul formulations for the expanded distance family
  * Pallas kernels for fused epilogues (fused L2 argmin/top-k)
  * ``jax.sharding.Mesh`` + XLA collectives instead of NCCL/UCX
  * functional, jit-compatible APIs with static shapes

Layout mirrors the reference's area map (SURVEY.md §2):

  core/      handle/resources, mdarray-shaped views, logger, errors  (§2.1)
  comms/     communicator iface over XLA collectives                 (§2.2)
  distance/  20 pairwise metrics, fused L2 NN, gram kernels          (§2.3)
  linalg/    BLAS/solver wrappers, elementwise & reduction framework (§2.4)
  matrix/    gather, sort, slicing, math utilities                   (§2.5)
  sparse/    COO/CSR, convert/op/linalg/distance/neighbors/solver    (§2.6)
  neighbors/ brute-force & ANN indexes, top-k selection              (§2.7)
  cluster/   kmeans, balanced kmeans, single-linkage                 (§2.8)
  spectral/, solver/, label/, stats/, random/                        (§2.9)
  ops/       Pallas kernel tier
  parallel/  mesh utilities + multi-node-multi-device algorithms
"""

__version__ = "0.1.0"

from raft_tpu.core.resources import Resources, DeviceResources

# pylibraft spells the resource context ``Handle`` (common/handle.pyx:30)
Handle = DeviceResources

_SUBPACKAGES = (
    "cluster", "comms", "core", "distance", "label", "linalg", "matrix",
    "neighbors", "obs", "ops", "parallel", "random", "serve", "solver",
    "sparse",
    "spatial", "spectral", "stats", "util",
)

__all__ = [
    "Resources",
    "DeviceResources",
    "Handle",
    "__version__",
    *_SUBPACKAGES,
]


def __dir__():
    return sorted(set(list(globals()) + list(__all__)))


def __getattr__(name):
    # lazy subpackage import (PEP 562): `import raft_tpu` stays light but
    # `raft_tpu.neighbors...` works without explicit submodule imports
    if name in _SUBPACKAGES:
        import importlib
        return importlib.import_module(f"raft_tpu.{name}")
    raise AttributeError(f"module 'raft_tpu' has no attribute {name!r}")
