"""Rank-1 Cholesky update.

Reference: ``raft/linalg/cholesky_r1_update.cuh`` — incrementally extends a
Cholesky factor L of A[:n,:n] to cover A[:n+1,:n+1] given the new
row/column; used by kmeans++ and GP-style workloads. The TPU formulation is
the same algebra (one triangular solve + scalar): given lower L (n,n) and
new column a (n+1,), compute b = L⁻¹ a[:n], d = sqrt(a[n] - bᵀb).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.core.precision import matmul_precision


def cholesky_r1_update(l_factor, new_col, eps: float = 0.0, res=None
                       ) -> jax.Array:
    """Extend lower-triangular ``l_factor`` (n, n) with ``new_col``
    (n+1,) -> (n+1, n+1) factor. ``eps`` is added to the new diagonal
    entry before sqrt for numerical safety (reference's eps parameter)."""
    l_factor = as_array(l_factor).astype(jnp.float32)
    new_col = as_array(new_col).astype(jnp.float32)
    n = l_factor.shape[0]
    expects(new_col.shape[0] == n + 1, "cholesky_r1_update: need n+1 entries")
    if n == 0:
        return jnp.sqrt(jnp.maximum(new_col[:1, None], eps if eps > 0 else 0.0))
    b = jax.scipy.linalg.solve_triangular(l_factor, new_col[:n], lower=True)
    d2 = new_col[n] - jnp.dot(b, b, precision=matmul_precision()) + eps
    d = jnp.sqrt(jnp.maximum(d2, 0.0))
    top = jnp.concatenate([l_factor, jnp.zeros((n, 1), l_factor.dtype)], axis=1)
    bottom = jnp.concatenate([b, jnp.asarray([d], l_factor.dtype)])[None, :]
    return jnp.concatenate([top, bottom], axis=0)
