"""Elementwise lambda framework.

Reference: ``raft/linalg/{unary_op,binary_op,ternary_op,map,map_reduce,
eltwise,matrix_vector_op}.cuh`` + ``matrix/linewise_op.cuh`` — the CUDA
versions exist to give hand-written kernels vectorized IO; under XLA every
one of these is a fused elementwise HLO, so the framework here is a direct
functional surface whose value is API parity and the broadcast semantics of
``matrix_vector_op``/``linewise_op`` (Apply::ALONG_ROWS|ALONG_COLUMNS).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.linalg.reduce import Apply


def unary_op(x, op: Callable, res=None) -> jax.Array:
    return op(as_array(x))


def binary_op(x, y, op: Callable, res=None) -> jax.Array:
    return op(as_array(x), as_array(y))


def ternary_op(x, y, z, op: Callable, res=None) -> jax.Array:
    return op(as_array(x), as_array(y), as_array(z))


def map_(op: Callable, *arrays, res=None) -> jax.Array:
    """N-ary map (reference linalg/map.cuh)."""
    return op(*[as_array(a) for a in arrays])


def map_reduce(op: Callable, reduce_op: Callable, neutral, *arrays,
               res=None) -> jax.Array:
    """map_then_reduce (reference linalg/map_then_reduce.cuh): elementwise
    ``op`` then full reduction with ``reduce_op`` starting from
    ``neutral``."""
    mapped = op(*[as_array(a) for a in arrays])
    flat = mapped.reshape(-1)
    return jax.lax.reduce(flat, jnp.asarray(neutral, flat.dtype),
                          reduce_op, (0,))


# -- eltwise arithmetic (linalg/{add,subtract,multiply,divide,power,sqrt}.cuh)
def add(x, y, res=None):
    return as_array(x) + as_array(y)


def subtract(x, y, res=None):
    return as_array(x) - as_array(y)


def multiply(x, y, res=None):
    return as_array(x) * as_array(y)


def divide(x, y, res=None):
    return as_array(x) / as_array(y)


def power(x, y, res=None):
    return as_array(x) ** as_array(y)


def sqrt(x, res=None):
    return jnp.sqrt(as_array(x))


def eltwise_add(*xs, res=None):
    out = as_array(xs[0])
    for x in xs[1:]:
        out = out + as_array(x)
    return out


def init_arange(n: int, start=0, step=1, dtype=jnp.float32, res=None):
    """reference linalg/init.cuh (arange fill)."""
    return start + step * jnp.arange(n, dtype=dtype)


def mean_squared_error(a, b, weight: float = 1.0, res=None) -> jax.Array:
    """reference linalg/mean_squared_error.cuh."""
    a, b = as_array(a), as_array(b)
    d = (a - b).astype(jnp.float32)
    return weight * jnp.mean(d * d)


def matrix_vector_op(mat, vec, op: Callable = jnp.add,
                     apply: Apply = Apply.ALONG_ROWS,
                     bcast_along_rows: bool = None, res=None) -> jax.Array:
    """Broadcast a vector against every row or column of a matrix
    (reference linalg/matrix_vector_op.cuh).

    ``ALONG_ROWS``: vec has len n_cols, broadcast across rows (each row is
    combined with the whole vector). ``ALONG_COLUMNS``: vec has len n_rows.
    """
    mat, vec = as_array(mat), as_array(vec)
    if bcast_along_rows is not None:  # reference bool form
        apply = Apply.ALONG_ROWS if bcast_along_rows else Apply.ALONG_COLUMNS
    if apply == Apply.ALONG_ROWS:
        expects(vec.shape[0] == mat.shape[1],
                "matrix_vector_op: vec len %d != n_cols %d", vec.shape[0], mat.shape[1])
        return op(mat, vec[None, :])
    expects(vec.shape[0] == mat.shape[0],
            "matrix_vector_op: vec len %d != n_rows %d", vec.shape[0], mat.shape[0])
    return op(mat, vec[:, None])


def linewise_op(mat, op: Callable, along_lines: bool, *vecs, res=None) -> jax.Array:
    """Apply ``op(row_or_col_element, *vec_elements)`` line-wise (reference
    matrix/linewise_op.cuh). ``along_lines=True`` means vectors run along
    rows (length n_cols)."""
    mat = as_array(mat)
    vs = [as_array(v) for v in vecs]
    if along_lines:
        vs = [v[None, :] for v in vs]
    else:
        vs = [v[:, None] for v in vs]
    return op(mat, *vs)
