"""Least squares solvers — the reference's four algorithms
(``raft/linalg/lstsq.cuh``): lstsqSvdQR, lstsqSvdJacobi, lstsqEig
(normal equations via eigh), lstsqQR."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.core.mdarray import as_array


def _via_svd(a, b):
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    s_inv = jnp.where(s > 1e-7 * s[0], 1.0 / s, 0.0)
    return vt.T @ (s_inv * (u.T @ b))


def lstsq_svd_qr(a, b, res=None) -> jax.Array:
    """min ||Ax - b|| via SVD (reference lstsqSvdQR)."""
    return _via_svd(as_array(a).astype(jnp.float32),
                    as_array(b).astype(jnp.float32))


def lstsq_svd_jacobi(a, b, res=None) -> jax.Array:
    """Jacobi-SVD variant; same backend on TPU (reference lstsqSvdJacobi)."""
    return _via_svd(as_array(a).astype(jnp.float32),
                    as_array(b).astype(jnp.float32))


def lstsq_eig(a, b, res=None) -> jax.Array:
    """Normal-equations path: solve (AᵀA) x = Aᵀb via eigh (reference
    lstsqEig — the fastest reference path for well-conditioned systems)."""
    a = as_array(a).astype(jnp.float32)
    b = as_array(b).astype(jnp.float32)
    ata = a.T @ a
    atb = a.T @ b
    w, v = jnp.linalg.eigh(ata)
    w_inv = jnp.where(w > 1e-7 * jnp.max(w), 1.0 / w, 0.0)
    return v @ (w_inv * (v.T @ atb))


def lstsq_qr(a, b, res=None) -> jax.Array:
    """QR path: R x = Qᵀ b (reference lstsqQR)."""
    a = as_array(a).astype(jnp.float32)
    b = as_array(b).astype(jnp.float32)
    q, r = jnp.linalg.qr(a)
    return jax.scipy.linalg.solve_triangular(r, q.T @ b, lower=False)
