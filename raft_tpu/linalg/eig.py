"""Symmetric eigendecomposition.

Reference: ``raft/linalg/eig.cuh:130-199`` — ``eig_dc`` (cuSOLVER
divide-and-conquer), ``eig_dc_selective`` (syevdx subset), ``eig_jacobi``
(Jacobi with tolerance/sweeps). On TPU ``jnp.linalg.eigh`` is the
backend for all three (XLA's eigh is itself a QDWH/Jacobi-family method);
``eig_jacobi`` additionally offers a pure-JAX cyclic-Jacobi loop used when
callers need the tol/sweeps contract.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array


def eig_dc(a, res=None) -> Tuple[jax.Array, jax.Array]:
    """Full symmetric eig: returns (eigvals ascending, eigvecs columns)."""
    a = as_array(a)
    w, v = jnp.linalg.eigh(a)
    return w, v


def eig_dc_selective(a, n_eig_vals: int, largest: bool = True, res=None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Subset of eigenpairs (reference EigVecMemUsage/syevdx path).

    Returns ``n_eig_vals`` pairs; ``largest`` picks which end of the
    spectrum (the reference selects via il/iu range).
    """
    a = as_array(a)
    n = a.shape[0]
    expects(0 < n_eig_vals <= n, "eig_dc_selective: invalid n_eig_vals")
    w, v = jnp.linalg.eigh(a)
    if largest:
        w, v = w[n - n_eig_vals:], v[:, n - n_eig_vals:]
    else:
        w, v = w[:n_eig_vals], v[:, :n_eig_vals]
    return w, v


def eig_jacobi(a, tol: float = 1e-7, sweeps: int = 15, res=None
               ) -> Tuple[jax.Array, jax.Array]:
    """One-sided cyclic Jacobi eigensolver as a ``lax.while_loop``.

    Matches the reference's tol/sweeps contract (eig.cuh:180-199). For
    typical sizes callers should prefer :func:`eig_dc`; this exists for
    parity and for very small matrices where Jacobi converges quickly.
    """
    a = as_array(a).astype(jnp.float32)
    n = a.shape[0]

    def off(m):
        return jnp.sqrt(jnp.sum(jnp.tril(m, -1) ** 2) * 2.0)

    def rotate(carry):
        m, v, sweep = carry

        def rot_pq(mv, pq):
            m, v = mv
            p, q = pq
            app, aqq, apq = m[p, p], m[q, q], m[p, q]
            theta = 0.5 * jnp.arctan2(2.0 * apq, aqq - app)
            c, s = jnp.cos(theta), jnp.sin(theta)
            g = jnp.eye(n, dtype=m.dtype)
            g = g.at[p, p].set(c).at[q, q].set(c).at[p, q].set(s).at[q, p].set(-s)
            m = g.T @ m @ g
            v = v @ g
            return (m, v), None

        idx = jnp.asarray([(p, q) for p in range(n) for q in range(p + 1, n)],
                          dtype=jnp.int32)
        (m, v), _ = lax.scan(rot_pq, (m, v), idx)
        return m, v, sweep + 1

    def cond(carry):
        m, _, sweep = carry
        return jnp.logical_and(off(m) > tol, sweep < sweeps)

    m, v, _ = lax.while_loop(cond, rotate,
                             (a, jnp.eye(n, dtype=a.dtype), jnp.asarray(0)))
    w = jnp.diag(m)
    order = jnp.argsort(w)
    return w[order], v[:, order]
