"""Dense linear algebra (SURVEY.md §2.4, reference ``raft/linalg``).

The reference's ~35 ops in four groups: BLAS wrappers (cuBLAS), solver
wrappers (cuSOLVER), the elementwise lambda framework, and the reduction
framework. On TPU the BLAS group is XLA-native (``jnp.dot`` hits the MXU),
solvers use ``jnp.linalg``/``jax.scipy`` plus bespoke JAX loops where the
reference used Jacobi/rank-1 variants, and both frameworks keep the
reference's lambda-parameterized shape (main_op/reduce_op/final_op).
"""

from raft_tpu.linalg.blas import gemm, gemv, axpy, dot, transpose
from raft_tpu.linalg.eig import eig_dc, eig_dc_selective, eig_jacobi
from raft_tpu.linalg.svd import (
    svd_qr,
    svd_eig,
    svd_jacobi,
    svd_reconstruction,
    rsvd,
)
from raft_tpu.linalg.qr import qr_get_q, qr_get_qr
from raft_tpu.linalg.lstsq import lstsq_svd_qr, lstsq_svd_jacobi, lstsq_eig, lstsq_qr
from raft_tpu.linalg.cholesky import cholesky_r1_update
from raft_tpu.linalg.elementwise import (
    unary_op,
    binary_op,
    ternary_op,
    map_,
    map_reduce,
    add,
    subtract,
    multiply,
    divide,
    power,
    sqrt,
    eltwise_add,
    mean_squared_error,
    matrix_vector_op,
    linewise_op,
    init_arange,
)
from raft_tpu.linalg.reduce import (
    Apply,
    reduce,
    coalesced_reduction,
    strided_reduction,
    norm,
    NormType,
    row_norm,
    col_norm,
    reduce_rows_by_key,
    reduce_cols_by_key,
    normalize_rows,
)

__all__ = [
    "gemm", "gemv", "axpy", "dot", "transpose",
    "eig_dc", "eig_dc_selective", "eig_jacobi",
    "svd_qr", "svd_eig", "svd_jacobi", "svd_reconstruction", "rsvd",
    "qr_get_q", "qr_get_qr",
    "lstsq_svd_qr", "lstsq_svd_jacobi", "lstsq_eig", "lstsq_qr",
    "cholesky_r1_update",
    "unary_op", "binary_op", "ternary_op", "map_", "map_reduce",
    "add", "subtract", "multiply", "divide", "power", "sqrt", "eltwise_add",
    "mean_squared_error", "matrix_vector_op", "linewise_op", "init_arange",
    "Apply", "reduce", "coalesced_reduction", "strided_reduction",
    "norm", "NormType", "row_norm", "col_norm",
    "reduce_rows_by_key", "reduce_cols_by_key", "normalize_rows",
]
