"""QR decomposition (reference ``raft/linalg/qr.cuh``: qrGetQ / qrGetQR
over cuSOLVER geqrf/orgqr)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.mdarray import as_array


def qr_get_q(a, res=None) -> jax.Array:
    a = as_array(a)
    q, _ = jnp.linalg.qr(a)
    return q


def qr_get_qr(a, res=None) -> Tuple[jax.Array, jax.Array]:
    a = as_array(a)
    return jnp.linalg.qr(a)
