"""Singular value decomposition family.

Reference: ``raft/linalg/svd.cuh:44-370`` — ``svdQR`` (cuSOLVER gesvd),
``svdEig`` (via eigh of AᵀA, the fast path for tall-skinny), ``svdJacobi``
(gesvdj), ``svdReconstruction``, plus ``rsvd.cuh`` randomized SVD.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.mdarray import as_array
from raft_tpu.random.rng import KeyLike, _key


def svd_qr(a, gen_u: bool = True, gen_v: bool = True, res=None):
    """Full SVD: returns (U, S, V) with A = U diag(S) Vᵀ.

    Note the reference returns V (not Vᵀ); we match that convention.
    """
    a = as_array(a)
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return (u if gen_u else None), s, (vt.T if gen_v else None)


def svd_eig(a, res=None):
    """SVD via eigendecomposition of AᵀA (reference svdEig, svd.cuh:109) —
    the tall-skinny fast path: one (k,k) eigh instead of an (m,k) svd."""
    a = as_array(a).astype(jnp.float32)
    ata = a.T @ a
    w, v = jnp.linalg.eigh(ata)  # ascending
    w = w[::-1]
    v = v[:, ::-1]
    s = jnp.sqrt(jnp.maximum(w, 0.0))
    u = (a @ v) / jnp.where(s[None, :] == 0.0, 1.0, s[None, :])
    return u, s, v


def svd_jacobi(a, tol: float = 1e-7, sweeps: int = 15, res=None):
    """Jacobi-flavoured SVD (reference svdJacobi). XLA's svd is already a
    Jacobi-family iteration on TPU; tol/sweeps accepted for API parity."""
    return svd_qr(a)


def svd_reconstruction(u, s, v, res=None) -> jax.Array:
    """A ≈ U diag(S) Vᵀ (reference svdReconstruction, svd.cuh:246)."""
    u, s, v = as_array(u), as_array(s), as_array(v)
    return (u * s[None, :]) @ v.T


def rsvd(a, k: int, p: Optional[int] = None, n_iter: int = 2,
         seed: KeyLike = 0, res=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Randomized SVD (reference ``linalg/rsvd.cuh``: range finder via
    gaussian sketch + power iterations + small exact SVD).

    ``p`` is the oversampling (reference PC_perc/UpS_perc expressed
    directly); returns rank-k (U, S, V).
    """
    a = as_array(a).astype(jnp.float32)
    m, n = a.shape
    if p is None:
        p = max(5, k // 10)
    ell = min(n, k + p)
    omega = jax.random.normal(_key(seed), (n, ell), dtype=a.dtype)
    y = a @ omega
    # power iterations with QR re-orthonormalization for spectral accuracy
    for _ in range(n_iter):
        q, _ = jnp.linalg.qr(y)
        z = a.T @ q
        q, _ = jnp.linalg.qr(z)
        y = a @ q
    q, _ = jnp.linalg.qr(y)
    b = q.T @ a  # (ell, n)
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    return u[:, :k], s[:k], vt[:k].T
