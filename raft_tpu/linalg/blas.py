"""BLAS-level ops.

Reference: ``raft/linalg/gemm.cuh:55`` (cuBLAS gemm with alpha/beta and
transpose flags), ``gemv.cuh``, ``axpy.cuh``, ``transpose.cuh``
(cublasgeam). On TPU each is one XLA op; gemm accumulates fp32 on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.mdarray import as_array
from raft_tpu.core.precision import matmul_precision


def gemm(a, b, alpha: float = 1.0, beta: float = 0.0, c=None,
         trans_a: bool = False, trans_b: bool = False, res=None) -> jax.Array:
    """C = alpha * op(A) @ op(B) + beta * C (reference linalg/gemm.cuh:55)."""
    a, b = as_array(a), as_array(b)
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32,
                          precision=matmul_precision())
    out = alpha * out
    if c is not None and beta != 0.0:
        out = out + beta * as_array(c)
    return out.astype(a.dtype)


def gemv(a, x, alpha: float = 1.0, beta: float = 0.0, y=None,
         trans: bool = False, res=None) -> jax.Array:
    """y = alpha * op(A) @ x + beta * y (reference linalg/gemv.cuh)."""
    a, x = as_array(a), as_array(x)
    if trans:
        a = a.T
    out = alpha * (a @ x)
    if y is not None and beta != 0.0:
        out = out + beta * as_array(y)
    return out


def axpy(alpha: float, x, y, res=None) -> jax.Array:
    """alpha * x + y (reference linalg/axpy.cuh)."""
    return alpha * as_array(x) + as_array(y)


def dot(x, y, res=None) -> jax.Array:
    """<x, y> (reference linalg/dot.cuh)."""
    return jnp.dot(as_array(x), as_array(y),
                   preferred_element_type=jnp.float32,
                   precision=matmul_precision())


def transpose(a, res=None) -> jax.Array:
    """Out-of-place transpose (reference linalg/transpose.cuh; XLA fuses
    this into consumers rather than materializing, which is strictly better
    than the cublasgeam copy)."""
    return as_array(a).T
