"""Reduction framework.

Reference: ``raft/linalg/reduce.cuh`` with ``coalesced_reduction.cuh``
(reduce along the contiguous dim) and ``strided_reduction.cuh`` (the
other), all parameterized by main_op (per-element), reduce_op (pairwise),
final_op (epilogue); plus ``norm.cuh`` (L1/L2/Linf row/col norms),
``reduce_rows_by_key.cuh`` and ``reduce_cols_by_key.cuh``.

On TPU both reduction orientations lower to the same XLA reduce (layout is
the compiler's concern — the coalesced/strided distinction is CUDA-physical
and intentionally collapses here); by-key reductions use segment_sum, the
XLA-native equivalent of the reference's atomic scatter-accumulate.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array


class Apply(enum.IntEnum):
    """reference linalg_types.hpp Apply::ALONG_ROWS|ALONG_COLUMNS."""

    ALONG_ROWS = 0
    ALONG_COLUMNS = 1


class NormType(enum.IntEnum):
    """reference linalg/norm_types.hpp."""

    L1Norm = 0
    L2Norm = 1
    LinfNorm = 2


_id = lambda x: x


def reduce(data, along_rows: bool = True,
           main_op: Callable = _id,
           reduce_op: str = "add",
           final_op: Callable = _id,
           init=None, res=None) -> jax.Array:
    """Row- or column-wise lambda reduction (reference linalg/reduce.cuh).

    ``along_rows=True`` reduces each row to a scalar (output length m).
    ``reduce_op`` is one of {"add", "min", "max"} — the set the reference
    kernels are instantiated with. ``init`` seeds the accumulator exactly
    as in the reference (always combined when given); when omitted it
    defaults to the op's neutral element (0 / +inf / -inf).
    """
    data = as_array(data)
    mapped = main_op(data)
    axis = 1 if along_rows else 0
    if reduce_op == "add":
        out = jnp.sum(mapped, axis=axis)
        if init is not None:
            out = out + init
    elif reduce_op == "min":
        out = jnp.min(mapped, axis=axis)
        if init is not None:
            out = jnp.minimum(out, init)
    elif reduce_op == "max":
        out = jnp.max(mapped, axis=axis)
        if init is not None:
            out = jnp.maximum(out, init)
    else:
        raise ValueError(f"unsupported reduce_op {reduce_op}")
    return final_op(out)


def coalesced_reduction(data, main_op: Callable = _id, reduce_op: str = "add",
                        final_op: Callable = _id, init=None, res=None):
    """Reduce along the contiguous (last) dim — row-wise for row-major
    (reference coalesced_reduction.cuh)."""
    return reduce(data, True, main_op, reduce_op, final_op, init, res)


def strided_reduction(data, main_op: Callable = _id, reduce_op: str = "add",
                      final_op: Callable = _id, init=None, res=None):
    """Reduce along the strided (first) dim — column-wise for row-major
    (reference strided_reduction.cuh)."""
    return reduce(data, False, main_op, reduce_op, final_op, init, res)


def norm(data, norm_type: NormType, along_rows: bool = True,
         sqrt: bool = False, res=None) -> jax.Array:
    """L1/L2/Linf norms per row or column (reference linalg/norm.cuh;
    note reference L2 returns the *squared* norm unless sqrt=true)."""
    data = as_array(data).astype(jnp.float32)
    axis = 1 if along_rows else 0
    if norm_type == NormType.L1Norm:
        out = jnp.sum(jnp.abs(data), axis=axis)
    elif norm_type == NormType.L2Norm:
        out = jnp.sum(data * data, axis=axis)
    elif norm_type == NormType.LinfNorm:
        out = jnp.max(jnp.abs(data), axis=axis)
    else:
        raise ValueError(f"unknown norm type {norm_type}")
    return jnp.sqrt(out) if sqrt else out


def row_norm(data, norm_type: NormType = NormType.L2Norm, sqrt: bool = False,
             res=None):
    return norm(data, norm_type, True, sqrt, res)


def col_norm(data, norm_type: NormType = NormType.L2Norm, sqrt: bool = False,
             res=None):
    return norm(data, norm_type, False, sqrt, res)


def normalize_rows(data, res=None) -> jax.Array:
    """Row L2-normalization (reference matrix/normalize used by cosine
    preprocessing, spatial/knn/detail/processing.cuh)."""
    data = as_array(data)
    n = jnp.sqrt(jnp.sum(data.astype(jnp.float32) ** 2, axis=1, keepdims=True))
    return (data / jnp.where(n == 0.0, 1.0, n)).astype(data.dtype)


def reduce_rows_by_key(data, keys, n_keys: Optional[int] = None,
                       weights=None, res=None) -> jax.Array:
    """Sum rows sharing a key → (n_keys, n_cols) (reference
    linalg/reduce_rows_by_key.cuh). The CUDA version scatter-adds with
    atomics; segment_sum is the deterministic XLA equivalent."""
    data = as_array(data)
    keys = as_array(keys).astype(jnp.int32)
    expects(keys.shape[0] == data.shape[0], "reduce_rows_by_key: key/row mismatch")
    if n_keys is None:
        n_keys = int(jax.device_get(jnp.max(keys))) + 1
    if weights is not None:
        data = data * as_array(weights)[:, None]
    return jax.ops.segment_sum(data, keys, num_segments=n_keys)


def reduce_cols_by_key(data, keys, n_keys: Optional[int] = None, res=None
                       ) -> jax.Array:
    """Sum columns sharing a key → (n_rows, n_keys) (reference
    linalg/reduce_cols_by_key.cuh)."""
    data = as_array(data)
    keys = as_array(keys).astype(jnp.int32)
    expects(keys.shape[0] == data.shape[1], "reduce_cols_by_key: key/col mismatch")
    if n_keys is None:
        n_keys = int(jax.device_get(jnp.max(keys))) + 1
    return jax.ops.segment_sum(data.T, keys, num_segments=n_keys).T
