"""Compaction: fold the delta segment + tombstones into the main lists.

Two modes (``MutateConfig.compact_mode``):

* **fold** (default) — coarse centers stay FROZEN: tombstoned slots are
  purged (their ``lists_indices`` entries flip to -1, the universal
  dead-slot sentinel every scan tier masks on), then the live delta
  rows ride the family's ``extend`` path (label against the trained
  centers, encode with the frozen codebooks/rotation, one re-bucketize
  of the combined set). O(n) re-bucket, no re-training — the
  steady-state mode a serving system can afford on every compaction.
* **rebuild** — from-scratch re-train on the reconstructed live corpus
  (IVF-Flat only: flat lists dequantize back to the exact rows). Routes
  through ``host_memory.build_streaming`` when a chunk budget is set
  (O(chunk) device memory — PR 4's streaming ingestion) or through
  ``parallel.ivf.sharded_ivf_flat_build`` when a mesh is passed (the
  sharded list-layout build, landing directly in the serving layout) —
  the periodic center-refresh that bounds drift after many folds.

Everything here runs on the COMPACTOR thread against an immutable
snapshot (rows + tombstone set frozen under the index lock); the
serving path never blocks on any of it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

import jax.numpy as jnp

from raft_tpu.core.error import expects

__all__ = ["fold", "purge", "reconstruct_rows"]


def _family(index) -> str:
    from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq
    if isinstance(index, ivf_flat.Index):
        return "ivf_flat"
    if isinstance(index, ivf_pq.Index):
        return "ivf_pq"
    if isinstance(index, ivf_bq.Index):
        return "ivf_bq"
    expects(False, "mutate: unsupported index type %s (want ivf_flat/"
            "ivf_pq/ivf_bq Index)", type(index).__name__)


def purge(index, tombstoned_ids):
    """Drop tombstoned rows from the main lists WITHOUT re-bucketing:
    their ``lists_indices`` slots flip to -1 — the pad sentinel every
    scan tier already masks to +inf — and the per-list sizes / logical
    size are refreshed. Returns a new Index sharing the untouched
    arrays (cheap; the stale payload bytes in dead slots are never
    scored)."""
    tombs = np.asarray(sorted(tombstoned_ids), dtype=np.int64)
    if tombs.size == 0:
        return index, 0
    ids = np.asarray(index.lists_indices)
    dead = (ids >= 0) & np.isin(ids, tombs)
    n_removed = int(dead.sum())
    if n_removed == 0:
        return index, 0
    new_ids = np.where(dead, np.int32(-1), ids)
    sizes = (new_ids >= 0).sum(axis=1).astype(np.int32)
    return dataclasses.replace(
        index, lists_indices=jnp.asarray(new_ids),
        list_sizes=jnp.asarray(sizes),
        size=int(index.size) - n_removed), n_removed


def reconstruct_rows(index):
    """(rows, ids) of every live slot of an IVF-Flat index, dequantized
    to f32 — the rebuild-mode corpus. Row order is list-major (the
    bucketize order), which is irrelevant to a re-train."""
    from raft_tpu.neighbors import ivf_flat
    expects(isinstance(index, ivf_flat.Index),
            "mutate: rebuild compaction reconstructs rows from flat "
            "lists only — use compact_mode='fold' for ivf_pq/ivf_bq")
    ids = np.asarray(index.lists_indices).reshape(-1)
    valid = ids >= 0
    data = np.asarray(index.lists_data).reshape(-1, index.dim)[valid]
    if data.dtype == np.int8:
        data = data.astype(np.float32) * float(index.scale)
    else:
        data = np.asarray(data, np.float32)
    return data, ids[valid].astype(np.int32)


def fold(index, delta_rows, delta_ids, tombstoned_ids,
         mode: str = "fold", mesh=None, axis: str = "data",
         stream_chunk: int = 0, params=None):
    """Produce the next epoch's index from the frozen snapshot: purge
    tombstones, then absorb the live delta rows. See the module doc for
    the two modes; ``mesh``/``stream_chunk`` select the PR 4 sharded /
    streaming build machinery under ``mode='rebuild'``."""
    from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq
    fam = _family(index)
    delta_rows = np.asarray(delta_rows, np.float32)
    delta_ids = np.asarray(delta_ids, np.int32)
    expects(delta_rows.shape[0] == delta_ids.shape[0],
            "mutate.fold: %d rows vs %d ids", delta_rows.shape[0],
            delta_ids.shape[0])
    purged, _removed = purge(index, tombstoned_ids)
    if mode == "rebuild":
        return _rebuild(purged, delta_rows, delta_ids, mesh=mesh,
                        axis=axis, stream_chunk=stream_chunk,
                        params=params)
    expects(mode == "fold", "mutate.fold: unknown mode %r", mode)
    if delta_rows.shape[0] == 0:
        return purged
    ext = {"ivf_flat": ivf_flat.extend, "ivf_pq": ivf_pq.extend,
           "ivf_bq": ivf_bq.extend}[fam]
    return ext(purged, delta_rows, new_indices=delta_ids)


def _rebuild(purged, delta_rows, delta_ids, mesh=None,
             axis: str = "data", stream_chunk: int = 0, params=None):
    """From-scratch re-train on the live corpus (flat only): the
    recall yardstick every fold-mode compaction is benchmarked against
    (``bench_suite.bench_mutate``), and the periodic center refresh."""
    from raft_tpu.neighbors import ivf_flat
    old_rows, old_ids = reconstruct_rows(purged)
    rows = np.concatenate([old_rows, delta_rows], axis=0)
    ids = np.concatenate([old_ids, delta_ids])
    if params is None:
        params = ivf_flat.IndexParams(
            n_lists=purged.n_lists, metric=purged.metric,
            kmeans_n_iters=10)
    if mesh is not None:
        # PR 4 sharded list-layout build: lands directly in the
        # list-sharded serving layout, then the ids are rewritten to
        # the mutable id space (the sharded build numbers rows 0..n)
        from raft_tpu.parallel.ivf import sharded_ivf_flat_build
        built = sharded_ivf_flat_build(rows, params=params, mesh=mesh,
                                       axis=axis)
        return _renumber(built, ids)
    if stream_chunk > 0:
        from raft_tpu.neighbors.host_memory import build_streaming

        def chunks():
            for s in range(0, rows.shape[0], stream_chunk):
                yield rows[s:s + stream_chunk]

        built = build_streaming(chunks(), params=params,
                                train_rows=min(rows.shape[0],
                                               4 * stream_chunk))
        built = _as_device_flat(built, purged.metric)
        return _renumber(built, ids)
    return _renumber(ivf_flat.build(rows, params), ids)


def _renumber(index, row_ids):
    """Rewrite a freshly built index's 0..n-1 slot ids to the mutable
    id space (``row_ids[slot]``); pads stay -1."""
    lists = np.asarray(index.lists_indices)
    out = np.where(lists >= 0,
                   np.asarray(row_ids, np.int32)[np.clip(lists, 0,
                                                         None)],
                   np.int32(-1))
    return dataclasses.replace(index, lists_indices=jnp.asarray(out))


def _as_device_flat(host_index, metric):
    """Materialize a host-resident streaming build as a device
    ivf_flat.Index (the rebuild path serves device-resident)."""
    from raft_tpu.neighbors import ivf_flat
    if isinstance(host_index, ivf_flat.Index):
        return host_index
    ids = np.asarray(host_index.lists_indices)
    return ivf_flat.Index(
        centers=jnp.asarray(host_index.centers),
        lists_data=jnp.asarray(host_index.lists_data),
        lists_indices=jnp.asarray(ids),
        lists_norms=jnp.asarray(host_index.lists_norms),
        list_sizes=jnp.asarray((ids >= 0).sum(axis=1).astype(np.int32)),
        metric=metric, size=int(host_index.size),
        scale=float(getattr(host_index, "scale", 1.0)))
