"""raft_tpu.mutate — live mutable indexes over the serving stack.

The capability gap this closes (ROADMAP item 3): RAFT's IVF indexes
are build-once, but a served corpus changes while serving — and until
now every upsert or delete was a full rebuild. ``MutableIndex`` wraps
any built ivf_flat / ivf_pq / ivf_bq index with

* an append-only **delta segment** on a pre-warmed fixed-capacity
  shape ladder (no mutation ever triggers an XLA recompile — the
  ``serve/ladder.py`` discipline applied to growing state, the Ragged
  Paged Attention move, arxiv 2604.15464),
* **tombstone bitmaps** for deletes, filtered at postprocess inside
  the compiled search program (upsert = tombstone + append),
* a **background compactor** that folds the delta into the main lists
  (family ``extend`` with frozen centers, or a from-scratch rebuild
  through PR 4's streaming/sharded build machinery) and atomically
  swaps epochs under live traffic — zero serving downtime, zero
  steady-state compiles (the next epoch's program grid is pre-warmed
  on the compactor thread before the swap).

Quick use::

    from raft_tpu import mutate, serve
    from raft_tpu.neighbors import ivf_flat

    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=1024))
    m = mutate.MutableIndex(index, k=10)
    srv = serve.SearchServer.from_index(m, sample_queries, k=10)
    comp = mutate.Compactor(m)           # background folds
    m.upsert(new_rows); m.delete([12, 99])
    dists, ids = srv.search(queries)     # live view, through the batcher
    comp.close(); srv.close()

Observability rides the ``raft.mutate.*`` taxonomy
(docs/observability.md); ``/healthz`` degrades when the delta hits its
top rung with no compaction running. Architecture + capacity planning:
docs/mutability.md.
"""

from raft_tpu.mutate.compactor import Compactor
from raft_tpu.mutate.mutable import (MutableIndex, build_dist_serve_ladder,
                                     build_serve_ladder)
from raft_tpu.mutate.types import DeltaFullError, MutateConfig
from raft_tpu.mutate.wal import MutationWAL

__all__ = [
    "Compactor",
    "DeltaFullError",
    "MutableIndex",
    "MutateConfig",
    "MutationWAL",
    "build_dist_serve_ladder",
    "build_serve_ladder",
]
