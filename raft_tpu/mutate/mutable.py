"""MutableIndex: live upsert/delete over a built IVF index.

RAFT's IVF indexes are build-once artifacts; serving millions of users
means the corpus changes *while* serving (ROADMAP item 3). This module
makes any built ivf_flat/ivf_pq/ivf_bq index mutable without ever
paying a steady-state XLA compile:

* **delta segment** — upserts append into a fixed-capacity flat buffer
  whose capacity walks a pre-warmed shape ladder
  (``MutateConfig.delta_capacities``, the ``serve/ladder.py`` trick
  applied to growing state); every query searches it EXACTLY and
  merges with the main IVF top-k inside one compiled program
  (:mod:`raft_tpu.mutate.program`).
* **tombstones** — deletes set a bit in a packed bitmap over the main
  index's id space, filtered at postprocess inside the same program;
  an upsert of an existing id is tombstone + append (the delta row
  shadows the stale main row). Delta rows die in place: their slot id
  flips to -1.
* **background compaction** — a compactor
  (:class:`raft_tpu.mutate.compactor.Compactor`, or a manual
  :meth:`MutableIndex.compact`) freezes a snapshot, folds it into the
  main lists (:mod:`raft_tpu.mutate.compact`), pre-warms the NEXT
  epoch's full program grid off the serving path, and atomically swaps
  the epoch under the lock. Mutations landing during the fold stay in
  the delta tail and survive the swap; deletes during the fold are
  replayed onto the new epoch's bitmap. Old-epoch programs drain;
  serving threads never observe a half-swapped state and never compile.

Threading model (the GL003 ``GUARDED_BY`` contract below): caller
threads mutate, the serving dispatcher searches, the compactor folds —
all state hand-off happens under ``self._cond``; device dispatch and
XLA compilation always run OUTSIDE the lock against immutable
snapshots.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.obs import profiler
from raft_tpu.core.error import expects
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.mutate import compact as compact_mod
from raft_tpu.mutate import program as program_mod
from raft_tpu.mutate.types import DeltaFullError, MutateConfig
from raft_tpu.mutate.wal import (OP_DELETE, OP_META, OP_UPSERT,
                                 MutationWAL)
from raft_tpu.testing import faults

__all__ = ["MutableIndex", "build_serve_ladder",
           "build_dist_serve_ladder"]


def _tomb_words(id_base: int) -> int:
    return max(1, -(-int(id_base) // 32))


@dataclass
class _Epoch:
    """One immutable generation of the wrapped index plus its compiled
    program grid. Searches snapshot (epoch, device-state) atomically;
    a compaction installs a fully pre-warmed replacement."""

    index: object
    id_base: int                    # ids < id_base live in the main lists
    number: int
    tomb_words: int
    plans: Dict[tuple, object] = field(default_factory=dict)
    tails: Dict[tuple, object] = field(default_factory=dict)
    dist: Optional[dict] = None     # sharded view + DistSearchPlans


@dataclass
class _DeviceState:
    """The delta/tombstone operands currently on device, pinned to the
    epoch and delta rung they were shaped for."""

    epoch_number: int
    rung: int
    delta_data: jax.Array
    delta_norms: jax.Array
    delta_ids: jax.Array
    tomb: jax.Array


class MutableIndex:
    """Live mutable wrapper over a built IVF index: ``upsert`` /
    ``delete`` / ``search`` under traffic, background compaction, zero
    steady-state compiles. ``k`` is fixed at construction (the plan
    contract); serving callers slice smaller k like the batcher does."""

    # static race contract (tools/graftlint GL003): caller threads,
    # the serving dispatcher and the compactor meet on these fields —
    # touch them only under `with self._cond` or in `_locked` methods
    GUARDED_BY = ("_epoch", "_dev", "_delta_data", "_delta_norms",
                  "_delta_ids", "_delta_used", "_delta_live",
                  "_delta_map", "_tomb", "_tomb_ids", "_next_id",
                  "_compacting", "_frozen_id_base", "_pending_tombs",
                  "_rep", "_rungs", "_grid", "_dist_cfg", "_wal",
                  "_wal_ckpt", "_epoch_listeners")

    def __init__(self, index, k: int, params=None,
                 config: Optional[MutateConfig] = None):
        from raft_tpu.neighbors import plan as plan_mod
        family, _ = plan_mod._resolve_builder(index)
        expects(getattr(index, "raw", None) is None,
                "mutate: the wrapped %s index carries a host rescore "
                "corpus (raw) whose id-indexing cannot survive "
                "deletes — rebuild with keep_raw=False (estimator + "
                "device tiers still apply)", family)
        self.family = family
        self.k = int(k)
        self.cfg = config if config is not None else MutateConfig()
        self.params = (params if params is not None
                       else plan_mod._default_params(family))
        self._cond = threading.Condition()
        top = self.cfg.delta_capacities[-1]
        dim = int(index.dim)
        with self._cond:
            self._epoch = _Epoch(index=index, id_base=int(index.size),
                                 number=0,
                                 tomb_words=_tomb_words(index.size))
            self._delta_data = np.zeros((top, dim), np.float32)
            self._delta_norms = np.zeros((top,), np.float32)
            self._delta_ids = np.full((top,), -1, np.int32)
            self._delta_used = 0
            self._delta_live = 0
            self._delta_map: Dict[int, int] = {}
            self._tomb = np.zeros((self._epoch.tomb_words,), np.uint32)
            self._tomb_ids: set = set()
            self._next_id = int(index.size)
            self._compacting = False
            self._frozen_id_base = 0
            self._pending_tombs: set = set()
            self._rep: Optional[np.ndarray] = None
            self._rungs: Tuple[int, ...] = (
                min(self.params.n_probes, index.n_lists),)
            self._grid: set = set()
            self._dist_cfg: Optional[dict] = None
            self._wal: Optional[MutationWAL] = None
            self._wal_ckpt: Optional[str] = None
            self._epoch_listeners: Tuple = ()
            self._dev: Optional[_DeviceState] = None
            self._push_dev_locked()

    # -- introspection -----------------------------------------------------
    @property
    def dim(self) -> int:
        with self._cond:
            return int(self._epoch.index.dim)

    @property
    def metric(self) -> DistanceType:
        with self._cond:
            return self._epoch.index.metric

    @property
    def epoch(self) -> int:
        with self._cond:
            return self._epoch.number

    @property
    def index(self):
        """The CURRENT epoch's immutable inner index (pending delta
        rows and tombstones are NOT reflected — search through the
        MutableIndex for the live view)."""
        with self._cond:
            return self._epoch.index

    @property
    def size(self) -> int:
        """Live logical row count (main minus tombstones plus live
        delta rows; deletes of never-existing ids undercount)."""
        with self._cond:
            return (int(self._epoch.index.size) - len(self._tomb_ids)
                    + self._delta_live)

    def stats(self) -> dict:
        with self._cond:
            rung = self._rung_for_locked(self._delta_used)
            cap = self.cfg.delta_capacities[rung]
            return {
                "epoch": self._epoch.number,
                "id_base": self._epoch.id_base,
                "delta_used": self._delta_used,
                "delta_live": self._delta_live,
                "delta_rung": rung,
                "delta_capacity": cap,
                "delta_fill_frac": self._delta_used / cap,
                "tombstones": len(self._tomb_ids),
                "tombstone_frac": (len(self._tomb_ids)
                                   / max(1, self._epoch.id_base)),
                "compacting": self._compacting,
                "next_id": self._next_id,
            }

    def should_compact(self) -> bool:
        """Trigger predicate the background compactor polls: used delta
        slots past ``compact_trigger_frac`` of the TOP rung (and no
        fold already running)."""
        with self._cond:
            trigger = (self.cfg.compact_trigger_frac
                       * self.cfg.delta_capacities[-1])
            return (not self._compacting
                    and self._delta_used >= trigger)

    # -- mutation ----------------------------------------------------------
    def upsert(self, vectors, ids=None) -> np.ndarray:
        """Insert-or-replace rows → the int32 ids they live under.
        Auto-assigned ids continue the monotone id space; passing an
        existing id replaces that row (tombstone + append). Raises
        :class:`DeltaFullError` when the delta segment is at its top
        rung — compaction is the only way to drain it."""
        x = np.asarray(vectors, np.float32)
        if x.ndim == 1:
            x = x[None, :]
        n = x.shape[0]
        with self._cond:
            dim = int(self._epoch.index.dim)
            metric = self._epoch.index.metric
        expects(x.ndim == 2 and x.shape[1] == dim,
                "mutate.upsert: vectors must be (n, dim=%d), got %s",
                dim, x.shape)
        if metric == DistanceType.CosineExpanded:
            # build() stores row-normalized vectors for cosine; the
            # delta segment must match or the ip core scores raw dots
            x = x / np.maximum(
                np.linalg.norm(x, axis=1, keepdims=True), 1e-30)
        top = self.cfg.delta_capacities[-1]
        with self._cond:
            if ids is None:
                expects(self._next_id + n < 2 ** 31,
                        "mutate.upsert: int32 id space exhausted")
                ids_arr = np.arange(self._next_id, self._next_id + n,
                                    dtype=np.int32)
            else:
                ids_arr = np.asarray(ids, np.int32).reshape(-1)
                expects(ids_arr.shape[0] == n and (ids_arr >= 0).all(),
                        "mutate.upsert: need %d non-negative ids", n)
            if self._delta_used + n > top:
                obs.counter("raft.mutate.delta.overflow.total").inc()
                raise DeltaFullError(
                    f"delta segment full ({self._delta_used}+{n} > "
                    f"top rung {top}): waiting on compaction")
            if self._wal is not None:
                # write-ahead: the record is durable (fsync'd) BEFORE
                # the in-memory apply, so an ack implies recoverability;
                # an append that made it to disk without the apply
                # (crash in between) replays harmlessly — the caller
                # was never acked, and at-least-once replay of explicit
                # ids reproduces the same logical state.  The fsync
                # MUST happen under the mutation lock (GL008): the log
                # must preserve the total mutation order the lock
                # defines, and durable-before-apply is only atomic
                # while the lock pins the apply.
                self._wal.append_upsert(ids_arr, x)  # graftlint: disable=GL008
            slots = np.arange(self._delta_used, self._delta_used + n)
            self._delta_data[slots] = x
            self._delta_norms[slots] = (x * x).sum(axis=1)
            self._delta_ids[slots] = ids_arr
            self._delta_used += n
            self._delta_live += n
            for j in range(n):
                id_ = int(ids_arr[j])
                old = self._delta_map.pop(id_, None)
                if old is not None:
                    self._delta_ids[old] = -1   # shadowed delta row
                    self._delta_live -= 1
                self._delta_map[id_] = int(slots[j])
                self._tombstone_locked(id_)
                self._next_id = max(self._next_id, id_ + 1)
            obs.counter("raft.mutate.upserts.total").inc()
            obs.counter("raft.mutate.upserts.rows").inc(n)
            self._push_dev_locked()
            self._cond.notify_all()
        return ids_arr

    def delete(self, ids) -> int:
        """Tombstone rows by id → number of ids newly marked dead.
        Main-index rows are filtered at search postprocess until the
        next compaction purges them; delta rows die in place."""
        ids_arr = np.asarray(ids, np.int64).reshape(-1)
        hit = 0
        with self._cond:
            if self._wal is not None:
                # same justified hold as upsert's append (GL008): the
                # WAL's total-order + durable-before-apply contract is
                # defined BY this lock
                self._wal.append_delete(ids_arr)  # graftlint: disable=GL008
            for id_ in ids_arr:
                id_ = int(id_)
                dead = False
                slot = self._delta_map.pop(id_, None)
                if slot is not None:
                    self._delta_ids[slot] = -1
                    self._delta_live -= 1
                    dead = True
                if self._tombstone_locked(id_):
                    dead = True
                hit += bool(dead)
            obs.counter("raft.mutate.deletes.total").inc()
            obs.counter("raft.mutate.deletes.rows").inc(
                int(ids_arr.shape[0]))
            self._push_dev_locked()
        return hit

    def _tombstone_locked(self, id_: int) -> bool:
        """Mark one id dead in the main-index bitmap (and the pending
        replay log while a fold is in flight) → True when the bit was
        newly set."""
        fresh = False
        if id_ < self._epoch.id_base and id_ not in self._tomb_ids:
            self._tomb_ids.add(id_)
            self._tomb[id_ >> 5] |= np.uint32(1 << (id_ & 31))
            fresh = True
        if self._compacting and id_ < self._frozen_id_base:
            self._pending_tombs.add(id_)
        return fresh

    # -- device state ------------------------------------------------------
    def _rung_for_locked(self, used: int) -> int:
        for r, cap in enumerate(self.cfg.delta_capacities):
            if used <= cap:
                return r
        return len(self.cfg.delta_capacities) - 1

    def _push_dev_locked(self) -> None:
        """Refresh the device snapshot after a state change: the delta
        buffer view at the CURRENT rung capacity + the bitmap. Plain
        host→device transfers — never a compile."""
        rung = self._rung_for_locked(self._delta_used)
        cap = self.cfg.delta_capacities[rung]
        try:
            faults.inject("mutate.transfer", epoch=self._epoch.number)
            # justified hold (GL008): these host->device transfers are
            # bounded by the delta rung capacity (a few MB, never a
            # compile) and MUST be atomic with the host-state change —
            # publishing _dev outside the lock would let an older
            # refresh overwrite a newer one (ABA on the snapshot)
            self._dev = _DeviceState(
                epoch_number=self._epoch.number, rung=rung,
                delta_data=jnp.asarray(self._delta_data[:cap]),  # graftlint: disable=GL008
                delta_norms=jnp.asarray(self._delta_norms[:cap]),
                delta_ids=jnp.asarray(self._delta_ids[:cap]),
                tomb=jnp.asarray(self._tomb))
        except Exception:
            # a failed host→device refresh leaves the PREVIOUS
            # consistent snapshot serving (stale by exactly this
            # mutation batch); the caller sees the error — with a WAL
            # attached the mutation is already durable, so the next
            # successful mutation (or recovery) repairs the view
            obs.counter("raft.mutate.transfer.errors").inc()
            raise
        self._set_gauges_locked(rung, cap)

    def _set_gauges_locked(self, rung: int, cap: int) -> None:
        top = len(self.cfg.delta_capacities) - 1
        obs.gauge("raft.mutate.epoch").set(self._epoch.number)
        obs.gauge("raft.mutate.delta.rows").set(self._delta_live)
        obs.gauge("raft.mutate.delta.capacity").set(cap)
        obs.gauge("raft.mutate.delta.rung").set(rung)
        obs.gauge("raft.mutate.delta.fill_frac").set(
            round(self._delta_used / cap, 4))
        # a delta at its TOP rung with no fold in flight is a stalled
        # compactor — /healthz degrades on this gauge (ISSUE 9)
        obs.gauge("raft.mutate.delta.stalled").set(
            1.0 if (rung == top and not self._compacting) else 0.0)
        obs.gauge("raft.mutate.tombstone.rows").set(len(self._tomb_ids))
        obs.gauge("raft.mutate.tombstone.frac").set(
            round(len(self._tomb_ids) / max(1, self._epoch.id_base), 6))
        obs.gauge("raft.mutate.compact.inflight").set(
            1.0 if self._compacting else 0.0)

    # -- search ------------------------------------------------------------
    def search(self, queries, k: Optional[int] = None,
               block: bool = False) -> Tuple[jax.Array, jax.Array]:
        """Search the LIVE view (main minus tombstones plus delta) →
        (dists, ids), both (nq, k). Arbitrary nq: a cold shape compiles
        once (counted under ``raft.plan.cache.misses``) and is cached
        on the epoch; warmed shapes never compile again."""
        expects(k is None or int(k) == self.k,
                "mutate.search: k=%s != plan k=%d (fixed at "
                "construction; slice smaller k caller-side)", k, self.k)
        return self._search_rung(queries, 0, block)

    def _search_rung(self, queries, rung_idx: int, block: bool
                     ) -> Tuple[jax.Array, jax.Array]:
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        nq = q.shape[0]
        # resource profiler admission (one None read when off): a
        # sampled blocking call splits host-enqueue vs device-wait
        # around the sync it was paying anyway
        prof = block and profiler.sampled()
        t0 = time.perf_counter()
        entry, dev = self._entry_for(nq, rung_idx, q)
        d, i = entry.run(jnp.asarray(q), dev.delta_data,
                         dev.delta_norms, dev.delta_ids, dev.tomb)
        if block:
            if prof:
                profiler.record_dispatch(
                    t0, time.perf_counter(), (d, i), program="mutate",
                    family=self.family, rung=rung_idx)
            else:
                jax.block_until_ready((d, i))
        return d, i

    def _entry_for(self, nq: int, rung_idx: int, rep_q):
        """Atomically snapshot (compiled entry, device state) for the
        current epoch at the current delta rung, compiling the entry
        outside the lock when cold."""
        while True:
            with self._cond:
                epoch = self._epoch
                dev = self._dev
                entry = epoch.plans.get((nq, rung_idx, dev.rung))
            if entry is not None and dev.epoch_number == epoch.number:
                return entry, dev
            self._build_entry(epoch, nq, rung_idx, dev.rung, rep_q)

    def _build_entry(self, epoch: _Epoch, nq: int, rung_idx: int,
                     delta_rung: int, rep_q=None, warm: bool = True):
        """Compile one (nq, n_probes-rung, delta-rung) program for
        ``epoch`` — plan-cache-counted, inserted under the lock."""
        import dataclasses as _dc
        key = (nq, rung_idx, delta_rung)
        with self._cond:
            entry = epoch.plans.get(key)
            if entry is not None:
                return entry
            rep = self._rep if self._rep is not None else rep_q
            n_probes = self._rungs[min(rung_idx, len(self._rungs) - 1)]
        expects(rep is not None,
                "mutate: no representative queries available — call "
                "warmup() before background prewarm")
        params = _dc.replace(self.params, n_probes=n_probes)
        delta_cap = self.cfg.delta_capacities[delta_rung]
        # GL012 audit (ISSUE 15): `nq` is runtime-derived on the
        # documented cold path — "a cold shape compiles once" is the
        # flexible/debug contract of MutableIndex.search. Ladder-served
        # traffic (build_serve_ladder -> _MutableServePlan) pins nq to
        # the pre-warmed shape grid, each compile is cached on the
        # epoch, and the epoch grid is re-warmed by the compactor — so
        # steady state stays at zero compiles (asserted from
        # raft.plan.cache.* in tests/test_mutate.py).
        entry = program_mod.compile_mutate_program(  # compile-surface: bounded=cold-shape compile, once per (nq, rung, epoch); ladder-served traffic pins nq to the warmed grid
            epoch.index, rep, nq, self.k, params, delta_cap,
            epoch.tomb_words, slack=self.cfg.tombstone_slack)
        if warm:
            # run once on dummy operands so device-side warmup is off
            # the serving path (the build_plan warm contract)
            reps = -(-nq // np.asarray(rep).shape[0])
            qw = jnp.asarray(np.tile(np.asarray(rep, np.float32),
                                     (reps, 1))[:nq])
            dim = qw.shape[1]
            jax.block_until_ready(entry.run(
                qw, jnp.zeros((delta_cap, dim), jnp.float32),
                jnp.zeros((delta_cap,), jnp.float32),
                jnp.full((delta_cap,), -1, jnp.int32),
                jnp.zeros((epoch.tomb_words,), jnp.uint32)))
        with self._cond:
            cur = epoch.plans.get(key)
            if cur is None:
                epoch.plans[key] = entry
            else:
                entry = cur
        return entry

    # -- warmup / ladder registration --------------------------------------
    def warmup(self, rep_queries,
               shapes: Tuple[int, ...] = (1, 8, 32, 128),
               probes_ladder: Tuple[int, ...] = ()) -> "MutableIndex":
        """Pre-warm the full (shape × n_probes-rung × delta-rung)
        program grid so steady-state traffic — including delta growth
        across rung boundaries and post-compaction epochs — never
        compiles. The grid is remembered: every future epoch is
        pre-warmed to the same grid by the compactor BEFORE it swaps
        in."""
        rep = np.asarray(rep_queries, np.float32)
        with self._cond:
            index = self._epoch.index
        expects(rep.ndim == 2 and rep.shape[1] == index.dim,
                "mutate.warmup: rep_queries must be (nq, dim=%d), "
                "got %s", index.dim, rep.shape)
        with self._cond:
            self._rep = rep
            if probes_ladder:
                self._rungs = tuple(
                    min(p, index.n_lists) for p in probes_ladder)
            self._grid |= {(int(s), r) for s in shapes
                           for r in range(len(self._rungs))}
            epoch = self._epoch
        self._prewarm_epoch(epoch)
        return self

    def _warm_delta_rungs(self) -> range:
        n = len(self.cfg.delta_capacities)
        if self.cfg.prewarm_rungs > 0:
            n = min(n, self.cfg.prewarm_rungs)
        return range(n)

    def _prewarm_epoch(self, epoch: _Epoch) -> None:
        """Compile + warm the registered grid for ``epoch`` (runs on
        the warmup caller or the compactor — never the serving path)."""
        with self._cond:
            grid = sorted(self._grid)
            dist_cfg = self._dist_cfg
        for (nq, rung_idx) in grid:
            for dr in self._warm_delta_rungs():
                self._build_entry(epoch, nq, rung_idx, dr)
        if dist_cfg is not None:
            self._prewarm_dist(epoch, dist_cfg)

    # -- distributed serving (ISSUE 8 composition) -------------------------
    def register_dist(self, mesh, axis: str, rep_queries,
                      shapes: Tuple[int, ...],
                      probes_ladder: Tuple[int, ...] = (),
                      merge: Optional[str] = None) -> None:
        """Attach a mesh: every epoch (current and future) additionally
        pre-warms a list-sharded view served by ``DistSearchPlan``
        shard_map programs, with the delta merge + tombstone filter
        composed as a standalone tail program after the cross-shard
        merge (the delta segment is replicated — it is orders of
        magnitude smaller than the sharded lists)."""
        from raft_tpu.serve.merge import merge_mode
        rep = np.asarray(rep_queries, np.float32)
        with self._cond:
            index = self._epoch.index
            self._rep = rep if self._rep is None else self._rep
            if probes_ladder:
                self._rungs = tuple(probes_ladder)
            cfg = {"mesh": mesh, "axis": axis,
                   "shapes": tuple(int(s) for s in shapes),
                   "merge": (merge_mode(default="int8")
                             if merge is None else merge)}
            self._dist_cfg = cfg
            epoch = self._epoch
        expects(index.n_lists % mesh.shape[axis] == 0,
                "mutate.register_dist: n_lists=%d not divisible by %d "
                "shards", index.n_lists, mesh.shape[axis])
        self._prewarm_dist(epoch, cfg)

    def _prewarm_dist(self, epoch: _Epoch, cfg: dict) -> None:
        import dataclasses as _dc
        from raft_tpu.parallel import ivf as pivf
        from raft_tpu.serve.dist import DistSearchPlan
        mesh, axis = cfg["mesh"], cfg["axis"]
        with self._cond:
            rep = self._rep
            rungs = self._rungs
        sharded = pivf.shard_ivf_flat(epoch.index, mesh, axis=axis) \
            if self.family == "ivf_flat" else \
            pivf.shard_ivf_pq(epoch.index, mesh, axis=axis)
        comms = pivf.get_comms(mesh, axis)
        plans = {}
        d_dt = i_dt = None
        # the mesh-wide main phase over-fetches k + slack candidates so
        # the tail's tombstone filter never costs a result slot
        k_fetch = self.k + self.cfg.tombstone_slack
        for ri, n_probes in enumerate(rungs):
            p_r = _dc.replace(self.params, n_probes=n_probes)
            for s in cfg["shapes"]:
                dp = DistSearchPlan(self.family, sharded, mesh, axis, s,
                                    k_fetch, p_r, cfg["merge"], comms,
                                    level=ri)
                reps = -(-s // rep.shape[0])
                d, i = dp.search(np.tile(rep, (reps, 1))[:s],
                                 block=True)
                d_dt, i_dt = d.dtype, i.dtype
                plans[(s, ri)] = dp
        epoch.dist = {"index": sharded, "plans": plans,
                      "d_dtype": d_dt, "i_dtype": i_dt}
        with self._cond:
            dim = int(epoch.index.dim)
        for s in cfg["shapes"]:
            for dr in self._warm_delta_rungs():
                self._build_tail(epoch, s, dr, dim)

    def _build_tail(self, epoch: _Epoch, nq: int, delta_rung: int,
                    dim: int):
        key = (nq, delta_rung)
        with self._cond:
            tail = epoch.tails.get(key)
        if tail is not None:
            return tail
        dist = epoch.dist
        tail = program_mod.compile_tail_program(
            nq, self.k, dim, epoch.index.metric,
            self.cfg.delta_capacities[delta_rung], epoch.tomb_words,
            k_main=self.k + self.cfg.tombstone_slack,
            d_dtype=dist["d_dtype"], i_dtype=dist["i_dtype"])
        with self._cond:
            cur = epoch.tails.get(key)
            if cur is None:
                epoch.tails[key] = tail
            else:
                tail = cur
        return tail

    def _dist_search(self, nq: int, rung_idx: int, queries,
                     block: bool) -> Tuple[jax.Array, jax.Array]:
        q = np.asarray(queries, np.float32)
        with self._cond:
            epoch = self._epoch
            dev = self._dev
            dist_cfg = self._dist_cfg
        if epoch.dist is None:
            # mesh registered after this epoch was built (cold path):
            # shard + warm it now, off the steady-state contract
            expects(dist_cfg is not None,
                    "mutate: no mesh registered (register_dist)")
            self._prewarm_dist(epoch, dist_cfg)
        dp = epoch.dist["plans"][(nq, rung_idx)]
        d, i = dp.search(q, block=False)
        # the cross-shard merge returns mesh-replicated (nq, k) arrays;
        # the tail executable is a single-device program — re-place the
        # tiny merged block (k*8 bytes/row, an async local copy)
        dev0 = jax.devices()[0]
        d, i = jax.device_put(d, dev0), jax.device_put(i, dev0)
        tail = epoch.tails.get((nq, dev.rung))
        if tail is None:
            tail = self._build_tail(epoch, nq, dev.rung, q.shape[1])
        d, i = tail.run(jnp.asarray(q), d, i, dev.delta_data,
                        dev.delta_norms, dev.delta_ids, dev.tomb)
        if block:
            jax.block_until_ready((d, i))
        return d, i

    def _dist_plan(self, nq: int, rung_idx: int):
        """The current epoch's underlying DistSearchPlan at a grid
        point (gauge/introspection surface for the serving tier)."""
        with self._cond:
            dist = self._epoch.dist
        expects(dist is not None,
                "mutate: no mesh registered (register_dist)")
        return dist["plans"][(nq, rung_idx)]

    # -- epoch listeners (ISSUE 11: quality observability) -----------------
    def add_epoch_listener(self, fn) -> "MutableIndex":
        """Register ``fn(new_epoch_number)`` to run after every
        compaction epoch swap (on the compacting thread, OUTSIDE the
        lock — listeners may touch this index). The quality monitor
        subscribes its :meth:`~raft_tpu.obs.quality.QualityMonitor.
        note_epoch` here so recall windows split exactly where the
        fold did and ``raft.obs.quality.drift`` compares epoch against
        epoch, not a smear across the swap."""
        with self._cond:
            self._epoch_listeners = self._epoch_listeners + (fn,)
        return self

    def _notify_epoch_listeners(self, number: int) -> None:
        with self._cond:
            listeners = self._epoch_listeners
        from raft_tpu.core.logger import get_logger
        for fn in listeners:
            try:
                fn(number)
            except Exception as e:
                obs.counter("raft.mutate.epoch_listener.errors").inc()
                # warning(): the stdlib-spelling alias (ISSUE 11
                # satellite) — the PR 10 compactor died calling it
                # before the alias existed
                get_logger("mutate").warning(
                    "mutate: epoch listener %r failed for epoch %d: "
                    "%r", fn, number, e)

    # -- compaction --------------------------------------------------------
    def compact(self, mode: Optional[str] = None, mesh=None,
                axis: str = "data") -> bool:
        """Fold the delta + tombstones into the main lists and swap the
        epoch — under live traffic, zero serving downtime, zero
        serving-path compiles (the next epoch's grid is pre-warmed
        HERE, on the calling/compactor thread, before the swap).
        Returns False when a fold is already in flight."""
        from raft_tpu.obs import spans
        # chaos-harness site (kill_compactor): raises BEFORE any state
        # is frozen, so a killed fold leaves serving untouched
        faults.inject("mutate.compact")
        with self._cond:
            if self._compacting:
                return False
            self._compacting = True
            self._frozen_id_base = self._next_id
            self._pending_tombs = set()
            used = self._delta_used
            live = self._delta_ids[:used] >= 0
            snap_rows = self._delta_data[:used][live].copy()
            snap_ids = self._delta_ids[:used][live].copy()
            snap_tombs = frozenset(self._tomb_ids)
            freeze_used = used
            old_epoch = self._epoch
            new_id_base = self._frozen_id_base
            self._set_gauges_locked(
                self._rung_for_locked(used),
                self.cfg.delta_capacities[self._rung_for_locked(used)])
        mode = mode if mode is not None else self.cfg.compact_mode
        try:
            with spans.span("raft.mutate.compact",
                            epoch=old_epoch.number, mode=mode,
                            rows=int(snap_rows.shape[0]),
                            tombstones=len(snap_tombs)) as sp, \
                    obs.timed("raft.mutate.compact"):
                new_index = compact_mod.fold(
                    old_epoch.index, snap_rows, snap_ids, snap_tombs,
                    mode=mode, mesh=mesh, axis=axis,
                    stream_chunk=self.cfg.rebuild_stream_chunk)
                new_epoch = _Epoch(index=new_index,
                                   id_base=new_id_base,
                                   number=old_epoch.number + 1,
                                   tomb_words=_tomb_words(new_id_base))
                # pre-warm the whole registered grid for the NEW epoch
                # before anyone can route to it — the serving threads
                # keep draining old-epoch programs meanwhile
                self._prewarm_epoch(new_epoch)
                sp.set_attr("new_size", int(new_index.size))
                # durable checkpoint of the folded index (compactor
                # thread, off the serving path) so the swap may
                # truncate the WAL (ISSUE 10)
                ckpt_tmp = self._checkpoint_epoch(new_index)
            self._swap_epoch(new_epoch, freeze_used, new_id_base,
                             ckpt_tmp=ckpt_tmp)
            obs.counter("raft.mutate.compact.total").inc()
            self._notify_epoch_listeners(new_epoch.number)
            return True
        except BaseException:
            obs.counter("raft.mutate.compact.errors").inc()
            with self._cond:
                self._compacting = False
                self._push_dev_locked()
            raise

    def _checkpoint_epoch(self, new_index) -> Optional[str]:
        """Save the folded inner index next to the WAL checkpoint path
        (tmp file; the swap promotes it atomically). None when no WAL /
        checkpoint is configured — then the log is never truncated and
        recovery replays it in full onto the original base."""
        with self._cond:
            wal, ckpt = self._wal, self._wal_ckpt
        if wal is None or not ckpt:
            return None
        from raft_tpu.neighbors import serialize
        tmp = ckpt + ".tmp"
        serialize.save(new_index, tmp)
        return tmp

    def _swap_epoch(self, new_epoch: _Epoch, freeze_used: int,
                    new_id_base: int,
                    ckpt_tmp: Optional[str] = None) -> None:
        with self._cond:
            # rebase the delta: rows appended after the freeze slide to
            # the front; everything folded leaves the segment
            tail_n = self._delta_used - freeze_used
            if tail_n:
                self._delta_data[:tail_n] = \
                    self._delta_data[freeze_used:self._delta_used].copy()
                self._delta_norms[:tail_n] = \
                    self._delta_norms[freeze_used:self._delta_used].copy()
                self._delta_ids[:tail_n] = \
                    self._delta_ids[freeze_used:self._delta_used].copy()
            self._delta_ids[tail_n:self._delta_used] = -1
            self._delta_used = tail_n
            self._delta_map = {
                int(i): s for s, i in
                enumerate(self._delta_ids[:tail_n]) if i >= 0}
            self._delta_live = len(self._delta_map)
            # deletes that raced the fold replay onto the new bitmap
            self._tomb_ids = {i for i in self._pending_tombs
                              if i < new_id_base}
            self._pending_tombs = set()
            self._tomb = np.zeros((new_epoch.tomb_words,), np.uint32)
            for id_ in self._tomb_ids:
                self._tomb[id_ >> 5] |= np.uint32(1 << (id_ & 31))
            self._epoch = new_epoch
            self._compacting = False
            if self._wal is not None and ckpt_tmp is not None:
                # promote the checkpoint, then truncate the log to the
                # still-pending tail: deletes first, then live tail
                # upserts, so a replayed tail upsert re-shadows its
                # tombstoned main row (both steps atomic; a crash
                # between them replays the old full log onto the new
                # checkpoint — at-least-once, same logical state)
                os.replace(ckpt_tmp, self._wal_ckpt)
                live = self._delta_ids[:self._delta_used] >= 0
                # justified hold (GL008): the checkpoint promotion and
                # the log truncation to the still-pending tail must be
                # atomic with the epoch swap itself — a mutation landing
                # between swap and rewrite would be lost from the log;
                # this runs once per compaction, on the compactor thread
                self._wal.rewrite(  # graftlint: disable=GL008
                    meta={"epoch": new_epoch.number,
                          "id_base": new_epoch.id_base,
                          "next_id": self._next_id},
                    tomb_ids=np.asarray(sorted(self._tomb_ids),
                                        np.int64),
                    upsert_ids=self._delta_ids[:self._delta_used][live],
                    upsert_rows=self._delta_data[:self._delta_used][live])
            self._push_dev_locked()
            self._cond.notify_all()

    def apply_meta(self, meta: dict) -> "MutableIndex":
        """Restore the epoch/id-space counters a checkpointed inner
        index was folded under — the WAL meta record at the head of a
        post-compaction log, applied before replaying the tail
        (:meth:`recover` and the fleet tier's
        :func:`raft_tpu.fleet.replication.bootstrap_replica` both run
        through here). Only meaningful on a freshly-wrapped index:
        pending delta rows / tombstones would be stranded in the old
        id space (id_base may exceed the inner index's row count —
        ids are a space, rows are a count)."""
        with self._cond:
            expects(self._delta_used == 0 and not self._tomb_ids,
                    "mutate.apply_meta: only valid before any mutation "
                    "is applied (%d delta rows, %d tombstones pending)",
                    self._delta_used, len(self._tomb_ids))
            id_base = int(meta["id_base"])
            self._epoch = _Epoch(index=self._epoch.index,
                                 id_base=id_base,
                                 number=int(meta["epoch"]),
                                 tomb_words=_tomb_words(id_base))
            self._tomb = np.zeros((self._epoch.tomb_words,), np.uint32)
            self._next_id = int(meta["next_id"])
            self._push_dev_locked()
        return self

    # -- durability: mutation WAL (ISSUE 10) -------------------------------
    def attach_wal(self, wal: MutationWAL,
                   checkpoint_path: Optional[str] = None
                   ) -> "MutableIndex":
        """Make every acked mutation durable: subsequent ``upsert`` /
        ``delete`` calls append + fsync their WAL record BEFORE the
        in-memory apply, so :meth:`recover` replays 100% of acked
        mutations after process death. ``checkpoint_path`` additionally
        lets compactions truncate the log — the folded inner index is
        saved there (tmp + atomic replace at the epoch swap) and the
        WAL rewrites to just the still-pending tail; without it the log
        grows until rotated externally and recovery replays it in full
        onto the original base index (docs/robustness.md)."""
        with self._cond:
            self._wal = wal
            self._wal_ckpt = checkpoint_path
        return self

    @classmethod
    def recover(cls, wal_path: str, k: int, base_index=None,
                checkpoint_path: Optional[str] = None, params=None,
                config: Optional[MutateConfig] = None,
                sync: bool = True) -> "MutableIndex":
        """Rebuild the live mutable state after process death: load the
        latest durable inner index (the compaction checkpoint when one
        exists, else ``base_index`` — the index the WAL was started
        against), replay every acked mutation from the log in order,
        and re-attach the log for new writes. Replay is at-least-once
        over explicit ids, so a record that was fsync'd but never
        acked/applied reproduces the same logical state; a replay that
        overflows the delta segment compacts inline and continues —
        recovery never fails on volume."""
        from raft_tpu.neighbors import serialize
        inner = None
        if checkpoint_path and os.path.exists(checkpoint_path):
            inner = serialize.load(checkpoint_path)
        else:
            inner = base_index
        expects(inner is not None,
                "mutate.recover: no checkpoint at %r and no base_index "
                "— recovery needs the index the WAL was started "
                "against", checkpoint_path)
        wal = MutationWAL(wal_path, sync=sync)
        records = wal.replay()
        m = cls(inner, k=int(k), params=params, config=config)
        if records and records[0].op == OP_META:
            m.apply_meta(records[0].meta)
            records = records[1:]
        top = m.cfg.delta_capacities[-1]
        for rec in records:
            if rec.op == OP_DELETE:
                m.delete(rec.ids)
            elif rec.op == OP_UPSERT:
                ids32 = np.asarray(rec.ids, np.int32)
                # chunk to the top rung: the log may have been written
                # under a LARGER delta budget than the recovering
                # process configures
                for s in range(0, ids32.shape[0], top):
                    try:
                        m.upsert(rec.rows[s:s + top],
                                 ids=ids32[s:s + top])
                    except DeltaFullError:
                        m.compact()
                        m.upsert(rec.rows[s:s + top],
                                 ids=ids32[s:s + top])
        m.attach_wal(wal, checkpoint_path=checkpoint_path)
        return m

    # -- persistence (neighbors/serialize.py) ------------------------------
    def export_state(self) -> dict:
        """Consistent snapshot for :func:`serialize.save_mutable`."""
        with self._cond:
            used = self._delta_used
            return {
                "index": self._epoch.index,
                "epoch": self._epoch.number,
                "id_base": self._epoch.id_base,
                "next_id": self._next_id,
                "k": self.k,
                "delta_data": self._delta_data[:used].copy(),
                "delta_ids": self._delta_ids[:used].copy(),
                "tomb_ids": np.asarray(sorted(self._tomb_ids),
                                       np.int64),
            }

    @classmethod
    def restore(cls, index, state: dict, params=None,
                config: Optional[MutateConfig] = None
                ) -> "MutableIndex":
        """Rebuild a MutableIndex from :meth:`export_state` payload —
        pending delta rows and tombstones survive the round trip."""
        m = cls(index, k=int(state["k"]), params=params, config=config)
        rows = np.asarray(state["delta_data"], np.float32)
        ids = np.asarray(state["delta_ids"], np.int32)
        tombs = np.asarray(state["tomb_ids"], np.int64)
        with m._cond:
            id_base = int(state["id_base"])
            m._epoch = _Epoch(index=index, id_base=id_base,
                              number=int(state["epoch"]),
                              tomb_words=_tomb_words(id_base))
            n = rows.shape[0]
            expects(n <= m.cfg.delta_capacities[-1],
                    "mutate.restore: %d saved delta rows exceed the "
                    "configured top rung %d", n,
                    m.cfg.delta_capacities[-1])
            m._delta_data[:n] = rows
            m._delta_norms[:n] = (rows * rows).sum(axis=1)
            m._delta_ids[:n] = ids
            m._delta_used = n
            m._delta_map = {int(i): s for s, i in enumerate(ids)
                            if i >= 0}
            m._delta_live = len(m._delta_map)
            m._tomb_ids = {int(i) for i in tombs}
            m._tomb = np.zeros((m._epoch.tomb_words,), np.uint32)
            for id_ in m._tomb_ids:
                m._tomb[id_ >> 5] |= np.uint32(1 << (id_ & 31))
            m._next_id = int(state["next_id"])
            m._push_dev_locked()
        return m


# ---------------------------------------------------------------------------
# serving-tier glue: PlanLadder handles over a MutableIndex
# ---------------------------------------------------------------------------


class _MutableServePlan:
    """Plan-like handle (the :class:`PlanLadder` contract: ``search``,
    ``nq``, ``n_probes``) pinned to one (shape, rung) point; resolution
    to the current epoch/delta-rung executable happens per call, so the
    ladder object survives every compaction."""

    def __init__(self, mindex: MutableIndex, nq: int, rung: int,
                 n_probes: int):
        self._m = mindex
        self.nq = int(nq)
        self.rung = int(rung)
        self.n_probes = int(n_probes)

    def search(self, queries, block: bool = False):
        return self._m._search_rung(queries, self.rung, block)


class _MutableDistPlan:
    """The distributed counterpart: one cached shard_map dispatch (the
    current epoch's :class:`DistSearchPlan`) followed by the compiled
    delta/tombstone tail."""

    dist_like = True     # accepted by DistributedSearchServer

    def __init__(self, mindex: MutableIndex, nq: int, rung: int,
                 n_probes: int):
        self._m = mindex
        self.nq = int(nq)
        self.rung = int(rung)
        self.n_probes = int(n_probes)

    @property
    def mesh(self):
        return self._m._dist_plan(self.nq, self.rung).mesh

    @property
    def n_shards(self) -> int:
        return self._m._dist_plan(self.nq, self.rung).n_shards

    @property
    def merge_ratio(self) -> float:
        return self._m._dist_plan(self.nq, self.rung).merge_ratio

    def search(self, queries, block: bool = False):
        return self._m._dist_search(self.nq, self.rung, queries, block)


def build_serve_ladder(mindex: MutableIndex, rep_queries,
                       shapes: Tuple[int, ...] = (1, 8, 32, 128),
                       probes_ladder: Tuple[int, ...] = (),
                       prewarm: bool = True):
    """The mutable analogue of :meth:`PlanLadder.build`: pre-warm the
    (shape × rung × delta-rung) grid on the CURRENT epoch, register it
    so compactions pre-warm every future epoch, and return a
    :class:`PlanLadder` of stable handles the micro-batcher serves
    from across epoch swaps."""
    from raft_tpu.serve.ladder import PlanLadder
    if prewarm:
        mindex.warmup(rep_queries, shapes=shapes,
                      probes_ladder=probes_ladder)
    else:
        with mindex._cond:
            mindex._rep = np.asarray(rep_queries, np.float32)
            if probes_ladder:
                mindex._rungs = tuple(probes_ladder)
            mindex._grid |= {(int(s), r) for s in shapes
                             for r in range(len(mindex._rungs))}
    with mindex._cond:
        rungs = mindex._rungs
    plans = {(s, r): _MutableServePlan(mindex, s, r, rungs[r])
             for s in shapes for r in range(len(rungs))}
    return PlanLadder(shapes=tuple(shapes), rungs=rungs, plans=plans,
                      dim=mindex.dim, k=mindex.k)


def build_dist_serve_ladder(mindex: MutableIndex, rep_queries,
                            mesh=None, axis: str = "data",
                            shapes: Tuple[int, ...] = (1, 8, 32, 128),
                            probes_ladder: Tuple[int, ...] = (),
                            merge: Optional[str] = None):
    """Mesh-wide mutable serving: list-shard the current epoch, build
    the :class:`DistSearchPlan` grid + tail programs, register the mesh
    so every compaction re-shards and pre-warms the next epoch before
    swapping. Returns a :class:`PlanLadder` of stable dist handles."""
    from raft_tpu.serve.ladder import PlanLadder
    expects(mesh is not None, "build_dist_serve_ladder: mesh required")
    mindex.register_dist(mesh, axis, rep_queries, shapes=shapes,
                         probes_ladder=probes_ladder, merge=merge)
    with mindex._cond:
        rungs = mindex._rungs
    plans = {}
    for s in shapes:
        for r in range(len(rungs)):
            dp = mindex._dist_plan(s, r)
            plans[(s, r)] = _MutableDistPlan(mindex, s, r, dp.n_probes)
    return PlanLadder(shapes=tuple(shapes), rungs=rungs, plans=plans,
                      dim=mindex.dim, k=mindex.k)
