"""Crash-safe mutation write-ahead log (ISSUE 10; sequenced ISSUE 13).

The gap this closes: every acked ``MutableIndex`` mutation since the
last :func:`~raft_tpu.neighbors.serialize.save_mutable` snapshot lived
only in process memory — a crash lost them all. The WAL makes the ack
durable: a mutation call appends (and fsyncs) its record *before* the
in-memory state changes, so after process death
:meth:`raft_tpu.mutate.MutableIndex.recover` replays 100% of acked
mutations. A record appended but not yet applied when the process died
replays harmlessly — at-least-once replay reproduces the same logical
state because upsert/delete are keyed by explicit ids and the log
preserves total mutation order (appends happen under the index lock).

Since ISSUE 13 the log is also the **replication stream** of the fleet
tier (:mod:`raft_tpu.fleet.replication`): every record carries a
monotonically increasing **sequence number** plus the wall-clock write
time (both inside the CRC'd payload), and :class:`WalReader` gives a
read-only follower a positioned ``tail(from_seq)`` view that survives
the checkpoint-time :meth:`MutationWAL.rewrite`.

Format (binary, versioned, no pickling — a torn tail must be
recognizable, never executable)::

    header   8 bytes   b"RTPUWAL2"
    record   u32 payload_length | u32 crc32(payload) | payload
    payload  u64 seq, f64 wall_ts, u8 op, then
             op=1 upsert: u32 n, u32 dim, n×i64 ids, n×dim×f32 rows
             op=2 delete: u32 n, n×i64 ids
             op=3 meta:   u32 json_len, json bytes
                          (epoch/id_base/next_id — written as the first
                          record of a post-compaction rewrite)

Sequence contract: ``seq`` starts at 1 and increases by exactly 1 per
appended record — the log is *contiguous*. :meth:`rewrite` CONSUMES
sequence numbers for the snapshot records it writes (it never reuses
or resets them), so the space stays monotone across truncation: a
reader caught up to the pre-rewrite tip resumes at the meta record
with no gap, while a reader that was still behind sees a hole (its
missing records were folded into the checkpoint) and gets a typed
:class:`WalGapError` — re-bootstrap from the checkpoint is the only
correct continuation, and the error says so instead of silently
skipping state. The rewrite's meta record carries
``snapshot_upto_seq`` (the seq of the last snapshot record) so a
caught-up follower can skip the snapshot records it already holds.

Durability contract: ``append_*`` returns only after ``flush`` +
``os.fsync`` (one fsync per mutation *batch* — the unit callers ack).
``sync=False`` drops the fsync for tests/bulk loads that accept the OS
page-cache window.

Truncation: at a compaction epoch swap the folded prefix becomes
redundant *provided the folded index is durably checkpointed* —
:meth:`rewrite` atomically replaces the log (tmp + fsync +
``os.replace``) with a meta record plus the still-pending tail.
Without a checkpoint path the log simply keeps growing and recovery
replays it in full onto the original base index.

A torn final record (crash mid-append) is detected by length/CRC,
counted under ``raft.mutate.wal.torn.total``, and truncated away when
the log is reopened for appending — the log never wedges on its own
crash artifact.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Iterator, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core.error import expects

__all__ = ["MutationWAL", "WalReader", "WalRecord", "WalGapError",
           "read_raw", "decode_stream"]

_MAGIC = b"RTPUWAL2"
_HDR = struct.Struct("<II")     # payload length, crc32
_SEQ = struct.Struct("<Qd")     # sequence number, wall-clock write time
OP_UPSERT = 1
OP_DELETE = 2
OP_META = 3
# sanity bound: one record is one mutation batch; anything bigger than
# this is a corrupt length field, not a real batch
_MAX_RECORD = 1 << 30


class WalGapError(RuntimeError):
    """The reader's position predates the oldest record the log still
    holds — the records in between were folded into a checkpoint by
    :meth:`MutationWAL.rewrite`. Tailing cannot continue; re-bootstrap
    from the checkpoint (``fleet.replication.bootstrap_replica``)."""

    def __init__(self, last_seq: int, first_seq: int):
        super().__init__(
            f"wal: reader at seq {last_seq} but the log now starts at "
            f"seq {first_seq} — the gap was folded into a checkpoint; "
            f"re-bootstrap from the snapshot")
        self.last_seq = int(last_seq)
        self.first_seq = int(first_seq)


class WalRecord:
    """One decoded log record: ``op`` plus the op-specific fields,
    the replication ``seq`` and the wall-clock write time ``ts``."""

    __slots__ = ("op", "ids", "rows", "meta", "seq", "ts")

    def __init__(self, op: int, ids=None, rows=None, meta=None,
                 seq: int = 0, ts: float = 0.0):
        self.op = op
        self.ids = ids
        self.rows = rows
        self.meta = meta
        self.seq = seq
        self.ts = ts


def _encode_upsert(ids: np.ndarray, rows: np.ndarray) -> bytes:
    n, dim = rows.shape
    return b"".join((
        struct.pack("<BII", OP_UPSERT, n, dim),
        np.ascontiguousarray(ids, np.int64).tobytes(),
        np.ascontiguousarray(rows, np.float32).tobytes()))


def _encode_delete(ids: np.ndarray) -> bytes:
    return (struct.pack("<BI", OP_DELETE, ids.shape[0])
            + np.ascontiguousarray(ids, np.int64).tobytes())


def _encode_meta(meta: dict) -> bytes:
    blob = json.dumps(meta, sort_keys=True).encode("utf-8")
    return struct.pack("<BI", OP_META, len(blob)) + blob


def _decode(payload: bytes) -> WalRecord:
    seq, ts = _SEQ.unpack_from(payload, 0)
    base = _SEQ.size
    op = payload[base]
    if op == OP_UPSERT:
        _, n, dim = struct.unpack_from("<BII", payload, base)
        off = base + struct.calcsize("<BII")
        ids = np.frombuffer(payload, np.int64, n, off)
        rows = np.frombuffer(payload, np.float32, n * dim,
                             off + n * 8).reshape(n, dim)
        return WalRecord(OP_UPSERT, ids=ids, rows=rows, seq=seq, ts=ts)
    if op == OP_DELETE:
        _, n = struct.unpack_from("<BI", payload, base)
        ids = np.frombuffer(payload, np.int64, n,
                            base + struct.calcsize("<BI"))
        return WalRecord(OP_DELETE, ids=ids, seq=seq, ts=ts)
    if op == OP_META:
        _, ln = struct.unpack_from("<BI", payload, base)
        off = base + struct.calcsize("<BI")
        return WalRecord(OP_META, meta=json.loads(payload[off:off + ln]),
                         seq=seq, ts=ts)
    raise ValueError(f"wal: unknown record op {op}")


def _iter_file_records(path: str) -> Iterator[Tuple[WalRecord, int]]:
    """Yield (record, end_offset) for every intact record; stop at the
    first torn/corrupt one. Shared by the appending WAL and the
    read-only :class:`WalReader`. Raises StopIteration value via
    generator return of the torn byte count (0 = clean EOF)."""
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        expects(magic == _MAGIC,
                "wal: %s is not a mutation WAL (bad magic)", path)
        off = len(_MAGIC)
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return len(hdr)
            length, crc = _HDR.unpack(hdr)
            if length > _MAX_RECORD or length < _SEQ.size + 1:
                return _HDR.size
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return _HDR.size + len(payload)
            try:
                rec = _decode(payload)
            except Exception:   # graftlint: disable=GL006
                # an undecodable-but-checksummed record is a version
                # skew / corruption boundary, handled exactly like a
                # torn tail: stop replay here (justified swallow —
                # replay MUST return the intact prefix, not raise)
                return _HDR.size + length
            off += _HDR.size + length
            yield rec, off


class MutationWAL:
    """Append-only mutation log for one :class:`MutableIndex`.

    Not thread-safe on its own — the owning index serializes appends
    under its lock (mutations are already totally ordered there, and
    the log must preserve that order)."""

    def __init__(self, path: str, sync: bool = True,
                 start_seq: int = 1):
        self.path = path
        self.sync = bool(sync)
        self.torn_bytes = 0
        # next sequence number to assign (contiguous from 1; restored
        # by scanning at reopen so the space never restarts).
        # ``start_seq`` > 1 seeds a FRESH log deeper into the sequence
        # space — the promoted-follower hand-off (fleet tier): the new
        # primary's own log continues exactly where the applied stream
        # ended, so a caught-up peer resumes contiguously and a behind
        # peer gets the typed gap instead of silent divergence.
        expects(start_seq >= 1,
                "wal: start_seq must be >= 1, got %d", start_seq)
        self.next_seq = int(start_seq)
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        if fresh:
            self._f = open(path, "wb")
            self._f.write(_MAGIC)
            self._flush()
        else:
            # reopen for append: verify the header and truncate any
            # torn tail a crash mid-append left behind
            good = self._scan_good_length()
            with open(path, "rb+") as f:
                f.truncate(good)
            self._f = open(path, "ab")

    # -- internals ---------------------------------------------------------
    def _flush(self) -> None:
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())
            obs.counter("raft.mutate.wal.fsyncs.total").inc()

    def _stamp(self, body: bytes) -> bytes:
        """Prefix the op body with the next (seq, wall-ts) pair —
        inside the CRC'd region, so a corrupted seq can never be
        mistaken for a real position."""
        # wall clock by design (GL005): the ts feeds the cross-process
        # replication-lag gauge — a follower compares it against ITS
        # wall clock, which monotonic time cannot do
        payload = _SEQ.pack(self.next_seq, time.time()) + body  # graftlint: disable=GL005
        self.next_seq += 1
        return payload

    def _append(self, body: bytes) -> None:
        payload = self._stamp(body)
        rec = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        self._f.write(rec)
        self._flush()
        obs.counter("raft.mutate.wal.appends.total").inc()
        obs.counter("raft.mutate.wal.bytes.total").inc(len(rec))

    def _scan_good_length(self) -> int:
        """Byte offset of the last intact record's end (validates the
        whole file; called once at reopen). Also restores
        ``next_seq`` past the highest surviving record."""
        good = len(_MAGIC)
        it = _iter_file_records(self.path)
        torn = 0
        while True:
            try:
                rec, end = next(it)
            except StopIteration as stop:
                torn = stop.value or 0
                break
            good = end
            self.next_seq = max(self.next_seq, rec.seq + 1)
        if torn:
            self.torn_bytes = torn
            obs.counter("raft.mutate.wal.torn.total").inc()
        return good

    # -- public API --------------------------------------------------------
    def append_upsert(self, ids, rows) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        expects(rows.ndim == 2 and rows.shape[0] == ids.shape[0],
                "wal.append_upsert: rows must be (n=%d, dim), got %s",
                ids.shape[0], rows.shape)
        self._append(_encode_upsert(ids, rows))

    def append_delete(self, ids) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        self._append(_encode_delete(ids))

    def append_meta(self, meta: dict) -> None:
        """Append a meta record mid-log (epoch/id-space counters).
        The promotion path writes one as the FIRST record of the new
        primary's own log so a replica bootstrapping from it without
        the checkpoint still restores the inherited counters."""
        self._append(_encode_meta(dict(meta)))

    def replay(self) -> List[WalRecord]:
        """Every intact record in append order (stops at the first
        torn/corrupt one — the crash boundary)."""
        out = []
        it = _iter_file_records(self.path)
        while True:
            try:
                rec, _end = next(it)
            except StopIteration as stop:
                if stop.value:
                    self.torn_bytes = stop.value
                    obs.counter("raft.mutate.wal.torn.total").inc()
                break
            out.append(rec)
        obs.counter("raft.mutate.wal.replayed.total").inc(len(out))
        return out

    def rewrite(self, meta: Optional[dict] = None,
                tomb_ids=None, upsert_ids=None,
                upsert_rows=None) -> None:
        """Atomically replace the log with a compaction-epoch prefix:
        a meta record (epoch/id-space counters) + the still-pending
        deletes and delta-tail upserts. tmp + fsync + ``os.replace`` —
        a crash at any point leaves either the old complete log or the
        new complete log, never a hybrid.

        The snapshot records CONSUME fresh sequence numbers (the space
        is monotone, never reset): a reader caught up to the
        pre-rewrite tip resumes here contiguously, and the meta record
        carries ``snapshot_upto_seq`` so it can recognize — and skip —
        snapshot records whose state it already holds."""
        chunks = []
        if tomb_ids is not None and len(tomb_ids):
            chunks.append(_encode_delete(
                np.asarray(tomb_ids, np.int64).reshape(-1)))
        if upsert_ids is not None and len(upsert_ids):
            chunks.append(_encode_upsert(
                np.asarray(upsert_ids, np.int64).reshape(-1),
                np.asarray(upsert_rows, np.float32)))
        if meta is not None:
            meta = dict(meta,
                        snapshot_upto_seq=self.next_seq + len(chunks))
            chunks.insert(0, _encode_meta(meta))
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            for body in chunks:
                payload = self._stamp(body)
                f.write(_HDR.pack(len(payload), zlib.crc32(payload))
                        + payload)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        obs.counter("raft.mutate.wal.truncations.total").inc()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "MutationWAL":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class WalReader:
    """Read-only positioned view over a (possibly live) mutation WAL —
    the replication follower's end of the log.

    ``tail()`` returns every record newer than the reader's position
    and advances it. The reader NEVER writes (no truncation, no
    repair): a torn tail simply ends the batch — the appending side
    repairs it at its next reopen, and the torn record re-delivers
    once rewritten intact (at-least-once, the same contract replay
    has).

    Surviving ``rewrite``: the writer atomically replaces the file, so
    the reader watches the inode. When the file was replaced (or
    shrank under its offset) it rescans from the header, skipping
    records at or below its position. Because the sequence space is
    monotone and contiguous, a caught-up reader resumes exactly at the
    rewrite's snapshot records; a reader that was still behind finds
    the log's first record more than one seq ahead — those records
    were folded into the checkpoint — and gets :class:`WalGapError`
    (re-bootstrap is the only correct continuation)."""

    def __init__(self, path: str, from_seq: int = 0):
        self.path = path
        self.last_seq = int(from_seq)
        self._off = len(_MAGIC)
        self._ino = self._stat_ino()

    def _stat_ino(self):
        try:
            st = os.stat(self.path)
            return (st.st_dev, st.st_ino, st.st_size)
        except OSError:
            return None

    def tail(self, from_seq: Optional[int] = None,
             max_records: int = 0) -> List[WalRecord]:
        """Records with ``seq > from_seq`` (default: the reader's
        position) in order, advancing the position past everything
        returned. ``max_records`` > 0 bounds one call (the rest stays
        for the next). Empty list = caught up (or the file does not
        exist yet)."""
        if from_seq is not None:
            self.last_seq = int(from_seq)
            self._off = len(_MAGIC)
        st = self._stat_ino()
        if st is None:
            return []
        if self._ino is None or st[:2] != self._ino[:2] \
                or st[2] < self._off:
            # the writer replaced (rewrite) or restarted the file:
            # rescan from the header, filtering on seq
            self._off = len(_MAGIC)
        self._ino = st
        out: List[WalRecord] = []
        first_seen: Optional[int] = None
        it = _iter_file_records(self.path)
        off = len(_MAGIC)
        while True:
            try:
                rec, end = next(it)
            except StopIteration:
                break       # clean EOF or torn tail — stop either way
            off = end
            if off <= self._off:
                continue    # already consumed (byte-position resume)
            if rec.seq <= self.last_seq:
                self._off = off     # pre-position records after rescan
                continue
            if first_seen is None:
                first_seen = rec.seq
                if rec.seq > self.last_seq + 1 and self.last_seq > 0:
                    obs.counter("raft.mutate.wal.reader.gaps.total").inc()
                    raise WalGapError(self.last_seq, rec.seq)
            out.append(rec)
            self._off = off
            self.last_seq = rec.seq
            if max_records and len(out) >= max_records:
                break
        obs.counter("raft.mutate.wal.reader.records.total").inc(len(out))
        return out

    @property
    def position(self) -> int:
        """Seq of the last record returned (0 = nothing yet)."""
        return self.last_seq


# -- the log as the wire format (fleet transport, ISSUE 20) ----------------

def read_raw(path: str, from_seq: int = 0, max_records: int = 0
             ) -> Tuple[bytes, int, int]:
    """Raw wire slice of a WAL: the on-disk bytes of every intact
    record with ``seq > from_seq``, prefixed with the format magic —
    the returned buffer is itself a valid WAL fragment in the exact
    framing :func:`decode_stream` (and a future ``MutationWAL`` reopen)
    parses. The fleet transport streams THIS over
    ``GET /rpc/wal/tail`` — the log IS the wire format, no re-encode,
    CRCs travel verbatim. Returns ``(buf, n_records, last_seq)``;
    raises :class:`WalGapError` when ``from_seq`` predates the oldest
    surviving record (folded into a checkpoint — re-bootstrap).
    Single pass over one open file handle, so a concurrent
    :meth:`MutationWAL.rewrite` can never interleave two file
    generations into one response."""
    from_seq = int(from_seq)
    out = [_MAGIC]
    n = 0
    last = from_seq
    first_seen: Optional[int] = None
    try:
        f = open(path, "rb")
    except OSError:
        return b"".join(out), 0, last     # no log yet = empty tail
    with f:
        magic = f.read(len(_MAGIC))
        expects(magic == _MAGIC,
                "wal: %s is not a mutation WAL (bad magic)", path)
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break
            length, crc = _HDR.unpack(hdr)
            if length > _MAX_RECORD or length < _SEQ.size + 1:
                break
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break           # torn tail — ends the batch, like tail()
            seq, _ts = _SEQ.unpack_from(payload, 0)
            if seq <= from_seq:
                continue
            if first_seen is None:
                first_seen = seq
                if seq > from_seq + 1 and from_seq > 0:
                    obs.counter("raft.mutate.wal.reader.gaps.total").inc()
                    raise WalGapError(from_seq, seq)
            out.append(hdr)
            out.append(payload)
            last = seq
            n += 1
            if max_records and n >= max_records:
                break
    return b"".join(out), n, last


def decode_stream(buf: bytes) -> List[WalRecord]:
    """Decode a :func:`read_raw` buffer (magic + framed records) back
    into :class:`WalRecord` objects — the follower's end of the wire.
    A torn/corrupt suffix ends the batch (same contract as ``tail()``
    over a live file: the intact prefix is the answer, re-delivery is
    the sender's job)."""
    expects(buf[:len(_MAGIC)] == _MAGIC,
            "wal: wire stream has bad magic")
    out: List[WalRecord] = []
    off = len(_MAGIC)
    while off + _HDR.size <= len(buf):
        length, crc = _HDR.unpack_from(buf, off)
        start = off + _HDR.size
        payload = buf[start:start + length]
        if length > _MAX_RECORD or length < _SEQ.size + 1 \
                or len(payload) < length or zlib.crc32(payload) != crc:
            break
        try:
            out.append(_decode(payload))
        except Exception:   # graftlint: disable=GL006
            # undecodable-but-checksummed = version skew boundary,
            # handled like a torn tail (justified swallow — the intact
            # prefix must be returned, not raised away)
            break
        off = start + length
    return out
