"""Crash-safe mutation write-ahead log (ISSUE 10).

The gap this closes: every acked ``MutableIndex`` mutation since the
last :func:`~raft_tpu.neighbors.serialize.save_mutable` snapshot lived
only in process memory — a crash lost them all. The WAL makes the ack
durable: a mutation call appends (and fsyncs) its record *before* the
in-memory state changes, so after process death
:meth:`raft_tpu.mutate.MutableIndex.recover` replays 100% of acked
mutations. A record appended but not yet applied when the process died
replays harmlessly — at-least-once replay reproduces the same logical
state because upsert/delete are keyed by explicit ids and the log
preserves total mutation order (appends happen under the index lock).

Format (binary, versioned, no pickling — a torn tail must be
recognizable, never executable)::

    header   8 bytes   b"RTPUWAL1"
    record   u32 payload_length | u32 crc32(payload) | payload
    payload  u8 op, then
             op=1 upsert: u32 n, u32 dim, n×i64 ids, n×dim×f32 rows
             op=2 delete: u32 n, n×i64 ids
             op=3 meta:   u32 json_len, json bytes
                          (epoch/id_base/next_id — written as the first
                          record of a post-compaction rewrite)

Durability contract: ``append_*`` returns only after ``flush`` +
``os.fsync`` (one fsync per mutation *batch* — the unit callers ack).
``sync=False`` drops the fsync for tests/bulk loads that accept the OS
page-cache window.

Truncation: at a compaction epoch swap the folded prefix becomes
redundant *provided the folded index is durably checkpointed* —
:meth:`rewrite` atomically replaces the log (tmp + fsync +
``os.replace``) with a meta record plus the still-pending tail.
Without a checkpoint path the log simply keeps growing and recovery
replays it in full onto the original base index.

A torn final record (crash mid-append) is detected by length/CRC,
counted under ``raft.mutate.wal.torn.total``, and truncated away when
the log is reopened for appending — the log never wedges on its own
crash artifact.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Iterator, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core.error import expects

__all__ = ["MutationWAL", "WalRecord"]

_MAGIC = b"RTPUWAL1"
_HDR = struct.Struct("<II")     # payload length, crc32
OP_UPSERT = 1
OP_DELETE = 2
OP_META = 3
# sanity bound: one record is one mutation batch; anything bigger than
# this is a corrupt length field, not a real batch
_MAX_RECORD = 1 << 30


class WalRecord:
    """One decoded log record: ``op`` plus the op-specific fields."""

    __slots__ = ("op", "ids", "rows", "meta")

    def __init__(self, op: int, ids=None, rows=None, meta=None):
        self.op = op
        self.ids = ids
        self.rows = rows
        self.meta = meta


def _encode_upsert(ids: np.ndarray, rows: np.ndarray) -> bytes:
    n, dim = rows.shape
    return b"".join((
        struct.pack("<BII", OP_UPSERT, n, dim),
        np.ascontiguousarray(ids, np.int64).tobytes(),
        np.ascontiguousarray(rows, np.float32).tobytes()))


def _encode_delete(ids: np.ndarray) -> bytes:
    return (struct.pack("<BI", OP_DELETE, ids.shape[0])
            + np.ascontiguousarray(ids, np.int64).tobytes())


def _encode_meta(meta: dict) -> bytes:
    blob = json.dumps(meta, sort_keys=True).encode("utf-8")
    return struct.pack("<BI", OP_META, len(blob)) + blob


def _decode(payload: bytes) -> WalRecord:
    op = payload[0]
    if op == OP_UPSERT:
        _, n, dim = struct.unpack_from("<BII", payload, 0)
        off = struct.calcsize("<BII")
        ids = np.frombuffer(payload, np.int64, n, off)
        rows = np.frombuffer(payload, np.float32, n * dim,
                             off + n * 8).reshape(n, dim)
        return WalRecord(OP_UPSERT, ids=ids, rows=rows)
    if op == OP_DELETE:
        _, n = struct.unpack_from("<BI", payload, 0)
        ids = np.frombuffer(payload, np.int64, n,
                            struct.calcsize("<BI"))
        return WalRecord(OP_DELETE, ids=ids)
    if op == OP_META:
        _, ln = struct.unpack_from("<BI", payload, 0)
        off = struct.calcsize("<BI")
        return WalRecord(OP_META,
                         meta=json.loads(payload[off:off + ln]))
    raise ValueError(f"wal: unknown record op {op}")


class MutationWAL:
    """Append-only mutation log for one :class:`MutableIndex`.

    Not thread-safe on its own — the owning index serializes appends
    under its lock (mutations are already totally ordered there, and
    the log must preserve that order)."""

    def __init__(self, path: str, sync: bool = True):
        self.path = path
        self.sync = bool(sync)
        self.torn_bytes = 0
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        if fresh:
            self._f = open(path, "wb")
            self._f.write(_MAGIC)
            self._flush()
        else:
            # reopen for append: verify the header and truncate any
            # torn tail a crash mid-append left behind
            good = self._scan_good_length()
            with open(path, "rb+") as f:
                f.truncate(good)
            self._f = open(path, "ab")

    # -- internals ---------------------------------------------------------
    def _flush(self) -> None:
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())
            obs.counter("raft.mutate.wal.fsyncs.total").inc()

    def _append(self, payload: bytes) -> None:
        rec = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        self._f.write(rec)
        self._flush()
        obs.counter("raft.mutate.wal.appends.total").inc()
        obs.counter("raft.mutate.wal.bytes.total").inc(len(rec))

    def _scan_good_length(self) -> int:
        """Byte offset of the last intact record's end (validates the
        whole file; called once at reopen)."""
        good = len(_MAGIC)
        for _rec, end in self._iter_records(count_torn=True):
            good = end
        return good

    def _iter_records(self, count_torn: bool = False
                      ) -> Iterator[Tuple[WalRecord, int]]:
        with open(self.path, "rb") as f:
            magic = f.read(len(_MAGIC))
            expects(magic == _MAGIC,
                    "wal: %s is not a mutation WAL (bad magic)",
                    self.path)
            off = len(_MAGIC)
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    torn = len(hdr)
                    break
                length, crc = _HDR.unpack(hdr)
                if length > _MAX_RECORD:
                    torn = _HDR.size
                    break
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    torn = _HDR.size + len(payload)
                    break
                try:
                    rec = _decode(payload)
                except Exception:   # graftlint: disable=GL006
                    # an undecodable-but-checksummed record is a
                    # version skew / corruption boundary, handled
                    # exactly like a torn tail: stop replay here and
                    # count it (justified swallow — replay MUST return
                    # the intact prefix rather than raise)
                    torn = _HDR.size + length
                    break
                off += _HDR.size + length
                yield rec, off
            if torn and count_torn:
                self.torn_bytes = torn
                obs.counter("raft.mutate.wal.torn.total").inc()

    # -- public API --------------------------------------------------------
    def append_upsert(self, ids, rows) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        expects(rows.ndim == 2 and rows.shape[0] == ids.shape[0],
                "wal.append_upsert: rows must be (n=%d, dim), got %s",
                ids.shape[0], rows.shape)
        self._append(_encode_upsert(ids, rows))

    def append_delete(self, ids) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        self._append(_encode_delete(ids))

    def replay(self) -> List[WalRecord]:
        """Every intact record in append order (stops at the first
        torn/corrupt one — the crash boundary)."""
        out = [rec for rec, _ in self._iter_records(count_torn=True)]
        obs.counter("raft.mutate.wal.replayed.total").inc(len(out))
        return out

    def rewrite(self, meta: Optional[dict] = None,
                tomb_ids=None, upsert_ids=None,
                upsert_rows=None) -> None:
        """Atomically replace the log with a compaction-epoch prefix:
        a meta record (epoch/id-space counters) + the still-pending
        deletes and delta-tail upserts. tmp + fsync + ``os.replace`` —
        a crash at any point leaves either the old complete log or the
        new complete log, never a hybrid."""
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            chunks = []
            if meta is not None:
                chunks.append(_encode_meta(meta))
            if tomb_ids is not None and len(tomb_ids):
                chunks.append(_encode_delete(
                    np.asarray(tomb_ids, np.int64).reshape(-1)))
            if upsert_ids is not None and len(upsert_ids):
                chunks.append(_encode_upsert(
                    np.asarray(upsert_ids, np.int64).reshape(-1),
                    np.asarray(upsert_rows, np.float32)))
            for payload in chunks:
                f.write(_HDR.pack(len(payload), zlib.crc32(payload))
                        + payload)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        obs.counter("raft.mutate.wal.truncations.total").inc()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "MutationWAL":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
