"""Background compactor: folds the delta into the main lists while the
index keeps serving.

One daemon thread polls :meth:`MutableIndex.should_compact` (delta
slots past ``compact_trigger_frac`` of the top rung) and runs
:meth:`MutableIndex.compact` when it trips — the fold, the next
epoch's program prewarm and the atomic swap all happen on THIS thread;
the serving dispatcher only ever swaps a reference. ``trigger()``
forces a fold on the next wakeup regardless of fill (operational
lever: fold before a deploy, a snapshot, a traffic spike).

Crash-loop guard (ISSUE 10): the WHOLE iteration body — including the
``should_compact`` poll, which previously ran outside the try and
could kill the daemon forever with one exception — is guarded. A
failed attempt is counted (``raft.mutate.compactor.errors``), the poll
interval backs off exponentially (a poisoned fold must not busy-loop
the machine), and after ``fail_threshold`` consecutive failures the
``raft.mutate.compactor.failing`` gauge degrades ``/healthz``: a
compactor that cannot fold means the delta WILL hit its
:class:`~raft_tpu.mutate.DeltaFullError` wall, and the box must say so
before writes start bouncing. The serving state is untouched by any
failed attempt (the swap is compact()'s last step), and the first
success clears the gauge and resets the backoff."""

from __future__ import annotations

import threading
from typing import Optional

from raft_tpu import obs
from raft_tpu.core.logger import get_logger

__all__ = ["Compactor"]


class Compactor:
    """Owns the compaction thread for one
    :class:`~raft_tpu.mutate.MutableIndex`. Context-manager friendly;
    ``close()`` joins the thread (an in-flight fold finishes first —
    it must, the swap is what frees the delta)."""

    # static race contract (tools/graftlint GL003): the trigger flag
    # and shutdown flag sit on the caller/compactor thread boundary
    GUARDED_BY = ("_closed", "_force")

    def __init__(self, mindex, mode: Optional[str] = None, mesh=None,
                 axis: str = "data", poll_ms: Optional[float] = None,
                 fail_threshold: int = 3, backoff_mult: float = 2.0,
                 max_backoff_s: float = 5.0, start: bool = True):
        self._m = mindex
        self._mode = mode
        self._mesh = mesh
        self._axis = axis
        self._poll_s = (poll_ms if poll_ms is not None
                        else mindex.cfg.compact_poll_ms) / 1e3
        self._fail_threshold = max(1, int(fail_threshold))
        self._backoff_mult = max(1.0, float(backoff_mult))
        self._max_backoff_s = float(max_backoff_s)
        self._cond = threading.Condition()
        self._closed = False
        self._force = False
        self._thread: Optional[threading.Thread] = None
        obs.gauge("raft.mutate.compactor.failing").set(0)
        if start:
            self.start()

    def start(self) -> "Compactor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="raft-mutate-compactor")
            self._thread.start()
        return self

    def trigger(self) -> None:
        """Force a fold on the next wakeup (without waiting for the
        fill trigger)."""
        with self._cond:
            self._force = True
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=120.0)
            self._thread = None

    def __enter__(self) -> "Compactor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _wait_s(self, consecutive_failures: int) -> float:
        """Poll interval with exponential backoff while failing."""
        if consecutive_failures <= 0:
            return self._poll_s
        return min(self._poll_s
                   * self._backoff_mult ** consecutive_failures,
                   self._max_backoff_s)

    def _loop(self) -> None:
        log = get_logger("mutate")
        consec = 0
        while True:
            with self._cond:
                if self._closed:
                    break
                self._cond.wait(timeout=self._wait_s(consec))
                if self._closed:
                    break
                force, self._force = self._force, False
            # crash-loop guard: EVERYTHING the iteration does is inside
            # the try — one exception (in the poll or the fold) used to
            # kill the daemon and silently stall the delta at its top
            # rung forever
            try:
                if not (force or self._m.should_compact()):
                    continue
                self._m.compact(mode=self._mode, mesh=self._mesh,
                                axis=self._axis)
                if consec:
                    log.warn("compactor recovered after %d failed "
                             "attempt(s)", consec)
                consec = 0
                obs.gauge("raft.mutate.compactor.failing").set(0)
            except Exception as e:
                consec += 1
                obs.counter("raft.mutate.compactor.errors").inc()
                if consec >= self._fail_threshold:
                    # /healthz degrades on this gauge: N consecutive
                    # failed folds mean DeltaFullError is coming
                    obs.gauge("raft.mutate.compactor.failing").set(1)
                # NB: the framework logger has warn(), not warning() —
                # the pre-guard code called log.warning here, so the
                # "failure handler" itself raised AttributeError and
                # killed the daemon (exactly the bug class GL006 hunts)
                log.warn(
                    "compaction failed (attempt %d, next retry in "
                    "%.3gs): %r", consec, self._wait_s(consec), e)