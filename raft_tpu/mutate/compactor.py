"""Background compactor: folds the delta into the main lists while the
index keeps serving.

One daemon thread polls :meth:`MutableIndex.should_compact` (delta
slots past ``compact_trigger_frac`` of the top rung) and runs
:meth:`MutableIndex.compact` when it trips — the fold, the next
epoch's program prewarm and the atomic swap all happen on THIS thread;
the serving dispatcher only ever swaps a reference. ``trigger()``
forces a fold on the next wakeup regardless of fill (operational
lever: fold before a deploy, a snapshot, a traffic spike).

A failed fold is counted (``raft.mutate.compact.errors``), logged, and
retried on the next trigger — the serving state is untouched by a
failed attempt (the swap is the last step)."""

from __future__ import annotations

import threading
from typing import Optional

from raft_tpu.core.logger import get_logger

__all__ = ["Compactor"]


class Compactor:
    """Owns the compaction thread for one
    :class:`~raft_tpu.mutate.MutableIndex`. Context-manager friendly;
    ``close()`` joins the thread (an in-flight fold finishes first —
    it must, the swap is what frees the delta)."""

    # static race contract (tools/graftlint GL003): the trigger flag
    # and shutdown flag sit on the caller/compactor thread boundary
    GUARDED_BY = ("_closed", "_force")

    def __init__(self, mindex, mode: Optional[str] = None, mesh=None,
                 axis: str = "data", poll_ms: Optional[float] = None,
                 start: bool = True):
        self._m = mindex
        self._mode = mode
        self._mesh = mesh
        self._axis = axis
        self._poll_s = (poll_ms if poll_ms is not None
                        else mindex.cfg.compact_poll_ms) / 1e3
        self._cond = threading.Condition()
        self._closed = False
        self._force = False
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> "Compactor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="raft-mutate-compactor")
            self._thread.start()
        return self

    def trigger(self) -> None:
        """Force a fold on the next wakeup (without waiting for the
        fill trigger)."""
        with self._cond:
            self._force = True
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=120.0)
            self._thread = None

    def __enter__(self) -> "Compactor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _loop(self) -> None:
        log = get_logger("mutate")
        while True:
            with self._cond:
                if self._closed:
                    break
                self._cond.wait(timeout=self._poll_s)
                if self._closed:
                    break
                force, self._force = self._force, False
            if not (force or self._m.should_compact()):
                continue
            try:
                self._m.compact(mode=self._mode, mesh=self._mesh,
                                axis=self._axis)
            except Exception as e:   # counted in compact(); keep serving
                log.warning("compaction failed (will retry on next "
                            "trigger): %r", e)
