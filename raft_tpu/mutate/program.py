"""The mutable-serving program: main IVF search + delta merge +
tombstone filter compiled as ONE executable.

The :mod:`neighbors.plan` family builders produce the pure jittable
serving function ``fn(q, *operands) -> (d, i)`` for the wrapped index;
this module appends two stages and AOT-compiles the whole thing:

* **tombstone filter** — result ids from the main index are looked up
  in a packed uint32 bitmap (one gather + shift per candidate); dead
  ids drop to the metric's worst value before the merge, so a deleted
  row can never outrank a live one. The bitmap only needs to cover the
  main index's id space ``[0, id_base)`` — delta rows that die are
  invalidated in place (their slot id flips to -1), so the filter
  stays one fixed-shape operand per epoch.
* **delta merge** — the delta segment (a fixed-capacity append-only
  flat buffer) is scored EXACTLY against every query (one MXU matmul
  over ``(cap, dim)``), top-k selected, and merged with the filtered
  main results inside the same program. Capacities come from the
  pre-warmed rung ladder, so delta growth swaps operand shapes between
  compiled programs instead of recompiling (the ``serve/ladder.py``
  discipline applied to mutable state).

All stages honor the family's OUTPUT convention (`ivf_flat._postprocess`):
L2 metrics merge ascending, InnerProduct descending, cosine as 1 - cos
over normalized rows — the merge key flips sign accordingly and
invalid/dead slots sit at the convention's worst value.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.obs import profiler
from raft_tpu.core.error import expects
from raft_tpu.core.precision import matmul_precision
from raft_tpu.distance.distance_types import DistanceType

__all__ = ["compile_mutate_program", "compile_tail_program",
           "delta_scores", "mutate_tail"]

# Compile-surface rung declarations (graftlint GL012–GL014): the
# mutable tier's key dimensions.  delta_cap is the one GRID here —
# delta growth must swap between pre-warmed capacity rungs, never
# recompile (the PR 9 discipline GL013 now enforces statically).
COMPILE_SURFACE_RUNGS = {
    "delta_cap": ("delta_capacities", (1024, 4096, 16384),
                  "the delta-segment capacity rung ladder "
                  "(MutateConfig.delta_capacities) — growth swaps "
                  "operand shapes between pre-warmed programs"),
    "delta_rung": ("delta_capacities", None,
                   "a rung INDEX into delta_capacities"),
    "tomb_words": ("tomb_words", None,
                   "packed tombstone bitmap width — fixed per epoch "
                   "(id_base/32), changes only at compaction swap"),
    "tombstone_slack": ("tombstone_slack", None,
                        "k + slack over-fetch — config, fixed per "
                        "index"),
}

_SQRT_METRICS = (DistanceType.L2SqrtExpanded,
                 DistanceType.L2SqrtUnexpanded)


def _descending(metric: DistanceType) -> bool:
    """True when the family's OUTPUT distances sort larger-is-better
    (InnerProduct returns similarities)."""
    return metric == DistanceType.InnerProduct


def delta_scores(q, delta_data, delta_norms, delta_ids,
                 metric: DistanceType) -> jax.Array:
    """Exact (nq, cap) delta-segment scores in the family OUTPUT
    convention; invalid slots (id < 0) land at the worst value."""
    from raft_tpu.neighbors.ivf_flat import _metric_kind, _postprocess
    kind = _metric_kind(metric)
    if metric == DistanceType.CosineExpanded:
        # delta rows are stored normalized (upsert applies the build()
        # row normalization); queries normalize here like the main fn
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-30)
    ip = jnp.matmul(q, delta_data.T, precision=matmul_precision(),
                    preferred_element_type=jnp.float32)
    if kind == "ip":
        s = -ip
    else:
        qq = jnp.sum(q * q, axis=1)
        s = jnp.maximum(qq[:, None] + delta_norms[None, :] - 2.0 * ip,
                        0.0)
        if metric in _SQRT_METRICS:
            s = jnp.sqrt(s)
    s = jnp.where(delta_ids[None, :] >= 0, s, jnp.inf)
    return _postprocess(s, metric)


def _tombstone_dead(ids, tomb_words) -> jax.Array:
    """Per-candidate dead mask from the packed uint32 bitmap. -1
    (pad) ids shift to word 0 via the clip but are dead regardless."""
    word = tomb_words[jnp.clip(ids >> 5, 0, tomb_words.shape[0] - 1)]
    bit = (word >> (ids & 31).astype(jnp.uint32)) & jnp.uint32(1)
    return (ids < 0) | (bit != 0)


def mutate_tail(d_main, i_main, ds, delta_ids, tomb_words, k: int,
                metric: DistanceType) -> Tuple[jax.Array, jax.Array]:
    """Tombstone-filter the main results, top-k the delta scores, and
    merge — the postprocess stages of the mutable serving program."""
    desc = _descending(metric)
    worst = -jnp.inf if desc else jnp.inf
    dead = _tombstone_dead(i_main, tomb_words)
    d_main = jnp.where(dead, worst, d_main)
    i_main = jnp.where(dead, -1, i_main)
    # delta top-k (cap may undercut k on the smallest rung — merging
    # fewer candidates is still exact, the delta only HAS cap rows)
    kd = min(k, ds.shape[1])
    vd, sel = lax.top_k(ds if desc else -ds, kd)
    dd = vd if desc else -vd
    id_d = jnp.take(delta_ids, sel)
    id_d = jnp.where(jnp.isfinite(dd), id_d, -1)
    cat_d = jnp.concatenate([d_main, dd], axis=1)
    cat_i = jnp.concatenate([i_main, id_d], axis=1)
    v, sel2 = lax.top_k(cat_d if desc else -cat_d, k)
    return (v if desc else -v), jnp.take_along_axis(cat_i, sel2, axis=1)


class MutateExecutable:
    """One AOT-compiled (nq, n_probes, delta-rung) operating point of a
    mutable index's epoch: ``run(q, dd, dn, di, tw)`` hands the
    executable its baked main-index operands plus the CURRENT delta /
    tombstone device buffers (same shapes each call — that is the
    rung contract)."""

    __slots__ = ("executable", "operands", "nq", "k", "n_probes", "cap",
                 "delta_cap", "tomb_words")

    def __init__(self, executable, operands, nq, k, n_probes, cap,
                 delta_cap, tomb_words):
        self.executable = executable
        self.operands = operands
        self.nq = int(nq)
        self.k = int(k)
        self.n_probes = int(n_probes)
        self.cap = int(cap)
        self.delta_cap = int(delta_cap)
        self.tomb_words = int(tomb_words)

    def run(self, q, delta_data, delta_norms, delta_ids, tomb_words):
        return self.executable(q, *self.operands, delta_data,
                               delta_norms, delta_ids, tomb_words)


def _delta_structs(delta_cap: int, dim: int, tomb_words: int):
    return (jax.ShapeDtypeStruct((delta_cap, dim), jnp.float32),
            jax.ShapeDtypeStruct((delta_cap,), jnp.float32),
            jax.ShapeDtypeStruct((delta_cap,), jnp.int32),
            jax.ShapeDtypeStruct((tomb_words,), jnp.uint32))


def compile_mutate_program(index, rep_queries, nq: int, k: int, params,
                           delta_cap: int, tomb_words: int,
                           slack: int = 16) -> MutateExecutable:
    """AOT-compile the full mutable serving program — the family's plan
    program (ISSUE 2 builders, fused kernels and all) with the delta
    merge + tombstone filter appended — for one (nq, n_probes,
    delta-rung) point. The main phase fetches ``k + slack`` candidates
    (the tombstone filter runs post-top-k: slack absorbs dead ids
    without losing result slots — ``MutateConfig.tombstone_slack``).
    The ONE cap-measurement sync of the plan lifecycle happens here,
    never on the serving path. Counted under
    ``raft.plan.cache.misses`` / ``raft.plan.build.total`` so the
    zero-steady-state-compile assertion reads the same counters as the
    immutable serving tier."""
    import numpy as np
    from raft_tpu.neighbors import _ivf_scan
    from raft_tpu.neighbors import plan as plan_mod

    family, builder = plan_mod._resolve_builder(index)
    q = np.asarray(rep_queries, np.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "mutate: rep_queries must be (nq, dim=%d), got %s",
            index.dim, q.shape)
    reps = -(-nq // q.shape[0])
    q = np.tile(q, (reps, 1))[:nq]
    k_main = k + max(0, int(slack))
    make, n_probes, kind, use_pallas_coarse = builder(index, k_main,
                                                      params)
    _ivf_scan.count_coarse_fallback(n_probes, use_pallas_coarse)
    metric = index.metric
    obs.counter("raft.plan.cache.misses").inc()
    obs.counter("raft.plan.build.total").inc()
    with obs.timed("raft.mutate.plan.build", family=family):
        cap = _ivf_scan.resolve_cap(index.cap_cache, jnp.asarray(q),
                                    index.centers, params, n_probes,
                                    index.n_lists, kind=kind,
                                    use_pallas=use_pallas_coarse)
        fn_main, operands, host_epilogue, _key_bits = make(nq, cap)
        expects(host_epilogue is None,
                "mutate: the wrapped %s plan needs a host-side rescore "
                "epilogue (raw corpus off-device) — mutable serving "
                "requires a sync-free plan (keep_raw=False, or device "
                "rescore)", family)
        n_ops = len(operands)

        def fused(q_in, *ops):
            core, (dd, dn, di, tw) = ops[:n_ops], ops[n_ops:]
            d, i = fn_main(q_in, *core)
            ds = delta_scores(q_in, dd, dn, di, metric)
            return mutate_tail(d, i.astype(jnp.int32), ds, di, tw, k,
                               metric)

        q_struct = jax.ShapeDtypeStruct((nq, index.dim), jnp.float32)
        # plan-cache idiom: compiled ONCE per (epoch, nq, rung) key and
        # cached on the epoch — the fresh callable never re-traces
        t_c0 = time.perf_counter()
        executable = jax.jit(fused).lower(  # graftlint: disable=GL002
            q_struct, *operands,
            *_delta_structs(delta_cap, index.dim, tomb_words)).compile()
        # compile-time ledger (resource profiler): idle-chip seconds
        profiler.note_compile("mutate", time.perf_counter() - t_c0)
    return MutateExecutable(executable, operands, nq, k, n_probes, cap,
                            delta_cap, tomb_words)


class TailExecutable:
    """The delta-merge + tombstone-filter stages compiled ALONE —
    composed after a search whose main phase is its own dispatch (the
    distributed serving tier: the shard_map program and its cross-shard
    merge stay untouched; this program post-processes the merged
    results against the replicated delta segment)."""

    __slots__ = ("executable", "nq", "k", "delta_cap", "tomb_words")

    def __init__(self, executable, nq, k, delta_cap, tomb_words):
        self.executable = executable
        self.nq = int(nq)
        self.k = int(k)
        self.delta_cap = int(delta_cap)
        self.tomb_words = int(tomb_words)

    def run(self, q, d, i, delta_data, delta_norms, delta_ids,
            tomb_words):
        return self.executable(q, d, i, delta_data, delta_norms,
                               delta_ids, tomb_words)


def compile_tail_program(nq: int, k: int, dim: int, metric,
                         delta_cap: int, tomb_words: int,
                         k_main: Optional[int] = None,
                         d_dtype=jnp.float32, i_dtype=jnp.int32
                         ) -> TailExecutable:
    """AOT-compile the standalone tail for one (nq, delta-rung) point
    (counted under the same plan counters as the fused program).
    ``k_main`` is the width of the incoming main-phase results (``k +
    tombstone_slack`` when the upstream search over-fetches)."""
    obs.counter("raft.plan.cache.misses").inc()
    obs.counter("raft.plan.build.total").inc()
    k_main = k if k_main is None else int(k_main)

    def tail(q, d, i, dd, dn, di, tw):
        ds = delta_scores(q, dd, dn, di, metric)
        return mutate_tail(d.astype(jnp.float32), i.astype(jnp.int32),
                           ds, di, tw, k, metric)

    # plan-cache idiom: compiled ONCE per (epoch, nq, delta-rung) key
    # and cached on the epoch — the fresh callable never re-traces
    t_c0 = time.perf_counter()
    executable = jax.jit(tail).lower(  # graftlint: disable=GL002
        jax.ShapeDtypeStruct((nq, dim), jnp.float32),
        jax.ShapeDtypeStruct((nq, k_main), d_dtype),
        jax.ShapeDtypeStruct((nq, k_main), i_dtype),
        *_delta_structs(delta_cap, dim, tomb_words)).compile()
    profiler.note_compile("mutate", time.perf_counter() - t_c0)
    return TailExecutable(executable, nq, k, delta_cap, tomb_words)
