"""Mutable-index types: config + typed errors.

Kept dependency-light (stdlib only — no jax import) so the error types
can be raised through the serving stack and caught by HTTP routes
without pulling the device runtime into the import graph (the
``serve/types.py`` convention).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

__all__ = ["DeltaFullError", "MutateConfig"]


class DeltaFullError(RuntimeError):
    """The delta segment is at its top ladder rung and cannot absorb
    more rows until a compaction folds it into the main lists —
    explicit admission control for writes, the mutation-side analogue
    of :class:`raft_tpu.serve.RejectedError`. Nothing was applied."""


@dataclass(frozen=True)
class MutateConfig:
    """Operating contract of a :class:`~raft_tpu.mutate.MutableIndex`.

    * ``delta_capacities`` — the delta-segment shape ladder (ascending
      row capacities). The live delta buffer always executes at one of
      these compiled widths (the ``serve/ladder.py`` fixed-shape trick
      applied to *growing* state — Ragged Paged Attention, arxiv
      2604.15464, pages growing KV state the same way): crossing a rung
      boundary swaps to the next pre-warmed program instead of
      triggering an XLA recompile. Appends past the top rung fail NOW
      with :class:`DeltaFullError`.
    * ``compact_trigger_frac`` — the background compactor starts a fold
      when used delta slots reach this fraction of the TOP rung
      capacity (headroom so mutations keep landing while the fold
      runs; a compaction must finish before the remaining
      ``1 - frac`` of the ladder fills).
    * ``compact_mode`` — ``"fold"`` keeps the trained coarse centers
      frozen and folds the delta into the main lists via the family's
      ``extend`` path (fast, the steady-state mode); ``"rebuild"``
      re-trains from the reconstructed corpus via the family ``build``
      (or the PR 4 sharded/streaming build machinery when a mesh /
      chunk budget is passed) — the periodic center-refresh mode.
    * ``compact_poll_ms`` — the compactor thread's trigger-check
      interval while idle.
    * ``tombstone_slack`` — extra candidates the MAIN phase fetches
      (the program compiles at ``k + tombstone_slack`` and the merge
      cuts back to ``k``). The tombstone filter runs AFTER the main
      top-k, so each dead id in a query's main candidates costs one
      slot — slack absorbs up to this many per query; past it, recall
      dips until compaction purges (the ``raft.mutate.tombstone.frac``
      gauge is the watch signal; docs/mutability.md "Capacity
      planning").
    """

    delta_capacities: Tuple[int, ...] = (1024, 4096, 16384)
    tombstone_slack: int = 16
    compact_trigger_frac: float = 0.5
    compact_mode: str = "fold"
    compact_poll_ms: float = 50.0
    # rebuild-mode knobs: host-streaming chunk rows (0 = plain build)
    rebuild_stream_chunk: int = 0
    # optional cap on pre-warmed delta rungs counted from the bottom
    # (0 = warm every rung); a library user who never expects the top
    # rung can trim startup compiles
    prewarm_rungs: int = 0

    def __post_init__(self):
        caps = tuple(int(c) for c in self.delta_capacities)
        if not caps or list(caps) != sorted(set(caps)) or min(caps) < 8:
            raise ValueError(
                "MutateConfig.delta_capacities must be distinct "
                "ascending ints >= 8")
        object.__setattr__(self, "delta_capacities", caps)
        if not 0.0 < self.compact_trigger_frac <= 1.0:
            raise ValueError(
                "MutateConfig.compact_trigger_frac must be in (0, 1]")
        if self.compact_mode not in ("fold", "rebuild"):
            raise ValueError(
                "MutateConfig.compact_mode must be 'fold' or 'rebuild'")
        if self.tombstone_slack < 0:
            raise ValueError(
                "MutateConfig.tombstone_slack must be >= 0")
