"""Shared helpers for the Pallas kernel tier."""

from __future__ import annotations

# Sentinel for argmin-of-masked reductions (plain int: no backend init at
# import). Any masked lane gets this index; real indices are < 2**30.
BIG_I32 = 2**30

# Scoped-VMEM cap passed to Mosaic by the fused kernels. Their (TN, TM)
# distance blocks plus bf16 operand splits exceed the 16 MiB default;
# v5e has 128 MiB VMEM per core — leave headroom for double-buffered DMA.
VMEM_LIMIT = 100 * 1024 * 1024


def round_up(v: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``v`` (the Pow2 round-up of
    reference ``util/pow2_utils.cuh:29``, for arbitrary moduli)."""
    return -(-v // m) * m


def dot_nt_f32(a, b, mode):
    """``a @ b.T`` with f32 accumulation, at kernel precision ``mode``.

    ``mode``:

    * ``"bf16x3"`` — the split-matmul trick: each f32 operand is written
      as ``hi + lo`` with ``hi = bf16(x)`` and ``lo = bf16(x - hi)``;
      three bf16 MXU passes (``hi·hi + hi·lo + lo·hi``) recover ~16 of
      f32's 24 mantissa bits: the dropped ``lo·lo`` term is ~2^-17
      relative worst case (|lo| ≤ 2^-9·|x| per operand; measured ~1e-6
      on unit-scale data where signs cancel) at half the cost of XLA's
      6-pass ``HIGHEST``. Mosaic has no ``Precision.HIGH`` lowering
      in-kernel, so the split is spelled out by hand.
    * a ``lax.Precision`` — passed straight to ``dot_general``.
    """
    import jax.numpy as jnp
    from jax import lax

    dn = (((1,), (1,)), ((), ()))
    if mode != "bf16x3":
        return lax.dot_general(a, b, dn, preferred_element_type=jnp.float32,
                               precision=mode)
    ah = a.astype(jnp.bfloat16)
    bh = b.astype(jnp.bfloat16)
    al = (a - ah.astype(jnp.float32)).astype(jnp.bfloat16)
    bl = (b - bh.astype(jnp.float32)).astype(jnp.bfloat16)
    acc = lax.dot_general(ah, bl, dn, preferred_element_type=jnp.float32)
    acc += lax.dot_general(al, bh, dn, preferred_element_type=jnp.float32)
    acc += lax.dot_general(ah, bh, dn, preferred_element_type=jnp.float32)
    return acc
