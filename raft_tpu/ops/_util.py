"""Shared helpers for the Pallas kernel tier."""

from __future__ import annotations

# Sentinel for argmin-of-masked reductions (plain int: no backend init at
# import). Any masked lane gets this index; real indices are < 2**30.
BIG_I32 = 2**30


def round_up(v: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``v`` (the Pow2 round-up of
    reference ``util/pow2_utils.cuh:29``, for arbitrary moduli)."""
    return -(-v // m) * m
