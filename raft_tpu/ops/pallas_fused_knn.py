"""Pallas fused brute-force k-NN kernel (distance + in-kernel top-k).

Reference: ``spatial/knn/detail/fused_l2_knn.cuh:196`` — a single CUDA
kernel computing expanded-L2 tiles and maintaining per-warp ``WarpSelect``
top-k heaps, so the distance matrix never hits global memory.

TPU design (no warp shuffles, no heaps): the TPU-KNN partial-top-k trick
(PAPERS.md: "TPU-KNN: K Nearest Neighbor Search at Peak FLOP/s").
Per (query-tile, db-tile) grid cell:

1. MXU matmul → transposed distance block ``d (TN, TM)`` (rows = db
   points, cols = queries) entirely in VMEM.
2. *Binned partial reduction*: split the TN db rows into ``L`` bins and
   take each bin's (min, argmin) along the sublane axis → ``(L, TM)``
   candidates. This is the approximate step: of two true top-k hits in
   the same bin of the same tile, only the nearer survives. Recall is
   controlled by ``L`` (quality ~ the paper's recall target; L ≥ 2k
   default).
3. Merge candidates with the running (k, TM) state (resident in the
   output block across the db grid dimension) by k rounds of
   extract-min — O(k·(k+L)) VPU work vs O(TN·K) MXU work per tile.

Supports L2 (expanded, optional sqrt) and negated inner-product
("largest" selection via negation — how the reference routes IP through
FAISS max-heaps, ``knn_brute_force_faiss.cuh:220``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.dispatch import pallas_interpret
from raft_tpu.ops._util import (BIG_I32 as _BIG_I32, VMEM_LIMIT as _VMEM_LIMIT,
                                round_up as _round_up, dot_nt_f32)
from raft_tpu.core.precision import resolve_kernel_mode


def _merge_epilogue(d, row, od_ref, oi_ref, *, j, gn: int, k: int,
                    l_bins: int, metric: str, sqrt: bool):
    """Shared tail of the 2-D and K-tiled kernels: binned partial top-1
    candidates, filtered exact merge into the resident (k, TM) state,
    final sqrt/negate pass."""
    tn, tm = d.shape
    # (2) binned partial top-1: (TN, TM) → (L, TM) candidates
    b = tn // l_bins
    db_ = d.reshape(l_bins, b, tm)
    cand_d = jnp.min(db_, axis=1)                        # (L, TM)

    @pl.when(j == 0)
    def _():
        od_ref[:] = jnp.full(od_ref.shape, jnp.inf, jnp.float32)
        oi_ref[:] = jnp.full(oi_ref.shape, -1, jnp.int32)

    # filtered merge (the role of the reference's warp_sort_filtered,
    # topk/warpsort_topk.cuh:136): once the running top-k is warm, most
    # tiles can't improve any query's k-th best — skip their merge (and
    # the bin-argmin pass, which only merging needs).
    kth = od_ref[0, k - 1:k, :]                          # (1, TM)
    improves = jnp.any(cand_d < kth)

    # (3) merge candidates into the running top-k: k rounds of extract-min
    @pl.when(improves)
    def _():
        rb = row.reshape(l_bins, b, tm)
        cand_i = jnp.min(
            jnp.where(db_ == cand_d[:, None, :], rb, _BIG_I32),
            axis=1)                                      # (L, TM)
        c_d = jnp.concatenate([od_ref[0], cand_d], axis=0)   # (k+L, TM)
        c_i = jnp.concatenate([oi_ref[0], cand_i], axis=0)
        ri = jax.lax.broadcasted_iota(jnp.int32, (k + l_bins, tm), 0)
        new_d, new_i = [], []
        for _ in range(k):
            m_ = jnp.min(c_d, axis=0, keepdims=True)         # (1, TM)
            first = jnp.min(jnp.where(c_d == m_, ri, _BIG_I32), axis=0,
                            keepdims=True)
            sel = ri == first                            # one-hot per column
            new_d.append(m_)
            new_i.append(jnp.sum(jnp.where(sel, c_i, 0), axis=0,
                                 keepdims=True))
            c_d = jnp.where(sel, jnp.inf, c_d)
        od_ref[0] = jnp.concatenate(new_d, axis=0)       # (k, TM), sorted
        oi_ref[0] = jnp.concatenate(new_i, axis=0)

    is_last = j == gn - 1
    if metric == "l2" and sqrt:
        @pl.when(is_last)
        def _():
            od_ref[:] = jnp.sqrt(od_ref[:])
    if metric == "ip":
        @pl.when(is_last)
        def _():
            od_ref[:] = -od_ref[:]


def _knn_kernel(x_ref, y_ref, od_ref, oi_ref, *, n: int, tn: int, gn: int,
                k: int, l_bins: int, metric: str, sqrt: bool,
                precision):
    j = pl.program_id(1)
    x = x_ref[:]                                         # (TM, K)
    y = y_ref[:]                                         # (TN, K)
    tm = x.shape[0]
    ip = dot_nt_f32(y, x, precision)
    if metric == "l2":
        xx = jnp.sum(x * x, axis=1, keepdims=True).T     # (1, TM)
        yy = jnp.sum(y * y, axis=1, keepdims=True)       # (TN, 1)
        d = jnp.maximum(yy + xx - 2.0 * ip, 0.0)
    else:  # "ip": similarity → negate so smaller-is-better uniformly
        d = -ip
    row = jax.lax.broadcasted_iota(jnp.int32, (tn, tm), 0) + j * tn
    if n % tn:  # only pay the padded-row masking pass when padding exists
        d = jnp.where(row < n, d, jnp.inf)
    _merge_epilogue(d, row, od_ref, oi_ref, j=j, gn=gn, k=k,
                    l_bins=l_bins, metric=metric, sqrt=sqrt)


def _knn_kernel_ktiled(x_ref, y_ref, od_ref, oi_ref, acc_ref, xx_ref,
                       yy_ref, *, n: int, tn: int, gn: int, gk: int,
                       k: int, l_bins: int, metric: str, sqrt: bool,
                       precision):
    """K-staged variant (reference contractions.cuh:71-307): the
    contraction dimension is tiled on the innermost grid axis and the
    (TN, TM) partial products accumulate in VMEM scratch; the distance
    epilogue + merge run on the final K step only. Lifts the dim ≤ 4096
    cap — VMEM holds one (TM+TN)·KT operand pair per step, never the
    full dim."""
    j = pl.program_id(1)
    kk = pl.program_id(2)
    x = x_ref[:]                                         # (TM, KT)
    y = y_ref[:]                                         # (TN, KT)
    tm = x.shape[0]

    @pl.when(kk == 0)
    def _():
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)
        if metric == "l2":
            xx_ref[:] = jnp.zeros(xx_ref.shape, jnp.float32)
            yy_ref[:] = jnp.zeros(yy_ref.shape, jnp.float32)

    acc_ref[:] += dot_nt_f32(y, x, precision)
    if metric == "l2":
        xx_ref[:] += jnp.sum(x * x, axis=1, keepdims=True).T  # (1, TM)
        yy_ref[:] += jnp.sum(y * y, axis=1, keepdims=True)    # (TN, 1)

    @pl.when(kk == gk - 1)
    def _():
        ip = acc_ref[:]
        if metric == "l2":
            d = jnp.maximum(yy_ref[:] + xx_ref[:] - 2.0 * ip, 0.0)
        else:
            d = -ip
        row = jax.lax.broadcasted_iota(jnp.int32, (tn, tm), 0) + j * tn
        if n % tn:
            d = jnp.where(row < n, d, jnp.inf)
        _merge_epilogue(d, row, od_ref, oi_ref, j=j, gn=gn, k=k,
                        l_bins=l_bins, metric=metric, sqrt=sqrt)


@functools.partial(jax.jit, static_argnames=(
    "k", "metric", "sqrt", "tm", "tn", "kt", "l_bins", "interpret",
    "kernel_precision"))
def _fused_knn_call(x, y, k: int, metric: str, sqrt: bool, tm: int, tn: int,
                    l_bins: int, interpret: bool, kt: int = 0,
                    kernel_precision=None):
    m, dim = x.shape
    n = y.shape[0]
    mp, np_ = _round_up(m, tm), _round_up(n, tn)
    gm, gn = mp // tm, np_ // tn
    common = dict(
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * np_ * dim,
            bytes_accessed=4 * (gm * np_ * dim + gn * mp * dim
                                + 2 * mp * k),
            transcendentals=0),
        interpret=interpret,
    )
    if kt <= 0 or kt >= dim:
        xp = jnp.pad(x.astype(jnp.float32), ((0, mp - m), (0, 0)))
        yp = jnp.pad(y.astype(jnp.float32), ((0, np_ - n), (0, 0)))
        kern = functools.partial(_knn_kernel, n=n, tn=tn, gn=gn, k=k,
                                 l_bins=l_bins, metric=metric, sqrt=sqrt,
                                 precision=resolve_kernel_mode(
                                     kernel_precision, interpret))
        od, oi = pl.pallas_call(
            kern,
            grid=(gm, gn),
            in_specs=[pl.BlockSpec((tm, dim), lambda i, j: (i, 0)),
                      pl.BlockSpec((tn, dim), lambda i, j: (j, 0))],
            out_specs=[pl.BlockSpec((1, k, tm), lambda i, j: (i, 0, 0)),
                       pl.BlockSpec((1, k, tm), lambda i, j: (i, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct((gm, k, tm), jnp.float32),
                       jax.ShapeDtypeStruct((gm, k, tm), jnp.int32)],
            **common,
        )(xp, yp)
    else:
        # K-staged path for large dim: pad the contraction dim to a KT
        # multiple (zero pad — contributes nothing to ip or norms)
        dp = _round_up(dim, kt)
        gk = dp // kt
        xp = jnp.pad(x.astype(jnp.float32),
                     ((0, mp - m), (0, dp - dim)))
        yp = jnp.pad(y.astype(jnp.float32),
                     ((0, np_ - n), (0, dp - dim)))
        kern = functools.partial(
            _knn_kernel_ktiled, n=n, tn=tn, gn=gn, gk=gk, k=k,
            l_bins=l_bins, metric=metric, sqrt=sqrt,
            precision=resolve_kernel_mode(kernel_precision, interpret))
        od, oi = pl.pallas_call(
            kern,
            grid=(gm, gn, gk),
            in_specs=[pl.BlockSpec((tm, kt), lambda i, j, kk: (i, kk)),
                      pl.BlockSpec((tn, kt), lambda i, j, kk: (j, kk))],
            out_specs=[
                pl.BlockSpec((1, k, tm), lambda i, j, kk: (i, 0, 0)),
                pl.BlockSpec((1, k, tm), lambda i, j, kk: (i, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct((gm, k, tm), jnp.float32),
                       jax.ShapeDtypeStruct((gm, k, tm), jnp.int32)],
            scratch_shapes=[pltpu.VMEM((tn, tm), jnp.float32),
                            pltpu.VMEM((1, tm), jnp.float32),
                            pltpu.VMEM((tn, 1), jnp.float32)],
            **common,
        )(xp, yp)
    # (gm, k, TM) → (m, k)
    od = jnp.moveaxis(od, 1, 2).reshape(gm * tm, k)[:m]
    oi = jnp.moveaxis(oi, 1, 2).reshape(gm * tm, k)[:m]
    return od, oi


def fused_knn_pallas(x, y, k: int, metric: str = "l2", sqrt: bool = False,
                     tm: int = 0, tn: int = 0, l_bins: int = 0,
                     kernel_precision: str | None = None):
    """Fused brute-force k-NN of queries ``x`` against database ``y``.

    Returns ``(dists (m, k), idx int32 (m, k))``, rows sorted
    best-first. ``metric``: ``"l2"`` (expanded, ``sqrt`` optional) or
    ``"ip"`` (inner product, largest selected). ``l_bins`` controls the
    per-tile partial-top-k width (0 → ``max(2k, 64)``); larger = higher
    recall, more VPU work. Exact when ``l_bins == tn``.
    ``kernel_precision``: ``None`` (env default, bf16x3) | ``"bf16x3"``
    | ``"bf16"`` (one MXU pass — ~3x the matmul throughput at ~5e-4
    relative error; pair with a recall gate) | ``"highest"``.
    """
    if metric not in ("l2", "ip"):
        raise ValueError(f"fused_knn_pallas: metric={metric!r}: want l2|ip")
    m, dim = x.shape
    n = y.shape[0]
    if k > n:
        raise ValueError(f"fused_knn_pallas: k={k} > n={n}")
    if m == 0:
        raise ValueError("fused_knn_pallas: empty query set")
    kt = 0
    if dim > 4096:
        # K-staged kernel (reference contractions.cuh K tiles): the
        # contraction dim streams through VMEM in KT-wide stages with a
        # resident accumulator, so arbitrary dim runs fused
        kt = 2048
        tm, tn = (tm or 256), (tn or 1024)
    if tm <= 0 or tn <= 0:
        # VMEM heuristic: the (TN, TM) f32 distance block dominates —
        # 16 MiB at 4096×1024 — plus (tm+tn)·dim·4 operand blocks
        # (double-buffered) and the bf16 split copies. Measured on v5e:
        # per-grid-step overhead makes small tiles ~2× slower, so tiles
        # are as large as the raised VMEM cap allows.
        if dim <= 512:
            tm, tn = 1024, 4096
        elif dim <= 2048:
            tm, tn = 512, 1024
        else:
            tm, tn = 256, 512
    tm = min(tm, _round_up(m, 8))
    tn = min(tn, _round_up(n, 8))
    if l_bins <= 0:
        l_bins = max(2 * k, 64)
    l_bins = min(l_bins, tn)
    while tn % l_bins:  # terminates: tn % tn == 0
        l_bins += 1
    return _fused_knn_call(x, y, int(k), metric, bool(sqrt), tm, tn,
                           l_bins, pallas_interpret(), kt=kt,
                           kernel_precision=kernel_precision)
