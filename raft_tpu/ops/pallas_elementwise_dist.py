"""Pallas tile kernel for the elementwise (non-MXU) distance family.

Reference: ``distance/detail/pairwise_distance_base.cuh:330`` — the same
GEMM-like tiled kernel serves every metric; only ``core_op`` changes
(abs-diff for L1, masked ratio for Canberra, …). The expanded metrics
ride the MXU; this family cannot (no inner-product form), so the TPU
budget is VPU throughput and the win over the XLA ``lax.map`` tiling is
locality: one (TM, dim)×(TN, dim) operand pair stays resident in VMEM
while TM row-sweeps reduce over the lane (dim) axis — no (t, n, k)
broadcast materializes in HBM.

Supported cores (one kernel, static ``metric``): l1, l2unexp (+sqrt),
linf, canberra, minkowski(p), hamming, jensen_shannon, kl, braycurtis.
The feature dim is zero-padded to the lane width — every core maps
(0, 0) → 0, so pad lanes contribute nothing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.dispatch import pallas_interpret
from raft_tpu.ops._util import (VMEM_LIMIT as _VMEM_LIMIT,
                                round_up as _round_up)
# single source of truth for the per-metric cores — shared with the XLA
# tier and the wide sparse path (distance/_elementwise_cores.py)
from raft_tpu.distance._elementwise_cores import (
    MAX_REDUCE as _MAX_REDUCE,
    combine as _combine,
    finalize as _finalize,
)

# operand blocks are (tm+tn, dp) f32, double-buffered; beyond this
# feature dim the caller must fall back to the XLA tiling (the kernel
# has no K-staging) — see MAX_DIM users in distance/pairwise.py
MAX_DIM = 16384


# rows of x processed together per inner step: a (RC, TN, dp) broadcast
# keeps all 8 sublanes busy instead of one row's worth of VPU work
_ROW_CHUNK = 8


def _elt_kernel(x_ref, y_ref, od_ref, *, tm: int, metric: str, p: float,
                dim: int, sqrt: bool):
    y = y_ref[:]                                         # (TN, dp)

    def chunk(a, _):
        base = a * _ROW_CHUNK
        xa = x_ref[pl.dslice(base, _ROW_CHUNK), :]       # (RC, dp)
        xa3 = xa[:, None, :]                             # (RC, 1, dp)
        y3 = y[None, :, :]                               # (1, TN, dp)
        if metric == "braycurtis":
            diff = jnp.sum(jnp.abs(xa3 - y3), axis=2)    # (RC, TN)
            ssum = jnp.sum(jnp.abs(xa3 + y3), axis=2)
            r = diff / jnp.where(ssum == 0.0, 1.0, ssum)
        else:
            e = _combine(metric, xa3, y3, p)             # (RC, TN, dp)
            if metric in _MAX_REDUCE:
                r = jnp.max(e, axis=2)                   # (RC, TN)
            else:
                r = jnp.sum(e, axis=2)
            r = _finalize(metric, r, p, dim, sqrt)
        od_ref[pl.dslice(base, _ROW_CHUNK), :] = r
        return _

    jax.lax.fori_loop(0, tm // _ROW_CHUNK, chunk, 0, unroll=False)


@functools.partial(jax.jit, static_argnames=("metric", "p", "sqrt", "tm",
                                             "tn", "interpret"))
def _elt_call(x, y, metric: str, p: float, sqrt: bool, tm: int, tn: int,
              interpret: bool):
    m, dim = x.shape
    n = y.shape[0]
    mp, np_ = _round_up(m, tm), _round_up(n, tn)
    dp = _round_up(dim, 128)
    xp = jnp.pad(x.astype(jnp.float32), ((0, mp - m), (0, dp - dim)))
    yp = jnp.pad(y.astype(jnp.float32), ((0, np_ - n), (0, dp - dim)))
    gm, gn = mp // tm, np_ // tn
    kern = functools.partial(_elt_kernel, tm=tm, metric=metric, p=p,
                             dim=dim, sqrt=sqrt)
    d = pl.pallas_call(
        kern,
        grid=(gm, gn),
        in_specs=[pl.BlockSpec((tm, dp), lambda i, j: (i, 0)),
                  pl.BlockSpec((tn, dp), lambda i, j: (j, 0))],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=3 * mp * np_ * dp,
            bytes_accessed=4 * (gn * mp * dp + gm * np_ * dp + mp * np_),
            transcendentals=(mp * np_ * dp
                             if metric in ("jensen_shannon", "kl") else 0)),
        interpret=interpret,
    )(xp, yp)
    return d[:m, :n]


def elementwise_dist_pallas(x, y, metric: str, p: float = 2.0,
                            sqrt: bool = False, tm: int = 0, tn: int = 0):
    """Pairwise distances for the elementwise metric family.

    ``metric``: l1 | l2unexp | linf | canberra | minkowski | hamming |
    jensen_shannon | kl | braycurtis. Returns (m, n) f32.
    """
    m, dim = x.shape
    n = y.shape[0]
    dp = _round_up(dim, 128)
    if tm <= 0 or tn <= 0:
        # operand blocks (tm+tn)·dp·4 double-buffered + (tm, tn) out;
        # deep-ish TN so the lane reduction amortizes
        if dim <= 1024:
            tm, tn = 256, 512
        else:
            tm, tn = 128, 256
    # the row-chunked combine materializes a (_ROW_CHUNK, TN, dp) f32
    # transient: cap TN so it stays well inside VMEM at wide dims
    tn_cap = max(8, (32 << 20) // (4 * _ROW_CHUNK * dp))
    tn = min(tn, max(8, tn_cap - tn_cap % 8))
    tm = min(tm, _round_up(m, 8))
    tn = min(tn, _round_up(n, 8))
    # the kernel loop strides whole row chunks: tm must be a multiple
    # of _ROW_CHUNK or trailing block rows would never be written
    tm = _round_up(tm, _ROW_CHUNK)
    return _elt_call(x, y, metric, float(p), bool(sqrt), tm, tn,
                     pallas_interpret())
