"""Compile-time budget + automatic tier fallback for fused searches.

Why this exists: on 2026-08-01 the first compile of the fused IVF-Flat
search sat 75 minutes on the remote TPU compile service and the
service died under it (BASELINE.md round-3 notes). The reference's
search always compiles — its kernels are precompiled template
instantiations (``ivf_flat_search.cuh:1026`` launcher) — so a search
that can wedge an entire round on one pathological compile is a
library defect, not an ops problem. This module is the in-library
defense:

* every fused-search entry runs as a ladder of TIERS, structurally
  simplest-last (Pallas auto-lc → Pallas lc=1 → XLA formulation →
  probe-major eager scan);
* the first call of a tier is given a wall-clock compile budget
  (``RAFT_TPU_COMPILE_BUDGET_S``, default 300 s on TPU backends,
  disabled elsewhere); a tier that exceeds it is marked POISONED for
  the process and the next tier serves the query instead;
* the over-budget compile is **parked, never killed** — a client
  killed mid-remote-compile is the known service-wedge trigger
  (tools/tunnel_probe.sh) — it keeps running in a daemon thread, and
  if it eventually completes the tier un-poisons (its executable sits
  in the process-wide jit cache, so later same-shape calls are cheap);
* a tier that has succeeded once runs inline with no thread or budget
  (the jit cache makes repeat calls microseconds of Python).

The ladder therefore guarantees: no search blocks longer than
``budget × (len(tiers) − 1)`` before reaching the always-compilable
probe-major tail, and no compile is ever aborted mid-flight.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from raft_tpu.core.logger import logger

# tier state, process-global: (ladder name, tier name) -> True
_OK: dict = {}
# (ladder name, tier name) -> time.monotonic() when the budget expired
# (monotonic, not wall clock: an NTP step must not stretch or shrink a
# poison window)
_POISONED: dict = {}
_LOCK = threading.Lock()


def budget_s() -> float:
    """Compile budget in seconds; 0 disables budgeting (tiers run
    inline). Default: 300 s when the default backend is a real TPU
    (where remote compiles have hung), else 0 — CPU/interpret compiles
    are fast and tests stay deterministic."""
    env = os.environ.get("RAFT_TPU_COMPILE_BUDGET_S")
    if env is not None:
        return float(env)
    import jax
    return 300.0 if jax.default_backend() == "tpu" else 0.0


def tier_state(ladder: str, tier: str) -> str:
    """"ok" | "poisoned" | "untried" — introspection for tests/tools."""
    key = (ladder, tier)
    with _LOCK:
        if key in _OK:
            return "ok"
        if key in _POISONED:
            return "poisoned"
    return "untried"


def snapshot() -> dict:
    """``{ladder: {tier: "ok"|"poisoned"}}`` — for tools/logs (the
    bisect ladder prints this so a parked compile is still NAMED even
    though the search it was part of served from a fallback tier)."""
    with _LOCK:
        out: dict = {}
        for (name, tier) in _OK:
            out.setdefault(name, {})[tier] = "ok"
        for (name, tier) in _POISONED:
            out.setdefault(name, {}).setdefault(tier, "poisoned")
    return out


def reset(ladder: Optional[str] = None) -> None:
    """Forget tier state (all ladders, or one) — test/bench helper."""
    with _LOCK:
        for d in (_OK, _POISONED):
            for key in [k for k in d
                        if ladder is None or k[0] == ladder]:
                del d[key]


def _run_inline(name: str, tname: str, thunk: Callable):
    out = thunk()
    with _LOCK:
        _OK[(name, tname)] = True
    return out


def run_tiers(name: str, tiers: Sequence[Tuple[str, Callable]],
              budget: Optional[float] = None):
    """Run the first tier of ``tiers`` that completes within the
    compile budget; fall down the ladder on timeout or error.

    ``tiers``: ``[(tier_name, thunk)]`` — each thunk traces, compiles
    (first call) and executes its formulation; order them structurally
    simplest-LAST. The final tier always runs inline (there is nothing
    to fall back to, and parking it would leave the caller with no
    result), so put the proven-compilable formulation there.
    """
    assert tiers, "run_tiers: empty ladder"
    b = budget_s() if budget is None else budget
    errors: List[Tuple[str, BaseException]] = []
    for i, (tname, thunk) in enumerate(tiers):
        key = (name, tname)
        last = i == len(tiers) - 1
        with _LOCK:
            ok = key in _OK
            poisoned = key in _POISONED and not ok
        if poisoned:
            continue
        if b <= 0 or ok or last:
            try:
                return _run_inline(name, tname, thunk)
            except Exception as e:  # noqa: BLE001 - ladder semantics
                if last:
                    raise
                errors.append((tname, e))
                logger.warn("%s: tier %s failed (%s); falling back",
                            name, tname, type(e).__name__)
                continue
        result: dict = {}
        done = threading.Event()

        def work(thunk=thunk, result=result, done=done, key=key):
            try:
                result["out"] = thunk()
            except BaseException as e:  # noqa: BLE001
                result["err"] = e
            finally:
                with _LOCK:
                    if "err" not in result:
                        # late completion un-poisons: the executable is
                        # now in the jit cache, future calls are cheap
                        _OK[key] = True
                        _POISONED.pop(key, None)
                done.set()

        t = threading.Thread(target=work, daemon=True,
                             name=f"raft-tpu-compile-{tname}")
        t.start()
        if done.wait(b):
            if "err" in result:
                errors.append((tname, result["err"]))
                logger.warn("%s: tier %s failed (%s); falling back",
                            name, tname,
                            type(result["err"]).__name__)
                continue
            with _LOCK:
                _OK[key] = True
            return result["out"]
        with _LOCK:
            _POISONED[key] = time.monotonic()
        logger.warn(
            "%s: tier %s exceeded the %.0f s compile budget; compile "
            "PARKED (never killed — see compile_budget docstring), "
            "falling back to the next tier", name, tname, b)
        # sibling skip: a parked compile indicates backend-family
        # pathology at this shape, and its same-family siblings are
        # near-identical programs — poison them too rather than burn
        # another full budget each (measured 2026-08-02: BQ cap=512
        # parked BOTH Pallas rungs back-to-back, 600 s of a scarce TPU
        # window). A sibling that should be tried anyway can be
        # reordered to the front (e.g. RAFT_TPU_IVF_LC=1).
        family = tname.split("_", 1)[0]
        for sib, _ in tiers[i + 1:len(tiers) - 1]:
            if sib.split("_", 1)[0] == family:
                sibkey = (name, sib)
                with _LOCK:
                    if sibkey not in _OK and sibkey not in _POISONED:
                        _POISONED[sibkey] = time.monotonic()
                        logger.warn("%s: tier %s skipped (same-family "
                                    "sibling of the parked %s)",
                                    name, sib, tname)
    # every tier poisoned/failed and the last raised nothing? only
    # reachable when the last tier was skipped as poisoned — run it
    # anyway (a poisoned final tier may have un-poisoned since, and
    # inline is the only option left)
    tname, thunk = tiers[-1]
    try:
        return _run_inline(name, tname, thunk)
    except Exception:
        if errors:
            logger.error("%s: all %d tiers failed; earlier errors: %s",
                         name, len(tiers),
                         "; ".join(f"{t}: {type(e).__name__}"
                                   for t, e in errors))
        raise
