"""Pallas exact k-selection kernel (the warpsort role).

Reference: ``spatial/knn/detail/topk.cuh:65-83`` dispatches k≤256 to
warp-sort (``topk/warpsort_topk.cuh:99-366``: per-warp sorted queues
merged through registers) and larger k to multi-pass radix
(``topk/radix_topk.cuh``). Neither maps to TPU (no warp shuffles); XLA's
``lax.top_k`` is a full variadic sort (28 ms for 1000×4096 on v5e —
BASELINE.md), orders of magnitude off a merge-pass budget.

TPU design — same transposed geometry as the fused kNN kernel
(``pallas_fused_knn.py``): candidates live on sublanes, rows (queries)
on lanes, so cross-candidate reductions are sublane reductions.

  1. The input (m, n) is transposed once by XLA to (n, m) and tiled
     (TN, TM); the kernel keeps a running sorted (k, TM) state resident
     in the output block across the candidate-tile grid dimension.
  2. Per tile, a *filtered* merge (warp_sort_filtered's trick,
     ``warpsort_topk.cuh:136``): if no tile value beats any lane's
     current k-th best, the tile is skipped after one vectorized
     compare.
  3. Merging is EXACT: k rounds of (min, argmin-by-row, invalidate)
     over the concatenated [state; tile] block — O(k·TN/8) sublane
     vector ops per merging tile, ~0.4 ms for 1000×4096 k=32 vs 28 ms
     for the XLA sort. No binning: unlike the recall-gated fused-kNN
     candidate pass, ``select_k`` is a parity primitive and must return
     exactly the k best.

k > 256 falls back to ``lax.top_k`` (the radix side of the reference
dispatch) in ``neighbors/selection.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.dispatch import pallas_interpret
from raft_tpu.ops._util import (BIG_I32 as _BIG_I32,
                                VMEM_LIMIT as _VMEM_LIMIT,
                                round_up as _round_up)


def _select_kernel(v_ref, od_ref, oi_ref, *, tn: int, k: int):
    # pad candidates arrive as +inf (padded before the transpose), so no
    # in-kernel mask is needed: an inf candidate ties the inf init state
    # and loses to its lower concat row (the state's -1 sentinel)
    j = pl.program_id(1)
    d = v_ref[:]                                         # (TN, TM)
    tm = d.shape[1]
    row = jax.lax.broadcasted_iota(jnp.int32, (tn, tm), 0) + j * tn

    @pl.when(j == 0)
    def _():
        od_ref[:] = jnp.full(od_ref.shape, jnp.inf, jnp.float32)
        oi_ref[:] = jnp.full(oi_ref.shape, -1, jnp.int32)

    kth = od_ref[0, k - 1:k, :]                          # (1, TM)
    improves = jnp.any(d < kth)

    @pl.when(improves)
    def _():
        c_d = jnp.concatenate([od_ref[0], d], axis=0)    # (k+TN, TM)
        c_i = jnp.concatenate([oi_ref[0], row], axis=0)
        ri = jax.lax.broadcasted_iota(jnp.int32, (k + tn, tm), 0)

        def round_(r, carry):
            cd, ci = carry
            m_ = jnp.min(cd, axis=0, keepdims=True)      # (1, TM)
            first = jnp.min(jnp.where(cd == m_, ri, _BIG_I32), axis=0,
                            keepdims=True)
            sel = ri == first                            # one-hot per lane
            idx = jnp.sum(jnp.where(sel, ci, 0), axis=0, keepdims=True)
            od_ref[0, pl.dslice(r, 1), :] = m_
            oi_ref[0, pl.dslice(r, 1), :] = idx
            return jnp.where(sel, jnp.inf, cd), ci

        jax.lax.fori_loop(0, k, round_, (c_d, c_i), unroll=False)


@functools.partial(jax.jit, static_argnames=("k", "tm", "tn", "interpret"))
def _select_k_call(v, k: int, tm: int, tn: int, interpret: bool):
    m, n = v.shape
    mp, np_ = _round_up(m, tm), _round_up(n, tn)
    # one XLA transpose: candidates onto sublanes, rows onto lanes
    vt = jnp.pad(v.astype(jnp.float32).T, ((0, np_ - n), (0, mp - m)),
                 constant_values=jnp.inf)
    gm, gn = mp // tm, np_ // tn
    kern = functools.partial(_select_kernel, tn=tn, k=k)
    od, oi = pl.pallas_call(
        kern,
        grid=(gm, gn),
        in_specs=[pl.BlockSpec((tn, tm), lambda i, j: (j, i))],
        out_specs=[pl.BlockSpec((1, k, tm), lambda i, j: (i, 0, 0)),
                   pl.BlockSpec((1, k, tm), lambda i, j: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((gm, k, tm), jnp.float32),
                   jax.ShapeDtypeStruct((gm, k, tm), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * np_,
            bytes_accessed=4 * (mp * np_ + 2 * mp * k),
            transcendentals=0),
        interpret=interpret,
    )(vt)
    od = jnp.moveaxis(od, 1, 2).reshape(gm * tm, k)[:m]
    oi = jnp.moveaxis(oi, 1, 2).reshape(gm * tm, k)[:m]
    return od, oi


def select_k_pallas(values, k: int, select_min: bool = True,
                    tm: int = 0, tn: int = 0):
    """Exact per-row top-k (smallest when ``select_min``) of a dense
    (m, n) matrix → ``(vals (m, k) f32 sorted best-first, idx (m, k)
    int32)``. Values are exact; tie-breaking between equal values is
    deterministic (lowest index within a merge; a tile whose best only
    *ties* the running k-th is skipped, so cross-tile ties keep the
    earlier tile's index). Rows with fewer than k finite candidates get
    ``-1`` ids and ``+inf`` values in the unfilled slots."""
    m, n = values.shape
    if not 1 <= k <= n:
        raise ValueError(f"select_k_pallas: k={k} outside [1, n={n}]")
    if tm <= 0 or tn <= 0:
        # (TN, TM) f32 tile; TN deep enough to amortize the k-round
        # merge, TM wide enough to fill lanes across the grid row
        tm = 256 if m >= 256 else max(128, _round_up(m, 8))
        tn = 2048 if n >= 2048 else _round_up(n, 8)
    tm = min(tm, _round_up(m, 8))
    tn = min(tn, _round_up(n, 8))
    v = values if select_min else -values
    d, i = _select_k_call(v, int(k), tm, tn, pallas_interpret())
    if not select_min:
        d = -d
    return d, i
