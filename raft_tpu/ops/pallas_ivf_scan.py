"""Pallas IVF list-scan kernel (fused fine phase of IVF-Flat search).

Reference: ``spatial/knn/detail/ivf_flat_search.cuh:665`` — the
``interleaved_scan_kernel``: one CUDA block per (query, probe) streams
the probed list's interleaved vectors, accumulates distances with
vectorized ILP, and keeps an in-kernel ``block_sort`` top-k so the
per-list score matrix never reaches global memory.

TPU re-design (list-major, not probe-major): a gather of "this query's
p-th list" per step re-reads every probed list ~nq·n_probes/n_lists
times from HBM. Instead the probe map is inverted (list → its probing
queries, the ``_ivf_scan`` inversion) and ONE kernel pass scans all
lists:

  grid cell = a chunk of ``LC`` lists. Per list ``l``:
    1. MXU matmul: list rows (max_list, dim) × gathered probing queries
       (cap, dim)ᵀ → transposed score block (max_list, cap) in VMEM —
       rows on sublanes, queries on lanes, the fused-kNN geometry.
    2. epilogue: + list-row norms + query norms − 2·ip, pad rows → +inf.
    3. binned partial top-k along sublanes → (B, cap) candidates with
       global db ids (TPU-KNN partial reduce; B ≥ 2k for the recall
       gate, B == max_list ⇒ exact).

Each list's rows are read from HBM exactly once per query batch; the
(max_list, cap) score block lives and dies in VMEM — the property the
reference's fused kernel has on GPU. Candidates are gathered back
per (query, probe) and merged with the exact Pallas ``select_k``.

The FUSED tier (``fused=True`` / ``RAFT_TPU_IVF_FUSED``, ISSUE 7) goes
one step further: the per-query top-k state stays resident in VMEM
across the list grid (the ``_select_kernel`` output-block-revisiting
trick, filtered-merge early-skip included), so the candidate tensor
never reaches HBM and the whole fine phase — scan, scatter, select —
is ONE ``pallas_call`` where the unfused path needs three dispatches
(scan kernel → XLA gather → select_k kernel). This is the in-kernel
``block_sort`` of the reference's ``interleaved_scan_kernel``
(``ivf_flat_search.cuh:665``) rebuilt for the list-major TPU geometry.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.dispatch import pallas_interpret
from raft_tpu.ops._util import (BIG_I32 as _BIG_I32,
                                VMEM_LIMIT as _VMEM_LIMIT,
                                round_up as _round_up, dot_nt_f32)
from raft_tpu.core.precision import kernel_matmul_mode


def _flat_list_candidates(scale, q, y, norms_l, ids, *, bins: int,
                          metric: str, precision):
    """One IVF-Flat list's binned candidates — the shared per-list body
    of the unfused scan kernel (which writes the blocks to HBM for a
    separate merge dispatch) and the fused scan+select kernel (which
    merges them straight into the VMEM-resident top-k state).

    ``q`` (cap, dim) probing queries, ``y`` (ML, dim) list rows,
    ``norms_l``/``ids`` (ML,) → ``(cd (bins, cap), ci (bins, cap))``.
    """
    ml = y.shape[0]
    cap = q.shape[0]
    if y.dtype == jnp.bfloat16:
        ip = jax.lax.dot_general(
            y, q.astype(jnp.bfloat16), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    elif y.dtype == jnp.int8:
        # int8 rides the MXU as bf16 (exact for |v| ≤ 127); the
        # kDivisor-style scale folds into the accumulated product
        ip = scale * jax.lax.dot_general(
            y.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        ip = dot_nt_f32(y, q, precision)             # (ML, cap)
    ids_b = jnp.broadcast_to(ids[:, None], (ml, cap))
    if metric == "ip":
        # similarity → negate: smaller-is-better uniformly (the
        # reference's max-heap IP routing, fused_l2_knn.cuh:947)
        d = jnp.where(ids_b >= 0, -ip, jnp.inf)
    else:
        qq = jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32),
                     axis=1)[None, :]                # (1, cap)
        d = norms_l[:, None] + qq - 2.0 * ip
        d = jnp.where(ids_b >= 0, jnp.maximum(d, 0.0), jnp.inf)

    # STRIDED bins (row r → bin r % B): bucketized rows follow
    # dataset order, so a query's true neighbors sit in adjacent
    # rows — contiguous bins would collide them (measured 0.87 vs
    # 0.99+ recall on clustered data); striding decorrelates free
    w = ml // bins
    db_ = d.reshape(w, bins, cap)
    cd = jnp.min(db_, axis=0)                        # (B, cap)
    rb = ids_b.reshape(w, bins, cap)
    ci = jnp.min(jnp.where(db_ == cd[None, :, :], rb, _BIG_I32),
                 axis=0)
    return cd, jnp.where(ci == _BIG_I32, -1, ci)


def _list_scan_kernel(scale_ref, qsub_ref, data_ref, norms_ref, ids_ref,
                      cd_ref, ci_ref, *, lc: int, bins: int, metric: str,
                      precision):
    scale = scale_ref[0, 0]

    def one_list(l):
        cd, ci = _flat_list_candidates(
            scale, qsub_ref[l], data_ref[l], norms_ref[l, 0],
            ids_ref[l, 0], bins=bins, metric=metric, precision=precision)
        cd_ref[l] = cd.astype(cd_ref.dtype)
        ci_ref[l] = ci

    # lc > 1 iterates via fori_loop so the Mosaic program stays ONE
    # list-body regardless of lc — a Python loop here unrolls lc
    # matmul+epilogue copies into the kernel, and that unbounded
    # program growth is the prime suspect in the 2026-08-01 75-minute
    # remote-compile hang (VERDICT r3). lc == 1 stays loop-free (the
    # structurally simplest fallback tier).
    if lc == 1:
        one_list(0)
    else:
        jax.lax.fori_loop(0, lc, lambda l, c: (one_list(l), c)[1], 0)


@functools.partial(jax.jit, static_argnames=("bins", "lc", "metric",
                                             "out_dtype", "interpret"))
def _list_scan_call(qsub, data, norms, ids, bins: int, lc: int,
                    scale, interpret: bool, metric: str = "l2",
                    out_dtype=jnp.float32):
    n_lists, cap, dim = qsub.shape
    max_list = data.shape[1]
    gc = n_lists // lc
    kern = functools.partial(
        _list_scan_kernel, lc=lc, bins=bins, metric=metric,
        precision=kernel_matmul_mode(interpret))
    # scale rides as a (1,1) traced input: a static arg would recompile
    # the kernel for every distinct int8 index scale
    scale_arr = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    # norms/ids ride with a singleton middle axis: Mosaic constrains the
    # LAST TWO block dims (divisible by (8, 128) or equal to the array
    # dim); as 2-D (lc, max_list) blocks the lc slot is constrained and
    # lc < 8 fails to lower — as (lc, 1, max_list) the constrained pair
    # is (1, max_list) == the array dims, legal for every lc
    norms3 = norms[:, None, :]
    ids3 = ids[:, None, :]
    cd, ci = pl.pallas_call(
        kern,
        grid=(gc,),
        in_specs=[pl.BlockSpec((1, 1), lambda g: (0, 0)),
                  pl.BlockSpec((lc, cap, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, max_list, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0))],
        out_specs=[pl.BlockSpec((lc, bins, cap), lambda g: (g, 0, 0)),
                   pl.BlockSpec((lc, bins, cap), lambda g: (g, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_lists, bins, cap), out_dtype),
                   jax.ShapeDtypeStruct((n_lists, bins, cap), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_lists * max_list * cap * dim,
            bytes_accessed=(data.dtype.itemsize * n_lists * max_list * dim
                            + 4 * n_lists * cap * dim
                            + 8 * n_lists * bins * cap),
            transcendentals=0),
        interpret=interpret,
    )(scale_arr, qsub, data, norms3, ids3)
    return cd, ci


def lc_mode() -> int:
    """Resolve the ``RAFT_TPU_IVF_LC`` override OUTSIDE jit (the
    ``gather_mode()`` contract): callers thread the value through the
    fused searches as a static argument, so the jit cache keys on it
    and an in-process env flip takes effect on the next call instead of
    silently re-executing the first-compiled program. 0 = auto."""
    import os
    return int(os.environ.get("RAFT_TPU_IVF_LC", "0"))


def _pick_lc(n_lists: int, max_list: int, cap: int, dim: int,
             itemsize: int, override: int = 0) -> int:
    """Lists per grid cell: enough to amortize per-step overhead while
    the (LC·max_list·dim) data block + score blocks stay well under the
    VMEM cap (double-buffered).

    ``override`` > 0 pins the value (snapped down to a divisor of
    n_lists) — resolved from ``RAFT_TPU_IVF_LC`` by ``lc_mode()`` at
    the public search entries and threaded here statically. ``1`` =
    grid-per-list: the PQ kernel's structure, loop-free kernel body,
    the compile-budget ladder's middle tier."""
    if override > 0:
        lc = min(override, n_lists)
        while n_lists % lc:
            lc -= 1
        return lc
    per_list = (max_list * dim * itemsize          # data block
                + cap * dim * 4                    # gathered queries
                + max_list * cap * 4               # score block
                + max_list * (4 + 4))              # norms + ids
    budget = _VMEM_LIMIT // 3
    # ≤ 8 bounds the grid-step working set; the kernel body itself is
    # lc-independent now (fori_loop), so this is a VMEM/pipelining
    # knob, not a program-size one
    lc = max(1, min(8, budget // max(per_list, 1)))
    while n_lists % lc:
        lc -= 1
    return lc


# ---------------------------------------------------------------------------
# Fused scan + select-k (ISSUE 7): the list scan keeps a running
# per-query top-k state RESIDENT IN VMEM across the list-chunk grid
# dimension — the same output-block-revisiting trick `_select_kernel`
# uses across candidate tiles, including its filtered-merge early-skip —
# so the (n_lists, bins, cap) candidate tensor never reaches HBM and
# the scan → gather → select_k chain collapses from three dispatches
# (two pallas_calls + an XLA gather) to ONE pallas_call.
# ---------------------------------------------------------------------------

# finite stand-in for +inf through the scatter matmul (inf · 0 = NaN
# would poison the one-hot accumulation); far above any real distance
_BIG_F32 = 3.0e38


def fused_mode() -> bool:
    """Resolve the ``RAFT_TPU_IVF_FUSED`` routing flag OUTSIDE jit (the
    ``lc_mode()``/``gather_mode()`` contract): callers thread it through
    the fused searches as a static argument so the jit cache keys on it.
    Default ON — the unfused Pallas / XLA tiers stay in the
    compile-budget ladder as fallbacks."""
    import os
    return os.environ.get("RAFT_TPU_IVF_FUSED", "1").lower() \
        not in ("0", "never", "off")


def _merge_state(od_ref, oi_ref, cd, ci, qm, *, k: int, cap_axis: int):
    """Scatter one list's candidate block onto the per-query running
    top-k state resident in the revisited ``(kp, nqp)`` output block,
    then an exact filtered merge.

    ``cd``/``ci`` carry the probing-slot axis at ``cap_axis`` (flat/bq
    bin-major ``(bins, cap)`` → 1; pq slot-major ``(cap, bins)`` → 0);
    ``qm`` (cap,) holds the list's probing-query ids (−1 pad). The
    scatter rides the MXU as one-hot × candidates: each list's slot →
    query map is injective (a query probes a list at most once), so
    every output lane receives EXACTLY one slot's value and, at
    ``Precision.HIGHEST``, the permutation is exact (products with 1.0,
    single nonzero per accumulation — even the 3×bf16 decomposition
    reconstructs f32 exactly). Ids split into f32-exact halves
    (``id >> 12`` and ``id & 0xFFF`` are both < 2^24 for id < 2^31;
    the −1 sentinel round-trips: (−1)·4096 + 4095 = −1). Lanes no slot
    maps to read ``_BIG_F32``/−1 and lose every merge; callers mask
    id < 0 → +inf after the final grid step.

    The merge is the ``_select_kernel`` filtered merge verbatim: if no
    scattered candidate beats any lane's current k-th best, the list is
    skipped after one vectorized compare; otherwise k rounds of
    (min, argmin-by-row, invalidate) over the concatenated
    [state; candidates] block re-sort the state in place.
    """
    nqp = od_ref.shape[1]
    cap = qm.shape[0]
    iq = jax.lax.broadcasted_iota(jnp.int32, (cap, nqp), 1)
    oh = ((qm[:, None] == iq) & (qm[:, None] >= 0)).astype(jnp.float32)
    mapped = jnp.max(oh, axis=0, keepdims=True) > 0.0    # (1, nqp)
    cn = (((cap_axis,), (0,)), ((), ()))
    hp = jax.lax.Precision.HIGHEST
    cdf = jnp.minimum(cd.astype(jnp.float32), _BIG_F32)
    sd = jax.lax.dot_general(cdf, oh, cn, precision=hp,
                             preferred_element_type=jnp.float32)
    hi = jax.lax.dot_general((ci >> 12).astype(jnp.float32), oh, cn,
                             precision=hp,
                             preferred_element_type=jnp.float32)
    lo = jax.lax.dot_general((ci & 0xFFF).astype(jnp.float32), oh, cn,
                             precision=hp,
                             preferred_element_type=jnp.float32)
    si = hi.astype(jnp.int32) * 4096 + lo.astype(jnp.int32)
    sd = jnp.where(mapped, sd, _BIG_F32)                 # (B, nqp)
    si = jnp.where(mapped, si, -1)
    b = sd.shape[0]

    kth = od_ref[k - 1:k, :]                             # (1, nqp)

    @pl.when(jnp.any(sd < kth))
    def _():
        c_d = jnp.concatenate([od_ref[0:k, :], sd], axis=0)
        c_i = jnp.concatenate([oi_ref[0:k, :], si], axis=0)
        ri = jax.lax.broadcasted_iota(jnp.int32, (k + b, nqp), 0)

        def round_(r, carry):
            cdd, cii = carry
            m_ = jnp.min(cdd, axis=0, keepdims=True)     # (1, nqp)
            first = jnp.min(jnp.where(cdd == m_, ri, _BIG_I32), axis=0,
                            keepdims=True)
            sel = ri == first                            # one-hot/lane
            idx = jnp.sum(jnp.where(sel, cii, 0), axis=0, keepdims=True)
            od_ref[pl.dslice(r, 1), :] = m_
            oi_ref[pl.dslice(r, 1), :] = idx
            return jnp.where(sel, jnp.inf, cdd), cii

        jax.lax.fori_loop(0, k, round_, (c_d, c_i), unroll=False)


def _init_state(od_ref, oi_ref):
    """First-grid-step init of the revisited top-k state block."""

    @pl.when(pl.program_id(0) == 0)
    def _():
        od_ref[...] = jnp.full(od_ref.shape, jnp.inf, od_ref.dtype)
        oi_ref[...] = jnp.full(oi_ref.shape, -1, jnp.int32)


def _finish_fused(od, oi, nq: int, k: int, sqrt: bool):
    """Tail of the fused scan+select calls: slice the resident state
    back to (nq, k) and apply the ``merge_candidates`` output
    conventions (id −1 ⇒ +inf distance, optional sqrt)."""
    d = od[:k, :nq].T
    i = oi[:k, :nq].T
    d = jnp.where(i >= 0, d, jnp.inf)
    if sqrt:
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    return d, i


def _pick_lc_fused(n_lists: int, max_list: int, cap: int, dim: int,
                   itemsize: int, k: int, nq: int, bins: int,
                   override: int = 0) -> int:
    """``_pick_lc`` with the fused kernel's extra VMEM residents: the
    (kp, nqp) state blocks (revisited outputs — live the whole grid)
    and the per-list scatter/merge temporaries (one-hot, scattered
    halves, merge concat). The temporaries don't scale with lc (the
    fori body reuses them) but they shrink the per-list budget."""
    if override > 0:
        lc = min(override, n_lists)
        while n_lists % lc:
            lc -= 1
        return lc
    kp = _round_up(k, 8)
    nqp = _round_up(nq, 128)
    fixed = (2 * kp * nqp * 8          # d+id state blocks
             + cap * nqp * 4           # one-hot
             + 3 * bins * nqp * 4      # scattered d / id halves
             + (k + bins) * nqp * 8)   # merge concat block
    per_list = (max_list * dim * itemsize
                + cap * dim * 4
                + max_list * cap * 4
                + max_list * (4 + 4))
    budget = max((_VMEM_LIMIT // 3) - fixed, 0)
    lc = max(1, min(8, budget // max(per_list, 1)))
    while n_lists % lc:
        lc -= 1
    return lc


def _fused_list_scan_kernel(scale_ref, qsub_ref, data_ref, norms_ref,
                            ids_ref, qmap_ref, od_ref, oi_ref, *,
                            lc: int, bins: int, k: int, metric: str,
                            precision):
    """IVF-Flat fine phase as ONE program: per list, the shared scoring
    + binned-candidate body, merged straight into the resident state."""
    scale = scale_ref[0, 0]
    _init_state(od_ref, oi_ref)

    def one_list(l):
        cd, ci = _flat_list_candidates(
            scale, qsub_ref[l], data_ref[l], norms_ref[l, 0],
            ids_ref[l, 0], bins=bins, metric=metric, precision=precision)
        _merge_state(od_ref, oi_ref, cd, ci, qmap_ref[l, 0], k=k,
                     cap_axis=1)

    if lc == 1:
        one_list(0)
    else:
        jax.lax.fori_loop(0, lc, lambda l, c: (one_list(l), c)[1], 0)


@functools.partial(jax.jit, static_argnames=("bins", "lc", "k", "nq",
                                             "metric", "interpret"))
def _fused_list_scan_call(qsub, data, norms, ids, qmap, bins: int,
                          lc: int, k: int, nq: int, scale,
                          interpret: bool, metric: str = "l2"):
    n_lists, cap, dim = qsub.shape
    max_list = data.shape[1]
    gc = n_lists // lc
    kp = _round_up(k, 8)
    nqp = _round_up(nq, 128)
    kern = functools.partial(
        _fused_list_scan_kernel, lc=lc, bins=bins, k=k, metric=metric,
        precision=kernel_matmul_mode(interpret))
    scale_arr = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    norms3 = norms[:, None, :]
    ids3 = ids[:, None, :]
    qmap3 = qmap[:, None, :]
    od, oi = pl.pallas_call(
        kern,
        grid=(gc,),
        in_specs=[pl.BlockSpec((1, 1), lambda g: (0, 0)),
                  pl.BlockSpec((lc, cap, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, max_list, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, cap), lambda g: (g, 0, 0))],
        # the whole (kp, nqp) state is ONE block revisited by every
        # grid step (constant index map) — it stays in VMEM across the
        # list grid and is written back once
        out_specs=[pl.BlockSpec((kp, nqp), lambda g: (0, 0)),
                   pl.BlockSpec((kp, nqp), lambda g: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((kp, nqp), jnp.float32),
                   jax.ShapeDtypeStruct((kp, nqp), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_lists * max_list * cap * dim
            + 6 * n_lists * bins * cap * nqp,
            bytes_accessed=(data.dtype.itemsize * n_lists * max_list * dim
                            + 4 * n_lists * cap * dim + 8 * kp * nqp),
            transcendentals=0),
        interpret=interpret,
    )(scale_arr, qsub, data, norms3, ids3, qmap3)
    return od, oi


def _fused_bq_scan_kernel(qsub_ref, bits_ref, norms2_ref, scales_ref,
                          ids_ref, qmap_ref, cent_ref, od_ref, oi_ref, *,
                          lc: int, bins: int, dim: int, k: int,
                          metric: str):
    _init_state(od_ref, oi_ref)

    def one_list(l):
        cd, ci = _bq_list_candidates(
            qsub_ref[l], bits_ref[l], norms2_ref[l, 0], scales_ref[l, 0],
            ids_ref[l, 0], bins=bins, dim=dim, metric=metric)
        if metric == "ip":
            # the per-(list, slot) center term −q·c_l — the unfused
            # tier's post-scan rank-1 correction applied in-kernel:
            # constant per slot, so it commutes with the binned min
            corr = jnp.sum(qsub_ref[l] * cent_ref[l, 0][None, :], axis=1)
            cd = cd - corr[None, :]
        _merge_state(od_ref, oi_ref, cd, ci, qmap_ref[l, 0], k=k,
                     cap_axis=1)

    if lc == 1:
        one_list(0)
    else:
        jax.lax.fori_loop(0, lc, lambda l, c: (one_list(l), c)[1], 0)


@functools.partial(jax.jit, static_argnames=("bins", "lc", "dim", "k",
                                             "nq", "interpret", "metric"))
def _fused_bq_scan_call(qsub, bits_i32, norms2, scales, ids, qmap,
                        centers_rot, bins: int, lc: int, dim: int,
                        k: int, nq: int, interpret: bool,
                        metric: str = "l2"):
    n_lists, cap, _ = qsub.shape
    max_list = bits_i32.shape[1]
    w = bits_i32.shape[2]
    gc = n_lists // lc
    kp = _round_up(k, 8)
    nqp = _round_up(nq, 128)
    kern = functools.partial(_fused_bq_scan_kernel, lc=lc, bins=bins,
                             dim=dim, k=k, metric=metric)
    norms3 = norms2[:, None, :]
    scales3 = scales[:, None, :]
    ids3 = ids[:, None, :]
    qmap3 = qmap[:, None, :]
    cent3 = centers_rot[:, None, :]
    od, oi = pl.pallas_call(
        kern,
        grid=(gc,),
        in_specs=[pl.BlockSpec((lc, cap, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, max_list, w), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, cap), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, dim), lambda g: (g, 0, 0))],
        out_specs=[pl.BlockSpec((kp, nqp), lambda g: (0, 0)),
                   pl.BlockSpec((kp, nqp), lambda g: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((kp, nqp), jnp.float32),
                   jax.ShapeDtypeStruct((kp, nqp), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_lists * max_list * cap * dim
            + 6 * n_lists * bins * cap * nqp,
            bytes_accessed=(4 * n_lists * max_list * w
                            + 4 * n_lists * cap * dim + 8 * kp * nqp),
            transcendentals=0),
        interpret=interpret,
    )(qsub, bits_i32, norms3, scales3, ids3, qmap3, cent3)
    return od, oi


def _fused_pq_scan_kernel(qsub_ref, codes_ref, norms_ref, ids_ref,
                          books_ref, qmap_ref, cent_ref, od_ref, oi_ref,
                          *, bins: int, k: int, metric: str, pq_dim: int,
                          pq_len: int, n_codes: int, lut_dtype,
                          per_cluster: bool):
    _init_state(od_ref, oi_ref)
    cd, ci = _pq_cell_candidates(
        qsub_ref[0], codes_ref[0], norms_ref[0, 0], ids_ref[0, 0],
        books_ref, bins=bins, metric=metric, pq_dim=pq_dim,
        pq_len=pq_len, n_codes=n_codes, lut_dtype=lut_dtype,
        per_cluster=per_cluster)
    if metric == "ip":
        # −q·c_l in-kernel (see _fused_bq_scan_kernel); for PER_CLUSTER
        # both operands arrive p-major permuted — the dot is invariant
        corr = jnp.sum(qsub_ref[0] * cent_ref[0, 0][None, :], axis=1)
        cd = cd - corr[:, None]
    _merge_state(od_ref, oi_ref, cd, ci, qmap_ref[0, 0], k=k, cap_axis=0)


@functools.partial(jax.jit, static_argnames=("bins", "k", "nq", "metric",
                                             "lut_dtype", "interpret",
                                             "split", "per_cluster"))
def _fused_pq_scan_call(qsub, codes_t, norms, ids, books, qmap,
                        centers_rot, bins: int, k: int, nq: int,
                        interpret: bool, metric: str, lut_dtype,
                        split: int = 1, per_cluster: bool = False):
    """The fused tail of the code scan: same grid/operands as
    ``_pq_scan_call`` (incl. the ``split`` sub-cell sharing of a list's
    query/qmap blocks via ``g // split``) plus the qmap and rotated
    centers, with the candidate blocks replaced by the revisited
    state."""
    n_lists, cap, rot_dim = qsub.shape
    n_cells, pq_dim, max_list = codes_t.shape
    kp = _round_up(k, 8)
    nqp = _round_up(nq, 128)
    if per_cluster:
        n_codes, pq_len = books.shape[1], books.shape[2]
        books_spec = pl.BlockSpec((1, n_codes, pq_len),
                                  lambda g: (g // split, 0, 0))
    else:
        n_codes = books.shape[1] // pq_dim
        pq_len = rot_dim // pq_dim
        books_spec = pl.BlockSpec((rot_dim, pq_dim * n_codes),
                                  lambda g: (0, 0))
    kern = functools.partial(
        _fused_pq_scan_kernel, bins=bins, k=k, metric=metric,
        pq_dim=pq_dim, pq_len=pq_len, n_codes=n_codes,
        lut_dtype=jnp.dtype(lut_dtype), per_cluster=per_cluster)
    norms3 = norms[:, None, :]
    ids3 = ids[:, None, :]
    qmap3 = qmap[:, None, :]
    cent3 = centers_rot[:, None, :]
    od, oi = pl.pallas_call(
        kern,
        grid=(n_cells,),
        in_specs=[pl.BlockSpec((1, cap, rot_dim),
                               lambda g: (g // split, 0, 0)),
                  pl.BlockSpec((1, pq_dim, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((1, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((1, 1, max_list), lambda g: (g, 0, 0)),
                  books_spec,
                  pl.BlockSpec((1, 1, cap), lambda g: (g // split, 0, 0)),
                  pl.BlockSpec((1, 1, rot_dim),
                               lambda g: (g // split, 0, 0))],
        out_specs=[pl.BlockSpec((kp, nqp), lambda g: (0, 0)),
                   pl.BlockSpec((kp, nqp), lambda g: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((kp, nqp), jnp.float32),
                   jax.ShapeDtypeStruct((kp, nqp), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_cells * max_list * rot_dim * pq_dim * n_codes
            + 2 * n_cells * max_list * cap * rot_dim
            + 6 * n_cells * bins * cap * nqp,
            bytes_accessed=(n_cells * max_list * pq_dim
                            + 4 * n_lists * cap * rot_dim + 8 * kp * nqp),
            transcendentals=0),
        interpret=interpret,
    )(qsub, jax.lax.bitcast_convert_type(codes_t, jnp.int8), norms3,
      ids3, books, qmap3, cent3)
    return od, oi


class _Layout:
    """Shared prologue of both list-major scans: bins resolution, probe
    inversion, list-axis padding to a bins multiple, lane-aligned
    inverted-table width.

    ``bins``: 0 = auto — 4k bins. IVF lists concentrate a query's true
    neighbors far more than brute-force tiles do, so the collision
    budget needs more width than fused_knn's 2k default (recall 0.944 →
    0.97+ at 16/64 probes on clustered data); the merge rides the fast
    select_k, so the wider candidate set costs little. -1 = exact (one
    row per bin); >0 explicit.
    """

    def __init__(self, probes, n_lists: int, max_list: int, cap: int,
                 bins: int, k: int):
        from raft_tpu.neighbors._ivf_scan import _invert_probes
        if bins == 0:
            bins = min(max(4 * k, 64), max_list)
        self.qmap, self.inv_pos = _invert_probes(probes, n_lists, cap)
        # pad the list axis so bins divides it (pad rows: id -1 → +inf)
        self.mlp = _round_up(max_list, bins if bins > 0 else 1)
        self.bins = self.mlp if bins < 0 else bins
        self.cap = cap
        self.capp = _round_up(max(cap, 8), 8)  # lane-aligned table width

    def pad_lists(self, arr, max_list: int, fill=0):
        if self.mlp == max_list:
            return arr
        pad = [(0, 0), (0, self.mlp - max_list)] + [(0, 0)] * (arr.ndim - 2)
        return jnp.pad(arr, pad, constant_values=fill)

    def padded_qmap(self):
        if self.capp == self.cap:
            return self.qmap
        return jnp.pad(self.qmap, ((0, 0), (0, self.capp - self.cap)),
                       constant_values=-1)

    def merge(self, cd, ci, probes, k: int, sqrt: bool):
        cd = jnp.swapaxes(cd, 1, 2)                # (n_lists, cap, B)
        ci = jnp.swapaxes(ci, 1, 2)
        return self.merge_cap_major(cd, ci, probes, k, sqrt)

    def merge_cap_major(self, cd, ci, probes, k: int, sqrt: bool):
        """Merge candidate blocks already in (n_lists, cap, B) layout."""
        from raft_tpu.neighbors._ivf_scan import merge_candidates
        return merge_candidates(
            cd[:, :self.cap].astype(jnp.float32), ci[:, :self.cap],
            probes, self.inv_pos, k, sqrt, use_pallas_select=True,
            cap=self.cap)


def ivf_list_scan_pallas(queries, lists_data, lists_norms, lists_indices,
                         probes, k: int, cap: int, scale=1.0,
                         bins: int = 0, sqrt: bool = False,
                         metric: str = "l2", gather: str = "",
                         internal_dtype=None, lc: int = 0,
                         fused: bool = False):
    """Fused list-major IVF-Flat fine scan + merge.

    ``queries`` (nq, dim) f32; ``lists_data`` (n_lists, max_list, dim)
    f32/bf16/int8; ``probes`` (nq, n_probes) int32; ``cap`` the inverted
    table width (``_ivf_scan.probe_cap``). ``bins``: see ``_Layout``.
    ``metric``: "l2" (squared, ``sqrt`` optional) or "ip" (returns
    NEGATED similarities, ascending — callers postprocess). ``lc``:
    lists per grid cell, 0 = auto (callers resolve ``lc_mode()``
    outside jit). ``fused``: keep the top-k state resident in the scan
    kernel (ONE pallas_call — no candidate tensor, no gather, no
    select_k dispatch; callers resolve ``fused_mode()`` outside jit).
    Returns (dists (nq, k), ids (nq, k)) sorted best-first.
    """
    nq, dim = queries.shape
    n_lists, max_list = lists_indices.shape
    lay = _Layout(probes, n_lists, max_list, cap, bins, k)
    lists_data = lay.pad_lists(lists_data, max_list)
    lists_norms = lay.pad_lists(lists_norms, max_list)
    lists_indices = lay.pad_lists(lists_indices, max_list, fill=-1)

    # pre-gather: each list's probing queries → (n_lists, cap, dim).
    # ~cap/mean-probes ≤ 2× the query bytes; read once by the kernel.
    # Strategy (row gather vs one-hot MXU) via RAFT_TPU_GATHER; jitted
    # callers pass it resolved (``gather``) so the env isn't trace-frozen
    from raft_tpu.neighbors._ivf_scan import gather_query_rows
    qsub = gather_query_rows(queries, lay.padded_qmap(), mode=gather)
    if fused:
        lc = _pick_lc_fused(n_lists, lay.mlp, lay.capp, dim,
                            lists_data.dtype.itemsize, k, nq, lay.bins,
                            override=lc)
        od, oi = _fused_list_scan_call(
            qsub, lists_data, lists_norms, lists_indices,
            lay.padded_qmap(), lay.bins, lc, k, nq, scale,
            pallas_interpret(), metric=metric)
        return _finish_fused(od, oi, nq, k, sqrt)
    lc = _pick_lc(n_lists, lay.mlp, lay.capp, dim,
                  lists_data.dtype.itemsize, override=lc)
    # internal_dtype: candidate-block dtype carried to the merge (the
    # IVF-PQ internal_distance_dtype role) — bf16 halves the kernel's
    # HBM writeback+readback; the merge re-ranks in f32 either way
    cd, ci = _list_scan_call(qsub, lists_data, lists_norms, lists_indices,
                             lay.bins, lc, scale, pallas_interpret(),
                             metric=metric,
                             out_dtype=internal_dtype or jnp.float32)
    return lay.merge(cd, ci, probes, k, sqrt)


def _bq_list_candidates(q, words, n2_l, sc_l, ids, *, bins: int,
                        dim: int, metric: str):
    """One BQ list's binned estimator candidates (the shared per-list
    body — see ``_flat_list_candidates``). ``q`` (cap, dim) f32 probing
    queries (center-offset for the l2 core), ``words`` (ML, w) int32
    bit payload → ``(cd (bins, cap), ci (bins, cap))``."""
    ml = words.shape[0]
    cap = q.shape[0]
    w = words.shape[1]
    cols = []
    for j in range(w):
        wj = words[:, j:j + 1]                       # (ML, 1)
        sh = jax.lax.broadcasted_iota(jnp.int32, (1, 32), 1)
        # (x >> s) & 1 extracts bit s for any int32 x, arithmetic
        # shift included — only bit 0 of the shifted value is read
        cols.append((jax.lax.shift_right_logical(
            jnp.broadcast_to(wj, (ml, 32)),
            jnp.broadcast_to(sh, (ml, 32))) & 1))
    bits = jnp.concatenate(cols, axis=1)[:, :dim]    # (ML, dim) 0/1
    pm1 = (2 * bits - 1).astype(jnp.bfloat16)        # ±1
    ip = jax.lax.dot_general(
        pm1, q.astype(jnp.bfloat16), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (ML, cap)
    qq = jnp.sum(q * q, axis=1)[None, :]             # (1, cap)
    n2 = n2_l[:, None]                               # (ML, 1)
    sc = sc_l[:, None]                               # (ML, 1)
    ids_b = jnp.broadcast_to(ids[:, None], (ml, cap))
    if metric == "ip":
        # estimator core −s·⟨q, dec⟩; the per-(list, query) center
        # term −q·c_l is a rank-1 correction applied to the
        # candidate blocks AFTER the scan (the ivf_pq ip pattern;
        # the fused kernel applies it in-kernel — constant per slot,
        # so it commutes with the binned min)
        d = -(sc * ip)
    else:
        d = n2 + qq - 2.0 * sc * ip
    # NO maximum(d, 0) clamp here: the 1-bit estimator legitimately
    # goes negative when it overshoots near a true neighbor, and
    # clamping would collapse exactly the strongest candidates into
    # id-order ties (unlike the exact-distance kernels, where the
    # clamp only removes fp noise). The XLA tier matches.
    d = jnp.where(ids_b >= 0, d, jnp.inf)
    wb = ml // bins
    db_ = d.reshape(wb, bins, cap)                   # strided bins
    cd = jnp.min(db_, axis=0)
    rb = ids_b.reshape(wb, bins, cap)
    ci = jnp.min(jnp.where(db_ == cd[None, :, :], rb, _BIG_I32),
                 axis=0)
    return cd, jnp.where(ci == _BIG_I32, -1, ci)


def _bq_scan_kernel(qsub_ref, bits_ref, norms2_ref, scales_ref, ids_ref,
                    cd_ref, ci_ref, *, lc: int, bins: int, dim: int,
                    metric: str):
    """Binary-quantized list scan (ivf_bq's fine phase): unpack the
    1-bit sign codes to a transient ±1 bf16 tile IN VMEM — the 8×-HBM
    win over reading bf16 rows — then the same transposed-score
    geometry as ``_list_scan_kernel`` (rows on sublanes, probing
    queries on lanes) and its strided binned partial top-k.

    Estimator: ``est = ||q_l||² + ||r||² − 2·s·⟨q_l, sign(r)⟩``
    (see ivf_bq.py). Shift/mask unpack loops over the w ≤ dim/32 words
    in Python — w is 4 at d=128, so that unroll stays tiny; the list
    loop is a fori_loop like ``_list_scan_kernel``'s (program size
    must not scale with lc).
    """
    def one_list(l):
        cd, ci = _bq_list_candidates(
            qsub_ref[l], bits_ref[l], norms2_ref[l, 0], scales_ref[l, 0],
            ids_ref[l, 0], bins=bins, dim=dim, metric=metric)
        cd_ref[l] = cd.astype(cd_ref.dtype)
        ci_ref[l] = ci

    if lc == 1:
        one_list(0)
    else:
        jax.lax.fori_loop(0, lc, lambda l, c: (one_list(l), c)[1], 0)


@functools.partial(jax.jit, static_argnames=("bins", "lc", "dim",
                                             "interpret", "metric"))
def _bq_scan_call(qsub, bits_i32, norms2, scales, ids, bins: int,
                  lc: int, dim: int, interpret: bool,
                  metric: str = "l2"):
    n_lists, cap, _ = qsub.shape
    max_list = bits_i32.shape[1]
    w = bits_i32.shape[2]
    gc = n_lists // lc
    kern = functools.partial(_bq_scan_kernel, lc=lc, bins=bins, dim=dim,
                             metric=metric)
    norms3 = norms2[:, None, :]
    scales3 = scales[:, None, :]
    ids3 = ids[:, None, :]
    cd, ci = pl.pallas_call(
        kern,
        grid=(gc,),
        in_specs=[pl.BlockSpec((lc, cap, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, max_list, w), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, 1, max_list), lambda g: (g, 0, 0))],
        out_specs=[pl.BlockSpec((lc, bins, cap), lambda g: (g, 0, 0)),
                   pl.BlockSpec((lc, bins, cap), lambda g: (g, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_lists, bins, cap),
                                        jnp.float32),
                   jax.ShapeDtypeStruct((n_lists, bins, cap), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_lists * max_list * cap * dim,
            bytes_accessed=(4 * n_lists * max_list * w
                            + 4 * n_lists * cap * dim
                            + 8 * n_lists * bins * cap),
            transcendentals=0),
        interpret=interpret,
    )(qsub, bits_i32, norms3, scales3, ids3)
    return cd, ci


def ivf_bq_scan_pallas(q_rot, centers_rot, bits, norms2, scales,
                       lists_indices, probes, k: int, cap: int,
                       bins: int = 0, sqrt: bool = False,
                       gather: str = "", metric: str = "l2",
                       lc: int = 0, fused: bool = False):
    """Fused Pallas fine phase for ivf_bq: probe inversion + per-list
    query gather (rotated; center-offset for the l2 core) + the in-VMEM
    unpack scan + the shared candidate merge. Mirrors
    ``ivf_list_scan_pallas`` (incl. ``fused`` — the single-pallas_call
    scan+select tier, with the ip center term applied in-kernel);
    unfused ``metric`` "ip" scores negated similarities with the center
    term applied post-scan."""
    nq, dim = q_rot.shape
    n_lists, max_list = lists_indices.shape
    lay = _Layout(probes, n_lists, max_list, cap, bins, k)
    bits_i32 = jax.lax.bitcast_convert_type(bits, jnp.int32)
    bits_i32 = lay.pad_lists(bits_i32, max_list)
    norms2 = lay.pad_lists(norms2, max_list)
    scales = lay.pad_lists(scales, max_list)
    lists_indices = lay.pad_lists(lists_indices, max_list, fill=-1)
    from raft_tpu.neighbors._ivf_scan import gather_query_rows
    qg = gather_query_rows(q_rot, lay.padded_qmap(), mode=gather)
    qsub = qg if metric == "ip" else qg - centers_rot[:, None, :]
    if fused:
        lc = _pick_lc_fused(n_lists, lay.mlp, lay.capp, dim, 2, k, nq,
                            lay.bins, override=lc)
        od, oi = _fused_bq_scan_call(
            qsub, bits_i32, norms2, scales, lists_indices,
            lay.padded_qmap(), centers_rot, lay.bins, lc, dim, k, nq,
            pallas_interpret(), metric=metric)
        return _finish_fused(od, oi, nq, k, sqrt)
    # VMEM: the unpacked (ML, dim) bf16 tile + (ML, cap) scores dominate
    lc = _pick_lc(n_lists, lay.mlp, lay.capp, dim, 2, override=lc)
    cd, ci = _bq_scan_call(qsub, bits_i32, norms2, scales,
                           lists_indices, lay.bins, lc, dim,
                           pallas_interpret(), metric=metric)
    if metric == "ip":
        # kernel scored −s·⟨q, dec⟩; complete −q·x with the center term
        from raft_tpu.core.precision import matmul_precision
        corr = jnp.einsum("lqd,ld->lq", qsub, centers_rot,
                          precision=matmul_precision(),
                          preferred_element_type=jnp.float32)
        cd = cd.astype(jnp.float32) - corr[:, None, :]  # (L, bins, capp)
    return lay.merge(cd, ci, probes, k, sqrt)


def _pq_scan_kernel(qsub_ref, codes_ref, norms_ref, ids_ref, books_ref,
                    cd_ref, ci_ref, *, bins: int, metric: str, pq_dim: int,
                    pq_len: int, n_codes: int, lut_dtype,
                    per_cluster: bool):
    """One IVF list per grid cell, scored straight from its u8 codes.

    Decode is ONE one-hot × codebook matmul on the MXU, lanes-major
    over list rows: the codes arrive pre-transposed (pq_dim, ML), one
    vectorized compare builds the stacked one-hot
    ``oh[(s, c), m] = (codes_t[s, m] == c)`` of shape (pq_dim·C, ML),
    and the BLOCK-DIAGONAL codebook matrix ``B`` (rot_dim, pq_dim·C) —
    built once outside the kernel, ``B[s·pl:(s+1)·pl, s·C:(s+1)·C] =
    books[s]ᵀ`` — decodes every subspace in a single K = pq_dim·C
    matmul: ``dec_t = B @ oh`` (rot_dim, ML). Each dec row still
    selects exactly ONE codeword entry (the off-block zeros contribute
    nothing), so values are bit-identical to a per-subspace gather; the
    formulation trades the old pq_dim-unrolled strip loop (a Mosaic
    program that GREW with pq_dim — the r3 compile-hazard class, and
    ~3% MXU utilization at M = pq_len) for one fully-utilized matmul.
    The decode tile lives and dies in VMEM (the reference's smem-LUT
    property, ivf_pq_search.cuh:593) and ONE K = rot_dim matmul scores
    all probing queries against it.

    PER_CLUSTER: the cell's single codebook (C, pl) is shared across
    subspaces, so block-diagonal stacking would need a per-list B.
    Instead the one-hot stacks on the LANE axis — ``oh2`` (C,
    pq_dim·ML) from the flattened codes — and ``bookᵀ @ oh2`` decodes
    all subspaces at once into (pl, pq_dim·ML) ≡ p-major rows
    (pl·pq_dim, ML); the probing queries arrive pre-permuted to the
    matching p-major column order (``_PER_CLUSTER_PERM``), so scoring
    needs no in-kernel transpose.
    """
    cd, ci = _pq_cell_candidates(
        qsub_ref[0], codes_ref[0], norms_ref[0, 0], ids_ref[0, 0],
        books_ref, bins=bins, metric=metric, pq_dim=pq_dim,
        pq_len=pq_len, n_codes=n_codes, lut_dtype=lut_dtype,
        per_cluster=per_cluster)
    cd_ref[0] = cd.astype(cd_ref.dtype)
    ci_ref[0] = ci


def _pq_cell_candidates(q, codes_i8, norms_l, ids, books_ref, *,
                        bins: int, metric: str, pq_dim: int, pq_len: int,
                        n_codes: int, lut_dtype, per_cluster: bool):
    """One PQ cell's binned candidates scored straight from its u8
    codes (the shared per-cell body — see ``_flat_list_candidates``;
    ``books_ref`` stays a ref because PER_CLUSTER reads a per-cell
    block while PER_SUBSPACE reads the shared decode matrix).
    Returns ``(cd (cap, bins), ci (cap, bins))`` — slot-major, unlike
    the flat/bq helpers."""
    # codes arrive as i8 bitcast of the u8 store (1 B/code of HBM
    # traffic), pre-transposed; recover 0..255 with a mask
    codes_t = codes_i8.astype(jnp.int32) & 0xFF      # (pq_dim, ML)
    ml = codes_t.shape[1]
    cap = q.shape[0]
    # bf16 LUT = single MXU pass (the reference's fp16-LUT speed tier);
    # f32 LUT = HIGHEST-precision passes (its fp32 accuracy tier);
    # fp8 LUT (float8_e4m3fn) = books arrive fp8-quantized — half the
    # codebook VMEM/HBM of bf16 (the reference's fp_8bit tier,
    # ivf_pq_search.cuh:780-1004) — and upcast to bf16 for the MXU
    f32_lut = jnp.dtype(lut_dtype) == jnp.dtype(jnp.float32)
    operand = jnp.float32 if f32_lut else jnp.bfloat16
    prec = jax.lax.Precision.HIGHEST if f32_lut else None

    if per_cluster:
        codes_flat = codes_t.reshape(1, pq_dim * ml)
        iota = jax.lax.broadcasted_iota(
            jnp.int32, (n_codes, pq_dim * ml), 0)
        oh2 = (iota == codes_flat).astype(operand)   # (C, pq_dim·ML)
        book = books_ref[0]                          # (C, pl)
        dec_pm = jax.lax.dot_general(
            book.astype(operand), oh2, (((0,), (0,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32)      # (pl, pq_dim·ML)
        dec_t = dec_pm.reshape(pq_len * pq_dim, ml)  # p-major rows
    else:
        iota = jax.lax.broadcasted_iota(
            jnp.int32, (pq_dim, n_codes, ml), 1)
        oh = (iota == codes_t[:, None, :]).astype(operand)
        oh2 = oh.reshape(pq_dim * n_codes, ml)
        dec_t = jax.lax.dot_general(
            books_ref[...].astype(operand), oh2, (((1,), (0,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32)      # (rot_dim, ML)

    ip = jax.lax.dot_general(
        q.astype(operand), dec_t.astype(operand),
        (((1,), (0,)), ((), ())), precision=prec,
        preferred_element_type=jnp.float32)          # (cap, ML)
    ids_b = jnp.broadcast_to(ids[None, :], (cap, ml))
    if metric == "ip":
        d = jnp.where(ids_b >= 0, -ip, jnp.inf)
    else:
        rr = jnp.sum(q * q, axis=1)[:, None]             # (cap, 1)
        d = rr + norms_l[None, :] - 2.0 * ip
        d = jnp.where(ids_b >= 0, jnp.maximum(d, 0.0), jnp.inf)

    # strided bins along the row axis (row r → bin r % B), row-major
    # reshape (cap, w, B): element [., i, b] = row i·B + b
    w = ml // bins
    db_ = d.reshape(cap, w, bins)
    cd = jnp.min(db_, axis=1)                            # (cap, B)
    rb = ids_b.reshape(cap, w, bins)
    ci = jnp.min(jnp.where(db_ == cd[:, None, :], rb, _BIG_I32), axis=1)
    return cd, jnp.where(ci == _BIG_I32, -1, ci)


@functools.partial(jax.jit, static_argnames=("bins", "metric", "out_dtype",
                                             "lut_dtype", "interpret",
                                             "split", "per_cluster"))
def _pq_scan_call(qsub, codes_t, norms, ids, books, bins: int,
                  interpret: bool, metric: str, lut_dtype,
                  out_dtype=jnp.float32, split: int = 1,
                  per_cluster: bool = False):
    """``split`` > 1: codes/norms/ids carry ``split`` sub-lists per
    original list (leading dim n_lists·split); the query blocks stay
    per-ORIGINAL-list and are shared across a list's sub-cells via the
    index map — no duplicated HBM. ``codes_t`` arrives pre-transposed
    (n_cells, pq_dim, sub_ml) u8. ``books``: PER_SUBSPACE — the
    block-diagonal decode matrix (rot_dim, pq_dim·C), one shared block
    fetched once; PER_CLUSTER — (n_lists, C, pl), each cell fetches its
    own list's codebook (and ``qsub`` arrives p-major permuted, see
    ``_pq_scan_kernel``)."""
    n_lists, cap, rot_dim = qsub.shape
    n_cells, pq_dim, max_list = codes_t.shape
    if per_cluster:
        n_codes, pq_len = books.shape[1], books.shape[2]
        books_spec = pl.BlockSpec((1, n_codes, pq_len),
                                  lambda g: (g // split, 0, 0))
    else:
        n_codes = books.shape[1] // pq_dim
        pq_len = rot_dim // pq_dim
        books_spec = pl.BlockSpec((rot_dim, pq_dim * n_codes),
                                  lambda g: (0, 0))
    kern = functools.partial(
        _pq_scan_kernel, bins=bins, metric=metric, pq_dim=pq_dim,
        pq_len=pq_len, n_codes=n_codes,
        lut_dtype=jnp.dtype(lut_dtype), per_cluster=per_cluster)
    # norms/ids carry a singleton middle axis (see _list_scan_call): the
    # 2-D (1, max_list) block put 1 in a Mosaic-constrained slot and
    # failed to lower on real TPU (r3 measurement)
    norms3 = norms[:, None, :]
    ids3 = ids[:, None, :]
    cd, ci = pl.pallas_call(
        kern,
        grid=(n_cells,),
        in_specs=[pl.BlockSpec((1, cap, rot_dim),
                               lambda g: (g // split, 0, 0)),
                  pl.BlockSpec((1, pq_dim, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((1, 1, max_list), lambda g: (g, 0, 0)),
                  pl.BlockSpec((1, 1, max_list), lambda g: (g, 0, 0)),
                  books_spec],
        out_specs=[pl.BlockSpec((1, cap, bins), lambda g: (g, 0, 0)),
                   pl.BlockSpec((1, cap, bins), lambda g: (g, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_cells, cap, bins), out_dtype),
                   jax.ShapeDtypeStruct((n_cells, cap, bins), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            # dec = B @ oh (K = pq_dim·C dense — the one-hot formulation
            # pays C× the gather FLOPs to stay on the MXU) + the score
            flops=2 * n_cells * max_list * rot_dim * pq_dim * n_codes
            + 2 * n_cells * max_list * cap * rot_dim,
            bytes_accessed=(n_cells * max_list * pq_dim
                            + 4 * n_lists * cap * rot_dim
                            + 8 * n_cells * cap * bins),
            transcendentals=0),
        interpret=interpret,
    )(qsub, jax.lax.bitcast_convert_type(codes_t, jnp.int8), norms3, ids3,
      books)
    return cd, ci


def ivf_pq_code_scan_pallas(q_rot, centers_rot, pq_centers, codes,
                            code_norms, lists_indices, probes, k: int,
                            cap: int, bins: int = 0, sqrt: bool = False,
                            lut_dtype=jnp.bfloat16,
                            internal_distance_dtype=jnp.float32,
                            metric: str = "l2",
                            per_cluster: bool = False,
                            gather: str = "", fused: bool = False):
    """IVF-PQ fine scan directly over the compressed codes.

    Reference ``ivf_pq_search.cuh:593`` scans the bit-packed
    ``pq_dataset`` against a smem LUT. Per-lane LUT gathers are hostile
    to the TPU (XLA lowers them to the scalar core), so the TPU
    formulation decodes **inside the kernel** with one-hot × codebook
    MXU matmuls (``_pq_scan_kernel``): the u8 codes (pq_dim B/vector)
    are the only persistent payload; the (rot_dim, max_list) decode tile
    lives and dies in VMEM — the "on-the-fly decode tile that never
    persists". For L2 each list's probing queries are pre-offset by its
    rotated center so the kernel scores ``||(q_rot − c_l) − decoded||²``;
    IP adds the center term to the decode tile instead.

    The reference's LUT-precision variants (``ivf_pq_search.cuh:
    780-1004``, fp32/fp16/fp8 LUT × fp32/fp16 internal) map to
    ``lut_dtype`` — the decode/score operand dtype (bf16 = one MXU pass,
    f32 = highest-precision passes) — and ``internal_distance_dtype`` —
    the candidate score dtype carried to the merge (bf16 halves
    candidate HBM).

    ``code_norms`` are exact: PQ subspaces concatenate orthogonally, so
    ``||decoded_i||² = Σ_s ||book_s[c_is]||²`` is computed once at build
    from the codebook norm table.
    """
    nq = q_rot.shape[0]
    n_lists, max_list, pq_dim = codes.shape
    _, n_codes, pq_len = pq_centers.shape
    lay = _Layout(probes, n_lists, max_list, cap, bins, k)
    codes = lay.pad_lists(codes, max_list)
    code_norms = lay.pad_lists(code_norms, max_list)
    lists_indices = lay.pad_lists(lists_indices, max_list, fill=-1)
    from raft_tpu.neighbors._ivf_scan import gather_query_rows
    qg = gather_query_rows(q_rot, lay.padded_qmap(), mode=gather)
    if metric == "ip":
        # IP decomposes linearly: q·(c_l + dec) = q·c_l + q·dec. The
        # kernel scores plain rotated queries against decoded residuals
        # (-q·dec); the per-(list, query) center term is a rank-1
        # correction applied to the candidate blocks after the scan.
        qsub = qg
    else:
        # per-list probing queries, residual form: (n_lists, cap, rot_dim)
        qsub = qg - centers_rot[:, None, :]

    rot_dim = pq_dim * pq_len
    fp8 = jnp.dtype(lut_dtype) == jnp.dtype(jnp.float8_e4m3fn)
    f32_lut = jnp.dtype(lut_dtype) == jnp.dtype(jnp.float32)
    operand = jnp.float32 if f32_lut else jnp.bfloat16
    if per_cluster:
        # per-list books ride full precision except the fp8 tier
        # (storage quantization; compute upcasts to bf16 in-kernel)
        books_in = (pq_centers.astype(jnp.float8_e4m3fn) if fp8
                    else pq_centers)
    else:
        # PER_SUBSPACE: build the block-diagonal decode matrix ONCE —
        # B[s·pl:(s+1)·pl, s·C:(s+1)·C] = books[s]ᵀ. Every dec row
        # still selects exactly one codeword (off-block zeros), so the
        # kernel's single K = pq_dim·C matmul is value-identical to
        # per-subspace strips; stored in the compute operand dtype
        # (fp8 for the fp8 tier — half the block's VMEM/HBM)
        B = jnp.zeros((rot_dim, pq_dim * n_codes), jnp.float32)
        for s in range(pq_dim):
            B = jax.lax.dynamic_update_slice(
                B, pq_centers[s].T, (s * pq_len, s * n_codes))
        # fp8 tier: codebook STORAGE quantizes (callers pass code_norms
        # computed over the fp8 books — ivf_pq.search caches that
        # table — so the L2 epilogue stays self-consistent)
        books_in = B.astype(jnp.float8_e4m3fn if fp8 else operand)

    # VMEM bound: per grid cell the stacked one-hot (pq_dim·C, sub_ml),
    # decode tile (rot_dim, sub_ml) and score block (cap, sub_ml) all
    # scale with the list length — split oversized lists into `split`
    # sub-lists (extra grid cells sharing the list's probing queries)
    # so skewed or low-n_lists indexes still compile.
    op_item = 4 if f32_lut else 2
    per_row = (pq_dim * n_codes * op_item + rot_dim * 4 + lay.capp * 4
               + pq_dim * 4)
    row_budget = max(lay.bins, (_VMEM_LIMIT // 3) // per_row)
    split = -(-lay.mlp // _round_up(row_budget, lay.bins))
    sub_ml = _round_up(-(-lay.mlp // split), lay.bins)
    mlp2 = sub_ml * split
    if mlp2 != lay.mlp:
        pad = [(0, 0), (0, mlp2 - lay.mlp)]
        codes = jnp.pad(codes, pad + [(0, 0)])
        code_norms = jnp.pad(code_norms, pad)
        lists_indices = jnp.pad(lists_indices, pad, constant_values=-1)

    def as_sub(a):
        return a.reshape(n_lists * split, sub_ml, *a.shape[2:])

    if per_cluster:
        # p-major column permutation matching the kernel's PER_CLUSTER
        # decode-row order (see _pq_scan_kernel): column p·pq_dim + s
        # reads the query's s·pl + p coordinate. Applied AFTER the ip
        # correction below is computed from the unpermuted blocks.
        perm = (jnp.arange(rot_dim) % pq_dim) * pq_len \
            + jnp.arange(rot_dim) // pq_dim
        qsub_k = qsub[..., perm]
    else:
        qsub_k = qsub

    codes_t = jnp.swapaxes(as_sub(codes), 1, 2)   # (cells, pq_dim, sub_ml)
    if fused:
        # the single-pallas_call tier replaces merge_cap_major's tail
        # outright: candidates merge into the resident state in-kernel,
        # the split sub-cells sharing their list's qmap/query blocks;
        # the ip center correction moves in-kernel too (constant per
        # slot — commutes with the binned min). PER_CLUSTER permutes
        # the centers like the queries so the in-kernel dot is the
        # same q·c_l (permutation-invariant).
        cent_k = (centers_rot[..., perm]
                  if (per_cluster and metric == "ip") else centers_rot)
        od, oi = _fused_pq_scan_call(
            qsub_k, codes_t, as_sub(code_norms), as_sub(lists_indices),
            books_in, lay.padded_qmap(), cent_k, lay.bins, k, nq,
            pallas_interpret(), metric=metric, lut_dtype=lut_dtype,
            split=split, per_cluster=per_cluster)
        return _finish_fused(od, oi, nq, k, sqrt)
    cd, ci = _pq_scan_call(qsub_k, codes_t, as_sub(code_norms),
                           as_sub(lists_indices), books_in, lay.bins,
                           pallas_interpret(), metric=metric,
                           lut_dtype=lut_dtype,
                           out_dtype=internal_distance_dtype, split=split,
                           per_cluster=per_cluster)
    if split > 1:
        # sub-lists of a list are contiguous: fold them back into a
        # wider candidate block per original list
        cd = cd.reshape(n_lists, split, lay.capp, lay.bins) \
               .transpose(0, 2, 1, 3).reshape(n_lists, lay.capp, -1)
        ci = ci.reshape(n_lists, split, lay.capp, lay.bins) \
               .transpose(0, 2, 1, 3).reshape(n_lists, lay.capp, -1)
    if metric == "ip":
        # kernel scored -q·dec; the true negated similarity is
        # -(q·dec + q·c_l): shift each (list, query) candidate row
        from raft_tpu.core.precision import matmul_precision
        corr = jnp.einsum("lqd,ld->lq", qsub, centers_rot,
                          precision=matmul_precision(),
                          preferred_element_type=jnp.float32)
        cd = cd.astype(jnp.float32) - corr[:, :, None]
    return lay.merge_cap_major(cd, ci, probes, k, sqrt)
