"""Pallas IVF list-scan kernel (fused fine phase of IVF-Flat search).

Reference: ``spatial/knn/detail/ivf_flat_search.cuh:665`` — the
``interleaved_scan_kernel``: one CUDA block per (query, probe) streams
the probed list's interleaved vectors, accumulates distances with
vectorized ILP, and keeps an in-kernel ``block_sort`` top-k so the
per-list score matrix never reaches global memory.

TPU re-design (list-major, not probe-major): a gather of "this query's
p-th list" per step re-reads every probed list ~nq·n_probes/n_lists
times from HBM. Instead the probe map is inverted (list → its probing
queries, the ``_ivf_scan`` inversion) and ONE kernel pass scans all
lists:

  grid cell = a chunk of ``LC`` lists. Per list ``l``:
    1. MXU matmul: list rows (max_list, dim) × gathered probing queries
       (cap, dim)ᵀ → transposed score block (max_list, cap) in VMEM —
       rows on sublanes, queries on lanes, the fused-kNN geometry.
    2. epilogue: + list-row norms + query norms − 2·ip, pad rows → +inf.
    3. binned partial top-k along sublanes → (B, cap) candidates with
       global db ids (TPU-KNN partial reduce; B ≥ 2k for the recall
       gate, B == max_list ⇒ exact).

Each list's rows are read from HBM exactly once per query batch; the
(max_list, cap) score block lives and dies in VMEM — the property the
reference's fused kernel has on GPU. Candidates are gathered back
per (query, probe) and merged with the exact Pallas ``select_k``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.dispatch import pallas_interpret
from raft_tpu.ops._util import (BIG_I32 as _BIG_I32,
                                VMEM_LIMIT as _VMEM_LIMIT,
                                round_up as _round_up, dot_nt_f32)
from raft_tpu.core.precision import kernel_matmul_mode


def _list_scan_kernel(scale_ref, qsub_ref, data_ref, norms_ref, ids_ref,
                      cd_ref, ci_ref, *, lc: int, bins: int, metric: str,
                      precision):
    scale = scale_ref[0, 0]
    for l in range(lc):
        q = qsub_ref[l]                                  # (cap, dim)
        y = data_ref[l]                                  # (ML, dim)
        ml = y.shape[0]
        cap = q.shape[0]
        if y.dtype == jnp.bfloat16:
            ip = jax.lax.dot_general(
                y, q.astype(jnp.bfloat16), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        elif y.dtype == jnp.int8:
            # int8 rides the MXU as bf16 (exact for |v| ≤ 127); the
            # kDivisor-style scale folds into the accumulated product
            ip = scale * jax.lax.dot_general(
                y.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            ip = dot_nt_f32(y, q, precision)             # (ML, cap)
        ids = ids_ref[l]                                 # (ML,) int32
        ids_b = jnp.broadcast_to(ids[:, None], (ml, cap))
        if metric == "ip":
            # similarity → negate: smaller-is-better uniformly (the
            # reference's max-heap IP routing, fused_l2_knn.cuh:947)
            d = jnp.where(ids_b >= 0, -ip, jnp.inf)
        else:
            qq = jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32),
                         axis=1)[None, :]                # (1, cap)
            d = norms_ref[l][:, None] + qq - 2.0 * ip
            d = jnp.where(ids_b >= 0, jnp.maximum(d, 0.0), jnp.inf)

        # STRIDED bins (row r → bin r % B): bucketized rows follow
        # dataset order, so a query's true neighbors sit in adjacent
        # rows — contiguous bins would collide them (measured 0.87 vs
        # 0.99+ recall on clustered data); striding decorrelates free
        w = ml // bins
        db_ = d.reshape(w, bins, cap)
        cd = jnp.min(db_, axis=0)                        # (B, cap)
        rb = ids_b.reshape(w, bins, cap)
        ci = jnp.min(jnp.where(db_ == cd[None, :, :], rb, _BIG_I32),
                     axis=0)
        ci = jnp.where(ci == _BIG_I32, -1, ci)
        cd_ref[l] = cd.astype(cd_ref.dtype)
        ci_ref[l] = ci


@functools.partial(jax.jit, static_argnames=("bins", "lc", "metric",
                                             "out_dtype", "interpret"))
def _list_scan_call(qsub, data, norms, ids, bins: int, lc: int,
                    scale, interpret: bool, metric: str = "l2",
                    out_dtype=jnp.float32):
    n_lists, cap, dim = qsub.shape
    max_list = data.shape[1]
    gc = n_lists // lc
    kern = functools.partial(
        _list_scan_kernel, lc=lc, bins=bins, metric=metric,
        precision=kernel_matmul_mode(interpret))
    # scale rides as a (1,1) traced input: a static arg would recompile
    # the kernel for every distinct int8 index scale
    scale_arr = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    cd, ci = pl.pallas_call(
        kern,
        grid=(gc,),
        in_specs=[pl.BlockSpec((1, 1), lambda g: (0, 0)),
                  pl.BlockSpec((lc, cap, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, max_list, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, max_list), lambda g: (g, 0)),
                  pl.BlockSpec((lc, max_list), lambda g: (g, 0))],
        out_specs=[pl.BlockSpec((lc, bins, cap), lambda g: (g, 0, 0)),
                   pl.BlockSpec((lc, bins, cap), lambda g: (g, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_lists, bins, cap), out_dtype),
                   jax.ShapeDtypeStruct((n_lists, bins, cap), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_lists * max_list * cap * dim,
            bytes_accessed=(data.dtype.itemsize * n_lists * max_list * dim
                            + 4 * n_lists * cap * dim
                            + 8 * n_lists * bins * cap),
            transcendentals=0),
        interpret=interpret,
    )(scale_arr, qsub, data, norms, ids)
    return cd, ci


def _pick_lc(n_lists: int, max_list: int, cap: int, dim: int,
             itemsize: int) -> int:
    """Lists per grid cell: enough to amortize per-step overhead while
    the (LC·max_list·dim) data block + score blocks stay well under the
    VMEM cap (double-buffered)."""
    per_list = (max_list * dim * itemsize          # data block
                + cap * dim * 4                    # gathered queries
                + max_list * cap * 4               # score block
                + max_list * (4 + 4))              # norms + ids
    budget = _VMEM_LIMIT // 3
    # ≤ 8: the kernel body Python-unrolls lc list iterations — VMEM is
    # not the only bound, Mosaic program size is too
    lc = max(1, min(8, budget // max(per_list, 1)))
    while n_lists % lc:
        lc -= 1
    return lc


class _Layout:
    """Shared prologue of both list-major scans: bins resolution, probe
    inversion, list-axis padding to a bins multiple, lane-aligned
    inverted-table width.

    ``bins``: 0 = auto — 4k bins. IVF lists concentrate a query's true
    neighbors far more than brute-force tiles do, so the collision
    budget needs more width than fused_knn's 2k default (recall 0.944 →
    0.97+ at 16/64 probes on clustered data); the merge rides the fast
    select_k, so the wider candidate set costs little. -1 = exact (one
    row per bin); >0 explicit.
    """

    def __init__(self, probes, n_lists: int, max_list: int, cap: int,
                 bins: int, k: int):
        from raft_tpu.neighbors._ivf_scan import _invert_probes
        if bins == 0:
            bins = min(max(4 * k, 64), max_list)
        self.qmap, self.inv_pos = _invert_probes(probes, n_lists, cap)
        # pad the list axis so bins divides it (pad rows: id -1 → +inf)
        self.mlp = _round_up(max_list, bins if bins > 0 else 1)
        self.bins = self.mlp if bins < 0 else bins
        self.cap = cap
        self.capp = _round_up(max(cap, 8), 8)  # lane-aligned table width

    def pad_lists(self, arr, max_list: int, fill=0):
        if self.mlp == max_list:
            return arr
        pad = [(0, 0), (0, self.mlp - max_list)] + [(0, 0)] * (arr.ndim - 2)
        return jnp.pad(arr, pad, constant_values=fill)

    def padded_qmap(self):
        if self.capp == self.cap:
            return self.qmap
        return jnp.pad(self.qmap, ((0, 0), (0, self.capp - self.cap)),
                       constant_values=-1)

    def merge(self, cd, ci, probes, k: int, sqrt: bool):
        from raft_tpu.neighbors._ivf_scan import merge_candidates
        cd = jnp.swapaxes(cd, 1, 2)                # (n_lists, cap, B)
        ci = jnp.swapaxes(ci, 1, 2)
        return merge_candidates(
            cd[:, :self.cap].astype(jnp.float32), ci[:, :self.cap],
            probes, self.inv_pos, k, sqrt, use_pallas_select=True)


def ivf_list_scan_pallas(queries, lists_data, lists_norms, lists_indices,
                         probes, k: int, cap: int, scale=1.0,
                         bins: int = 0, sqrt: bool = False,
                         metric: str = "l2"):
    """Fused list-major IVF-Flat fine scan + merge.

    ``queries`` (nq, dim) f32; ``lists_data`` (n_lists, max_list, dim)
    f32/bf16/int8; ``probes`` (nq, n_probes) int32; ``cap`` the inverted
    table width (``_ivf_scan.probe_cap``). ``bins``: see ``_Layout``.
    ``metric``: "l2" (squared, ``sqrt`` optional) or "ip" (returns
    NEGATED similarities, ascending — callers postprocess). Returns
    (dists (nq, k), ids (nq, k)) sorted best-first.
    """
    nq, dim = queries.shape
    n_lists, max_list = lists_indices.shape
    lay = _Layout(probes, n_lists, max_list, cap, bins, k)
    lists_data = lay.pad_lists(lists_data, max_list)
    lists_norms = lay.pad_lists(lists_norms, max_list)
    lists_indices = lay.pad_lists(lists_indices, max_list, fill=-1)

    # XLA pre-gather: each list's probing queries → (n_lists, cap, dim).
    # ~cap/mean-probes ≤ 2× the query bytes; read once by the kernel.
    qsub = queries[jnp.clip(lay.padded_qmap(), 0, nq - 1)]
    lc = _pick_lc(n_lists, lay.mlp, lay.capp, dim,
                  lists_data.dtype.itemsize)
    cd, ci = _list_scan_call(qsub, lists_data, lists_norms, lists_indices,
                             lay.bins, lc, scale, pallas_interpret(),
                             metric=metric)
    return lay.merge(cd, ci, probes, k, sqrt)


def _pq_chunk(n_lists: int, max_list: int, rot_dim: int, itemsize: int,
              budget_bytes: int = 32 << 20) -> int:
    """Lists per decode chunk: the transient decode tile
    (chunk·max_list·rot_dim·itemsize) stays under ``budget_bytes``."""
    from raft_tpu.neighbors._ivf_scan import largest_divisor_at_most
    want = max(1, budget_bytes // max(1, max_list * rot_dim * itemsize))
    return largest_divisor_at_most(n_lists, want)


def ivf_pq_code_scan_pallas(q_rot, centers_rot, pq_centers, codes,
                            code_norms, lists_indices, probes, k: int,
                            cap: int, bins: int = 0, sqrt: bool = False,
                            lut_dtype=jnp.bfloat16,
                            internal_distance_dtype=jnp.float32,
                            metric: str = "l2"):
    """IVF-PQ fine scan directly over the compressed codes.

    Reference ``ivf_pq_search.cuh:593`` scans the bit-packed
    ``pq_dataset`` against a smem LUT. Per-lane LUT gathers are hostile
    to the TPU vector unit, so the TPU formulation decodes each chunk of
    lists on the fly — codes (u8, pq_dim B/vector) are the only
    persistent payload; the decoded (chunk, max_list, rot_dim) tile is
    transient (the "on-the-fly decode tile that never persists") and
    feeds the same fused list-scan kernel as IVF-Flat, with each list's
    probing queries pre-offset by its rotated center so the kernel
    scores ``||(q_rot − c_l) − decoded||²``.

    The reference's LUT-precision variants (``ivf_pq_search.cuh:
    780-1004``, fp32/fp16/fp8 LUT × fp32/fp16 internal) map to
    ``lut_dtype`` — the decode-tile dtype (bf16 = one MXU pass, f32 =
    bf16x3 split) — and ``internal_distance_dtype`` — the candidate
    score dtype carried to the merge (bf16 halves candidate HBM).

    ``code_norms`` are exact: PQ subspaces concatenate orthogonally, so
    ``||decoded_i||² = Σ_s ||book_s[c_is]||²`` is computed once at build
    from the codebook norm table.
    """
    nq = q_rot.shape[0]
    n_lists, max_list, pq_dim = codes.shape
    _, n_codes, pq_len = pq_centers.shape
    rot_dim = pq_dim * pq_len
    itemsize = jnp.dtype(lut_dtype).itemsize
    lay = _Layout(probes, n_lists, max_list, cap, bins, k)
    codes = lay.pad_lists(codes, max_list)
    code_norms = lay.pad_lists(code_norms, max_list)
    lists_indices = lay.pad_lists(lists_indices, max_list, fill=-1)
    mlp, capp = lay.mlp, lay.capp
    qg = q_rot[jnp.clip(lay.padded_qmap(), 0, nq - 1)]
    if metric == "ip":
        # IP has no residual form: q·y = q_rot·(c_rot + dec) — decode
        # FULL rotated vectors (center added to the transient tile) and
        # score plain rotated queries against them
        qsub = qg
    else:
        # per-list probing queries, residual form: (n_lists, cap, rot_dim)
        qsub = qg - centers_rot[:, None, :]

    chunk = _pq_chunk(n_lists, mlp, rot_dim, itemsize)
    lc = _pick_lc(chunk, mlp, capp, rot_dim, itemsize)
    n_chunks = n_lists // chunk
    interpret = pallas_interpret()

    def one_chunk(args):
        codes_c, norms_c, ids_c, qsub_c, crot_c = args
        flat = codes_c.reshape(-1, pq_dim).astype(jnp.int32)
        # decode: one row-gather per subquantizer (O(N·pq_len) each)
        dec = jnp.concatenate(
            [pq_centers[s][flat[:, s]] for s in range(pq_dim)], axis=1)
        dec = dec.reshape(chunk, mlp, rot_dim)
        if metric == "ip":
            dec = dec + crot_c[:, None, :]
        dec = dec.astype(lut_dtype)
        return _list_scan_call(qsub_c, dec, norms_c, ids_c, lay.bins, lc,
                               1.0, interpret, metric=metric,
                               out_dtype=internal_distance_dtype)

    cd, ci = jax.lax.map(one_chunk, (
        codes.reshape(n_chunks, chunk, mlp, pq_dim),
        code_norms.reshape(n_chunks, chunk, mlp),
        lists_indices.reshape(n_chunks, chunk, mlp),
        qsub.reshape(n_chunks, chunk, capp, rot_dim),
        centers_rot.reshape(n_chunks, chunk, rot_dim)))
    return lay.merge(cd.reshape(n_lists, lay.bins, capp),
                     ci.reshape(n_lists, lay.bins, capp), probes, k, sqrt)
