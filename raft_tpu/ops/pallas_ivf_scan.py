"""Pallas IVF list-scan kernel (fused fine phase of IVF-Flat search).

Reference: ``spatial/knn/detail/ivf_flat_search.cuh:665`` — the
``interleaved_scan_kernel``: one CUDA block per (query, probe) streams
the probed list's interleaved vectors, accumulates distances with
vectorized ILP, and keeps an in-kernel ``block_sort`` top-k so the
per-list score matrix never reaches global memory.

TPU re-design (list-major, not probe-major): a gather of "this query's
p-th list" per step re-reads every probed list ~nq·n_probes/n_lists
times from HBM. Instead the probe map is inverted (list → its probing
queries, the ``_ivf_scan`` inversion) and ONE kernel pass scans all
lists:

  grid cell = a chunk of ``LC`` lists. Per list ``l``:
    1. MXU matmul: list rows (max_list, dim) × gathered probing queries
       (cap, dim)ᵀ → transposed score block (max_list, cap) in VMEM —
       rows on sublanes, queries on lanes, the fused-kNN geometry.
    2. epilogue: + list-row norms + query norms − 2·ip, pad rows → +inf.
    3. binned partial top-k along sublanes → (B, cap) candidates with
       global db ids (TPU-KNN partial reduce; B ≥ 2k for the recall
       gate, B == max_list ⇒ exact).

Each list's rows are read from HBM exactly once per query batch; the
(max_list, cap) score block lives and dies in VMEM — the property the
reference's fused kernel has on GPU. Candidates are gathered back
per (query, probe) and merged with the exact Pallas ``select_k``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.dispatch import pallas_interpret
from raft_tpu.ops._util import (BIG_I32 as _BIG_I32,
                                VMEM_LIMIT as _VMEM_LIMIT,
                                round_up as _round_up, dot_nt_f32)
from raft_tpu.core.precision import kernel_matmul_mode


def _list_scan_kernel(scale_ref, qsub_ref, data_ref, norms_ref, ids_ref,
                      cd_ref, ci_ref, *, lc: int, bins: int,
                      precision):
    scale = scale_ref[0, 0]
    for l in range(lc):
        q = qsub_ref[l]                                  # (cap, dim)
        y = data_ref[l]                                  # (ML, dim)
        ml = y.shape[0]
        cap = q.shape[0]
        if y.dtype == jnp.bfloat16:
            ip = jax.lax.dot_general(
                y, q.astype(jnp.bfloat16), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        elif y.dtype == jnp.int8:
            # int8 rides the MXU as bf16 (exact for |v| ≤ 127); the
            # kDivisor-style scale folds into the accumulated product
            ip = scale * jax.lax.dot_general(
                y.astype(jnp.bfloat16), q.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            ip = dot_nt_f32(y, q, precision)             # (ML, cap)
        qq = jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32),
                     axis=1)[None, :]                    # (1, cap)
        ids = ids_ref[l]                                 # (ML,) int32
        d = norms_ref[l][:, None] + qq - 2.0 * ip
        ids_b = jnp.broadcast_to(ids[:, None], (ml, cap))
        d = jnp.where(ids_b >= 0, jnp.maximum(d, 0.0), jnp.inf)

        # STRIDED bins (row r → bin r % B): bucketized rows follow
        # dataset order, so a query's true neighbors sit in adjacent
        # rows — contiguous bins would collide them (measured 0.87 vs
        # 0.99+ recall on clustered data); striding decorrelates free
        w = ml // bins
        db_ = d.reshape(w, bins, cap)
        cd = jnp.min(db_, axis=0)                        # (B, cap)
        rb = ids_b.reshape(w, bins, cap)
        ci = jnp.min(jnp.where(db_ == cd[None, :, :], rb, _BIG_I32),
                     axis=0)
        ci = jnp.where(ci == _BIG_I32, -1, ci)
        cd_ref[l] = cd
        ci_ref[l] = ci


@functools.partial(jax.jit, static_argnames=("bins", "lc", "interpret"))
def _list_scan_call(qsub, data, norms, ids, bins: int, lc: int,
                    scale, interpret: bool):
    n_lists, cap, dim = qsub.shape
    max_list = data.shape[1]
    gc = n_lists // lc
    kern = functools.partial(
        _list_scan_kernel, lc=lc, bins=bins,
        precision=kernel_matmul_mode(interpret))
    # scale rides as a (1,1) traced input: a static arg would recompile
    # the kernel for every distinct int8 index scale
    scale_arr = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    cd, ci = pl.pallas_call(
        kern,
        grid=(gc,),
        in_specs=[pl.BlockSpec((1, 1), lambda g: (0, 0)),
                  pl.BlockSpec((lc, cap, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, max_list, dim), lambda g: (g, 0, 0)),
                  pl.BlockSpec((lc, max_list), lambda g: (g, 0)),
                  pl.BlockSpec((lc, max_list), lambda g: (g, 0))],
        out_specs=[pl.BlockSpec((lc, bins, cap), lambda g: (g, 0, 0)),
                   pl.BlockSpec((lc, bins, cap), lambda g: (g, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_lists, bins, cap), jnp.float32),
                   jax.ShapeDtypeStruct((n_lists, bins, cap), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_lists * max_list * cap * dim,
            bytes_accessed=(data.dtype.itemsize * n_lists * max_list * dim
                            + 4 * n_lists * cap * dim
                            + 8 * n_lists * bins * cap),
            transcendentals=0),
        interpret=interpret,
    )(scale_arr, qsub, data, norms, ids)
    return cd, ci


def _pick_lc(n_lists: int, max_list: int, cap: int, dim: int,
             itemsize: int) -> int:
    """Lists per grid cell: enough to amortize per-step overhead while
    the (LC·max_list·dim) data block + score blocks stay well under the
    VMEM cap (double-buffered)."""
    per_list = (max_list * dim * itemsize          # data block
                + cap * dim * 4                    # gathered queries
                + max_list * cap * 4               # score block
                + max_list * (4 + 4))              # norms + ids
    budget = _VMEM_LIMIT // 3
    # ≤ 8: the kernel body Python-unrolls lc list iterations — VMEM is
    # not the only bound, Mosaic program size is too
    lc = max(1, min(8, budget // max(per_list, 1)))
    while n_lists % lc:
        lc -= 1
    return lc


def ivf_list_scan_pallas(queries, lists_data, lists_norms, lists_indices,
                         probes, k: int, cap: int, scale=1.0,
                         bins: int = 0, sqrt: bool = False):
    """Fused list-major IVF-Flat fine scan + merge.

    ``queries`` (nq, dim) f32; ``lists_data`` (n_lists, max_list, dim)
    f32/bf16/int8; ``probes`` (nq, n_probes) int32; ``cap`` the inverted
    table width (``_ivf_scan.probe_cap``). ``bins``: 0 = auto (4k
    strided bins), -1 = exact (one row per bin), >0 explicit. Returns
    (dists (nq, k), ids (nq, k)) sorted best-first — squared L2
    (``sqrt`` optional).
    """
    from raft_tpu.neighbors._ivf_scan import (_invert_probes,
                                              merge_candidates)

    nq, dim = queries.shape
    n_lists, max_list = lists_indices.shape
    if bins == 0:
        # auto: 4k bins. IVF lists concentrate a query's true neighbors
        # far more than brute-force tiles do, so the collision budget
        # needs more width than fused_knn's 2k default (recall 0.944 →
        # 0.97+ at 16/64 probes on clustered data); the merge rides the
        # fast select_k, so the wider candidate set costs little
        bins = min(max(4 * k, 64), max_list)

    qmap, inv_pos = _invert_probes(probes, n_lists, cap)

    # pad the list axis so bins divides it (pad rows carry id -1 → +inf)
    mlp = _round_up(max_list, bins if bins > 0 else 1)
    if bins < 0:
        bins = mlp  # exact mode: one row per bin
    if mlp != max_list:
        pad = ((0, 0), (0, mlp - max_list))
        lists_data = jnp.pad(lists_data, pad + ((0, 0),))
        lists_norms = jnp.pad(lists_norms, pad)
        lists_indices = jnp.pad(lists_indices, pad, constant_values=-1)
    # lane-align the inverted-table width
    capp = _round_up(max(cap, 8), 8)

    # XLA pre-gather: each list's probing queries → (n_lists, cap, dim).
    # ~cap/mean-probes ≤ 2× the query bytes; read once by the kernel.
    qm = qmap if capp == cap else jnp.pad(qmap, ((0, 0), (0, capp - cap)),
                                          constant_values=-1)
    qsub = queries[jnp.clip(qm, 0, nq - 1)]
    lc = _pick_lc(n_lists, mlp, capp, dim, lists_data.dtype.itemsize)
    cd, ci = _list_scan_call(qsub, lists_data, lists_norms, lists_indices,
                             bins, lc, scale, pallas_interpret())

    cd = jnp.swapaxes(cd, 1, 2)                       # (n_lists, cap, B)
    ci = jnp.swapaxes(ci, 1, 2)
    return merge_candidates(cd[:, :cap], ci[:, :cap], probes, inv_pos, k,
                            sqrt, use_pallas_select=True)
