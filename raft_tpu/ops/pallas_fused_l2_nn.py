"""Pallas fused L2 nearest-neighbor (argmin epilogue) kernel.

Reference: ``raft::distance::fusedL2NN`` — CUDA kernel
``distance/detail/fused_l2_nn.cuh:132`` fuses the expanded-L2 GEMM tiles
with a per-row argmin reduction (custom KVP atomics + a mutex buffer) so
the (m, n) distance matrix never reaches global memory.

TPU design: one MXU matmul per (query-tile, db-tile) grid cell with the
argmin epilogue applied in VMEM before anything is written back; the
running (best-dist, best-idx) state lives in the output block, which
Pallas keeps resident in VMEM while the inner (db) grid dimension
iterates. The block is computed *transposed* — rows are database points,
columns are queries — so the reduction runs along the sublane axis and
the per-query results are natural ``(1, TM)`` row vectors (no in-kernel
transpose). No atomics are needed: the TPU grid is sequential, the CUDA
kernel's inter-CTA mutex disappears.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.ops.dispatch import pallas_interpret
from raft_tpu.ops._util import (BIG_I32 as _BIG_I32, VMEM_LIMIT as _VMEM_LIMIT,
                                round_up as _round_up, dot_nt_f32)
from raft_tpu.core.precision import resolve_kernel_mode


def _nn_kernel(x_ref, y_ref, od_ref, oi_ref, *, n: int, tn: int, gn: int,
               sqrt: bool, precision):
    j = pl.program_id(1)
    x = x_ref[:]                                         # (TM, K)
    y = y_ref[:]                                         # (TN, K)
    xx = jnp.sum(x * x, axis=1, keepdims=True).T         # (1, TM)
    yy = jnp.sum(y * y, axis=1, keepdims=True)           # (TN, 1)
    # transposed expanded-L2 block: d[p, q] = ||y_p - x_q||^2
    d = yy + xx - 2.0 * dot_nt_f32(y, x, precision)
    tm = x.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, (tn, tm), 0) + j * tn
    d = jnp.where(row < n, jnp.maximum(d, 0.0), jnp.inf)
    tmin = jnp.min(d, axis=0, keepdims=True)             # (1, TM)
    arg = jnp.min(jnp.where(d == tmin, row, _BIG_I32), axis=0, keepdims=True)

    @pl.when(j == 0)
    def _():
        od_ref[:] = jnp.full(od_ref.shape, jnp.inf, jnp.float32)
        oi_ref[:] = jnp.zeros(oi_ref.shape, jnp.int32)

    take = tmin[None] < od_ref[:]
    oi_ref[:] = jnp.where(take, arg[None], oi_ref[:])
    od_ref[:] = jnp.where(take, tmin[None], od_ref[:])

    if sqrt:
        @pl.when(j == gn - 1)
        def _():
            od_ref[:] = jnp.sqrt(od_ref[:])


@functools.partial(jax.jit,
                   static_argnames=("sqrt", "tm", "tn", "interpret",
                                    "kernel_precision"))
def _fused_l2_nn_call(x, y, sqrt: bool, tm: int, tn: int, interpret: bool,
                      kernel_precision=None):
    m, k = x.shape
    n = y.shape[0]
    mp, np_ = _round_up(m, tm), _round_up(n, tn)
    xp = jnp.pad(x.astype(jnp.float32), ((0, mp - m), (0, 0)))
    yp = jnp.pad(y.astype(jnp.float32), ((0, np_ - n), (0, 0)))
    gm, gn = mp // tm, np_ // tn
    kern = functools.partial(_nn_kernel, n=n, tn=tn, gn=gn, sqrt=sqrt,
                             precision=resolve_kernel_mode(
                                 kernel_precision, interpret))
    od, oi = pl.pallas_call(
        kern,
        grid=(gm, gn),
        in_specs=[pl.BlockSpec((tm, k), lambda i, j: (i, 0)),
                  pl.BlockSpec((tn, k), lambda i, j: (j, 0))],
        out_specs=[pl.BlockSpec((1, 1, tm), lambda i, j: (i, 0, 0)),
                   pl.BlockSpec((1, 1, tm), lambda i, j: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((gm, 1, tm), jnp.float32),
                   jax.ShapeDtypeStruct((gm, 1, tm), jnp.int32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * np_ * k,
            bytes_accessed=4 * (gm * np_ * k + gn * mp * k + 2 * mp),
            transcendentals=0),
        interpret=interpret,
    )(xp, yp)
    return oi.reshape(-1)[:m], od.reshape(-1)[:m]


def fused_l2_nn_pallas(x, y, sqrt: bool = False, tm: int = 0, tn: int = 0,
                       kernel_precision: str | None = None):
    """For each row of ``x``: (index, distance) of its nearest row of ``y``
    under (squared) L2 — single fused kernel, no (m, n) buffer.

    Returns ``(idx int32 (m,), dist float32 (m,))``. Tile sizes ``tm``
    (queries, lane axis) and ``tn`` (db, sublane axis) default to a
    VMEM-budget heuristic (1024² for small k; shrunk as the feature dim
    grows — the VMEM-capacity analogue of the reference's smem policy
    selection, ``pairwise_distance_base.cuh:76``) and are clamped to the
    padded problem; padded db rows are masked to +inf.
    """
    m, k = x.shape
    if tm <= 0 or tn <= 0:
        if k <= 512:
            tm, tn = 1024, 4096
        elif k <= 2048:
            tm, tn = 512, 1024
        else:
            tm, tn = 256, 512
    tm = min(tm, _round_up(m, 8))
    tn = min(tn, _round_up(y.shape[0], 8))
    return _fused_l2_nn_call(x, y, bool(sqrt), tm, tn, pallas_interpret(),
                             kernel_precision=kernel_precision)
