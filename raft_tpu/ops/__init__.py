"""Pallas kernel tier (SURVEY.md §7 step 3).

This package holds the hand-written TPU kernels that back the hot paths of
the XLA-first primitive layer — the TPU analogue of the reference's fused
CUDA kernels:

* :mod:`raft_tpu.ops.pallas_fused_l2_nn` — fused L2 + argmin epilogue
  (reference ``distance/detail/fused_l2_nn.cuh:132``).
* :mod:`raft_tpu.ops.pallas_fused_knn` — fused distance + in-kernel top-k
  (reference ``spatial/knn/detail/fused_l2_knn.cuh:196``), using the
  binned partial-top-k trick of TPU-KNN (PAPERS.md).
* :mod:`raft_tpu.ops.pallas_select_k` — exact k-selection by filtered
  merge (reference warpsort, ``spatial/knn/detail/topk.cuh:65``).

Every kernel has an XLA reference formulation in the primitive layer; the
public APIs dispatch between them via :mod:`raft_tpu.ops.dispatch`. A
kernel only lands here if it beats the XLA tier on the bench suite.

Kernel symbols load lazily (PEP 562) so that importing the dispatch
module — which the primitive layer does on every public call — works
even on jax builds without ``jax.experimental.pallas``.
"""

from raft_tpu.ops.dispatch import (
    pallas_available,
    pallas_enabled,
    pallas_interpret,
)

__all__ = [
    "pallas_available",
    "pallas_enabled",
    "pallas_interpret",
    "fused_l2_nn_pallas",
    "fused_knn_pallas",
    "select_k_pallas",
    "ivf_list_scan_pallas",
    "elementwise_dist_pallas",
]

_LAZY = {
    "fused_l2_nn_pallas": "raft_tpu.ops.pallas_fused_l2_nn",
    "fused_knn_pallas": "raft_tpu.ops.pallas_fused_knn",
    "select_k_pallas": "raft_tpu.ops.pallas_select_k",
    "ivf_list_scan_pallas": "raft_tpu.ops.pallas_ivf_scan",
    "elementwise_dist_pallas": "raft_tpu.ops.pallas_elementwise_dist",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(mod), name)
