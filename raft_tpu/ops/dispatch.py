"""Kernel-tier dispatch: when to route a primitive to its Pallas kernel.

The reference makes the equivalent choice at CMake/template-instantiation
time (precompiled specializations vs header-only paths,
``cpp/CMakeLists.txt:236-406``); here it is a runtime decision per call:

* on a TPU backend the Pallas kernels compile natively (Mosaic);
* elsewhere (the CPU test mesh) they can still run under the Pallas
  interpreter for correctness tests, but are off by default because the
  XLA formulation is faster on CPU.

``RAFT_TPU_PALLAS`` overrides: ``never`` | ``auto`` (default) |
``always`` (use Pallas even off-TPU, interpreted off-TPU — what the unit
tests set).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from raft_tpu import obs


_MODES = ("auto", "0", "never", "off", "1", "always", "on")


def _mode() -> str:
    mode = os.environ.get("RAFT_TPU_PALLAS", "auto").lower()
    if mode not in _MODES:
        raise ValueError(
            f"RAFT_TPU_PALLAS={mode!r}: want auto|never|always")
    return mode


def pallas_available() -> bool:
    """True when the Pallas TPU lowering path exists for this process."""
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except ImportError:  # pragma: no cover - pallas ships with jax
        return False


def pallas_enabled(backend: Optional[str] = None) -> bool:
    """Should a primitive route to its Pallas kernel? Every call counts
    the decision into ``raft.dispatch.route{path=pallas|xla}`` — the
    telemetry that says which kernel tier actually served traffic
    (bench records embed the diff, so BENCH_r*.json rows are
    self-describing about their code path)."""
    mode = _mode()
    if mode in ("0", "never", "off"):
        use = False
    elif mode in ("1", "always", "on"):
        use = pallas_available()
    else:
        backend = backend or jax.default_backend()
        use = backend == "tpu" and pallas_available()
    obs.counter("raft.dispatch.route",
                path="pallas" if use else "xla").inc()
    return use


def pallas_interpret(backend: Optional[str] = None) -> bool:
    """Run kernels under the Pallas interpreter (non-TPU backends)."""
    backend = backend or jax.default_backend()
    interp = backend != "tpu"
    if interp:
        # interpret-mode fallback: correct but orders of magnitude
        # slower than a compiled kernel — worth a counter of its own
        obs.counter("raft.dispatch.interpret_fallback").inc()
    return interp
