"""ANN index serialization (save/load).

The reference snapshot has NO index serialization — indexes are rebuilt
per process (SURVEY.md §5 "Checkpoint/resume: none"; serialize arrived
in later RAFT). This module is the explicit improvement called for
there: IVF-Flat and IVF-PQ indexes round-trip through a single ``.npz``
file (array payloads + a JSON metadata record), so a multi-hour build
of a 100M-vector index is paid once.

Format: numpy ``.npz`` with key ``__meta__`` holding a JSON object
{format, version, fields...}; every jax.Array field is stored as its
host numpy value and restored with ``jnp.asarray`` (device placement
follows the caller's default device / sharding context).
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.distance.distance_types import DistanceType

_VERSION = 1


def _pack(path: str, fmt: str, meta: dict, arrays: dict) -> None:
    # bfloat16 has no numpy-native representation: npz would silently
    # store it as raw void ('|V2'); persist as uint16 bit patterns and
    # record which fields to view back
    out, bf16_fields = {}, []
    for k, v in arrays.items():
        a = np.asarray(v)
        if a.dtype == jnp.bfloat16:
            a = a.view(np.uint16)
            bf16_fields.append(k)
        out[k] = a
    meta = dict(meta, format=fmt, version=_VERSION,
                bf16_fields=bf16_fields)
    np.savez(path, __meta__=np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8), **out)
    if not path.endswith(".npz") and os.path.exists(path + ".npz"):
        os.replace(path + ".npz", path)  # np.savez appends .npz; honor the
        # exact path the caller asked for so load(path) round-trips


def _unpack(path: str, fmt: str):
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        expects(meta.get("format") == fmt,
                f"serialize: {path} holds {meta.get('format')!r}, "
                f"expected {fmt!r}")
        expects(meta.get("version") == _VERSION,
                f"serialize: unsupported version {meta.get('version')}")
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    import ml_dtypes
    for k in meta.get("bf16_fields", []):
        arrays[k] = arrays[k].view(ml_dtypes.bfloat16)
    return meta, arrays


def save_ivf_flat(index, path: str) -> None:
    """Write an :class:`raft_tpu.neighbors.ivf_flat.Index` to ``path``."""
    _pack(path, "ivf_flat",
          {"metric": int(index.metric), "size": int(index.size),
           "scale": float(index.scale)},
          {"centers": index.centers, "lists_data": index.lists_data,
           "lists_indices": index.lists_indices,
           "lists_norms": index.lists_norms,
           "list_sizes": index.list_sizes})


def load_ivf_flat(path: str):
    """Read an IVF-Flat index written by :func:`save_ivf_flat`."""
    from raft_tpu.neighbors.ivf_flat import Index
    meta, a = _unpack(path, "ivf_flat")
    return Index(
        centers=jnp.asarray(a["centers"]),
        lists_data=jnp.asarray(a["lists_data"]),
        lists_indices=jnp.asarray(a["lists_indices"]),
        lists_norms=jnp.asarray(a["lists_norms"]),
        list_sizes=jnp.asarray(a["list_sizes"]),
        metric=DistanceType(meta["metric"]),
        size=meta["size"],
        scale=float(meta.get("scale", 1.0)))


def save_ivf_pq(index, path: str, include_raw: bool = True) -> None:
    """Write an :class:`raft_tpu.neighbors.ivf_pq.Index` to ``path``.
    ``include_raw=False`` drops the host rescore corpus (keep_raw
    builds) from the artifact — the compact index checkpoints without
    the n×dim f32 payload that dwarfs it at scale."""
    arrays = {"centers": index.centers, "centers_rot": index.centers_rot,
              "rotation_matrix": index.rotation_matrix,
              "pq_centers": index.pq_centers, "codes": index.codes,
              "lists_indices": index.lists_indices,
              "list_sizes": index.list_sizes}
    has_raw = include_raw and index.raw is not None
    if has_raw:
        arrays["raw"] = index.raw
    _pack(path, "ivf_pq",
          {"metric": int(index.metric), "size": int(index.size),
           "pq_bits": int(index.pq_bits),
           "codebook_kind": int(index.codebook_kind),
           "has_raw": has_raw}, arrays)


def load_ivf_pq(path: str):
    """Read an IVF-PQ index written by :func:`save_ivf_pq`. The bf16
    reconstruction cache is re-derived lazily from the compact codes at
    first reconstruct-mode search."""
    from raft_tpu.neighbors.ivf_pq import Index
    meta, a = _unpack(path, "ivf_pq")
    index = Index(
        centers=jnp.asarray(a["centers"]),
        centers_rot=jnp.asarray(a["centers_rot"]),
        rotation_matrix=jnp.asarray(a["rotation_matrix"]),
        pq_centers=jnp.asarray(a["pq_centers"]),
        codes=jnp.asarray(a["codes"]),
        lists_indices=jnp.asarray(a["lists_indices"]),
        list_sizes=jnp.asarray(a["list_sizes"]),
        metric=DistanceType(meta["metric"]),
        pq_bits=meta["pq_bits"],
        size=meta["size"],
        raw=a["raw"] if meta.get("has_raw") else None)
    from raft_tpu.neighbors.ivf_pq import CodebookGen
    index.codebook_kind = CodebookGen(meta.get("codebook_kind", 0))
    return index


def save_ivf_bq(index, path: str, include_raw: bool = True) -> None:
    """Write an :class:`raft_tpu.neighbors.ivf_bq.Index`. The raw host
    vectors (rescore tier) ride along when present; ``include_raw=
    False`` drops them — at the 100M×128 north star the raw corpus is
    ~51 GB against a ~2.8 GB index, so periodic checkpoints save the
    compact part only (ADVICE r3 #3)."""
    arrays = {"centers": index.centers, "centers_rot": index.centers_rot,
              "rotation_matrix": index.rotation_matrix,
              "bits": index.bits, "norms2": index.norms2,
              "scales": index.scales,
              "lists_indices": index.lists_indices,
              "list_sizes": index.list_sizes}
    has_raw = include_raw and index.raw is not None
    if has_raw:
        arrays["raw"] = index.raw
    _pack(path, "ivf_bq",
          {"metric": int(index.metric), "size": int(index.size),
           "has_raw": has_raw}, arrays)


def load_ivf_bq(path: str):
    """Read an IVF-BQ index written by :func:`save_ivf_bq`."""
    from raft_tpu.neighbors.ivf_bq import Index
    meta, a = _unpack(path, "ivf_bq")
    return Index(
        centers=jnp.asarray(a["centers"]),
        centers_rot=jnp.asarray(a["centers_rot"]),
        rotation_matrix=jnp.asarray(a["rotation_matrix"]),
        bits=jnp.asarray(a["bits"]),
        norms2=jnp.asarray(a["norms2"]),
        scales=jnp.asarray(a["scales"]),
        lists_indices=jnp.asarray(a["lists_indices"]),
        list_sizes=jnp.asarray(a["list_sizes"]),
        metric=DistanceType(meta["metric"]), size=meta["size"],
        raw=a["raw"] if meta.get("has_raw") else None)


def save_host_ivf_flat(index, path: str) -> None:
    """Write a host-resident :class:`host_memory.HostIvfFlat`. The list
    arrays stream from host numpy — nothing touches the device."""
    _pack(path, "host_ivf_flat",
          {"metric": int(index.metric), "size": int(index.size),
           "scale": float(index.scale)},
          {"centers": index.centers, "lists_data": index.lists_data,
           "lists_indices": index.lists_indices,
           "lists_norms": index.lists_norms})


def load_host_ivf_flat(path: str):
    """Read a host-resident index: lists stay in host numpy; only the
    coarse centers go to device."""
    from raft_tpu.neighbors.host_memory import HostIvfFlat
    meta, a = _unpack(path, "host_ivf_flat")
    return HostIvfFlat(
        centers=jnp.asarray(a["centers"]),
        lists_data=np.asarray(a["lists_data"]),
        lists_norms=np.asarray(a["lists_norms"]),
        lists_indices=np.asarray(a["lists_indices"]),
        metric=DistanceType(meta["metric"]),
        size=meta["size"],
        scale=float(meta.get("scale", 1.0)))


def save_ball_cover(index, path: str) -> None:
    """Write a :class:`ball_cover.BallCoverIndex`."""
    _pack(path, "ball_cover",
          {"metric": int(index.metric), "size": int(index.size)},
          {"landmarks": index.landmarks, "lists_data": index.lists_data,
           "lists_indices": index.lists_indices, "radii": index.radii})


def load_ball_cover(path: str):
    """Read a ball-cover index written by :func:`save_ball_cover`."""
    from raft_tpu.neighbors.ball_cover import BallCoverIndex
    meta, a = _unpack(path, "ball_cover")
    return BallCoverIndex(
        landmarks=jnp.asarray(a["landmarks"]),
        lists_data=jnp.asarray(a["lists_data"]),
        lists_indices=jnp.asarray(a["lists_indices"]),
        radii=jnp.asarray(a["radii"]),
        metric=DistanceType(meta["metric"]),
        size=meta["size"])


def save_mutable(mindex, path: str) -> None:
    """Write a :class:`raft_tpu.mutate.MutableIndex` — the inner index
    (via its family writer, embedded as bytes) PLUS the mutable state
    (pending delta rows, tombstone ids, epoch/id-space counters), so a
    mutated index reloads without losing a single pending mutation.
    The snapshot is consistent (taken under the index lock)."""
    import tempfile
    st = mindex.export_state()
    fd, tmp = tempfile.mkstemp(
        suffix=".npz", dir=os.path.dirname(os.path.abspath(path)) or ".")
    os.close(fd)
    try:
        save(st["index"], tmp)
        inner = np.fromfile(tmp, dtype=np.uint8)
    finally:
        os.remove(tmp)
    _pack(path, "mutable",
          {"k": int(st["k"]), "epoch": int(st["epoch"]),
           "id_base": int(st["id_base"]), "next_id": int(st["next_id"])},
          {"inner": inner, "delta_data": st["delta_data"],
           "delta_ids": st["delta_ids"], "tomb_ids": st["tomb_ids"]})


def load_mutable(path: str, params=None, config=None):
    """Read a mutable index written by :func:`save_mutable` →
    :class:`raft_tpu.mutate.MutableIndex` with the delta segment,
    tombstones and epoch counters restored (programs re-warm via
    ``warmup()`` / the serving ladder, exactly like a fresh wrap)."""
    import tempfile
    from raft_tpu.mutate import MutableIndex
    meta, a = _unpack(path, "mutable")
    fd, tmp = tempfile.mkstemp(
        suffix=".npz", dir=os.path.dirname(os.path.abspath(path)) or ".")
    os.close(fd)
    try:
        a["inner"].tofile(tmp)
        inner = load(tmp)
    finally:
        os.remove(tmp)
    state = {"k": meta["k"], "epoch": meta["epoch"],
             "id_base": meta["id_base"], "next_id": meta["next_id"],
             "delta_data": a["delta_data"], "delta_ids": a["delta_ids"],
             "tomb_ids": a["tomb_ids"]}
    return MutableIndex.restore(inner, state, params=params,
                                config=config)


def save(index, path: str) -> None:
    """Type-dispatching save for any supported ANN index."""
    from raft_tpu.neighbors import ivf_flat, ivf_pq, ivf_bq
    from raft_tpu.neighbors.ball_cover import BallCoverIndex
    from raft_tpu.neighbors.host_memory import HostIvfFlat
    from raft_tpu.mutate import MutableIndex
    if isinstance(index, MutableIndex):
        save_mutable(index, path)
    elif isinstance(index, ivf_flat.Index):
        save_ivf_flat(index, path)
    elif isinstance(index, ivf_pq.Index):
        save_ivf_pq(index, path)
    elif isinstance(index, ivf_bq.Index):
        save_ivf_bq(index, path)
    elif isinstance(index, HostIvfFlat):
        save_host_ivf_flat(index, path)
    elif isinstance(index, BallCoverIndex):
        save_ball_cover(index, path)
    else:
        raise TypeError(f"serialize.save: unsupported index {type(index)}")


def load(path: str):
    """Type-dispatching load: reads the format tag and returns the
    matching index type."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
    fmt = meta.get("format")
    if fmt == "ivf_flat":
        return load_ivf_flat(path)
    if fmt == "ivf_pq":
        return load_ivf_pq(path)
    if fmt == "ivf_bq":
        return load_ivf_bq(path)
    if fmt == "host_ivf_flat":
        return load_host_ivf_flat(path)
    if fmt == "ball_cover":
        return load_ball_cover(path)
    if fmt == "mutable":
        return load_mutable(path)
    raise ValueError(f"serialize.load: unknown format {fmt!r} in {path}")
