"""Epsilon neighborhood (ball query).

Reference: ``raft/neighbors/epsilon_neighborhood.cuh`` /
``spatial/knn/detail/epsilon_neighborhood.cuh`` — boolean adjacency of
points within eps² (squared L2) plus per-point vertex degrees.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import distance


def eps_neighbors_l2sq(x, y, eps_sq: float, res=None
                       ) -> Tuple[jax.Array, jax.Array]:
    """adj[i,j] = ||x_i - y_j||² < eps², plus row degrees (vd in the
    reference; the reference also appends the total count — derive with
    ``jnp.sum(degrees)``)."""
    d = distance(x, y, DistanceType.L2Expanded, res=res)
    adj = d < eps_sq
    degrees = jnp.sum(adj.astype(jnp.int32), axis=1)
    return adj, degrees
