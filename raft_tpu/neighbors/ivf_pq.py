"""IVF-PQ ANN index.

Reference: ``raft/neighbors/ivf_pq_types.hpp:31-116`` (params: pq_bits,
pq_dim, codebook_gen PER_SUBSPACE|PER_CLUSTER, lut_dtype,
internal_distance_dtype), build ``spatial/knn/detail/ivf_pq_build.cuh``
(:173 make_rotation_matrix, :464 train_per_subset, :532 train_per_cluster,
:605 extend/encode, :908 build) and search ``ivf_pq_search.cuh``
(:127 select_clusters, :593 ivfpq_compute_similarity_kernel — smem LUT +
bit-packed code scan, :1007 search worker, :1251 public search).

TPU re-design:
  * codes are stored one-byte-per-subquantizer in padded list buckets —
    the CUDA bit-packing optimizes smem bytes; on TPU u8 codes feed
    ``take_along_axis`` gathers directly and VMEM holds the (pq_dim, 256)
    LUT comfortably (the "smem LUT" analogue; SURVEY.md hard part (a)).
  * scoring, default ("reconstruct"): random-access LUT gathers are
    hostile to TPU (XLA lowers them to scalar-core gathers — measured
    ~100x slower than the MXU path), so build() decodes the codes once
    into a bf16 reconstruction cache and search scores probes with the
    same residual-vs-list einsum as IVF-Flat — identical asymmetric-PQ
    distances up to bf16 rounding, 2x less memory than f32 IVF-Flat.
    The CUDA-style LUT-gather scan is kept as scan_mode="lut" (exact
    f32 LUT, the reference's smem-LUT analogue) for parity testing and
    small problems.
  * rotation matrix: random orthogonal via QR of a gaussian, exactly the
    reference's make_rotation_matrix trick.
"""

from __future__ import annotations

import enum
import functools
import threading
from dataclasses import dataclass, field as dataclasses_field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import obs
from raft_tpu.obs import spans
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.neighbors.ivf_flat import (_bucketize, _bucketize_static,
                                         _counts_and_max)
from raft_tpu.core.precision import matmul_precision
from raft_tpu.util.host_sample import (sample_rows, sample_rows_np,
                                       take_rows)


class CodebookGen(enum.IntEnum):
    """reference ivf_pq_types.hpp codebook_gen."""

    PER_SUBSPACE = 0
    PER_CLUSTER = 1


@dataclass
class IndexParams:
    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    # reference-parity default; it feeds BOTH the coarse trainer and the
    # PQ codebook trainers. 10 costs ~0.3% recall on random data but
    # ~1% on clustered (codebook under-convergence, 2026-08-01 A/B) —
    # the speed knob stays at call sites
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8          # 4..8 in the reference
    pq_dim: int = 0           # 0 = dim/4 heuristic (reference default path)
    codebook_kind: CodebookGen = CodebookGen.PER_SUBSPACE
    force_random_rotation: bool = False
    # matmul tier for BOTH kmeans phases (docs/tuning.md): the Pallas
    # balanced-EM coarse trainer takes it verbatim; the grouped PQ
    # codebook trainer maps it onto the equivalent XLA einsum precision
    # (core.precision.xla_precision_for_kernel)
    kmeans_kernel_precision: object = None
    # keep the raw f32 vectors on HOST for exact rescoring
    # (SearchParams.rescore_factor — the refine.cuh role fused into
    # search, the ivf_bq pattern). The device never stores them; an
    # estimator-only index stays pq_dim+8 bytes/vector
    keep_raw: bool = False
    # grouped-codebook-trainer balancing: codewords whose assignment
    # count falls below reseed_threshold·(rows/n_codes) re-seed from
    # the highest-cost rows each EM sweep (the adjust_centers role,
    # reference ivf_pq_build.cuh:436 applied to train_per_subset).
    # 0 disables reseeding; the default matches the coarse trainer's
    # balance_threshold (was a hardcoded 0.25, ADVICE r5)
    reseed_threshold: float = 0.25


@dataclass
class SearchParams:
    n_probes: int = 20
    # the reference's LUT-precision variants (ivf_pq_search.cuh:780-1004)
    # mapped to TPU terms — all live on the "codes" scan path:
    # lut_dtype = decode dtype: bf16 (one MXU pass), f32 (bf16x3 split),
    # or float8_e4m3fn (the fp_8bit tier: books stored fp8 — half the
    # codebook VMEM/HBM — computed in bf16; requires scan_mode "codes");
    # internal_distance_dtype = candidate score dtype carried to the
    # merge (bf16 halves candidate HBM traffic)
    lut_dtype: object = jnp.bfloat16
    internal_distance_dtype: object = jnp.float32
    # "auto" = "codes" when the Pallas tier is live, else "reconstruct";
    # "codes" = fused Pallas scan over the u8 codes with transient
    #           per-chunk decode tiles (pq_dim+8 bytes resident/vector);
    # "reconstruct" = bf16 decoded-cache MXU scan (XLA formulation;
    #           persists an ~8x cache over the codes);
    # "lut" = per-probe f32 LUT + gather scan (the CUDA formulation)
    scan_mode: str = "auto"
    # rescore_factor·k estimator candidates re-ranked EXACTLY against
    # the host-resident raw vectors (requires keep_raw=True at build;
    # the reference's refine.cuh step fused into search, the ivf_bq
    # pattern). PQ distances are estimates — the codebook quantization
    # error, not the probe set, limits recall at high probes — so the
    # ≥0.9-recall operating points run with rescoring. 0 disables
    # (estimator distances returned). Like ivf_bq, a factor > 0 shapes
    # the DEVICE phase (kk = factor·k candidates) even without raw, so
    # benches chain the true serving program.
    rescore_factor: int = 0
    # "probe"/"list"/"auto" — see ivf_flat.SearchParams.scan_order;
    # list-major applies to the reconstruct scan only
    scan_order: str = "auto"
    # see ivf_flat.SearchParams.scan_bins
    scan_bins: int = 0
    # see ivf_flat.SearchParams.probe_cap / _ivf_scan.resolve_cap
    probe_cap: int = 0
    # "auto" | "always" | "never" — see ivf_bq.SearchParams: the exact
    # re-rank runs fused on device when the raw corpus fits the HBM
    # budget, else on host
    rescore_on_device: str = "auto"


@dataclass
class Index:
    centers: jax.Array            # (n_lists, dim) cluster centers
    centers_rot: jax.Array        # (n_lists, rot_dim) rotated centers
    rotation_matrix: jax.Array    # (rot_dim, dim)
    # PER_SUBSPACE: (pq_dim, 2^bits, pq_len) — one codebook per subspace
    # PER_CLUSTER:  (n_lists, 2^bits, pq_len) — one codebook per coarse
    #               cluster, shared across subspaces (reference
    #               ivf_pq_build.cuh:532 train_per_cluster)
    pq_centers: jax.Array
    codes: jax.Array              # (n_lists, max_list, pq_dim) uint8
    lists_indices: jax.Array      # (n_lists, max_list) int32, -1 pad
    list_sizes: jax.Array
    metric: DistanceType
    pq_bits: int
    size: int
    codebook_kind: CodebookGen = CodebookGen.PER_SUBSPACE
    # exact decoded-residual squared norms, (n_lists, max_list) f32:
    # PQ subspaces concatenate orthogonally so the norm is a sum of
    # per-subspace codeword norms — computed once at build. With ids
    # this bounds resident memory at pq_dim+8 bytes/vector.
    code_norms: Optional[jax.Array] = None
    # bf16 reconstruction cache for the non-Pallas MXU scan path
    # (decoded codes, (n_lists, max_list, rot_dim)) + its per-row squared
    # norms. Derived from codes/pq_centers; built lazily, never on the
    # "codes" path.
    decoded: Optional[jax.Array] = None
    decoded_norms: Optional[jax.Array] = None
    # fp8-LUT tier: code norms recomputed over the float8_e4m3fn-
    # quantized books so the L2 epilogue matches what the kernel decodes
    # (lazy, like decoded)
    code_norms_fp8: Optional[jax.Array] = None
    # raw f32 vectors on HOST (keep_raw builds), indexed by global id —
    # the exact-rescore corpus (ivf_bq.Index.raw role)
    raw: Optional["np.ndarray"] = None
    # measured inverted-table widths keyed (nq, n_probes) — see
    # _ivf_scan.resolve_cap (not index identity; not serialized)
    cap_cache: dict = dataclasses_field(default_factory=dict, repr=False,
                                        compare=False)
    # AOT-compiled serving plans keyed by shape identity — see
    # neighbors/plan.py (not index identity; not serialized)
    plan_cache: dict = dataclasses_field(default_factory=dict, repr=False,
                                         compare=False)
    # lazy device copy of `raw` for the fused rescore tier
    # (SearchParams.rescore_on_device); never serialized
    raw_dev: Optional[jax.Array] = None

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def pq_dim(self) -> int:
        # derived from the codes (valid for both codebook kinds; the
        # pq_centers leading dim is n_lists under PER_CLUSTER)
        return self.codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.pq_centers.shape[2]

    @property
    def rot_dim(self) -> int:
        return self.rotation_matrix.shape[0]


@functools.partial(jax.jit, static_argnames=("dim", "rot_dim"))
def _rotation_qr(seed_arr, dim: int, rot_dim: int):
    """jit core of :func:`make_rotation_matrix` — one program instead of
    an eager op per step (every eager op is its own remote compile on
    the tunneled TPU platform; cold-build time is compile-count-bound)."""
    g = jax.random.normal(jax.random.wrap_key_data(seed_arr),
                          (max(rot_dim, dim), dim), dtype=jnp.float32)
    q, _ = jnp.linalg.qr(g.T @ g + 1e-4 * jnp.eye(dim))
    full = q.T  # (dim, dim) orthogonal
    if rot_dim <= dim:
        return full[:rot_dim]
    pad = jnp.zeros((rot_dim - dim, dim), jnp.float32)
    return jnp.concatenate([full, pad], axis=0)


def make_rotation_matrix(dim: int, rot_dim: int, force_random: bool = False,
                         seed: int = 7) -> jax.Array:
    """Random orthogonal (rot_dim, dim) via QR of a gaussian (reference
    ivf_pq_build.cuh:173). When rot_dim == dim and not forced, identity is
    allowed — but the reference always rotates when padding is needed."""
    if rot_dim == dim and not force_random:
        # numpy identity + transfer: jnp.eye eagerly compiles ~5 tiny
        # programs (iota/add/equal/convert) — one remote-compile RPC
        # each on the tunneled TPU platform
        return jnp.asarray(np.eye(dim, dtype=np.float32))
    key_data = jax.random.key_data(jax.random.key(seed))
    return _rotation_qr(key_data, dim, rot_dim)


@jax.jit
def _prep_rotated(x, centers, labels, rot):
    """Rotation + residual phase as ONE program: centers_rot, residuals,
    residuals_rot (reference ivf_pq_build.cuh:908 does the same three
    GEMM/gather steps; eagerly they are 4+ separate remote compiles)."""
    centers_rot = jnp.matmul(centers, rot.T, precision=matmul_precision())
    residuals = x - centers[labels]
    residuals_rot = jnp.matmul(residuals, rot.T,
                               precision=matmul_precision())
    return centers_rot, residuals_rot


@jax.jit
def _labels_and_prep(x, centers, rot):
    """Coarse assignment + rotation/residual phase as ONE program
    (predict's fused-L2-NN argmin is traceable — folding it in saves
    its separate remote compile; VERDICT r4 #6 compile-count collapse)."""
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn
    labels = fused_l2_nn(x, centers, sqrt=False).key
    centers_rot, residuals_rot = _prep_rotated(x, centers, labels, rot)
    return labels, centers_rot, residuals_rot


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_len",
                                             "n_codes", "n_iters",
                                             "chunk", "precision"))
def _train_books_grouped(residuals_rot, cb_idx, valid, init_idx,
                         pq_dim: int, pq_len: int, n_codes: int,
                         n_iters: int, chunk: int,
                         precision=None, reseed_threshold=0.25):
    """All pq_dim subspace codebooks trained in ONE compiled program —
    the balanced-EM semantics of the former per-subspace
    balanced_kmeans loop (assignment + masked mean + small-cluster
    reseed from the globally worst-cost points, reference
    train_per_subset ivf_pq_build.cuh:464 + adjust_centers :436),
    batched over the subspace axis and row-chunked so the (S, B, C)
    distance blocks stay bounded.

    Why one program: round-4 measured the 500k PQ cold build at 357 s
    vs 3.7 s warm — compile-COUNT-bound through the remote-compile
    tunnel, and the sequential loop's traced init sampler + glue was
    ~12 of the ~32 programs (VERDICT r4 #6). The earlier revert note
    ("batched was 25% slower on CPU") predates that measurement: the
    few-hundred-ms warm difference is noise against ~10-20 s saved
    per removed compile.

    residuals_rot (n, rot_dim); cb_idx (m_pad,) int32 trainset rows
    (cyclically padded to a chunk multiple); valid (m_pad,) bool marks
    real rows; init_idx (pq_dim, n_codes) int32 init positions INTO
    the trainset. ``precision`` is the XLA tier for the assignment/
    update einsums (static; ``None`` = the process-wide
    matmul_precision default) — ``IndexParams.kmeans_kernel_precision``
    reaches here via ``core.precision.xla_precision_for_kernel``.
    ``reseed_threshold`` (traced scalar — distinct values never
    recompile) gates the small-codeword reseed:
    ``IndexParams.reseed_threshold``. Returns (pq_dim, n_codes, pq_len)
    codebooks."""
    if precision is None:
        precision = matmul_precision()
    m = cb_idx.shape[0]
    tr = residuals_rot[cb_idx]                          # (m, rot_dim)
    sub = tr.reshape(m, pq_dim, pq_len).transpose(1, 0, 2)  # (S, m, l)
    centers0 = jnp.take_along_axis(sub, init_idx[:, :, None], axis=1)
    vf = valid.astype(jnp.float32)
    avg = jnp.sum(vf) / n_codes
    n_chunks = m // chunk
    xs = (sub.reshape(pq_dim, n_chunks, chunk, pq_len)
          .transpose(1, 0, 2, 3))                       # (nc, S, B, l)
    vs = vf.reshape(n_chunks, chunk)
    base = jnp.arange(m, dtype=jnp.int32).reshape(n_chunks, chunk)

    def one_iter(_, centers):
        cc = jnp.sum(centers * centers, axis=2)         # (S, C)

        def chunk_step(carry, inp):
            counts, sums, wd, wi = carry
            xb, vb, ib = inp                            # (S,B,l),(B,),(B,)
            ip = jnp.einsum("sbl,scl->sbc", xb, centers,
                            preferred_element_type=jnp.float32,
                            precision=precision)
            bb = jnp.sum(xb * xb, axis=2)
            d = bb[:, :, None] + cc[:, None, :] - 2.0 * ip
            assign = jnp.argmin(d, axis=2)              # (S, B)
            dmin = jnp.min(d, axis=2)
            oh = jax.nn.one_hot(assign, n_codes, dtype=jnp.float32)
            oh = oh * vb[None, :, None]
            counts = counts + jnp.sum(oh, axis=1)
            sums = sums + jnp.einsum("sbc,sbl->scl", oh, xb,
                                     preferred_element_type=jnp.float32,
                                     precision=precision)
            # running top-C worst-cost rows per subspace (reseed pool);
            # padded rows never qualify
            dmin = jnp.where(vb[None, :] > 0, dmin, -jnp.inf)
            cd = jnp.concatenate([wd, dmin], axis=1)
            cix = jnp.concatenate(
                [wi, jnp.broadcast_to(ib[None, :], dmin.shape)], axis=1)
            nwd, sel = lax.top_k(cd, n_codes)
            nwi = jnp.take_along_axis(cix, sel, axis=1)
            return (counts, sums, nwd, nwi), None

        init = (jnp.zeros((pq_dim, n_codes), jnp.float32),
                jnp.zeros((pq_dim, n_codes, pq_len), jnp.float32),
                jnp.full((pq_dim, n_codes), -jnp.inf, jnp.float32),
                jnp.zeros((pq_dim, n_codes), jnp.int32))
        (counts, sums, wd, wi), _ = lax.scan(chunk_step, init,
                                             (xs, vs, base))
        newc = sums / jnp.maximum(counts, 1.0)[:, :, None]
        newc = jnp.where(counts[:, :, None] > 0, newc, centers)
        small = counts < reseed_threshold * avg
        slot = jnp.cumsum(small.astype(jnp.int32), axis=1) - 1
        seeds = jnp.take_along_axis(sub, wi[:, :, None], axis=1)
        reseed = jnp.take_along_axis(
            seeds, jnp.clip(slot, 0, n_codes - 1)[:, :, None], axis=1)
        return jnp.where(small[:, :, None], reseed, newc)

    return lax.fori_loop(0, n_iters, one_iter, centers0)


def _train_codebooks_per_subspace(residuals_rot, pq_dim: int, pq_len: int,
                                  n_codes: int, n_iters: int, seed: int,
                                  kernel_precision=None, cb_idx=None,
                                  reseed_threshold: float = 0.25):
    """Per-subspace k-means over residual subvectors (reference
    train_per_subset, ivf_pq_build.cuh:464) — host glue around the
    single-program grouped trainer (_train_books_grouped).

    ``cb_idx``: optional HOST int array of trainset rows (the caller's
    subsample); None trains on all rows. ``kernel_precision`` follows
    the Pallas-kernel spellings (None = env default, ``bf16x3``,
    ``bf16``, ``highest``) and is threaded into the grouped trainer's
    assignment/update einsums via
    ``core.precision.xla_precision_for_kernel`` — the public
    ``IndexParams.kmeans_kernel_precision`` knob therefore shapes PQ
    codebook training exactly like the coarse trainer (it used to be
    silently dropped here)."""
    from raft_tpu.core.precision import xla_precision_for_kernel
    precision = xla_precision_for_kernel(kernel_precision)
    n = residuals_rot.shape[0]
    if cb_idx is None:
        cb_idx = np.arange(n, dtype=np.int32)
    m = int(cb_idx.shape[0])
    chunk = min(m, 4096)
    m_pad = -(-m // chunk) * chunk
    pad_idx = np.asarray(cb_idx, np.int32)[np.arange(m_pad) % m]
    valid = np.arange(m_pad) < m
    rng = np.random.default_rng(seed)
    init_idx = np.stack([
        rng.choice(m, n_codes, replace=m < n_codes)
        for _ in range(pq_dim)]).astype(np.int32)
    return _train_books_grouped(
        residuals_rot, jnp.asarray(pad_idx), jnp.asarray(valid),
        jnp.asarray(init_idx), pq_dim, pq_len, n_codes, n_iters, chunk,
        precision=precision, reseed_threshold=reseed_threshold)


def _list_chunk(L: int, per_list_elems: int,
                budget: int = 1 << 26) -> int:
    """Largest divisor of L whose chunk keeps per_list_elems·chunk under
    the element budget (bounds the (chunk, M·pq_dim, C) intermediates)."""
    from raft_tpu.neighbors._ivf_scan import largest_divisor_at_most
    return largest_divisor_at_most(L, max(1, budget // max(1,
                                                           per_list_elems)))


@functools.partial(jax.jit, static_argnames=("n_codes", "n_iters",
                                             "chunk"))
def _batched_masked_kmeans(data, valid, n_codes: int, n_iters: int, key,
                           chunk: int):
    """One k-means per leading batch entry over masked rows — the
    PER_CLUSTER codebook trainer (reference train_per_cluster,
    ivf_pq_build.cuh:532), shape-bucketed (every cluster trains in one
    compiled program) and list-chunked (``lax.map`` over groups of
    ``chunk`` lists bounds the (chunk, M, C) distance blocks).

    data (L, M, D) f32, valid (L, M) bool → (L, n_codes, D) codebooks.
    Empty slots inherit their initial center (valid rows always win the
    masked assignment)."""
    L, M, D = data.shape

    def em_block(args):
        db, vb, kb = args                                # (G, M, D) ...
        score = jax.random.uniform(kb, vb.shape) + \
            jnp.where(vb, 0.0, 2.0)
        first = jnp.argsort(score, axis=1)[:, :n_codes]
        centers0 = jnp.take_along_axis(db, first[:, :, None], axis=1)

        def one_iter(c, _):
            xx = jnp.sum(db * db, axis=2)[:, :, None]
            cc = jnp.sum(c * c, axis=2)[:, None, :]
            ip = jnp.einsum("lmd,lcd->lmc", db, c,
                            preferred_element_type=jnp.float32,
                            precision=matmul_precision())
            d = xx + cc - 2.0 * ip
            assign = jnp.argmin(d, axis=2)
            oh = jax.nn.one_hot(assign, n_codes, dtype=jnp.float32)
            oh = oh * vb[:, :, None]
            counts = jnp.sum(oh, axis=1)
            sums = jnp.einsum("lmc,lmd->lcd", oh, db,
                              preferred_element_type=jnp.float32,
                              precision=matmul_precision())
            newc = sums / jnp.maximum(counts, 1.0)[:, :, None]
            return jnp.where(counts[:, :, None] > 0, newc, c), None

        c, _ = lax.scan(one_iter, centers0, None, length=n_iters)
        return c

    keys = jax.random.split(key, L // chunk)
    out = lax.map(em_block, (data.reshape(-1, chunk, M, D),
                             valid.reshape(-1, chunk, M), keys))
    return out.reshape(L, n_codes, D)


def _nearest_code(sub, books):
    """argmin_j ||sub − books[j]||² over the last axis, batched over any
    leading dims — THE per-cluster encoding equation, shared by build
    and extend so they can never diverge."""
    ip = jnp.einsum("...sl,...cl->...sc", sub, books,
                    preferred_element_type=jnp.float32,
                    precision=matmul_precision())
    bb = jnp.sum(books * books, axis=-1)[..., None, :]
    ss = jnp.sum(sub * sub, axis=-1)[..., :, None]
    return jnp.argmin(ss + bb - 2.0 * ip, axis=-1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("chunk",))
def _encode_per_cluster(bucketed_resid, books, chunk: int):
    """codes[l, i, s] = argmin_j ||sub(l, i, s) − books[l, j]||² over the
    bucketed rotated residuals (n_lists, max_list, rot_dim), in list
    chunks."""
    L, M, rot_dim = bucketed_resid.shape
    _, n_codes, pq_len = books.shape
    pq_dim = rot_dim // pq_len

    def enc_block(args):
        rb, bb_ = args
        sub = rb.reshape(rb.shape[0], M * pq_dim, pq_len)
        return _nearest_code(sub, bb_).reshape(rb.shape[0], M, pq_dim)

    out = lax.map(enc_block,
                  (bucketed_resid.reshape(-1, chunk, M, rot_dim),
                   books.reshape(-1, chunk, n_codes, pq_len)))
    return out.reshape(L, M, pq_dim)


@jax.jit
def _code_norms_per_cluster(codes_b, books, lists_indices):
    """Exact ||decoded||² per slot for PER_CLUSTER books: subspaces share
    the list's codebook, so the norm is Σ_s ||books_l[c_s]||²."""
    L, M, pq_dim = codes_b.shape
    bb = jnp.sum(books * books, axis=2)                  # (L, n_codes)
    norms = jnp.zeros((L, M), jnp.float32)
    for s in range(pq_dim):
        norms = norms + jnp.take_along_axis(
            bb, codes_b[:, :, s].astype(jnp.int32), axis=1)
    return jnp.where(lists_indices >= 0, norms, 0.0)


@functools.partial(jax.jit, static_argnames=())
def _encode(residuals_rot, pq_centers):
    """codes[i, s] = argmin_j ||residual_sub(i,s) - pq_centers[s, j]||²."""
    pq_dim, n_codes, pq_len = pq_centers.shape
    sub = residuals_rot.reshape(residuals_rot.shape[0], pq_dim, pq_len)

    def per_subspace(vecs, book):
        # (n, pq_len) vs (n_codes, pq_len)
        vv = jnp.sum(vecs * vecs, axis=1)
        bb = jnp.sum(book * book, axis=1)
        d = (vv[:, None] + bb[None, :]
             - 2.0 * jnp.matmul(vecs, book.T, precision=matmul_precision()))
        return jnp.argmin(d, axis=1).astype(jnp.uint8)

    return jax.vmap(per_subspace, in_axes=(1, 0), out_axes=1)(sub, pq_centers)


@functools.partial(jax.jit, static_argnames=("n_lists", "max_list"))
def _bucketize_codes(codes, labels, counts, pq_centers, n_lists: int,
                     max_list: int):
    """Bucket the (n, pq_dim) uint8 codes into the padded list layout
    AND compute the exact decoded norms in ONE program: the codes ride
    as their integer payload end-to-end (no f32 round-trip casts — the
    ivf_bq int32-payload contract) and the ``_code_norms`` pass fuses
    into the same compile instead of being its own dispatch."""
    codes_b, idx, _, counts = _bucketize_static(
        codes, labels, None, n_lists, max_list, counts=counts,
        compute_norms=False)
    return codes_b, idx, counts, _code_norms(codes_b, pq_centers, idx)


@spans.spanned("raft.ivf_pq.build")
@obs.timed("raft.ivf_pq.build")
def build(dataset, params: IndexParams = IndexParams(), seed: int = 0,
          res=None) -> Index:
    """Build (reference ivf_pq_build.cuh:908): balanced-kmeans coarse
    training → rotation → per-subspace codebooks on residuals → encode."""
    x = as_array(dataset).astype(jnp.float32)
    n, dim = x.shape
    expects(params.n_lists <= n, "ivf_pq.build: n_lists > n_samples")
    obs.counter("raft.ivf_pq.build.total").inc()
    obs.counter("raft.ivf_pq.build.rows").inc(n)
    spans.current_span().set_attrs(rows=n, n_lists=params.n_lists,
                                   pq_bits=params.pq_bits)
    pq_dim = params.pq_dim if params.pq_dim > 0 else max(1, dim // 4)
    rot_dim = ((dim + pq_dim - 1) // pq_dim) * pq_dim
    pq_len = rot_dim // pq_dim
    n_codes = 1 << params.pq_bits
    expects(n >= n_codes,
            "ivf_pq.build: need at least 2^pq_bits (%d) training rows", n_codes)
    expects(params.metric in (DistanceType.L2Expanded,
                              DistanceType.L2SqrtExpanded,
                              DistanceType.L2Unexpanded,
                              DistanceType.L2SqrtUnexpanded,
                              DistanceType.InnerProduct),
            "ivf_pq: L2-family and InnerProduct metrics are supported "
            "(got %s)", params.metric)

    n_train = max(params.n_lists, int(n * params.kmeans_trainset_fraction))
    if n_train < n:
        # host-side draw (util.host_sample): a traced
        # choice(replace=False) is an n-wide sort compile on TPU
        trainset = take_rows(x, sample_rows(n, n_train, seed))
    else:
        trainset = x
    centers = kmeans_balanced.build_hierarchical(
        trainset, params.n_lists, params.kmeans_n_iters,
        kernel_precision=params.kmeans_kernel_precision, res=res)

    rot = make_rotation_matrix(dim, rot_dim, params.force_random_rotation,
                               seed=seed + 1)
    # coarse assignment + rotation/residuals in ONE program
    labels, centers_rot, residuals_rot = _labels_and_prep(x, centers, rot)

    if params.codebook_kind == CodebookGen.PER_CLUSTER:
        # one codebook per coarse cluster (reference train_per_cluster):
        # bucket the rotated residuals, train a batched masked k-means
        # over every list's pooled subvectors, encode in place
        bucketed, idx, _, counts = _bucketize(residuals_rot, labels,
                                              params.n_lists)
        L, M, _ = bucketed.shape
        # per-subvector validity: each row contributes pq_dim subvectors
        valid = jnp.broadcast_to((idx >= 0)[:, :, None],
                                 (L, M, pq_dim)).reshape(L, -1)
        sub_all = bucketed.reshape(L, M * pq_dim, pq_len)
        t_sub = min(M * pq_dim, 4096)  # training subsample per list
        tr_sub, tr_valid = sub_all[:, :t_sub], valid[:, :t_sub]
        if t_sub < n_codes:
            # the trainer seeds n_codes centers from the slice: pad
            # short lists by cyclic repetition (duplicate seeds are
            # harmless — empty codewords keep their init)
            reps = -(-n_codes // t_sub)
            tr_sub = jnp.tile(tr_sub, (1, reps, 1))[:, :n_codes]
            tr_valid = jnp.tile(tr_valid, (1, reps))[:, :n_codes]
        chunk_t = _list_chunk(L, tr_sub.shape[1] * n_codes)
        books = _batched_masked_kmeans(
            tr_sub, tr_valid, n_codes,
            params.kmeans_n_iters, jax.random.key(seed + 2), chunk_t)
        chunk_e = _list_chunk(L, M * pq_dim * n_codes)
        codes_b = _encode_per_cluster(bucketed, books, chunk_e)
        return Index(centers=centers, centers_rot=centers_rot,
                     rotation_matrix=rot, pq_centers=books, codes=codes_b,
                     lists_indices=idx, list_sizes=counts,
                     metric=params.metric, pq_bits=params.pq_bits, size=n,
                     codebook_kind=CodebookGen.PER_CLUSTER,
                     code_norms=_code_norms_per_cluster(codes_b, books,
                                                        idx),
                     raw=(np.asarray(jax.device_get(x))
                          if params.keep_raw else None))

    n_cb_train = min(n, 1 << 16)
    # the trainset subsample stays HOST indices (padding/init glue runs
    # host-side; the gather rides inside the grouped trainer program)
    cb_idx = (sample_rows_np(n, n_cb_train, seed + 3)
              if n_cb_train < n else None)
    pq_centers = _train_codebooks_per_subspace(
        residuals_rot, pq_dim, pq_len, n_codes,
        params.kmeans_n_iters, seed + 2,
        kernel_precision=params.kmeans_kernel_precision, cb_idx=cb_idx,
        reseed_threshold=params.reseed_threshold)

    codes = _encode(residuals_rot, pq_centers)  # (n, pq_dim) u8

    # bucket codes by list using the same static padded layout as
    # IVF-Flat — directly as uint8 (integer payload: no norms pass, no
    # f32 round-trip casts; same contract as the ivf_bq int32 payloads),
    # with the code-norms pass fused into the same program
    counts, mx = _counts_and_max(labels, params.n_lists)
    max_list = int(jax.device_get(mx))
    max_list = max(8, -(-max_list // 8) * 8)
    codes_b, idx, counts, norms = _bucketize_codes(
        codes, labels, counts, pq_centers, params.n_lists, max_list)

    # the bf16 reconstruction cache is decoded lazily at first
    # reconstruct-mode search — codes/LUT-mode users and serialized
    # indexes never pay its ~8x memory over the codes
    return Index(centers=centers, centers_rot=centers_rot,
                 rotation_matrix=rot, pq_centers=pq_centers, codes=codes_b,
                 lists_indices=idx, list_sizes=counts, metric=params.metric,
                 pq_bits=params.pq_bits, size=n,
                 code_norms=norms,
                 raw=(np.asarray(jax.device_get(x))
                      if params.keep_raw else None))


def extend(index: Index, new_vectors, new_indices=None, res=None) -> Index:
    """Add vectors to an existing index (reference ``ivf_pq::extend``,
    ivf_pq_build.cuh:605): label against the trained centers, encode
    residuals with the FROZEN codebooks/rotation, and re-bucket the
    combined code set. Returns a new Index; the reconstruction cache is
    re-derived lazily."""
    x = as_array(new_vectors).astype(jnp.float32)
    expects(x.ndim == 2 and x.shape[1] == index.dim,
            "ivf_pq.extend: dim mismatch")
    n_new = x.shape[0]
    new_ids = (jnp.arange(index.size, index.size + n_new, dtype=jnp.int32)
               if new_indices is None
               else as_array(new_indices).astype(jnp.int32))
    expects(new_ids.shape == (n_new,), "ivf_pq.extend: bad new_indices")
    expects(bool((new_ids >= 0).all()),
            "ivf_pq.extend: new_indices must be non-negative (negative "
            "ids are the padding sentinel)")
    # the host rescore indexes `raw` BY global id — custom ids would
    # misalign it (the ivf_bq.extend contract)
    expects(index.raw is None or new_indices is None,
            "ivf_pq.extend: custom new_indices are only supported on "
            "keep_raw=False indexes (raw rescore rows are id-indexed)")

    labels = kmeans_balanced.predict(x, index.centers, res=res)
    residuals_rot = jnp.matmul(x - index.centers[labels],
                               index.rotation_matrix.T,
                               precision=matmul_precision())
    if index.codebook_kind == CodebookGen.PER_CLUSTER:
        # frozen per-list books: encode each new row through its label's
        # codebook (reference extend with codebook_gen PER_CLUSTER)
        sub = residuals_rot.reshape(x.shape[0], index.pq_dim,
                                    index.pq_len)
        new_codes = _nearest_code(sub, index.pq_centers[labels])
    else:
        new_codes = _encode(residuals_rot, index.pq_centers)

    # flatten existing valid slots back to (n_old, pq_dim) + their ids
    flat_codes = index.codes.reshape(-1, index.pq_dim)
    flat_ids = index.lists_indices.reshape(-1)
    n_lists, max_list = index.lists_indices.shape
    old_list = jnp.repeat(jnp.arange(n_lists, dtype=jnp.int32), max_list)
    valid = flat_ids >= 0  # eager boolean mask, as in ivf_flat.extend
    n_old = int(index.size)
    all_codes = jnp.concatenate([flat_codes[valid], new_codes], axis=0)
    all_labels = jnp.concatenate([old_list[valid], labels], axis=0)
    all_ids = jnp.concatenate([flat_ids[valid], new_ids], axis=0)

    bucketed, idx, _, counts = _bucketize(
        all_codes.astype(jnp.float32), all_labels, n_lists,
        row_ids=all_ids)
    codes_b = bucketed.astype(jnp.uint8)
    norms_fn = (_code_norms_per_cluster
                if index.codebook_kind == CodebookGen.PER_CLUSTER
                else _code_norms)
    return Index(centers=index.centers, centers_rot=index.centers_rot,
                 rotation_matrix=index.rotation_matrix,
                 pq_centers=index.pq_centers,
                 codes=codes_b,
                 lists_indices=idx, list_sizes=counts,
                 metric=index.metric, pq_bits=index.pq_bits,
                 size=n_old + n_new,
                 codebook_kind=index.codebook_kind,
                 code_norms=norms_fn(codes_b, index.pq_centers, idx),
                 raw=(np.concatenate(
                     [index.raw, np.asarray(jax.device_get(x))])
                     if index.raw is not None else None))


@jax.jit
def _code_norms(codes_b, pq_centers, lists_indices):
    """Exact ||decoded||² per bucketed slot from the codebook norm
    table: subspaces are orthogonal coordinate blocks, so the decoded
    squared norm is Σ_s ||book_s[c_s]||². Pad slots → 0."""
    n_lists, max_list, pq_dim = codes_b.shape
    bb = jnp.sum(pq_centers * pq_centers, axis=2)      # (pq_dim, n_codes)
    flat = codes_b.reshape(-1, pq_dim).astype(jnp.int32)
    norms = jnp.zeros((flat.shape[0],), jnp.float32)
    for s in range(pq_dim):
        norms = norms + bb[s][flat[:, s]]
    norms = norms.reshape(n_lists, max_list)
    return jnp.where(lists_indices >= 0, norms, 0.0)


@jax.jit
def _decode_lists_per_cluster(codes_b, books, lists_indices):
    """Decode PER_CLUSTER codes → bf16 reconstruction cache: subspace s
    of row i in list l decodes through list l's codebook."""
    L, M, pq_dim = codes_b.shape
    _, n_codes, pq_len = books.shape

    def one_list(codes_l, book):
        return book[codes_l.astype(jnp.int32)]        # (M, pq_dim, pl)

    dec = jax.vmap(one_list)(codes_b, books)
    dec = dec.reshape(L, M, pq_dim * pq_len)
    valid = (lists_indices >= 0)[:, :, None]
    return jnp.where(valid, dec, 0.0).astype(jnp.bfloat16)


@jax.jit
def _decode_lists(codes_b, pq_centers, lists_indices):
    """Decode bucketed PQ codes → bf16 reconstruction cache
    ((n_lists, max_list, rot_dim) rotated residuals). Its norms are NOT
    recomputed here — ``_code_norms`` already holds the identical exact
    quantity. One row-gather per subquantizer from its (n_codes, pq_len)
    table — a single fancy-gather over the stacked books broadcasts a
    huge (N, pq_dim, n_codes, pq_len) intermediate on TPU and OOMs at
    ~1M rows; the per-subspace loop stays O(N·pq_len) per step."""
    n_lists, max_list, pq_dim = codes_b.shape
    _, n_codes, pq_len = pq_centers.shape
    flat = codes_b.reshape(-1, pq_dim).astype(jnp.int32)   # (N, pq_dim)
    # decoded[i, s, :] = pq_centers[s, flat[i, s], :]
    dec = jnp.stack([pq_centers[s][flat[:, s]] for s in range(pq_dim)],
                    axis=1)                                # (N, s, l)
    dec = dec.reshape(n_lists, max_list, pq_dim * pq_len)
    # padded slots decode to code 0's centroid; zero them so their norms
    # are harmless (scores for pads are masked at search anyway)
    valid = (lists_indices >= 0)[:, :, None]
    dec = jnp.where(valid, dec, 0.0)
    return dec.astype(jnp.bfloat16)


def _score_probe_reconstruct(q_rot, centers_rot, decoded, decoded_norms,
                             lists_indices, list_id, kind: str = "l2"):
    """Score one probe rank via the bf16 reconstruction cache — shared
    by single-chip and sharded searches. ``kind`` "ip" scores
    ``q_rot·(c_l + decoded)`` and returns negated similarities."""
    data = decoded[list_id]                          # (nq, ml, rot_dim)
    ids = lists_indices[list_id]                     # (nq, ml)
    if kind == "ip":
        qb = q_rot.astype(jnp.bfloat16)
        # one MXU pass on purpose: the bf16 reconstruction scan tier
        ip = jnp.einsum("qd,qld->ql", qb, data,
                        preferred_element_type=jnp.float32,
                        precision=lax.Precision.DEFAULT)
        cq = jnp.sum(q_rot * centers_rot[list_id], axis=1)  # (nq,)
        return jnp.where(ids >= 0, -(ip + cq[:, None]), jnp.inf), ids
    resid = (q_rot - centers_rot[list_id]).astype(jnp.bfloat16)
    ip = jnp.einsum("qd,qld->ql", resid, data,
                    preferred_element_type=jnp.float32,
                    precision=lax.Precision.DEFAULT)
    rr = jnp.sum(resid.astype(jnp.float32) ** 2, axis=1)
    d = rr[:, None] + decoded_norms[list_id] - 2.0 * ip
    return jnp.where(ids >= 0, jnp.maximum(d, 0.0), jnp.inf), ids


@functools.partial(jax.jit,
                   static_argnames=("k", "n_probes", "sqrt", "kind"))
def _search_impl_reconstruct(queries, centers, centers_rot, rot, decoded,
                             decoded_norms, lists_indices, k: int,
                             n_probes: int, sqrt: bool,
                             kind: str = "l2"):
    """MXU scan over the bf16 reconstruction cache: per probe rank,
    score = ||resid - decoded||² via the expanded form — the IVF-Flat
    interleaved-scan analogue (ivf_flat_search.cuh:665) with residuals
    in place of raw queries."""
    nq, dim = queries.shape

    from raft_tpu.neighbors.ivf_flat import _coarse_scores
    coarse = _coarse_scores(queries, centers, kind)
    _, probes = lax.top_k(-coarse, n_probes)
    q_rot = jnp.matmul(queries, rot.T, precision=matmul_precision())

    def probe_step(carry, p):
        best_d, best_i = carry
        d, ids = _score_probe_reconstruct(
            q_rot, centers_rot, decoded, decoded_norms, lists_indices,
            probes[:, p], kind=kind)
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_i = jnp.concatenate([best_i, ids], axis=1)
        nd, sel = lax.top_k(-cat_d, k)
        return (-nd, jnp.take_along_axis(cat_i, sel, axis=1)), None

    init = (jnp.full((nq, k), jnp.inf, jnp.float32),
            jnp.full((nq, k), -1, jnp.int32))
    (d, i), _ = lax.scan(probe_step, init, jnp.arange(n_probes))
    if sqrt:
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    return d, i


@functools.partial(jax.jit,
                   static_argnames=("k", "n_probes", "sqrt", "kind",
                                    "per_cluster"))
def _search_impl(queries, centers, centers_rot, rot, pq_centers, codes,
                 lists_indices, k: int, n_probes: int, sqrt: bool,
                 kind: str = "l2", per_cluster: bool = False):
    nq, dim = queries.shape
    n_lists = centers.shape[0]
    pq_dim = codes.shape[2]
    n_codes, pq_len = pq_centers.shape[1], pq_centers.shape[2]

    # coarse: select_clusters (reference :127)
    from raft_tpu.neighbors.ivf_flat import _coarse_scores
    coarse = _coarse_scores(queries, centers, kind)
    _, probes = lax.top_k(-coarse, n_probes)

    q_rot = queries @ rot.T  # (nq, rot_dim) (reference :1360 query rotation)

    bb = jnp.sum(pq_centers * pq_centers, axis=2)  # (pq_dim|L, n_codes)

    # the per-subspace IP LUT is probe-independent (no residual):
    # LUT[q, s, j] = sub_q(q,s)·book[s, j]; the per-probe center term
    # q_rot·c_l is added after the code gather (reference ip distance
    # dispatch). Hoisted out of the scan so it runs once, not n_probes
    # times. PER_CLUSTER books depend on the probed list, so its LUTs
    # are built inside the scan for both metrics.
    ip_lut = None
    if kind == "ip" and not per_cluster:
        ip_lut = jnp.einsum("qsl,sjl->qsj",
                            q_rot.reshape(nq, pq_dim, pq_len), pq_centers,
                            preferred_element_type=jnp.float32,
                            precision=matmul_precision())

    def probe_step(carry, p):
        best_d, best_i = carry
        list_id = probes[:, p]                           # (nq,)
        if per_cluster:
            books_l = pq_centers[list_id]                # (nq, C, pl)
            if kind == "ip":
                sub = q_rot.reshape(nq, pq_dim, pq_len)
                lut = jnp.einsum("qsl,qjl->qsj", sub, books_l,
                                 preferred_element_type=jnp.float32,
                                 precision=matmul_precision())
            else:
                resid = q_rot - centers_rot[list_id]
                sub = resid.reshape(nq, pq_dim, pq_len)
                ip = jnp.einsum("qsl,qjl->qsj", sub, books_l,
                                preferred_element_type=jnp.float32,
                                precision=matmul_precision())
                ss = jnp.sum(sub * sub, axis=2)
                lut = (ss[:, :, None] + bb[list_id][:, None, :]
                       - 2.0 * ip)
        elif kind == "ip":
            lut = ip_lut
        else:
            # per-query LUT from the rotated residual wrt this center
            resid = q_rot - centers_rot[list_id]         # (nq, rot_dim)
            sub = resid.reshape(nq, pq_dim, pq_len)
            # LUT[q, s, j] = ||sub(q,s) - pq_centers[s, j]||²
            ip = jnp.einsum("qsl,sjl->qsj", sub, pq_centers,
                            preferred_element_type=jnp.float32,
                            precision=matmul_precision())
            ss = jnp.sum(sub * sub, axis=2)
            lut = ss[:, :, None] + bb[None, :, :] - 2.0 * ip

        pcodes = codes[list_id].astype(jnp.int32)        # (nq, max_list, pq_dim)
        ids = lists_indices[list_id]                     # (nq, max_list)
        # scores[q, i] = Σ_s lut[q, s, pcodes[q, i, s]]
        gathered = jnp.take_along_axis(
            lut[:, None, :, :],                          # (nq, 1, pq_dim, n_codes)
            pcodes[:, :, :, None],                       # (nq, max_list, pq_dim, 1)
            axis=3)[..., 0]                              # (nq, max_list, pq_dim)
        d = jnp.sum(gathered, axis=2)
        if kind == "ip":
            cq = jnp.sum(q_rot * centers_rot[list_id], axis=1)
            d = jnp.where(ids >= 0, -(d + cq[:, None]), jnp.inf)
        else:
            d = jnp.where(ids >= 0, jnp.maximum(d, 0.0), jnp.inf)
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_i = jnp.concatenate([best_i, ids], axis=1)
        nd, sel = lax.top_k(-cat_d, k)
        return (-nd, jnp.take_along_axis(cat_i, sel, axis=1)), None

    init = (jnp.full((nq, k), jnp.inf, jnp.float32),
            jnp.full((nq, k), -1, jnp.int32))
    (d, i), _ = lax.scan(probe_step, init, jnp.arange(n_probes))
    if sqrt:
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    return d, i


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "cap",
                                             "bins", "sqrt", "kind",
                                             "lut_dtype", "internal_dtype",
                                             "per_cluster", "gather",
                                             "fused"))
def _fused_code_search(q, centers, centers_rot, rot, pq_centers, codes,
                       code_norms, lists_indices, *, k: int,
                       n_probes: int, cap: int, bins: int, sqrt: bool,
                       kind: str, lut_dtype, internal_dtype,
                       per_cluster: bool, gather: str = "rows",
                       fused: bool = False):
    """Single-dispatch code-resident search: coarse select_clusters,
    query rotation, the Pallas code scan and the candidate merge in ONE
    jitted computation (the reference search worker is likewise one
    kernel stream, ``ivf_pq_search.cuh:1007``; see
    ``_ivf_scan.fused_list_search`` for why dispatch count was the
    round-3 QPS lever)."""
    from raft_tpu.neighbors import _ivf_scan
    from raft_tpu.ops.pallas_ivf_scan import ivf_pq_code_scan_pallas
    probes = _ivf_scan.coarse_probes(q, centers, n_probes, kind=kind,
                                     use_pallas=True)
    q_rot = jnp.matmul(q, rot.T, precision=matmul_precision())
    return ivf_pq_code_scan_pallas(
        q_rot, centers_rot, pq_centers, codes, code_norms, lists_indices,
        probes, k, cap, bins=bins, sqrt=sqrt, lut_dtype=lut_dtype,
        internal_distance_dtype=internal_dtype, metric=kind,
        per_cluster=per_cluster, gather=gather, fused=fused)


# guards the lazy reconstruction-cache materialization: ladder
# fallback tiers can run on a compile-budget thread concurrently with
# the inline tail (see _ensure_decoded)
_DECODE_LOCK = threading.Lock()


def _base_code_norms(index: Index):
    """Exact decoded-residual norms, derived once for older indexes
    that predate the build-time pass."""
    if index.code_norms is None:
        fn = (_code_norms_per_cluster
              if index.codebook_kind == CodebookGen.PER_CLUSTER
              else _code_norms)
        index.code_norms = fn(index.codes, index.pq_centers,
                              index.lists_indices)
    return index.code_norms


def _ensure_code_norms(index: Index, params: "SearchParams",
                       per_cluster: bool, kind: str):
    """Code norms matched to the LUT tier the code scan decodes: the
    fp8 tier's L2 epilogue must use norms of the fp8-QUANTIZED books
    (reference fp_8bit tier — the LUT there carries the same
    quantization in its distance terms); every other tier uses the
    exact build-time norms. Shared by ``search`` and the plan layer."""
    if (jnp.dtype(params.lut_dtype) == jnp.dtype(jnp.float8_e4m3fn)
            and kind == "l2"):
        if index.code_norms_fp8 is None:
            books8 = index.pq_centers.astype(
                jnp.float8_e4m3fn).astype(jnp.float32)
            fn = (_code_norms_per_cluster if per_cluster
                  else _code_norms)
            index.code_norms_fp8 = fn(index.codes, books8,
                                      index.lists_indices)
        return index.code_norms_fp8
    return _base_code_norms(index)


def _ensure_decoded(index: Index, per_cluster: bool) -> None:
    """Materialize the bf16 reconstruction cache lazily.

    Lock: ladder fallback tiers may run in a compile-budget thread
    while a later tier runs inline on the main thread — an unguarded
    check-then-set would materialize the ~8× decoded cache TWICE
    (peak-HBM hazard) and race the index mutation (r4 review finding).
    The decode programs are simple proven-compilable gathers, so
    holding the lock across them is bounded in practice."""
    if index.decoded is not None and index.decoded_norms is not None:
        return
    with _DECODE_LOCK:
        if index.decoded is None:
            dec_fn = (_decode_lists_per_cluster if per_cluster
                      else _decode_lists)
            index.decoded = dec_fn(index.codes, index.pq_centers,
                                   index.lists_indices)
        if index.decoded_norms is None:
            # alias the exact build-time norms — same quantity
            index.decoded_norms = _base_code_norms(index)


def search(index: Index, queries, k: int,
           params: SearchParams = SearchParams(), res=None
           ) -> Tuple[jax.Array, jax.Array]:
    """ANN search → (approx dists, neighbor ids) (reference
    ivf_pq_search.cuh:1251). ``params.scan_mode``: "auto" (default)
    resolves to the code-resident fused Pallas scan ("codes": u8 codes
    + transient decode tiles, pq_dim+8 bytes resident per vector) when
    the kernel tier is live, else the bf16 reconstruction-cache scan
    ("reconstruct", ~8x the codes' memory); "lut" is the CUDA-style
    gather formulation kept for parity testing."""
    with spans.span("raft.ivf_pq.search", k=k) as sp:
        return _search_spanned(index, queries, k, params, res, sp)


def _search_spanned(index: Index, queries, k: int, params, res, sp
                    ) -> Tuple[jax.Array, jax.Array]:
    q = as_array(queries).astype(jnp.float32)
    sp.set_attr("nq", int(q.shape[0]))
    expects(q.shape[1] == index.dim, "ivf_pq.search: dim mismatch")
    expects(params.scan_mode in ("auto", "codes", "reconstruct", "lut"),
            f"ivf_pq.search: unknown scan_mode {params.scan_mode!r}")
    from raft_tpu.neighbors.ann_types import (MAX_QUERY_BATCH,
                                              batched_search,
                                              pin_scan_order)
    if q.shape[0] > MAX_QUERY_BATCH:
        # reference batching loop (ivf_pq_search.cuh:1251/:1234); pin
        # "auto" choices from the FULL query count first
        import dataclasses
        mode = params.scan_mode
        if mode == "auto":
            from raft_tpu.ops.dispatch import pallas_enabled
            mode = "codes" if pallas_enabled() else "reconstruct"
        pinned = pin_scan_order(dataclasses.replace(params, scan_mode=mode),
                                q.shape[0], index.n_lists)
        return batched_search(
            lambda qb: search(index, qb, k, pinned, res=res), q)
    expects(params.scan_order in ("auto", "probe", "list"),
            f"ivf_pq.search: unknown scan_order {params.scan_order!r}")
    n_probes = min(params.n_probes, index.n_lists)
    sp.set_attr("n_probes", n_probes)
    # per-batch telemetry (the batched path recurses here per
    # sub-batch, so queries sum correctly across the split)
    obs.counter("raft.ivf_pq.search.queries").inc(q.shape[0])
    obs.histogram("raft.ivf_pq.search.batch_size",
                  buckets=obs.SIZE_BUCKETS).observe(q.shape[0])
    obs.histogram("raft.ivf_pq.search.n_probes",
                  buckets=obs.SIZE_BUCKETS).observe(n_probes)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    from raft_tpu.neighbors.ivf_flat import _metric_kind, _postprocess
    kind = _metric_kind(index.metric)
    per_cluster = index.codebook_kind == CodebookGen.PER_CLUSTER

    # exact re-ranking (SearchParams.rescore_factor): the device phase
    # returns kk = factor·k estimator candidates; the epilogue re-ranks
    # them against the host raw corpus (ivf_bq.finish_search — shared
    # so the exact-rescore semantics stay identical across families)
    expects(params.rescore_factor >= 0,
            "ivf_pq.search: rescore_factor must be >= 0")
    expects(params.rescore_on_device in ("auto", "always", "never"),
            "ivf_pq.search: rescore_on_device: want auto|always|never,"
            " got %r", params.rescore_on_device)
    rescoring = params.rescore_factor > 0 and index.raw is not None
    kk = max(params.rescore_factor, 1) * k
    # sqrt/output conventions move to the epilogue when it is not the
    # legacy slice (finish_search applies them itself)
    dev_sqrt = sqrt if (kk == k and not rescoring) else False

    def _epilogue(d, i):
        if kk == k and not rescoring:
            return _postprocess(d, index.metric), i
        from raft_tpu.neighbors.ivf_bq import (finish_search,
                                               resolve_raw_device)
        raw_dev = (resolve_raw_device(index, params.rescore_on_device)
                   if rescoring else None)
        return finish_search(d, i, index.raw, q, k, metric=index.metric,
                             rescore=rescoring, raw_dev=raw_dev)

    # candidate bins: when rescoring widens kk, the per-list 4·k auto
    # rule (pallas_ivf_scan._Layout) would blow the merge width
    # (n_probes·4·kk-wide selects, ~0.5 GB candidate blocks at the
    # bench point) — switch to the ivf_bq global-pool rule: a
    # 32×-oversampled pool spread over the probed lists, floor 128
    bins = params.scan_bins
    if bins == 0 and kk > k:
        max_list = index.codes.shape[1]
        bins = min(max(128, (32 * kk) // max(n_probes, 1)), max_list)

    scan_mode = params.scan_mode
    if scan_mode == "auto":
        from raft_tpu.ops.dispatch import pallas_enabled
        scan_mode = "codes" if pallas_enabled() else "reconstruct"
    sp.set_attrs(mode=scan_mode, rescoring=rescoring)
    expects(jnp.dtype(params.lut_dtype) in
            (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
             jnp.dtype(jnp.float8_e4m3fn)),
            "ivf_pq: lut_dtype must be float32|bfloat16|float8_e4m3fn")
    # the fp8 tier only exists on the code-resident scan: reject rather
    # than silently measure the full-precision reconstruct/lut paths
    expects(jnp.dtype(params.lut_dtype) != jnp.dtype(jnp.float8_e4m3fn)
            or scan_mode == "codes",
            "ivf_pq: lut_dtype=float8_e4m3fn requires scan_mode='codes' "
            "(resolved scan_mode is %r)", scan_mode)
    def _recon_list():
        """Reconstruct-cache fused list scan (l2 core only)."""
        from raft_tpu.neighbors import _ivf_scan
        _ensure_decoded(index, per_cluster)
        cap = _ivf_scan.resolve_cap(index.cap_cache, q, index.centers,
                                    params, n_probes, index.n_lists)
        # lists hold decoded rotated residuals: the scan offsets
        # each list's queries by its rotated center so the einsum
        # scores ||(q_rot - c_l) - decoded||²
        return _ivf_scan.fused_reconstruct_list_search(
            q, index.centers, index.centers_rot,
            index.rotation_matrix, index.decoded,
            index.decoded_norms, index.lists_indices, k=kk,
            n_probes=n_probes, cap=cap, bins=bins,
            sqrt=dev_sqrt)

    def _recon_probe():
        """Probe-major reconstruct scan — small per-probe programs,
        the always-compilable tail of the codes ladder."""
        _ensure_decoded(index, per_cluster)
        return _search_impl_reconstruct(
            q, index.centers, index.centers_rot,
            index.rotation_matrix, index.decoded,
            index.decoded_norms, index.lists_indices,
            kk, n_probes, dev_sqrt, kind=kind)

    if scan_mode == "codes":
        from raft_tpu.neighbors import _ivf_scan
        from raft_tpu.ops.compile_budget import run_tiers
        from raft_tpu.ops.pallas_ivf_scan import fused_mode
        _ivf_scan.count_coarse_fallback(n_probes, True)
        # RAII scope (reference nvtx range in search, ivf_pq_search.cuh:
        # 1263), exception-safe; obs.timed opens the trace range AND the
        # wall-time histogram under one taxonomy name
        with obs.timed("raft.ivf_pq.search", mode="codes"):
            cap = _ivf_scan.resolve_cap(index.cap_cache, q,
                                        index.centers, params, n_probes,
                                        index.n_lists, kind=kind,
                                        use_pallas=True)
            code_norms = _ensure_code_norms(index, params, per_cluster,
                                            kind)

            def codes_tier(fz: bool = False):
                return lambda: _fused_code_search(
                    q, index.centers, index.centers_rot,
                    index.rotation_matrix, index.pq_centers, index.codes,
                    code_norms, index.lists_indices, k=kk,
                    n_probes=n_probes, cap=cap, bins=bins,
                    sqrt=dev_sqrt, kind=kind, lut_dtype=params.lut_dtype,
                    internal_dtype=params.internal_distance_dtype,
                    per_cluster=per_cluster,
                    gather=_ivf_scan.gather_mode(), fused=fz)

            # compile-budget ladder (ops/compile_budget.py): the fused
            # scan+select code kernel (ONE pallas_call fine phase,
            # ISSUE 7), the unfused Pallas code scan, then the
            # reconstruct-cache XLA formulations (which trade the
            # codes' memory footprint for a proven program shape).
            # NOTE the fallbacks score bf16 reconstructions — same
            # recall class, not bit-identical.
            fused_on = fused_mode() and kk <= 256
            tiers = []
            if fused_on:
                obs.counter("raft.ivf_scan.fused.total",
                            family="ivf_pq").inc()
                obs.counter("raft.ivf_scan.fused.queries").inc(
                    q.shape[0])
                tiers.append(("pallas_fused_codes", codes_tier(True)))
            tiers.append(("pallas_codes", codes_tier()))
            if kind == "l2":
                tiers.append(("xla_reconstruct_list", _recon_list))
            tiers.append(("reconstruct_probe_major", _recon_probe))
            # key covers every program-shaping static (see
            # ivf_flat.search)
            shape_key = (f"ivf_pq[{q.shape[0]}x{index.dim},k={kk},"
                         f"p={n_probes},cap={cap},L={index.n_lists},"
                         f"pq={index.pq_dim}x{index.pq_bits}b,"
                         f"{kind},sqrt={dev_sqrt},b={bins},"
                         f"lut={jnp.dtype(params.lut_dtype).name},"
                         f"idt={jnp.dtype(params.internal_distance_dtype).name},"
                         f"pc={per_cluster},"
                         f"g={_ivf_scan.gather_mode()},"
                         f"fz={fused_on}]")
            d, i = run_tiers(shape_key, tiers)
        return _epilogue(d, i)
    if scan_mode == "reconstruct":
        with obs.timed("raft.ivf_pq.search", mode="reconstruct"):
            nq = q.shape[0]
            from raft_tpu.neighbors.ann_types import list_order_auto
            use_list = (kind == "l2"
                        and (params.scan_order == "list"
                             or (params.scan_order == "auto"
                                 and list_order_auto(nq, n_probes,
                                                     index.n_lists))))
            d, i = _recon_list() if use_list else _recon_probe()
        return _epilogue(d, i)
    with obs.timed("raft.ivf_pq.search", mode="lut"):
        d, i = _search_impl(q, index.centers, index.centers_rot,
                            index.rotation_matrix, index.pq_centers,
                            index.codes, index.lists_indices, kk, n_probes,
                            dev_sqrt, kind=kind, per_cluster=per_cluster)
    return _epilogue(d, i)
