"""Neighbors: brute-force and ANN indexes (SURVEY.md §2.7).

Reference surface: ``raft/neighbors`` facade over ``spatial/knn/detail``:
brute-force k-NN, fused L2 k-NN, top-k selection (warpsort/radix), IVF-Flat,
IVF-PQ, ball cover, epsilon neighborhood.

TPU re-design highlights:
  * top-k: ``lax.top_k`` (exact) and ``lax.approx_min_k`` (the TPU-KNN
    paper's partial-reduce op, PAPERS.md) replace warp_sort/radix_topk.
  * brute-force: scan over DB tiles carrying a running top-k — the same
    no-materialize property as the reference's fused_l2_knn.
  * IVF indexes: lane-aligned padded list layouts replace the CUDA
    32-interleaved groups; list scans are dense MXU matmuls over buckets.
"""

from raft_tpu.neighbors.ann_types import IndexParams, SearchParams
from raft_tpu.neighbors.selection import select_k
from raft_tpu.neighbors.brute_force import knn, brute_force_knn, knn_merge_parts, fused_l2_knn, haversine_knn
from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors_l2sq
from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors import ivf_bq
from raft_tpu.neighbors import ball_cover
from raft_tpu.neighbors.refine import refine
from raft_tpu.neighbors import serialize
from raft_tpu.neighbors import processing
from raft_tpu.neighbors import host_memory
from raft_tpu.neighbors import plan
from raft_tpu.neighbors import tiered

__all__ = [
    "IndexParams", "SearchParams",
    "select_k", "knn", "brute_force_knn", "knn_merge_parts", "fused_l2_knn",
    "haversine_knn",
    "eps_neighbors_l2sq", "ivf_flat", "ivf_pq", "ivf_bq", "ball_cover",
    "refine",
    "serialize", "processing", "host_memory", "plan", "tiered",
]
