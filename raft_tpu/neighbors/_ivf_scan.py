"""List-major ("inverted") IVF fine scan, shared by IVF-Flat and IVF-PQ.

The probe-major scan (``ivf_flat._search_impl``) gathers each query's
p-th probed list per step: every (query, probe) pair re-reads its list's
rows from HBM, so a batch of ``nq`` queries × ``n_probes`` streams
``nq·n_probes·(n/n_lists)·dim`` bytes — 64× the index size at the
default 1024-query/64-probe operating point. The reference reduces the
equivalent waste by sorting the probe list by cluster so same-cluster
work shares the L2 (``ivf_pq_search.cuh:1058-1097``, cub radix sort by
label); the TPU-native version inverts the map outright:

  1. invert (query → probes) into (list → probing queries), a padded
     (n_lists, cap) table (static shape; ``cap`` ≥ the observed max is
     computed on host and bucketed to limit recompiles);
  2. scan lists in chunks: per chunk one dense MXU einsum scores each
     list against *all* queries probing it — each list's rows are read
     exactly once per batch;
  3. per-(list, query) top-k candidates are scattered back through the
     inverse map and merged per query with one final ``select_k``.

Worth it when the reuse factor ``nq·n_probes / n_lists`` is high; the
probe-major scan stays the right call for small/online batches (it only
touches probed lists). ``search()`` picks automatically.
"""

from __future__ import annotations

import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.precision import matmul_precision


def _round_cap(want: int, nq: int) -> int:
    """Shared inverted-table width bucketing: next power of two (so jit
    caches bucket instead of recompiling per batch), ≥ 8, ≤ nq."""
    cap = 8
    while cap < want:
        cap *= 2
    return min(cap, nq)


def probe_cap(probes, n_lists: int) -> int:
    """Smallest safe static width for the inverted table: the max number
    of queries probing any one list, bucketed by ``_round_cap``. The
    count+max runs as one program (``_counts_and_max``) — the measure
    path is a cold-compile site on the tunneled platform."""
    from raft_tpu.neighbors.ivf_flat import _counts_and_max
    _, m = _counts_and_max(probes.reshape(-1), n_lists)
    return _round_cap(int(jax.device_get(m)), probes.shape[0])


def _invert_probes(probes, n_lists: int, cap: int):
    """(nq, n_probes) → ``qmap`` (n_lists, cap) query ids (-1 pad) and
    ``inv_pos`` (nq, n_probes): each pair's slot within its list's row.

    Slots are assigned in PROBE-RANK priority order: within a list, pairs
    from low probe ranks (a query's most-promising probes) fill first, so
    when ``cap`` is smaller than a hot list's true probe count the
    overflow drops the *least*-promising (high-rank) probes. With the
    drop-free measured cap (``probe_cap``) the ordering is irrelevant;
    with a cached/static cap it bounds the recall cost of overflow.
    Dropped pairs keep ``inv_pos ≥ cap`` — mergers mask them out."""
    nq, n_probes = probes.shape
    flat_list = probes.reshape(-1)
    qid = jnp.broadcast_to(jnp.arange(nq, dtype=jnp.int32)[:, None],
                           (nq, n_probes)).reshape(-1)
    p_rank = jnp.broadcast_to(jnp.arange(n_probes, dtype=jnp.int32)[None],
                              (nq, n_probes)).reshape(-1)
    counts = jax.ops.segment_sum(jnp.ones(nq * n_probes, jnp.int32),
                                 flat_list, num_segments=n_lists)
    # composite key (list, probe rank); n_lists·n_probes stays well under
    # int32 (≤ n_lists² ≤ 2^34 only for n_lists > 2^17-class indexes —
    # far beyond the list counts this layout targets). Unstable sort:
    # equal keys are same-(list, rank) pairs from different queries,
    # and the drop policy only cares about rank classes — which query
    # within a rank class yields at overflow is arbitrary either way
    # (XLA's sort network is still deterministic for a given shape)
    order = jnp.argsort(flat_list * n_probes + p_rank, stable=False)
    sl = flat_list[order]
    starts = jnp.cumsum(jnp.concatenate([jnp.zeros(1, jnp.int32),
                                         counts]))[:-1]
    pos = jnp.arange(nq * n_probes, dtype=jnp.int32) - starts[sl]
    slot = jnp.where(pos < cap, sl * cap + pos, n_lists * cap)
    qmap = jnp.full((n_lists * cap,), -1, jnp.int32)
    qmap = qmap.at[slot].set(qid[order], mode="drop")
    inv_pos = jnp.zeros((nq * n_probes,), jnp.int32)
    inv_pos = inv_pos.at[order].set(pos)
    return qmap.reshape(n_lists, cap), inv_pos.reshape(nq, n_probes)


def largest_divisor_at_most(n: int, want: int) -> int:
    """Largest divisor of ``n`` that is ≤ ``want`` (≥ 1)."""
    c = 1
    for d in range(1, n + 1):
        if n % d == 0 and d <= want:
            c = d
    return c


def _chunk_size(n_lists: int, cap: int, max_list: int,
                budget_elems: int = 1 << 24) -> int:
    """Largest divisor of n_lists whose (chunk, cap, max_list) score
    block stays under ~``budget_elems`` f32 elements (64 MiB default)."""
    want = max(1, budget_elems // max(1, cap * max_list))
    return largest_divisor_at_most(n_lists, want)


def _score_block(qsub, data, norms, scale):
    """(chunk, cap, dim) queries × (chunk, max_list, dim) list rows →
    (chunk, cap, max_list) squared-L2, mirroring the dtype handling of
    ``ivf_flat._score_probe`` (bf16 on the MXU; int8 via folded scale)."""
    qq = jnp.sum(qsub * qsub, axis=2)
    if data.dtype == jnp.bfloat16:
        # one MXU pass on purpose: operands are already bf16
        ip = jnp.einsum("gcd,gld->gcl", qsub.astype(jnp.bfloat16), data,
                        preferred_element_type=jnp.float32,
                        precision=lax.Precision.DEFAULT)
    elif data.dtype == jnp.int8:
        ip = scale * jnp.einsum("gcd,gld->gcl", qsub,
                                data.astype(jnp.float32),
                                preferred_element_type=jnp.float32,
                                precision=matmul_precision())
    else:
        ip = jnp.einsum("gcd,gld->gcl", qsub, data,
                        preferred_element_type=jnp.float32,
                        precision=matmul_precision())
    return qq[:, :, None] + norms[:, None, :] - 2.0 * ip


def binned_partial_topk(d, lid, bins: int):
    """Binned (min, argmin) along the trailing list axis — the TPU-KNN
    partial top-k shared by the XLA-tier scans. Bins are STRIDED
    (column c → bin c % bins), matching the Pallas kernels: bucketized
    rows follow dataset order, so a query's true neighbors sit in
    ADJACENT columns — contiguous bins collide them (the kernel
    measured 0.87 vs 0.99+ recall on clustered data; the same ~5%
    recall cliff reproduced here on blobs when bins < list length).
    ``d`` (..., cap, ML) scores, ``lid`` (..., ML) global ids (−1 pad)
    → per-bin ``(min (..., cap, bins), min-id)``; of two hits in one
    bin only the nearer survives (ties: smallest id)."""
    *lead, cap, max_list = d.shape
    b = -(-max_list // bins)
    pad = bins * b - max_list
    dp = jnp.pad(d, [(0, 0)] * (d.ndim - 1) + [(0, pad)],
                 constant_values=jnp.inf)
    db_ = dp.reshape(*lead, cap, b, bins)
    cd = jnp.min(db_, axis=-2)
    col = jnp.pad(jnp.broadcast_to(lid[..., None, :], d.shape),
                  [(0, 0)] * (d.ndim - 1) + [(0, pad)],
                  constant_values=-1).reshape(*lead, cap, b, bins)
    big = jnp.iinfo(jnp.int32).max
    gl = jnp.min(jnp.where(db_ == cd[..., None, :], col, big), axis=-2)
    return cd, jnp.where(gl == big, -1, gl)


def merge_candidates(cand_d, cand_i, probes, inv_pos, k: int,
                     sqrt: bool, use_pallas_select: bool = False,
                     cap: Optional[int] = None):
    """Shared tail of both list-major scans: gather each (query, probe)
    pair's candidate row from the (n_lists, cap, kk) blocks and merge to
    the per-query top-k. ``-1`` candidate ids stay ``-1``. ``cap``, when
    given, masks pairs the inversion dropped (``inv_pos ≥ cap`` — a hot
    list overflowed a cached/static table width)."""
    nq = probes.shape[0]
    kept = None
    if cap is not None:
        kept = inv_pos < cap
        inv_pos = jnp.minimum(inv_pos, cap - 1)
    pd = cand_d[probes, inv_pos].reshape(nq, -1)
    pi = cand_i[probes, inv_pos].reshape(nq, -1)
    if kept is not None:
        kk = pd.shape[1] // probes.shape[1]
        keep_f = jnp.repeat(kept, kk, axis=1)
        pd = jnp.where(keep_f, pd, jnp.inf)
        pi = jnp.where(keep_f, pi, -1)
    pd = jnp.where(pi >= 0, pd, jnp.inf)
    if pd.shape[1] < k:  # fewer candidates than k: pad like the carry init
        short = k - pd.shape[1]
        pd = jnp.pad(pd, ((0, 0), (0, short)), constant_values=jnp.inf)
        pi = jnp.pad(pi, ((0, 0), (0, short)), constant_values=-1)
        use_pallas_select = False
    if use_pallas_select:
        from raft_tpu.ops.pallas_select_k import select_k_pallas
        d, sel = select_k_pallas(pd, k)
    else:
        nd, sel = lax.top_k(-pd, k)
        d = -nd
    ids = jnp.take_along_axis(pi, jnp.maximum(sel, 0), axis=1)
    ids = jnp.where(sel >= 0, ids, -1)
    if sqrt:
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    return d, ids


@functools.partial(jax.jit, static_argnames=("n_probes", "kind",
                                             "use_pallas"))
def coarse_probes(queries, centers, n_probes: int, kind: str = "l2",
                  use_pallas: bool = False):
    """Coarse phase (reference select_clusters, ivf_pq_search.cuh:127):
    query×centers GEMM + n_probes-selection. ``kind`` "ip" probes the
    largest-dot-product centers. With ``use_pallas`` the selection runs
    through the exact Pallas ``select_k`` kernel (the warpsort slot) —
    ``lax.top_k`` is a full variadic sort, tens of ms at
    (1000, 1024+)-wide score matrices (BASELINE.md select_k rows), and
    inside the fused single-dispatch search it would dominate the
    coarse phase."""
    from raft_tpu.distance.pairwise import _l2_expanded
    if kind == "ip":
        coarse = -jnp.matmul(queries, centers.T,
                             precision=matmul_precision())
    else:
        coarse = _l2_expanded(queries, centers, sqrt=False)
    if use_pallas and n_probes <= 256:
        from raft_tpu.ops.pallas_select_k import select_k_pallas
        return select_k_pallas(coarse, n_probes)[1]
    return lax.top_k(-coarse, n_probes)[1]


def count_coarse_fallback(n_probes: int, use_pallas: bool) -> None:
    """Telemetry for the coarse-selection cliff: ``coarse_probes`` with
    ``use_pallas=True`` but ``n_probes > 256`` silently falls back to
    the full ``lax.top_k`` variadic sort (the Pallas ``select_k``
    kernel's k ≤ 256 bound — tens of ms at serving widths, see
    docs/performance.md "The coarse n_probes cliff"). Host-side only:
    called once per search / plan build from the routing layers, never
    from inside a trace (a traced increment would count per COMPILE,
    not per call)."""
    if use_pallas and n_probes > 256:
        from raft_tpu import obs
        obs.counter("raft.ivf_scan.coarse.fallback").inc()


class ProbeStats:
    """Bounded host-side per-list probe-mass accumulator — the hotness
    signal the tiered placement policy (and any future multi-tenant
    router) reads. One ``np.bincount`` per batch over the
    already-materialized coarse output; never called from inside a
    trace (same host-side-only discipline as
    :func:`count_coarse_fallback`). Memory is bounded: when more than
    ``2 * bound`` lists are tracked, the tail below the top ``bound``
    by mass is dropped (probe mass is heavy-headed by construction —
    that tail is exactly the cold set)."""

    GUARDED_BY = ("_mass", "_batches", "_total")

    def __init__(self, bound: int = 4096):
        self._lock = threading.Lock()
        self._bound = max(1, int(bound))
        self._mass: dict = {}
        self._batches = 0
        self._total = 0

    def note(self, probes_np) -> None:
        """Fold one coarse output (any int array of list ids) in."""
        flat = np.asarray(probes_np).reshape(-1)
        if flat.size == 0:
            return
        counts = np.bincount(flat)
        nz = np.nonzero(counts)[0]
        with self._lock:
            self._batches += 1
            self._total += int(flat.size)
            for lid in nz:
                li = int(lid)
                self._mass[li] = self._mass.get(li, 0) + int(counts[li])
            if len(self._mass) > 2 * self._bound:
                keep = sorted(self._mass.items(),
                              key=lambda kv: (-kv[1], kv[0]))
                self._mass = dict(keep[:self._bound])

    def histogram(self, n: int = 16):
        """Top-``n`` ``(list_id, probe_mass)`` pairs, mass-descending
        (ties by list id for determinism)."""
        with self._lock:
            items = sorted(self._mass.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return items[:max(0, int(n))]

    def reset(self) -> None:
        with self._lock:
            self._mass = {}
            self._batches = 0
            self._total = 0


_GLOBAL_PROBE_STATS = ProbeStats()


def note_probes(probes_np, stats: Optional[ProbeStats] = None) -> None:
    """Export per-list probe mass from one coarse output, cheaply:
    ``raft.ivf_scan.probes.{batches,mass}`` counters plus the bounded
    top-N tracker behind :func:`probe_histogram`. Host-side only, like
    :func:`count_coarse_fallback` — call with the materialized probes,
    never under a trace."""
    from raft_tpu import obs
    flat = np.asarray(probes_np)
    obs.counter("raft.ivf_scan.probes.batches").inc()
    obs.counter("raft.ivf_scan.probes.mass").inc(int(flat.size))
    _GLOBAL_PROBE_STATS.note(flat)
    if stats is not None:
        stats.note(flat)


def probe_histogram(n: int = 16):
    """Top-``n`` hottest lists by cumulative probe mass, process-wide
    (the ``raft.ivf_scan.probes.*`` tracker)."""
    return _GLOBAL_PROBE_STATS.histogram(n)


@functools.partial(jax.jit,
                   static_argnames=("k", "cap", "chunk", "bins", "sqrt"))
def inverted_scan(queries, data, norms, ids, probes, k: int, cap: int,
                  chunk: int, scale=1.0, center_offset: Optional[jax.Array]
                  = None, bins: int = 0, sqrt: bool = False):
    """Score every (query, probed list) pair list-major and return the
    merged per-query top-k: (dists (nq, k), global ids (nq, k)).

    ``center_offset`` (n_lists, dim), when given, is subtracted from each
    list's probing queries before scoring — the IVF-PQ residual form
    (queries are pre-rotated; lists hold decoded rotated residuals).

    ``bins`` > 0 replaces the exact per-(list, query) top-k with a
    binned (min, argmin) over ``bins`` row-bins — the TPU-KNN partial
    top-k of the fused kNN kernel (``pallas_fused_knn.py``): of two true
    hits in one bin of one list only the nearer survives. Sort-based
    selection dominates the exact path's runtime; bins ≥ 2k makes the
    candidate pass a cheap VPU reduction at small recall cost.
    """
    nq = queries.shape[0]
    n_lists, max_list = ids.shape
    qmap, inv_pos = _invert_probes(probes, n_lists, cap)

    n_chunks = n_lists // chunk
    qmap_c = qmap.reshape(n_chunks, chunk, cap)
    data_c = data.reshape(n_chunks, chunk, max_list, -1)
    norms_c = norms.reshape(n_chunks, chunk, max_list)
    ids_c = ids.reshape(n_chunks, chunk, max_list)
    off_c = (None if center_offset is None
             else center_offset.reshape(n_chunks, chunk, -1))

    kk = min(k, max_list) if bins <= 0 else min(bins, max_list)

    def one_chunk(args):
        qm, dat, nrm, lid, off = args
        qsub = queries[jnp.clip(qm, 0, nq - 1)]          # (chunk, cap, dim)
        if off is not None:
            qsub = qsub - off[:, None, :]
        d = _score_block(qsub, dat, nrm, scale)
        d = jnp.where(lid[:, None, :] >= 0, jnp.maximum(d, 0.0), jnp.inf)
        if bins > 0 and kk < max_list:
            return binned_partial_topk(d, lid, kk)
        flat = d.reshape(chunk * cap, max_list)
        cd, csel = lax.top_k(-flat, kk)
        cd = -cd
        gl = jnp.take_along_axis(
            jnp.broadcast_to(lid[:, None, :], (chunk, cap, max_list))
            .reshape(chunk * cap, max_list), csel, axis=1)
        return (cd.reshape(chunk, cap, kk), gl.reshape(chunk, cap, kk))

    if off_c is None:
        cand_d, cand_i = lax.map(
            lambda a: one_chunk((*a, None)),
            (qmap_c, data_c, norms_c, ids_c))
    else:
        cand_d, cand_i = lax.map(
            one_chunk, (qmap_c, data_c, norms_c, ids_c, off_c))
    cand_d = cand_d.reshape(n_lists, cap, kk)
    cand_i = cand_i.reshape(n_lists, cap, kk)
    return merge_candidates(cand_d, cand_i, probes, inv_pos, k, sqrt,
                            cap=cap)


def resolve_cap(cache: Optional[dict], queries, centers, params,
                n_probes: int, n_lists: int, kind: str = "l2",
                use_pallas: bool = False) -> int:
    """Inverted-table width policy shared by IVF-Flat and IVF-PQ.

    ``params.probe_cap``: 0 (default) measures the drop-free cap once per
    (nq, n_probes) and caches it on the index — every later same-shape
    search is then a SINGLE dispatch (the measurement costs one extra
    device round-trip, which at ~tens of ms through the axon tunnel was
    the round-2 reason IVF trailed brute force); -1 re-measures every
    batch (guaranteed drop-free, the round-2 behavior); > 0 pins an
    explicit cap with no sync at all. A later batch that overflows a
    cached/pinned cap sheds its highest-rank probes only
    (``_invert_probes`` priority order) and the merge masks them.

    Measurement (the -1 mode, and the first 0-mode call per shape) runs
    the coarse phase once here and once again inside the fused search —
    the duplication keeps measured and cached searches byte-identical
    through one jit cache entry; -1 is the drop-free debug mode, not the
    serving path, so the extra coarse GEMM is accepted."""
    from raft_tpu import obs
    from raft_tpu.obs import spans
    pc = getattr(params, "probe_cap", 0)
    if pc > 0:
        cap = _round_cap(pc, queries.shape[0])
        spans.current_span().set_attrs(cap=cap, cap_mode="pinned")
        return cap
    # the tier is part of the key: a cap measured under one coarse
    # selection program must not serve the other (a tie resolved
    # differently could push a list past it — see below)
    key = (queries.shape[0], n_probes, use_pallas)
    if pc == 0 and cache is not None and key in cache:
        obs.counter("raft.ivf_scan.resolve_cap.cache_hits").inc()
        spans.current_span().set_attrs(cap=cache[key],
                                       cap_mode="cache_hit")
        return cache[key]
    # measure over the SAME coarse selection the serving search runs
    # (use_pallas must match) — a tie resolved differently between two
    # selection programs could otherwise push a list past the measured
    # cap and silently shed probes in the drop-free modes. The
    # measurement is a device round-trip (probe_cap's device_get) —
    # the serving-path fixed cost the plan layer's warmup() exists to
    # eliminate; the counter proves a warmed path never lands here.
    obs.counter("raft.ivf_scan.resolve_cap.syncs").inc()
    # the measurement is the request's one host round-trip — a child
    # span makes it visible in the per-request trace (and its absence
    # on a warm path equally so)
    with spans.span("raft.ivf_scan.resolve_cap",
                    nq=int(queries.shape[0]), n_probes=n_probes):
        probes = coarse_probes(queries, centers, n_probes, kind=kind,
                               use_pallas=use_pallas)
        cap = probe_cap(probes, n_lists)
    if pc == 0:
        # ceiling on the AUTO-measured width (drop-free -1 mode stays
        # unbounded): clustered query skew can double the drop-free cap
        # (512 observed at the 500k bench point, 2026-08-02), and a big
        # cap is wrong on BOTH axes — the list-major scan's work grows
        # ∝ cap (the overflow it sheds is the least-promising probe
        # ranks), and the Mosaic kernels' compile time explodes past
        # ~256 (two 300 s-budget parks burned a scarce TPU window).
        # Overridable per call via params.probe_cap, per process via
        # the env.
        import os
        cap_max = int(os.environ.get("RAFT_TPU_AUTO_CAP_MAX", "256"))
        if cap_max > 0:
            # round the ceiling DOWN to the cap bucketing grid — a
            # non-power-of-two env value must not round up past the
            # compile-explosion threshold it exists to guard
            floor = 8
            while floor * 2 <= cap_max:
                floor *= 2
            cap = min(cap, floor)
    if pc == 0 and cache is not None:
        cache[key] = cap
    spans.current_span().set_attrs(cap=cap, cap_mode="measured")
    return cap


def gather_mode() -> str:
    """Resolve the RAFT_TPU_GATHER strategy OUTSIDE jit so the A/B knob
    is a static argument of the fused searches, not an env read frozen
    into the first trace."""
    import os
    mode = os.environ.get("RAFT_TPU_GATHER", "rows")
    from raft_tpu.core.error import expects
    expects(mode in ("rows", "onehot"),
            "RAFT_TPU_GATHER=%s: want rows|onehot", mode)
    return mode


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "cap",
                                             "bins", "sqrt", "kind",
                                             "use_pallas", "gather",
                                             "internal_dtype", "lc",
                                             "fused"))
def fused_list_search(queries, centers, data, norms, ids, scale, *,
                      k: int, n_probes: int, cap: int, bins: int,
                      sqrt: bool, kind: str, use_pallas: bool,
                      gather: str = "rows", internal_dtype=None,
                      lc: int = 0, fused: bool = False):
    """Single-dispatch list-major IVF-Flat search: coarse probe GEMM +
    top-k, probe inversion, query gather, the list scan (Pallas kernel or
    XLA tier) and the candidate merge — ONE jitted computation. The
    reference's search is likewise one stream of kernels with no host
    round-trips (``ivf_flat_search.cuh:1057``); on the tunneled axon
    platform each avoided dispatch saves ~22 ms, which is why the fused
    form, not the kernel, was the round-3 QPS lever. ``lc`` (static):
    kernel lists-per-grid-cell, 0 = auto — resolved by callers via
    ``pallas_ivf_scan.lc_mode()`` outside jit so the cache keys on it.
    ``fused`` (static, ``pallas_ivf_scan.fused_mode()`` resolved by
    callers likewise): route the fine phase through the single-
    pallas_call scan+select kernel — the top-k state stays resident in
    VMEM and the scan → gather → select_k chain disappears (ISSUE 7)."""
    probes = coarse_probes(queries, centers, n_probes, kind=kind,
                           use_pallas=use_pallas)
    if use_pallas:
        from raft_tpu.ops.pallas_ivf_scan import ivf_list_scan_pallas
        return ivf_list_scan_pallas(queries, data, norms, ids, probes, k,
                                    cap, scale=scale, bins=bins,
                                    sqrt=sqrt, metric=kind,
                                    gather=gather,
                                    internal_dtype=internal_dtype,
                                    lc=lc, fused=fused)
    # XLA tier scores the l2 core only; search() gates routing
    chunk = _chunk_size(ids.shape[0], cap, ids.shape[1])
    return inverted_scan(queries, data, norms, ids, probes, k, cap,
                         chunk, scale, bins=bins, sqrt=sqrt)


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "cap",
                                             "bins", "sqrt"))
def fused_reconstruct_list_search(queries, centers, centers_rot, rot,
                                  decoded, decoded_norms, ids, *,
                                  k: int, n_probes: int, cap: int,
                                  bins: int, sqrt: bool):
    """Single-dispatch IVF-PQ reconstruct-cache list search (the XLA
    tier's analogue of ``fused_list_search``): coarse on the unrotated
    centers, query rotation, residual-form inverted scan, merge."""
    probes = coarse_probes(queries, centers, n_probes)
    q_rot = jnp.matmul(queries, rot.T, precision=matmul_precision())
    chunk = _chunk_size(ids.shape[0], cap, ids.shape[1])
    return inverted_scan(q_rot, decoded, decoded_norms, ids, probes, k,
                         cap, chunk, center_offset=centers_rot,
                         bins=bins, sqrt=sqrt)


def gather_query_rows(queries, qmap, mode: str = ""):
    """Build the per-list query blocks (n_lists, cap, dim) from the probe
    inversion table.

    Two strategies, switchable via ``RAFT_TPU_GATHER`` (A/B-able on
    hardware):

    * ``rows`` (default) — plain XLA row gather.
    * ``onehot`` — one-hot × queries on the MXU in list chunks, with a
      bf16x2 (hi + lo) split: rows are near-f32 (~2^-16 relative, the
      kernel tier's accuracy class), NOT bitwise-exact. XLA lowers big
      row gathers through the scalar core, which has repeatedly been the
      slow path on TPU (BASELINE.md: LUT-gather scans); this trades them
      for matmul FLOPs.
    """
    import os

    from raft_tpu.core.error import expects

    mode = mode or os.environ.get("RAFT_TPU_GATHER", "rows")
    expects(mode in ("rows", "onehot"),
            "RAFT_TPU_GATHER=%s: want rows|onehot", mode)
    # NOTE: jitted callers must resolve the mode via gather_mode() and
    # pass it explicitly — an env read here would freeze into the trace
    nq = queries.shape[0]
    safe = jnp.clip(qmap, 0, nq - 1)
    if mode != "onehot":
        return queries[safe]

    n_lists, cap = qmap.shape
    # chunk so the (chunk, cap, nq) one-hot stays modest
    chunk = largest_divisor_at_most(
        n_lists, max(1, (64 << 20) // max(1, cap * nq * 2)))

    qh = queries.astype(jnp.bfloat16)
    ql = (queries - qh.astype(jnp.float32)).astype(jnp.bfloat16)

    def one_chunk(idx_c):
        oh = jax.nn.one_hot(idx_c, nq, dtype=jnp.bfloat16)  # (c, cap, nq)
        hi = jnp.einsum("lcq,qd->lcd", oh, qh,
                        preferred_element_type=jnp.float32,
                        precision=lax.Precision.DEFAULT)
        lo = jnp.einsum("lcq,qd->lcd", oh, ql,
                        preferred_element_type=jnp.float32,
                        precision=lax.Precision.DEFAULT)
        return hi + lo

    out = jax.lax.map(one_chunk, safe.reshape(-1, chunk, cap))
    return out.reshape(n_lists, cap, queries.shape[1])
