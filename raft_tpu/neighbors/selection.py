"""k-selection (top-k smallest/largest).

Reference: ``spatial/knn/detail/topk.cuh:65-83`` dispatches k≤256 to
warp-sort (``topk/warpsort_topk.cuh``) and larger k to multi-pass radix
(``topk/radix_topk.cuh``). Neither maps to TPU (no warp shuffles, no
atomics); the TPU-native selection kernels are:

  * ``lax.top_k`` — exact, XLA's sorting-network selection; and
  * ``lax.approx_min_k``/``approx_max_k`` — the TPU-KNN partial-reduce
    operator (PAPERS.md: "TPU-KNN: K Nearest Neighbor Search at Peak
    FLOP/s") with tunable ``recall_target``, fused with its producer.

``select_k`` mirrors the reference dispatch with ``mode``:
"exact" | "approx" — default exact for parity; ANN searches pass approx
with a recall target, recovering the reference's perf-over-exactness
tradeoff in TPU terms.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.mdarray import as_array


def select_k(
    values,
    k: int,
    select_min: bool = True,
    input_indices=None,
    mode: str = "exact",
    recall_target: float = 0.95,
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row k smallest (or largest) values with their indices.

    values: (n_rows, n_cols); returns (dists (n_rows, k), ids (n_rows, k)
    int32). ``input_indices`` optionally maps local columns to global ids
    (the role of translations in the reference's select_k,
    ``spatial/knn/knn.cuh:125``).
    """
    v = as_array(values)
    if mode == "approx":
        if select_min:
            d, i = lax.approx_min_k(v, k, recall_target=recall_target)
        else:
            d, i = lax.approx_max_k(v, k, recall_target=recall_target)
    else:
        if select_min:
            d, i = lax.top_k(-v, k)
            d = -d
        else:
            d, i = lax.top_k(v, k)
    i = i.astype(jnp.int32)
    if input_indices is not None:
        idx = as_array(input_indices).astype(jnp.int32)
        i = jnp.take_along_axis(
            jnp.broadcast_to(idx, (v.shape[0], idx.shape[-1])), i, axis=1)
    return d, i
