"""k-selection (top-k smallest/largest).

Reference: ``spatial/knn/detail/topk.cuh:65-83`` dispatches k≤256 to
warp-sort (``topk/warpsort_topk.cuh``) and larger k to multi-pass radix
(``topk/radix_topk.cuh``). Neither maps to TPU (no warp shuffles, no
atomics); the TPU-native selection kernels are:

  * ``lax.top_k`` — exact, XLA's sorting-network selection; and
  * ``lax.approx_min_k``/``approx_max_k`` — the TPU-KNN partial-reduce
    operator (PAPERS.md: "TPU-KNN: K Nearest Neighbor Search at Peak
    FLOP/s") with tunable ``recall_target``, fused with its producer.

``select_k`` mirrors the reference dispatch with ``mode``:
"exact" | "approx" — default exact for parity; ANN searches pass approx
with a recall target, recovering the reference's perf-over-exactness
tradeoff in TPU terms.

Exact selection at k ≤ 256 routes to the Pallas merge kernel
(``ops/pallas_select_k.py`` — the warpsort slot: running sorted state +
filtered exact merges, ~70× the XLA sort at 1000×4096 k=32); k > 256
falls back to ``lax.top_k`` (the radix slot).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.mdarray import as_array


def _use_kernel(v, k: int) -> bool:
    """The k≤256 warpsort-slot dispatch (reference topk.cuh:65-83):
    Pallas exact-merge kernel for dense 2-D float inputs; radix-slot
    ``lax.top_k`` otherwise."""
    from raft_tpu.ops.dispatch import pallas_enabled
    # f64 stays on lax.top_k: the kernel computes (and returns) f32,
    # which would silently change select_k's dtype and tie ordering
    return (k <= 256 and v.ndim == 2 and v.shape[1] >= 2 * k
            and v.dtype in (jnp.float32, jnp.bfloat16, jnp.float16)
            and pallas_enabled())


def select_k(
    values,
    k: int,
    select_min: bool = True,
    input_indices=None,
    mode: str = "exact",
    recall_target: float = 0.95,
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row k smallest (or largest) values with their indices.

    values: (n_rows, n_cols); returns (dists (n_rows, k), ids (n_rows, k)
    int32). ``input_indices`` optionally maps local columns to global ids
    (the role of translations in the reference's select_k,
    ``spatial/knn/knn.cuh:125``).
    """
    v = as_array(values)
    if mode == "approx":
        if select_min:
            d, i = lax.approx_min_k(v, k, recall_target=recall_target)
        else:
            d, i = lax.approx_max_k(v, k, recall_target=recall_target)
    elif _use_kernel(v, k):
        from raft_tpu.ops.pallas_select_k import select_k_pallas
        d, i = select_k_pallas(v, k, select_min=select_min)
    else:
        if select_min:
            d, i = lax.top_k(-v, k)
            d = -d
        else:
            d, i = lax.top_k(v, k)
    i = i.astype(jnp.int32)
    if input_indices is not None:
        idx = as_array(input_indices).astype(jnp.int32)
        # kernel-path rows with < k finite candidates carry -1 sentinels;
        # keep them -1 instead of letting the gather clamp to column 0
        mapped = jnp.take_along_axis(
            jnp.broadcast_to(idx, (v.shape[0], idx.shape[-1])),
            jnp.maximum(i, 0), axis=1)
        i = jnp.where(i >= 0, mapped, -1)
    return d, i
