"""Brute-force k-NN.

Reference: ``raft/neighbors/brute_force.cuh:48,102,134`` (``knn_merge_parts``,
``knn``, ``fused_l2_knn``) over ``spatial/knn/detail/knn_brute_force_faiss.cuh``
(FAISS bfKnn per tile + heap merge) and ``fused_l2_knn.cuh`` (single-kernel
L2 top-k that never materializes the distance matrix).

TPU design: one formulation covers both — a ``lax.scan`` over database
tiles, each step computing an (n_queries, tile) distance block on the MXU
and merging it into a carried (n_queries, k) running top-k. Peak memory is
n_queries × (tile + k), independent of database size; XLA keeps the merge
in VMEM. Metrics needing preprocessing (cosine/correlation) follow the
reference's row-normalization trick (``spatial/knn/detail/processing.hpp``).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import _pairwise
from raft_tpu.neighbors.selection import select_k

_TILE_ELEMS = 1 << 22  # per-tile f32 budget for the (n_queries, tile) block


def _db_tile(n_queries: int, n_db: int) -> int:
    t = max(128, min(n_db, _TILE_ELEMS // max(1, n_queries)))
    if t >= 128:
        t -= t % 128
    return min(t, n_db)


@functools.partial(jax.jit,
                   static_argnames=("k", "metric", "metric_arg", "tile",
                                    "select_min"))
def _knn_scan(queries, db, k: int, metric: DistanceType, metric_arg: float,
              tile: int, select_min: bool = True):
    nq = queries.shape[0]
    n = db.shape[0]
    pad = (-n) % tile
    dbp = jnp.pad(db, ((0, pad), (0, 0))) if pad else db
    n_tiles = (n + pad) // tile
    db_tiles = dbp.reshape(n_tiles, tile, -1)
    offs = jnp.arange(n_tiles, dtype=jnp.int32) * tile
    sign = 1.0 if select_min else -1.0

    def step(carry, inp):
        best_d, best_i = carry  # (nq, k) each
        dtile, off = inp
        d = sign * _pairwise(queries, dtile, metric, metric_arg)  # (nq, tile)
        col = jnp.arange(tile, dtype=jnp.int32)[None, :] + off
        d = jnp.where(col < n, d, jnp.inf)
        # two-phase: per-tile top-k first (wide select), then a narrow 2k
        # merge with the carry — keeps every sort small (the same split as
        # the reference's per-tile WarpSelect + merge pass)
        td, tsel = lax.top_k(-d, min(k, tile))
        ti = jnp.take_along_axis(jnp.broadcast_to(col, (nq, tile)), tsel, axis=1)
        cat_d = jnp.concatenate([best_d, -td], axis=1)
        cat_i = jnp.concatenate([best_i, ti], axis=1)
        nd, sel = lax.top_k(-cat_d, k)
        ni = jnp.take_along_axis(cat_i, sel, axis=1)
        return (-nd, ni), None

    init = (jnp.full((nq, k), jnp.inf, dtype=jnp.float32),
            jnp.full((nq, k), -1, dtype=jnp.int32))
    (d, i), _ = lax.scan(step, init, (db_tiles, offs))
    return sign * d, i


# Only expanded-form L2 (what the fused kernel computes) and IP route to
# Pallas; unexpanded L2 is excluded on purpose — a caller choosing it is
# asking for the cancellation-free formulation, which the fused kernel
# does not provide.
_PALLAS_METRICS = {
    DistanceType.L2Expanded: ("l2", False),
    DistanceType.L2SqrtExpanded: ("l2", True),
    DistanceType.InnerProduct: ("ip", False),
}


def brute_force_knn(
    db,
    queries,
    k: int,
    metric: DistanceType = DistanceType.L2SqrtExpanded,
    metric_arg: float = 2.0,
    mode: str = "auto",
    kernel_precision: str | None = None,
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN of ``queries`` against ``db`` → (dists, indices), both
    (n_queries, k). Any :class:`DistanceType` (larger-is-better metrics
    like plain InnerProduct select max via distance negation, matching the
    reference's treatment of IP in FAISS).

    ``mode``: ``"auto"``/``"exact"`` run the exact XLA scan; ``"fused"``
    routes to the Pallas fused kernel (L2/IP only, binned partial top-k —
    the TPU-KNN recall/throughput tradeoff, near-exact at default bin
    width). The fused kernel is the TPU analogue of the reference's
    k ≤ 64 fusedL2Knn fast path (``knn_brute_force_faiss.cuh:281``); it
    is opt-in here because its selection is approximate.
    ``kernel_precision`` (fused path only): ``None`` = env default
    (bf16x3, ~f32-exact) | ``"bf16"`` = single-pass MXU speed tier
    (~5e-4 relative; recall-gate it) | ``"bf16x3"`` | ``"highest"``."""
    db, queries = as_array(db), as_array(queries)
    expects(db.shape[1] == queries.shape[1], "knn: dim mismatch")
    expects(k <= db.shape[0], "knn: k > database size")
    expects(mode in ("auto", "exact", "fused"),
            f"knn: unknown mode {mode!r} (auto|exact|fused)")
    metric = DistanceType(metric)
    pal = _PALLAS_METRICS.get(metric)
    if mode == "fused":
        if metric in (DistanceType.CosineExpanded,
                      DistanceType.CorrelationExpanded):
            # row-normalize (+ center) → IP kernel → 1 - sim, the
            # reference's preprocessing route (processing.hpp)
            from raft_tpu.neighbors.processing import fused_knn_preprocessed
            return fused_knn_preprocessed(db, queries, k, metric)
        expects(pal is not None,
                f"fused knn supports L2/IP/cosine/correlation, got {metric}")
        from raft_tpu.ops.pallas_fused_knn import fused_knn_pallas
        m_name, sq = pal
        return fused_knn_pallas(queries, db, k, metric=m_name, sqrt=sq,
                                kernel_precision=kernel_precision)
    tile = _db_tile(queries.shape[0], db.shape[0])
    # InnerProduct is a similarity: select the k LARGEST (the reference
    # routes IP through FAISS's max-heap select)
    select_min = metric != DistanceType.InnerProduct
    return _knn_scan(queries, db, k, metric, metric_arg, tile,
                     select_min=select_min)


def knn(
    index: Sequence,
    search,
    k: int,
    metric: DistanceType = DistanceType.L2SqrtExpanded,
    metric_arg: float = 2.0,
    translations: Optional[Sequence[int]] = None,
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """Multi-part brute-force k-NN (reference ``neighbors/brute_force.cuh:102``):
    ``index`` is a list of database parts; per-part results are merged and
    indices translated by part offsets (or explicit ``translations``)."""
    if not isinstance(index, (list, tuple)):
        index = [index]
    parts_d, parts_i = [], []
    offset = 0
    for p_idx, part in enumerate(index):
        part = as_array(part)
        d, i = brute_force_knn(part, search, min(k, part.shape[0]),
                               metric, metric_arg, res=res)
        base = translations[p_idx] if translations is not None else offset
        parts_d.append(d)
        parts_i.append(i + jnp.int32(base))
        offset += part.shape[0]
    if len(parts_d) == 1:
        return parts_d[0], parts_i[0]
    return knn_merge_parts(parts_d, parts_i, k,
                           select_min=metric != DistanceType.InnerProduct)


def knn_merge_parts(part_dists, part_indices, k: int, select_min: bool = True,
                    res=None) -> Tuple[jax.Array, jax.Array]:
    """Merge per-part top-k lists into a global top-k (reference
    ``knn_merge_parts``, brute_force.cuh:48 — BlockSelect heap merge; here
    one concat + ``select_k``, whose Pallas merge kernel is the
    BlockSelect analogue)."""
    from raft_tpu.neighbors.selection import select_k
    d = jnp.concatenate([as_array(x) for x in part_dists], axis=1)
    i = jnp.concatenate([as_array(x) for x in part_indices], axis=1)
    vals, sel = select_k(d, k, select_min=select_min)
    # kernel-path -1 sentinels (rows with < k finite candidates) must
    # stay -1, not clamp-gather part 0's first id
    out_i = jnp.take_along_axis(i, jnp.maximum(sel, 0), axis=1)
    return vals, jnp.where(sel >= 0, out_i, -1)


def haversine_knn(db, queries, k: int, res=None
                  ) -> Tuple[jax.Array, jax.Array]:
    """k-NN under the haversine great-circle metric over (lat, lon)
    radian pairs (reference ``spatial/knn/detail/haversine_distance.cuh``
    — a bespoke brute-force kernel there; here the generic scan with the
    haversine core)."""
    return brute_force_knn(db, queries, k, DistanceType.Haversine,
                           res=res)


def fused_l2_knn(db, queries, k: int, sqrt: bool = False, res=None
                 ) -> Tuple[jax.Array, jax.Array]:
    """L2 k-NN without materializing distances (reference
    ``spatial/knn/detail/fused_l2_knn.cuh:947``). The scan formulation IS
    fused on TPU; this entry point fixes the metric and exposes the
    sqrt toggle of the reference's L2 exp/unexp variants."""
    metric = (DistanceType.L2SqrtExpanded if sqrt else DistanceType.L2Expanded)
    return brute_force_knn(db, queries, k, metric, res=res)
