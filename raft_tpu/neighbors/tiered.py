"""Tiered HBM/host-RAM IVF serving: hot lists pinned on device, cold
lists prefetched under the hot-tier scan.

Today an index must be fully device-resident to serve, so HBM — not
the corpus — caps servable rows per chip (ROADMAP item 3).
``host_memory`` already serves past HBM but pays the full probe
working set in transfers every batch. This module splits the
difference with a two-tier layout:

* **hot tier** — the highest-probe-mass lists live in a fixed-capacity
  device table (``(hot_cap + 1, max_list, ...)``; the extra slot is a
  permanent zeros/-1 pad target). Hotness is an EMA over per-list
  probe mass (the ``_ivf_scan.ProbeStats`` export); promotion /
  demotion happens ONLY at :meth:`TieredIndex.refresh` boundaries,
  under an explicit HBM byte budget derived from the profiler's
  ``headroom_frac`` guardrail (or set explicitly). Capacity moves
  along the pre-warmed ``hot_capacities`` pow2 ladder, so a budget
  drop swaps to a smaller compiled shape instead of recompiling — and
  the placement policy never allocates a table the budget cannot
  hold, so no OOM path is reachable from it.
* **cold tier** — everything else stays in host RAM in the
  ``HostIvfFlat`` transfer-ready padded layout, staged per batch into
  pre-allocated fixed-shape rungs (``stage_capacities``, pow2 over
  the unique cold-list count) and ``device_put`` **while the hot-tier
  scan is already in flight** — the transfer window hides under
  device compute (async dispatch), measured by
  ``raft.tiered.overlap.*``.

Search = coarse (centers always resident) → partition probes by tier
→ enqueue hot scan → stage + ``device_put`` cold payload → pre-warmed
cold scan → device top-k merge. Both tiers run the shared
``ivf_flat._fine_phase`` over the same row values, so the merged
top-k is bit-identical to the fully-resident probe-order search at
the same ``(nq, k, n_probes)`` point.

Metrics (``raft.tiered.*``): ``probes.{hot,cold}`` tier hit/miss,
``hit_rate``, ``fetch.{bytes,seconds}``, ``overlap.{seconds,frac}``,
``{promotions,demotions}.total``, ``refresh.total``, ``search.total``,
``budget.bytes``, ``hot.{lists,bytes}``.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import obs
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors._ivf_scan import ProbeStats, note_probes
from raft_tpu.neighbors.host_memory import HostIvfFlat, _probe_scan, to_host
from raft_tpu.neighbors.ivf_flat import (
    Index,
    SearchParams,
    _coarse_scores,
    _metric_kind,
    _postprocess,
)
from raft_tpu.obs import profiler, spans

__all__ = ["TieredConfig", "TieredIndex", "TieredPlan", "build_plan",
           "build_ladder", "from_index", "from_host"]

# Compile-surface rung declarations (graftlint GL012–GL014): the
# tiered tier's key dimensions. ``hot_cap`` and ``stage_cap`` are the
# GRIDs — capacity moves between pre-warmed pow2 rungs (the mutate
# delta-ladder trick), never recompiles; ``_prewarm`` is the GL013
# warm loop over both.
COMPILE_SURFACE_RUNGS = {
    "nq": ("shapes", (1, 8, 32, 128),
           "serving batch shapes — same ladder grid as serve/ladder.py"),
    "n_probes": ("rungs", None,
                 "the n_probes degradation ladder — config-supplied; "
                 "lower rungs probe (and therefore fetch) less"),
    "hot_cap": ("hot_capacities", None,
                "pow2 hot-table capacity ladder — demotion under a "
                "budget drop swaps DOWN between pre-warmed rungs"),
    "stage_cap": ("stage_capacities", None,
                  "pow2 cold staging rung ladder — the per-batch "
                  "unique cold-list count buckets up to a rung"),
    "k": ("k", None, "result depth — fixed per plan at construction"),
}

_SQRT_METRICS = (DistanceType.L2SqrtExpanded,
                 DistanceType.L2SqrtUnexpanded)


def _pow2_ladder(top: int, lo: int = 8) -> Tuple[int, ...]:
    """Ascending pow2 rungs covering ``(0, top]``: ``lo, 2·lo, …`` plus
    the pow2 ceiling of ``top`` itself."""
    top = max(1, int(top))
    cap = 1 << max(top - 1, 0).bit_length()    # pow2 ceiling
    rungs = []
    c = min(lo, cap)
    while c < cap:
        rungs.append(c)
        c *= 2
    rungs.append(cap)
    return tuple(rungs)


@functools.partial(jax.jit, static_argnames=("n_probes", "kind"))
def _coarse_topk(queries, centers, n_probes: int, kind: str):
    """Coarse phase on the always-resident centers → (nq, n_probes)
    probed list ids."""
    return lax.top_k(-_coarse_scores(queries, centers, kind),
                     n_probes)[1]


@functools.partial(jax.jit, static_argnames=("k",))
def _merge_topk(d_a, i_a, d_b, i_b, k: int):
    """Fold two per-tier (nq, k) candidate sets into one — the same
    concat + ``lax.top_k`` merge step ``_fine_phase`` runs per probe
    rank, so the merged set equals the single-scan result."""
    cat_d = jnp.concatenate([d_a, d_b], axis=1)
    cat_i = jnp.concatenate([i_a, i_b], axis=1)
    nd, sel = lax.top_k(-cat_d, k)
    return -nd, jnp.take_along_axis(cat_i, sel, axis=1)


@dataclasses.dataclass(frozen=True)
class TieredConfig:
    """Placement policy knobs.

    Exactly one budget source applies, in precedence order:
    ``budget_bytes`` (explicit), ``hot_frac`` (that fraction of the
    total list payload), or the live HBM headroom signal —
    ``max(0, bytes_limit · (1 - headroom_frac) - bytes_in_use)`` from
    :func:`raft_tpu.core.memory.hbm_stats`, i.e. pin as much as fits
    while keeping the PR 14 ``/healthz`` guardrail fraction free."""

    budget_bytes: Optional[int] = None
    hot_frac: Optional[float] = None
    headroom_frac: Optional[float] = None
    ema_decay: float = 0.8
    # staging rung ceiling: one batch's unique cold lists above this
    # are staged in multiple chunks (bounds transient device bytes)
    max_stage_lists: int = 1024


class TieredIndex:
    """Two-tier IVF-Flat index: device-pinned hot lists + host-RAM
    cold lists behind fixed-shape staging rungs. Build via
    :func:`from_index` / :func:`from_host`, serve via
    :func:`build_plan` (or drop it straight into
    ``SearchServer.from_index`` / ``PlanLadder.build``)."""

    # graftlint GL003: the placement / prefetcher state — every field
    # is swapped or read under ``_lock`` (search takes an immutable
    # snapshot; refresh replaces wholesale)
    GUARDED_BY = ("_hot_slot", "_hot_ids", "_hot_cap", "_hot_tables",
                  "_mass", "_ema", "_stage", "_budget_bytes",
                  "_cum_probes", "_cum_hot", "_cum_fetch_s",
                  "_cum_overlap_s")

    def __init__(self, host: HostIvfFlat,
                 config: Optional[TieredConfig] = None):
        self.cfg = config if config is not None else TieredConfig()
        self.centers = host.centers
        self.lists_data = host.lists_data
        self.lists_norms = host.lists_norms
        self.lists_indices = host.lists_indices
        self.metric = host.metric
        self.size = int(host.size)
        self.scale = float(host.scale)
        self.plan_cache: Dict[tuple, "TieredPlan"] = {}
        self.probe_stats = ProbeStats()
        # per-list payload bytes in the padded layout (the unit of
        # both the budget math and the fetch accounting)
        self.bytes_per_list = int(self.lists_data[0].nbytes
                                  + self.lists_norms[0].nbytes
                                  + self.lists_indices[0].nbytes)
        self.hot_capacities = _pow2_ladder(self.n_lists)
        self.stage_capacities = _pow2_ladder(
            min(self.n_lists, max(1, int(self.cfg.max_stage_lists))))
        self._lock = threading.Lock()
        self._hot_slot = np.full(self.n_lists, -1, np.int32)
        self._hot_ids = np.zeros(0, np.int64)
        self._hot_cap = 0
        self._hot_tables = None      # (data, norms, ids) device arrays
        self._mass = np.zeros(self.n_lists, np.float64)
        self._ema = np.zeros(self.n_lists, np.float64)
        self._stage: Dict[int, dict] = {}
        self._budget_bytes = 0
        self._cum_probes = 0
        self._cum_hot = 0
        self._cum_fetch_s = 0.0
        self._cum_overlap_s = 0.0
        # the highest capacity rung plans will pre-warm — later budget
        # RAISES clamp here (an unwarmed promotion would compile in
        # steady state); drops swap down the warmed ladder
        self._warm_top = self._rung_for(self._derive_budget(None))
        self.refresh()

    # -- geometry ----------------------------------------------------------
    @property
    def n_lists(self) -> int:
        return int(self.centers.shape[0])

    @property
    def dim(self) -> int:
        return int(self.centers.shape[1])

    @property
    def max_list(self) -> int:
        return int(self.lists_data.shape[1])

    @property
    def hot_lists(self) -> int:
        with self._lock:
            return int(len(self._hot_ids))

    @property
    def budget_bytes(self) -> int:
        with self._lock:
            return int(self._budget_bytes)

    def table_bytes(self, cap: int) -> int:
        """Device bytes of a hot table at capacity rung ``cap`` (the
        +1 is the permanent pad slot)."""
        return (int(cap) + 1) * self.bytes_per_list if cap else 0

    # -- placement policy --------------------------------------------------
    def _derive_budget(self, budget_bytes: Optional[int]) -> int:
        if budget_bytes is not None:
            return max(0, int(budget_bytes))
        if self.cfg.budget_bytes is not None:
            return max(0, int(self.cfg.budget_bytes))
        total = self.n_lists * self.bytes_per_list
        if self.cfg.hot_frac is not None:
            return max(0, int(float(self.cfg.hot_frac) * total))
        from raft_tpu.core.memory import hbm_stats
        stats = hbm_stats(self.centers.devices().pop()
                          if hasattr(self.centers, "devices")
                          else None)
        frac = (self.cfg.headroom_frac
                if self.cfg.headroom_frac is not None
                else profiler.ProfilerConfig().hbm_headroom_frac)
        free = (stats["bytes_limit"] * (1.0 - float(frac))
                - stats["bytes_in_use"])
        return max(0, min(int(free), total))

    def _rung_for(self, budget: int) -> int:
        """Largest capacity rung whose pinned payload fits ``budget``
        (0 = no hot tier). The permanent pad slot (one list of zeros)
        rides as fixed overhead rather than against the budget — so
        ``hot_frac=1.0`` pins the whole index. This is the no-OOM
        invariant: the policy only ever allocates
        ``rung * bytes_per_list`` budgeted bytes."""
        rung = 0
        for cap in self.hot_capacities:
            if cap * self.bytes_per_list <= budget:
                rung = cap
        return rung

    def refresh(self, budget_bytes: Optional[int] = None) -> dict:
        """Re-score hotness (EMA over the probe mass since the last
        refresh) and promote/demote under the byte budget. Returns a
        summary dict; increments ``raft.tiered.{promotions,demotions}
        .total``. Capacity only moves along the pre-warmed rung
        ladder, so a refresh never compiles."""
        with self._lock:
            decay = float(self.cfg.ema_decay)
            self._ema = decay * self._ema + (1.0 - decay) * self._mass
            self._mass[:] = 0.0
            budget = self._derive_budget(budget_bytes)
            rung = min(self._rung_for(budget), self._warm_top)
            n_pin = min(rung, self.n_lists)
            # stable mass-descending order → deterministic placement
            order = np.argsort(-self._ema, kind="stable")
            new_ids = np.sort(order[:n_pin].astype(np.int64))
            old = set(int(i) for i in self._hot_ids)
            new = set(int(i) for i in new_ids)
            promoted = len(new - old)
            demoted = len(old - new)
            if rung != self._hot_cap or promoted or demoted:
                self._install_hot_locked(rung, new_ids)
            self._budget_bytes = budget
        obs.counter("raft.tiered.refresh.total").inc()
        if promoted:
            obs.counter("raft.tiered.promotions.total").inc(promoted)
        if demoted:
            obs.counter("raft.tiered.demotions.total").inc(demoted)
        obs.gauge("raft.tiered.budget.bytes").set(float(budget))
        obs.gauge("raft.tiered.hot.lists").set(float(n_pin))
        obs.gauge("raft.tiered.hot.bytes").set(
            float(self.table_bytes(rung)))
        return {"budget_bytes": budget, "hot_cap": rung,
                "hot_lists": n_pin, "promoted": promoted,
                "demoted": demoted}

    def _install_hot_locked(self, rung: int, new_ids) -> None:
        """Swap the device hot table to ``rung`` holding ``new_ids``
        (sorted). Caller holds the lock."""
        if rung == 0:
            self._hot_tables = None
            self._hot_ids = np.zeros(0, np.int64)
            self._hot_slot = np.full(self.n_lists, -1, np.int32)
            self._hot_cap = 0
            return
        n = len(new_ids)
        data = np.zeros((rung + 1,) + self.lists_data.shape[1:],
                        self.lists_data.dtype)
        norms = np.zeros((rung + 1,) + self.lists_norms.shape[1:],
                         self.lists_norms.dtype)
        ids = np.full((rung + 1,) + self.lists_indices.shape[1:], -1,
                      self.lists_indices.dtype)
        np.take(self.lists_data, new_ids, axis=0, out=data[:n])
        np.take(self.lists_norms, new_ids, axis=0, out=norms[:n])
        np.take(self.lists_indices, new_ids, axis=0, out=ids[:n])
        self._hot_tables = (jnp.asarray(data), jnp.asarray(norms),
                            jnp.asarray(ids))
        slot = np.full(self.n_lists, -1, np.int32)
        slot[new_ids] = np.arange(n, dtype=np.int32)
        self._hot_slot = slot
        self._hot_ids = np.asarray(new_ids, np.int64)
        self._hot_cap = int(rung)

    # -- staging -----------------------------------------------------------
    def _stage_rung(self, want: int) -> int:
        for cap in self.stage_capacities:
            if want <= cap:
                return cap
        return self.stage_capacities[-1]

    def _stage_acquire(self, rung: int):
        """Check the pooled staging buffers for ``rung`` out (or
        allocate a transient set when another search holds them).
        Returns ``(bufs, guard)`` — block on ``guard`` before refilling
        (the previous batch's transfer may still read the buffer)."""
        with self._lock:
            entry = self._stage.pop(rung, None)
        if entry is not None:
            return entry["bufs"], entry["guard"]
        data = np.zeros((rung + 1,) + self.lists_data.shape[1:],
                        self.lists_data.dtype)
        norms = np.zeros((rung + 1,) + self.lists_norms.shape[1:],
                         self.lists_norms.dtype)
        ids = np.full((rung + 1,) + self.lists_indices.shape[1:], -1,
                      self.lists_indices.dtype)
        return (data, norms, ids), None

    def _stage_release(self, rung: int, bufs, guard) -> None:
        with self._lock:
            if rung not in self._stage:
                self._stage[rung] = {"bufs": bufs, "guard": guard}

    # -- search ------------------------------------------------------------
    def _tier_search(self, q, k: int, n_probes: int
                     ) -> Tuple[jax.Array, jax.Array]:
        """The prepared two-tier search at one (nq, k, n_probes)
        point. All compiled shapes were pre-warmed by the owning
        plan's build, so this path never traces in steady state."""
        kind = _metric_kind(self.metric)
        sqrt = self.metric in _SQRT_METRICS
        if self.metric == DistanceType.CosineExpanded:
            q = q / jnp.maximum(
                jnp.linalg.norm(q, axis=1, keepdims=True), 1e-30)
        scale = jnp.float32(self.scale)
        probes = _coarse_topk(q, self.centers, n_probes, kind)
        probes_np = np.asarray(probes)      # the one mid-search sync
        note_probes(probes_np, stats=self.probe_stats)
        with self._lock:
            hot_slot = self._hot_slot
            hot_cap = self._hot_cap
            hot_tables = self._hot_tables
            np.add.at(self._mass, probes_np.reshape(-1), 1.0)
        pos_hot = hot_slot[probes_np]                  # (nq, n_probes)
        hot_mask = pos_hot >= 0
        n_hot = int(hot_mask.sum())
        n_total = int(probes_np.size)

        parts = []
        t_enq = time.perf_counter()
        if hot_tables is not None and n_hot:
            ph = np.where(hot_mask, pos_hot, hot_cap).astype(np.int32)
            parts.append(_probe_scan(
                q, hot_tables[0], hot_tables[1], hot_tables[2],
                jnp.asarray(ph), scale, k=k, sqrt=sqrt, kind=kind))

        fetch_s = 0.0
        fetch_bytes = 0
        ucold = np.unique(probes_np[~hot_mask]) if n_hot < n_total \
            else np.zeros(0, np.int64)
        # stage cold lists in rung-sized chunks, each device_put
        # issued while the hot scan is (asynchronously) in flight
        off = 0
        while off < len(ucold):
            chunk = ucold[off:off + self.stage_capacities[-1]]
            off += len(chunk)
            stage_cap = self._stage_rung(len(chunk))
            bufs, guard = self._stage_acquire(stage_cap)
            if guard is not None:
                jax.block_until_ready(guard)
            t_f0 = time.perf_counter()
            u = len(chunk)
            bd, bn, bi = bufs
            np.take(self.lists_data, chunk, axis=0, out=bd[:u])
            np.take(self.lists_norms, chunk, axis=0, out=bn[:u])
            np.take(self.lists_indices, chunk, axis=0, out=bi[:u])
            dd = jax.device_put(bd)
            dn = jax.device_put(bn)
            di = jax.device_put(bi)
            fetch_s += time.perf_counter() - t_f0
            fetch_bytes += bd.nbytes + bn.nbytes + bi.nbytes
            idx = np.searchsorted(chunk, probes_np)
            idx = np.minimum(idx, u - 1)
            in_chunk = (~hot_mask) & (chunk[idx] == probes_np)
            pc = np.where(in_chunk, idx, stage_cap).astype(np.int32)
            parts.append(_probe_scan(
                q, dd, dn, di, jnp.asarray(pc), scale, k=k, sqrt=sqrt,
                kind=kind))
            self._stage_release(stage_cap, bufs, (dd, dn, di))

        # the overlap accounting: fetch walls above were spent while
        # the hot-tier program ran under async dispatch — credit them
        # as hidden only while the hot result is demonstrably not
        # ready yet (conservative: a finished hot scan credits zero)
        overlap_s = 0.0
        if parts and hot_tables is not None and n_hot and fetch_s > 0:
            is_ready = getattr(parts[0][0], "is_ready", None)
            inflight = (not is_ready()) if is_ready is not None else True
            if inflight:
                overlap_s = fetch_s
        d, i = parts[0] if parts else (
            jnp.full((q.shape[0], k), jnp.inf, jnp.float32),
            jnp.full((q.shape[0], k), -1, jnp.int32))
        for d_p, i_p in parts[1:]:
            d, i = _merge_topk(d, i, d_p, i_p, k)
        self._note_search(n_total, n_hot, fetch_s, fetch_bytes,
                          overlap_s, time.perf_counter() - t_enq)
        return _postprocess(d, self.metric), i

    def _note_search(self, n_total: int, n_hot: int, fetch_s: float,
                     fetch_bytes: int, overlap_s: float,
                     wall_s: float) -> None:
        obs.counter("raft.tiered.search.total").inc()
        obs.counter("raft.tiered.probes.hot").inc(n_hot)
        obs.counter("raft.tiered.probes.cold").inc(n_total - n_hot)
        if fetch_bytes:
            obs.counter("raft.tiered.fetch.bytes").inc(fetch_bytes)
            obs.counter("raft.tiered.fetch.seconds").inc(fetch_s)
            obs.counter("raft.tiered.overlap.seconds").inc(overlap_s)
        with self._lock:
            self._cum_probes += n_total
            self._cum_hot += n_hot
            self._cum_fetch_s += fetch_s
            self._cum_overlap_s += overlap_s
            hit = (self._cum_hot / self._cum_probes
                   if self._cum_probes else 0.0)
            ofr = (self._cum_overlap_s / self._cum_fetch_s
                   if self._cum_fetch_s > 0 else 0.0)
        obs.gauge("raft.tiered.hit_rate").set(hit)
        obs.gauge("raft.tiered.overlap.frac").set(ofr)


class TieredPlan:
    """The plan-contract handle over one prepared ``(nq, k, n_probes)``
    point of a :class:`TieredIndex` — drop-in for
    ``plan.SearchPlan`` in the serve ladder (``.search(q, block=)``,
    ``.nq`` / ``.k`` / ``.n_probes`` / ``.dim``)."""

    family = "tiered_ivf_flat"

    def __init__(self, index: TieredIndex, nq: int, k: int,
                 n_probes: int, key: tuple):
        self.index = index
        self.nq = int(nq)
        self.k = int(k)
        self.n_probes = int(n_probes)
        self.dim = index.dim
        self.key = key

    def search(self, queries, block: bool = False
               ) -> Tuple[jax.Array, jax.Array]:
        """Serve one batch of exactly ``plan.nq`` queries → (dists,
        ids). The coarse→partition step syncs once mid-call (the
        probe ids drive the host-side staging); everything after is
        async until ``block``."""
        prof = block and profiler.sampled()
        t_call = time.perf_counter()
        q = as_array(queries).astype(jnp.float32)
        expects(q.shape == (self.nq, self.dim),
                "tiered plan.search: queries %s != plan shape (%d, %d)",
                q.shape, self.nq, self.dim)
        obs.counter("raft.plan.search.total").inc()
        obs.counter("raft.plan.search.queries").inc(self.nq)
        with spans.span("raft.tiered.search", nq=self.nq, k=self.k,
                        n_probes=self.n_probes,
                        hot_lists=self.index.hot_lists,
                        blocked=block):
            d, i = self.index._tier_search(q, self.k, self.n_probes)
            t_enq = t_ready = 0.0
            if block:
                t_enq = time.perf_counter()
                jax.block_until_ready((d, i))
                t_ready = time.perf_counter()
                if prof:
                    spans.add_child_span(
                        profiler.SYNC_SPAN, t_enq, t_ready - t_enq,
                        program="tiered",
                        host_ms=round((t_enq - t_call) * 1e3, 3),
                        device_ms=round((t_ready - t_enq) * 1e3, 3))
        if prof and block:
            profiler.record_sample(
                program="tiered", family=self.family,
                rung=self.n_probes,
                host_s=(t_enq - t_call)
                + (time.perf_counter() - t_ready),
                device_s=t_ready - t_enq)
        return d, i

    def search_batched(self, queries, block: bool = True
                       ) -> Tuple[jax.Array, jax.Array]:
        """Arbitrary query counts through the plan's compiled shape
        (pad-to-shape per sub-batch, concatenate, one trim)."""
        q = as_array(queries).astype(jnp.float32)
        expects(q.shape[1] == self.dim,
                "tiered plan.search_batched: dim mismatch (%d != %d)",
                q.shape[1], self.dim)
        if q.shape[0] == self.nq:
            return self.search(q, block=block)
        outs = []
        for off in range(0, q.shape[0], self.nq):
            qb = q[off:off + self.nq]
            if qb.shape[0] < self.nq:
                qb = jnp.concatenate(
                    [qb, jnp.zeros((self.nq - qb.shape[0], self.dim),
                                   jnp.float32)])
            outs.append(self.search(qb, block=False))
        d = jnp.concatenate([o[0] for o in outs])[:q.shape[0]]
        i = jnp.concatenate([o[1] for o in outs])[:q.shape[0]]
        if block:
            jax.block_until_ready((d, i))
        return d, i


def from_host(host: HostIvfFlat,
              config: Optional[TieredConfig] = None) -> TieredIndex:
    """Wrap a host-resident index (its payload arrays are shared, not
    copied)."""
    return TieredIndex(host, config)


def from_index(index: Index,
               config: Optional[TieredConfig] = None) -> TieredIndex:
    """Tier a fully-resident ``ivf_flat.Index``: payload moves to host
    RAM (``host_memory.to_host``), then the placement policy pins what
    the budget affords back onto the device."""
    return TieredIndex(to_host(index), config)


def _prewarm(index: TieredIndex, nq: int, k: int, n_probes: int
             ) -> None:
    """GL013 warm coverage: loop every grid rung
    (``hot_capacities`` up to the budgeted top, all
    ``stage_capacities``) through the shared scan + the coarse and
    merge programs, so steady-state serving — including every
    refresh-boundary capacity swap — replays compiled code."""
    kind = _metric_kind(index.metric)
    sqrt = index.metric in _SQRT_METRICS
    q = jnp.zeros((nq, index.dim), jnp.float32)
    scale = jnp.float32(index.scale)
    pos = jnp.zeros((nq, n_probes), jnp.int32)
    _coarse_topk(q, index.centers, n_probes, kind)
    for hot_cap in index.hot_capacities:
        if hot_cap > index._warm_top:
            continue
        data = jnp.zeros((hot_cap + 1, index.max_list, index.dim),
                         index.lists_data.dtype)
        norms = jnp.zeros((hot_cap + 1, index.max_list),
                          index.lists_norms.dtype)
        ids = jnp.full((hot_cap + 1, index.max_list), -1,
                       index.lists_indices.dtype)
        _probe_scan(q, data, norms, ids, pos, scale, k=k, sqrt=sqrt,
                    kind=kind)
    for stage_cap in index.stage_capacities:
        data = jnp.zeros((stage_cap + 1, index.max_list, index.dim),
                         index.lists_data.dtype)
        norms = jnp.zeros((stage_cap + 1, index.max_list),
                          index.lists_norms.dtype)
        ids = jnp.full((stage_cap + 1, index.max_list), -1,
                       index.lists_indices.dtype)
        _probe_scan(q, data, norms, ids, pos, scale, k=k, sqrt=sqrt,
                    kind=kind)
    dk = jnp.zeros((nq, k), jnp.float32)
    ik = jnp.zeros((nq, k), jnp.int32)
    out = _merge_topk(dk, ik, dk, ik, k)
    jax.block_until_ready(out)


def build_plan(index: TieredIndex, queries, k: int,
               params: Optional[SearchParams] = None,
               warm: bool = True) -> TieredPlan:
    """Build (or fetch from ``index.plan_cache``) the prepared tiered
    plan for this batch shape — same cache counters and LRU bound as
    ``plan.build_plan`` (``raft.plan.cache.*`` / ``raft.plan.build
    .total``), so the zero-steady-state-compile assertions read one
    taxonomy across families."""
    from raft_tpu.neighbors import plan as plan_mod
    if params is None:
        params = SearchParams()
    q = np.asarray(queries, np.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "tiered.build_plan: queries must be (nq, dim=%d), got %s",
            index.dim, q.shape)
    nq = int(q.shape[0])
    n_probes = min(int(params.n_probes), index.n_lists)
    key = ("tiered_ivf_flat", nq, index.dim, k, n_probes,
           _metric_kind(index.metric))
    with spans.span("raft.plan.build", family="tiered_ivf_flat",
                    nq=nq, k=k, n_probes=n_probes) as bsp, \
            obs.timed("raft.plan.build", family="tiered_ivf_flat"):
        cached = index.plan_cache.pop(key, None)
        if cached is not None:
            index.plan_cache[key] = cached      # LRU touch
            obs.counter("raft.plan.cache.hits").inc()
            bsp.set_attr("plan_cache", "hit")
            return cached
        obs.counter("raft.plan.cache.misses").inc()
        obs.counter("raft.plan.build.total").inc()
        bsp.set_attr("plan_cache", "miss")
        t_c0 = time.perf_counter()
        if warm:
            _prewarm(index, nq, k, n_probes)
        profiler.note_compile("tiered", time.perf_counter() - t_c0)
        plan = TieredPlan(index, nq, k, n_probes, key)
        index.plan_cache[key] = plan
        cache_max = plan_mod._plan_cache_max()
        if cache_max > 0:
            while len(index.plan_cache) > cache_max:
                index.plan_cache.pop(next(iter(index.plan_cache)))
                obs.counter("raft.plan.cache.evictions").inc()
        return plan


def build_ladder(index: TieredIndex, rep_queries, k: int,
                 params: Optional[SearchParams] = None,
                 shapes: Tuple[int, ...] = (1, 8, 32, 128),
                 probes_ladder: Tuple[int, ...] = (),
                 prewarm: bool = True):
    """The (shape × rung) tiered plan grid, in ``PlanLadder`` form —
    what ``PlanLadder.build`` (and therefore
    ``SearchServer.from_index``) delegates to for a
    :class:`TieredIndex`. Degrade interplay: a lower rung probes
    fewer lists, which also shrinks the cold fetch working set — load
    shedding and transfer pressure back off together."""
    import dataclasses as _dc

    from raft_tpu.serve.ladder import PlanLadder

    if params is None:
        params = SearchParams()
    q = np.asarray(rep_queries, np.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "tiered.build_ladder: rep_queries must be (nq, dim=%d), "
            "got %s", index.dim, q.shape)
    rungs = tuple(probes_ladder) or (min(params.n_probes,
                                         index.n_lists),)
    plans: Dict[Tuple[int, int], TieredPlan] = {}
    for ri, n_probes in enumerate(rungs):
        p_r = _dc.replace(params, n_probes=n_probes)
        for s in shapes:
            reps = -(-s // q.shape[0])
            q_s = np.tile(q, (reps, 1))[:s]
            plans[(s, ri)] = build_plan(index, q_s, k, p_r,
                                        warm=prewarm)
    return PlanLadder(shapes=tuple(shapes), rungs=rungs, plans=plans,
                      dim=index.dim, k=k)
