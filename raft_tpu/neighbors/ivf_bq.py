"""IVF-BQ: binary-quantized inverted-file index (1 bit/dim + per-row
scale), with exact host-side rescoring.

A capability tier beyond the reference's IVF-Flat/IVF-PQ axis
(`spatial/knn/detail/ivf_flat_build.cuh:228`, `ivf_pq_build.cuh:908`
define the build/search structure mirrored here), following the
sign-random-rotation binary-quantization pattern of the IVF-RaBitQ
line of work (PAPERS.md). Why it earns its place on TPU:

* **Memory**: d/8 code bytes + 12 B stats + 4 B id per vector —
  100M×128 ≈ **2.8 GB**, so
  the BASELINE.md north-star dataset fits a single v5e chip's HBM with
  room to spare (f32 IVF-Flat needs 51 GB, IVF-PQ codes ≈ 3.2 GB).
* **Build speed**: NO codebook training — beyond the shared coarse
  k-means the encode is one subtract + sign, so build ≈ IVF-Flat's
  coarse phase alone (the reference's PQ `train_per_subset` loop
  disappears entirely).
* **MXU scoring**: the quantized scan is a plain ±1 bf16 matmul —
  decode is shift/mask VPU work and the estimator rides the MXU at
  full tile shapes; no LUT gathers anywhere.

Scoring model (residual form, like IVF-PQ): for query q probing list
l with center c_l, and a stored point x = c_l + r,

    ||q − x||² = ||q_l||² + ||r||² − 2⟨q_l, r⟩,   q_l = q − c_l
    ⟨q_l, r⟩ ≈ s·⟨q_l, sign(r)⟩,                 s = mean(|r|)

(s·sign(r) is the best {±s}^d approximation of r in L2.) Inner
product uses the same decomposition — ``q·x ≈ q·c_l + s·⟨q_rot,
sign(r_rot)⟩`` — and cosine rides the ip core after row
normalization. The estimator ranks candidates; `rescore_factor`·k
survivors are re-ranked with EXACT f32 scores against the raw vectors
kept host-side (the `host_memory` role: device holds bits, host holds
truth), so returned values are exact and recall approaches the probe
ceiling.

Two device tiers, routed by ``ops.dispatch``: the XLA formulation
(chunked decode tiles + einsum) and the Pallas kernel
(``pallas_ivf_scan._bq_scan_kernel``) that unpacks the bits INSIDE
VMEM — the scan then reads 1 bit/dim from HBM instead of 16, the
binary tier's bandwidth headline. Either way the device phase is one
jitted dispatch.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.core.precision import matmul_precision
from raft_tpu import obs
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.util.host_sample import sample_rows, take_rows


@dataclass
class IndexParams:
    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    kmeans_n_iters: int = 10          # coarse only; there is no codebook
    kmeans_trainset_fraction: float = 0.5
    kmeans_kernel_precision: object = None
    # keep the raw f32 vectors on HOST for exact rescoring (the
    # device never stores them); False = estimator-only index
    keep_raw: bool = True


@dataclass
class SearchParams:
    n_probes: int = 20
    # rescore_factor·k estimator candidates are re-ranked exactly on
    # host; 0 disables rescoring (estimator distances returned). 8 by
    # default: the estimator, not the probe set, is the recall limiter
    # (measured 0.77 → 0.88 recall@10 going 4 → 8 on clustered 50k×64)
    rescore_factor: int = 8
    # inverted-table width policy, as ivf_flat (see _ivf_scan.resolve_cap)
    probe_cap: int = 0
    # per-list candidate bins; 0 = auto (global pool n_probes·bins ≈
    # 32·rescore_factor·k, floor 128/list — see search()); exact scan
    # when ≥ max_list
    scan_bins: int = 0
    # where the exact re-rank runs: "auto" copies the raw corpus to
    # device HBM once (cached on the index) when it fits
    # RAFT_TPU_RESCORE_DEVICE_MB (default 4096) and fuses the rescore
    # into the search dispatch — the host epilogue costs two
    # device↔host round-trips, ~300 ms/1000-query batch through the
    # axon tunnel (stage-2 measurement 2026-08-02); "never" keeps the
    # host path (the 100M tier, where raw exceeds HBM); "always"
    # forces the device copy regardless of size
    rescore_on_device: str = "auto"


@dataclass
class Index:
    centers: jax.Array          # (n_lists, dim) f32
    centers_rot: jax.Array      # (n_lists, dim) f32 — P @ centers
    rotation_matrix: jax.Array  # (dim, dim) random orthogonal P
    bits: jax.Array             # (n_lists, max_list, words) uint32
    norms2: jax.Array           # (n_lists, max_list) f32  ||r||²
    scales: jax.Array           # (n_lists, max_list) f32  mean|r|
    lists_indices: jax.Array    # (n_lists, max_list) int32, -1 pad
    list_sizes: jax.Array       # (n_lists,) int32
    metric: DistanceType
    size: int
    raw: Optional[np.ndarray] = None   # (n, dim) f32 host copy
    cap_cache: dict = dataclasses.field(default_factory=dict)
    # AOT-compiled serving plans keyed by shape identity — see
    # neighbors/plan.py (not index identity; not serialized)
    plan_cache: dict = dataclasses.field(default_factory=dict,
                                         repr=False, compare=False)
    # lazy device copy of `raw` for the fused rescore tier
    # (SearchParams.rescore_on_device); never serialized
    raw_dev: Optional[jax.Array] = None

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def words(self) -> int:
        return self.bits.shape[2]


def _pack_bits(r) -> jax.Array:
    """sign bits of (n, d) → (n, ceil(d/32)) uint32, bit i of word w =
    (r[:, 32w+i] >= 0)."""
    n, d = r.shape
    pad = (-d) % 32
    b = (r >= 0).astype(jnp.uint32)
    if pad:
        b = jnp.pad(b, ((0, 0), (0, pad)))
    b = b.reshape(n, -1, 32)
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, None, :]
    return jnp.sum(b << shifts, axis=2, dtype=jnp.uint32)


def _unpack_pm1(words, d: int, dtype=jnp.bfloat16) -> jax.Array:
    """(..., w) uint32 → (..., d) ±1: the decode tile. VPU shift/mask;
    the result feeds the MXU einsum directly."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[..., None] >> shifts) & jnp.uint32(1)
    flat = bits.reshape(*words.shape[:-1], words.shape[-1] * 32)[..., :d]
    return (2.0 * flat.astype(dtype) - 1.0).astype(dtype)


_SUPPORTED_METRICS = (DistanceType.L2Expanded,
                      DistanceType.L2SqrtExpanded,
                      DistanceType.InnerProduct,
                      DistanceType.CosineExpanded)


def build(dataset, params: IndexParams = IndexParams(), res=None) -> Index:
    """Coarse k-means + sign-encode residuals (no codebook training —
    the build-speed headline of the binary tier). Cosine datasets are
    row-normalized at build (the ivf_flat/processing.cuh convention) so
    the ip scoring core applies; ``raw`` stores the normalized rows."""
    x = as_array(dataset).astype(jnp.float32)
    n, d = x.shape
    expects(params.n_lists <= n, "ivf_bq.build: n_lists > n_samples")
    expects(params.metric in _SUPPORTED_METRICS,
            "ivf_bq: unsupported metric %s", params.metric)
    if params.metric == DistanceType.CosineExpanded:
        x = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True),
                            1e-30)
    obs.counter("raft.ivf_bq.build.total").inc()
    obs.counter("raft.ivf_bq.build.rows").inc(n)
    from raft_tpu.obs import spans
    with spans.span("raft.ivf_bq.build", rows=n,
                    n_lists=params.n_lists), \
            obs.timed("raft.ivf_bq.build"):
        n_train = max(params.n_lists,
                      int(n * params.kmeans_trainset_fraction))
        trainset = (take_rows(x, sample_rows(n, n_train, 0))
                    if n_train < n else x)
        centers = kmeans_balanced.build_hierarchical(
            trainset, params.n_lists, params.kmeans_n_iters,
            kernel_precision=params.kmeans_kernel_precision, res=res)
        labels = kmeans_balanced.predict(x, centers, res=res)
        # random rotation before the sign code (the RaBitQ trick, via
        # the same construction as ivf_pq.make_rotation_matrix):
        # isotropizes residual coordinates so each bit carries ~equal
        # information. Neutral on already-isotropic data (gaussian /
        # post-kmeans blobs measure within noise), load-bearing on
        # anisotropic real features (low-rank/correlated dims would
        # otherwise waste bits); kept unconditional like the reference's
        # PQ rotation
        from raft_tpu.neighbors.ivf_pq import make_rotation_matrix
        rot = make_rotation_matrix(d, d, force_random=True)
        payload, centers_rot = _encode_payload(x, centers, labels, rot)
        from raft_tpu.neighbors.ivf_flat import _bucketize
        bucketed, idx, _, counts = _bucketize(payload, labels,
                                              params.n_lists,
                                              compute_norms=False)
        w = payload.shape[1] - 2
        bits, norms2, scales = _split_payload(bucketed, w)
        raw = np.asarray(jax.device_get(x)) if params.keep_raw else None
    return Index(centers=centers, centers_rot=centers_rot,
                 rotation_matrix=rot, bits=bits, norms2=norms2,
                 scales=scales,
                 lists_indices=idx, list_sizes=counts,
                 metric=params.metric, size=n, raw=raw)


@jax.jit
def _encode_payload(x, centers, labels, rot):
    """Residual rotation + sign-pack + payload assembly as ONE program
    (eagerly this phase was ~20 op-by-op remote compiles; cold build is
    compile-count-bound through the tunnel).

    Full-precision rotation: the sign code IS the payload, and TPU
    default-precision (single-pass bf16) matmul flips signs of
    near-zero rotated components vs host f32 math — observed on
    hardware 2026-08-02 (bq_roundtrip_check stage 0a).

    The payload is one combined INT32 block (word bit-patterns +
    bitcast norm/scale columns): int32 has no canonicalization hazard,
    unlike f32 whose NaN-patterned bitcasts XLA may rewrite in
    concatenate/gather/scatter (ADVICE r3 #2); the squared-norm pass
    over the payload is skipped outright (compute_norms=False)."""
    r = jnp.matmul(x - centers[labels], rot.T,
                   precision=matmul_precision())
    norms2 = jnp.sum(r * r, axis=1)
    scales = jnp.mean(jnp.abs(r), axis=1)
    words = _pack_bits(r)
    payload = jnp.concatenate(
        [lax.bitcast_convert_type(words, jnp.int32),
         lax.bitcast_convert_type(norms2[:, None], jnp.int32),
         lax.bitcast_convert_type(scales[:, None], jnp.int32)],
        axis=1)
    centers_rot = jnp.matmul(centers, rot.T,
                             precision=matmul_precision())
    return payload, centers_rot


@functools.partial(jax.jit, static_argnames=("w",))
def _split_payload(bucketed, w: int):
    """Bucketed int32 payload → (bits u32, norms2 f32, scales f32)."""
    bits = lax.bitcast_convert_type(bucketed[:, :, :w], jnp.uint32)
    norms2 = lax.bitcast_convert_type(bucketed[:, :, w], jnp.float32)
    scales = lax.bitcast_convert_type(bucketed[:, :, w + 1], jnp.float32)
    return bits, norms2, scales


@functools.partial(jax.jit, static_argnames=("kk", "bins", "n_probes",
                                             "cap", "chunk", "dim",
                                             "kind"))
def _fused_bq_search(queries, centers, centers_rot, rot, bits, norms2,
                     scales, ids, *, kk: int, bins: int, n_probes: int,
                     cap: int, chunk: int, dim: int, kind: str = "l2"):
    """Single-dispatch device phase: coarse GEMM + top-k probes, query
    rotation, probe inversion, chunked decode-tile estimator scan,
    candidate merge. Returns (est dists (nq, kk), global ids (nq, kk))
    — estimator ordering, smaller-is-better (squared-L2 for the l2
    core; NEGATED similarity ``−(q·c_l + s·⟨q_rot, sign(r_rot)⟩)``
    for ip — the x = c_l + r decomposition of q·x)."""
    from raft_tpu.neighbors import _ivf_scan as S
    nq = queries.shape[0]
    n_lists, max_list = ids.shape
    probes = S.coarse_probes(queries, centers, n_probes, kind=kind)
    q_rot = queries @ rot.T      # orthogonal: geometry unchanged
    qmap, inv_pos = S._invert_probes(probes, n_lists, cap)

    n_chunks = n_lists // chunk
    qmap_c = qmap.reshape(n_chunks, chunk, cap)
    bits_c = bits.reshape(n_chunks, chunk, max_list, -1)
    n2_c = norms2.reshape(n_chunks, chunk, max_list)
    sc_c = scales.reshape(n_chunks, chunk, max_list)
    ids_c = ids.reshape(n_chunks, chunk, max_list)
    cent_c = centers_rot.reshape(n_chunks, chunk, dim)

    def one_chunk(args):
        qm, bw, n2, sc, lid, cl = args
        qg = q_rot[jnp.clip(qm, 0, nq - 1)]           # (chunk, cap, d)
        pm1 = _unpack_pm1(bw, dim)                    # (chunk, ML, d) ±1
        if kind == "ip":
            # one-pass bf16 estimator tier on purpose (exact re-rank
            # follows)
            ip = jnp.einsum("gcd,gld->gcl", qg.astype(jnp.bfloat16),
                            pm1, preferred_element_type=jnp.float32,
                            precision=lax.Precision.DEFAULT)
            # q·c_l dominates the estimator: full precision, like the
            # Pallas tier's post-scan correction
            corr = jnp.einsum("gcd,gd->gc", qg, cl,
                              precision=matmul_precision(),
                              preferred_element_type=jnp.float32)
            est = -(corr[:, :, None] + sc[:, None, :] * ip)
        else:
            qsub = qg - cl[:, None, :]
            ip = jnp.einsum("gcd,gld->gcl", qsub.astype(jnp.bfloat16),
                            pm1, preferred_element_type=jnp.float32,
                            precision=lax.Precision.DEFAULT)
            qq = jnp.sum(qsub * qsub, axis=2)         # (chunk, cap)
            est = (qq[:, :, None] + n2[:, None, :]
                   - 2.0 * sc[:, None, :] * ip)       # (chunk, cap, ML)
        est = jnp.where(lid[:, None, :] >= 0, est, jnp.inf)
        return S.binned_partial_topk(est, lid, bins)

    cand_d, cand_i = lax.map(one_chunk,
                             (qmap_c, bits_c, n2_c, sc_c, ids_c, cent_c))
    cand_d = cand_d.reshape(n_lists, cap, -1)
    cand_i = cand_i.reshape(n_lists, cap, -1)
    return S.merge_candidates(cand_d, cand_i, probes, inv_pos, kk,
                              sqrt=False, cap=cap)


def extend(index: Index, new_vectors, new_indices=None, res=None
           ) -> Index:
    """Add vectors to an existing index (the ivf_flat/ivf_pq extend
    contract, reference ``ivf_pq_build.cuh:605``): label against the
    FROZEN centers, sign-encode with the frozen rotation, re-bucketize
    the combined per-row payloads. Per-row payloads are immutable under
    fixed centers+rotation, so old rows are moved, never re-encoded."""
    x = as_array(new_vectors).astype(jnp.float32)
    expects(x.ndim == 2 and x.shape[1] == index.dim,
            "ivf_bq.extend: dim mismatch")
    if index.metric == DistanceType.CosineExpanded:
        # build() stores normalized rows; extended rows must match or
        # the ip core scores raw dot products (ivf_flat.extend ditto)
        x = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True),
                            1e-30)
    n_new = x.shape[0]
    new_ids = (jnp.arange(index.size, index.size + n_new,
                          dtype=jnp.int32)
               if new_indices is None
               else as_array(new_indices).astype(jnp.int32))
    expects(new_ids.shape == (n_new,), "ivf_bq.extend: bad new_indices")
    expects(bool((new_ids >= 0).all()),
            "ivf_bq.extend: new_indices must be non-negative")
    # the host rescore indexes `raw` BY global id — custom ids would
    # misalign it; estimator-only (keep_raw=False) indexes are free to
    # use any id scheme
    expects(index.raw is None or new_indices is None,
            "ivf_bq.extend: custom new_indices are only supported on "
            "keep_raw=False indexes (raw rescore rows are id-indexed)")

    n_lists, ml, w = index.bits.shape
    # flat view of current contents; a slot's list id is its label
    valid = (index.lists_indices >= 0).reshape(-1)
    old_labels = jnp.broadcast_to(
        jnp.arange(n_lists, dtype=jnp.int32)[:, None],
        (n_lists, ml)).reshape(-1)[valid]
    # int32 payload end-to-end (see build): bit words never ride as f32
    old_payload = jnp.concatenate(
        [lax.bitcast_convert_type(index.bits, jnp.int32)
         .reshape(-1, w)[valid],
         lax.bitcast_convert_type(
             index.norms2.reshape(-1)[valid][:, None], jnp.int32),
         lax.bitcast_convert_type(
             index.scales.reshape(-1)[valid][:, None], jnp.int32)],
        axis=1)
    old_ids = index.lists_indices.reshape(-1)[valid]

    new_labels = kmeans_balanced.predict(x, index.centers, res=res)
    # full precision like build(): sign stability (see build comment)
    r = jnp.matmul(x - index.centers[new_labels],
                   index.rotation_matrix.T,
                   precision=matmul_precision())
    new_payload = jnp.concatenate(
        [lax.bitcast_convert_type(_pack_bits(r), jnp.int32),
         lax.bitcast_convert_type(
             jnp.sum(r * r, axis=1)[:, None], jnp.int32),
         lax.bitcast_convert_type(
             jnp.mean(jnp.abs(r), axis=1)[:, None], jnp.int32)],
        axis=1)

    from raft_tpu.neighbors.ivf_flat import _bucketize
    payload = jnp.concatenate([old_payload, new_payload], axis=0)
    labels = jnp.concatenate([old_labels, new_labels])
    ids = jnp.concatenate([old_ids, new_ids])
    bucketed, idx, _, counts = _bucketize(payload, labels, n_lists,
                                          row_ids=ids,
                                          compute_norms=False)
    raw = None
    if index.raw is not None:
        raw = np.concatenate([index.raw,
                              np.asarray(jax.device_get(x))], axis=0)
    return Index(
        centers=index.centers, centers_rot=index.centers_rot,
        rotation_matrix=index.rotation_matrix,
        bits=lax.bitcast_convert_type(bucketed[:, :, :w], jnp.uint32),
        norms2=lax.bitcast_convert_type(bucketed[:, :, w], jnp.float32),
        scales=lax.bitcast_convert_type(bucketed[:, :, w + 1],
                                        jnp.float32),
        lists_indices=idx, list_sizes=counts, metric=index.metric,
        size=index.size + n_new, raw=raw)


@functools.partial(jax.jit, static_argnames=("kk", "bins", "n_probes",
                                             "cap", "gather", "kind",
                                             "lc", "fused"))
def _fused_bq_search_pallas(queries, centers, centers_rot, rot, bits,
                            norms2, scales, ids, *, kk: int, bins: int,
                            n_probes: int, cap: int,
                            gather: str = "rows", kind: str = "l2",
                            lc: int = 0, fused: bool = False):
    """Kernel-tier single-dispatch device phase: the in-VMEM unpack
    scan (``pallas_ivf_scan.ivf_bq_scan_pallas``) reads the 1-bit codes
    straight from HBM — 8× less scan bandwidth than the XLA tier's
    materialized decode tiles. ``gather`` is the RAFT_TPU_GATHER
    strategy resolved OUTSIDE jit (the _ivf_scan contract); ``lc``
    likewise (``pallas_ivf_scan.lc_mode``), 0 = auto; ``fused``
    (``pallas_ivf_scan.fused_mode``) routes the fine phase through the
    single-pallas_call scan+select kernel (ISSUE 7)."""
    from raft_tpu.neighbors import _ivf_scan as S
    from raft_tpu.ops.pallas_ivf_scan import ivf_bq_scan_pallas
    probes = S.coarse_probes(queries, centers, n_probes, kind=kind,
                             use_pallas=True)
    q_rot = queries @ rot.T
    return ivf_bq_scan_pallas(q_rot, centers_rot, bits, norms2, scales,
                              ids, probes, kk, cap, bins=bins,
                              gather=gather, metric=kind, lc=lc,
                              fused=fused)


def _resolve(index: Index, queries, params: SearchParams,
             n_probes: int, use_pallas: bool, kind: str = "l2") -> int:
    from raft_tpu.neighbors import _ivf_scan as S
    # use_pallas/kind must match the serving path's coarse selection —
    # a tie resolved differently could push a list past the measured
    # cap and silently shed probes (resolve_cap docstring)
    return S.resolve_cap(index.cap_cache, queries, index.centers,
                         params, n_probes, index.n_lists, kind=kind,
                         use_pallas=use_pallas)


@functools.partial(jax.jit, static_argnames=("k", "kind"))
def _exact_rescore_device(raw_dev, q, ids, *, k: int, kind: str):
    """Exact re-rank of the kk estimator survivors on DEVICE: gather by
    global id + f32 scores + top-k, one fused dispatch. Value-identical
    to the host epilogue (same scores, same ordering rule) but with no
    device↔host round-trip, so the whole search stays jittable."""
    cand = raw_dev[jnp.maximum(ids, 0)]                 # (nq, kk, d)
    qf = q.astype(jnp.float32)
    if kind == "ip":
        ex = -jnp.einsum("qkd,qd->qk", cand, qf,
                         precision=matmul_precision(),
                         preferred_element_type=jnp.float32)
    else:
        diff = cand - qf[:, None, :]
        ex = jnp.sum(diff * diff, axis=2)
    ex = jnp.where(ids >= 0, ex, jnp.inf)
    nd, sel = lax.top_k(-ex, k)
    return -nd, jnp.take_along_axis(ids, sel, axis=1)


_RAW_DEV_LOCK = threading.Lock()


def resolve_raw_device(index, mode: str) -> Optional[jax.Array]:
    """Device copy of ``index.raw`` per the ``rescore_on_device``
    policy ("auto" | "always" | "never"), cached on the index. None
    means: use the host epilogue. "never" also RELEASES a cached copy
    (the reclaim path after an "always" experiment); "auto" falls back
    to host if the device copy fails to materialize (e.g. HBM already
    full) rather than failing the search."""
    expects(mode in ("auto", "always", "never"),
            "rescore_on_device: want auto|always|never, got %r", mode)
    if mode == "never" or index.raw is None:
        index.raw_dev = None
        return None
    if mode == "auto":
        import os
        budget_mb = int(os.environ.get("RAFT_TPU_RESCORE_DEVICE_MB",
                                       "4096"))
        if index.raw.nbytes > budget_mb << 20:
            return None
    with _RAW_DEV_LOCK:
        if (index.raw_dev is None
                or index.raw_dev.shape != index.raw.shape):
            try:
                index.raw_dev = jnp.asarray(index.raw)
            except Exception:
                if mode == "always":
                    raise
                return None    # auto: HBM full → host epilogue
        return index.raw_dev


def finish_search(d_est, ids, raw, q, k: int,
                  metric: DistanceType = DistanceType.L2Expanded,
                  rescore: bool = False, raw_dev=None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Shared epilogue of the single-chip and distributed searches:
    either slice the estimator top-k, or exactly re-rank the kk
    survivors against the host-resident raw vectors. Internal scores
    are uniformly smaller-is-better (−similarity for the ip core);
    the ivf_flat output conventions are applied last (IP →
    similarities, cosine → 1 − cos, L2Sqrt → euclidean)."""
    from raft_tpu.neighbors.ivf_flat import _metric_kind, _postprocess
    kind = _metric_kind(metric)
    # both Sqrt metrics: ivf_pq routes through here too and supports
    # L2SqrtUnexpanded (r4 review finding)
    sqrt = metric in (DistanceType.L2SqrtExpanded,
                      DistanceType.L2SqrtUnexpanded)
    if not rescore:
        d_est, ids = d_est[:, :k], ids[:, :k]
        if sqrt:
            d_est = jnp.sqrt(jnp.maximum(d_est, 0.0))
        return _postprocess(d_est, metric), ids
    if raw_dev is not None:
        ex, i_out = _exact_rescore_device(raw_dev, q, ids,
                                          k=k, kind=kind)
        i_out = jnp.where(jnp.isfinite(ex), i_out, -1)
        d_out = jnp.where(jnp.isfinite(ex), ex, jnp.inf)
        if sqrt:
            d_out = jnp.sqrt(jnp.maximum(d_out, 0.0))
        return _postprocess(d_out, metric), i_out
    ids_h = np.asarray(jax.device_get(ids))
    qh = np.asarray(jax.device_get(q))
    cand = raw[np.maximum(ids_h, 0)]                    # (nq, kk, d)
    if kind == "ip":
        ex = -np.einsum("qkd,qd->qk", cand, qh)         # −similarity
    else:
        diff = cand - qh[:, None, :]
        ex = np.einsum("qkd,qkd->qk", diff, diff)
    ex = np.where(ids_h >= 0, ex, np.inf)
    order = np.argsort(ex, axis=1)[:, :k]
    d_out = np.take_along_axis(ex, order, axis=1)
    i_out = np.take_along_axis(ids_h, order, axis=1)
    i_out = np.where(np.isfinite(d_out), i_out, -1)
    d_out = np.where(np.isfinite(d_out), d_out, np.inf)
    if sqrt:
        d_out = np.sqrt(np.maximum(d_out, 0.0))
    return _postprocess(jnp.asarray(d_out), metric), jnp.asarray(i_out)


def search(index: Index, queries, k: int,
           params: SearchParams = SearchParams(), res=None
           ) -> Tuple[jax.Array, jax.Array]:
    """Estimator scan on device (one dispatch) + exact host rescore.
    When rescoring, returned values are exact and follow the family
    output conventions (ivf_flat._postprocess): squared-L2 ascending
    (euclidean for the Sqrt metric), similarities DESCENDING for
    InnerProduct, 1 − cos ascending for cosine; estimator values in
    the same conventions otherwise."""
    from raft_tpu.obs import spans
    with spans.span("raft.ivf_bq.search", k=k) as sp:
        return _search_spanned(index, queries, k, params, res, sp)


def _search_spanned(index: Index, queries, k: int, params, res, sp
                    ) -> Tuple[jax.Array, jax.Array]:
    q = as_array(queries).astype(jnp.float32)
    sp.set_attr("nq", int(q.shape[0]))
    expects(q.shape[1] == index.dim, "ivf_bq.search: dim mismatch")
    from raft_tpu.neighbors.ann_types import (MAX_QUERY_BATCH,
                                              batched_search)
    if q.shape[0] > MAX_QUERY_BATCH:
        # reference batching loop (ivf_pq_search.cuh:1234 role): bounds
        # the inverted-table width (cap ≤ nq) and reuses one compiled
        # shape per batch
        return batched_search(
            lambda qb: search(index, qb, k, params, res=res), q)
    from raft_tpu.neighbors.ivf_flat import _metric_kind
    # per-batch telemetry (the batched path recurses per sub-batch)
    obs.counter("raft.ivf_bq.search.queries").inc(q.shape[0])
    obs.histogram("raft.ivf_bq.search.batch_size",
                  buckets=obs.SIZE_BUCKETS).observe(q.shape[0])
    kind = _metric_kind(index.metric)
    if index.metric == DistanceType.CosineExpanded:
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-30)
    n_probes = min(params.n_probes, index.n_lists)
    # mirror the n_probes/probe_cap validation style: a negative value
    # would bypass the auto-bins branch ('or' catches only 0) and fail
    # deep in the scan with an opaque reshape error (ADVICE r3 #4)
    expects(params.scan_bins >= 0,
            "ivf_bq.search: scan_bins must be >= 0 (0 = auto), got %d",
            params.scan_bins)
    expects(params.rescore_factor >= 0,
            "ivf_bq.search: rescore_factor must be >= 0, got %d",
            params.rescore_factor)
    expects(params.rescore_on_device in ("auto", "always", "never"),
            "ivf_bq.search: rescore_on_device: want auto|always|never,"
            " got %r", params.rescore_on_device)
    rescore = params.rescore_factor > 0 and index.raw is not None
    # rescore_factor shapes the DEVICE phase (candidate count) whether
    # or not raw vectors exist — so an estimator-only index (or a bench
    # chaining the device program) runs the same compiled search as the
    # rescored one; without raw the estimator top-k is returned.
    # No clamp to index.size: merge_candidates pads short candidate
    # sets, preserving the (nq, k) output contract of the other indexes.
    kk = max(params.rescore_factor, 1) * k
    from raft_tpu.ops.dispatch import pallas_enabled
    use_pallas = pallas_enabled()
    cap = _resolve(index, q, params, n_probes, use_pallas, kind=kind)
    max_list = index.bits.shape[1]
    # auto bins: a 32x-oversampled GLOBAL candidate pool (n_probes·bins
    # ≈ 32·kk, floor 128/list) instead of the flat/pq per-list 4·k rule
    # — kk here is rescore_factor·k, and scaling bins with it directly
    # would blow the merge width (64 probes × 4·256 bins = 32k-wide
    # select) and the candidate blocks (~0.5 GB at the 500k bench
    # point). Safe because bins are STRIDED in both tiers
    # (binned_partial_topk / the kernels): narrow bins no longer
    # collide dataset-adjacent true neighbors — measured 0.920 vs the
    # contiguous formulation's 0.868 recall@10 at 30k×64/128-list with
    # this same pool size
    bins = min(params.scan_bins
               or max(128, (32 * kk) // max(n_probes, 1)), max_list)
    # chunk bound: BOTH the (chunk, cap, max_list) estimator block
    # (the _ivf_scan._chunk_size budget every XLA-tier search uses)
    # AND the (chunk, max_list, dim) decode tile must stay modest
    from raft_tpu.neighbors._ivf_scan import (_chunk_size,
                                              largest_divisor_at_most)
    chunk = min(  # both are divisors of n_lists, so their min is too
        _chunk_size(index.n_lists, cap, max_list),
        largest_divisor_at_most(
            index.n_lists,
            max(1, (64 << 20) // max(1, max_list * index.dim * 2))))
    obs.histogram("raft.ivf_bq.search.n_probes",
                  buckets=obs.SIZE_BUCKETS).observe(n_probes)
    sp.set_attrs(n_probes=n_probes, rescore=rescore)
    from raft_tpu.neighbors._ivf_scan import count_coarse_fallback
    count_coarse_fallback(n_probes, use_pallas)
    with obs.timed("raft.ivf_bq.search"):
        from raft_tpu.ops.compile_budget import run_tiers
        from raft_tpu.ops.pallas_ivf_scan import fused_mode, lc_mode

        def pallas_tier(lc, fz: bool = False):
            from raft_tpu.neighbors._ivf_scan import gather_mode
            return lambda: _fused_bq_search_pallas(
                q, index.centers, index.centers_rot,
                index.rotation_matrix, index.bits, index.norms2,
                index.scales, index.lists_indices, kk=kk, bins=bins,
                n_probes=n_probes, cap=cap, gather=gather_mode(),
                kind=kind, lc=lc, fused=fz)

        # compile-budget ladder (ops/compile_budget.py): fused
        # scan+select (ONE pallas_call fine phase, ISSUE 7) → Pallas
        # unpack scan → Pallas grid-per-list → the XLA decode-tile
        # formulation (proven-compilable tail)
        tiers = []
        fused_on = use_pallas and fused_mode() and kk <= 256
        if fused_on:
            obs.counter("raft.ivf_scan.fused.total",
                        family="ivf_bq").inc()
            obs.counter("raft.ivf_scan.fused.queries").inc(q.shape[0])
            lc0f = lc_mode()
            tiers.append((f"pallas_fused_lc{lc0f or 'auto'}",
                          pallas_tier(lc0f, True)))
        if use_pallas:
            from raft_tpu.ops.pallas_ivf_scan import _pick_lc
            lc0 = lc_mode()
            tiers.append((f"pallas_lc{lc0 or 'auto'}", pallas_tier(lc0)))
            # no lc=1 rung when the first tier already resolves to it
            # (see ivf_flat.search)
            auto_lc = _pick_lc(index.n_lists, max_list, cap,
                               index.dim, 2)
            if lc0 != 1 and not (lc0 == 0 and auto_lc == 1):
                tiers.append(("pallas_lc1", pallas_tier(1)))
        tiers.append(("xla_decode", lambda: _fused_bq_search(
            q, index.centers, index.centers_rot,
            index.rotation_matrix, index.bits, index.norms2,
            index.scales, index.lists_indices, kk=kk, bins=bins,
            n_probes=n_probes, cap=cap, chunk=chunk, dim=index.dim,
            kind=kind)))
        # key covers every program-shaping static (see ivf_flat.search)
        from raft_tpu.neighbors._ivf_scan import gather_mode
        shape_key = (f"ivf_bq[{q.shape[0]}x{index.dim},kk={kk},"
                     f"p={n_probes},cap={cap},L={index.n_lists},"
                     f"bins={bins},{kind},g={gather_mode()},"
                     f"fz={fused_on}]")
        d_est, ids = run_tiers(shape_key, tiers)
        raw_dev = (resolve_raw_device(index, params.rescore_on_device)
                   if rescore else None)
        return finish_search(d_est, ids, index.raw, q, k,
                             metric=index.metric, rescore=rescore,
                             raw_dev=raw_dev)
