"""Host-memory-resident ANN indexes (the reference's host-transfer axis).

Reference: the knn bench's NO_COPY / MAP_PINNED / MANAGED host-memory
strategies (``cpp/bench/neighbors/knn.cuh:380-389``) — indexes larger
than device memory live in host RAM and the working set migrates per
batch. TPU-native equivalent: inverted lists stay in **host numpy**
(51 GB of 100M×128 f32 does not fit a 16 GB v5e chip); per search batch,
only the UNION OF PROBED LISTS is shipped to HBM and scored with the
same fine-phase GEMM as the resident index. For online/small-batch
serving the union is a small fraction of the database, so HBM holds
O(probed) bytes instead of O(n).

Complements (not replaces) the sharded path: `raft_tpu.parallel.ivf`
scales by adding chips; this scales a single chip beyond its HBM.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors._ivf_scan import note_probes
from raft_tpu.neighbors.ivf_flat import (
    Index,
    IndexParams,
    SearchParams,
    _coarse_scores,
    _fine_phase,
    _metric_kind,
    _postprocess,
)


def _fetch(a):
    """Host→device transfer point (module-local so tests can observe
    fetch sizes without touching jax.numpy globally). Both directions
    of the host-memory contract route through here: list fetches at
    search AND chunk ingestion at streaming build — a test asserting
    peak device allocation hooks ONE symbol."""
    return jnp.asarray(a)


def _place_chunk(n_lists: int, cursor, chunk, labels, id_base: int,
                 lists_data, lists_idx, lists_norms=None, row_norms=None):
    """Place one host chunk's rows into their list slots (per-list write
    cursors) — the shared host-side assembly step of :func:`build` and
    :func:`build_streaming`. ``row_norms`` (when given) land in
    ``lists_norms`` alongside the rows."""
    order = np.argsort(labels, kind="stable")
    bounds = np.searchsorted(labels[order], np.arange(n_lists + 1))
    for l in range(n_lists):
        rows = order[bounds[l]:bounds[l + 1]]
        if rows.size:
            c = cursor[l]
            lists_data[l, c:c + rows.size] = chunk[rows]
            lists_idx[l, c:c + rows.size] = (id_base + rows)
            if lists_norms is not None:
                lists_norms[l, c:c + rows.size] = row_norms[rows]
            cursor[l] += rows.size


@dataclass
class HostIvfFlat:
    """IVF-Flat index with device-resident centers and host-resident
    lists. Build normally (possibly shard-by-shard), then `to_host`."""

    centers: jax.Array              # (n_lists, dim) — stays on device
    lists_data: np.ndarray          # (n_lists, max_list, dim) host
    lists_norms: np.ndarray         # (n_lists, max_list) host
    lists_indices: np.ndarray       # (n_lists, max_list) host
    metric: DistanceType
    size: int
    scale: float = 1.0

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]


def to_host(index: Index) -> HostIvfFlat:
    """Demote an IVF-Flat index's lists to host memory (device keeps only
    the coarse centers, O(n_lists·dim)). For datasets that never fit the
    device in the first place, use :func:`build` instead."""
    return HostIvfFlat(
        centers=index.centers,
        lists_data=np.asarray(index.lists_data),
        lists_norms=np.asarray(index.lists_norms),
        lists_indices=np.asarray(index.lists_indices),
        metric=index.metric, size=index.size, scale=index.scale)


def build(dataset, params: IndexParams = IndexParams(),
          chunk_rows: int = 1 << 20, train_rows: int = 1 << 18,
          seed: int = 0, res=None) -> HostIvfFlat:
    """Build a host-resident index WITHOUT ever materializing the dataset
    (or the lists) on device — the construction path for indexes larger
    than HBM.

    The coarse centers train on a ``train_rows`` device subsample; then
    the dataset streams through the chip in ``chunk_rows`` slices (label
    + norm per chunk on device, O(chunk) HBM), while the inverted lists
    assemble **on the host** in numpy. Labeling shares the same
    ``predict`` as the resident build, so with equal centers the list
    membership is identical.
    """
    from raft_tpu.cluster import kmeans_balanced

    x = np.asarray(dataset, dtype=np.float32)
    n, dim = x.shape
    expects(params.n_lists <= n, "host ivf build: n_lists > n_samples")

    rng = np.random.default_rng(seed)
    t_rows = min(n, train_rows)
    sub = x[rng.choice(n, t_rows, replace=False)] if t_rows < n else x
    centers = kmeans_balanced.build_hierarchical(
        jnp.asarray(sub), params.n_lists, params.kmeans_n_iters,
        kernel_precision=params.kmeans_kernel_precision,
        res=res)

    # pass 1: labels only (n·4 bytes of bookkeeping) — keeps peak host
    # memory at dataset + padded lists, not 3× the dataset
    labels_all = np.empty(n, np.int32)
    for start in range(0, n, chunk_rows):
        chunk = x[start:start + chunk_rows]
        labels_all[start:start + chunk.shape[0]] = np.asarray(
            kmeans_balanced.predict(jnp.asarray(chunk), centers, res=res))

    counts = np.bincount(labels_all, minlength=params.n_lists)
    max_list = max(8, int(-(-int(counts.max()) // 8) * 8))
    lists_data = np.zeros((params.n_lists, max_list, dim), np.float32)
    lists_idx = np.full((params.n_lists, max_list), -1, np.int32)

    # pass 2: place rows directly into their list slots (per-list write
    # cursors), chunk by chunk — no intermediate per-list copies
    cursor = np.zeros(params.n_lists, np.int64)
    for start in range(0, n, chunk_rows):
        chunk = x[start:start + chunk_rows]
        labels = labels_all[start:start + chunk.shape[0]]
        _place_chunk(params.n_lists, cursor, chunk, labels, start,
                     lists_data, lists_idx)

    # norms in list blocks: O(block·max_list·dim) f64 temporaries only
    norms = np.empty((params.n_lists, max_list), np.float32)
    blk = 64
    for l0 in range(0, params.n_lists, blk):
        seg = lists_data[l0:l0 + blk].astype(np.float64)
        norms[l0:l0 + blk] = (seg * seg).sum(-1).astype(np.float32)
    return HostIvfFlat(centers=centers, lists_data=lists_data,
                       lists_norms=norms, lists_indices=lists_idx,
                       metric=params.metric, size=n, scale=1.0)


def _label_norm_impl(chunk, centers):
    from raft_tpu.cluster.kmeans_balanced import _nn
    labels, _ = _nn(chunk, centers)
    return labels.astype(jnp.int32), jnp.sum(chunk * chunk, axis=1)


_LABEL_JIT = None


def _label_chunk_fn():
    """Fused label+norm program for streaming ingestion. The chunk
    operand is DONATED on backends that support donation (TPU/GPU), so
    each chunk's transfer buffer is recycled in place — peak device
    memory stays one chunk, not one per in-flight dispatch. (CPU has no
    donation; the loop's synchronous device_get bounds liveness there.)"""
    global _LABEL_JIT
    if _LABEL_JIT is None:
        donate = (0,) if jax.default_backend() in ("tpu", "gpu") else ()
        _LABEL_JIT = jax.jit(_label_norm_impl, donate_argnums=donate)
    return _LABEL_JIT


def build_streaming(chunks, params: IndexParams = IndexParams(),
                    train_rows: int = 1 << 18, seed: int = 0,
                    res=None) -> HostIvfFlat:
    """Build a host-resident IVF-Flat index from an ITERATOR of host
    chunks — the ingestion path for corpora that never fit in HBM.

    Peak device allocation is O(chunk + train_rows + n_lists·dim): the
    coarse centers train on a bounded subsample drawn across the whole
    stream, then every chunk takes ONE fused label+norm dispatch (the
    chunk operand donated — see :func:`_label_chunk_fn`) while the
    inverted lists assemble on the host. Chunks are buffered host-side
    (numpy): host RAM bounds the corpus, device HBM never does. Every
    host→device transfer routes through :func:`_fetch`, so tests can
    assert the O(chunk) property by hooking one symbol.

    Parity: labeling shares ``kmeans_balanced`` with the resident
    build, so with ``train_rows >= n`` the trainer sees exactly the
    in-memory ``ivf_flat.build`` trainset (fraction 1.0) and list
    membership is identical to the resident index's.
    """
    from raft_tpu import obs
    from raft_tpu.obs import spans
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.distance.distance_types import DistanceType as _DT

    chunk_list = []
    for c in chunks:
        c = np.ascontiguousarray(np.asarray(c, dtype=np.float32))
        expects(c.ndim == 2, "build_streaming: chunks must be 2-D")
        if chunk_list:
            expects(c.shape[1] == chunk_list[0].shape[1],
                    "build_streaming: chunk dim mismatch (%d vs %d)",
                    c.shape[1], chunk_list[0].shape[1])
        if params.metric == _DT.CosineExpanded:
            c = c / np.maximum(
                np.linalg.norm(c, axis=1, keepdims=True), 1e-30)
        chunk_list.append(c)
    expects(len(chunk_list) > 0, "build_streaming: empty chunk stream")
    n = sum(c.shape[0] for c in chunk_list)
    dim = chunk_list[0].shape[1]
    expects(params.n_lists <= n, "build_streaming: n_lists > n_samples")

    with spans.span("raft.build.streaming", rows=n,
                    chunks=len(chunk_list), n_lists=params.n_lists):
        obs.counter("raft.build.streaming.chunks").inc(len(chunk_list))
        obs.counter("raft.build.streaming.rows").inc(n)

        # bounded trainset drawn across the whole stream (host-side
        # draw, row order preserved: train_rows >= n degenerates to the
        # exact in-memory trainset)
        t_rows = min(n, train_rows)
        if t_rows < n:
            rng = np.random.default_rng(seed)
            sel = np.sort(rng.choice(n, t_rows, replace=False))
        else:
            sel = np.arange(n)
        train = np.empty((t_rows, dim), np.float32)
        off = pos = 0
        for c in chunk_list:
            hit = sel[(sel >= off) & (sel < off + c.shape[0])] - off
            train[pos:pos + hit.size] = c[hit]
            pos += hit.size
            off += c.shape[0]
        with obs.timed("raft.build.streaming.train"):
            centers = kmeans_balanced.build_hierarchical(
                _fetch(train), params.n_lists, params.kmeans_n_iters,
                kernel_precision=params.kmeans_kernel_precision,
                res=res)
        del train

        # pass 1 over the stream: one fused label+norm dispatch per
        # chunk, results landing host-side immediately (O(chunk) HBM)
        labels_h, norms_h = [], []
        with obs.timed("raft.build.streaming.label"):
            label_fn = _label_chunk_fn()
            for c in chunk_list:
                lbl, nrm = label_fn(_fetch(c), centers)
                labels_h.append(np.asarray(jax.device_get(lbl)))
                norms_h.append(np.asarray(jax.device_get(nrm)))

        counts = np.zeros(params.n_lists, np.int64)
        for lbl in labels_h:
            counts += np.bincount(lbl, minlength=params.n_lists)
        max_list = max(8, int(-(-int(counts.max()) // 8) * 8))
        lists_data = np.zeros((params.n_lists, max_list, dim),
                              np.float32)
        lists_idx = np.full((params.n_lists, max_list), -1, np.int32)
        lists_norms = np.zeros((params.n_lists, max_list), np.float32)

        # pass 2: host-side placement, chunk by chunk (no device work)
        cursor = np.zeros(params.n_lists, np.int64)
        base = 0
        for c, lbl, nrm in zip(chunk_list, labels_h, norms_h):
            _place_chunk(params.n_lists, cursor, c, lbl, base,
                         lists_data, lists_idx, lists_norms, nrm)
            base += c.shape[0]
    return HostIvfFlat(centers=centers, lists_data=lists_data,
                       lists_norms=lists_norms, lists_indices=lists_idx,
                       metric=params.metric, size=n, scale=1.0)


@functools.partial(jax.jit, static_argnames=("k", "sqrt", "kind"))
def _probe_scan(queries, sub_data, sub_norms, sub_indices, probe_pos,
                scale, k: int, sqrt: bool, kind: str):
    """The shared probe-major fine phase over the fetched sub-lists."""
    return _fine_phase(queries, sub_data, sub_norms, sub_indices,
                       probe_pos, scale, k, sqrt, kind)


def search(index: HostIvfFlat, queries, k: int,
           params: SearchParams = SearchParams(), res=None
           ) -> Tuple[jax.Array, jax.Array]:
    """Search a host-resident index: coarse phase on device, fetch the
    union of probed lists host→HBM, fine phase on device (the shared
    ``ivf_flat._fine_phase`` with probe ids remapped into the union).

    Peak HBM per batch: ``pow2_ceil(n_unique_probed) · max_list · dim``
    bytes (the pow2 ceiling — up to 2× the unique count — buys jit shape
    bucketing; pad slots transfer zeros) — bounded by the probe working
    set, never by the database size. Query sets above MAX_QUERY_BATCH
    are batched, each batch fetching its own union.
    """
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == index.dim, "host ivf search: dim mismatch")
    from raft_tpu.neighbors.ann_types import (MAX_QUERY_BATCH,
                                              batched_search)
    if q.shape[0] > MAX_QUERY_BATCH:
        return batched_search(
            lambda qb: search(index, qb, k, params, res=res), q)
    n_probes = min(params.n_probes, index.n_lists)
    kind = _metric_kind(index.metric)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    if index.metric == DistanceType.CosineExpanded:
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-30)

    # coarse phase on device (centers are resident)
    coarse = _coarse_scores(q, index.centers, kind)
    _, probes = lax.top_k(-coarse, n_probes)      # (nq, n_probes)
    probes_np = np.asarray(probes)
    note_probes(probes_np)     # hotness export (raft.ivf_scan.probes.*)

    # host side: union of probed lists, fetched once per batch; pad
    # slots (pow2 bucketing) transfer zeros with -1 ids, never real data
    uniq, inv = np.unique(probes_np, return_inverse=True)
    u = len(uniq)
    up = 1 << max(u - 1, 0).bit_length() if u else 1   # pow2 bucket
    pad = up - u
    if pad:
        # preallocate the padded buffers once and fill the head — one
        # copy per batch, not fancy-index + concatenate (two)
        sub_data_np = np.zeros((up,) + index.lists_data.shape[1:],
                               index.lists_data.dtype)
        np.take(index.lists_data, uniq, axis=0, out=sub_data_np[:u])
        sub_norms_np = np.zeros((up,) + index.lists_norms.shape[1:],
                                index.lists_norms.dtype)
        np.take(index.lists_norms, uniq, axis=0, out=sub_norms_np[:u])
        sub_idx_np = np.full((up,) + index.lists_indices.shape[1:], -1,
                             index.lists_indices.dtype)
        np.take(index.lists_indices, uniq, axis=0, out=sub_idx_np[:u])
    else:
        sub_data_np = index.lists_data[uniq]
        sub_norms_np = index.lists_norms[uniq]
        sub_idx_np = index.lists_indices[uniq]
    sub_data = _fetch(sub_data_np)
    sub_norms = _fetch(sub_norms_np)
    probe_pos = jnp.asarray(inv.reshape(probes_np.shape).astype(np.int32))

    d, i = _probe_scan(q, sub_data, sub_norms, _fetch(sub_idx_np),
                       probe_pos, jnp.float32(index.scale), k=k,
                       sqrt=sqrt, kind=kind)
    return _postprocess(d, index.metric), i
