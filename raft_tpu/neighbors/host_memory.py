"""Host-memory-resident ANN indexes (the reference's host-transfer axis).

Reference: the knn bench's NO_COPY / MAP_PINNED / MANAGED host-memory
strategies (``cpp/bench/neighbors/knn.cuh:380-389``) — indexes larger
than device memory live in host RAM and the working set migrates per
batch. TPU-native equivalent: inverted lists stay in **host numpy**
(51 GB of 100M×128 f32 does not fit a 16 GB v5e chip); per search batch,
only the UNION OF PROBED LISTS is shipped to HBM and scored with the
same fine-phase GEMM as the resident index. For online/small-batch
serving the union is a small fraction of the database, so HBM holds
O(probed) bytes instead of O(n).

Complements (not replaces) the sharded path: `raft_tpu.parallel.ivf`
scales by adding chips; this scales a single chip beyond its HBM.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors import ivf_flat as _ivf_flat
from raft_tpu.neighbors.ivf_flat import (
    Index,
    SearchParams,
    _coarse_scores,
    _fine_phase,
    _metric_kind,
    _postprocess,
)


@dataclass
class HostIvfFlat:
    """IVF-Flat index with device-resident centers and host-resident
    lists. Build normally (possibly shard-by-shard), then `to_host`."""

    centers: jax.Array              # (n_lists, dim) — stays on device
    lists_data: np.ndarray          # (n_lists, max_list, dim) host
    lists_norms: np.ndarray         # (n_lists, max_list) host
    lists_indices: np.ndarray       # (n_lists, max_list) host
    metric: DistanceType
    size: int
    scale: float = 1.0

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]


def to_host(index: Index) -> HostIvfFlat:
    """Demote an IVF-Flat index's lists to host memory (device keeps only
    the coarse centers, O(n_lists·dim))."""
    return HostIvfFlat(
        centers=index.centers,
        lists_data=np.asarray(index.lists_data),
        lists_norms=np.asarray(index.lists_norms),
        lists_indices=np.asarray(index.lists_indices),
        metric=index.metric, size=index.size, scale=index.scale)


@functools.partial(jax.jit, static_argnames=("k", "sqrt", "kind"))
def _probe_scan(queries, sub_data, sub_norms, sub_indices, probe_pos,
                scale, k: int, sqrt: bool, kind: str):
    """The shared probe-major fine phase over the fetched sub-lists."""
    return _fine_phase(queries, sub_data, sub_norms, sub_indices,
                       probe_pos, scale, k, sqrt, kind)


def search(index: HostIvfFlat, queries, k: int,
           params: SearchParams = SearchParams(), res=None
           ) -> Tuple[jax.Array, jax.Array]:
    """Search a host-resident index: coarse phase on device, fetch the
    union of probed lists host→HBM, fine phase on device (the shared
    ``ivf_flat._fine_phase`` with probe ids remapped into the union).

    Peak HBM per batch: ``n_unique_probed · max_list · dim`` bytes —
    bounded by the probe working set, never by the database size. Query
    sets above MAX_QUERY_BATCH are batched (each batch fetches its own
    union, keeping the bound per batch); the fetched union is padded to
    the next power of two of unique lists so jit shapes bucket instead
    of recompiling per batch.
    """
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == index.dim, "host ivf search: dim mismatch")
    from raft_tpu.neighbors.ann_types import (MAX_QUERY_BATCH,
                                              batched_search)
    if q.shape[0] > MAX_QUERY_BATCH:
        return batched_search(
            lambda qb: search(index, qb, k, params, res=res), q)
    n_probes = min(params.n_probes, index.n_lists)
    kind = _metric_kind(index.metric)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    if index.metric == DistanceType.CosineExpanded:
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-30)

    # coarse phase on device (centers are resident)
    coarse = _coarse_scores(q, index.centers, kind)
    _, probes = lax.top_k(-coarse, n_probes)      # (nq, n_probes)
    probes_np = np.asarray(probes)

    # host side: union of probed lists, fetched once per batch
    uniq, inv = np.unique(probes_np, return_inverse=True)
    u = len(uniq)
    up = 1 << max(u - 1, 0).bit_length() if u else 1   # pow2 bucket
    pad = up - u
    sel = np.concatenate([uniq, np.zeros(pad, uniq.dtype)]) if pad else uniq
    sub_data = jnp.asarray(index.lists_data[sel])
    sub_norms = jnp.asarray(index.lists_norms[sel])
    sub_idx = np.asarray(index.lists_indices[sel])
    if pad:
        sub_idx = sub_idx.copy()
        sub_idx[u:] = -1                           # pad lists never match
    probe_pos = jnp.asarray(inv.reshape(probes_np.shape).astype(np.int32))

    d, i = _probe_scan(q, sub_data, sub_norms, jnp.asarray(sub_idx),
                       probe_pos, jnp.float32(index.scale), k=k,
                       sqrt=sqrt, kind=kind)
    return _postprocess(d, index.metric), i
