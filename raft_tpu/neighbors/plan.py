"""Search plans: AOT-compiled, host-sync-free IVF serving.

Why this layer exists: the last green TPU window measured IVF-Flat at
9,769 QPS end-to-end against a 73,781 QPS chained marginal — a ~9 ms
per-batch FIXED cost (host dispatch, cap measurement, tier routing,
Python glue) swallowed 87% of the speedup the index should buy.
TPU-KNN (arxiv 2206.14286) and the serving-kernel literature agree:
TPU k-NN serving is dispatch-bound unless the whole query path is one
compiled program that the host merely enqueues.

A :class:`SearchPlan` is the serving-shape contract made explicit:

* **AOT compile** — the full fused search (coarse GEMM + top-k, probe
  inversion, list scan, merge, metric postprocess, and — when the raw
  corpus is device-resident — the exact re-rank) is lowered and
  compiled ONCE at plan-build time via ``jax.jit(...).lower(...)
  .compile()``, keyed by (index shapes, nq, k, n_probes, cap, dtypes).
  Serving calls hand the executable its buffers; no tracing, no tier
  ladder, no shape hashing on the hot path.
* **No host syncs** — :func:`warmup` measures the inverted-table cap
  once from representative queries and prefills the index's
  ``cap_cache``, so ``_ivf_scan.resolve_cap`` never round-trips on the
  serving path (counted by ``raft.ivf_scan.resolve_cap.syncs`` — a
  warmed plan must keep that counter flat, asserted in tests).
* **Async pipelined batching** — :meth:`SearchPlan.search_batched`
  enqueues sub-batches back-to-back (donating the padded query buffers
  it creates on backends that support donation) and performs a single
  terminal ``block_until_ready``; the dispatch-sync-dispatch loop of
  the cold path disappears.

Plans are cached on the index (``index.plan_cache``; hits/misses/
evictions under ``raft.plan.cache.*``, LRU-bounded by
``RAFT_TPU_PLAN_CACHE_MAX`` — the serving shape ladder churns shapes
routinely). The cold path — ``ivf_flat.search`` etc. — is
unchanged and remains the flexible/debug entry; see
docs/performance.md for the serving guide.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.obs import profiler, spans
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType


# Compile-surface rung declarations (graftlint GL012–GL014): the plan
# key's non-grid dimensions — each fixed per plan/server/index at
# build time, so the compiled-program count stays a finite product.
COMPILE_SURFACE_RUNGS = {
    "k": ("k", None,
          "result depth — fixed per plan/server at construction"),
    "cap": ("cap", None,
            "inverted-table cap — measured ONCE per (shape, params) "
            "at plan build, then cached (cap_cache)"),
    "kk": ("kk", None,
           "rescore over-fetch depth (rescore_factor * k) — fixed "
           "per plan"),
    "bins": ("bins", None,
             "scan binning — derived from (k, n_probes, list cap) at "
             "build"),
    "scan_bins": ("scan_bins", None,
                  "SearchParams.scan_bins — config, fixed per plan"),
    "slack": ("slack", None,
              "tombstone over-fetch slack — config, fixed per index"),
}


def _plan_cache_max() -> int:
    """LRU bound on ``index.plan_cache`` (``RAFT_TPU_PLAN_CACHE_MAX``,
    default 64 plans; <= 0 disables the bound). Read per call so tests
    and operators can move it at runtime. The serving shape ladder
    (``raft_tpu.serve``) makes (nq, k, n_probes, cap) churn routine —
    an unbounded cache would hold every executable ever compiled."""
    try:
        return int(os.environ.get("RAFT_TPU_PLAN_CACHE_MAX", "64"))
    except ValueError:
        return 64


def _donate_ok() -> bool:
    """Buffer donation is a no-op (with a noisy warning) on CPU; only
    request it where the backend honors it."""
    return jax.default_backend() in ("tpu", "gpu", "axon")


# the stage structure of a compiled serving program, in program order
# with static attribution weights (the fused executable cannot be
# host-timed per stage — spans.add_stage_spans marks these
# attributed=True; tools/profile_ivf_pieces.py measures the real
# split, see docs/observability.md "Diagnosing one slow query")
_PLAN_STAGES = (
    ("raft.plan.stage.coarse", 0.12),
    ("raft.plan.stage.inversion", 0.05),
    ("raft.plan.stage.scan", 0.55),
    ("raft.plan.stage.merge", 0.18),
    ("raft.plan.stage.postprocess", 0.10),
)
_RESCORE_STAGE = ("raft.plan.stage.rescore", 0.25)


@dataclass
class SearchPlan:
    """One AOT-compiled serving program for a fixed (index, nq, k,
    params) operating point. Built by :func:`build_plan` /
    :func:`warmup`; never constructed directly."""

    family: str                 # "ivf_flat" | "ivf_pq" | "ivf_bq"
    key: tuple                  # the plan-cache key (shape identity)
    nq: int
    dim: int
    k: int
    n_probes: int
    cap: int
    metric: DistanceType
    _executable: object = field(repr=False)
    _operands: tuple = field(repr=False)
    # host epilogue (d, i, q) -> (d, i), or None when the compiled
    # program already returns final results (the sync-free case)
    _host_epilogue: Optional[Callable] = field(default=None, repr=False)
    _donate: bool = False

    @property
    def sync_free(self) -> bool:
        """True when a serving call performs zero host round-trips
        (no host rescore epilogue)."""
        return self._host_epilogue is None

    def _run(self, q: jax.Array) -> Tuple[jax.Array, jax.Array]:
        d, i = self._executable(q, *self._operands)
        if self._host_epilogue is not None:
            d, i = self._host_epilogue(d, i, q)
        return d, i

    def search(self, queries, block: bool = False
               ) -> Tuple[jax.Array, jax.Array]:
        """Serve one batch of exactly ``plan.nq`` queries → (dists,
        ids), both (nq, k). The call only enqueues (async dispatch)
        unless ``block``; donation-compiled plans consume the query
        buffer, so a defensive device copy is made when the caller's
        array would otherwise be invalidated."""
        # resource profiler admission (one None read when off): a
        # sampled BLOCKING call is split into host work (everything up
        # to enqueue-complete, conversions and spans included) vs the
        # device wait — around the sync it was paying anyway
        prof = block and profiler.sampled()
        t_call = time.perf_counter()
        q = as_array(queries).astype(jnp.float32)
        expects(q.shape == (self.nq, self.dim),
                "plan.search: queries %s != plan shape (%d, %d) — build "
                "a plan per serving batch shape", q.shape, self.nq,
                self.dim)
        obs.counter("raft.plan.search.total").inc()
        obs.counter("raft.plan.search.queries").inc(self.nq)
        with spans.span("raft.plan.search", family=self.family,
                        nq=self.nq, k=self.k, n_probes=self.n_probes,
                        cap=self.cap, sync_free=self.sync_free,
                        blocked=block) as sp:
            if self._donate and isinstance(queries, jax.Array):
                q = jnp.array(q, copy=True)  # caller keeps their buffer
            t0 = time.perf_counter()
            d, i = self._run(q)
            t_enq = t_ready = 0.0
            if block:
                if prof:
                    t_enq = time.perf_counter()
                jax.block_until_ready((d, i))
                if prof:
                    t_ready = time.perf_counter()
                    spans.add_child_span(
                        profiler.SYNC_SPAN, t_enq, t_ready - t_enq,
                        program="plan",
                        host_ms=round((t_enq - t_call) * 1e3, 3),
                        device_ms=round((t_ready - t_enq) * 1e3, 3))
            # per-stage breakdown of the fused program (attributed —
            # host walls only exist for the whole executable; under
            # async dispatch this is enqueue time unless `block`)
            spans.add_stage_spans(
                self._stages(), time.perf_counter() - t0,
                family=self.family, compiled=True)
            sp.set_attr("plan_key", repr(self.key))
        if prof and block:
            # the span/trace epilogue above is host work too: charge
            # everything outside the device wait to the host half, so
            # host_s + device_s ≈ this call's whole wall
            profiler.record_sample(
                program="plan", family=self.family, rung=self.n_probes,
                host_s=(t_enq - t_call)
                + (time.perf_counter() - t_ready),
                device_s=t_ready - t_enq)
        return d, i

    def _stages(self):
        return (_PLAN_STAGES + (_RESCORE_STAGE,)
                if self._host_epilogue is not None else _PLAN_STAGES)

    def search_batched(self, queries, block: bool = True
                       ) -> Tuple[jax.Array, jax.Array]:
        """Serve an arbitrary query count through the plan's compiled
        shape: sub-batches are enqueued back-to-back with NO host sync
        between them (the padded tail buffer is plan-owned, so
        donation is always safe), then concatenated and — by default —
        synced once at the end (the single terminal barrier of the
        issue contract)."""
        from raft_tpu.neighbors.ann_types import batched_search
        q = as_array(queries).astype(jnp.float32)
        expects(q.shape[1] == self.dim, "plan.search_batched: dim "
                "mismatch (%d != %d)", q.shape[1], self.dim)
        if q.shape[0] == self.nq:
            # exact plan shape: route through search(), whose
            # defensive copy protects the caller's buffer from a
            # donation-compiled executable
            return self.search(queries, block=block)
        obs.counter("raft.plan.search.queries").inc(q.shape[0])
        # root span for the whole request; batched_search opens one
        # child span per enqueued sub-batch under it
        with spans.span("raft.plan.search_batched", family=self.family,
                        nq=int(q.shape[0]), k=self.k,
                        n_probes=self.n_probes, cap=self.cap,
                        plan_nq=self.nq, blocked=block):
            d, i = batched_search(self._run, q, max_batch=self.nq,
                                  pad_partial=True)
            if block:
                jax.block_until_ready((d, i))
        return d, i


# ---------------------------------------------------------------------------
# family builders: each returns (fn, operands, host_epilogue) where
# ``fn(q, *operands) -> (d, i)`` is the pure jittable serving program
# ---------------------------------------------------------------------------


def _flat_builder(index, k: int, params):
    from raft_tpu.neighbors import _ivf_scan
    from raft_tpu.neighbors.ann_types import list_order_auto
    from raft_tpu.neighbors.ivf_flat import (_metric_kind, _postprocess,
                                             _search_impl)
    from raft_tpu.ops.dispatch import pallas_enabled
    from raft_tpu.ops.pallas_ivf_scan import fused_mode, lc_mode

    n_probes = min(params.n_probes, index.n_lists)
    kind = _metric_kind(index.metric)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    use_pallas = pallas_enabled()

    def make(nq: int, cap: int):
        use_list = ((use_pallas or kind == "l2")
                    and (params.scan_order == "list"
                         or (params.scan_order == "auto"
                             and list_order_auto(nq, n_probes,
                                                 index.n_lists))))
        gather = _ivf_scan.gather_mode()
        lc = lc_mode()
        # fused scan+select tier (ISSUE 7): the plan compiles the ONE-
        # pallas_call fine phase — zero new steady-state compiles, the
        # ladder machinery rides the same build_plan path unchanged
        use_fused = use_pallas and fused_mode() and k <= 256
        if use_list and use_fused:
            obs.counter("raft.ivf_scan.fused.total",
                        family="ivf_flat").inc()

        def fn(q, centers, data, norms, ids, scale):
            if index.metric == DistanceType.CosineExpanded:
                q = q / jnp.maximum(
                    jnp.linalg.norm(q, axis=1, keepdims=True), 1e-30)
            if use_list:
                d, i = _ivf_scan.fused_list_search(
                    q, centers, data, norms, ids, scale, k=k,
                    n_probes=n_probes, cap=cap, bins=params.scan_bins,
                    sqrt=sqrt, kind=kind, use_pallas=use_pallas,
                    gather=gather,
                    internal_dtype=params.internal_distance_dtype,
                    lc=lc, fused=use_fused)
            else:
                d, i = _search_impl(q, centers, data, ids, norms, scale,
                                    k, n_probes, sqrt, kind=kind)
            return _postprocess(d, index.metric), i

        operands = (index.centers, index.lists_data, index.lists_norms,
                    index.lists_indices, jnp.float32(index.scale))
        key_bits = (use_list, use_pallas, use_fused, gather, lc,
                    params.scan_bins,
                    jnp.dtype(params.internal_distance_dtype).name,
                    index.lists_data.dtype.name)
        return fn, operands, None, key_bits

    return make, n_probes, kind, use_pallas


def _pq_builder(index, k: int, params):
    from raft_tpu.neighbors import _ivf_scan, ivf_pq
    from raft_tpu.neighbors.ann_types import list_order_auto
    from raft_tpu.neighbors.ivf_bq import (finish_search,
                                           resolve_raw_device)
    from raft_tpu.neighbors.ivf_flat import _metric_kind, _postprocess
    from raft_tpu.ops.dispatch import pallas_enabled

    n_probes = min(params.n_probes, index.n_lists)
    kind = _metric_kind(index.metric)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    per_cluster = index.codebook_kind == ivf_pq.CodebookGen.PER_CLUSTER
    use_pallas = pallas_enabled()
    scan_mode = params.scan_mode
    if scan_mode == "auto":
        scan_mode = "codes" if use_pallas else "reconstruct"
    rescoring = params.rescore_factor > 0 and index.raw is not None
    kk = max(params.rescore_factor, 1) * k
    dev_sqrt = sqrt if (kk == k and not rescoring) else False
    bins = params.scan_bins
    if bins == 0 and kk > k:
        max_list = index.codes.shape[1]
        bins = min(max(128, (32 * kk) // max(n_probes, 1)), max_list)
    raw_dev = (resolve_raw_device(index, params.rescore_on_device)
               if rescoring else None)

    def _device_epilogue(d, i, q, raw):
        """In-jit tail: exact device rescore (when the raw corpus is
        device-resident) or the estimator slice, then the family output
        conventions — mirrors ivf_bq.finish_search's device branch.
        The sqrt applies only when the device phase didn't already
        (``dev_sqrt``: the kk == k no-rescore case sqrt's in-scan)."""
        from raft_tpu.neighbors.ivf_bq import _exact_rescore_device
        if raw is not None:
            ex, i_out = _exact_rescore_device(raw, q, i, k=k, kind=kind)
            i_out = jnp.where(jnp.isfinite(ex), i_out, -1)
            d = jnp.where(jnp.isfinite(ex), ex, jnp.inf)
        else:
            d, i_out = d[:, :k], i[:, :k]
        if sqrt and not dev_sqrt:
            d = jnp.sqrt(jnp.maximum(d, 0.0))
        return _postprocess(d, index.metric), i_out

    def make(nq: int, cap: int):
        host_epilogue = None
        if scan_mode == "codes":
            from raft_tpu.ops.pallas_ivf_scan import fused_mode
            code_norms = ivf_pq._ensure_code_norms(index, params,
                                                   per_cluster, kind)
            gather = _ivf_scan.gather_mode()
            use_fused = fused_mode() and kk <= 256
            if use_fused:
                obs.counter("raft.ivf_scan.fused.total",
                            family="ivf_pq").inc()

            def device_phase(q, centers, centers_rot, rot, books, codes,
                             norms, ids):
                return ivf_pq._fused_code_search(
                    q, centers, centers_rot, rot, books, codes, norms,
                    ids, k=kk, n_probes=n_probes, cap=cap, bins=bins,
                    sqrt=dev_sqrt, kind=kind,
                    lut_dtype=params.lut_dtype,
                    internal_dtype=params.internal_distance_dtype,
                    per_cluster=per_cluster, gather=gather,
                    fused=use_fused)

            operands = [index.centers, index.centers_rot,
                        index.rotation_matrix, index.pq_centers,
                        index.codes, code_norms, index.lists_indices]
            key_bits = ("codes", gather, use_fused,
                        jnp.dtype(params.lut_dtype).name,
                        jnp.dtype(params.internal_distance_dtype).name,
                        bins, kk, rescoring, raw_dev is not None)
        else:
            expects(scan_mode == "reconstruct",
                    "plan: ivf_pq scan_mode %r has no serving plan "
                    "(use 'auto', 'codes' or 'reconstruct')", scan_mode)
            ivf_pq._ensure_decoded(index, per_cluster)
            use_list = (kind == "l2"
                        and (params.scan_order == "list"
                             or (params.scan_order == "auto"
                                 and list_order_auto(nq, n_probes,
                                                     index.n_lists))))

            def device_phase(q, centers, centers_rot, rot, decoded,
                             decoded_norms, ids):
                if use_list:
                    return _ivf_scan.fused_reconstruct_list_search(
                        q, centers, centers_rot, rot, decoded,
                        decoded_norms, ids, k=kk, n_probes=n_probes,
                        cap=cap, bins=bins, sqrt=dev_sqrt)
                return ivf_pq._search_impl_reconstruct(
                    q, centers, centers_rot, rot, decoded,
                    decoded_norms, ids, kk, n_probes, dev_sqrt,
                    kind=kind)

            operands = [index.centers, index.centers_rot,
                        index.rotation_matrix, index.decoded,
                        index.decoded_norms, index.lists_indices]
            key_bits = ("reconstruct", use_list, bins, kk, rescoring,
                        raw_dev is not None)

        if rescoring and raw_dev is None:
            # raw corpus exceeds the device budget: the exact re-rank
            # runs host-side per batch — correct, but NOT sync-free
            def host_epilogue(d, i, q):
                return finish_search(d, i, index.raw, q, k,
                                     metric=index.metric, rescore=True,
                                     raw_dev=None)

            fn_tail = None
        else:
            fn_tail = raw_dev

        def fn(q, *ops):
            if fn_tail is not None:
                *core, raw = ops
            else:
                core, raw = ops, None
            d, i = device_phase(q, *core)
            if host_epilogue is not None:
                return d, i   # estimator phase only; host tail follows
            return _device_epilogue(d, i, q, raw)

        if fn_tail is not None:
            operands.append(fn_tail)
        return fn, tuple(operands), host_epilogue, key_bits

    return make, n_probes, kind, (use_pallas and scan_mode == "codes")


def _bq_builder(index, k: int, params):
    from raft_tpu.neighbors import _ivf_scan, ivf_bq
    from raft_tpu.neighbors._ivf_scan import (_chunk_size,
                                              largest_divisor_at_most)
    from raft_tpu.neighbors.ivf_flat import _metric_kind
    from raft_tpu.ops.dispatch import pallas_enabled
    from raft_tpu.ops.pallas_ivf_scan import lc_mode

    n_probes = min(params.n_probes, index.n_lists)
    kind = _metric_kind(index.metric)
    use_pallas = pallas_enabled()
    rescoring = params.rescore_factor > 0 and index.raw is not None
    kk = max(params.rescore_factor, 1) * k
    max_list = index.bits.shape[1]
    raw_dev = (ivf_bq.resolve_raw_device(index, params.rescore_on_device)
               if rescoring else None)

    def make(nq: int, cap: int):
        from raft_tpu.ops.pallas_ivf_scan import fused_mode
        bins = min(params.scan_bins
                   or max(128, (32 * kk) // max(n_probes, 1)), max_list)
        chunk = min(
            _chunk_size(index.n_lists, cap, max_list),
            largest_divisor_at_most(
                index.n_lists,
                max(1, (64 << 20) // max(1, max_list * index.dim * 2))))
        gather = _ivf_scan.gather_mode()
        lc = lc_mode()
        use_fused = use_pallas and fused_mode() and kk <= 256
        if use_fused:
            obs.counter("raft.ivf_scan.fused.total",
                        family="ivf_bq").inc()

        def device_phase(q, centers, centers_rot, rot, bits, norms2,
                         scales, ids):
            if use_pallas:
                return ivf_bq._fused_bq_search_pallas(
                    q, centers, centers_rot, rot, bits, norms2, scales,
                    ids, kk=kk, bins=bins, n_probes=n_probes, cap=cap,
                    gather=gather, kind=kind, lc=lc, fused=use_fused)
            return ivf_bq._fused_bq_search(
                q, centers, centers_rot, rot, bits, norms2, scales,
                ids, kk=kk, bins=bins, n_probes=n_probes, cap=cap,
                chunk=chunk, dim=index.dim, kind=kind)

        operands = [index.centers, index.centers_rot,
                    index.rotation_matrix, index.bits, index.norms2,
                    index.scales, index.lists_indices]
        host_epilogue = None
        if rescoring and raw_dev is None:
            def host_epilogue(d, i, q):
                return ivf_bq.finish_search(d, i, index.raw, q, k,
                                            metric=index.metric,
                                            rescore=True, raw_dev=None)

        def fn(q, *ops):
            if index.metric == DistanceType.CosineExpanded:
                q = q / jnp.maximum(
                    jnp.linalg.norm(q, axis=1, keepdims=True), 1e-30)
            if raw_dev is not None:
                *core, raw = ops
            else:
                core, raw = ops, None
            d, i = device_phase(q, *core)
            if host_epilogue is not None:
                return d, i
            return _bq_device_tail(d, i, q, raw, index.metric, k, kind,
                                   rescoring)

        if raw_dev is not None:
            operands.append(raw_dev)
        key_bits = (use_pallas, use_fused, gather, lc, bins, chunk, kk,
                    rescoring, raw_dev is not None)
        return fn, tuple(operands), host_epilogue, key_bits

    return make, n_probes, kind, use_pallas


def _bq_device_tail(d, i, q, raw, metric, k: int, kind: str,
                    rescoring: bool):
    """In-jit estimator slice / device rescore + output conventions
    (finish_search's jittable branches, shared by the bq and pq plans
    when no host epilogue is needed)."""
    from raft_tpu.neighbors.ivf_bq import _exact_rescore_device
    from raft_tpu.neighbors.ivf_flat import _postprocess
    sqrt = metric in (DistanceType.L2SqrtExpanded,
                      DistanceType.L2SqrtUnexpanded)
    if rescoring and raw is not None:
        ex, i_out = _exact_rescore_device(raw, q, i, k=k, kind=kind)
        i_out = jnp.where(jnp.isfinite(ex), i_out, -1)
        d = jnp.where(jnp.isfinite(ex), ex, jnp.inf)
    else:
        d, i_out = d[:, :k], i[:, :k]
    if sqrt:
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    return _postprocess(d, metric), i_out


_BUILDERS = {}


def _resolve_builder(index):
    from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq
    if not _BUILDERS:
        _BUILDERS.update({ivf_flat.Index: ("ivf_flat", _flat_builder),
                          ivf_pq.Index: ("ivf_pq", _pq_builder),
                          ivf_bq.Index: ("ivf_bq", _bq_builder)})
    for cls, (name, builder) in _BUILDERS.items():
        if isinstance(index, cls):
            return name, builder
    expects(False, "plan: unsupported index type %s (want ivf_flat/"
            "ivf_pq/ivf_bq Index)", type(index).__name__)


def _default_params(family: str):
    from raft_tpu.neighbors import ivf_bq, ivf_flat, ivf_pq
    return {"ivf_flat": ivf_flat.SearchParams,
            "ivf_pq": ivf_pq.SearchParams,
            "ivf_bq": ivf_bq.SearchParams}[family]()


def build_plan(index, queries, k: int, params=None,
               warm: bool = True) -> SearchPlan:
    """Build (or fetch from ``index.plan_cache``) the AOT-compiled
    serving plan for this (index, nq, k, params) point.

    ``queries`` — a REPRESENTATIVE batch (real shape AND distribution:
    the inverted-table cap is measured from it, exactly like the cold
    path's first call). One host sync happens here, never on the
    serving path. With ``warm`` the compiled program is also executed
    once on the sample batch so device-side warmup (e.g. kernel
    autotuning) is off the serving path too.
    """
    from raft_tpu.neighbors import _ivf_scan
    family, builder = _resolve_builder(index)
    if params is None:
        params = _default_params(family)
    q = as_array(queries).astype(jnp.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "plan: queries must be (nq, dim=%d), got %s", index.dim,
            q.shape)
    nq = q.shape[0]
    make, n_probes, kind, use_pallas_coarse = builder(index, k, params)
    _ivf_scan.count_coarse_fallback(n_probes, use_pallas_coarse)
    with spans.span("raft.plan.build", family=family, nq=nq,
                    k=k) as bsp, \
            obs.timed("raft.plan.build", family=family):
        # the ONE measurement round-trip of the plan lifecycle: also
        # prefills index.cap_cache so the cold path (ivf_flat.search et
        # al.) is sync-free at this shape from now on
        cap = _ivf_scan.resolve_cap(index.cap_cache, q, index.centers,
                                    params, n_probes, index.n_lists,
                                    kind=kind,
                                    use_pallas=use_pallas_coarse)
        bsp.set_attrs(cap=cap, n_probes=n_probes)
        fn, operands, host_epilogue, key_bits = make(nq, cap)
        key = (family, nq, index.dim, k, n_probes, cap, kind) + key_bits
        cached = index.plan_cache.pop(key, None)
        if cached is not None:
            # re-insert at the MRU end: the plain insertion-ordered dict
            # doubles as the LRU order
            index.plan_cache[key] = cached
            obs.counter("raft.plan.cache.hits").inc()
            bsp.set_attr("plan_cache", "hit")
            return cached
        obs.counter("raft.plan.cache.misses").inc()
        obs.counter("raft.plan.build.total").inc()
        bsp.set_attr("plan_cache", "miss")
        donate = _donate_ok()
        jitted = jax.jit(fn, donate_argnums=(0,) if donate else ())
        q_struct = jax.ShapeDtypeStruct((nq, index.dim), jnp.float32)
        t_c0 = time.perf_counter()
        executable = jitted.lower(q_struct, *operands).compile()
        # compile-time ledger (resource profiler): the seconds the
        # chip sat idle while the host built this program
        profiler.note_compile("plan", time.perf_counter() - t_c0)
        plan = SearchPlan(family=family, key=key, nq=nq, dim=index.dim,
                          k=k, n_probes=n_probes, cap=cap,
                          metric=index.metric, _executable=executable,
                          _operands=operands,
                          _host_epilogue=host_epilogue, _donate=donate)
        index.plan_cache[key] = plan
        cache_max = _plan_cache_max()
        if cache_max > 0:
            while len(index.plan_cache) > cache_max:
                index.plan_cache.pop(next(iter(index.plan_cache)))
                obs.counter("raft.plan.cache.evictions").inc()
    if warm:
        plan.search(q, block=True)
    return plan


def warmup(index, queries, k: int, params=None) -> SearchPlan:
    """Serving warmup: measure the cap, AOT-compile the plan, run it
    once — after this, same-shape serving calls (plan.search OR the
    family's own ``search``) perform zero measurement syncs. Alias of
    ``build_plan(..., warm=True)`` under the name the serving guide
    uses."""
    return build_plan(index, queries, k, params, warm=True)


def cached_plans(index) -> dict:
    """The index's plan cache (key → SearchPlan) — introspection."""
    return dict(index.plan_cache)
