"""Candidate refinement (exact re-ranking).

Counterpart of the reference's refinement step for quantized indexes
(IVF-PQ results re-ranked with exact distances; in RAFT this landed as
``neighbors/refine.cuh`` shortly after the snapshot — included here
because IVF-PQ recall targets depend on it).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.core.precision import matmul_precision


def refine(dataset, queries, candidates, k: int,
           metric: DistanceType = DistanceType.L2Expanded, res=None
           ) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates`` (nq, n_cand) with exact distances against
    ``dataset`` rows; returns exact (dists, ids) top-k. Padded candidate
    slots (-1) are ignored."""
    x = as_array(dataset).astype(jnp.float32)
    q = as_array(queries).astype(jnp.float32)
    cand = as_array(candidates).astype(jnp.int32)
    vecs = x[jnp.clip(cand, 0, x.shape[0] - 1)]       # (nq, n_cand, dim)
    qq = jnp.sum(q * q, axis=1)
    vv = jnp.sum(vecs * vecs, axis=2)
    ip = jnp.einsum("qd,qcd->qc", q, vecs, preferred_element_type=jnp.float32,
                    precision=matmul_precision())
    d = jnp.maximum(qq[:, None] + vv - 2.0 * ip, 0.0)
    if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        d = jnp.sqrt(d)
    d = jnp.where(cand >= 0, d, jnp.inf)
    nd, sel = lax.top_k(-d, k)
    return -nd, jnp.take_along_axis(cand, sel, axis=1)
