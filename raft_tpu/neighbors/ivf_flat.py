"""IVF-Flat ANN index.

Reference: ``raft/neighbors/ivf_flat_types.hpp:31-275`` (index: interleaved
groups of 32 vectors for coalesced access), ``spatial/knn/detail/
ivf_flat_build.cuh:228`` (build = balanced-kmeans train + partition,
``extend`` :108) and ``ivf_flat_search.cuh:1057`` (coarse GEMM + top-k →
fused per-probe ``interleaved_scan_kernel`` with in-kernel block_sort).

TPU re-design:
  * list layout: dense padded buckets — (n_lists, max_list, dim) with the
    pad rows carrying +inf distance. The CUDA 32-interleave exists for
    warp-coalescing; the TPU analogue is simply lane-aligned contiguous
    tiles (max_list rounded to 8 sublanes) that the MXU consumes directly.
  * search: coarse = one (nq, n_lists) MXU matmul + top-k; fine = a scan
    over probe ranks — at probe rank p every query gathers its p-th list
    and scores it with one batched matmul, merging into a running top-k.
    Probed-list scoring is thus n_probes batched MXU ops with *no*
    variable-length control flow (SURVEY.md hard part (c): lists are
    bucketed/padded to static shapes).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import _l2_expanded
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core.precision import matmul_precision
from raft_tpu.util.host_sample import sample_rows, take_rows


@dataclass
class IndexParams:
    """reference ivf_flat_types.hpp index_params."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    # reference-parity default. 10 measured downstream-recall-neutral
    # for IVF-Flat (Δ < 0.005 at 16/32 probes on random AND clustered
    # 100k×64, 2026-08-01 A/B) and the EM assignment matmuls are the
    # TPU build bottleneck — the bench/build-speed paths pass 10
    # explicitly (docs/tuning.md)
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False
    # Pallas matmul tier for the balanced-EM trainer ("bf16" = one MXU
    # pass — the build-speed knob; see docs/tuning.md). None = default.
    kmeans_kernel_precision: object = None
    # list storage dtype: "float32" | "bfloat16" | "int8". The reference
    # indexes f32/f16/u8/s8 datasets (ivf_flat_types.hpp index<T>,
    # quantized dtypes via the kDivisor convention, ann_utils.cuh:79);
    # here narrower storage halves/quarters the HBM bytes the probe
    # scans gather — the search bottleneck — at a small recall cost.
    storage_dtype: str = "float32"


@dataclass
class SearchParams:
    """reference ivf_flat_types.hpp search_params.

    ``scan_order``: "probe" gathers each query's p-th list per step
    (touches only probed lists — right for small/online batches);
    "list" inverts the probe map and scores list-major (each list's rows
    read once per batch — the TPU analogue of the reference's
    sort-probes-by-cluster locality trick, ``ivf_pq_search.cuh:1058``);
    "auto" picks by the reuse factor nq·n_probes/n_lists."""

    n_probes: int = 20
    scan_order: str = "auto"
    # list-order candidate selection: 0 = auto (exact per-(list,query)
    # top-k on the XLA path; 4k strided min-bins in the Pallas kernel —
    # the TPU-KNN partial top-k, recall-gated); -1 = exact on every
    # path; >0 = explicitly that many min-bins per list
    scan_bins: int = 0
    # inverted-table width: 0 = measure once per (nq, n_probes), cache
    # on the index (warm searches are ONE dispatch); -1 = re-measure
    # every batch (drop-free); > 0 = explicit static width, never syncs.
    # Overflowing pairs shed highest-rank probes (see _ivf_scan.resolve_cap)
    probe_cap: int = 0
    # candidate score dtype the Pallas list scan carries to the merge
    # (the internal_distance_dtype role, reference ivf_pq_search.cuh:
    # 780-1004, applied to IVF-Flat): bfloat16 halves the candidate-
    # block HBM writeback+readback; final distances are still f32
    internal_distance_dtype: object = jnp.float32


@dataclass
class Index:
    """IVF-Flat index (reference ``ivf_flat::index``): cluster centers +
    padded per-list data/indices/norms. ``lists_data`` may be stored
    narrow (bf16/int8); ``scale`` dequantizes int8 (value ≈ stored *
    scale — the kDivisor convention, reference ann_utils.cuh:79-123)."""

    centers: jax.Array          # (n_lists, dim)
    lists_data: jax.Array       # (n_lists, max_list, dim)
    lists_indices: jax.Array    # (n_lists, max_list) int32, -1 = pad
    lists_norms: jax.Array      # (n_lists, max_list) squared L2 norms
    list_sizes: jax.Array       # (n_lists,) int32
    metric: DistanceType
    size: int
    scale: float = 1.0
    # measured inverted-table widths keyed (nq, n_probes) — see
    # _ivf_scan.resolve_cap (not part of index identity/serialization)
    cap_cache: dict = field(default_factory=dict, repr=False,
                            compare=False)
    # AOT-compiled serving plans keyed by shape identity — see
    # neighbors/plan.py (not index identity; not serialized)
    plan_cache: dict = field(default_factory=dict, repr=False,
                             compare=False)

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]


def _coarse_scores(queries, centers, kind: str):
    """Coarse cluster scores, smaller-is-better (reference
    select_clusters GEMM, ivf_pq_search.cuh:127): expanded L2, or
    negated dot for the ip core."""
    if kind == "ip":
        return -jnp.matmul(queries, centers.T,
                           precision=matmul_precision())
    return _l2_expanded(queries, centers, sqrt=False)


@functools.partial(jax.jit, static_argnames=("n_lists", "max_list",
                                             "compute_norms"))
def _bucketize_static(x, labels, row_ids, n_lists: int, max_list: int,
                      counts=None, compute_norms: bool = True):
    """jit-safe core of :func:`_bucketize`: scatter rows into padded
    per-list buckets of a caller-chosen static width. ``row_ids`` are
    the ids stored for each row (global ids in sharded builds); rows
    whose list position overflows ``max_list`` are dropped (cannot
    happen when max_list ≥ the true max count). ``counts`` may be
    passed by callers that already computed the per-list totals.
    ``compute_norms=False`` (integer bit-payloads — ivf_bq) skips the
    squared-norm pass and returns ``norms=None``: payloads that are
    not real numbers must ride as int32, never as f32 bitcasts whose
    NaN patterns XLA may canonicalize (ADVICE r3 #2)."""
    n, dim = x.shape
    if counts is None:
        counts = jax.ops.segment_sum(jnp.ones((n,), jnp.int32), labels,
                                     num_segments=n_lists)
    if row_ids is None:  # default ids 0..n-1, built in-trace (None is a
        row_ids = jnp.arange(n, dtype=jnp.int32)  # static arg structure)
    order = jnp.argsort(labels, stable=True)
    sorted_labels = labels[order]
    # position of each row within its list
    pos = jnp.arange(n, dtype=jnp.int32) - jnp.cumsum(
        jnp.concatenate([jnp.zeros(1, jnp.int32), counts]))[sorted_labels]
    flat_slot = jnp.where(pos < max_list, sorted_labels * max_list + pos,
                          n_lists * max_list)
    data = jnp.zeros((n_lists * max_list + 1, dim), x.dtype)
    data = data.at[flat_slot].set(x[order], mode="drop")
    idx = jnp.full((n_lists * max_list + 1,), -1, jnp.int32)
    idx = idx.at[flat_slot].set(row_ids[order].astype(jnp.int32),
                                mode="drop")
    data = data[:-1].reshape(n_lists, max_list, dim)
    idx = idx[:-1].reshape(n_lists, max_list)
    if not compute_norms:
        return data, idx, None, counts
    norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=2)
    norms = jnp.where(idx >= 0, norms, 0.0)
    return data, idx, norms, counts


def _bucketize(x, labels, n_lists: int, round_to: int = 8,
               row_ids=None, compute_norms: bool = True):
    """Scatter rows into padded per-list buckets — static-shape layout.
    The bucket width is sized from the observed max count (one host
    sync); sharded builds pre-agree a width and call the static core.
    ``row_ids`` defaults to 0..n-1 (fresh builds); extends pass the
    combined global ids."""
    counts, mx = _counts_and_max(labels, n_lists)
    max_list = int(jax.device_get(mx))
    max_list = max(round_to, (max_list + round_to - 1) // round_to * round_to)
    data, idx, norms, counts = _bucketize_static(
        x, labels, row_ids, n_lists, max_list, counts=counts,
        compute_norms=compute_norms)
    return data, idx, norms, counts


@functools.partial(jax.jit, static_argnames=("n_lists",))
def _counts_and_max(labels, n_lists: int):
    """Per-list counts + their max as ONE program (the max is the one
    host sync of the bucketing path; eager this was 4+ tiny remote
    compiles on the tunneled platform)."""
    counts = jax.ops.segment_sum(
        jnp.ones(labels.shape, jnp.int32), labels, num_segments=n_lists)
    return counts, jnp.max(counts)


_SIM_METRICS = (DistanceType.InnerProduct, DistanceType.CosineExpanded)


def _metric_kind(metric: DistanceType) -> str:
    """"l2" or "ip" — the two scoring cores (reference
    ivf_flat_search.cuh metric dispatch; cosine rides the ip core after
    row normalization, the processing.cuh preprocessing trick)."""
    return "ip" if metric in _SIM_METRICS else "l2"


def _postprocess(d, metric: DistanceType):
    """Kernel-internal scores are uniformly smaller-is-better (-sim for
    the ip core); map back to the metric's output convention: IP →
    similarities (descending), cosine → 1 − cos (ascending)."""
    if metric == DistanceType.InnerProduct:
        return -d
    if metric == DistanceType.CosineExpanded:
        return 1.0 + d
    return d


def build(dataset, params: IndexParams = IndexParams(), res=None) -> Index:
    """Train + populate (reference ivf_flat_build.cuh:228 build =
    train balanced kmeans then extend with the full dataset). Cosine
    datasets are row-normalized at build (reference processing.cuh) so
    the ip scoring core applies."""
    x = as_array(dataset).astype(jnp.float32)
    n = x.shape[0]
    expects(params.n_lists <= n, "ivf_flat.build: n_lists > n_samples")
    expects(params.metric in (DistanceType.L2Expanded,
                              DistanceType.L2SqrtExpanded,
                              DistanceType.L2Unexpanded,
                              DistanceType.L2SqrtUnexpanded,
                              DistanceType.InnerProduct,
                              DistanceType.CosineExpanded),
            "ivf_flat: unsupported metric %s", params.metric)
    obs.counter("raft.ivf_flat.build.total").inc()
    obs.counter("raft.ivf_flat.build.rows").inc(n)
    from raft_tpu.obs import spans
    # RAII scope like the reference's nvtx range in build (nvtx.hpp:69);
    # obs.timed also lands the wall time in raft.ivf_flat.build.seconds,
    # the span puts the build in the flight recorder
    with spans.span("raft.ivf_flat.build", rows=n,
                    n_lists=params.n_lists), \
            obs.timed("raft.ivf_flat.build"):
        if params.metric == DistanceType.CosineExpanded:
            x = x / jnp.maximum(
                jnp.linalg.norm(x, axis=1, keepdims=True), 1e-30)
        n_train = max(params.n_lists,
                      int(n * params.kmeans_trainset_fraction))
        # random trainset subsample — a prefix would bias centers when
        # input rows arrive ordered (reference subsamples too); drawn
        # host-side (util.host_sample): a traced choice(replace=False)
        # is an n-wide sort compile on TPU
        if n_train < n:
            trainset = take_rows(x, sample_rows(n, n_train, 0))
        else:
            trainset = x
        centers = kmeans_balanced.build_hierarchical(
            trainset, params.n_lists, params.kmeans_n_iters,
            kernel_precision=params.kmeans_kernel_precision, res=res)
        labels = kmeans_balanced.predict(x, centers, res=res)
        data, idx, norms, counts = _bucketize(x, labels, params.n_lists)
        data, norms, scale = _quantize_lists(data, norms,
                                             params.storage_dtype)
    return Index(centers=centers, lists_data=data, lists_indices=idx,
                 lists_norms=norms, list_sizes=counts,
                 metric=params.metric, size=n, scale=scale)


def _quantize_lists(data, norms, storage_dtype: str):
    """Narrow the bucketed list storage; for narrow dtypes the norms are
    recomputed over the dequantized values so probe distances stay
    self-consistent (f32 keeps the caller's precomputed norms)."""
    expects(storage_dtype in ("float32", "bfloat16", "int8"),
            "ivf_flat: storage_dtype must be float32|bfloat16|int8")
    if storage_dtype == "float32":
        return data, norms, 1.0
    if storage_dtype == "bfloat16":
        q = data.astype(jnp.bfloat16)
        return (q, jnp.sum(q.astype(jnp.float32) ** 2, axis=2), 1.0)
    # int8: one global scale (the kDivisor convention uses one fixed
    # divisor for the whole dataset)
    max_abs = float(jax.device_get(jnp.max(jnp.abs(data))))
    scale = max(max_abs, 1e-30) / 127.0
    q = jnp.clip(jnp.round(data / scale), -127, 127).astype(jnp.int8)
    deq = q.astype(jnp.float32) * scale
    return q, jnp.sum(deq * deq, axis=2), scale


def extend(index: Index, new_vectors, new_indices=None, res=None) -> Index:
    """Add vectors to an existing index (reference extend :108): assign to
    nearest centers and re-bucket. Centers are kept fixed (the reference's
    default; adaptive_centers handled at build)."""
    x_new = as_array(new_vectors).astype(jnp.float32)
    if index.metric == DistanceType.CosineExpanded:
        # build() stores row-normalized vectors for cosine; extended
        # rows must match or the ip core scores raw dot products
        x_new = x_new / jnp.maximum(
            jnp.linalg.norm(x_new, axis=1, keepdims=True), 1e-30)
    n_lists = index.n_lists
    # reconstruct flat (data, ids) view of current contents, dequantized
    # to f32 (narrow storage is re-applied after re-bucketing)
    valid = index.lists_indices >= 0
    old_data = index.lists_data.reshape(-1, index.dim)[valid.reshape(-1)]
    if old_data.dtype == jnp.int8:
        old_data = old_data.astype(jnp.float32) * index.scale
        storage = "int8"
    elif old_data.dtype == jnp.bfloat16:
        old_data = old_data.astype(jnp.float32)
        storage = "bfloat16"
    else:
        storage = "float32"
    old_ids = index.lists_indices.reshape(-1)[valid.reshape(-1)]
    if new_indices is None:
        new_ids = jnp.arange(index.size, index.size + x_new.shape[0],
                             dtype=jnp.int32)
    else:
        new_ids = as_array(new_indices).astype(jnp.int32)
    all_data = jnp.concatenate([old_data, x_new], axis=0)
    all_ids = jnp.concatenate([old_ids, new_ids])
    labels = kmeans_balanced.predict(all_data, index.centers, res=res)
    data, idx, norms, counts = _bucketize(all_data, labels, n_lists,
                                          row_ids=all_ids)
    data, norms, scale = _quantize_lists(data, norms, storage)
    return Index(centers=index.centers, lists_data=data, lists_indices=idx,
                 lists_norms=norms, list_sizes=counts, metric=index.metric,
                 size=index.size + x_new.shape[0], scale=scale)


def _score_probe(queries, qq, lists_data, lists_norms, lists_indices,
                 list_id, scale: float = 1.0, kind: str = "l2"):
    """Score one probe rank: per-query (max_list,) scores + ids — the
    fine-phase GEMM shared by single-chip and sharded searches
    (reference interleaved_scan_kernel, ivf_flat_search.cuh:665).
    Handles narrow list storage: bf16 rides the MXU directly; int8 is
    dequantized by folding ``scale`` into the accumulated product.
    ``kind`` "ip" returns negated similarities (smaller-is-better)."""
    data = lists_data[list_id]                  # (nq, max_list, dim)
    ids = lists_indices[list_id]                # (nq, max_list)
    if data.dtype == jnp.bfloat16:
        # one MXU pass on purpose: operands are already bf16
        ip = jnp.einsum("qd,qld->ql", queries.astype(jnp.bfloat16), data,
                        preferred_element_type=jnp.float32,
                        precision=lax.Precision.DEFAULT)
    elif data.dtype == jnp.int8:
        ip = scale * jnp.einsum("qd,qld->ql", queries,
                                data.astype(jnp.float32),
                                preferred_element_type=jnp.float32,
                                precision=matmul_precision())
    else:
        ip = jnp.einsum("qd,qld->ql", queries, data,
                        preferred_element_type=jnp.float32,
                        precision=matmul_precision())
    if kind == "ip":
        return jnp.where(ids >= 0, -ip, jnp.inf), ids
    d = qq[:, None] + lists_norms[list_id] - 2.0 * ip
    return jnp.where(ids >= 0, jnp.maximum(d, 0.0), jnp.inf), ids


def _fine_phase(queries, lists_data, lists_norms, lists_indices, probes,
                scale, k: int, sqrt: bool, kind: str):
    """Probe-major fine phase: scan over probe rank, each rank one
    batched GEMM + top-k merge. ``probes`` may hold list ids OR positions
    into a fetched sub-list table (the host-memory path) — the math is
    identical, which is why this is the single shared definition."""
    nq = queries.shape[0]
    n_probes = probes.shape[1]
    qq = jnp.sum(queries * queries, axis=1)

    def probe_step(carry, p):
        best_d, best_i = carry
        d, ids = _score_probe(queries, qq, lists_data, lists_norms,
                              lists_indices, probes[:, p], scale,
                              kind=kind)
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_i = jnp.concatenate([best_i, ids], axis=1)
        nd, sel = lax.top_k(-cat_d, k)
        return (-nd, jnp.take_along_axis(cat_i, sel, axis=1)), None

    init = (jnp.full((nq, k), jnp.inf, jnp.float32),
            jnp.full((nq, k), -1, jnp.int32))
    (d, i), _ = lax.scan(probe_step, init, jnp.arange(n_probes))
    if sqrt:
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    return d, i


@functools.partial(jax.jit,
                   static_argnames=("k", "n_probes", "sqrt", "kind"))
def _search_impl(queries, centers, lists_data, lists_indices, lists_norms,
                 scale, k: int, n_probes: int, sqrt: bool,
                 kind: str = "l2"):
    # ---- coarse phase (reference ivf_flat_search.cuh:1070-1147):
    # query×centers GEMM + top-k probes
    coarse = _coarse_scores(queries, centers, kind)
    _, probes = lax.top_k(-coarse, n_probes)  # (nq, n_probes)
    return _fine_phase(queries, lists_data, lists_norms, lists_indices,
                       probes, scale, k, sqrt, kind)


def search(index: Index, queries, k: int,
           params: SearchParams = SearchParams(), res=None
           ) -> Tuple[jax.Array, jax.Array]:
    """Search → (dists (nq, k), neighbor ids (nq, k)) (reference
    ivf_flat_search.cuh:1210)."""
    from raft_tpu.obs import spans
    # root span of the request (or child when batched/nested): the
    # per-request story next to the aggregate counters below
    with spans.span("raft.ivf_flat.search", k=k) as sp:
        return _search_spanned(index, queries, k, params, res, sp)


def _search_spanned(index: Index, queries, k: int, params, res, sp
                    ) -> Tuple[jax.Array, jax.Array]:
    q = as_array(queries).astype(jnp.float32)
    sp.set_attr("nq", int(q.shape[0]))
    expects(q.shape[1] == index.dim, "ivf_flat.search: dim mismatch")
    expects(params.scan_order in ("auto", "probe", "list"),
            f"ivf_flat.search: unknown scan_order {params.scan_order!r}")
    from raft_tpu.neighbors.ann_types import (MAX_QUERY_BATCH,
                                              batched_search,
                                              pin_scan_order)
    if q.shape[0] > MAX_QUERY_BATCH:
        # reference search batching (ivf_pq_search.cuh:1234 role); pin
        # "auto" choices from the FULL query count first
        pinned = pin_scan_order(params, q.shape[0], index.n_lists)
        return batched_search(
            lambda qb: search(index, qb, k, pinned, res=res), q)
    n_probes = min(params.n_probes, index.n_lists)
    sp.set_attr("n_probes", n_probes)
    # per-batch telemetry (the batched path recurses here per
    # sub-batch, so queries sum correctly across the split)
    obs.counter("raft.ivf_flat.search.queries").inc(q.shape[0])
    obs.histogram("raft.ivf_flat.search.batch_size",
                  buckets=obs.SIZE_BUCKETS).observe(q.shape[0])
    obs.histogram("raft.ivf_flat.search.n_probes",
                  buckets=obs.SIZE_BUCKETS).observe(n_probes)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    kind = _metric_kind(index.metric)
    if index.metric == DistanceType.CosineExpanded:
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-30)
    from raft_tpu.ops.dispatch import pallas_enabled
    nq = q.shape[0]
    # the XLA-tier list scan only has the l2 core; don't pay the coarse
    # phase + probe_cap host sync just to fall through to probe-major
    from raft_tpu.neighbors.ann_types import list_order_auto
    use_list = ((pallas_enabled() or kind == "l2")
                and (params.scan_order == "list"
                     or (params.scan_order == "auto"
                         and list_order_auto(nq, n_probes,
                                             index.n_lists))))
    sp.set_attr("order", "list" if use_list else "probe")
    # RAII scope at the public search (the reference's nvtx range slot);
    # covers both the list-major and probe-major paths — obs.timed opens
    # the trace range and the order-labeled latency histogram together
    with obs.timed("raft.ivf_flat.search",
                   order="list" if use_list else "probe"):
        if use_list:
            from raft_tpu.neighbors import _ivf_scan
            from raft_tpu.ops.compile_budget import run_tiers
            from raft_tpu.ops.pallas_ivf_scan import fused_mode, lc_mode
            use_pallas = pallas_enabled()
            _ivf_scan.count_coarse_fallback(n_probes, use_pallas)
            cap = _ivf_scan.resolve_cap(index.cap_cache, q,
                                        index.centers, params, n_probes,
                                        index.n_lists, kind=kind,
                                        use_pallas=use_pallas)

            def fused(pallas: bool, lc: int = 0, fz: bool = False):
                return lambda: _ivf_scan.fused_list_search(
                    q, index.centers, index.lists_data,
                    index.lists_norms, index.lists_indices,
                    jnp.float32(index.scale), k=k, n_probes=n_probes,
                    cap=cap, bins=params.scan_bins, sqrt=sqrt,
                    kind=kind, use_pallas=pallas,
                    gather=_ivf_scan.gather_mode(),
                    internal_dtype=params.internal_distance_dtype,
                    lc=lc, fused=fz)

            # compile-budget ladder, structurally simplest LAST (see
            # ops/compile_budget.py): fused scan+select (ONE pallas_call
            # fine phase, ISSUE 7) → Pallas kernel (auto or env lc) →
            # Pallas grid-per-list (loop-free body) → XLA inverted scan
            # (l2 core only) → probe-major eager scan (always
            # compiles — small per-probe programs)
            lc0 = lc_mode()
            tiers = []
            # the resident state keeps k on sublanes; past the select_k
            # bound the merge rounds stop paying for themselves — the
            # unfused tiers cover large k
            fused_on = use_pallas and fused_mode() and k <= 256
            if fused_on:
                obs.counter("raft.ivf_scan.fused.total",
                            family="ivf_flat").inc()
                obs.counter("raft.ivf_scan.fused.queries").inc(nq)
                tiers.append((f"pallas_fused_lc{lc0 or 'auto'}",
                              fused(True, lc0, True)))
            if use_pallas:
                from raft_tpu.ops.pallas_ivf_scan import _pick_lc
                tiers.append((f"pallas_lc{lc0 or 'auto'}",
                              fused(True, lc0)))
                # skip the lc=1 rung when the first tier already IS
                # lc=1 (explicitly, or via the auto pick — approximated
                # on unpadded shapes): re-submitting the same program
                # would burn a second budget on a wedged service
                auto_lc = _pick_lc(index.n_lists,
                                   index.lists_data.shape[1], cap,
                                   index.dim,
                                   index.lists_data.dtype.itemsize)
                if lc0 != 1 and not (lc0 == 0 and auto_lc == 1):
                    tiers.append(("pallas_lc1", fused(True, 1)))
            if kind == "l2":
                tiers.append(("xla_inverted", fused(False)))
            tiers.append(("probe_major", lambda: _search_impl(
                q, index.centers, index.lists_data,
                index.lists_indices, index.lists_norms,
                jnp.float32(index.scale), k, n_probes, sqrt,
                kind=kind)))
            # the key must cover EVERY static arg that changes the
            # compiled program — tier state shared across distinct
            # programs would bypass the budget for never-compiled
            # variants (r4 review finding)
            shape_key = (f"ivf_flat[{nq}x{index.dim},k={k},"
                         f"p={n_probes},cap={cap},L={index.n_lists},"
                         f"ml={index.lists_data.shape[1]},"
                         f"{kind},sqrt={sqrt},b={params.scan_bins},"
                         f"g={_ivf_scan.gather_mode()},"
                         f"idt={jnp.dtype(params.internal_distance_dtype).name},"
                         f"dt={index.lists_data.dtype.name},"
                         f"fz={fused_on}]")
            d, i = run_tiers(shape_key, tiers)
        else:
            d, i = _search_impl(q, index.centers, index.lists_data,
                                index.lists_indices, index.lists_norms,
                                jnp.float32(index.scale), k, n_probes,
                                sqrt, kind=kind)
    return _postprocess(d, index.metric), i
