"""Random ball cover k-NN.

Reference: ``raft/neighbors/ball_cover.cuh:46-131`` /
``spatial/knn/detail/ball_cover.cuh`` — √n landmarks (sampled), points
assigned to nearest landmark; search prunes whole balls with the triangle
inequality (d(q, landmark) - radius > kth-best ⇒ skip) in a two-pass
scheme; specialized haversine/2D/3D register kernels.

TPU design: landmark ordering and ball scanning become static-shape batch
ops — every query ranks all landmarks by the triangle-inequality lower
bound ``d(q, L) - radius_L`` and scans the first ``n_probes`` balls with
the same scanned gather+matmul+top-k merge as IVF-Flat. With
``n_probes = n_landmarks`` the search is exhaustive-exact; the default
probe budget covers the reference's `weight`-controlled recall knob.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import _pairwise
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.neighbors.ivf_flat import _bucketize


@dataclass
class BallCoverIndex:
    landmarks: jax.Array        # (n_l, dim)
    lists_data: jax.Array       # (n_l, max_list, dim)
    lists_indices: jax.Array    # (n_l, max_list)
    radii: jax.Array            # (n_l,) max distance landmark -> member
    metric: DistanceType
    size: int

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]


def build(dataset, metric: DistanceType = DistanceType.L2SqrtExpanded,
          n_landmarks: int = 0, res=None) -> BallCoverIndex:
    """Build the ball cover (reference BallCoverIndex + rbc_build_index):
    √n landmarks via balanced kmeans, members bucketed, ball radii kept."""
    x = as_array(dataset).astype(jnp.float32)
    n = x.shape[0]
    if n_landmarks <= 0:
        n_landmarks = max(1, int(math.isqrt(n)))
    expects(metric in (DistanceType.L2SqrtExpanded, DistanceType.Haversine,
                       DistanceType.L2SqrtUnexpanded),
            "ball_cover supports L2/haversine metrics (reference limitation)")
    landmarks = kmeans_balanced.balanced_kmeans(x, n_landmarks, res=res)
    labels = kmeans_balanced.predict(x, landmarks, res=res)
    data, idx, _, counts = _bucketize(x, labels, n_landmarks)
    mdist = _member_dists(landmarks, data, idx, metric)
    radii = jnp.max(jnp.where(idx >= 0, mdist, 0.0), axis=1)
    return BallCoverIndex(landmarks=landmarks, lists_data=data,
                          lists_indices=idx, radii=radii, metric=metric,
                          size=n)


def _member_dists(landmarks, data, idx, metric):
    def per_ball(l, vecs):
        return _pairwise(l[None, :], vecs, metric, 2.0)[0]
    return jax.vmap(per_ball)(landmarks, data)


def knn_query(index: BallCoverIndex, queries, k: int, n_probes: int = 0,
              prune: bool = True, res=None) -> Tuple[jax.Array, jax.Array]:
    """k-NN via ball cover (reference rbc_knn_query).

    Two-pass pruned search (reference ``ball_cover.cuh`` /
    ``ball_cover/registers.cuh`` triangle-inequality scheme), re-designed
    for TPU: balls are ranked by the lower bound ``d(q, L) - radius_L``
    and scanned rank-by-rank in a ``lax.while_loop`` that terminates as
    soon as **every** query's next ball is excluded by
    ``lower_bound > kth_best`` — the same per-query prune as the
    reference's pass 2, batched over the query set. With
    ``n_probes = n_landmarks`` (the default here) results are exact, yet
    typically only a few balls are scanned.

    ``n_probes`` caps the scan depth (``0`` → all landmarks when pruning,
    else the 2·√n heuristic); ``prune=False`` restores the fixed-budget
    scan.
    """
    q = as_array(queries).astype(jnp.float32)
    nq = q.shape[0]
    n_l = index.n_landmarks
    if n_probes <= 0:
        n_probes = n_l if prune else min(n_l, max(1, 2 * int(math.isqrt(n_l)) + 1))
    n_probes = min(n_probes, n_l)
    metric = index.metric

    # rank balls by triangle-inequality lower bound
    d_ql = _pairwise(q, index.landmarks, metric, 2.0)     # (nq, n_l)
    lower = jnp.maximum(d_ql - index.radii[None, :], 0.0)
    neg_lb, order = lax.top_k(-lower, n_probes)           # (nq, n_probes)
    lb_ordered = -neg_lb                                  # ascending bounds

    def probe_step(p, best_d, best_i):
        ball = order[:, p]
        vecs = index.lists_data[ball]                      # (nq, max_list, dim)
        ids = index.lists_indices[ball]
        d = jax.vmap(lambda qq, vv: _pairwise(qq[None, :], vv, metric, 2.0)[0]
                     )(q, vecs)
        d = jnp.where(ids >= 0, d, jnp.inf)
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_i = jnp.concatenate([best_i, ids], axis=1)
        nd, sel = lax.top_k(-cat_d, k)
        return -nd, jnp.take_along_axis(cat_i, sel, axis=1)

    init_d = jnp.full((nq, k), jnp.inf, jnp.float32)
    init_i = jnp.full((nq, k), -1, jnp.int32)

    if not prune:
        def scan_body(carry, p):
            return probe_step(p, *carry), None
        (d, i), _ = lax.scan(scan_body, (init_d, init_i),
                             jnp.arange(n_probes))
        return d, i

    def cond(state):
        p, best_d, _ = state
        # any query whose next-ranked ball could still hold a closer point
        live = lb_ordered[:, jnp.minimum(p, n_probes - 1)] < best_d[:, k - 1]
        return (p < n_probes) & jnp.any(live)

    def body(state):
        p, best_d, best_i = state
        best_d, best_i = probe_step(p, best_d, best_i)
        return p + 1, best_d, best_i

    _, d, i = lax.while_loop(cond, body, (jnp.int32(0), init_d, init_i))
    return d, i


def all_knn_query(index: BallCoverIndex, k: int, n_probes: int = 0, res=None
                  ) -> Tuple[jax.Array, jax.Array]:
    """All-points k-NN over the indexed dataset itself (reference
    rbc_all_knn_query)."""
    valid = index.lists_indices.reshape(-1) >= 0
    # reconstruct dataset in original order; pad slots scatter out of
    # bounds and are dropped so they can never clobber a real row
    flat = index.lists_data.reshape(-1, index.landmarks.shape[1])
    ids = index.lists_indices.reshape(-1)
    x = jnp.zeros((index.size, flat.shape[1]), flat.dtype)
    x = x.at[jnp.where(valid, ids, index.size)].set(flat, mode="drop")
    return knn_query(index, x, k, n_probes, res=res)
