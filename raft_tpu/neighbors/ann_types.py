"""Base ANN parameter structs.

Reference: ``raft/neighbors/ann_types.hpp:23-45`` — ``index_params``
(metric, metric_arg, add_data_on_build) and ``search_params`` bases that
IVF-Flat/IVF-PQ extend.
"""

from __future__ import annotations

from dataclasses import dataclass

from raft_tpu.distance.distance_types import DistanceType


@dataclass
class IndexParams:
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    add_data_on_build: bool = True


@dataclass
class SearchParams:
    pass


# Reference ivf_pq_search.cuh:1234 get_max_batch_size: searches run in
# query batches so per-batch scratch (probe tables, candidate blocks)
# stays bounded however many queries arrive at once.
MAX_QUERY_BATCH = 4096


def batched_search(search_one_batch, queries, max_batch: int = 0,
                   pad_partial: bool = False, block: bool = False):
    """Run ``search_one_batch(q_slice) -> (d, i)`` over query batches and
    concatenate (the reference's search batching loop). The ragged last
    slice is padded to the batch size (last row repeated) and trimmed, so
    every batch reuses ONE compiled shape.

    Async pipelined dispatch: nothing in this loop forces a host sync —
    every sub-batch search is ENQUEUED back-to-back (JAX async
    dispatch), the slice/pad buffers are loop-owned temporaries (safe
    for callees that donate their query operand, e.g. an AOT
    :class:`~raft_tpu.neighbors.plan.SearchPlan` executable), and the
    terminal concatenate is the only consumer. ``block`` adds the
    single terminal ``block_until_ready`` barrier — the serving-loop
    contract: one sync per request, however many sub-batches it split
    into. Callees must keep their own path sync-free (warm plans /
    cached caps); a cap measurement inside the callee would serialize
    the pipeline (counted by ``raft.ivf_scan.resolve_cap.syncs``).

    ``pad_partial``: also pad a FULL query set smaller than
    ``max_batch`` up to the batch size (fixed-shape callees — compiled
    plan executables); default keeps the historic pass-through.
    """
    import jax
    import jax.numpy as jnp

    from raft_tpu import obs
    from raft_tpu.obs import spans

    mb = max_batch if max_batch > 0 else MAX_QUERY_BATCH
    nq = queries.shape[0]
    if nq <= mb and not (pad_partial and nq < mb):
        out = search_one_batch(queries)
        if block:
            jax.block_until_ready(out)
        return out
    outs = []
    n_sub = 0
    for s in range(0, nq, mb):
        qb = queries[s:s + mb]
        short = mb - qb.shape[0]
        n_sub += 1
        # one child span per enqueued sub-batch: the request trace
        # shows the split (same trace_id as the enclosing root span;
        # durations are enqueue walls — nothing here syncs)
        with spans.span("raft.ann.sub_batch", index=n_sub - 1,
                        offset=s, rows=int(qb.shape[0]), padded=short):
            if short:
                # pad with REAL rows from earlier batches when
                # available: a tail padded with one repeated row
                # concentrates its probes on that row's lists and can
                # overflow a pinned/cached inverted-table cap, shedding
                # real probes; earlier rows keep the pad
                # in-distribution (their results are discarded). A
                # single short batch cycles its own rows.
                if s >= short:
                    fill = queries[s - short:s]
                else:
                    reps = -(-short // qb.shape[0])
                    fill = jnp.tile(qb, (reps, 1))[:short]
                d, i = search_one_batch(
                    jnp.concatenate([qb, fill], axis=0))
                outs.append((d[:mb - short], i[:mb - short]))
            else:
                outs.append(search_one_batch(qb))
    obs.counter("raft.ann.batched_search.sub_batches").inc(n_sub)
    d, i = zip(*outs)
    if len(outs) == 1:
        d, i = d[0], i[0]
    else:
        d, i = jnp.concatenate(d, axis=0), jnp.concatenate(i, axis=0)
    if block:
        jax.block_until_ready((d, i))
    return d, i


def pin_scan_order(params, nq: int, n_lists: int):
    """Resolve ``scan_order='auto'`` from the FULL query count (the
    shared batching pin for ivf_flat/ivf_pq): every batch then takes the
    same scan path, keeping batched results identical to unbatched."""
    import dataclasses

    if getattr(params, "scan_order", None) != "auto":
        return params
    n_pr = min(params.n_probes, n_lists)
    so = "list" if list_order_auto(nq, n_pr, n_lists) else "probe"
    return dataclasses.replace(params, scan_order=so)


def list_order_auto(nq: int, n_probes: int, n_lists: int) -> bool:
    """The single definition of the probe-major vs list-major auto
    heuristic (reuse factor nq·n_probes/n_lists): shared by the inline
    scan dispatch and the query-batching pin so batched and unbatched
    searches always take the same path."""
    return nq >= 64 and nq * n_probes >= 4 * n_lists
