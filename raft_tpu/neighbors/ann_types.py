"""Base ANN parameter structs.

Reference: ``raft/neighbors/ann_types.hpp:23-45`` — ``index_params``
(metric, metric_arg, add_data_on_build) and ``search_params`` bases that
IVF-Flat/IVF-PQ extend.
"""

from __future__ import annotations

from dataclasses import dataclass

from raft_tpu.distance.distance_types import DistanceType


@dataclass
class IndexParams:
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    add_data_on_build: bool = True


@dataclass
class SearchParams:
    pass
