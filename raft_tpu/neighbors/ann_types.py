"""Base ANN parameter structs.

Reference: ``raft/neighbors/ann_types.hpp:23-45`` — ``index_params``
(metric, metric_arg, add_data_on_build) and ``search_params`` bases that
IVF-Flat/IVF-PQ extend.
"""

from __future__ import annotations

from dataclasses import dataclass

from raft_tpu.distance.distance_types import DistanceType


@dataclass
class IndexParams:
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    add_data_on_build: bool = True


@dataclass
class SearchParams:
    pass


# Reference ivf_pq_search.cuh:1234 get_max_batch_size: searches run in
# query batches so per-batch scratch (probe tables, candidate blocks)
# stays bounded however many queries arrive at once.
MAX_QUERY_BATCH = 4096


def batched_search(search_one_batch, queries, max_batch: int = 0):
    """Run ``search_one_batch(q_slice) -> (d, i)`` over query batches and
    concatenate (the reference's search batching loop). The ragged last
    slice is padded to the batch size (last row repeated) and trimmed, so
    every batch reuses ONE compiled shape."""
    import jax.numpy as jnp

    mb = max_batch if max_batch > 0 else MAX_QUERY_BATCH
    nq = queries.shape[0]
    if nq <= mb:
        return search_one_batch(queries)
    outs = []
    for s in range(0, nq, mb):
        qb = queries[s:s + mb]
        short = mb - qb.shape[0]
        if short:
            fill = jnp.broadcast_to(qb[-1:], (short,) + qb.shape[1:])
            d, i = search_one_batch(jnp.concatenate([qb, fill], axis=0))
            outs.append((d[:mb - short], i[:mb - short]))
        else:
            outs.append(search_one_batch(qb))
    d, i = zip(*outs)
    return jnp.concatenate(d, axis=0), jnp.concatenate(i, axis=0)


def pin_scan_order(params, nq: int, n_lists: int):
    """Resolve ``scan_order='auto'`` from the FULL query count (the
    shared batching pin for ivf_flat/ivf_pq): every batch then takes the
    same scan path, keeping batched results identical to unbatched."""
    import dataclasses

    if getattr(params, "scan_order", None) != "auto":
        return params
    n_pr = min(params.n_probes, n_lists)
    so = "list" if list_order_auto(nq, n_pr, n_lists) else "probe"
    return dataclasses.replace(params, scan_order=so)


def list_order_auto(nq: int, n_probes: int, n_lists: int) -> bool:
    """The single definition of the probe-major vs list-major auto
    heuristic (reuse factor nq·n_probes/n_lists): shared by the inline
    scan dispatch and the query-batching pin so batched and unbatched
    searches always take the same path."""
    return nq >= 64 and nq * n_probes >= 4 * n_lists
