"""Query/index preprocessing for metrics the fused kernels lack.

Reference: ``spatial/knn/detail/processing.{hpp,cuh}`` — FAISS only
speaks L2/IP, so cosine queries are row-normalized and correlation
queries additionally mean-centered before search, then distances are
post-processed. The TPU fused kNN kernel (``ops/pallas_fused_knn.py``)
has the same l2|ip vocabulary, so the same trick extends it to
cosine/correlation: preprocess both sides → search IP (largest) →
distance = 1 − similarity."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType

_EPS = 1e-12


def preprocess_rows(x, metric: DistanceType):
    """Row-transform ``x`` so inner product equals the metric's
    similarity: cosine → L2-normalize (CosineMetricProcessor); correlation
    → mean-center then L2-normalize (CorrelationMetricProcessor)."""
    x = as_array(x).astype(jnp.float32)
    if metric == DistanceType.CorrelationExpanded:
        x = x - jnp.mean(x, axis=1, keepdims=True)
    norms = jnp.linalg.norm(x, axis=1, keepdims=True)
    return x / jnp.maximum(norms, _EPS)


def postprocess_distances(sims, metric: DistanceType):
    """Similarity → distance: both cosine and correlation report
    ``1 − similarity`` (the reference's post-search epilogue)."""
    del metric
    return 1.0 - sims


def fused_knn_preprocessed(db, queries, k: int, metric: DistanceType
                           ) -> Tuple[jax.Array, jax.Array]:
    """Cosine/correlation k-NN through the fused IP kernel."""
    from raft_tpu.ops.pallas_fused_knn import fused_knn_pallas
    if metric not in (DistanceType.CosineExpanded,
                      DistanceType.CorrelationExpanded):
        raise ValueError(
            f"fused_knn_preprocessed: metric {metric} needs no preprocessing"
            " (use brute_force_knn)")
    dbp = preprocess_rows(db, metric)
    qp = preprocess_rows(queries, metric)
    sims, idx = fused_knn_pallas(qp, dbp, k, metric="ip")
    return postprocess_distances(sims, metric), idx
