"""``timed`` — one name, two observability planes.

``timed("raft.ivf_pq.search", mode="codes")`` opens a
``core.trace.range`` named ``raft.ivf_pq.search`` (so the scope shows
up in xprof/Perfetto exactly where the wall-time went) AND observes the
elapsed wall seconds into the histogram ``raft.ivf_pq.search.seconds``
with the given labels. Metrics and profiler annotations therefore share
ONE ``raft.<module>.<op>`` taxonomy: a histogram spike names the trace
range to open in the profile, and vice versa.

Usable as a context manager or a decorator::

    with obs.timed("raft.kmeans.fit"):
        ...

    @obs.timed("raft.ivf_pq.build")
    def build(...): ...

Wall-clock caveat (docs/observability.md): under JAX async dispatch the
scope measures host time in the block — enqueue time unless the block
synchronizes (fetches a value). The instrumented raft_tpu call sites
all sit at natural sync points (public API boundaries that return
materialized results or cache a host-side decision), so the histograms
track end-to-end service time, the quantity a serving dashboard wants.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

from raft_tpu.obs import registry as _registry


class timed:
    """Context manager / decorator timing a scope into
    ``<name>.seconds`` and a trace range named ``name``."""

    __slots__ = ("name", "labels", "registry", "_t0", "_range")

    def __init__(self, name: str,
                 registry: Optional[_registry.MetricsRegistry] = None,
                 **labels):
        self.name = name
        self.labels = labels
        self.registry = registry if registry is not None \
            else _registry.REGISTRY
        self._t0 = 0.0
        self._range = None

    def __enter__(self) -> "timed":
        # trace ranges stay on even when metrics are off: the xprof
        # annotation costs nothing without a profiler session and is
        # gated by trace.enable_tracing independently
        from raft_tpu.core import trace
        self._range = trace.range(self.name)
        self._range.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        rng, self._range = self._range, None
        try:
            self.registry.histogram(self.name + ".seconds",
                                    **self.labels).observe(dt)
        finally:
            if rng is not None:
                rng.__exit__(exc_type, exc, tb)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            # fresh instance per call: the decorator form must be
            # re-entrant (recursion, threads)
            with timed(self.name, self.registry, **self.labels):
                return fn(*args, **kwargs)
        return wrapper
