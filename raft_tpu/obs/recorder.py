"""Flight recorder — the last N request traces, always on.

A bounded ring buffer of completed span traces
(:mod:`raft_tpu.obs.spans` hands every finished root trace here): the
per-request story behind the aggregate metrics — per-stage breakdown,
plan/cap attributes, sub-batch and shard spans — kept cheap enough to
leave on in production (a deque append under a lock per REQUEST, not
per span; zero work when no spans are opened, nothing at all under
``RAFT_TPU_TRACE=0``).

Knobs (read at construction):

* ``RAFT_TPU_TRACE_RING`` — ring capacity in traces (default 128).
* ``RAFT_TPU_TRACE_SLOW_MS`` — slow-request threshold; traces at or
  above it are ALSO kept in a separate slow ring (so a burst of fast
  requests cannot evict the interesting one) and logged through
  ``core.logger`` at WARN (default 250 ms; runtime override via
  :meth:`FlightRecorder.set_slow_threshold_ms`).

Exports: :meth:`FlightRecorder.to_json` (the ``/debug/requests``
body) and :func:`to_chrome_trace` — any recorded trace as Chrome
trace-event JSON, loadable in Perfetto / ``chrome://tracing``.

Cross-process stitching (ISSUE 16): one routed request leaves trace
FRAGMENTS in several recorders — the router's ``raft.fleet.route``
root in its process, each replica's ``raft.serve.request`` root
(remote-parented, same trace id) in its own.
:meth:`FlightRecorder.fragments` finds every local fragment of a
trace id, :func:`fetch_fragments` pulls a peer endpoint's fragments
over ``/debug/requests?trace=<id>&all=1`` (estimating clock skew from
the scrape round trip), and :func:`stitch_chrome_trace` merges them
into ONE Chrome trace — one ``pid`` lane per fragment/instance,
reusing the rank→pid convention, with each lane's estimated skew
stamped as ``clock_skew_ms`` on its events rather than silently
baked into the timestamps. :func:`stitch_from_endpoints` is the
one-call form the debug endpoint serves at ``/fleet/trace``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.obs import registry as _registry

__all__ = ["FlightRecorder", "RECORDER", "to_chrome_trace",
           "fetch_fragments", "stitch_chrome_trace",
           "stitch_from_endpoints"]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class FlightRecorder:
    """Bounded ring of completed request traces + slow-query log."""

    def __init__(self, capacity: Optional[int] = None,
                 slow_ms: Optional[float] = None,
                 slow_capacity: int = 32,
                 registry: Optional[object] = None):
        if capacity is None:
            capacity = int(os.environ.get("RAFT_TPU_TRACE_RING", "128"))
        if slow_ms is None:
            slow_ms = _env_float("RAFT_TPU_TRACE_SLOW_MS", 250.0)
        self.capacity = max(1, capacity)
        self.slow_ms = slow_ms
        self._ring = collections.deque(maxlen=self.capacity)
        self._slow = collections.deque(maxlen=max(1, slow_capacity))
        self._lock = threading.Lock()
        self._registry = registry if registry is not None \
            else _registry.REGISTRY
        self.recorded_total = 0

    # -- ingest ------------------------------------------------------------
    @staticmethod
    def _is_request(trace: dict) -> bool:
        """Slow-query handling applies to REQUEST traces — search-path
        roots (or anything tagged ``request=True``). A build or a
        kmeans fit is expected to take seconds; warning on every one
        would bury the signal the slow-query log exists for."""
        name = trace.get("name", "")
        return (name.endswith(".search") or ".search" in name
                or bool(trace.get("attrs", {}).get("request")))

    def record(self, trace: dict) -> None:
        # wall clock by design (GL005): black-box dumps and history
        # frames are correlated ACROSS processes by the doctor — every
        # trace entering the rings carries an absolute arrival stamp
        # (spans only carry relative durations)
        trace.setdefault("ts_unix", time.time())  # graftlint: disable=GL005
        dur = trace.get("duration_ms", 0.0)
        slow = dur >= self.slow_ms and self._is_request(trace)
        with self._lock:
            self._ring.append(trace)
            if slow:
                self._slow.append(trace)
            self.recorded_total += 1
        self._registry.counter("raft.obs.recorder.traces").inc()
        if slow:
            self._registry.counter("raft.obs.recorder.slow_traces").inc()
            # the slow-query log line: enough to find the full trace in
            # the ring (or the endpoint) without grepping spans
            from raft_tpu.core.logger import get_logger
            attrs = trace.get("attrs", {})
            get_logger("obs").warn(
                "slow request %s (%s): %.1f ms >= %.1f ms threshold "
                "(%d spans%s)", trace.get("trace_id"), trace.get("name"),
                dur, self.slow_ms, len(trace.get("spans", ())),
                f", attrs={attrs}" if attrs else "")

    # -- knobs -------------------------------------------------------------
    def set_slow_threshold_ms(self, ms: float) -> None:
        self.slow_ms = float(ms)

    # -- query -------------------------------------------------------------
    def requests(self, n: Optional[int] = None) -> List[dict]:
        """Most-recent-first recorded traces (up to ``n``)."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:n] if n is not None else out

    def slow_requests(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._slow)
        out.reverse()
        return out[:n] if n is not None else out

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            for t in reversed(self._ring):
                if t.get("trace_id") == trace_id:
                    return t
            for t in reversed(self._slow):
                if t.get("trace_id") == trace_id:
                    return t
        return None

    def fragments(self, trace_id: str) -> List[dict]:
        """EVERY recorded fragment of ``trace_id``, oldest first. A
        remote-parented trace shares its id with the upstream root, so
        one routed request can leave several fragments even in one
        recorder (router root + N in-process replica roots). Dedupes
        ring/slow by object identity."""
        with self._lock:
            seen_ids, out = set(), []
            for t in list(self._ring) + list(self._slow):
                if t.get("trace_id") == trace_id and id(t) not in seen_ids:
                    seen_ids.add(id(t))
                    out.append(t)
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- export ------------------------------------------------------------
    def to_json(self, n: Optional[int] = None) -> dict:
        """The structured ``/debug/requests`` dump: recorder config +
        most-recent-first traces (+ the slow ring's trace ids, so a
        reader can tell which survived because they were slow)."""
        with self._lock:
            traces = list(self._ring)
            slow_ids = [t.get("trace_id") for t in self._slow]
        traces.reverse()
        if n is not None:
            traces = traces[:n]
        return {
            "capacity": self.capacity,
            "slow_threshold_ms": self.slow_ms,
            "recorded_total": self.recorded_total,
            "slow_trace_ids": slow_ids,
            # wall clock at export: the remote stitcher estimates this
            # process's clock skew from it (see fetch_fragments)
            "now_unix": time.time(),  # graftlint: disable=GL005
            "traces": traces,
        }


def to_chrome_trace(trace: dict) -> dict:
    """One recorded trace as Chrome trace-event JSON (the object form:
    ``{"traceEvents": [...]}`` — loads in Perfetto and
    ``chrome://tracing``). Spans become complete (``ph="X"``) events
    with microsecond ``ts``/``dur``; a span's ``rank`` attribute (the
    shard spans of ``parallel/ivf.py``) maps to the event ``pid`` so
    per-rank rows group visually, everything else rides in ``args``."""
    base_us = float(trace.get("start_unix", 0.0)) * 1e6
    events = [{
        "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
        "args": {"name": f"raft_tpu {trace.get('trace_id', '')}"},
    }]
    for sp in trace.get("spans", ()):
        attrs = sp.get("attrs", {})
        try:
            pid = int(attrs.get("rank", 0))
        except (TypeError, ValueError):
            pid = 0
        args = {"trace_id": trace.get("trace_id"),
                "span_id": sp.get("span_id")}
        if sp.get("parent_id"):
            args["parent_id"] = sp["parent_id"]
        args.update(attrs)
        events.append({
            "name": sp.get("name", ""),
            "cat": "raft",
            "ph": "X",
            "ts": base_us + sp.get("t_start_ms", 0.0) * 1e3,
            "dur": max(0.0, sp.get("duration_ms", 0.0) * 1e3),
            "pid": pid,
            # fold the 64-bit thread ident into the int32 range chrome
            # tooling expects
            "tid": int(sp.get("tid", 0)) % (1 << 31),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace.get("trace_id"),
                          "name": trace.get("name"),
                          "duration_ms": trace.get("duration_ms")}}


def stitch_chrome_trace(fragments: Sequence[dict],
                        instances: Optional[Sequence[str]] = None,
                        skews_s: Optional[Sequence[float]] = None
                        ) -> dict:
    """Merge the fragments of ONE distributed trace into a single
    Chrome trace. Each fragment gets its own ``pid`` lane (named after
    ``instances[i]`` when given — the replica/router endpoint it came
    from — reusing the rank→pid lane convention of
    :func:`to_chrome_trace`). ``skews_s[i]`` is the estimated clock
    skew of fragment *i*'s process (remote − local, seconds): it is
    APPLIED to that lane's timestamps so the lanes line up, and
    stamped on each of its events as ``clock_skew_ms`` so a reader
    can tell corrected time from measured time. Fragment order is by
    ``start_unix`` (skew-corrected), so the upstream root lane comes
    first."""
    frags = list(fragments)
    n = len(frags)
    insts = list(instances) if instances is not None else [""] * n
    skews = list(skews_s) if skews_s is not None else [0.0] * n
    order = sorted(
        range(n),
        key=lambda i: float(frags[i].get("start_unix", 0.0)) - skews[i])
    trace_id = frags[order[0]].get("trace_id", "") if n else ""
    events: List[dict] = []
    total_spans = 0
    for lane, i in enumerate(order):
        frag, inst, skew = frags[i], insts[i], skews[i]
        pid = lane
        label = inst or frag.get("name", "") or f"fragment-{lane}"
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{label} {frag.get('trace_id', '')}"},
        })
        base_us = (float(frag.get("start_unix", 0.0)) - skew) * 1e6
        skew_ms = round(skew * 1e3, 3)
        for sp in frag.get("spans", ()):
            args = {"trace_id": frag.get("trace_id"),
                    "span_id": sp.get("span_id")}
            if sp.get("parent_id"):
                args["parent_id"] = sp["parent_id"]
            if inst:
                args["instance"] = inst
            if skew_ms:
                args["clock_skew_ms"] = skew_ms
            args.update(sp.get("attrs", {}))
            events.append({
                "name": sp.get("name", ""),
                "cat": "raft",
                "ph": "X",
                "ts": base_us + sp.get("t_start_ms", 0.0) * 1e3,
                "dur": max(0.0, sp.get("duration_ms", 0.0) * 1e3),
                "pid": pid,
                "tid": int(sp.get("tid", 0)) % (1 << 31),
                "args": args,
            })
            total_spans += 1
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace_id,
                          "fragments": n,
                          "spans": total_spans,
                          "stitched": True}}


def fetch_fragments(base_url: str, trace_id: str,
                    timeout_s: float = 2.0
                    ) -> Tuple[List[dict], float]:
    """Pull one peer endpoint's fragments of ``trace_id`` over
    ``GET /debug/requests?trace=<id>&all=1`` → ``(fragments,
    skew_s)``. The skew estimate is the peer's export-time wall clock
    minus the midpoint of our request round trip (the standard
    NTP-style offset under a symmetric-delay assumption) — good to
    ~half the round trip, which is plenty to line up millisecond
    span lanes. Network errors raise (the caller decides whether a
    missing peer is fatal)."""
    url = (f"{base_url.rstrip('/')}/debug/requests"
           f"?trace={trace_id}&all=1")
    # wall-clock midpoint wants the same clock the peer exports
    t0 = time.time()  # graftlint: disable=GL005
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        body = json.loads(resp.read().decode("utf-8"))
    t1 = time.time()  # graftlint: disable=GL005
    remote_now = float(body.get("now_unix", (t0 + t1) / 2.0))
    skew_s = remote_now - (t0 + t1) / 2.0
    return list(body.get("fragments", ())), skew_s


def stitch_from_endpoints(trace_id: str,
                          peers: Dict[str, str],
                          recorder: Optional[FlightRecorder] = None,
                          timeout_s: float = 2.0) -> dict:
    """One-call stitch: local fragments (from ``recorder``, default
    the process recorder) + every peer endpoint's fragments, merged
    by :func:`stitch_chrome_trace`. ``peers`` maps instance name →
    base URL. Unreachable peers contribute nothing (their absence is
    recorded in ``otherData["unreachable"]``) — a stitch must degrade,
    not fail, when a replica is down."""
    # lazy import: spans depends on recorder (one-way), so the stitch
    # span is opened via the module registry rather than a top import
    from raft_tpu.obs import spans as _spans
    with _spans.span("raft.obs.fed.stitch", peers=len(peers)) as sp:
        frags: List[dict] = []
        insts: List[str] = []
        skews: List[float] = []
        rec = recorder if recorder is not None else RECORDER
        for f in rec.fragments(trace_id):
            frags.append(f)
            insts.append("local")
            skews.append(0.0)
        unreachable = []
        for name, url in sorted(peers.items()):
            try:
                peer_frags, skew = fetch_fragments(
                    url, trace_id, timeout_s=timeout_s)
            except Exception:
                unreachable.append(name)
                continue
            for f in peer_frags:
                frags.append(f)
                insts.append(name)
                skews.append(skew)
        out = stitch_chrome_trace(frags, instances=insts,
                                  skews_s=skews)
        out["otherData"]["unreachable"] = unreachable
        sp.set_attrs(fragments=len(frags),
                     unreachable=len(unreachable))
    return out


# the process-wide recorder every completed root span lands in; tests
# can build private instances
RECORDER = FlightRecorder()
