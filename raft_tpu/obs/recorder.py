"""Flight recorder — the last N request traces, always on.

A bounded ring buffer of completed span traces
(:mod:`raft_tpu.obs.spans` hands every finished root trace here): the
per-request story behind the aggregate metrics — per-stage breakdown,
plan/cap attributes, sub-batch and shard spans — kept cheap enough to
leave on in production (a deque append under a lock per REQUEST, not
per span; zero work when no spans are opened, nothing at all under
``RAFT_TPU_TRACE=0``).

Knobs (read at construction):

* ``RAFT_TPU_TRACE_RING`` — ring capacity in traces (default 128).
* ``RAFT_TPU_TRACE_SLOW_MS`` — slow-request threshold; traces at or
  above it are ALSO kept in a separate slow ring (so a burst of fast
  requests cannot evict the interesting one) and logged through
  ``core.logger`` at WARN (default 250 ms; runtime override via
  :meth:`FlightRecorder.set_slow_threshold_ms`).

Exports: :meth:`FlightRecorder.to_json` (the ``/debug/requests``
body) and :func:`to_chrome_trace` — any recorded trace as Chrome
trace-event JSON, loadable in Perfetto / ``chrome://tracing``.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import List, Optional

from raft_tpu.obs import registry as _registry

__all__ = ["FlightRecorder", "RECORDER", "to_chrome_trace"]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class FlightRecorder:
    """Bounded ring of completed request traces + slow-query log."""

    def __init__(self, capacity: Optional[int] = None,
                 slow_ms: Optional[float] = None,
                 slow_capacity: int = 32,
                 registry: Optional[object] = None):
        if capacity is None:
            capacity = int(os.environ.get("RAFT_TPU_TRACE_RING", "128"))
        if slow_ms is None:
            slow_ms = _env_float("RAFT_TPU_TRACE_SLOW_MS", 250.0)
        self.capacity = max(1, capacity)
        self.slow_ms = slow_ms
        self._ring = collections.deque(maxlen=self.capacity)
        self._slow = collections.deque(maxlen=max(1, slow_capacity))
        self._lock = threading.Lock()
        self._registry = registry if registry is not None \
            else _registry.REGISTRY
        self.recorded_total = 0

    # -- ingest ------------------------------------------------------------
    @staticmethod
    def _is_request(trace: dict) -> bool:
        """Slow-query handling applies to REQUEST traces — search-path
        roots (or anything tagged ``request=True``). A build or a
        kmeans fit is expected to take seconds; warning on every one
        would bury the signal the slow-query log exists for."""
        name = trace.get("name", "")
        return (name.endswith(".search") or ".search" in name
                or bool(trace.get("attrs", {}).get("request")))

    def record(self, trace: dict) -> None:
        dur = trace.get("duration_ms", 0.0)
        slow = dur >= self.slow_ms and self._is_request(trace)
        with self._lock:
            self._ring.append(trace)
            if slow:
                self._slow.append(trace)
            self.recorded_total += 1
        self._registry.counter("raft.obs.recorder.traces").inc()
        if slow:
            self._registry.counter("raft.obs.recorder.slow_traces").inc()
            # the slow-query log line: enough to find the full trace in
            # the ring (or the endpoint) without grepping spans
            from raft_tpu.core.logger import get_logger
            attrs = trace.get("attrs", {})
            get_logger("obs").warn(
                "slow request %s (%s): %.1f ms >= %.1f ms threshold "
                "(%d spans%s)", trace.get("trace_id"), trace.get("name"),
                dur, self.slow_ms, len(trace.get("spans", ())),
                f", attrs={attrs}" if attrs else "")

    # -- knobs -------------------------------------------------------------
    def set_slow_threshold_ms(self, ms: float) -> None:
        self.slow_ms = float(ms)

    # -- query -------------------------------------------------------------
    def requests(self, n: Optional[int] = None) -> List[dict]:
        """Most-recent-first recorded traces (up to ``n``)."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out[:n] if n is not None else out

    def slow_requests(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._slow)
        out.reverse()
        return out[:n] if n is not None else out

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            for t in reversed(self._ring):
                if t.get("trace_id") == trace_id:
                    return t
            for t in reversed(self._slow):
                if t.get("trace_id") == trace_id:
                    return t
        return None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- export ------------------------------------------------------------
    def to_json(self, n: Optional[int] = None) -> dict:
        """The structured ``/debug/requests`` dump: recorder config +
        most-recent-first traces (+ the slow ring's trace ids, so a
        reader can tell which survived because they were slow)."""
        with self._lock:
            traces = list(self._ring)
            slow_ids = [t.get("trace_id") for t in self._slow]
        traces.reverse()
        if n is not None:
            traces = traces[:n]
        return {
            "capacity": self.capacity,
            "slow_threshold_ms": self.slow_ms,
            "recorded_total": self.recorded_total,
            "slow_trace_ids": slow_ids,
            "traces": traces,
        }


def to_chrome_trace(trace: dict) -> dict:
    """One recorded trace as Chrome trace-event JSON (the object form:
    ``{"traceEvents": [...]}`` — loads in Perfetto and
    ``chrome://tracing``). Spans become complete (``ph="X"``) events
    with microsecond ``ts``/``dur``; a span's ``rank`` attribute (the
    shard spans of ``parallel/ivf.py``) maps to the event ``pid`` so
    per-rank rows group visually, everything else rides in ``args``."""
    base_us = float(trace.get("start_unix", 0.0)) * 1e6
    events = [{
        "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
        "args": {"name": f"raft_tpu {trace.get('trace_id', '')}"},
    }]
    for sp in trace.get("spans", ()):
        attrs = sp.get("attrs", {})
        try:
            pid = int(attrs.get("rank", 0))
        except (TypeError, ValueError):
            pid = 0
        args = {"trace_id": trace.get("trace_id"),
                "span_id": sp.get("span_id")}
        if sp.get("parent_id"):
            args["parent_id"] = sp["parent_id"]
        args.update(attrs)
        events.append({
            "name": sp.get("name", ""),
            "cat": "raft",
            "ph": "X",
            "ts": base_us + sp.get("t_start_ms", 0.0) * 1e3,
            "dur": max(0.0, sp.get("duration_ms", 0.0) * 1e3),
            "pid": pid,
            # fold the 64-bit thread ident into the int32 range chrome
            # tooling expects
            "tid": int(sp.get("tid", 0)) % (1 << 31),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace.get("trace_id"),
                          "name": trace.get("name"),
                          "duration_ms": trace.get("duration_ms")}}


# the process-wide recorder every completed root span lands in; tests
# can build private instances
RECORDER = FlightRecorder()
