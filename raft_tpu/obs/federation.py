"""Metric federation — one fleet rollup over N replica registries.

Every observability surface below this module is per-process: the
registry, ``/metrics``, ``/healthz``, ``/debug/*`` each describe ONE
process. The moment the fleet splits across processes (ROADMAP item
1), fleet counters live in N disjoint registries and there is no
single page the fleet-level actuator can read. This module is that
page's engine:

* :func:`parse_prometheus_text` / :func:`render_prometheus_text` —
  the exposition format round trip. The parser is the first real
  consumer of our own exporter
  (:meth:`~raft_tpu.obs.registry.MetricsRegistry
  .to_prometheus_text`); ``render(parse(text)) == text`` BYTE-STABLY
  for any exporter output (pinned in tier-1), so federation can never
  silently corrupt a sample on the way through.
* :class:`MetricsFederator` — scrapes N instances (HTTP ``/metrics``
  endpoints and/or in-process registries) on a ``time.monotonic``
  cadence and merges them under an added ``instance`` label with
  per-kind semantics:

  ========== ============================================ ===========
  kind       per-instance series                          fleet rollup
  ========== ============================================ ===========
  counter    kept, ``instance`` label added               SUM (no
                                                          instance
                                                          label)
  gauge      kept, ``instance`` label added               none in
                                                          text;
                                                          ``report()``
                                                          carries
                                                          sum/min/max
  histogram  kept, ``instance`` label added               buckets,
                                                          sum, count
                                                          ADD
  ========== ============================================ ===========

  Gauges get no text rollup on purpose: summing queue depths is
  meaningful, summing duty cycles is not, and the federator cannot
  know which — the typed rollups live in :meth:`report` where the
  reader picks.

* **Staleness** — a failed scrape is typed and counted
  (``raft.obs.fed.scrape.errors{instance}``); the last good sample
  set ages out after ``stale_after_s`` (default 3× the scrape
  interval). A STALE instance is ABSENT from the merged export — a
  dead replica must read as missing, never as frozen-healthy.
* :meth:`MetricsFederator.healthz` — the fleet verdict:
  worst-of across per-instance ``/healthz`` verdicts (stale and
  unreachable both degrade), plus per-instance replication lag and
  the attached router's suspect set.
* :meth:`MetricsFederator.report` — the ``/debug/fleet`` federation
  section: per-instance scrape state side by side with the
  well-known per-replica gauges (duty cycle, HBM headroom, SLO
  burn), and the aggregator's own scrape overhead.

The scraper thread and report/merge readers share state under one
lock; network and registry I/O never happens while it is held
(GL003/GL007 discipline — ``GUARDED_BY`` below).
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple, Union

from raft_tpu import obs
from raft_tpu.obs.registry import _fmt, _prom_labels
from raft_tpu.testing import faults

__all__ = [
    "Sample",
    "Family",
    "parse_prometheus_text",
    "render_prometheus_text",
    "merge_families",
    "MetricsFederator",
]

# seconds buckets for the scrape-duration histogram: scrapes are
# local-network small-payload GETs — sub-ms to a few hundred ms
_SCRAPE_BUCKETS = (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 1.0, 5.0)

LabelTuple = Tuple[Tuple[str, str], ...]


class Sample:
    """One exposition sample line: full sample name (including any
    ``_bucket``/``_sum``/``_count`` suffix), labels in parsed order
    (values unescaped), float value."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: LabelTuple, value: float):
        self.name = name
        self.labels = labels
        self.value = value

    def __repr__(self) -> str:
        return f"Sample({self.name!r}, {self.labels!r}, {self.value!r})"


class Family:
    """One metric family as exposed: prom-charset name exactly as the
    ``# TYPE`` line spells it (counters keep ``_total``), kind, HELP
    text, samples in exposition order."""

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str, kind: str, help: str = ""):
        self.name = name
        self.kind = kind
        self.help = help
        self.samples: List[Sample] = []

    def __repr__(self) -> str:
        return (f"Family({self.name!r}, {self.kind!r}, "
                f"{len(self.samples)} samples)")


_LABEL_RE = re.compile(r'\s*([a-zA-Z_][a-zA-Z0-9_]*)='
                       r'"((?:[^"\\]|\\.)*)"\s*,?')
# one regex pass per escape set — sequential str.replace would corrupt
# r"\\n" (escaped backslash + n) into a newline
_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPE_MAP = {"n": "\n", '"': '"', "\\": "\\"}
_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")


def _unescape(v: str) -> str:
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPE_MAP.get(m.group(1), m.group(0)), v)


def _parse_sample(line: str) -> Optional[Sample]:
    m = _NAME_RE.match(line)
    if m is None:
        return None
    name = m.group(0)
    pos = m.end()
    labels: List[Tuple[str, str]] = []
    if pos < len(line) and line[pos] == "{":
        pos += 1
        while pos < len(line) and line[pos] != "}":
            lm = _LABEL_RE.match(line, pos)
            if lm is None:
                return None
            labels.append((lm.group(1), _unescape(lm.group(2))))
            pos = lm.end()
        if pos >= len(line):
            return None
        pos += 1  # past '}'
    try:
        value = float(line[pos:].strip())
    except ValueError:
        return None
    return Sample(name, tuple(labels), value)


def _base_name(fam: Family, sample_name: str) -> bool:
    """Does ``sample_name`` belong to ``fam``? Histograms expose under
    three suffixes of the family name."""
    if sample_name == fam.name:
        return True
    if fam.kind == "histogram":
        return sample_name in (fam.name + "_bucket",
                               fam.name + "_sum",
                               fam.name + "_count")
    return False


def parse_prometheus_text(text: str) -> List[Family]:
    """Parse exposition text into :class:`Family` objects, order
    preserved. Tolerant of other exporters' output (unknown escapes
    pass through, untyped samples become gauge families), but exact
    on our own: :func:`render_prometheus_text` of the result
    reproduces the input byte for byte."""
    fams: List[Family] = []
    cur: Optional[Family] = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if cur is None or cur.name != name or cur.samples:
                cur = Family(name, "untyped")
                fams.append(cur)
            cur.help = help_text
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, kind = rest.partition(" ")
            kind = kind.strip() or "untyped"
            if cur is not None and cur.name == name and not cur.samples:
                cur.kind = kind
            else:
                cur = Family(name, kind)
                fams.append(cur)
            continue
        if line.startswith("#"):
            continue
        sample = _parse_sample(line)
        if sample is None:
            continue
        if cur is None or not _base_name(cur, sample.name):
            cur = Family(sample.name, "untyped")
            fams.append(cur)
        cur.samples.append(sample)
    return fams


def render_prometheus_text(families: Sequence[Family]) -> str:
    """Render families back to exposition text, preserving order.
    Inverse of :func:`parse_prometheus_text` over the image of our
    exporter (``_fmt`` is a true inverse of ``float`` there, label
    escaping round-trips)."""
    lines: List[str] = []
    for fam in families:
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        if fam.kind != "untyped":
            lines.append(f"# TYPE {fam.name} {fam.kind}")
        for s in fam.samples:
            lines.append(
                f"{s.name}{_prom_labels(s.labels)} {_fmt(s.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def _with_instance(labels: LabelTuple, instance: str) -> LabelTuple:
    """Insert the ``instance`` label in sorted key position (matching
    the exporter's sorted-series-key convention). A scraped sample
    that already carries ``instance`` — e.g. a downstream federator's
    self-metrics, or the shared-registry single-process fleet — keeps
    it as ``exported_instance`` (the Prometheus federation
    convention) so the output never holds a duplicate label key."""
    kept = tuple(("exported_instance", v) if k == "instance" else
                 (k, v) for k, v in labels)
    return tuple(sorted(kept + (("instance", instance),)))


def merge_families(per_instance: Dict[str, List[Family]]
                   ) -> List[Family]:
    """Merge each instance's families into the fleet view: every
    sample reappears with an ``instance`` label; counter and
    histogram families additionally get rollup samples WITHOUT the
    instance label (values summed across instances — cumulative
    bucket counts sum bucket-wise, which is exact when instances
    share bucket bounds, i.e. run the same binary). Gauges get no
    text rollup (see module docstring). Families are merged by name;
    kind/help come from the first instance exposing them."""
    merged: Dict[str, Family] = {}
    rollups: Dict[str, Dict[Tuple[str, LabelTuple], float]] = {}
    for inst in sorted(per_instance):
        for fam in per_instance[inst]:
            out = merged.get(fam.name)
            if out is None:
                out = Family(fam.name, fam.kind, fam.help)
                merged[fam.name] = out
                rollups[fam.name] = {}
            for s in fam.samples:
                out.samples.append(Sample(
                    s.name, _with_instance(s.labels, inst), s.value))
                if out.kind in ("counter", "histogram"):
                    # rollup keys get the same instance →
                    # exported_instance rename as the per-instance
                    # samples, so a scraped target's own `instance`
                    # label never reappears as OUR instance dimension
                    key = (s.name, tuple(sorted(
                        ("exported_instance", v) if k == "instance"
                        else (k, v) for k, v in s.labels)))
                    roll = rollups[fam.name]
                    roll[key] = roll.get(key, 0.0) + s.value
    for name, fam in merged.items():
        for (sname, labels), value in sorted(rollups[name].items()):
            fam.samples.append(Sample(sname, labels, value))
    return [merged[name] for name in sorted(merged)]


class _Instance:
    """Scrape-side state of one instance (guarded by the federator
    lock): last good parse + when, cumulative stats."""

    __slots__ = ("families", "t_good", "scrapes", "errors",
                 "last_error", "last_scrape_s")

    def __init__(self):
        self.families: Optional[List[Family]] = None
        self.t_good: Optional[float] = None     # monotonic
        self.scrapes = 0
        self.errors = 0
        self.last_error: Optional[str] = None
        self.last_scrape_s = 0.0


# a source is either a base URL ("http://host:port") or an in-process
# registry-like object (to_prometheus_text + snapshot)
Source = Union[str, object]


class MetricsFederator:
    """Scrape N instances, merge, re-export — see module docstring.

    ``instances`` maps instance name → source: a base URL string
    (scraped over ``GET <url>/metrics``, health over ``/healthz``) or
    an in-process registry-like object (``to_prometheus_text()`` +
    ``snapshot()``). ``fleet`` optionally attaches the local
    :class:`~raft_tpu.fleet.FleetRouter` so :meth:`healthz` can fold
    in its suspect set.

    Thread model: ONE scraper thread (:meth:`start`) sweeps on a
    ``time.monotonic`` cadence; any thread may read
    :meth:`merged_text`/:meth:`healthz`/:meth:`report` concurrently.
    Network and peer-registry I/O always happens OUTSIDE the lock —
    a slow replica can delay freshness, never block a reader."""

    GUARDED_BY = ("_sources", "_instances", "_scrape_s_total",
                  "_blackboxes")

    def __init__(self, instances: Optional[Dict[str, Source]] = None,
                 interval_s: float = 5.0,
                 stale_after_s: Optional[float] = None,
                 timeout_s: float = 2.0,
                 fleet: Optional[object] = None):
        self.interval_s = float(interval_s)
        self.stale_after_s = (float(stale_after_s)
                              if stale_after_s is not None
                              else 3.0 * self.interval_s)
        self.timeout_s = float(timeout_s)
        self.fleet = fleet
        self._lock = threading.Lock()
        self._sources: Dict[str, Source] = dict(instances or {})
        self._instances: Dict[str, _Instance] = {}
        # per-instance black-box dump paths (ISSUE 18): the aggregator
        # remembers where each replica's flight recorder spills so a
        # dead instance's report row still points at its forensics
        self._blackboxes: Dict[str, str] = {}
        self._scrape_s_total = 0.0
        self._t_started = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- membership --------------------------------------------------------
    def add_instance(self, name: str, source: Source) -> None:
        with self._lock:
            self._sources[name] = source

    def set_blackbox_path(self, name: str, path: Optional[str]) -> None:
        """Record (or clear, ``path=None``) where instance ``name``'s
        black box dumps — surfaced per-row in :meth:`report` so the
        doctor can be pointed at a dead replica straight from
        ``/debug/fleet``. Deliberately NOT dropped with the source in
        :meth:`remove_instance`'s instances map: the path outlives the
        process it names."""
        with self._lock:
            if path is None:
                self._blackboxes.pop(name, None)
            else:
                self._blackboxes[name] = str(path)

    def remove_instance(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)
            self._instances.pop(name, None)
            self._blackboxes.pop(name, None)

    def instance_names(self) -> List[str]:
        with self._lock:
            return sorted(self._sources)

    def url_instances(self) -> Dict[str, str]:
        """The URL-backed instances (name → base URL) — the peers a
        trace stitch can fetch fragments from (in-process registry
        instances share the local recorder already)."""
        with self._lock:
            return {n: s for n, s in self._sources.items()
                    if isinstance(s, str)}

    # -- scraping ----------------------------------------------------------
    def _fetch(self, name: str, source: Source) -> str:
        """One instance's exposition text (I/O — never under the
        lock). The fault site ``fed.scrape`` lets chaos tests fail or
        delay exactly this boundary."""
        faults.inject("fed.scrape", instance=name)
        if isinstance(source, str):
            url = source.rstrip("/") + "/metrics"
            with urllib.request.urlopen(
                    url, timeout=self.timeout_s) as resp:
                return resp.read().decode("utf-8")
        return source.to_prometheus_text()

    def scrape_once(self) -> dict:
        """One full sweep over every instance → ``{"scraped": n,
        "errors": n}``. Serial on purpose: N is replica-count small,
        and a serial sweep keeps the fault/timeout story trivially
        bounded at ``N * timeout_s``."""
        from raft_tpu.obs import spans
        with self._lock:
            sources = dict(self._sources)
        errors = 0
        with spans.span("raft.obs.fed.scrape",
                        instances=len(sources)) as sp:
            for name in sorted(sources):
                t0 = time.monotonic()
                err: Optional[str] = None
                fams: Optional[List[Family]] = None
                try:
                    fams = parse_prometheus_text(
                        self._fetch(name, sources[name]))
                except Exception as e:
                    err = f"{type(e).__name__}: {e}"
                dur = time.monotonic() - t0
                obs.counter("raft.obs.fed.scrapes.total",
                            instance=name).inc()
                obs.histogram("raft.obs.fed.scrape.seconds",
                              buckets=_SCRAPE_BUCKETS).observe(dur)
                if err is not None:
                    errors += 1
                    obs.counter("raft.obs.fed.scrape.errors",
                                instance=name).inc()
                with self._lock:
                    inst = self._instances.setdefault(name, _Instance())
                    inst.scrapes += 1
                    inst.last_scrape_s = dur
                    self._scrape_s_total += dur
                    if err is None:
                        inst.families = fams
                        inst.t_good = t0
                        inst.last_error = None
                    else:
                        inst.errors += 1
                        inst.last_error = err
            sp.set_attrs(errors=errors)
        live = self.live_instances()
        obs.gauge("raft.obs.fed.instances").set(len(sources))
        obs.gauge("raft.obs.fed.stale").set(len(sources) - len(live))
        return {"scraped": len(sources), "errors": errors}

    def _stale_locked(self, name: str, now: float) -> bool:
        inst = self._instances.get(name)
        return (inst is None or inst.t_good is None
                or now - inst.t_good > self.stale_after_s)

    def live_instances(self) -> List[str]:
        """Instances with a good scrape inside the staleness window."""
        now = time.monotonic()
        with self._lock:
            return sorted(n for n in self._sources
                          if not self._stale_locked(n, now))

    def stale_instances(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return sorted(n for n in self._sources
                          if self._stale_locked(n, now))

    # -- export ------------------------------------------------------------
    def merged(self) -> List[Family]:
        """The fleet-merged families over LIVE instances only (stale
        instances are absent — never frozen-healthy)."""
        now = time.monotonic()
        with self._lock:
            per = {name: list(inst.families)
                   for name, inst in self._instances.items()
                   if name in self._sources
                   and inst.families is not None
                   and not self._stale_locked(name, now)}
        return merge_families(per)

    def merged_text(self) -> str:
        """The aggregator ``/metrics`` body."""
        return render_prometheus_text(self.merged())

    def _extract(self, fams: List[Family], name: str) -> Dict[str, float]:
        """All samples of prom family ``name`` as series → value."""
        out: Dict[str, float] = {}
        for fam in fams:
            for s in fam.samples:
                if s.name == name:
                    out[f"{s.name}{_prom_labels(s.labels)}"] = s.value
        return out

    def healthz(self) -> dict:
        """The fleet health verdict: worst-of across per-instance
        verdicts. Stale and unreachable instances degrade — absence
        of evidence of health is evidence of degradation here."""
        now = time.monotonic()
        with self._lock:
            sources = dict(self._sources)
            stale = {n: self._stale_locked(n, now) for n in sources}
            lag: Dict[str, Dict[str, float]] = {}
            for n, inst in self._instances.items():
                if n in sources and inst.families is not None:
                    lag[n] = self._extract(
                        inst.families, "raft_fleet_replication_lag_records")
        per: Dict[str, dict] = {}
        for name in sorted(sources):
            if stale[name]:
                per[name] = {"status": "stale"}
                continue
            per[name] = self._instance_health(name, sources[name])
            if lag.get(name):
                per[name]["replication_lag_records"] = lag[name]
        degraded = (not per) or any(
            v.get("status") != "ok" for v in per.values())
        body = {
            "status": "degraded" if degraded else "ok",
            "instances": per,
            "stale": sorted(n for n in sources if stale[n]),
        }
        if self.fleet is not None:
            body["suspects"] = list(self.fleet.suspects())
        return body

    def _instance_health(self, name: str, source: Source) -> dict:
        """One instance's /healthz verdict (I/O — never under the
        lock)."""
        try:
            if isinstance(source, str):
                url = source.rstrip("/") + "/healthz"
                req = urllib.request.Request(url)
                try:
                    with urllib.request.urlopen(
                            req, timeout=self.timeout_s) as resp:
                        return json.loads(resp.read().decode("utf-8"))
                except urllib.error.HTTPError as he:
                    # /healthz answers 503 WITH a body when degraded
                    return json.loads(he.read().decode("utf-8"))
            from raft_tpu.obs import endpoint as _endpoint
            return _endpoint._health_body(source.snapshot())
        except Exception as e:
            return {"status": "unreachable",
                    "error": f"{type(e).__name__}: {e}"}

    def report(self) -> dict:
        """The ``/debug/fleet`` federation section: per-instance
        scrape state + the well-known per-replica gauges side by
        side, gauge rollups (sum/min/max per series), and the
        aggregator's own overhead."""
        now = time.monotonic()
        with self._lock:
            sources = dict(self._sources)
            blackboxes = dict(self._blackboxes)
            rows: Dict[str, dict] = {}
            gauge_values: Dict[str, Dict[str, float]] = {}
            for name in sorted(sources):
                inst = self._instances.get(name)
                if inst is None:
                    row = {"state": "absent", "scrapes": 0,
                           "errors": 0}
                    if name in blackboxes:
                        row["blackbox"] = blackboxes[name]
                    rows[name] = row
                    continue
                state = ("stale" if self._stale_locked(name, now)
                         else "live")
                row = {
                    "state": state,
                    "scrapes": inst.scrapes,
                    "errors": inst.errors,
                    "last_scrape_s": round(inst.last_scrape_s, 6),
                    "age_s": (round(now - inst.t_good, 3)
                              if inst.t_good is not None else None),
                }
                if inst.last_error:
                    row["last_error"] = inst.last_error
                if name in blackboxes:
                    # the post-mortem pointer: a STALE row plus this
                    # path is the doctor's entry point
                    row["blackbox"] = blackboxes[name]
                if inst.families is not None:
                    for label, prom in (
                            ("duty_cycle",
                             "raft_obs_profile_duty_cycle"),
                            ("hbm_headroom_frac",
                             "raft_obs_profile_hbm_headroom_frac"),
                            ("slo_burn_rate", "raft_slo_burn_rate"),
                            ("replication_lag_records",
                             "raft_fleet_replication_lag_records"),
                            ("tiered_hit_rate", "raft_tiered_hit_rate"),
                            ("tiered_overlap_frac",
                             "raft_tiered_overlap_frac")):
                        vals = self._extract(inst.families, prom)
                        if vals:
                            row[label] = vals
                    if state == "live":
                        for fam in inst.families:
                            if fam.kind != "gauge":
                                continue
                            for s in fam.samples:
                                series = (f"{s.name}"
                                          f"{_prom_labels(s.labels)}")
                                gauge_values.setdefault(
                                    series, {})[name] = s.value
                rows[name] = row
            scrape_s = self._scrape_s_total
        uptime = max(1e-9, now - self._t_started)
        rollups = {
            series: {"sum": sum(vs.values()),
                     "min": min(vs.values()),
                     "max": max(vs.values())}
            for series, vs in sorted(gauge_values.items())
            if len(vs) > 1}
        return {
            "interval_s": self.interval_s,
            "stale_after_s": self.stale_after_s,
            "instances": rows,
            "gauge_rollups": rollups,
            "scrape_overhead": {
                "total_s": round(scrape_s, 6),
                "uptime_s": round(uptime, 3),
                "frac": round(scrape_s / uptime, 6),
            },
        }

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MetricsFederator":
        """Start the scraper thread (idempotent). One immediate sweep,
        then one per ``interval_s`` on the monotonic clock."""
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="raft-obs-federator")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:
                # the sweep itself must never kill the thread; per-
                # instance failures are already typed and counted
                pass
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(2.0, self.timeout_s + 1.0))
            self._thread = None

    def __enter__(self) -> "MetricsFederator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
