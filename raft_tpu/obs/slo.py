"""Declarative SLOs — multi-window burn rates over the obs registry.

The serving metrics say what happened; an SLO says whether it was
ACCEPTABLE — and "acceptable" must be declared once, not re-derived in
every dashboard. An :class:`Objective` declares one contract:

* ``kind="latency"`` — fraction of requests completing within
  ``threshold_ms`` must be ≥ ``target`` (e.g. p99 under the 200 ms
  watermark → ``threshold_ms=200, target=0.99``), read from the
  ``raft.serve.request.seconds`` histogram buckets (pick a threshold
  on a bucket edge — ``serve.SERVE_LATENCY_BUCKETS`` — or the check
  conservatively rounds DOWN to the nearest edge);
* ``kind="availability"`` — fraction of offered requests answered
  (shed + deadline + error are the failures) must be ≥ ``target``,
  from the ``raft.serve.{requests,shed,deadline,errors}`` counters;
* ``kind="recall"`` — the live shadow-exact recall estimate
  (``raft.obs.quality.recall`` full-coverage gauges, worst series)
  must stay ≥ ``target``; burn = shortfall / ``tolerance``.

Each objective is evaluated as **burn rates over multiple windows**
(the SRE multi-window multi-burn pattern): burn = error rate ÷ error
budget (``1 − target``), so burn 1.0 = exactly consuming budget,
burn 10 = burning it 10× too fast. A **breach** requires EVERY window
of the objective to burn ≥ ``burn_threshold`` — the short window
proves it is happening NOW, the long window proves it is not a blip.

Exported as ``raft.slo.burn_rate{objective,window}`` /
``raft.slo.breach{objective}`` gauges (written into the same registry
the tracker reads, so ``/healthz`` folds breaches into its degraded
verdict and ``/debug/slo`` serves the full report — endpoint.py).

Use::

    from raft_tpu.obs import slo
    tracker = slo.SLOTracker([
        slo.Objective("p99_latency", "latency", target=0.99,
                      threshold_ms=200.0),
        slo.Objective("availability", "availability", target=0.999),
        slo.Objective("recall_floor", "recall", target=0.85),
    ])                      # polling daemon; tracker.close() to stop
    tracker.report()        # {objective: {windows, burn, breach}, ...}

Deterministic tests drive :meth:`SLOTracker.tick` with an injected
clock instead of the polling thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

from raft_tpu.core.error import expects
from raft_tpu.obs import registry as _registry

__all__ = ["Objective", "SLOTracker", "active", "endpoint_body"]

_KINDS = ("latency", "availability", "recall")
_FAIL_COUNTERS = ("raft.serve.shed.total", "raft.serve.deadline.total",
                  "raft.serve.errors.total")


@dataclass(frozen=True)
class Objective:
    """One declared service objective (module docstring for kinds).

    ``windows`` are seconds, ascending; ``burn_threshold`` is the
    burn-rate level EVERY window must reach before the objective
    breaches (1.0 = budget consumed exactly at the sustainable rate).
    ``tolerance`` applies to ``recall`` only: the shortfall that
    counts as burn 1.0."""

    name: str
    kind: str
    target: float
    threshold_ms: float = 0.0
    tolerance: float = 0.02
    windows: Tuple[float, ...] = (60.0, 300.0)
    burn_threshold: float = 1.0
    description: str = ""

    def __post_init__(self):
        expects(bool(self.name) and all(
            c.isascii() and (c.islower() or c.isdigit() or c == "_")
            for c in self.name),
            "Objective: name %r must be a [a-z0-9_]+ token (it rides "
            "as a metric label)", self.name)
        expects(self.kind in _KINDS,
                "Objective %r: kind must be one of %s", self.name,
                _KINDS)
        expects(0.0 < self.target < 1.0 if self.kind != "recall"
                else 0.0 < self.target <= 1.0,
                "Objective %r: target must be in (0, 1)", self.name)
        expects(self.kind != "latency" or self.threshold_ms > 0,
                "Objective %r: latency objectives need threshold_ms",
                self.name)
        expects(len(self.windows) >= 1
                and list(self.windows) == sorted(set(self.windows))
                and min(self.windows) > 0,
                "Objective %r: windows must be ascending positive "
                "seconds", self.name)
        expects(self.tolerance > 0,
                "Objective %r: tolerance must be > 0", self.name)


def _sum_series(table: dict, name: str) -> float:
    return sum(v for k, v in table.items()
               if k == name or k.startswith(name + "{"))


def _latency_counts(snapshot: dict, threshold_s: float
                    ) -> Tuple[float, float]:
    """(total, over-threshold) request counts across every
    ``raft.serve.request.seconds`` series. Bucket edges are inclusive
    upper bounds; a threshold between edges rounds DOWN (conservative:
    borderline-fast requests count as slow, never the reverse)."""
    total = over = 0.0
    for series, h in snapshot.get("histograms", {}).items():
        base = series.split("{")[0]
        if base != "raft.serve.request.seconds":
            continue
        total += h["count"]
        good = 0.0
        for edge, c in h["buckets"].items():
            if edge != "+Inf" and float(edge) <= threshold_s + 1e-12:
                good += c
        over += h["count"] - good
    return total, over


def _recall_floor_value(snapshot: dict) -> Optional[float]:
    """Worst full-coverage live recall across families/epochs (partial
    failover series are availability, not quality — excluded)."""
    vals = [v for k, v in snapshot.get("gauges", {}).items()
            if k.split("{")[0] == "raft.obs.quality.recall"
            and "coverage=partial" not in k]
    return min(vals) if vals else None


class SLOTracker:
    """Evaluates a set of :class:`Objective`\\ s against periodic
    registry snapshots and publishes ``raft.slo.*`` gauges. Runs a
    polling daemon by default; tests call :meth:`tick` with an
    injected ``clock``. Reads AND writes ``registry`` (default: the
    process registry) so one snapshot carries signal and verdict."""

    # static race contract (tools/graftlint GL003): the polling daemon
    # (tick) and report() readers share the burn-rate ring and the
    # last report under self._lock
    GUARDED_BY = ("_ring", "_report", "_breached")

    def __init__(self, objectives: Sequence[Objective],
                 registry=None, poll_s: float = 1.0, clock=None,
                 start: bool = True, install: bool = True):
        objectives = tuple(objectives)
        expects(len(objectives) > 0, "SLOTracker: need >= 1 objective")
        expects(len({o.name for o in objectives}) == len(objectives),
                "SLOTracker: objective names must be unique")
        self.objectives = objectives
        self._reg = registry if registry is not None \
            else _registry.REGISTRY
        self._poll_s = float(poll_s)
        self._clock = clock if clock is not None else time.monotonic
        horizon = max(max(o.windows) for o in objectives)
        # ring of (t, snapshot-derived cumulative signals); one extra
        # slot so a full window always has a sample at/behind its start
        slots = int(horizon / max(self._poll_s, 1e-3)) + 2
        self._ring: deque = deque(maxlen=min(slots, 100_000))
        self._lock = threading.Lock()
        self._report: Dict[str, dict] = {}
        self._breached: set = set()
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # local alias named like the module-level registry facade so
        # instrument call sites read (and lint) like every other
        # instrumented module's
        obs = self._reg
        obs.gauge("raft.slo.objectives").set(len(objectives))
        if install:
            _install(self)
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "SLOTracker":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="raft-slo-tracker")
            self._thread.start()
        return self

    def close(self) -> None:
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        _uninstall(self)

    def __enter__(self) -> "SLOTracker":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _loop(self) -> None:
        while not self._closed.wait(self._poll_s):
            try:
                self.tick()
            except Exception:
                self._reg.counter("raft.slo.errors.total").inc()

    # -- evaluation --------------------------------------------------------
    def _signals(self) -> dict:
        snap = self._reg.snapshot()
        counters = snap.get("counters", {})
        sig = {
            "requests": _sum_series(counters,
                                    "raft.serve.requests.total"),
            "failed": sum(_sum_series(counters, n)
                          for n in _FAIL_COUNTERS),
        }
        for o in self.objectives:
            if o.kind == "latency":
                total, over = _latency_counts(snap,
                                              o.threshold_ms / 1e3)
                sig[f"lat_total:{o.name}"] = total
                sig[f"lat_over:{o.name}"] = over
            elif o.kind == "recall":
                sig[f"recall:{o.name}"] = _recall_floor_value(snap)
        return sig

    def _window_start_locked(self, now: float,
                             w: float) -> Optional[dict]:
        """The newest ring sample at or before ``now - w`` (None until
        the ring covers the window — a cold tracker must not breach on
        a half-filled window). Caller holds ``self._lock``."""
        best = None
        for t, sig in self._ring:
            if t <= now - w + 1e-9:
                best = sig
            else:
                break
        return best

    def tick(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Sample signals, evaluate every (objective × window) burn
        rate, publish gauges, return the report dict."""
        now = self._clock() if now is None else float(now)
        sig = self._signals()
        obs = self._reg      # lint-visible instrument call sites
        with self._lock:
            self._ring.append((now, sig))
            report: Dict[str, dict] = {}
            for o in self.objectives:
                burns: Dict[str, Optional[float]] = {}
                for w in o.windows:
                    base = self._window_start_locked(now, w)
                    burns[f"{int(w)}s"] = self._burn_locked(
                        o, w, now, sig, base)
                breach = (all(b is not None and b >= o.burn_threshold
                              for b in burns.values())
                          and len(burns) > 0)
                for wl, b in burns.items():
                    # -1 = no data yet (cold window / zero traffic) —
                    # distinguishable from a genuine burn of 0
                    obs.gauge("raft.slo.burn_rate", objective=o.name,
                              window=wl).set(
                        -1.0 if b is None else round(b, 6))
                obs.gauge("raft.slo.breach", objective=o.name).set(
                    1.0 if breach else 0.0)
                if breach and o.name not in self._breached:
                    obs.counter("raft.slo.breach.total",
                                objective=o.name).inc()
                (self._breached.add(o.name) if breach
                 else self._breached.discard(o.name))
                report[o.name] = {
                    "kind": o.kind,
                    "target": o.target,
                    "burn_threshold": o.burn_threshold,
                    "burn": {wl: (None if b is None else round(b, 4))
                             for wl, b in burns.items()},
                    "breach": breach,
                }
                if o.kind == "latency":
                    report[o.name]["threshold_ms"] = o.threshold_ms
                if o.kind == "recall":
                    report[o.name]["live_recall"] = sig.get(
                        f"recall:{o.name}")
            obs.counter("raft.slo.evaluations.total").inc()
            self._report = report
            return report

    def _burn_locked(self, o: Objective, w: float, now: float,
                     now_sig: dict, base_sig: Optional[dict]
                     ) -> Optional[float]:
        """Burn rate of one objective over one window → None while the
        window has no data (cold start, zero traffic). Caller holds
        ``self._lock`` (the ring is read here)."""
        if o.kind == "recall":
            # gauges are already windowed by the quality monitor; the
            # SLO window uses the worst value sampled INSIDE it
            vals = [v for t, sig in self._ring
                    if t >= now - w - 1e-9
                    for v in [sig.get(f"recall:{o.name}")]
                    if v is not None]
            if not vals:
                return None
            return max(0.0, o.target - min(vals)) / o.tolerance
        if base_sig is None:
            return None
        if o.kind == "latency":
            total = (now_sig[f"lat_total:{o.name}"]
                     - base_sig.get(f"lat_total:{o.name}", 0.0))
            bad = (now_sig[f"lat_over:{o.name}"]
                   - base_sig.get(f"lat_over:{o.name}", 0.0))
        else:  # availability
            total = now_sig["requests"] - base_sig.get("requests", 0.0)
            bad = now_sig["failed"] - base_sig.get("failed", 0.0)
        if total <= 0:
            return None
        return (bad / total) / max(1e-9, 1.0 - o.target)

    def report(self) -> Dict[str, dict]:
        """Last :meth:`tick` result (evaluates once if never run)."""
        with self._lock:
            rep = dict(self._report)
        return rep if rep else self.tick()


# -- endpoint integration (one active tracker per process) ----------------
_active_lock = threading.Lock()
_active: Optional[SLOTracker] = None


def _install(tracker: SLOTracker) -> None:
    global _active
    with _active_lock:
        _active = tracker


def _uninstall(tracker: SLOTracker) -> None:
    global _active
    with _active_lock:
        if _active is tracker:
            _active = None


def active() -> Optional[SLOTracker]:
    """The most recently constructed (still-open) tracker — what
    ``/debug/slo`` serves."""
    with _active_lock:
        return _active


def endpoint_body(snapshot: dict) -> dict:
    """The ``/debug/slo`` response: the active tracker's full report
    when one runs in-process, else the ``raft.slo.*`` gauges already
    in ``snapshot`` (a scraped box whose tracker lives elsewhere)."""
    tracker = active()
    if tracker is not None:
        return {"source": "tracker", "objectives": tracker.report()}
    gauges = {k: v for k, v in snapshot.get("gauges", {}).items()
              if k.split("{")[0].startswith("raft.slo.")}
    return {"source": "gauges" if gauges else "none",
            "gauges": gauges}
