"""Online quality observability — shadow-exact recall estimation.

The serving stack observes everything about *speed* and *availability*
(``raft.serve.*`` histograms, spans, ``/healthz``) but, before this
module, nothing about *result quality*: recall was measured offline in
``bench_suite`` and the cheap unrescored estimator there drifts 0.13+
from truth (BENCH_r05: 0.7159 estimated vs 0.8612 true for ivf_pq).
This is the always-on quality signal — the "measured signal" half of
the self-driving loop (ROADMAP item 5), the bench yardstick
productionized:

* the batcher **reservoir-samples** live queries at
  ``ServeConfig.quality_sample_rate`` (``SearchServer.enable_quality``
  attaches a :class:`QualityMonitor`);
* a **background shadow thread** replays the sampled queries — off the
  serving path, never occupying a batch slot — through a pre-warmed
  :class:`ExactScorer` (fixed-shape brute force over the corpus, or a
  bounded deterministic sample of it past ``max_rows``) and compares
  the SERVED ids against the exact ids;
* windowed per-query recall lands in
  ``raft.obs.quality.recall{family,epoch}`` gauges; partial-mesh
  failover results are attributed separately
  (``coverage=partial, excluded=<ranks>``) so degraded recall is
  explainable, not mysterious;
* an optional cheap **estimator** (e.g. the unrescored PQ search) runs
  on the same samples and ``raft.obs.quality.calibration.gap`` = shadow
  recall − estimator recall quantifies the 0.13 estimator gap online;
* recall is tracked **per compaction epoch**: when a fold's epoch rolls
  (the :class:`~raft_tpu.mutate.MutableIndex` epoch listener calls
  :meth:`QualityMonitor.note_epoch`), the previous epoch's windowed
  mean becomes the baseline, and ``raft.obs.quality.drift`` fires —
  gauge + ``raft.obs.quality.drift.total`` — the moment the new epoch
  degrades recall PAST ``drift_budget``. This is the trigger ROADMAP
  item 5's fold→rebuild policy consumes.

Zero-overhead contract (the PR 3 discipline): with sampling off the
serving hot path reads exactly one flag (``SearchServer._quality is
None`` — no allocation, no thread); with sampling on, the shadow
replay performs ZERO steady-state compiles — the scorer is one
fixed-shape jitted program per (batch, chunk) compiled at construction
(``warm()``), asserted in tests from ``raft.plan.cache.*`` staying
flat plus jax's own compile cache.

Caveats, stated rather than hidden:

* past ``max_rows`` the scorer scores a deterministic corpus
  **sample**; "exact" ids are then exact over the sample and the
  recall gauge is an estimator (still unbiased enough for drift/SLO
  purposes — the window compares like against like).
* for a mutable corpus the scorer snapshots construction-time rows;
  re-attach (``enable_quality``) after heavy churn, or rebuild on the
  epoch listener, to keep ground truth fresh. Epoch-to-epoch DRIFT is
  still meaningful under churn: both windows score against the same
  snapshot, so a fold that loses candidates moves the gauge.
* the ``epoch`` label is bounded by the registry cardinality cap
  (``RAFT_TPU_METRICS_MAX_SERIES``); a process compacting thousands of
  epochs should raise it or restart the monitor.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core.error import expects
from raft_tpu.core.logger import get_logger
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.obs import spans
from raft_tpu.obs.registry import CardinalityError

__all__ = ["ExactScorer", "QualityConfig", "QualityMonitor",
           "corpus_from_index"]

# metrics whose ranking the scorer reproduces exactly; everything else
# must go through a custom scorer object (duck-typed .topk)
_L2_KINDS = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
             DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded)


def _score_chunk(q, rows, norms, kind: str, kmax: int):
    """One (batch, chunk) exact scoring tile → (top-kmax dists, chunk-
    local indices). Ranking-exact: HIGHEST-precision dot products, L2
    via the expanded form with the query norm dropped (rank-invariant
    per query), similarities negated so ascending-best holds for every
    kind. Pad rows carry +inf (masked via ``norms``)."""
    import jax
    import jax.numpy as jnp
    dots = jnp.einsum("qd,cd->qc", q, rows,
                      precision=jax.lax.Precision.HIGHEST)
    if kind == "l2":
        d = norms[None, :] - 2.0 * dots
    else:  # ip / cosine (corpus pre-normalized for cosine)
        d = jnp.where(jnp.isinf(norms)[None, :], jnp.inf, -dots)
    neg_top, idx = jax.lax.top_k(-d, kmax)
    return -neg_top, idx


_score_chunk_jit = None  # built lazily so importing quality stays jax-free


def _get_score_fn():
    global _score_chunk_jit
    if _score_chunk_jit is None:
        import jax
        _score_chunk_jit = jax.jit(_score_chunk,
                                   static_argnames=("kind", "kmax"))
    return _score_chunk_jit


class ExactScorer:
    """Pre-warmed fixed-shape exact brute-force scorer: the shadow
    ground truth. One jitted (batch × chunk) program compiled at
    construction scores ANY corpus size by tiling — the shadow path
    never compiles again (the zero-steady-state-compile contract).

    ``corpus`` is host rows ``(n, dim)``; ``ids`` maps row → global id
    (default ``arange``; pass the real id map for mutable / re-indexed
    corpora). Past ``max_rows`` a seeded deterministic sample is scored
    instead (``self.sampled`` says so; the recall gauge becomes an
    estimator — module docstring)."""

    def __init__(self, corpus, ids=None,
                 metric: DistanceType = DistanceType.L2Expanded,
                 kmax: int = 64, max_rows: int = 1 << 18,
                 chunk: int = 1 << 16, batch: int = 32, seed: int = 0,
                 warm: bool = True):
        import jax.numpy as jnp
        x = np.ascontiguousarray(np.asarray(corpus, np.float32))
        expects(x.ndim == 2 and x.shape[0] > 0,
                "ExactScorer: corpus must be a non-empty (n, dim) "
                "array, got %s", x.shape)
        n, dim = x.shape
        row_ids = (np.arange(n, dtype=np.int64) if ids is None
                   else np.asarray(ids, np.int64))
        expects(row_ids.shape == (n,),
                "ExactScorer: ids must be (n=%d,), got %s", n,
                row_ids.shape)
        self.sampled = n > max_rows
        if self.sampled:
            sel = np.sort(np.random.default_rng(seed).choice(
                n, size=max_rows, replace=False))
            x, row_ids, n = x[sel], row_ids[sel], max_rows
        if metric == DistanceType.CosineExpanded:
            self._kind = "cos"
            nrm = np.linalg.norm(x, axis=1, keepdims=True)
            x = x / np.maximum(nrm, 1e-30)
        elif metric == DistanceType.InnerProduct:
            self._kind = "ip"
        else:
            expects(metric in _L2_KINDS,
                    "ExactScorer: unsupported metric %s (l2 family, ip "
                    "or cosine)", metric)
            self._kind = "l2"
        self.metric = metric
        self.dim = dim
        self.rows = n
        self.batch = int(batch)
        self.kmax = int(min(kmax, n))
        chunk = int(min(chunk, 1 << 20))
        n_chunks = -(-n // chunk)
        chunk = min(chunk, n) if n_chunks == 1 else chunk
        self._k_tile = int(min(self.kmax, chunk))
        pad = n_chunks * chunk - n
        if pad:
            x = np.concatenate([x, np.zeros((pad, dim), np.float32)])
            row_ids = np.concatenate(
                [row_ids, np.full((pad,), -1, np.int64)])
        # per-row scoring norms: ||row||^2 for l2 (query norm dropped —
        # rank-invariant), 0 for similarities; +inf marks pad rows so
        # they can never enter a top-k
        norms = (np.einsum("cd,cd->c", x, x) if self._kind == "l2"
                 else np.zeros((n_chunks * chunk,), np.float32))
        norms = norms.astype(np.float32)
        norms[n:] = np.inf
        self._ids = row_ids.reshape(n_chunks, chunk)
        self._chunks = [jnp.asarray(x[c * chunk:(c + 1) * chunk])
                        for c in range(n_chunks)]
        self._norms = [jnp.asarray(norms[c * chunk:(c + 1) * chunk])
                       for c in range(n_chunks)]
        if warm:
            self.warm()

    def warm(self) -> "ExactScorer":
        """Compile + run the one (batch × chunk) program now, so the
        shadow thread never compiles (every chunk shares the shape)."""
        z = np.zeros((self.batch, self.dim), np.float32)
        self.topk(z, min(2, self.kmax))
        return self

    def topk(self, queries, k: int) -> np.ndarray:
        """Exact top-``k`` global ids for ``queries`` → ``(nq, k)``
        int64. Tiles queries to the fixed ``batch`` shape and the
        corpus to fixed chunks; merges chunk winners host-side."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        expects(q.shape[1] == self.dim,
                "ExactScorer.topk: queries must be (nq, dim=%d), got "
                "%s", self.dim, q.shape)
        k = int(min(k, self.kmax))
        expects(k > 0, "ExactScorer.topk: k must be >= 1")
        if self._kind == "cos":
            q = q / np.maximum(
                np.linalg.norm(q, axis=1, keepdims=True), 1e-30)
        fn = _get_score_fn()
        nq = q.shape[0]
        out = np.empty((nq, k), np.int64)
        for s in range(0, nq, self.batch):
            qb = q[s:s + self.batch]
            pad = self.batch - qb.shape[0]
            if pad:
                qb = np.concatenate([qb, np.tile(qb[:1], (pad, 1))])
            ds, gs = [], []
            for c, (rows, norms) in enumerate(
                    zip(self._chunks, self._norms)):
                d, i = fn(qb, rows, norms, kind=self._kind,
                          kmax=self._k_tile)
                d, i = np.asarray(d), np.asarray(i)
                ds.append(d)
                gs.append(self._ids[c][i])
            d_all = np.concatenate(ds, axis=1)
            g_all = np.concatenate(gs, axis=1)
            order = np.argsort(d_all, axis=1, kind="stable")[:, :k]
            ids_b = np.take_along_axis(g_all, order, axis=1)
            out[s:s + self.batch - pad] = ids_b[:self.batch - pad]
        return out


def corpus_from_index(index) -> Tuple[np.ndarray, np.ndarray]:
    """Reconstruct ``(rows, ids)`` from an IVF-Flat index's list layout
    (the common enable_quality source when the caller no longer holds
    the build-time corpus). Raw-vector lists only — PQ/BQ corpora
    should pass the original rows (or ``index.raw`` when kept)."""
    data = np.asarray(index.lists_data)
    idx = np.asarray(index.lists_indices)
    valid = idx >= 0
    rows = data[valid].astype(np.float32, copy=False)
    if getattr(index, "scale", None) is not None:
        rows = rows * np.float32(index.scale)
    return rows, idx[valid].astype(np.int64)


@dataclass(frozen=True)
class QualityConfig:
    """Shadow-path knobs of a :class:`QualityMonitor`.

    * ``window`` — per-(epoch, coverage) rolling window of per-query
      recalls behind each gauge; ``min_window`` samples must accumulate
      before the drift comparison speaks (a 3-sample "regression" is
      noise, not signal).
    * ``max_pending`` — the reservoir bound: between shadow drains at
      most this many sampled queries are held; further samples
      reservoir-replace uniformly (``raft.obs.quality.evicted.total``
      counts the overwritten ones) so a hot burst can never grow host
      memory or bias toward its tail.
    * ``shadow_batch`` / ``chunk`` / ``max_rows`` — the
      :class:`ExactScorer` tile shapes (fixed → compiled once).
    * ``drift_budget`` — an epoch whose windowed recall falls MORE than
      this below the previous epoch's baseline fires
      ``raft.obs.quality.drift`` (strictly past the budget: equal-to-
      budget degradation is within contract).
    * ``poll_ms`` — shadow-thread wake cadence when idle.
    """

    window: int = 256
    min_window: int = 16
    max_pending: int = 256
    shadow_batch: int = 32
    chunk: int = 1 << 16
    max_rows: int = 1 << 18
    drift_budget: float = 0.05
    poll_ms: float = 50.0
    seed: int = 0

    def __post_init__(self):
        if self.window < 1 or self.min_window < 1 \
                or self.max_pending < 1:
            raise ValueError("QualityConfig: window, min_window and "
                             "max_pending must be >= 1")
        if not 0.0 < self.drift_budget < 1.0:
            raise ValueError("QualityConfig: drift_budget must be in "
                             "(0, 1)")


class QualityMonitor:
    """The always-on quality signal: reservoir-sampled live queries,
    shadow-scored exactly on a background thread, folded into windowed
    ``raft.obs.quality.*`` gauges. Construct with any scorer exposing
    ``.topk(queries, k) -> (nq, k) ids`` (tests plant fakes); attach to
    a server via :meth:`raft_tpu.serve.SearchServer.enable_quality`.

    ``estimator`` (optional, ``fn(queries, k) -> ids``) is the CHEAP
    recall estimator being calibrated — e.g. the unrescored PQ search;
    it runs on the shadow thread over the same samples and
    ``raft.obs.quality.calibration.gap`` publishes shadow − estimator
    recall, the gap ``bench_suite`` could previously only see offline.
    """

    # static race contract (tools/graftlint GL003): the dispatcher
    # thread (offer), the shadow thread (_loop/_process) and the epoch
    # listener (note_epoch, on the compactor thread) meet on these
    # fields — touch them only under `with self._cond` or in a
    # `_locked`-suffix method
    GUARDED_BY = ("_pending", "_streamed", "_inflight", "_closed",
                  "_windows", "_est_windows", "_epoch", "_baseline",
                  "_alarmed", "_samples_total")

    def __init__(self, scorer, sample_rate: float,
                 config: Optional[QualityConfig] = None,
                 family: str = "index",
                 estimator: Optional[Callable] = None,
                 start: bool = True):
        expects(0.0 < sample_rate <= 1.0,
                "QualityMonitor: sample_rate must be in (0, 1], got "
                "%s (rate 0 means: do not construct a monitor)",
                sample_rate)
        self.cfg = config if config is not None else QualityConfig()
        self.scorer = scorer
        self.rate = float(sample_rate)
        self.family = str(family)
        self._estimator = estimator
        self._rng = random.Random(self.cfg.seed)
        self._cond = threading.Condition()
        self._pending: List[tuple] = []
        self._streamed = 0          # reservoir stream length since drain
        self._inflight = False
        self._closed = False
        self._windows: Dict[tuple, deque] = {}
        self._est_windows: Dict[tuple, deque] = {}
        self._epoch = 0
        self._baseline: Optional[Tuple[int, float]] = None
        self._alarmed: set = set()
        self._card_warned = False
        self._samples_total = 0
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "QualityMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="raft-obs-quality")
            self._thread.start()
        return self

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def __enter__(self) -> "QualityMonitor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- sampling (dispatcher thread) --------------------------------------
    def offer(self, queries, ids, k: int, epoch: int = 0,
              coverage: float = 1.0, excluded: str = "") -> None:
        """Sample served queries into the reservoir (called by the
        batcher on its dispatcher thread — per-query Bernoulli draw,
        then a bounded copy; never any device work). ``coverage`` < 1
        flags a partial-mesh failover answer: those samples land in
        coverage-attributed series so degraded recall has a cause
        attached, and never pollute the full-coverage drift baseline."""
        # benign racy read: a sample racing close() is dropped either
        # way; the reservoir insert below re-checks nothing on purpose
        if self._closed:  # graftlint: disable=GL003
            return
        rng, rate = self._rng, self.rate
        q = np.asarray(queries)
        take = [j for j in range(q.shape[0]) if rng.random() < rate]
        if not take:
            return
        served = np.asarray(ids)
        k = int(k)
        obs.counter("raft.obs.quality.sampled.total").inc(len(take))
        cap = self.cfg.max_pending
        with self._cond:
            for j in take:
                rec = (q[j].astype(np.float32, copy=True),
                       served[j, :k].astype(np.int64, copy=True),
                       k, int(epoch), float(coverage), str(excluded))
                self._streamed += 1
                if len(self._pending) < cap:
                    self._pending.append(rec)
                else:
                    # algorithm R: uniform over the whole stream since
                    # the last shadow drain — a burst can neither grow
                    # memory nor bias the reservoir toward its tail
                    j = rng.randrange(self._streamed)
                    if j < cap:
                        self._pending[j] = rec
                    obs.counter("raft.obs.quality.evicted.total").inc()
            self._cond.notify()

    def note_epoch(self, epoch: int) -> None:
        """Roll the drift baseline at a compaction boundary — wired as
        a :meth:`raft_tpu.mutate.MutableIndex.add_epoch_listener`
        callback so the window split lands exactly where the fold did.
        (Samples tagged with a newer epoch roll it implicitly too.)"""
        with self._cond:
            self._roll_epoch_locked(int(epoch))

    # -- results -----------------------------------------------------------
    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every pending sample has been shadow-scored
        (tests / bench hooks) → False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._pending or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(timeout=left)
        return True

    def stats(self) -> dict:
        """Current-window summary (the loadgen/bench report row)."""
        with self._cond:
            cur = self._windows.get((self._epoch, "full", ""))
            est = self._est_windows.get((self._epoch, "full", ""))
            out = {
                "epoch": self._epoch,
                "samples": self._samples_total,
                "window": len(cur) if cur else 0,
                "recall": (round(float(np.mean(cur)), 4)
                           if cur else None),
            }
            if est:
                out["estimator_recall"] = round(float(np.mean(est)), 4)
                if cur:
                    out["calibration_gap"] = round(
                        float(np.mean(cur)) - float(np.mean(est)), 4)
            if self._baseline is not None and cur \
                    and len(cur) >= self.cfg.min_window:
                out["drift"] = round(
                    self._baseline[1] - float(np.mean(cur)), 4)
                out["drift_alarm"] = self._epoch in self._alarmed
            return out

    # -- shadow thread -----------------------------------------------------
    def _loop(self) -> None:
        poll = self.cfg.poll_ms / 1e3
        log = get_logger("obs")
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait(timeout=poll)
                if self._closed and not self._pending:
                    return
                batch = self._pending
                self._pending = []
                self._streamed = 0
                self._inflight = True
            try:
                self._process(batch)
            except Exception as e:
                obs.counter("raft.obs.quality.errors.total").inc()
                log.warning("quality: shadow batch failed (%d samples "
                            "dropped): %r", len(batch), e)
            finally:
                with self._cond:
                    self._inflight = False
                    self._cond.notify_all()

    def _process(self, batch: List[tuple]) -> None:
        rows = np.stack([s[0] for s in batch])
        kmax = max(s[2] for s in batch)
        with spans.span("raft.obs.quality.shadow", family=self.family,
                        queries=len(batch), kmax=kmax):
            exact = np.asarray(self.scorer.topk(rows, kmax))
            est = (np.asarray(self._estimator(rows, kmax))
                   if self._estimator is not None else None)
        obs.counter("raft.obs.quality.shadow.total",
                    family=self.family).inc()
        obs.counter("raft.obs.quality.samples.total").inc(len(batch))
        with self._cond:
            for i, (_q, served, k, epoch, coverage, excl) in \
                    enumerate(batch):
                if epoch > self._epoch:
                    self._roll_epoch_locked(epoch)
                ex = set(int(v) for v in exact[i, :k] if v >= 0)
                r = (len(ex.intersection(int(v) for v in served))
                     / max(1, len(ex) if len(ex) < k else k))
                cov = "full" if coverage >= 1.0 else "partial"
                key = (epoch, cov, excl if cov == "partial" else "")
                self._win(self._windows, key).append(r)
                if est is not None:
                    e_ids = set(int(v) for v in est[i, :k] if v >= 0)
                    self._win(self._est_windows, key).append(
                        len(ex & e_ids)
                        / max(1, len(ex) if len(ex) < k else k))
            self._samples_total += len(batch)
            self._update_gauges_locked()

    def _win(self, table: Dict[tuple, deque], key: tuple) -> deque:
        w = table.get(key)
        if w is None:
            w = table[key] = deque(maxlen=self.cfg.window)
        return w

    def _roll_epoch_locked(self, epoch: int) -> None:
        if epoch <= self._epoch:
            return
        prev = self._windows.get((self._epoch, "full", ""))
        if prev is not None and len(prev) >= self.cfg.min_window:
            # the outgoing epoch's settled window becomes the drift
            # baseline; a short-lived epoch keeps the older baseline
            # (comparing against noise would fire false folds)
            self._baseline = (self._epoch, float(np.mean(prev)))
        self._epoch = epoch
        obs.gauge("raft.obs.quality.drift.alarm",
                  family=self.family).set(0.0)

    def _update_gauges_locked(self) -> None:
        try:
            self._publish_locked()
        except CardinalityError:
            # the epoch label is the only unbounded one; past the
            # registry cap new epoch series are dropped, loudly once
            if not self._card_warned:
                self._card_warned = True
                get_logger("obs").warning(
                    "quality: raft.obs.quality.* label cardinality "
                    "cap hit — raise RAFT_TPU_METRICS_MAX_SERIES or "
                    "restart the monitor; further epoch series are "
                    "dropped")

    def _publish_locked(self) -> None:
        for (epoch, cov, excl), win in self._windows.items():
            if not win:
                continue
            labels = {"family": self.family, "epoch": str(epoch)}
            if cov == "partial":
                labels["coverage"] = "partial"
                if excl:
                    labels["excluded"] = excl
            obs.gauge("raft.obs.quality.recall", **labels).set(
                float(np.mean(win)))
        cur = self._windows.get((self._epoch, "full", ""))
        est = self._est_windows.get((self._epoch, "full", ""))
        if est:
            obs.gauge("raft.obs.quality.estimator.recall",
                      family=self.family,
                      epoch=str(self._epoch)).set(float(np.mean(est)))
            if cur:
                obs.gauge("raft.obs.quality.calibration.gap",
                          family=self.family).set(
                    float(np.mean(cur)) - float(np.mean(est)))
        obs.gauge("raft.obs.quality.window.samples",
                  family=self.family).set(len(cur) if cur else 0)
        if self._baseline is None or not cur \
                or len(cur) < self.cfg.min_window:
            return
        drift = self._baseline[1] - float(np.mean(cur))
        obs.gauge("raft.obs.quality.drift", family=self.family).set(
            drift)
        if drift > self.cfg.drift_budget:
            if self._epoch not in self._alarmed:
                self._alarmed.add(self._epoch)
                obs.counter("raft.obs.quality.drift.total",
                            family=self.family).inc()
                get_logger("obs").warning(
                    "quality: epoch %d recall drifted %.4f below the "
                    "epoch-%d baseline (budget %.4f) — fold degraded "
                    "the index past budget", self._epoch, drift,
                    self._baseline[0], self.cfg.drift_budget)
            obs.gauge("raft.obs.quality.drift.alarm",
                      family=self.family).set(1.0)
        elif self._epoch not in self._alarmed:
            obs.gauge("raft.obs.quality.drift.alarm",
                      family=self.family).set(0.0)
