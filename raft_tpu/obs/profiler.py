"""Continuous resource profiler — device time, duty cycle, HBM.

Every signal the serving stack exports is host-side wall clock: a p99
histogram bucket, a queue-delay span, a batcher ``load()`` snapshot.
None of them distinguishes "the chip was busy" from "the host was
queueing / compiling / transferring" — the axis TPU-KNN's peak-FLOP/s
reasoning (arxiv 2206.14286) and memory-budgeted execution (Memory
Safe Computations with XLA, arxiv 2206.14148) both need measured, not
inferred. This module is that device-truth layer, three coordinated
parts under one always-cheap admission gate:

* **device-time attribution** — at ``RAFT_TPU_PROFILE_SAMPLE`` rate
  (default 0.01; the root-admission pattern of
  ``RAFT_TPU_TRACE_SAMPLE``) a serving dispatch already being synced on
  the dispatcher thread is timed in two halves: host work up to the
  enqueue (``raft.obs.profile.host.seconds{program,family,rung}``) and
  the ``block_until_ready`` wait that follows
  (``raft.obs.profile.device.seconds{...}``). Sampled device-seconds,
  extrapolated by the sample rate over a rolling window, yield the
  **duty-cycle gauge** ``raft.obs.profile.duty_cycle{device}`` — the
  "is the chip actually busy" number the batcher, fleet router and
  bench rows previously inferred from queue depth. Unsampled
  dispatches read exactly one ``None`` flag (the PR 3 discipline); a
  sampled dispatch adds zero syncs (the sites only profile dispatches
  that were blocking anyway) and zero compiles.
* **HBM accounting** — a background sampler polls
  :func:`raft_tpu.core.memory.hbm_stats` per device into
  ``raft.obs.profile.hbm.{bytes_in_use,peak_bytes,limit_bytes,
  headroom_frac}{device}`` gauges; when the worst device's headroom
  fraction falls below ``hbm_headroom_frac`` the
  ``raft.obs.profile.hbm.low_headroom`` gauge trips and ``/healthz``
  degrades — the guardrail ROADMAP item 3's cold-list fetches will
  budget against. A compile-time ledger
  (``raft.obs.profile.compile.seconds{program}``) accumulates the
  plan/mutate AOT builds (the existing ``raft.plan.build.total``
  sites) so "the chip was idle because the host was compiling" is a
  number, not a guess.
* **surfaces** — ``GET /debug/profile``
  (:mod:`raft_tpu.obs.endpoint`): per-program device/host split, duty
  cycles, the HBM table, top-N device-time programs; sampled requests
  gain one measured ``raft.obs.profile.sync`` child span in the
  Chrome-trace export (``attributed=False`` — this one is real); and
  the fleet router folds per-replica duty cycle into
  ``router.report()`` so p2c load and measured utilization sit side by
  side (the batcher tags its dispatcher thread with the replica name).

Zero-overhead contract (asserted in tests/test_profiler.py): at rate 0
nothing attaches — no state object, no thread, no gauges; every hook
site reads one module-level ``None``. At rate > 0 the only work on an
unsampled dispatch is one Bernoulli draw, and a sampled dispatch
performs zero steady-state compiles (the split is pure
``perf_counter`` arithmetic around a sync the dispatcher already
owed).

Caveats, stated rather than hidden:

* the device half of the split is "time from enqueue-complete to
  results-ready" — on an otherwise-idle device that IS kernel time;
  under pipelined back-to-back dispatches it includes waiting for
  earlier programs (still the right number for duty cycle, which asks
  how long the chip was busy, not who kept it busy).
* duty cycle extrapolates sampled device-seconds by ``1/rate`` over
  the window; at low rates and low traffic the gauge is noisy — widen
  ``RAFT_TPU_PROFILE_WINDOW`` or raise the rate when it matters.
* on backends without allocator stats (CPU) ``hbm_stats`` falls back
  to summing live jax arrays against physical RAM (``source:
  live_arrays``) — an approximation good for trend lines and the
  smoke tests, not for HBM capacity planning.

See docs/observability.md "Resource observability" for the taxonomy,
the knobs, and the low-duty-cycle diagnosis walkthrough.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from raft_tpu import obs
from raft_tpu.core.logger import get_logger
from raft_tpu.obs import spans

__all__ = [
    "ProfilerConfig",
    "SYNC_SPAN",
    "enable_profiling",
    "disable_profiling",
    "set_profile_sample_rate",
    "profile_sample_rate",
    "sampled",
    "record_dispatch",
    "record_sample",
    "note_compile",
    "tag_dispatch",
    "report",
    "endpoint_body",
    "duty_cycle",
]

_ENV_RATE = "RAFT_TPU_PROFILE_SAMPLE"
_ENV_WINDOW = "RAFT_TPU_PROFILE_WINDOW"
_ENV_HBM_MS = "RAFT_TPU_PROFILE_HBM_MS"
_ENV_HEADROOM = "RAFT_TPU_PROFILE_HBM_HEADROOM"

# the sampled-sync child span (REQUIRED_SPAN_NAMES): unlike the
# raft.plan.stage.* children this one is MEASURED, not attributed
SYNC_SPAN = _SYNC_SPAN = "raft.obs.profile.sync"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_rate() -> float:
    return min(max(_env_float(_ENV_RATE, 0.01), 0.0), 1.0)


class ProfilerConfig:
    """Knobs of the attached profiler state (env defaults; every field
    overridable through :func:`enable_profiling`).

    * ``window_s`` — the duty-cycle window: sampled device-seconds are
      summed over the trailing window and extrapolated by ``1/rate``.
    * ``hbm_poll_ms`` — HBM sampler cadence (0 disables the thread —
      dispatch attribution only).
    * ``hbm_headroom_frac`` — the ``/healthz`` guardrail: worst-device
      ``(limit − in_use) / limit`` below this trips
      ``raft.obs.profile.hbm.low_headroom``.
    * ``top_n`` — how many programs the ``/debug/profile`` top table
      carries.
    """

    __slots__ = ("window_s", "hbm_poll_ms", "hbm_headroom_frac",
                 "top_n")

    def __init__(self, window_s: Optional[float] = None,
                 hbm_poll_ms: Optional[float] = None,
                 hbm_headroom_frac: Optional[float] = None,
                 top_n: int = 10):
        self.window_s = float(window_s if window_s is not None
                              else _env_float(_ENV_WINDOW, 30.0))
        self.hbm_poll_ms = float(hbm_poll_ms if hbm_poll_ms is not None
                                 else _env_float(_ENV_HBM_MS, 500.0))
        self.hbm_headroom_frac = float(
            hbm_headroom_frac if hbm_headroom_frac is not None
            else _env_float(_ENV_HEADROOM, 0.1))
        self.top_n = int(top_n)
        if self.window_s <= 0:
            raise ValueError("ProfilerConfig: window_s must be > 0")
        if not 0.0 <= self.hbm_headroom_frac < 1.0:
            raise ValueError("ProfilerConfig: hbm_headroom_frac must "
                             "be in [0, 1)")


class _ProfilerState:
    """The attached profiler: per-(program, family, rung) and per-tag
    rolling windows of sampled dispatch splits, the HBM sampler
    thread, and the compile ledger. One instance lives in the module
    ``_STATE`` slot while profiling is on; ``None`` IS the off switch
    every hook site reads."""

    # static race contract (tools/graftlint GL003): dispatcher threads
    # (record/note_compile), the HBM sampler thread (_hbm_loop /
    # _refresh_duty_locked) and report() readers meet on these fields —
    # touch them only under `with self._lock` or in a `_locked`-suffix
    # method
    GUARDED_BY = ("_prog", "_tags", "_compile", "_hbm_peak",
                  "_started", "_closed", "_samples")

    def __init__(self, rate: float, config: ProfilerConfig,
                 seed: Optional[int] = None):
        self.rate = float(rate)
        self.cfg = config
        self._lock = threading.Lock()
        # admission RNG: intentionally outside GUARDED_BY — same as the
        # spans sampler, a racy draw only perturbs WHICH dispatch is
        # sampled, never correctness (CPython method call is atomic
        # enough for a Bernoulli gate)
        self._rng = random.Random(seed)
        self._t0 = time.monotonic()
        # (program, family, rung) -> deque[(t_mono, device_s, host_s)]
        self._prog: Dict[tuple, deque] = {}
        # dispatch tag (fleet replica name) -> deque[(t_mono, device_s)]
        self._tags: Dict[str, deque] = {}
        self._compile: Dict[str, float] = {}
        self._hbm_peak: Dict[str, int] = {}
        self._samples = 0
        self._started = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the HBM sampler thread (idempotent; no-op when
        ``hbm_poll_ms`` is 0)."""
        with self._lock:
            if self._started or self._closed:
                return
            self._started = True
        if self.cfg.hbm_poll_ms > 0:
            self._thread = threading.Thread(
                target=self._hbm_loop, daemon=True,
                name="raft-obs-profiler")
            self._thread.start()

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- ledger (dispatcher threads) ---------------------------------------
    def record(self, program: str, family: str, rung: str,
               host_s: float, device_s: float, tag: str) -> None:
        obs.counter("raft.obs.profile.samples.total",
                    program=program).inc()
        obs.counter("raft.obs.profile.device.seconds", program=program,
                    family=family, rung=rung).inc(device_s)
        obs.counter("raft.obs.profile.host.seconds", program=program,
                    family=family, rung=rung).inc(host_s)
        now = time.monotonic()
        with self._lock:
            key = (program, family, rung)
            win = self._prog.get(key)
            if win is None:
                win = self._prog[key] = deque()
            win.append((now, device_s, host_s))
            if tag:
                tw = self._tags.get(tag)
                if tw is None:
                    tw = self._tags[tag] = deque()
                tw.append((now, device_s))
            self._samples += 1
            self._refresh_duty_locked(now)

    def note_compile(self, program: str, seconds: float) -> None:
        obs.counter("raft.obs.profile.compile.seconds",
                    program=program).inc(seconds)
        with self._lock:
            self._compile[program] = (self._compile.get(program, 0.0)
                                      + seconds)

    # -- duty cycle --------------------------------------------------------
    def _window_span_locked(self, now: float) -> float:
        """The effective window: the configured span, shortened while
        the profiler is younger than it (a fresh attach must not read
        as near-zero duty cycle for window_s seconds)."""
        return max(min(self.cfg.window_s, now - self._t0), 1e-3)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.cfg.window_s
        for table in (self._prog, self._tags):
            for win in table.values():
                while win and win[0][0] < horizon:
                    win.popleft()

    def _refresh_duty_locked(self, now: float) -> None:
        self._prune_locked(now)
        span_s = self._window_span_locked(now)
        dev_total = sum(rec[1] for win in self._prog.values()
                        for rec in win)
        duty = min(dev_total / self.rate / span_s, 1.0)
        obs.gauge("raft.obs.profile.duty_cycle",
                  device=_device_label()).set(round(duty, 6))

    def duty_cycle(self, tag: Optional[str] = None) -> float:
        """Extrapolated duty cycle over the trailing window — global,
        or restricted to one dispatch tag (a fleet replica name)."""
        now = time.monotonic()
        with self._lock:
            self._prune_locked(now)
            span_s = self._window_span_locked(now)
            if tag is None:
                dev = sum(rec[1] for win in self._prog.values()
                          for rec in win)
            else:
                dev = sum(d for _, d in self._tags.get(tag, ()))
            return min(dev / self.rate / span_s, 1.0)

    # -- HBM sampler thread ------------------------------------------------
    def _hbm_loop(self) -> None:
        from raft_tpu.core import memory as _memory
        log = get_logger("obs")
        poll_s = self.cfg.hbm_poll_ms / 1e3
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                self._sample_hbm(_memory)
            except Exception as e:
                obs.counter("raft.obs.profile.errors.total").inc()
                log.warning("profiler: HBM sample failed: %r", e)
            now = time.monotonic()
            with self._lock:
                if self._closed:
                    return
                self._refresh_duty_locked(now)
            self._wake.wait(timeout=poll_s)

    def _sample_hbm(self, _memory) -> None:
        import jax
        worst_headroom = None
        for dev in jax.local_devices():
            stats = _memory.hbm_stats(dev)
            if not stats:
                continue
            label = f"{dev.platform}:{dev.id}"
            in_use = int(stats.get("bytes_in_use", 0))
            limit = int(stats.get("bytes_limit", 0))
            with self._lock:
                peak = max(self._hbm_peak.get(label, 0), in_use,
                           int(stats.get("peak_bytes_in_use", 0)))
                self._hbm_peak[label] = peak
            obs.gauge("raft.obs.profile.hbm.bytes_in_use",
                      device=label).set(in_use)
            obs.gauge("raft.obs.profile.hbm.peak_bytes",
                      device=label).set(peak)
            obs.gauge("raft.obs.profile.hbm.limit_bytes",
                      device=label).set(limit)
            if limit > 0:
                headroom = max(0.0, (limit - in_use) / limit)
                obs.gauge("raft.obs.profile.hbm.headroom_frac",
                          device=label).set(round(headroom, 6))
                if worst_headroom is None or headroom < worst_headroom:
                    worst_headroom = headroom
        if worst_headroom is not None:
            low = worst_headroom < self.cfg.hbm_headroom_frac
            obs.gauge("raft.obs.profile.hbm.low_headroom").set(
                1.0 if low else 0.0)

    # -- report ------------------------------------------------------------
    def report(self) -> dict:
        now = time.monotonic()
        with self._lock:
            self._prune_locked(now)
            span_s = self._window_span_locked(now)
            programs: List[dict] = []
            for (program, family, rung), win in self._prog.items():
                if not win:
                    continue
                dev = sum(r[1] for r in win)
                host = sum(r[2] for r in win)
                programs.append({
                    "program": program,
                    "family": family,
                    "rung": rung,
                    "samples": len(win),
                    "device_s": round(dev, 6),
                    "host_s": round(host, 6),
                    "device_frac": round(dev / max(dev + host, 1e-12),
                                         4),
                    "duty_cycle": round(
                        min(dev / self.rate / span_s, 1.0), 6),
                })
            tags = {}
            for tag, win in self._tags.items():
                if not win:
                    continue
                dev = sum(d for _, d in win)
                tags[tag] = {
                    "samples": len(win),
                    "device_s": round(dev, 6),
                    "duty_cycle": round(
                        min(dev / self.rate / span_s, 1.0), 6),
                }
            compile_s = dict(self._compile)
            samples = self._samples
            hbm_peak = dict(self._hbm_peak)
        programs.sort(key=lambda p: p["device_s"], reverse=True)
        dev_total = sum(p["device_s"] for p in programs)
        host_total = sum(p["host_s"] for p in programs)
        gauges = obs.snapshot().get("gauges", {})
        hbm = _hbm_table(gauges)
        for label, peak in hbm_peak.items():
            hbm.setdefault(label, {})["peak_bytes"] = peak
        return {
            "enabled": True,
            "rate": self.rate,
            "window_s": round(span_s, 3),
            "samples": samples,
            "duty_cycle": round(
                min(dev_total / self.rate / span_s, 1.0), 6),
            "device_s": round(dev_total, 6),
            "host_s": round(host_total, 6),
            "programs": programs,
            "top": programs[:self.cfg.top_n],
            "tags": tags,
            "compile_seconds": {k: round(v, 4)
                                for k, v in compile_s.items()},
            "hbm": hbm,
        }


# module-level attach point: None IS the off state (one read per hook)
_STATE: Optional[_ProfilerState] = None
_TLS = threading.local()
_device_label_cache: Optional[str] = None


def _device_label() -> str:
    global _device_label_cache
    if _device_label_cache is None:
        try:
            import jax
            d = jax.devices()[0]
            _device_label_cache = f"{d.platform}:{d.id}"
        except Exception:
            _device_label_cache = "unknown:0"
    return _device_label_cache


def _hbm_table(gauges: dict) -> dict:
    """The per-device HBM table out of exported gauges (shared by the
    live report and the gauges-only endpoint fallback)."""
    table: Dict[str, dict] = {}
    for series, value in gauges.items():
        name, _, labels = series.partition("{")
        if not name.startswith("raft.obs.profile.hbm.") \
                or name.endswith("low_headroom"):
            continue
        dev = "all"
        for part in labels.rstrip("}").split(","):
            if part.startswith("device="):
                dev = part[len("device="):]
        table.setdefault(dev, {})[name.rsplit(".", 1)[1]] = value
    return table


# ---------------------------------------------------------------------------
# public API — hook-site functions (hot path) and lifecycle
# ---------------------------------------------------------------------------


def enable_profiling(rate: Optional[float] = None,
                     config: Optional[ProfilerConfig] = None,
                     seed: Optional[int] = None,
                     start: bool = True) -> Optional[_ProfilerState]:
    """Attach (or re-attach) the profiler at ``rate`` (default: the
    ``RAFT_TPU_PROFILE_SAMPLE`` env, 0.01) and start the HBM sampler
    (``start=False`` defers the thread to the first sampled dispatch —
    the import-time env attach uses this so merely importing never
    spawns a thread). Rate 0 detaches instead — after it every hook
    site is back to one ``None`` read. Returns the attached state
    (None at rate 0)."""
    global _STATE
    rate = _env_rate() if rate is None else min(max(float(rate), 0.0),
                                                1.0)
    prev, _STATE = _STATE, None
    if prev is not None:
        prev.close()
    if rate <= 0:
        return None
    st = _ProfilerState(rate, config if config is not None
                        else ProfilerConfig(), seed=seed)
    if start:
        st.start()
    _STATE = st
    return st


def disable_profiling() -> None:
    """Detach: stop the sampler thread, drop the ledger. Hook sites
    are back to one ``None`` read."""
    enable_profiling(0.0)


def set_profile_sample_rate(rate: float, seed: Optional[int] = None
                            ) -> None:
    """Runtime rate setter (the :func:`spans.set_trace_sample_rate`
    shape): > 0 attaches/re-attaches, 0 detaches."""
    enable_profiling(rate, seed=seed)


def profile_sample_rate() -> float:
    st = _STATE
    return st.rate if st is not None else 0.0


def state() -> Optional[_ProfilerState]:
    """The attached profiler state, or None while profiling is off."""
    return _STATE


def sampled() -> bool:
    """Root admission for one dispatch: False when profiling is off
    (one module-level ``None`` read — the whole cost of an unsampled
    or unprofiled dispatch) or when this dispatch loses the Bernoulli
    draw."""
    st = _STATE
    if st is None:
        return False
    if st.rate < 1.0 and st._rng.random() >= st.rate:
        return False
    # deferred thread start (the import-time env attach): idempotent,
    # one brief lock on the sampled (≤ rate) path only
    st.start()
    return True


def tag_dispatch(tag: str) -> None:
    """Tag this thread's subsequent sampled dispatches (the batcher
    calls this with its replica name before dispatching — the fleet
    report's per-replica utilization fold). One ``None`` read when
    profiling is off."""
    if _STATE is None:
        return
    _TLS.tag = tag


def record_dispatch(t_start: float, t_enq: float, result=None, *,
                    program: str, family: str = "",
                    rung="") -> None:
    """Record one sampled dispatch: ``t_start``/``t_enq`` are
    ``perf_counter`` stamps at dispatch start and enqueue-complete;
    ``result`` (a pytree of jax arrays) is blocked on HERE — pass None
    when the caller already synchronized (the comms sync_stream path).
    The split lands in the ledger, the counters, and one measured
    ``raft.obs.profile.sync`` child span under the current request."""
    if result is not None:
        import jax
        jax.block_until_ready(result)
    t_done = time.perf_counter()
    st = _STATE
    if st is None:        # raced a detach: the sync already happened
        return
    host_s = max(t_enq - t_start, 0.0)
    device_s = max(t_done - t_enq, 0.0)
    tag = getattr(_TLS, "tag", "")
    st.record(program, family, str(rung), host_s, device_s, tag)
    spans.add_child_span(
        _SYNC_SPAN, t_enq, device_s, program=program,
        host_ms=round(host_s * 1e3, 3),
        device_ms=round(device_s * 1e3, 3))


def record_sample(*, program: str, family: str = "", rung="",
                  host_s: float, device_s: float) -> None:
    """Lower-level ledger entry for a site that measured its own
    split — ``SearchPlan.search`` uses it so the host half covers the
    WHOLE call (query conversion before the enqueue and span/trace
    work after the sync included), not just the enqueue window. The
    site records its own ``raft.obs.profile.sync`` child span at the
    sync point, where the request trace is still open."""
    st = _STATE
    if st is None:
        return
    st.record(program, family, str(rung), max(host_s, 0.0),
              max(device_s, 0.0), getattr(_TLS, "tag", ""))


def note_compile(program: str, seconds: float) -> None:
    """Accumulate one AOT build into the compile ledger (called from
    the ``raft.plan.build.total`` sites). One ``None`` read when
    profiling is off."""
    st = _STATE
    if st is None:
        return
    st.note_compile(program, float(seconds))


def duty_cycle(tag: Optional[str] = None) -> Optional[float]:
    """The extrapolated duty cycle over the trailing window (None when
    profiling is off). ``tag`` restricts to one dispatch tag — the
    fleet router passes each replica's name."""
    st = _STATE
    if st is None:
        return None
    return st.duty_cycle(tag)


def report() -> dict:
    """The full profiler report (the ``/debug/profile`` body): duty
    cycles, per-program device/host splits, the top device-time table,
    per-tag (replica) utilization, the compile ledger, the HBM table."""
    st = _STATE
    if st is None:
        return {"enabled": False, "rate": 0.0}
    return st.report()


def endpoint_body(snapshot: dict) -> dict:
    """``GET /debug/profile`` body: the in-process profiler's full
    report when one is attached, else reconstructed from the exported
    ``raft.obs.profile.*`` gauges (another process's scrape)."""
    st = _STATE
    if st is not None:
        return st.report()
    gauges = snapshot.get("gauges", {})
    prof = {k: v for k, v in gauges.items()
            if k.split("{")[0].startswith("raft.obs.profile.")}
    if not prof:
        return {"enabled": False, "rate": 0.0,
                "error": "no profiler attached and no "
                         "raft.obs.profile.* gauges exported"}
    return {"enabled": False, "source": "gauges",
            "duty_cycle": {k: v for k, v in prof.items()
                           if k.split("{")[0]
                           == "raft.obs.profile.duty_cycle"},
            "hbm": _hbm_table(gauges)}


# ambient opt-in (the RAFT_TPU_TRACE_SAMPLE pattern): an explicitly
# set env rate attaches at import — the sampler thread waits for the
# first sampled dispatch, so importing alone never spawns a thread
if os.environ.get(_ENV_RATE):
    _env_v = _env_rate()
    if _env_v > 0:
        enable_profiling(_env_v, start=False)
    del _env_v
