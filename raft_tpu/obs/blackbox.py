"""Crash-durable black box — observability that survives the process
(ISSUE 18).

Everything the observability planes know — history frames
(:mod:`raft_tpu.obs.history`), the flight recorder's trace + slow
rings, profiler/duty-cycle state, fleet replica transitions, the
``/healthz`` verdict — lives in process memory and dies with the
process. A ``kill_replica`` chaos kill, an OOM, or a hung TPU round
leaves zero evidence. The black box spills those sections to disk as
**CRC'd, length-prefixed, atomically-rotated segments** so
``tools/doctor.py`` can diagnose a corpse.

On-disk format (binary framing, JSON payloads, no pickling — a torn
tail must be recognizable, never executable; same framing lessons as
the mutation WAL v2, :mod:`raft_tpu.mutate.wal`)::

    segment  bb-%06d.open (active) / bb-%06d.seg (sealed)
    header   8 bytes   b"RTPUBBX1"
    record   u32 payload_length | u32 crc32(payload) | payload
    payload  compact JSON: {"kind", "t_unix", "reason", "box", "data"}

Record kinds: ``meta`` (pid/box/flush reason), ``snapshot`` (full
registry snapshot), ``healthz`` (the endpoint verdict for that
snapshot), ``frames`` (new history frames since the last flush,
deduped by seq), ``traces`` (recorder recent + slow rings), ``profile``
(profiler report when attached), ``fleet`` (router/federator report
when wired).

Durability contract:

* a flush appends all sections, then ``flush`` + ``os.fsync`` — when
  :meth:`BlackBox.flush` returns, the dump survives kill -9;
* rotation seals the active ``.open`` segment via ``os.replace`` to
  ``.seg`` — the sealed name only ever appears complete — and prunes
  the oldest sealed segments beyond the retention cap;
* reopening a directory with a leftover ``.open`` (a crash) truncates
  its torn tail (CRC/length scan, counted under
  ``raft.obs.blackbox.torn.total``) and seals the intact prefix —
  exactly the WAL's never-wedge-on-your-own-crash-artifact rule. The
  ``faults.inject("obs.blackbox.append")`` site between header and
  payload writes lets tests manufacture the torn tail a real kill -9
  mid-write leaves.

Flush triggers: cadence (``RAFT_TPU_BLACKBOX_INTERVAL``, default 5 s),
the healthz ok→degraded edge (polled at 0.5 s so the flight recorder
captures the moment things went wrong, not just the cadence after),
SIGTERM, atexit, :meth:`Replica.kill`/``stop`` for per-replica boxes,
and explicit :func:`flush` calls.

Off means OFF: with ``RAFT_TPU_BLACKBOX`` unset/0 nothing attaches —
``_STATE`` stays ``None`` and every hook is one module-flag read (the
< 2 % serving-overhead gate in the acceptance criteria is structural,
not tuned). Ambient attach lives in ``raft_tpu/obs/__init__.py``, not
here, so ``tools/doctor.py`` can import this module to READ dumps
without ever starting a recorder.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import struct
import threading
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from raft_tpu import obs
from raft_tpu.core.logger import get_logger
from raft_tpu.obs import registry as _registry
from raft_tpu.testing import faults as _faults

__all__ = ["BlackBox", "disable_blackbox", "enable_blackbox",
           "enabled", "flush", "read_dump", "read_segment", "state"]

_log = get_logger("obs")

_MAGIC = b"RTPUBBX1"
_HDR = struct.Struct("<II")     # payload length, crc32(payload)
_MAX_RECORD = 1 << 28

_ENV_INTERVAL = "RAFT_TPU_BLACKBOX_INTERVAL"
_ENV_SEG_BYTES = "RAFT_TPU_BLACKBOX_SEGMENT_BYTES"
_ENV_SEGMENTS = "RAFT_TPU_BLACKBOX_SEGMENTS"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# -- segment reading (classless: the doctor reads dumps with no box) ------

def _iter_segment(path: str) -> Iterator[Tuple[dict, int]]:
    """Yield ``(record, end_offset)`` for every intact record; return
    (StopIteration value) the torn byte count, 0 = clean EOF — the
    WAL's ``_iter_file_records`` contract."""
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            return len(magic)
        off = len(_MAGIC)
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                return len(hdr)
            length, crc = _HDR.unpack(hdr)
            if length > _MAX_RECORD or length < 2:
                return _HDR.size
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return _HDR.size + len(payload)
            try:
                rec = json.loads(payload.decode("utf-8"))
            except Exception:   # graftlint: disable=GL006
                # checksummed-but-undecodable = version skew /
                # corruption boundary — treat as the crash boundary,
                # return the intact prefix (readers must never raise
                # on a dump)
                return _HDR.size + length
            off += _HDR.size + length
            yield rec, off


def read_segment(path: str) -> List[dict]:
    """Intact records of one segment (torn tail silently ends it)."""
    out: List[dict] = []
    it = _iter_segment(path)
    while True:
        try:
            rec, _ = next(it)
        except StopIteration:
            break
        out.append(rec)
    return out


def _segment_files(path: str) -> List[str]:
    try:
        names = os.listdir(path)
    except OSError:
        return []
    segs = sorted(n for n in names
                  if n.startswith("bb-") and n.endswith(".seg"))
    opens = sorted(n for n in names
                   if n.startswith("bb-") and n.endswith(".open"))
    return [os.path.join(path, n) for n in segs + opens]


def read_dump(path: str) -> List[dict]:
    """Every intact record of a black-box directory, in write order
    (sealed segments by sequence, then any still-open one). Tolerates
    the torn tail a kill -9 mid-write leaves — the doctor's loader."""
    out: List[dict] = []
    for p in _segment_files(path):
        out.extend(read_segment(p))
    return out


class BlackBox:
    """One crash-durable recorder writing rotating segments under
    ``dir`` (module docstring has the format + triggers)."""

    # static race contract (tools/graftlint GL003): the flush thread,
    # signal/atexit handlers, Replica.kill() and the owning caller all
    # meet on the segment state — touch only under `with self._lock`
    GUARDED_BY = ("_f", "_open_path", "_seg_seq", "_seg_bytes",
                  "_last_frame_seq", "_closed")

    def __init__(self, path: str, box: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 max_segment_bytes: Optional[int] = None,
                 max_segments: Optional[int] = None,
                 history: Optional[object] = None,
                 fleet: Optional[object] = None,
                 registry: Optional[object] = None):
        self.dir = os.path.abspath(path)
        self.box = box if box is not None else os.path.basename(
            self.dir.rstrip(os.sep)) or "default"
        self.interval_s = max(0.1, float(
            interval_s if interval_s is not None
            else _env_float(_ENV_INTERVAL, 5.0)))
        self.max_segment_bytes = max(4096, int(
            max_segment_bytes if max_segment_bytes is not None
            else _env_int(_ENV_SEG_BYTES, 4 << 20)))
        self.max_segments = max(2, int(
            max_segments if max_segments is not None
            else _env_int(_ENV_SEGMENTS, 8)))
        self._history = history
        self._fleet = fleet
        self._registry = (registry if registry is not None
                          else _registry.REGISTRY)
        self._lock = threading.Lock()
        self._f = None
        self._open_path: Optional[str] = None
        self._seg_seq = 0
        self._seg_bytes = 0
        self._last_frame_seq = 0
        self._closed = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._atexit_cb = None
        self._prev_sigterm = None
        os.makedirs(self.dir, exist_ok=True)
        torn = self._recover_dir()
        with self._lock:
            self._open_next_locked()
        if torn:
            obs.counter("raft.obs.blackbox.torn.total").inc(torn)
        # the baseline flush: even a box that dies before its first
        # cadence leaves a snapshot to diff the death frame against
        self.flush("start")

    # -- segment plumbing --------------------------------------------------
    def _recover_dir(self) -> int:
        """Seal any ``.open`` segment a crash left behind, truncating
        its torn tail first (CRC/length scan) → count of torn
        segments. The sealed intact prefix stays readable — the
        kill-9-mid-write recovery contract."""
        torn = 0
        for name in sorted(os.listdir(self.dir)):
            if not (name.startswith("bb-") and name.endswith(".open")):
                continue
            p = os.path.join(self.dir, name)
            good = len(_MAGIC)
            it = _iter_segment(p)
            torn_bytes = 0
            while True:
                try:
                    _, end = next(it)
                except StopIteration as stop:
                    torn_bytes = stop.value or 0
                    break
                good = end
            if torn_bytes:
                with open(p, "rb+") as f:
                    f.truncate(good)
                torn += 1
            os.replace(p, p[: -len(".open")] + ".seg")
        return torn

    def _seal_locked(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._open_path is not None:
            os.replace(self._open_path,
                       self._open_path[: -len(".open")] + ".seg")
            self._open_path = None

    def _open_next_locked(self) -> None:
        existing = [-1]
        for name in os.listdir(self.dir):
            if name.startswith("bb-") and (name.endswith(".seg")
                                           or name.endswith(".open")):
                try:
                    existing.append(int(name[3:9]))
                except ValueError:
                    pass
        self._seg_seq = max(existing) + 1
        self._open_path = os.path.join(self.dir,
                                       "bb-%06d.open" % self._seg_seq)
        # unbuffered: a kill -9 mid-flush must lose at most the
        # in-flight record (the torn tail recovery truncates), never a
        # whole flush sitting in a userspace buffer
        self._f = open(self._open_path, "wb", buffering=0)
        self._f.write(_MAGIC)
        self._seg_bytes = len(_MAGIC)

    def _prune_locked(self) -> None:
        sealed = sorted(n for n in os.listdir(self.dir)
                        if n.startswith("bb-") and n.endswith(".seg"))
        # the open segment counts toward retention
        while len(sealed) + 1 > self.max_segments:
            victim = sealed.pop(0)
            try:
                os.remove(os.path.join(self.dir, victim))
            except OSError:
                _log.warning("blackbox: prune failed for %s", victim)

    def _append_locked(self, kind: str, reason: str, data,
                       t_unix: float) -> int:
        payload = json.dumps(
            {"kind": kind, "t_unix": t_unix, "reason": reason,
             "box": self.box, "data": data},
            separators=(",", ":"), default=str).encode("utf-8")
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        # the kill-9-mid-write window: header on disk, payload not —
        # tests inject here to manufacture the torn tail recovery
        # must truncate
        _faults.inject("obs.blackbox.append", kind=kind,
                       box=self.box)
        self._f.write(payload)
        self._seg_bytes += _HDR.size + len(payload)
        return _HDR.size + len(payload)

    # -- section gathering (NO lock held — sections call into other
    # planes' locks; gathering inside ours would build lock-order
    # edges GL007 forbids) -------------------------------------------------
    def _gather(self, reason: str) -> List[Tuple[str, object]]:
        sections: List[Tuple[str, object]] = []
        sections.append(("meta", {
            "pid": os.getpid(), "box": self.box, "dir": self.dir,
            "reason": reason, "interval_s": self.interval_s}))
        snap = None
        try:
            snap = self._registry.snapshot()
            sections.append(("snapshot", snap))
        except Exception:
            _log.warning("blackbox: snapshot failed", exc_info=True)
        if snap is not None:
            try:
                from raft_tpu.obs import endpoint as _endpoint
                sections.append(("healthz",
                                 _endpoint._health_body(snap)))
            except Exception:
                _log.warning("blackbox: healthz failed",
                             exc_info=True)
        hist = self._history
        if hist is not None:
            try:
                with self._lock:
                    since = self._last_frame_seq
                frames = hist.frames_since(since)
                if frames:
                    sections.append(("frames", frames))
            except Exception:
                _log.warning("blackbox: frames failed", exc_info=True)
        try:
            from raft_tpu.obs import recorder as _recorder
            rec = _recorder.RECORDER
            sections.append(("traces", {
                "recent": rec.requests(16),
                "slow": rec.slow_requests(8),
                "recorded_total": rec.recorded_total}))
        except Exception:
            _log.warning("blackbox: traces failed", exc_info=True)
        try:
            from raft_tpu.obs import profiler as _profiler
            if _profiler.state() is not None:
                sections.append(("profile", _profiler.report()))
        except Exception:
            _log.warning("blackbox: profile failed", exc_info=True)
        fleet = self._fleet
        if fleet is not None:
            try:
                rep = fleet.report()     # router OR federator, duck-typed
                sections.append(("fleet", rep))
            except Exception:
                _log.warning("blackbox: fleet failed", exc_info=True)
        return sections

    # -- the durability point ----------------------------------------------
    def flush(self, reason: str = "cadence") -> int:
        """Append every section, fsync, maybe rotate → bytes written.
        When this returns the dump survives kill -9."""
        # wall clock by design (GL005): dump records are correlated
        # across processes (doctor vs replica vs loadgen) — the stamp
        # must be comparable to OTHER processes' clocks
        t_unix = time.time()  # graftlint: disable=GL005
        sections = self._gather(reason)
        wrote = 0
        with self._lock:
            if self._closed or self._f is None:
                return 0
            for kind, data in sections:
                try:
                    wrote += self._append_locked(kind, reason, data,
                                                 t_unix)
                except (TypeError, ValueError):
                    # an unserializable section must not sink the
                    # whole flush (the other sections are the
                    # forensics) — default=str makes this rare
                    _log.warning("blackbox: %s section not "
                                 "serializable; skipped", kind)
                if kind == "frames":
                    self._last_frame_seq = max(
                        [f.get("seq", 0) for f in data]
                        + [self._last_frame_seq])
            self._f.flush()
            # fsync IS the durability contract of this module; writers
            # are genuinely concurrent (flush thread / SIGTERM /
            # atexit / Replica.kill) so it must stay under the lock —
            # a blocked flush delays only other flushes, never serving
            os.fsync(self._f.fileno())  # graftlint: disable=GL008
            if self._seg_bytes >= self.max_segment_bytes:
                self._seal_locked()
                self._open_next_locked()
                self._prune_locked()
        # registry effects after the lock (keep the lock graph acyclic)
        obs.counter("raft.obs.blackbox.flushes.total",
                    reason=reason).inc()
        obs.counter("raft.obs.blackbox.bytes.total").inc(wrote)
        obs.gauge("raft.obs.blackbox.segments.total").set(
            float(self._count_segments()))
        return wrote

    def _count_segments(self) -> int:
        return len(_segment_files(self.dir))

    # -- triggers ----------------------------------------------------------
    def start(self) -> "BlackBox":
        """Start the cadence/degrade-edge flush thread."""
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="raft-obs-blackbox")
            self._thread.start()
        return self

    def _loop(self) -> None:
        # poll fast (0.5 s) for the healthz ok→degraded EDGE, flush on
        # cadence otherwise — the degrade flush is the record of the
        # moment things went wrong, not the cadence after
        poll = min(0.5, self.interval_s)
        last_flush = time.monotonic()
        was_degraded = False    # loop-local: only this thread edges
        while not self._stop.wait(poll):
            try:
                degraded = self._health_degraded()
                edge = degraded and not was_degraded
                was_degraded = degraded
                now = time.monotonic()
                if edge:
                    self.flush("degrade")
                    last_flush = now
                elif now - last_flush >= self.interval_s:
                    self.flush("cadence")
                    last_flush = now
            except Exception:
                # the flusher must outlive any single bad flush — a
                # dead thread IS the failure mode this module exists
                # to prevent
                _log.warning("blackbox: flush failed", exc_info=True)

    def _health_degraded(self) -> bool:
        try:
            from raft_tpu.obs import endpoint as _endpoint
            body = _endpoint._health_body(self._registry.snapshot())
            return body.get("status") != "ok"
        except Exception:   # graftlint: disable=GL006
            # healthz evaluation must never kill the flush loop; an
            # unevaluable health body is "not an edge", nothing more
            return False

    def install_exit_hooks(self, sigterm: bool = True) -> None:
        """Flush on atexit and (main thread only) SIGTERM; SIGTERM
        chains to the previous handler after flushing."""
        if self._atexit_cb is None:
            def _on_exit():
                try:
                    self.flush("atexit")
                except Exception:   # graftlint: disable=GL006
                    # interpreter teardown: logging may be gone; a
                    # failed last-gasp flush must not mask the exit
                    pass
            self._atexit_cb = _on_exit
            atexit.register(_on_exit)
        if sigterm and self._prev_sigterm is None:
            def _on_term(signum, frame):
                try:
                    self.flush("sigterm")
                except Exception:   # graftlint: disable=GL006
                    pass    # dying anyway; the flush was best-effort
                prev = self._prev_sigterm
                if callable(prev):
                    prev(signum, frame)
                elif prev == signal.SIG_DFL:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)
            try:
                self._prev_sigterm = signal.signal(
                    signal.SIGTERM, _on_term)
            except ValueError:
                # signal.signal only works on the main thread; the
                # atexit + cadence paths still cover this box
                self._prev_sigterm = None

    def set_history(self, history) -> None:
        self._history = history

    def set_fleet(self, fleet) -> None:
        self._fleet = fleet

    def close(self, flush: bool = True) -> None:
        """Final flush, seal the open segment, detach hooks."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if flush:
            try:
                self.flush("close")
            except Exception:
                _log.warning("blackbox: close flush failed",
                             exc_info=True)
        with self._lock:
            self._closed = True
            self._seal_locked()
        if self._atexit_cb is not None:
            try:
                atexit.unregister(self._atexit_cb)
            except Exception:   # graftlint: disable=GL006
                pass    # already unregistered / interpreter teardown
            self._atexit_cb = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass    # non-main thread: leave the handler in place
            self._prev_sigterm = None

    def report(self) -> dict:
        with self._lock:
            return {"enabled": True, "box": self.box, "dir": self.dir,
                    "interval_s": self.interval_s,
                    "segment": self._seg_seq,
                    "segment_bytes": self._seg_bytes,
                    "max_segment_bytes": self.max_segment_bytes,
                    "max_segments": self.max_segments,
                    "last_frame_seq": self._last_frame_seq}


# -- module state (None IS the off state; one flag read per hook) ---------

_STATE: Optional[BlackBox] = None


def enable_blackbox(path: str, box: Optional[str] = None,
                    interval_s: Optional[float] = None,
                    max_segment_bytes: Optional[int] = None,
                    max_segments: Optional[int] = None,
                    fleet: Optional[object] = None,
                    registry: Optional[object] = None,
                    start: bool = True,
                    exit_hooks: bool = True) -> BlackBox:
    """Install the ambient black box writing under ``path`` (a
    previous one is closed first). Auto-wires the attached metrics
    history when one exists."""
    global _STATE
    prev, _STATE = _STATE, None
    if prev is not None:
        prev.close()
    from raft_tpu.obs import history as _history
    bb = BlackBox(path, box=box, interval_s=interval_s,
                  max_segment_bytes=max_segment_bytes,
                  max_segments=max_segments,
                  history=_history.history(), fleet=fleet,
                  registry=registry)
    if exit_hooks:
        bb.install_exit_hooks()
    if start:
        bb.start()
    _STATE = bb
    return bb


def disable_blackbox(flush: bool = True) -> None:
    global _STATE
    prev, _STATE = _STATE, None
    if prev is not None:
        prev.close(flush=flush)


def state() -> Optional[BlackBox]:
    """The ambient box, or None (None IS the off state)."""
    return _STATE


def enabled() -> bool:
    return _STATE is not None


def flush(reason: str = "manual") -> int:
    """Flush the ambient box now (0 when none attached) — the hook
    other planes call on their own degrade edges."""
    st = _STATE
    if st is None:
        return 0
    return st.flush(reason)
