"""raft_tpu.obs — metrics + runtime telemetry.

The quantitative observability layer the reference never had (its story
is NVTX ranges + spdlog — our ``core/trace.py`` / ``core/logger.py``):
a dependency-free, thread-safe registry of counters, gauges and
fixed-boundary histograms, wired into every hot path (ops dispatch,
compile cache, IVF search/build, k-means, comms/health) under one
``raft.<module>.<op>`` naming taxonomy shared with the xprof trace
ranges.

Quick use::

    from raft_tpu import obs
    obs.counter("raft.myapp.requests", route="search").inc()
    with obs.timed("raft.myapp.handle"):
        ...
    print(obs.to_prometheus_text())   # scrape endpoint body
    state = obs.snapshot()            # JSON-ready dict

``RAFT_TPU_METRICS=0`` no-ops the whole registry. See
docs/observability.md for the taxonomy, the exporters and how
``obs.timed`` relates to profiler trace ranges.
"""

from raft_tpu.obs.registry import (
    REGISTRY,
    DEFAULT_BUCKETS,
    SIZE_BUCKETS,
    NAME_RE,
    CardinalityError,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    snapshot,
    snapshot_diff,
    to_prometheus_text,
    reset,
    set_enabled,
    enabled,
)
from raft_tpu.obs.timing import timed

__all__ = [
    "REGISTRY",
    "DEFAULT_BUCKETS",
    "SIZE_BUCKETS",
    "NAME_RE",
    "CardinalityError",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "snapshot_diff",
    "to_prometheus_text",
    "reset",
    "set_enabled",
    "enabled",
    "timed",
]
