"""raft_tpu.obs — metrics, request tracing + runtime telemetry.

The observability layer the reference never had (its story is NVTX
ranges + spdlog — our ``core/trace.py`` / ``core/logger.py``), in
three planes sharing ONE ``raft.<module>.<op>`` naming taxonomy:

* **metrics** (:mod:`raft_tpu.obs.registry`) — dependency-free,
  thread-safe counters/gauges/fixed-boundary histograms wired into
  every hot path; ``RAFT_TPU_METRICS=0`` no-ops it.
* **request-scoped spans** (:mod:`raft_tpu.obs.spans`) — per-request
  traces (trace_id/parent links, wall durations, attributes) through
  the serving paths, landing in the always-on **flight recorder**
  (:mod:`raft_tpu.obs.recorder`): the last N request stories, a
  slow-query log, Chrome-trace export. ``RAFT_TPU_TRACE=0`` no-ops it.
* **endpoint** (:mod:`raft_tpu.obs.endpoint`) — ``obs.serve()``, a
  stdlib HTTP server exposing ``/metrics`` (Prometheus text),
  ``/healthz`` (comms health gauges) and ``/debug/requests`` (the
  recorder).

Further planes ride the same taxonomy and load lazily:
:mod:`raft_tpu.obs.quality` (shadow-exact recall, ISSUE 11),
:mod:`raft_tpu.obs.profiler` (sampled device-time attribution, duty
cycle, HBM accounting — ISSUE 14; ``RAFT_TPU_PROFILE_SAMPLE``,
``/debug/profile``) and :mod:`raft_tpu.obs.federation` (cross-process
metric federation + fleet rollup — ISSUE 16; ``obs.serve(
federator=...)`` turns the endpoint into the fleet aggregator).

Quick use::

    from raft_tpu import obs
    obs.counter("raft.myapp.requests", route="search").inc()
    with obs.timed("raft.myapp.handle"):
        ...
    with obs.span("raft.myapp.request", user="abc") as sp:
        ...
    obs.RECORDER.requests(5)          # last 5 request traces
    srv = obs.serve(port=9100)        # scrape/debug endpoint
    print(obs.to_prometheus_text())   # scrape endpoint body
    state = obs.snapshot()            # JSON-ready dict

See docs/observability.md for the taxonomy, the exporters, the span/
recorder knobs and how ``obs.timed`` relates to profiler trace ranges.
"""

from raft_tpu.obs.registry import (
    REGISTRY,
    DEFAULT_BUCKETS,
    SIZE_BUCKETS,
    NAME_RE,
    CardinalityError,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    snapshot,
    snapshot_diff,
    to_prometheus_text,
    reset,
    set_enabled,
    enabled,
)
from raft_tpu.obs.timing import timed
from raft_tpu.obs.spans import (
    Span,
    span,
    current_span,
    current_trace_id,
    current_traceparent,
    parse_traceparent,
    add_stage_spans,
    set_trace_enabled,
    trace_enabled,
    set_trace_sample_rate,
    trace_sample_rate,
)
from raft_tpu.obs.recorder import FlightRecorder, RECORDER, to_chrome_trace
from raft_tpu.obs.endpoint import DebugServer, serve

__all__ = [
    "REGISTRY",
    "DEFAULT_BUCKETS",
    "SIZE_BUCKETS",
    "NAME_RE",
    "CardinalityError",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "snapshot_diff",
    "to_prometheus_text",
    "reset",
    "set_enabled",
    "enabled",
    "timed",
    # spans / recorder / endpoint
    "Span",
    "span",
    "current_span",
    "current_trace_id",
    "current_traceparent",
    "parse_traceparent",
    "add_stage_spans",
    "set_trace_enabled",
    "trace_enabled",
    "set_trace_sample_rate",
    "trace_sample_rate",
    "FlightRecorder",
    "RECORDER",
    "to_chrome_trace",
    "DebugServer",
    "serve",
]
