"""raft_tpu.obs — metrics, request tracing + runtime telemetry.

The observability layer the reference never had (its story is NVTX
ranges + spdlog — our ``core/trace.py`` / ``core/logger.py``), in
three planes sharing ONE ``raft.<module>.<op>`` naming taxonomy:

* **metrics** (:mod:`raft_tpu.obs.registry`) — dependency-free,
  thread-safe counters/gauges/fixed-boundary histograms wired into
  every hot path; ``RAFT_TPU_METRICS=0`` no-ops it.
* **request-scoped spans** (:mod:`raft_tpu.obs.spans`) — per-request
  traces (trace_id/parent links, wall durations, attributes) through
  the serving paths, landing in the always-on **flight recorder**
  (:mod:`raft_tpu.obs.recorder`): the last N request stories, a
  slow-query log, Chrome-trace export. ``RAFT_TPU_TRACE=0`` no-ops it.
* **endpoint** (:mod:`raft_tpu.obs.endpoint`) — ``obs.serve()``, a
  stdlib HTTP server exposing ``/metrics`` (Prometheus text),
  ``/healthz`` (comms health gauges) and ``/debug/requests`` (the
  recorder).

Further planes ride the same taxonomy and load lazily:
:mod:`raft_tpu.obs.quality` (shadow-exact recall, ISSUE 11),
:mod:`raft_tpu.obs.profiler` (sampled device-time attribution, duty
cycle, HBM accounting — ISSUE 14; ``RAFT_TPU_PROFILE_SAMPLE``,
``/debug/profile``), :mod:`raft_tpu.obs.federation` (cross-process
metric federation + fleet rollup — ISSUE 16; ``obs.serve(
federator=...)`` turns the endpoint into the fleet aggregator), and
the post-mortem pair :mod:`raft_tpu.obs.history` +
:mod:`raft_tpu.obs.blackbox` (metrics history ring with mean-shift
anomaly detection at ``/debug/history``, plus the crash-durable
black-box flight data recorder — ISSUE 18;
``RAFT_TPU_BLACKBOX=<dir>`` ambient-attaches both, and
``tools/doctor.py`` reads the dumps).

Quick use::

    from raft_tpu import obs
    obs.counter("raft.myapp.requests", route="search").inc()
    with obs.timed("raft.myapp.handle"):
        ...
    with obs.span("raft.myapp.request", user="abc") as sp:
        ...
    obs.RECORDER.requests(5)          # last 5 request traces
    srv = obs.serve(port=9100)        # scrape/debug endpoint
    print(obs.to_prometheus_text())   # scrape endpoint body
    state = obs.snapshot()            # JSON-ready dict

See docs/observability.md for the taxonomy, the exporters, the span/
recorder knobs and how ``obs.timed`` relates to profiler trace ranges.
"""

from raft_tpu.obs.registry import (
    REGISTRY,
    DEFAULT_BUCKETS,
    SIZE_BUCKETS,
    NAME_RE,
    CardinalityError,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    snapshot,
    snapshot_diff,
    to_prometheus_text,
    reset,
    set_enabled,
    enabled,
)
from raft_tpu.obs.timing import timed
from raft_tpu.obs.spans import (
    Span,
    span,
    current_span,
    current_trace_id,
    current_traceparent,
    parse_traceparent,
    add_stage_spans,
    set_trace_enabled,
    trace_enabled,
    set_trace_sample_rate,
    trace_sample_rate,
)
from raft_tpu.obs.recorder import FlightRecorder, RECORDER, to_chrome_trace
from raft_tpu.obs.endpoint import DebugServer, serve

__all__ = [
    "REGISTRY",
    "DEFAULT_BUCKETS",
    "SIZE_BUCKETS",
    "NAME_RE",
    "CardinalityError",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "snapshot_diff",
    "to_prometheus_text",
    "reset",
    "set_enabled",
    "enabled",
    "timed",
    # spans / recorder / endpoint
    "Span",
    "span",
    "current_span",
    "current_trace_id",
    "current_traceparent",
    "parse_traceparent",
    "add_stage_spans",
    "set_trace_enabled",
    "trace_enabled",
    "set_trace_sample_rate",
    "trace_sample_rate",
    "FlightRecorder",
    "RECORDER",
    "to_chrome_trace",
    "DebugServer",
    "serve",
]

# -- black-box ambient attach (ISSUE 18) ----------------------------------
# RAFT_TPU_BLACKBOX=<dir> attaches the metrics-history sampler and the
# crash-durable black box at import, exactly like the profiler's
# RAFT_TPU_PROFILE_SAMPLE knob. Unset/0/off leaves BOTH modules
# unimported — the off state is one env read here and `_STATE is None`
# in each module, nothing else (the < 2% overhead gate is structural).
# The attach lives HERE rather than at blackbox-module import so
# tools/doctor.py can import the modules to READ a dump without ever
# starting a recorder into it.
import os as _os

_bb_dir = _os.environ.get("RAFT_TPU_BLACKBOX", "")
if _bb_dir and _bb_dir.lower() not in ("0", "false", "off", "no"):
    from raft_tpu.obs import blackbox as _blackbox
    from raft_tpu.obs import history as _history

    _history.enable_history()
    _blackbox.enable_blackbox(_bb_dir)
del _os, _bb_dir
