"""Serving debug endpoint — stdlib-only HTTP server for obs state.

``obs.serve()`` starts a daemon-threaded HTTP server (no dependency
beyond ``http.server``) exposing the three planes one serving box
needs inspectable:

* ``GET /metrics`` — the Prometheus exposition body
  (``obs.to_prometheus_text()``): point a scraper here.
* ``GET /healthz`` — health verdict from the ``raft.comms.health.*``
  gauges AND the ``raft.serve.*`` overload gauges: 200 ``{"status":
  "ok"}`` while no session reports suspect ranks and the serving
  runtime is not overloaded, 503 ``{"status": "degraded", ...}`` the
  moment either plane trips (suspect counts, heartbeat staleness,
  queue depth / shed rate / degrade level ride in the body).
* ``POST /search`` — JSON search route backed by an attached
  :class:`raft_tpu.serve.SearchServer` (``obs.serve(searcher=srv)``):
  ``{"queries": [[...], ...], "k": 10}`` → ``{"distances", "ids"}``;
  backpressure rejections return 429, expired deadlines 504
  (docs/serving.md).
* ``GET /debug/requests`` — the flight recorder
  (:mod:`raft_tpu.obs.recorder`): structured JSON of the last N
  request traces. Query params: ``n=<count>`` limits, ``slow=1``
  restricts to the slow ring, ``trace=<id>`` selects one trace, and
  ``format=chrome`` renders it (or, without ``trace``, the most
  recent) as Chrome-trace JSON — save the body and load it in
  Perfetto.
* ``GET /debug/fleet`` — the replica-fleet report
  (:mod:`raft_tpu.fleet`): per-replica state/load/route share and the
  suspect set from the attached :class:`~raft_tpu.fleet.FleetRouter`
  (``obs.serve(fleet=router)``), else the exported ``raft.fleet.*``
  gauges. ``/healthz`` degrades while any replica is out of the
  serving set.
* ``GET /debug/profile`` — the resource profiler
  (:mod:`raft_tpu.obs.profiler`): duty cycles, per-program device/host
  splits, the top device-time programs, the compile ledger, and the
  per-device HBM table — from the in-process profiler when one is
  attached, else the exported ``raft.obs.profile.*`` gauges.
  ``/healthz`` degrades while any device's HBM headroom sits below the
  profiler's ``hbm_headroom_frac`` guardrail.
* ``GET /debug/slo`` — the declarative SLO verdict
  (:mod:`raft_tpu.obs.slo`): every objective's per-window burn rates
  and breach flags, from the in-process :class:`~raft_tpu.obs.slo.
  SLOTracker` when one runs (full report) or the exported
  ``raft.slo.*`` gauges otherwise. Breached objectives also degrade
  ``/healthz``.

Fleet observability plane (ISSUE 16) — ``obs.serve(federator=fed)``
turns this endpoint into the fleet AGGREGATOR:

* ``GET /metrics`` then serves the federation-merged fleet body
  (per-replica series under ``instance`` labels + summed rollups —
  the aggregator's one-scrape fleet view; also at
  ``GET /fleet/metrics``).
* ``GET /fleet/healthz`` — worst-of fleet verdict: per-replica
  verdicts, staleness, replication lag, the router's suspect set.
* ``GET /fleet/trace?trace=<id>`` — the stitched cross-process
  Chrome trace: local fragments + every URL instance's fragments
  (:func:`raft_tpu.obs.recorder.stitch_from_endpoints`).
* ``GET /debug/fleet`` gains a ``federation`` section (per-instance
  scrape state, well-known per-replica gauges, scrape overhead).

Trace propagation rides ``POST /search``: an incoming ``traceparent``
header parents the handler's ``raft.serve.http`` span (and through it
the whole routed request); the response carries the request's
``trace_id`` (header + body) so a caller can fetch its stitched
trace. ``GET /debug/requests?trace=<id>&all=1`` returns EVERY local
fragment of a trace (``{"trace_id", "fragments", "now_unix"}``,
always 200) — the wire format ``fetch_fragments`` consumes.

Request handling is thread-per-connection (``ThreadingHTTPServer``)
with a concurrency bound (``RAFT_TPU_ENDPOINT_THREADS``, default 8):
a burst of slow debug fetches saturates the bound and further
connections are refused at accept — a federator scrape can never
head-of-line block ``POST /search`` into unbounded thread growth.

Use::

    from raft_tpu import obs
    srv = obs.serve(port=9100)        # or port=0 for an ephemeral port
    print(srv.url)                    # e.g. http://127.0.0.1:9100
    ...
    srv.close()

The server binds loopback by default — it exposes internals (query
shapes, timings); front it with real infrastructure before exposing it
beyond the host.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from raft_tpu.obs import recorder as _recorder
from raft_tpu.obs import registry as _registry

__all__ = ["DebugServer", "serve"]


def _health_body(snapshot: dict) -> dict:
    """Health verdict from TWO planes: the comms/health gauges (any
    session with ``raft.comms.health.suspects`` > 0) AND the serving
    overload gauges (``raft.serve.*`` — a single-host server drowning
    in its own queue must stop reporting healthy, not only one whose
    peers look suspect)."""
    gauges = snapshot.get("gauges", {})
    suspects = {}
    staleness = {}
    for series, value in gauges.items():
        if series.startswith("raft.comms.health.suspects"):
            suspects[series] = value
        elif series.startswith("raft.comms.health.max_staleness_seconds"):
            staleness[series] = value
    comms_degraded = any(v > 0 for v in suspects.values())

    def _gsum(prefix: str) -> float:
        return sum(v for k, v in gauges.items()
                   if k == prefix or k.startswith(prefix + "{"))

    overloaded = _gsum("raft.serve.overloaded")
    depth = _gsum("raft.serve.queue.depth")
    qmax = _gsum("raft.serve.queue.max")
    shed_rate = _gsum("raft.serve.shed.rate")
    serve_degraded = (overloaded > 0 or shed_rate > 0
                      or (qmax > 0 and depth >= qmax))
    # failure-handling plane (ISSUE 10): serving partial results over a
    # degraded mesh is availability, not health — /healthz must say so
    # until recovery clears the exclusion
    failover_engaged = _gsum("raft.serve.failover.engaged")
    serve_degraded = serve_degraded or failover_engaged > 0
    # mutable-index plane (ISSUE 9): a delta segment sitting at its TOP
    # ladder rung with no compaction in flight is a stalled compactor —
    # the next rung boundary is a hard DeltaFullError wall, so this box
    # must stop reporting healthy BEFORE writes start bouncing. A
    # crash-looping compactor (ISSUE 10) degrades the same way: N
    # consecutive failed folds mean the delta WILL hit that wall.
    mutate_stalled = _gsum("raft.mutate.delta.stalled")
    compactor_failing = _gsum("raft.mutate.compactor.failing")
    mutate_degraded = mutate_stalled > 0 or compactor_failing > 0
    # SLO plane (ISSUE 11): a breached declared objective — p99 burn,
    # availability burn, or the live recall floor — is a degraded box
    # by definition: the operator declared what "acceptable" means,
    # /healthz must honor it
    slo_breaches = {k: v for k, v in gauges.items()
                    if k.split("{")[0] == "raft.slo.breach" and v > 0}
    slo_degraded = bool(slo_breaches)
    body = {
        "status": ("degraded" if (comms_degraded or serve_degraded
                                  or mutate_degraded or slo_degraded)
                   else "ok"),
        "suspects": suspects,
        "max_staleness_seconds": staleness,
    }
    if any(k.split("{")[0].startswith("raft.slo.") for k in gauges):
        body["slo"] = {
            "objectives": _gsum("raft.slo.objectives"),
            "breaches": sorted(slo_breaches),
        }
    # quality plane (ISSUE 11): surface the live shadow-exact recall
    # windows informationally (the recall FLOOR verdict rides the SLO
    # plane above — raw recall being low is context, not by itself
    # a health verdict)
    quality = {k: v for k, v in gauges.items()
               if k.split("{")[0] == "raft.obs.quality.recall"}
    if quality:
        body["quality"] = {
            "recall": quality,
            "drift": {k: v for k, v in gauges.items()
                      if k.split("{")[0] in ("raft.obs.quality.drift",
                                             "raft.obs.quality.drift"
                                             ".alarm")},
        }
    if any(k.split("{")[0].startswith("raft.mutate.") for k in gauges):
        body["mutate"] = {
            "epoch": _gsum("raft.mutate.epoch"),
            "delta_fill_frac": _gsum("raft.mutate.delta.fill_frac"),
            "delta_rung": _gsum("raft.mutate.delta.rung"),
            "delta_rows": _gsum("raft.mutate.delta.rows"),
            "tombstone_frac": _gsum("raft.mutate.tombstone.frac"),
            "compact_inflight": _gsum("raft.mutate.compact.inflight"),
            "delta_stalled": mutate_stalled,
            "compactor_failing": compactor_failing,
        }
    if any(k.startswith("raft.serve.") for k in gauges):
        body["serve"] = {
            "overloaded": overloaded,
            "queue_depth": depth,
            "queue_max": qmax,
            "shed_rate_per_s": shed_rate,
            "degrade_level": _gsum("raft.serve.degrade.level"),
        }
        if failover_engaged:
            body["serve"]["failover"] = {
                "engaged": failover_engaged,
                "coverage": _gsum("raft.serve.failover.coverage"),
            }
    # resource plane (ISSUE 14): a device whose HBM headroom fell
    # below the profiler's configured fraction trips low_headroom —
    # the next allocation (a compaction, a cold-list fetch, a bigger
    # batch shape) may OOM, so this box must stop reporting healthy
    # BEFORE that happens, exactly like the stalled-delta guardrail
    hbm_low = _gsum("raft.obs.profile.hbm.low_headroom")
    if hbm_low > 0:
        body["status"] = "degraded"
    # tiered serving (ISSUE 19): informational placement row — the
    # budget reacts to the SAME low-headroom signal (a refresh under a
    # shrunk budget demotes lists instead of OOMing), so this row plus
    # ``hbm_low_headroom`` above reads as one coherent story
    tiered_gauges = {k.split("{")[0]: v for k, v in gauges.items()
                     if k.startswith("raft.tiered.")}
    if tiered_gauges:
        body["tiered"] = {
            "budget_bytes": tiered_gauges.get(
                "raft.tiered.budget.bytes", 0.0),
            "hot_lists": tiered_gauges.get("raft.tiered.hot.lists",
                                           0.0),
            "hot_bytes": tiered_gauges.get("raft.tiered.hot.bytes",
                                           0.0),
            "hit_rate": tiered_gauges.get("raft.tiered.hit_rate", 0.0),
            "overlap_frac": tiered_gauges.get(
                "raft.tiered.overlap.frac", 0.0),
        }
    duty = {k: v for k, v in gauges.items()
            if k.split("{")[0] == "raft.obs.profile.duty_cycle"}
    if duty or hbm_low:
        # informational: duty cycle being low is context (diagnose via
        # /debug/profile), only the HBM guardrail is a verdict
        body["profile"] = {
            "duty_cycle": duty,
            "hbm_low_headroom": hbm_low,
            "hbm_headroom_frac": {
                k: v for k, v in gauges.items()
                if k.split("{")[0]
                == "raft.obs.profile.hbm.headroom_frac"},
        }
    # history plane (ISSUE 18): active mean-shift anomalies ride the
    # body informationally — a shifted signal says WHERE to look
    # (/debug/history), the underlying plane (serve/profiler/SLO/
    # fleet) owns the degrade verdict for it
    anomalies = sorted(
        k for k, v in gauges.items()
        if k.split("{")[0] == "raft.obs.history.anomaly" and v > 0)
    if anomalies:
        body["history"] = {"anomalies": anomalies}
    # fleet tier (ISSUE 13): a registered replica fleet degrades the
    # verdict while any replica is out of the serving set (draining /
    # bootstrapping / down — a fleet at partial capacity must say so,
    # exactly like the failover plane above) and hard-degrades when
    # NOTHING serves
    fleet_total = _gsum("raft.fleet.replicas.total")
    if fleet_total:
        fleet_serving = _gsum("raft.fleet.replicas.serving")
        fleet_suspects = _gsum("raft.fleet.suspects")
        fleet_degraded = (fleet_serving < fleet_total
                          or fleet_serving == 0 or fleet_suspects > 0)
        body["fleet"] = {
            "replicas": fleet_total,
            "serving": fleet_serving,
            "suspects": fleet_suspects,
            "replication_lag_records": _gsum(
                "raft.fleet.replication.lag_records"),
        }
        if fleet_degraded:
            body["status"] = "degraded"
    # distributed serving tier (ISSUE 8): when a mesh-wide server is
    # active (shards gauge set), surface the mesh shape, the merge
    # compression it runs at, and — folding the per-shard comms-health
    # plane — exactly WHICH ranks look failed, so a degraded verdict
    # names the shard, not only a suspect count
    dist_shards = _gsum("raft.serve.dist.shards")
    if dist_shards:
        # one parser shared with the serving tier's failover exclusion
        # (lazy import: comms pulls obs, so a module-scope import here
        # would cycle through obs/__init__)
        from raft_tpu.comms.health import suspects_from_gauges
        suspect_ranks = suspects_from_gauges(gauges)
        body.setdefault("serve", {})["dist"] = {
            "shards": dist_shards,
            "merge_ratio": _gsum("raft.serve.dist.merge.ratio"),
            "suspect_ranks": suspect_ranks,
        }
    return body


class _Handler(BaseHTTPRequestHandler):
    # the server object carries the recorder/registry (see DebugServer)
    server: "DebugServer"

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj, indent=1).encode("utf-8"),
                   "application/json")

    def do_GET(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        url = urlparse(self.path)
        q = parse_qs(url.query)
        path = url.path.rstrip("/") or "/"
        try:
            if path == "/metrics":
                fed = getattr(self.server, "federator", None)
                # an aggregator's /metrics IS the fleet view: one
                # scrape target for the whole fleet, per-replica
                # series under instance labels, counters summed
                text = (fed.merged_text() if fed is not None
                        else self.server.registry.to_prometheus_text())
                self._send(200, text.encode("utf-8"),
                           "text/plain; version=0.0.4")
            elif path == "/fleet/metrics":
                self._fleet_metrics()
            elif path == "/fleet/healthz":
                self._fleet_healthz()
            elif path == "/fleet/trace":
                self._fleet_trace(q)
            elif path == "/healthz":
                body = _health_body(self.server.registry.snapshot())
                self._send_json(200 if body["status"] == "ok" else 503,
                                body)
            elif path == "/debug/requests":
                self._debug_requests(q)
            elif path == "/debug/slo":
                # lazy import: slo pulls the serve counter taxonomy —
                # keep the endpoint importable without it resolved
                from raft_tpu.obs import slo as _slo
                body = _slo.endpoint_body(self.server.registry
                                          .snapshot())
                self._send_json(200, body)
            elif path == "/debug/fleet":
                self._debug_fleet()
            elif path == "/debug/profile":
                # lazy import: profiler pulls spans/jax — keep the
                # endpoint importable without it resolved
                from raft_tpu.obs import profiler as _profiler
                body = _profiler.endpoint_body(self.server.registry
                                               .snapshot())
                self._send_json(200, body)
            elif path == "/debug/history":
                # lazy import: history only attaches when enabled
                # (ISSUE 18) — the route answers 404, not ImportError,
                # on a box without it
                from raft_tpu.obs import history as _history
                code, body = _history.endpoint_body(q)
                self._send_json(code, body)
            else:
                self._send_json(404, {"error": f"no route {path!r}",
                                      "routes": ["/metrics", "/healthz",
                                                 "/fleet/metrics",
                                                 "/fleet/healthz",
                                                 "/fleet/trace",
                                                 "/debug/requests",
                                                 "/debug/slo",
                                                 "/debug/fleet",
                                                 "/debug/profile",
                                                 "/debug/history"]})
        except BrokenPipeError:
            pass

    def do_POST(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        path = urlparse(self.path).path.rstrip("/") or "/"
        try:
            if path == "/search":
                self._search()
            else:
                self._send_json(404, {"error": f"no POST route {path!r}",
                                      "routes": ["/search"]})
        except BrokenPipeError:
            pass

    def _search(self) -> None:
        """``POST /search`` — JSON in, JSON out, backed by the attached
        :class:`raft_tpu.serve.SearchServer` (``serve(searcher=...)``).
        Body: ``{"queries": [[...], ...], "k": int?, "deadline_ms":
        float?}``. Admission errors map to explicit status codes: 429
        rejected (backpressure), 504 deadline expired."""
        # lazy import: raft_tpu.serve imports raft_tpu.obs — importing
        # it at module scope would cycle through obs/__init__
        from raft_tpu.serve.types import DeadlineExceeded, RejectedError
        srv = getattr(self.server, "searcher", None)
        if srv is None:
            self._send_json(404, {"error": "no searcher attached "
                                           "(obs.serve(searcher=...))"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            queries = body["queries"]
            k = body.get("k")
            deadline_ms = body.get("deadline_ms")
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"error": f"bad request body: {e!r}"})
            return
        from raft_tpu.obs import spans as _spans
        # cross-process propagation in: an upstream traceparent header
        # parents this handler's span — and through the open span, the
        # router/replica submits made inside it — under the caller
        incoming = self.headers.get("traceparent")
        trace_id = None
        try:
            with _spans.span("raft.serve.http", remote_parent=incoming,
                             route="/search") as sp:
                trace_id = sp.trace_id or None
                d, i = srv.search(queries, k=k, deadline_ms=deadline_ms)
        except RejectedError as e:
            self._send_json(429, {"error": "rejected", "detail": str(e),
                                  "trace_id": trace_id})
            return
        except DeadlineExceeded as e:
            self._send_json(504, {"error": "deadline", "detail": str(e),
                                  "trace_id": trace_id})
            return
        except Exception as e:
            self._send_json(500, {"error": type(e).__name__,
                                  "detail": str(e)[:500],
                                  "trace_id": trace_id})
            return
        # propagation out: the trace id rides the response so the
        # caller can fetch /fleet/trace?trace=<id> (or the fragments)
        self._send_json(200, {"distances": d.tolist(), "ids": i.tolist(),
                              "nq": len(i), "k": len(i[0]) if len(i)
                              else 0, "trace_id": trace_id})

    def _fleet_metrics(self) -> None:
        fed = getattr(self.server, "federator", None)
        if fed is None:
            self._send_json(404, {"error": "no federator attached "
                                           "(obs.serve(federator=...))"})
            return
        self._send(200, fed.merged_text().encode("utf-8"),
                   "text/plain; version=0.0.4")

    def _fleet_healthz(self) -> None:
        fed = getattr(self.server, "federator", None)
        if fed is None:
            self._send_json(404, {"error": "no federator attached "
                                           "(obs.serve(federator=...))"})
            return
        body = fed.healthz()
        self._send_json(200 if body["status"] == "ok" else 503, body)

    def _fleet_trace(self, q: dict) -> None:
        """``GET /fleet/trace?trace=<id>`` — the stitched Chrome trace
        of one routed request: local recorder fragments + every URL
        instance's fragments fetched over ``/debug/requests?trace=&
        all=1``."""
        fed = getattr(self.server, "federator", None)
        if fed is None:
            self._send_json(404, {"error": "no federator attached "
                                           "(obs.serve(federator=...))"})
            return
        trace_id = q.get("trace", [None])[0]
        if not trace_id:
            self._send_json(400, {"error": "trace=<id> is required"})
            return
        peers = fed.url_instances()
        body = _recorder.stitch_from_endpoints(
            trace_id, peers, recorder=self.server.recorder,
            timeout_s=fed.timeout_s)
        if not any(e.get("ph") == "X" for e in body["traceEvents"]):
            self._send_json(404, {"error": f"trace {trace_id!r} not "
                                           f"found on any instance"})
            return
        self._send_json(200, body)

    def _debug_fleet(self) -> None:
        """``GET /debug/fleet`` — the fleet router's full report when
        one is attached (``obs.serve(fleet=router)``: per-replica
        state/load/route share, suspects), else reconstructed from the
        exported ``raft.fleet.*`` gauges."""
        router = getattr(self.server, "fleet", None)
        fed = getattr(self.server, "federator", None)
        if router is not None:
            body = router.report()
            if fed is not None:
                body["federation"] = fed.report()
            self._send_json(200, body)
            return
        if fed is not None:
            self._send_json(200, {"federation": fed.report()})
            return
        gauges = self.server.registry.snapshot().get("gauges", {})
        fleet_g = {k: v for k, v in gauges.items()
                   if k.split("{")[0].startswith("raft.fleet.")}
        if not fleet_g:
            self._send_json(404, {"error": "no fleet attached and no "
                                           "raft.fleet.* gauges "
                                           "exported"})
            return
        self._send_json(200, {"source": "gauges", "gauges": fleet_g})

    def _debug_requests(self, q: dict) -> None:
        rec = self.server.recorder
        trace_id = q.get("trace", [None])[0]
        fmt = q.get("format", ["json"])[0]
        n = None
        if "n" in q:
            try:
                n = max(0, int(q["n"][0]))
            except ValueError:
                self._send_json(400, {"error": "n must be an integer"})
                return
        if trace_id is not None and \
                q.get("all", ["0"])[0] not in ("0", "", "false"):
            # the stitch wire format (recorder.fetch_fragments): every
            # local fragment of the trace + our clock, ALWAYS 200 — a
            # peer with no fragments is an answer, not an error
            import time as _time
            self._send_json(200, {
                "trace_id": trace_id,
                "fragments": rec.fragments(trace_id),
                # skew estimation wants wall clock (see recorder)
                "now_unix": _time.time(),  # graftlint: disable=GL005
            })
            return
        if trace_id is not None:
            trace = rec.get(trace_id)
            if trace is None:
                self._send_json(404, {"error": f"trace {trace_id!r} not "
                                               f"in the recorder ring"})
                return
            if fmt == "chrome":
                self._send_json(200, _recorder.to_chrome_trace(trace))
            else:
                self._send_json(200, trace)
            return
        if fmt == "chrome":
            latest = rec.requests(1)
            if not latest:
                self._send_json(404, {"error": "recorder is empty"})
                return
            self._send_json(200, _recorder.to_chrome_trace(latest[0]))
            return
        if q.get("slow", ["0"])[0] not in ("0", "", "false"):
            body = rec.to_json(0)
            body["traces"] = rec.slow_requests(n)
            self._send_json(200, body)
            return
        self._send_json(200, rec.to_json(n))

    def log_message(self, fmt: str, *args) -> None:
        # route access logs through the framework logger at DEBUG —
        # a scraper hitting /metrics every 15 s must not spam stderr
        from raft_tpu.core.logger import get_logger
        get_logger("obs").debug("endpoint: " + fmt % args)


class DebugServer(ThreadingHTTPServer):
    """The obs debug server; build via :func:`serve`."""

    daemon_threads = True

    def __init__(self, addr, recorder=None, registry=None,
                 searcher=None, fleet=None, federator=None,
                 max_threads: Optional[int] = None):
        super().__init__(addr, _Handler)
        self.recorder = recorder if recorder is not None \
            else _recorder.RECORDER
        self.registry = registry if registry is not None \
            else _registry.REGISTRY
        # optional raft_tpu.serve.SearchServer (or fleet.FleetRouter —
        # same submit/search shape) backing POST /search
        self.searcher = searcher
        # optional raft_tpu.fleet.FleetRouter backing GET /debug/fleet
        self.fleet = fleet
        # optional obs.federation.MetricsFederator: makes this endpoint
        # the fleet aggregator (/metrics merged, /fleet/*)
        self.federator = federator
        if max_threads is None:
            try:
                max_threads = int(os.environ.get(
                    "RAFT_TPU_ENDPOINT_THREADS", "8"))
            except ValueError:
                max_threads = 8
        # thread-per-connection with a hard bound: N slow debug
        # fetches can occupy N threads, connection N+1 is refused
        # instead of growing the pool without limit
        self._slots = threading.BoundedSemaphore(max(1, max_threads))
        self._thread: Optional[threading.Thread] = None

    def process_request_thread(self, request, client_address):
        if not self._slots.acquire(timeout=0.5):
            # saturated: drop the connection — the client sees a
            # reset, not an unbounded queue behind a stuck handler
            self.shutdown_request(request)
            return
        try:
            super().process_request_thread(request, client_address)
        finally:
            self._slots.release()

    @property
    def port(self) -> int:
        return self.server_address[1]

    @property
    def url(self) -> str:
        host = self.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "DebugServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self.serve_forever, kwargs={"poll_interval": 0.25},
                daemon=True, name=f"raft-obs-endpoint-{self.port}")
            self._thread.start()
        return self

    def close(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "DebugServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def serve(host: str = "127.0.0.1", port: int = 0, recorder=None,
          registry=None, searcher=None, fleet=None,
          federator=None) -> DebugServer:
    """Start the debug endpoint in a daemon thread → running
    :class:`DebugServer` (``.url``, ``.port``, ``.close()``).
    ``port=0`` binds an ephemeral port (tests, side-by-side procs).
    ``searcher`` (a :class:`raft_tpu.serve.SearchServer` or a
    :class:`raft_tpu.fleet.FleetRouter` — same call shape) enables the
    ``POST /search`` JSON route; ``fleet`` (a ``FleetRouter``) enables
    the full ``GET /debug/fleet`` report; ``federator`` (a
    :class:`raft_tpu.obs.federation.MetricsFederator`) makes this the
    fleet aggregator (merged ``/metrics``, ``/fleet/healthz``,
    ``/fleet/trace``)."""
    return DebugServer((host, port), recorder=recorder,
                       registry=registry, searcher=searcher,
                       fleet=fleet, federator=federator).start()
