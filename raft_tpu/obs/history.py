"""Metrics history ring — the registry over TIME (ISSUE 18).

Every observability plane built so far answers "what is the value
NOW": the registry is a point-in-time snapshot, ``/healthz`` is a
verdict about this instant, the profiler's duty cycle is one sliding
window. Trend questions — "did the shed rate jump when the compactor
started", "has HBM headroom been sinking for a minute", "what changed
in the 10 seconds before the replica died" — need the registry
sampled on a cadence and kept, which is exactly what ROADMAP item 5's
self-driving actuators and the ISSUE 18 post-mortem doctor both read.

:class:`MetricsHistory` snapshots a :class:`~raft_tpu.obs.registry.
MetricsRegistry` every ``interval_s`` (a daemon sampler thread, or
explicit :meth:`~MetricsHistory.tick` calls in tests) into
**delta-compressed frames**: a frame stores only the counter deltas
and changed gauge values since the previous frame (histograms fold in
as synthetic ``<family>.count`` / ``<family>.sum`` counter series), so
a quiet registry costs bytes per frame, not a full snapshot. Evicted
frames fold into a base state, so absolute series reconstruct exactly
over the whole retained window:

* :meth:`~MetricsHistory.series` — absolute ``(t_unix, value)``
  points per matched series;
* :meth:`~MetricsHistory.rate` / :meth:`~MetricsHistory.delta` —
  server-side ``(last-first)/span`` and ``last-first`` over a window
  (the ``GET /debug/history?name=&window=`` body, see
  :func:`endpoint_body`);
* :meth:`~MetricsHistory.frames_since` — JSON-ready frames for the
  black box (:mod:`raft_tpu.obs.blackbox`) to spill to disk.

Change-point detection rides the same cadence: each watched
:class:`Signal` (shed rate, duty cycle, HBM headroom, live recall,
replication lag by default) keeps a ``2*window`` ring of values and
flags a **windowed mean shift** — ``|mean(recent w) - mean(prior w)|``
above the signal's threshold. Detection is edge-triggered: the
``raft.obs.history.anomaly{signal}`` gauge holds 1 while the shift is
inside the detector window and the ``raft.obs.history.anomaly.total``
counter increments ONCE per shift (the fires-once contract
``tests/test_blackbox.py`` pins). ``/healthz`` folds active anomalies
in as an informational ``history`` section — the underlying planes
own their own degrade verdicts.

Module state follows the profiler's attach pattern:
:func:`enable_history` installs the module singleton (``_STATE is
None`` IS the off state — every consumer hook is one module-flag
read), :func:`disable_history` tears it down, and
``RAFT_TPU_BLACKBOX=<dir>`` ambient-attaches it together with the
black box (see ``raft_tpu/obs/__init__.py``).

Knobs: ``RAFT_TPU_HISTORY_INTERVAL`` (seconds per frame, default 1.0)
and ``RAFT_TPU_HISTORY_RING`` (retained frames, default 512 — ~8.5
minutes at the default cadence).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from raft_tpu import obs
from raft_tpu.obs import registry as _registry

__all__ = [
    "DEFAULT_SIGNALS",
    "MetricsHistory",
    "Signal",
    "disable_history",
    "enable_history",
    "endpoint_body",
    "history",
]

_ENV_INTERVAL = "RAFT_TPU_HISTORY_INTERVAL"
_ENV_RING = "RAFT_TPU_HISTORY_RING"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# -- watched signals -------------------------------------------------------

def _fam(series: str) -> str:
    return series.split("{", 1)[0]


def _gvals(gauges: Dict[str, float], family: str) -> List[float]:
    return [v for k, v in gauges.items() if _fam(k) == family]


def _sig_shed_rate(gauges: Dict[str, float]) -> Optional[float]:
    vals = _gvals(gauges, "raft.serve.shed.rate")
    return sum(vals) if vals else None


def _sig_duty_cycle(gauges: Dict[str, float]) -> Optional[float]:
    vals = _gvals(gauges, "raft.obs.profile.duty_cycle")
    return sum(vals) / len(vals) if vals else None


def _sig_hbm_headroom(gauges: Dict[str, float]) -> Optional[float]:
    vals = _gvals(gauges, "raft.obs.profile.hbm.headroom_frac")
    return min(vals) if vals else None


def _sig_recall(gauges: Dict[str, float]) -> Optional[float]:
    vals = _gvals(gauges, "raft.obs.quality.recall")
    return sum(vals) / len(vals) if vals else None


def _sig_replication_lag(gauges: Dict[str, float]) -> Optional[float]:
    vals = _gvals(gauges, "raft.fleet.replication.lag_records")
    return sum(vals) if vals else None


class Signal:
    """One watched scalar for mean-shift detection: a name, an
    extractor over the gauge snapshot (``None`` = signal absent this
    tick — the detector simply skips), and the shift thresholds: a
    shift fires when ``|mean2 - mean1| > max(min_delta,
    rel_frac * |mean1|)``."""

    __slots__ = ("name", "fn", "min_delta", "rel_frac")

    def __init__(self, name: str,
                 fn: Callable[[Dict[str, float]], Optional[float]],
                 min_delta: float, rel_frac: float = 0.5):
        self.name = name
        self.fn = fn
        self.min_delta = float(min_delta)
        self.rel_frac = float(rel_frac)


# the five trend signals the ISSUE 18 tentpole names — thresholds are
# per-signal because their units differ wildly (req/s vs fractions vs
# record counts)
DEFAULT_SIGNALS: Tuple[Signal, ...] = (
    Signal("shed_rate", _sig_shed_rate, min_delta=1.0),
    Signal("duty_cycle", _sig_duty_cycle, min_delta=0.15),
    Signal("hbm_headroom", _sig_hbm_headroom, min_delta=0.1),
    Signal("recall", _sig_recall, min_delta=0.05),
    Signal("replication_lag", _sig_replication_lag, min_delta=50.0),
)


class _Detector:
    """Per-signal mean-shift state. Mutated only by
    :meth:`MetricsHistory.tick` under the history lock."""

    __slots__ = ("signal", "window", "values", "shifted", "fired_total",
                 "last", "means")

    def __init__(self, signal: Signal, window: int):
        self.signal = signal
        self.window = max(2, int(window))
        self.values: List[float] = []
        self.shifted = False
        self.fired_total = 0
        self.last: Optional[float] = None
        self.means: Optional[Tuple[float, float]] = None

    def update(self, gauges: Dict[str, float]) -> Optional[str]:
        """Feed one tick → ``"fired"`` on the no-shift→shift edge,
        ``"cleared"`` on the reverse edge, else ``None``."""
        v = self.signal.fn(gauges)
        self.last = v
        if v is None:
            return None
        w = self.window
        self.values.append(float(v))
        if len(self.values) > 2 * w:
            del self.values[: len(self.values) - 2 * w]
        if len(self.values) < 2 * w:
            return None
        m1 = sum(self.values[:w]) / w
        m2 = sum(self.values[w:]) / w
        self.means = (m1, m2)
        thresh = max(self.signal.min_delta,
                     self.signal.rel_frac * abs(m1))
        shifted = abs(m2 - m1) > thresh
        if shifted and not self.shifted:
            self.shifted = True
            self.fired_total += 1
            return "fired"
        if not shifted and self.shifted:
            self.shifted = False
            return "cleared"
        return None


class _Frame:
    """One delta-compressed sample: counter deltas + changed gauges
    since the previous frame."""

    __slots__ = ("seq", "t_unix", "t_mono", "counters", "gauges")

    def __init__(self, seq: int, t_unix: float, t_mono: float,
                 counters: Dict[str, float], gauges: Dict[str, float]):
        self.seq = seq
        self.t_unix = t_unix
        self.t_mono = t_mono
        self.counters = counters
        self.gauges = gauges

    def to_json(self) -> dict:
        return {"seq": self.seq, "t_unix": self.t_unix,
                "t_mono": self.t_mono,
                "counters": dict(self.counters),
                "gauges": dict(self.gauges)}


class MetricsHistory:
    """Bounded ring of delta-compressed registry frames + the
    mean-shift anomaly detectors (module docstring)."""

    # static race contract (tools/graftlint GL003): the sampler
    # thread, the endpoint handler threads and the black-box flusher
    # meet on these fields — touch them only under `with self._lock`
    GUARDED_BY = ("_frames", "_base_counters", "_base_gauges",
                  "_last_counters", "_last_gauges", "_kinds", "_seq",
                  "_detectors")

    def __init__(self, registry: Optional[object] = None,
                 interval_s: Optional[float] = None,
                 capacity: Optional[int] = None,
                 anomaly_window: int = 8,
                 signals: Optional[Tuple[Signal, ...]] = None):
        self._registry = (registry if registry is not None
                          else _registry.REGISTRY)
        self.interval_s = max(0.05, float(
            interval_s if interval_s is not None
            else _env_float(_ENV_INTERVAL, 1.0)))
        self.capacity = max(4, int(
            capacity if capacity is not None
            else _env_int(_ENV_RING, 512)))
        self._lock = threading.Lock()
        self._frames: List[_Frame] = []
        # state as of just-before-the-oldest-retained-frame: evicted
        # frames FOLD in here, so reconstruction stays exact over the
        # whole retained window (the delta-compression invariant)
        self._base_counters: Dict[str, float] = {}
        self._base_gauges: Dict[str, float] = {}
        self._last_counters: Dict[str, float] = {}
        self._last_gauges: Dict[str, float] = {}
        self._kinds: Dict[str, str] = {}
        self._seq = 0
        self._detectors: Dict[str, _Detector] = {
            s.name: _Detector(s, anomaly_window)
            for s in (signals if signals is not None
                      else DEFAULT_SIGNALS)}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ----------------------------------------------------------
    def tick(self, t: Optional[float] = None) -> int:
        """Take one frame → its seq. ``t`` overrides the monotonic
        stamp (tests hand-drive the clock for exact rate() math)."""
        snap = self._registry.snapshot()
        flat_c = {k: float(v)
                  for k, v in snap.get("counters", {}).items()}
        flat_g = {k: float(v)
                  for k, v in snap.get("gauges", {}).items()}
        for series, h in snap.get("histograms", {}).items():
            fam, _, lbl = series.partition("{")
            suffix = ("{" + lbl) if lbl else ""
            flat_c[fam + ".count" + suffix] = float(h["count"])
            flat_c[fam + ".sum" + suffix] = float(h["sum"])
        t_mono = time.monotonic() if t is None else float(t)
        # frames are correlated across processes (doctor, blackbox
        # dumps, recorder ts stamps) by wall clock — the point of the
        # stamp is wall-clock export
        t_unix = time.time()  # graftlint: disable=GL005
        with self._lock:
            cd = {}
            for k, v in flat_c.items():
                d = v - self._last_counters.get(k, 0.0)
                if d:
                    cd[k] = d
            gd = {k: v for k, v in flat_g.items()
                  if self._last_gauges.get(k) != v}
            self._last_counters = flat_c
            self._last_gauges = flat_g
            for k in flat_c:
                self._kinds.setdefault(k, "counter")
            for k in flat_g:
                self._kinds.setdefault(k, "gauge")
            self._seq += 1
            seq = self._seq
            self._frames.append(_Frame(seq, t_unix, t_mono, cd, gd))
            while len(self._frames) > self.capacity:
                old = self._frames.pop(0)
                for k, v in old.counters.items():
                    self._base_counters[k] = (
                        self._base_counters.get(k, 0.0) + v)
                self._base_gauges.update(old.gauges)
            events = []
            for det in self._detectors.values():
                ev = det.update(flat_g)
                if ev is not None:
                    events.append((det.signal.name, ev))
        # registry effects AFTER releasing our lock: keeps the lock
        # graph acyclic (history lock never encloses the registry
        # one). Exported to the PROCESS registry even when sampling a
        # private one — the export is this plane's own accounting,
        # same as every other obs plane.
        obs.counter("raft.obs.history.frames.total").inc()
        for name, ev in events:
            g = obs.gauge("raft.obs.history.anomaly", signal=name)
            if ev == "fired":
                g.set(1.0)
                obs.counter("raft.obs.history.anomaly.total",
                            signal=name).inc()
            else:
                g.set(0.0)
        return seq

    # -- sampler thread ----------------------------------------------------
    def start(self) -> "MetricsHistory":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="raft-obs-history")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # the sampler must outlive a transient snapshot error
                # (e.g. a registry mid-reset in tests); the miss shows
                # up as a gap in frame seq timing, not a dead thread
                from raft_tpu.core.logger import get_logger
                get_logger("obs").warning(
                    "history: tick failed", exc_info=True)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- frame export (the black-box feed) ---------------------------------
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def frames_since(self, seq: int) -> List[dict]:
        """JSON-ready frames with ``seq > seq`` — what the black box
        spills each flush (dedupe key: ``seq``)."""
        with self._lock:
            return [f.to_json() for f in self._frames if f.seq > seq]

    # -- queries -----------------------------------------------------------
    def _walk(self, name: str, window_s: Optional[float]):
        """Reconstruct absolute values for every series matching
        ``name`` (exact series, exact family, or family prefix at a
        dot) → ``(points, kinds)`` with points per series as
        ``[(t_unix, t_mono, value), ...]`` inside the window."""
        with self._lock:
            frames = list(self._frames)
            base_c = dict(self._base_counters)
            base_g = dict(self._base_gauges)
            kinds = dict(self._kinds)
        if not frames:
            return {}, kinds

        def match(series: str) -> bool:
            fam = _fam(series)
            return (series == name or fam == name
                    or fam.startswith(name + "."))

        cutoff = (frames[-1].t_mono - float(window_s)
                  if window_s else None)
        run_c = {k: v for k, v in base_c.items() if match(k)}
        run_g = {k: v for k, v in base_g.items() if match(k)}
        out: Dict[str, List[Tuple[float, float, float]]] = {}
        for f in frames:
            for k, d in f.counters.items():
                if match(k):
                    run_c[k] = run_c.get(k, 0.0) + d
            for k, v in f.gauges.items():
                if match(k):
                    run_g[k] = v
            if cutoff is not None and f.t_mono < cutoff:
                continue
            for k, v in run_c.items():
                out.setdefault(k, []).append((f.t_unix, f.t_mono, v))
            for k, v in run_g.items():
                out.setdefault(k, []).append((f.t_unix, f.t_mono, v))
        return out, kinds

    def series(self, name: str, window_s: Optional[float] = None
               ) -> Dict[str, List[Tuple[float, float]]]:
        """Absolute ``(t_unix, value)`` points per matched series."""
        pts, _ = self._walk(name, window_s)
        return {k: [(t, v) for t, _tm, v in p]
                for k, p in pts.items()}

    def delta(self, name: str, window_s: Optional[float] = None
              ) -> Dict[str, float]:
        """``last - first`` per matched series over the window."""
        pts, _ = self._walk(name, window_s)
        return {k: p[-1][2] - p[0][2] for k, p in pts.items() if p}

    def rate(self, name: str, window_s: Optional[float] = None
             ) -> Dict[str, float]:
        """``(last - first) / (t_last - t_first)`` per matched series
        (per second, monotonic time base). Series with a zero-length
        span report 0.0."""
        pts, _ = self._walk(name, window_s)
        out = {}
        for k, p in pts.items():
            if not p:
                continue
            span = p[-1][1] - p[0][1]
            out[k] = (p[-1][2] - p[0][2]) / span if span > 0 else 0.0
        return out

    def kind(self, series: str) -> Optional[str]:
        with self._lock:
            return self._kinds.get(series)

    def anomalies(self) -> Dict[str, dict]:
        """Detector state per watched signal — the ``/debug/history``
        (and doctor) anomaly table."""
        with self._lock:
            out = {}
            for name, det in self._detectors.items():
                row = {"shifted": det.shifted,
                       "fired_total": det.fired_total,
                       "last": det.last,
                       "window": det.window,
                       "min_delta": det.signal.min_delta}
                if det.means is not None:
                    row["mean_prior"] = round(det.means[0], 6)
                    row["mean_recent"] = round(det.means[1], 6)
                out[name] = row
            return out

    def report(self, window_s: Optional[float] = None) -> dict:
        with self._lock:
            n = len(self._frames)
            first = self._frames[0] if n else None
            last = self._frames[-1] if n else None
            seq = self._seq
        body = {
            "enabled": True,
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "frames": n,
            "last_seq": seq,
            "window_s": window_s,
        }
        if first is not None and last is not None:
            body["span_s"] = round(last.t_mono - first.t_mono, 3)
            body["t_first_unix"] = first.t_unix
            body["t_last_unix"] = last.t_unix
        body["anomalies"] = self.anomalies()
        return body


# -- module state (the profiler's _STATE-is-None attach pattern) ----------

_STATE: Optional[MetricsHistory] = None


def enable_history(interval_s: Optional[float] = None,
                   capacity: Optional[int] = None,
                   registry: Optional[object] = None,
                   start: bool = True,
                   anomaly_window: int = 8,
                   signals: Optional[Tuple[Signal, ...]] = None
                   ) -> MetricsHistory:
    """Install (and by default start sampling into) the module history
    singleton; a previous one is closed first."""
    global _STATE
    prev, _STATE = _STATE, None
    if prev is not None:
        prev.close()
    st = MetricsHistory(registry=registry, interval_s=interval_s,
                        capacity=capacity,
                        anomaly_window=anomaly_window, signals=signals)
    if start:
        st.start()
    _STATE = st
    return st


def disable_history() -> None:
    global _STATE
    prev, _STATE = _STATE, None
    if prev is not None:
        prev.close()


def history() -> Optional[MetricsHistory]:
    """The attached history, or None (None IS the off state — one
    module-flag read per consumer hook)."""
    return _STATE


def endpoint_body(q: dict) -> Tuple[int, dict]:
    """The ``GET /debug/history?name=&window=[&points=1]`` body →
    ``(http_status, json_body)``. rate()/delta() are computed
    server-side per matched series; ``points=1`` inlines the
    reconstructed ``(t_unix, value)`` points."""
    st = _STATE
    if st is None:
        return 404, {"error": "no history attached "
                              "(obs.history.enable_history() or "
                              "RAFT_TPU_BLACKBOX=<dir>)"}
    name = (q.get("name") or [None])[0]
    try:
        window_s = float((q.get("window") or ["0"])[0]) or None
    except ValueError:
        return 400, {"error": "window must be seconds (a float)"}
    want_points = (q.get("points") or ["0"])[0] not in ("0", "",
                                                        "false")
    body = st.report(window_s=window_s)
    if name:
        pts = st.series(name, window_s=window_s)
        rates = st.rate(name, window_s=window_s)
        deltas = st.delta(name, window_s=window_s)
        series = {}
        for s in sorted(pts):
            p = pts[s]
            if not p:
                continue
            row = {"kind": st.kind(s),
                   "first": p[0][1], "last": p[-1][1],
                   "delta": deltas.get(s),
                   "rate_per_s": rates.get(s),
                   "points": len(p)}
            if want_points:
                row["values"] = [(round(t, 3), v) for t, v in p]
            series[s] = row
        body["name"] = name
        body["series"] = series
    return 200, body
