"""Request-scoped spans — the per-request story the aggregates lack.

``raft_tpu.obs`` metrics answer "how often / how slow on average";
``core.trace`` ranges answer "where inside one profiled session".
Neither ties a p99 histogram bucket back to *which* query, plan, cap
decision, or shard caused it after the fact. Spans do: every serving
entry point opens a **root span**, nested scopes (sub-batches, cap
resolution, shard dispatch) attach as **children** sharing one
``trace_id``, and the completed trace — names, parent links, wall
durations, attributes — lands in the always-on flight recorder
(:mod:`raft_tpu.obs.recorder`), exportable as Chrome-trace/Perfetto
JSON and served by the debug endpoint (:mod:`raft_tpu.obs.endpoint`).

Span names use the SAME ``raft.<module>.<op>`` taxonomy as metrics and
trace ranges (linted by ``tools/check_metric_names.py``), and every
span also opens a ``core.trace.range`` of its name, so one name finds
the histogram, the xprof range, and the recorded request.

Quick use::

    from raft_tpu.obs import spans
    with spans.span("raft.myapp.handle", route="search") as sp:
        with spans.span("raft.myapp.stage"):
            ...
        sp.set_attr("cache", "hit")

Semantics and caveats:

* **wall clock** — a span measures host time in its scope: under JAX
  async dispatch that is enqueue time unless the scope synchronizes
  (the same caveat as ``obs.timed``). ``sp.sync(value)`` optionally
  blocks on a device value and records the device-inclusive duration
  in ``attrs["device_ms"]``.
* **attributed stages** — an AOT plan executes coarse/inversion/scan/
  merge/postprocess as ONE fused program; per-stage host timing is
  impossible by design. :func:`add_stage_spans` records the program's
  stage structure as child spans whose durations split the measured
  wall by static weights, marked ``attributed=True``. They show the
  shape of the request; ``tools/profile_ivf_pieces.py`` is the
  measured ground truth (docs/observability.md walkthrough).
* **toggle** — ``RAFT_TPU_TRACE=0`` (mirroring ``RAFT_TPU_METRICS``)
  no-ops the whole layer: ``span()`` returns one shared null object
  (nothing is allocated or recorded), runtime toggle via
  :func:`set_trace_enabled`.
* **sampling** — ``RAFT_TPU_TRACE_SAMPLE`` (0.0–1.0, default 1.0)
  admits only that fraction of REQUESTS into the recorder, keeping the
  flight recorder affordable at high QPS: the decision happens once,
  at the would-be root span; sampled-out requests reuse the shared
  null span (a thread-local veto depth makes their nested ``span()``
  calls share it too — a child can never start an orphan trace).
  Runtime setter :func:`set_trace_sample_rate` (seedable for
  deterministic tests).
* **threads** — the active trace is thread-local; a trace never leaks
  across requests served on different threads.
* **cross-process propagation** (ISSUE 16) — a span can be parented
  across a thread or process boundary: :func:`current_traceparent`
  renders the innermost open span as a W3C-style ``traceparent``
  header value (``00-<trace_id>-<span_id>-01``), and ``span(name,
  remote_parent=hdr)`` roots a NEW local trace that *adopts* the
  remote trace id and records the remote span as its parent — the
  replica-side ``raft.serve.request`` root becomes a child of the
  router's ``raft.fleet.route`` span even when the two run in
  different processes. Each side records its own trace *fragment*;
  :func:`raft_tpu.obs.recorder.stitch_chrome_trace` merges fragments
  sharing one trace id back into ONE Chrome trace. A remote-parented
  root bypasses per-request sampling (the upstream root already made
  the admission decision — a trace must never lose its tail to an
  independent coin flip downstream).
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.obs.registry import NAME_RE

__all__ = [
    "Span",
    "span",
    "spanned",
    "current_span",
    "current_trace_id",
    "current_traceparent",
    "parse_traceparent",
    "add_stage_spans",
    "add_child_span",
    "set_trace_enabled",
    "trace_enabled",
    "set_trace_sample_rate",
    "trace_sample_rate",
]


def _env_enabled() -> bool:
    return os.environ.get("RAFT_TPU_TRACE", "1").lower() not in (
        "0", "false", "off", "no")


def _env_sample_rate() -> float:
    try:
        v = float(os.environ.get("RAFT_TPU_TRACE_SAMPLE", "1.0"))
    except ValueError:
        return 1.0
    return min(max(v, 0.0), 1.0)


_enabled = _env_enabled()
_sample_rate = _env_sample_rate()
_sample_rng = random.Random()
_tls = threading.local()
# itertools.count is atomic in CPython; ids only need process-local
# uniqueness (the pid prefixes exported traces where it matters)
_ids = itertools.count(1)


def set_trace_enabled(on: bool = True) -> None:
    """Runtime toggle (initial state from ``RAFT_TPU_TRACE``)."""
    global _enabled
    _enabled = bool(on)


def trace_enabled() -> bool:
    return _enabled


def set_trace_sample_rate(rate: float, seed: Optional[int] = None
                          ) -> None:
    """Runtime per-request sampling rate (initial state from
    ``RAFT_TPU_TRACE_SAMPLE``). ``seed`` re-seeds the admission RNG —
    deterministic tests only."""
    global _sample_rate
    _sample_rate = min(max(float(rate), 0.0), 1.0)
    if seed is not None:
        _sample_rng.seed(seed)


def trace_sample_rate() -> float:
    return _sample_rate


def _new_id() -> str:
    return f"{next(_ids):08x}"


class _TraceState:
    """Per-thread in-flight trace: the stack of open spans plus the
    records of finished ones."""

    __slots__ = ("trace_id", "spans", "stack", "t0", "t0_unix",
                 "remote_parent")

    def __init__(self, trace_id: Optional[str] = None,
                 remote_parent: Optional[str] = None):
        self.trace_id = (trace_id if trace_id is not None
                         else f"{os.getpid():x}-{_new_id()}")
        # span id of the remote parent this trace fragment hangs under
        # (cross-process propagation, ISSUE 16); None for a local root
        self.remote_parent = remote_parent
        self.spans: List[dict] = []
        self.stack: List["Span"] = []
        self.t0 = time.perf_counter()
        # wall-clock on purpose: exported trace timestamps must be
        # correlatable across processes
        self.t0_unix = time.time()  # graftlint: disable=GL005


class Span:
    """One open scope. Use via :func:`span`; context-manager only."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "trace_id",
                 "_t0", "_trace", "_range", "_tid", "_root", "_remote")

    def __init__(self, name: str, attrs: Dict[str, object],
                 remote: Optional[Tuple[str, str]] = None):
        if not NAME_RE.match(name):
            raise ValueError(
                f"span name {name!r} violates the raft.<module>.<op> "
                f"taxonomy (want {NAME_RE.pattern})")
        self.name = name
        self.attrs = attrs
        self.span_id = ""
        self.parent_id = None
        self.trace_id = ""
        self._t0 = 0.0
        self._trace = None
        self._range = None
        self._tid = 0
        self._root = False
        # parsed (trace_id, span_id) of a remote parent — consumed only
        # when this span roots a new trace
        self._remote = remote

    # -- attributes --------------------------------------------------------
    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def set_attrs(self, **kv) -> None:
        self.attrs.update(kv)

    def sync(self, value) -> float:
        """Block until ``value`` (any pytree of jax arrays) is ready and
        record the device-inclusive elapsed time since span start as
        ``attrs["device_ms"]``. Returns the elapsed seconds."""
        import jax
        jax.block_until_ready(value)
        dt = time.perf_counter() - self._t0
        self.attrs["device_ms"] = round(dt * 1e3, 3)
        return dt

    # -- scope -------------------------------------------------------------
    def __enter__(self) -> "Span":
        tr = getattr(_tls, "trace", None)
        if tr is None:
            if self._remote is not None:
                # adopt the remote trace id so every fragment of one
                # routed request shares it; the remote span id becomes
                # this root's parent link
                tr = _TraceState(trace_id=self._remote[0],
                                 remote_parent=self._remote[1])
            else:
                tr = _TraceState()
            _tls.trace = tr
            self._root = True
        self._trace = tr
        self.trace_id = tr.trace_id
        self.span_id = _new_id()
        if tr.stack:
            self.parent_id = tr.stack[-1].span_id
        elif tr.remote_parent is not None:
            self.parent_id = tr.remote_parent
        tr.stack.append(self)
        self._tid = threading.get_ident()
        # the span IS the profiler range (shared taxonomy): cheap no-op
        # without an active profiler session
        from raft_tpu.core import trace
        self._range = trace.range(self.name)
        self._range.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        rng, self._range = self._range, None
        if rng is not None:
            rng.__exit__(exc_type, exc, tb)
        tr = self._trace
        self._trace = None
        if tr is None:
            return False
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        try:
            tr.stack.remove(self)
        except ValueError:
            pass
        rec = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_start_ms": round((self._t0 - tr.t0) * 1e3, 3),
            "duration_ms": round(dur * 1e3, 3),
            "tid": self._tid,
        }
        if self.attrs:
            rec["attrs"] = dict(self.attrs)
        tr.spans.append(rec)
        if self._root:
            _tls.trace = None
            _finalize(tr, self, dur)
        return False


class _NullSpan:
    """Shared no-op span for the disabled layer: accepts every Span
    method, allocates nothing, records nothing."""

    __slots__ = ()
    name = ""
    span_id = ""
    trace_id = ""
    parent_id = None
    attrs: Dict[str, object] = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, key: str, value) -> None: ...

    def set_attrs(self, **kv) -> None: ...

    def sync(self, value) -> float:
        return 0.0


_NULL_SPAN = _NullSpan()


class _VetoSpan(_NullSpan):
    """The shared null span of a SAMPLED-OUT request: state-free (all
    bookkeeping lives in a thread-local depth counter), so one shared
    instance serves every suppressed scope. The veto depth keeps every
    nested ``span()`` of the rejected request on this same object —
    without it, a child opened inside a sampled-out root would roll
    its own admission and could record an orphan fragment trace."""

    __slots__ = ()

    def __enter__(self):
        _tls.veto = getattr(_tls, "veto", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.veto = max(0, getattr(_tls, "veto", 1) - 1)
        return False


_VETO_SPAN = _VetoSpan()


def span(name: str, remote_parent: Optional[str] = None,
         **attrs) -> Span:
    """Open a span named under the ``raft.<module>.<op>`` taxonomy.
    Returns the shared null object when tracing is disabled, or when
    this would start a new trace and per-request sampling
    (``RAFT_TPU_TRACE_SAMPLE``) rejects it.

    ``remote_parent`` (a :func:`current_traceparent` value, usually
    carried in an HTTP header or a ``submit(trace_context=...)``
    field) parents the span across a process/thread boundary: when
    this span roots a new trace, the trace adopts the remote trace id
    and the span records the remote span as its parent — and sampling
    is bypassed (the upstream root already admitted the request).
    Ignored when a trace is already open on this thread (a nested span
    has a real local parent) or when the value is malformed
    (propagation must never fail a request)."""
    if not _enabled:
        return _NULL_SPAN
    remote = (parse_traceparent(remote_parent)
              if remote_parent is not None else None)
    if getattr(_tls, "trace", None) is None and remote is None:
        # root-span admission: one Bernoulli draw per request; the
        # veto depth extends a rejection to the whole request
        if getattr(_tls, "veto", 0):
            return _VETO_SPAN
        if _sample_rate < 1.0 and _sample_rng.random() >= _sample_rate:
            return _VETO_SPAN
    return Span(name, attrs, remote=remote)


def spanned(name: str, **attrs):
    """Decorator form: run every call of the wrapped function inside
    ``span(name, **attrs)`` (fresh span per call — re-entrant). The
    body can enrich it via ``current_span().set_attrs(...)``."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name, **attrs):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def current_span():
    """The innermost open span on this thread (the null span when
    tracing is off or no span is open) — lets deep call sites attach
    attributes (resolved cap, cache hit/miss) to the request that is
    already in flight without opening a scope of their own."""
    if not _enabled:
        return _NULL_SPAN
    tr = getattr(_tls, "trace", None)
    if tr is not None and tr.stack:
        return tr.stack[-1]
    return _NULL_SPAN


def current_trace_id() -> Optional[str]:
    tr = getattr(_tls, "trace", None)
    return tr.trace_id if tr is not None else None


def current_traceparent() -> Optional[str]:
    """Render the innermost open span as a W3C-style ``traceparent``
    value (``00-<trace_id>-<span_id>-01``) for cross-process
    propagation, or None when no span is open (or tracing is off).
    The flags byte is always ``01`` (sampled): an open span means the
    admission decision already said yes."""
    if not _enabled:
        return None
    tr = getattr(_tls, "trace", None)
    if tr is None or not tr.stack:
        return None
    return f"00-{tr.trace_id}-{tr.stack[-1].span_id}-01"


def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """Parse a ``traceparent`` value into ``(trace_id, span_id)``, or
    None when missing/malformed — propagation must never fail a
    request. Lenient on the trace-id charset because our ids embed a
    dash (``{pid:x}-{counter:08x}``): split the version off the front,
    then the span id + flags off the back, and the middle is the trace
    id verbatim."""
    if not header:
        return None
    try:
        version, rest = header.strip().split("-", 1)
        trace_id, span_id, _flags = rest.rsplit("-", 2)
    except ValueError:
        return None
    if version != "00" or not trace_id or not span_id:
        return None
    if len(_flags) != 2 or not all(c in "0123456789abcdefABCDEF"
                                   for c in _flags):
        return None
    return trace_id, span_id


def add_stage_spans(stages: Sequence[Tuple[str, float]], total_s: float,
                    **attrs) -> None:
    """Record attributed child spans under the current span: ``stages``
    is a sequence of ``(name, weight)``; each stage's duration splits
    ``total_s`` proportionally, laid end-to-end over the interval that
    just elapsed (``[now - total_s, now]``). Used by the AOT plan path,
    where the stages execute inside ONE fused program and cannot be
    host-timed individually — spans carry ``attributed=True`` so
    exporters and readers can tell estimation from measurement."""
    if not _enabled:
        return
    tr = getattr(_tls, "trace", None)
    if tr is None or not tr.stack:
        return
    parent = tr.stack[-1]
    total_w = sum(w for _, w in stages)
    if total_w <= 0 or total_s < 0:
        return
    tid = threading.get_ident()
    cursor = time.perf_counter() - total_s
    for name, w in stages:
        if not NAME_RE.match(name):
            raise ValueError(
                f"stage span name {name!r} violates the taxonomy")
        dur = total_s * (w / total_w)
        tr.spans.append({
            "name": name,
            "span_id": _new_id(),
            "parent_id": parent.span_id,
            "t_start_ms": round((cursor - tr.t0) * 1e3, 3),
            "duration_ms": round(dur * 1e3, 3),
            "tid": tid,
            "attrs": {"attributed": True, **attrs},
        })
        cursor += dur


def add_child_span(name: str, start_s: float, duration_s: float,
                   **attrs) -> None:
    """Record one already-timed child span under the current span
    (``start_s`` on the ``time.perf_counter`` clock). The rank-tagged
    shard spans of ``parallel/ivf.py`` use this: the SPMD dispatch runs
    every rank inside one host call, so the per-rank spans share the
    dispatch interval and are merged host-side into the one trace."""
    if not _enabled:
        return
    tr = getattr(_tls, "trace", None)
    if tr is None or not tr.stack:
        return
    if not NAME_RE.match(name):
        raise ValueError(f"span name {name!r} violates the taxonomy")
    tr.spans.append({
        "name": name,
        "span_id": _new_id(),
        "parent_id": tr.stack[-1].span_id,
        "t_start_ms": round((start_s - tr.t0) * 1e3, 3),
        "duration_ms": round(duration_s * 1e3, 3),
        "tid": threading.get_ident(),
        "attrs": attrs,
    })


def _finalize(tr: _TraceState, root: Span, dur_s: float) -> None:
    trace = {
        "trace_id": tr.trace_id,
        "name": root.name,
        "start_unix": tr.t0_unix,
        "duration_ms": round(dur_s * 1e3, 3),
        "spans": tr.spans,
    }
    if root.attrs:
        trace["attrs"] = dict(root.attrs)
    if tr.remote_parent is not None:
        # marks this trace as a child FRAGMENT of a remote trace; the
        # stitcher uses it to tell router-side roots from replica-side
        trace["remote_parent"] = tr.remote_parent
    # lazy import: recorder depends on registry/logger only, so the
    # dependency between the two obs submodules stays one-way
    from raft_tpu.obs import recorder as _recorder
    _recorder.RECORDER.record(trace)
