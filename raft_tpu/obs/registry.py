"""Dependency-free, thread-safe metrics registry.

The quantitative half of the observability story (the qualitative half
is ``core/trace.py`` xprof ranges + ``core/logger.py``): counters,
gauges and fixed-boundary histograms, grouped into labeled families
keyed by frozen label tuples — the Prometheus data model, implemented
on the stdlib only so ``raft_tpu`` gains no dependency.

Design constraints (ISSUE 1 tentpole):

* **taxonomy** — every metric name is ``raft.<module>.<op>[...]``
  (lowercase, dot-separated), the SAME naming scheme ``obs.timed``
  uses for its xprof trace ranges, so a wall-time histogram and its
  profiler annotation are findable under one name.
  ``tools/check_metric_names.py`` lints the taxonomy.
* **hot-path safe** — instrument lookups are two dict hits under one
  registry lock (host-side microseconds; every instrumented site is a
  per-dispatch host path, never per-element device work).
* **no-op toggle** — ``RAFT_TPU_METRICS=0`` (or ``set_enabled(False)``)
  makes every instrument a shared null object: nothing is registered,
  ``snapshot()`` stays empty, overhead is one attribute check.
* **bounded cardinality** — a family refuses to materialize more than
  ``max_series`` children (:class:`CardinalityError`): an unbounded
  label (query id, pointer) must fail loudly, not leak memory forever.
"""

from __future__ import annotations

import bisect
import math
import os
import re
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

__all__ = [
    "CardinalityError",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "DEFAULT_BUCKETS",
    "SIZE_BUCKETS",
    "NAME_RE",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "snapshot_diff",
    "to_prometheus_text",
    "reset",
    "set_enabled",
    "enabled",
]

# the taxonomy contract: raft.<module>.<op>... — lowercase segments of
# [a-z0-9_], dot-separated, first segment literally "raft"
NAME_RE = re.compile(r"^raft\.[a-z0-9_]+(\.[a-z0-9_]+)*$")

# latency-shaped default boundaries (seconds): sub-ms kernel dispatches
# through minutes-long cold compiles on the tunneled platform. Upper
# bound of each bucket, +Inf implicit (Prometheus ``le`` semantics:
# a value exactly on a boundary counts in that bucket).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

# count-shaped boundaries (batch sizes, probe counts, iterations):
# powers of two up to 1M
SIZE_BUCKETS: Tuple[float, ...] = tuple(
    float(1 << i) for i in range(0, 21, 2))


class CardinalityError(RuntimeError):
    """A labeled family exceeded its configured series cap."""


def _env_enabled() -> bool:
    return os.environ.get("RAFT_TPU_METRICS", "1").lower() not in (
        "0", "false", "off", "no")


def _labels_key(labels: Dict[str, object]) -> Tuple[Tuple[str, str], ...]:
    """Frozen, order-independent label identity."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _series_name(name: str, key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in key) + "}"


class Counter:
    """Monotone counter. ``inc`` only accepts non-negative amounts."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("Counter.inc: negative amount")
        with self._lock:
            self.value += amount


class Gauge:
    """Settable point-in-time value."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram:
    """Fixed-boundary histogram (Prometheus bucket semantics: boundary
    is the inclusive upper edge ``le``; one implicit +Inf bucket)."""

    __slots__ = ("_lock", "bounds", "bucket_counts", "sum", "count")

    def __init__(self, lock: threading.RLock,
                 bounds: Sequence[float] = DEFAULT_BUCKETS):
        # strip a trailing +Inf if the caller spelled it out; it is
        # always implicit
        bounds = tuple(float(b) for b in bounds if not math.isinf(b))
        if list(bounds) != sorted(set(bounds)):
            raise ValueError("Histogram: bucket bounds must be strictly "
                             "increasing")
        self._lock = lock
        self.bounds = bounds
        self.bucket_counts = [0] * (len(bounds) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        # bisect_left: value == bounds[i] lands in bucket i (le=bounds[i])
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.bucket_counts[i] += 1
            self.sum += value
            self.count += 1


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric family: a kind + its children keyed by frozen
    label tuples."""

    __slots__ = ("name", "kind", "help", "bounds", "children")

    def __init__(self, name: str, kind: str, help: str = "",
                 bounds: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.kind = kind
        self.help = help
        self.bounds = tuple(bounds)
        self.children: Dict[Tuple[Tuple[str, str], ...], object] = {}


class _Null:
    """Shared no-op instrument for the disabled registry: accepts every
    instrument method and does nothing."""

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None: ...
    def dec(self, amount: float = 1.0) -> None: ...
    def set(self, value: float) -> None: ...
    def observe(self, value: float) -> None: ...


_NULL = _Null()


class MetricsRegistry:
    """Thread-safe registry of labeled metric families.

    One coarse ``RLock`` guards registration AND value mutation: every
    instrumented site is a host-side per-dispatch path where
    microseconds are invisible next to a device dispatch, and a single
    lock keeps ``snapshot()`` internally consistent.
    """

    def __init__(self, enabled: Optional[bool] = None,
                 max_series: Optional[int] = None):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._enabled = _env_enabled() if enabled is None else enabled
        if max_series is None:
            max_series = int(os.environ.get(
                "RAFT_TPU_METRICS_MAX_SERIES", "512"))
        self.max_series = max_series

    # -- enable toggle -----------------------------------------------------
    def set_enabled(self, on: bool = True) -> None:
        self._enabled = bool(on)

    def enabled(self) -> bool:
        return self._enabled

    # -- registration ------------------------------------------------------
    def _get(self, name: str, kind: str, help: str,
             bounds: Sequence[float], labels: Dict[str, object]):
        if not self._enabled:
            return _NULL
        if not NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} violates the raft.<module>.<op> "
                f"taxonomy (want {NAME_RE.pattern})")
        key = _labels_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, kind, help,
                                                     bounds)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"cannot re-register as {kind}")
            child = fam.children.get(key)
            if child is None:
                if len(fam.children) >= self.max_series:
                    raise CardinalityError(
                        f"metric family {name!r} exceeded max_series="
                        f"{self.max_series}: an unbounded label value "
                        f"(id, pointer, timestamp) is leaking series")
                if kind == "histogram":
                    child = Histogram(self._lock, fam.bounds)
                else:
                    child = _KINDS[kind](self._lock)
                fam.children[key] = child
            return child

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(name, "counter", help, (), labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(name, "gauge", help, (), labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        return self._get(name, "histogram", help, buckets, labels)

    # -- export ------------------------------------------------------------
    def snapshot(self) -> dict:
        """Point-in-time JSON-ready dict:
        ``{"counters": {series: value}, "gauges": {...},
        "histograms": {series: {"count", "sum", "buckets"}}}``.
        Series keys are ``name`` or ``name{k=v,...}`` with sorted
        labels."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            for fam in self._families.values():
                for key, child in fam.children.items():
                    series = _series_name(fam.name, key)
                    if fam.kind == "counter":
                        out["counters"][series] = child.value
                    elif fam.kind == "gauge":
                        out["gauges"][series] = child.value
                    else:
                        buckets = {}
                        for b, c in zip(child.bounds, child.bucket_counts):
                            buckets[repr(b)] = c
                        buckets["+Inf"] = child.bucket_counts[-1]
                        out["histograms"][series] = {
                            "count": child.count,
                            "sum": child.sum,
                            "buckets": buckets,
                        }
        return out

    def to_prometheus_text(self) -> str:
        """Render the Prometheus text exposition format. Dots in the
        taxonomy become underscores (Prometheus name charset); counters
        gain the ``_total`` suffix, histograms emit cumulative
        ``_bucket{le=...}`` plus ``_sum``/``_count``."""
        lines = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                pname = _prom_name(name)
                if fam.kind == "counter":
                    pname += "_total"
                if fam.help:
                    lines.append(f"# HELP {pname} {fam.help}")
                lines.append(f"# TYPE {pname} {fam.kind}")
                for key in sorted(fam.children):
                    child = fam.children[key]
                    lbl = _prom_labels(key)
                    if fam.kind in ("counter", "gauge"):
                        lines.append(f"{pname}{lbl} {_fmt(child.value)}")
                        continue
                    cum = 0
                    for b, c in zip(child.bounds, child.bucket_counts):
                        cum += c
                        lines.append(
                            f"{pname}_bucket{_prom_labels(key, le=_fmt(b))}"
                            f" {cum}")
                    cum += child.bucket_counts[-1]
                    lines.append(
                        f"{pname}_bucket{_prom_labels(key, le='+Inf')}"
                        f" {cum}")
                    lines.append(f"{pname}_sum{lbl} {_fmt(child.sum)}")
                    lines.append(f"{pname}_count{lbl} {child.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every registered family (tests, bench isolation)."""
        with self._lock:
            self._families.clear()


def _prom_name(name: str) -> str:
    return name.replace(".", "_")


def _prom_labels(key: Tuple[Tuple[str, str], ...], **extra) -> str:
    items = list(key) + sorted(extra.items())
    if not items:
        return ""
    # exposition-format escapes, in spec order (backslash FIRST so the
    # escapes it introduces are not re-escaped): \\ , \" , \n
    body = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", r"\\")
                         .replace('"', r'\"').replace("\n", r"\n"))
        for k, v in items)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    """Prometheus sample-value rendering. Must be a true inverse of
    ``float()`` over its image (the federation parser round-trips
    exported text byte-stably): ±Inf and NaN use the exposition
    spellings, integral floats drop the ``.0``, everything else uses
    ``repr`` (shortest float round trip)."""
    v = float(v)
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if math.isnan(v):
        return "NaN"
    if v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


# the process-wide default registry every instrumented raft_tpu module
# writes to; tests can build private MetricsRegistry instances
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "", **labels) -> Counter:
    return REGISTRY.counter(name, help, **labels)


def gauge(name: str, help: str = "", **labels) -> Gauge:
    return REGISTRY.gauge(name, help, **labels)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = DEFAULT_BUCKETS,
              **labels) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets, **labels)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def to_prometheus_text() -> str:
    return REGISTRY.to_prometheus_text()


def reset() -> None:
    REGISTRY.reset()


def set_enabled(on: bool = True) -> None:
    REGISTRY.set_enabled(on)


def enabled() -> bool:
    return REGISTRY.enabled()


def snapshot_diff(before: dict, after: dict) -> dict:
    """Delta between two :func:`snapshot` dicts — what a bounded piece
    of work (one bench case, one request) actually did. Counters and
    histogram counts subtract; gauges report their ``after`` value when
    it changed. Unchanged series are dropped, so the diff is compact
    enough to embed per bench record."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    b_c = before.get("counters", {})
    for k, v in after.get("counters", {}).items():
        d = v - b_c.get(k, 0.0)
        if d:
            out["counters"][k] = d
    b_g = before.get("gauges", {})
    for k, v in after.get("gauges", {}).items():
        if k not in b_g or b_g[k] != v:
            out["gauges"][k] = v
    b_h = before.get("histograms", {})
    for k, h in after.get("histograms", {}).items():
        hb = b_h.get(k, {"count": 0, "sum": 0.0, "buckets": {}})
        dc = h["count"] - hb["count"]
        if not dc:
            continue
        bkts = {edge: c - hb["buckets"].get(edge, 0)
                for edge, c in h["buckets"].items()
                if c - hb["buckets"].get(edge, 0)}
        out["histograms"][k] = {"count": dc,
                                "sum": h["sum"] - hb["sum"],
                                "buckets": bkts}
    return out
