"""MNMG k-means.

The reference keeps kmeans single-GPU and leaves MNMG to cuML, built from
exactly these pieces + ``handle.get_comms()`` allreduce of centroid
sums/counts (SURVEY.md §3.3 note); this framework ships the MNMG loop
itself. Data rows are sharded over the mesh's ``data`` axis (optionally
with features sharded over a ``model`` axis); each Lloyd step computes
local assignments and per-cluster partial sums, then a psum over the mesh
produces identical replicated centroids on every shard — the exact
communication pattern of cuML's MNMG kmeans, expressed as XLA collectives
on ICI.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.core.mdarray import as_array
from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams
from raft_tpu.cluster.kmeans import _plus_plus, sample_centroids
from raft_tpu.core.precision import matmul_precision


def distributed_kmeans_step(x_shard, centroids, valid, n_clusters: int,
                            axis: str = "data"):
    """One Lloyd step inside shard_map: local assign + segment-sum, psum
    across the data axis, replicated centroid update. ``valid`` masks the
    pad rows introduced by sharding."""
    # local assignment (fused argmin formulation)
    xx = jnp.sum(x_shard * x_shard, axis=1)
    cc = jnp.sum(centroids * centroids, axis=1)
    d = xx[:, None] + cc[None, :] - 2.0 * lax.dot_general(
        x_shard, centroids, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=matmul_precision())
    labels = jnp.argmin(d, axis=1)
    mind = jnp.min(d, axis=1)
    w = valid.astype(jnp.float32)

    local_sums = jax.ops.segment_sum(x_shard * w[:, None], labels,
                                     num_segments=n_clusters)
    local_counts = jax.ops.segment_sum(w, labels, num_segments=n_clusters)
    local_inertia = jnp.sum(jnp.maximum(mind, 0.0) * w)

    sums = lax.psum(local_sums, axis)
    counts = lax.psum(local_counts, axis)
    inertia = lax.psum(local_inertia, axis)
    new_centroids = sums / jnp.where(counts == 0.0, 1.0, counts)[:, None]
    # keep old centroid for empty clusters (replicated-deterministic)
    new_centroids = jnp.where((counts == 0.0)[:, None], centroids,
                              new_centroids)
    return new_centroids, inertia


def distributed_kmeans_fit(
    x,
    params: KMeansParams = KMeansParams(),
    mesh: Optional[jax.sharding.Mesh] = None,
    axis: str = "data",
    res=None,
) -> Tuple[jax.Array, jax.Array, int]:
    """Fit k-means over a mesh → (centroids, inertia, n_iter). The full
    Lloyd loop runs as ONE jit'd while_loop over the sharded data."""
    x = as_array(x).astype(jnp.float32)
    if mesh is None:
        mesh = (res.mesh if res is not None
                else jax.sharding.Mesh(jax.devices(), ("data",)))
    n_shards = mesh.shape[axis]
    n, dim = x.shape
    k = params.n_clusters
    pad = (-n) % n_shards
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    valid = (jnp.arange(n + pad) < n)

    if params.init == InitMethod.Random:
        c0 = sample_centroids(x[:n], k, params.seed, res)
    else:
        # kmeans++ seeding on the (host-visible) global data — the seeding
        # cost is O(k) scans, negligible next to the Lloyd loop
        c0 = _plus_plus(x[:n], jnp.ones((n,), jnp.float32),
                        jax.random.key(params.seed), k)

    def build():
        from raft_tpu.parallel.mesh import shard_map_compat

        def local(x_shard, valid_shard, c_init):
            def body(state):
                c, _, it, shift = state
                new_c, inertia = distributed_kmeans_step(
                    x_shard, c, valid_shard, k, axis)
                shift = jnp.sum((new_c - c) ** 2)
                return new_c, inertia, it + 1, shift

            def cond(state):
                _, _, it, shift = state
                return jnp.logical_and(it < params.max_iter,
                                       shift > params.tol)

            state = (c_init, jnp.asarray(jnp.inf, jnp.float32),
                     jnp.asarray(0, jnp.int32),
                     jnp.asarray(jnp.inf, jnp.float32))
            c, inertia, n_iter, _ = lax.while_loop(cond, body, state)
            return c, inertia, n_iter

        return jax.jit(shard_map_compat(
            local, mesh,
            in_specs=(P(axis, None), P(axis), P()),
            out_specs=(P(), P(), P())))

    from raft_tpu.parallel.ivf import _shmap_plan
    shmapped = _shmap_plan(
        ("kmeans_fit", mesh, axis, k, int(params.max_iter),
         float(params.tol)), build)
    xs = jax.device_put(x, NamedSharding(mesh, P(axis, None)))
    vs = jax.device_put(valid, NamedSharding(mesh, P(axis)))
    cr = jax.device_put(c0, NamedSharding(mesh, P()))
    centroids, inertia, n_iter = shmapped(xs, vs, cr)
    return centroids, inertia, int(n_iter)
