"""Mesh construction and sharding helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core.error import expects


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Tuple[str, ...] = ("data",),
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    expects(int(np.prod(shape)) == len(devs),
            "make_mesh: shape %s != %d devices", shape, len(devs))
    return Mesh(np.asarray(devs).reshape(shape), axis_names=axis_names)


def shard_rows(x, mesh: Mesh, axis: str = "data"):
    """Place an array with rows sharded along a mesh axis; pads rows to a
    multiple of the axis size (pad rows are all-zero — callers that care
    use valid-row masks)."""
    import jax.numpy as jnp
    n = mesh.shape[axis]
    pad = (-x.shape[0]) % n
    if pad:
        x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    sharding = NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
    return jax.device_put(x, sharding), pad


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
