"""Mesh construction and sharding helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core.error import expects


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Tuple[str, ...] = ("data",),
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    expects(int(np.prod(shape)) == len(devs),
            "make_mesh: shape %s != %d devices", shape, len(devs))
    return Mesh(np.asarray(devs).reshape(shape), axis_names=axis_names)


def shard_rows(x, mesh: Mesh, axis: str = "data"):
    """Place an array with rows sharded along a mesh axis; pads rows to a
    multiple of the axis size (pad rows are all-zero — callers that care
    use valid-row masks)."""
    import jax.numpy as jnp
    n = mesh.shape[axis]
    pad = (-x.shape[0]) % n
    if pad:
        x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    sharding = NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
    return jax.device_put(x, sharding), pad


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the public API when it
    exists, else the ``jax.experimental`` spelling of older jax with the
    replication checker relaxed (the old checker cannot prove the
    psum/all_gather-replicated outputs the new varying-manual-axes
    system tracks). New code that only needs shard_map + collectives
    (the build paths) goes through this shim so it runs on BOTH the
    virtual CPU test mesh of old-jax environments and real multi-chip
    meshes; serving paths that use newer primitives (``lax.pcast``)
    call ``jax.shard_map`` directly and require a current jax."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def pcast_varying_compat(x, axes):
    """``lax.pcast(x, axes, to='varying')`` when the primitive exists
    (current jax: casts a replicated value so the varying-manual-axes
    checker accepts it in a varying position). On older jax the
    :func:`shard_map_compat` path already runs with ``check_rep=False``
    — there is no replication tracking to satisfy — so the cast is the
    identity. Lets bodies written for the new checker (the distributed
    knn scan inits) run on old-jax CPU meshes too."""
    from jax import lax
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    return x
