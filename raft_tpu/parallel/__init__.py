"""Multi-node-multi-device algorithms over a Mesh (SURVEY.md §2.12/§5).

The reference builds MNMG algorithms (in cuML/cuGraph) from RAFT pieces +
``handle.get_comms()``; this package ships them in-framework: distributed
brute-force k-NN (sharded DB + ring top-k merge), MNMG k-means (sharded
data + psum'd centroid statistics), and sharded IVF search.
"""

from raft_tpu.parallel.mesh import (make_mesh, shard_rows, replicate,
                                    shard_map_compat)
from raft_tpu.parallel.knn import distributed_knn
from raft_tpu.parallel.kmeans import distributed_kmeans_fit, distributed_kmeans_step
from raft_tpu.parallel.ivf import (
    get_comms,
    shard_ivf_flat,
    shard_ivf_pq,
    distributed_ivf_flat_search,
    distributed_ivf_pq_search,
    DistributedIvfFlat,
    DistributedIvfPq,
    distributed_ivf_flat_build,
    distributed_ivf_flat_search_parts,
    distributed_ivf_pq_build,
    distributed_ivf_pq_search_parts,
    distributed_ivf_bq_build,
    distributed_ivf_bq_search_parts,
    sharded_ivf_flat_build,
    sharded_ivf_pq_build,
    sharded_ivf_bq_build,
)

__all__ = [
    "make_mesh", "shard_rows", "replicate", "shard_map_compat",
    "get_comms",
    "distributed_knn",
    "distributed_kmeans_fit", "distributed_kmeans_step",
    "shard_ivf_flat", "shard_ivf_pq",
    "distributed_ivf_flat_search", "distributed_ivf_pq_search",
    "DistributedIvfFlat", "DistributedIvfPq",
    "distributed_ivf_flat_build", "distributed_ivf_flat_search_parts",
    "distributed_ivf_pq_build", "distributed_ivf_pq_search_parts",
    "distributed_ivf_bq_build", "distributed_ivf_bq_search_parts",
    "sharded_ivf_flat_build", "sharded_ivf_pq_build",
    "sharded_ivf_bq_build",
]
