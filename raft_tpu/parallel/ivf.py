"""Distributed (sharded) IVF search — the 100M-vector north star
(SURVEY.md §6-§7: shard the IVF lists across a mesh, per-shard probe
scans, collective top-k merge).

Design: the index's list dimension (``n_lists``) is sharded over the
mesh's data axis; queries are replicated. Each shard runs the standard
coarse→fine search against its local lists (its local centers are a
disjoint subset of the global centers), then shards merge their top-k
with one all_gather + select. Like the reference's multi-part search
(``knn_merge_parts``-over-parts, brute_force.cuh:48 — and cuML's MNMG
ANN), each shard probes ``n_probes`` of *its own* lists, so total probed
lists grow with the mesh: recall at fixed n_probes is ≥ the single-chip
index's.

List indices hold global database row ids from the single build, so no
id translation is needed at merge.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.core.precision import matmul_precision
from raft_tpu.comms.comms import build_comms
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.pairwise import _l2_expanded


def _shard0(arr, mesh, axis):
    """Shard an array's leading (list) dimension over mesh[axis]."""
    spec = P(axis, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def shard_ivf_flat(index, mesh: jax.sharding.Mesh, axis: str = "data"):
    """Reshard an IVF-Flat index's lists over ``mesh[axis]`` (in place on
    a new Index). n_lists must divide evenly."""
    from raft_tpu.neighbors.ivf_flat import Index
    n_shards = mesh.shape[axis]
    expects(index.n_lists % n_shards == 0,
            f"shard_ivf_flat: n_lists={index.n_lists} not divisible by "
            f"{n_shards} shards")
    return Index(
        centers=_shard0(index.centers, mesh, axis),
        lists_data=_shard0(index.lists_data, mesh, axis),
        lists_indices=_shard0(index.lists_indices, mesh, axis),
        lists_norms=_shard0(index.lists_norms, mesh, axis),
        list_sizes=_shard0(index.list_sizes, mesh, axis),
        metric=index.metric, size=index.size, scale=index.scale)


def shard_ivf_pq(index, mesh: jax.sharding.Mesh, axis: str = "data"):
    """Reshard an IVF-PQ index's lists over ``mesh[axis]``. The bf16
    reconstruction cache is decoded first (sharded scans use it)."""
    from raft_tpu.neighbors.ivf_pq import (Index, _code_norms,
                                           _decode_lists)
    n_shards = mesh.shape[axis]
    expects(index.n_lists % n_shards == 0,
            f"shard_ivf_pq: n_lists={index.n_lists} not divisible by "
            f"{n_shards} shards")
    # shard the compact payload FIRST, then decode: the bf16 cache is the
    # one array sharding exists to split — it must never materialize on a
    # single device (the 100M north-star constraint)
    codes = _shard0(index.codes, mesh, axis)
    lists_indices = _shard0(index.lists_indices, mesh, axis)
    pq_centers = jax.device_put(index.pq_centers, NamedSharding(mesh, P()))
    decoded = _decode_lists(codes, pq_centers, lists_indices)
    decoded_norms = _code_norms(codes, pq_centers, lists_indices)
    return Index(
        centers=_shard0(index.centers, mesh, axis),
        centers_rot=_shard0(index.centers_rot, mesh, axis),
        rotation_matrix=jax.device_put(index.rotation_matrix,
                                       NamedSharding(mesh, P())),
        pq_centers=pq_centers,
        codes=codes,
        lists_indices=lists_indices,
        list_sizes=_shard0(index.list_sizes, mesh, axis),
        metric=index.metric, pq_bits=index.pq_bits, size=index.size,
        decoded=decoded, decoded_norms=decoded_norms)


def _fine_scan(queries, get_probe, k: int, n_probes: int, axis: str):
    """Shared probe-rank scan with a shard-varying carry (plain
    ``_search_impl`` carries an unvarying init that shard_map's
    varying-manual-axes tracking rejects)."""
    nq = queries.shape[0]

    def probe_step(carry, p):
        best_d, best_i = carry
        d, ids = get_probe(p)
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_i = jnp.concatenate([best_i, ids], axis=1)
        nd, sel = lax.top_k(-cat_d, k)
        return (-nd, jnp.take_along_axis(cat_i, sel, axis=1)), None

    init = (lax.pcast(jnp.full((nq, k), jnp.inf, jnp.float32),
                      (axis,), to="varying"),
            lax.pcast(jnp.full((nq, k), -1, jnp.int32),
                      (axis,), to="varying"))
    (d, i), _ = lax.scan(probe_step, init, jnp.arange(n_probes))
    return d, i


def _global_merge(comms, axis, d, i, k):
    gd = comms.allgather(d)                   # (n_shards, nq, k)
    gi = comms.allgather(i)
    cat_d = jnp.moveaxis(gd, 0, 1).reshape(d.shape[0], -1)
    cat_i = jnp.moveaxis(gi, 0, 1).reshape(d.shape[0], -1)
    nd, sel = lax.top_k(-cat_d, k)
    fd, fi = -nd, jnp.take_along_axis(cat_i, sel, axis=1)
    # identical on every rank; pmax proves replication to shard_map
    return lax.pmax(fd, axis), lax.pmax(fi, axis)


def distributed_ivf_flat_search(
    index, queries, k: int, params=None,
    mesh: jax.sharding.Mesh = None, axis: str = "data",
) -> Tuple[jax.Array, jax.Array]:
    """Search a list-sharded IVF-Flat index (see :func:`shard_ivf_flat`)."""
    from raft_tpu.neighbors.ivf_flat import SearchParams
    params = params or SearchParams()
    expects(mesh is not None, "distributed ivf_flat: mesh is required")
    from raft_tpu.neighbors.ivf_flat import (_coarse_scores, _metric_kind,
                                             _postprocess, _score_probe)
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == index.dim, "distributed ivf_flat: dim mismatch")
    if index.metric == DistanceType.CosineExpanded:
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-30)
    n_shards = mesh.shape[axis]
    nl_local = index.n_lists // n_shards
    n_probes = min(params.n_probes, nl_local)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    kind = _metric_kind(index.metric)
    comms = build_comms(mesh, axis)

    def local(centers, lists_data, lists_indices, lists_norms, q_rep):
        qq = jnp.sum(q_rep * q_rep, axis=1)
        coarse = _coarse_scores(q_rep, centers, kind)
        _, probes = lax.top_k(-coarse, n_probes)

        def get_probe(p):
            return _score_probe(q_rep, qq, lists_data, lists_norms,
                                lists_indices, probes[:, p],
                                float(index.scale), kind=kind)

        d, i = _fine_scan(q_rep, get_probe, k, n_probes, axis)
        if sqrt:
            d = jnp.sqrt(jnp.maximum(d, 0.0))
        return _global_merge(comms, axis, d, i, k)

    shmapped = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None, None), P(axis, None),
                  P(axis, None), P()),
        out_specs=(P(), P())))
    q_rep = jax.device_put(q, NamedSharding(mesh, P()))
    d, i = shmapped(index.centers, index.lists_data, index.lists_indices,
                    index.lists_norms, q_rep)
    return _postprocess(d, index.metric), i


def distributed_ivf_pq_search(
    index, queries, k: int, params=None,
    mesh: jax.sharding.Mesh = None, axis: str = "data",
) -> Tuple[jax.Array, jax.Array]:
    """Search a list-sharded IVF-PQ index (see :func:`shard_ivf_pq`) via
    the bf16 reconstruction scan."""
    from raft_tpu.neighbors.ivf_pq import SearchParams
    params = params or SearchParams()
    expects(mesh is not None, "distributed ivf_pq: mesh is required")
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == index.dim, "distributed ivf_pq: dim mismatch")
    expects(index.decoded is not None,
            "distributed ivf_pq: index not sharded via shard_ivf_pq")
    from raft_tpu.neighbors.ivf_flat import (_coarse_scores, _metric_kind,
                                             _postprocess)
    from raft_tpu.neighbors.ivf_pq import _score_probe_reconstruct
    n_shards = mesh.shape[axis]
    nl_local = index.n_lists // n_shards
    n_probes = min(params.n_probes, nl_local)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    kind = _metric_kind(index.metric)
    comms = build_comms(mesh, axis)

    def local(centers, centers_rot, rot, decoded, decoded_norms,
              lists_indices, q_rep):
        coarse = _coarse_scores(q_rep, centers, kind)
        _, probes = lax.top_k(-coarse, n_probes)
        q_rot = jnp.matmul(q_rep, rot.T, precision=matmul_precision())

        def get_probe(p):
            return _score_probe_reconstruct(
                q_rot, centers_rot, decoded, decoded_norms, lists_indices,
                probes[:, p], kind=kind)

        d, i = _fine_scan(q_rep, get_probe, k, n_probes, axis)
        if sqrt:
            d = jnp.sqrt(jnp.maximum(d, 0.0))
        return _global_merge(comms, axis, d, i, k)

    shmapped = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P(axis, None, None),
                  P(axis, None), P(axis, None), P()),
        out_specs=(P(), P())))
    q_rep = jax.device_put(q, NamedSharding(mesh, P()))
    d, i = shmapped(index.centers, index.centers_rot,
                    index.rotation_matrix, index.decoded,
                    index.decoded_norms, index.lists_indices, q_rep)
    return _postprocess(d, index.metric), i
