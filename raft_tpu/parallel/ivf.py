"""Distributed (sharded) IVF search — the 100M-vector north star
(SURVEY.md §6-§7: shard the IVF lists across a mesh, per-shard probe
scans, collective top-k merge).

Design: the index's list dimension (``n_lists``) is sharded over the
mesh's data axis; queries are replicated. Each shard runs the standard
coarse→fine search against its local lists (its local centers are a
disjoint subset of the global centers), then shards merge their top-k
with one all_gather + select. Like the reference's multi-part search
(``knn_merge_parts``-over-parts, brute_force.cuh:48 — and cuML's MNMG
ANN), each shard probes ``n_probes`` of *its own* lists, so total probed
lists grow with the mesh: recall at fixed n_probes is ≥ the single-chip
index's.

List indices hold global database row ids from the single build, so no
id translation is needed at merge.
"""

from __future__ import annotations

import functools
import time
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import obs
from raft_tpu.obs import spans
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import as_array
from raft_tpu.core.precision import matmul_precision
from raft_tpu.comms.comms import build_comms
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.parallel.mesh import pcast_varying_compat, shard_map_compat
from raft_tpu.util.host_sample import sample_rows


# ---------------------------------------------------------------------------
# Sharded search-plan cache (the neighbors/plan.py analogue at mesh
# scope). Every distributed search used to build its `local` closure
# and `jax.jit(jax.shard_map(local, ...))` wrapper PER CALL — a fresh
# function identity every time, so jax's jit cache missed and the whole
# shard_map re-traced (and, without a persistent compile cache,
# re-COMPILED) on every serving call. The builders below are keyed by
# everything that shapes the program (mesh, axis, k, n_probes, metric
# core, scalars baked into the closure), so a warm key reuses one
# compiled callable and the serving call is a single cached dispatch.
# ---------------------------------------------------------------------------
# Compile-surface rung declarations (graftlint GL012–GL014): the
# _shmap_plan key dimensions that are per-index/per-process constants
# — everything else in a key must be a grid rung, an enum or a
# structural handle, or GL012 flags the site as a retrace storm.
COMPILE_SURFACE_RUNGS = {
    "n_lists": ("n_lists", None,
                "coarse list count — fixed per index"),
    "scale": ("scale", None, "quantization scale — fixed per index"),
    "size": ("size", None, "corpus row count — fixed per epoch"),
    "ml": ("ml", None, "max list length — fixed per index layout"),
    "ml_shard": ("ml_shard", None,
                 "per-shard max list length — fixed per build"),
    "max_iter": ("max_iter", None, "trainer bound — config"),
    "tol": ("tol", None, "trainer tolerance — config"),
}

_SHMAP_PLANS: dict = {}


def _shmap_plan(key, builder):
    fn = _SHMAP_PLANS.get(key)
    if fn is None:
        obs.counter("raft.parallel.plan.misses").inc()
        spans.current_span().set_attr("shmap_plan", "miss")
        fn = _SHMAP_PLANS[key] = builder()
    else:
        obs.counter("raft.parallel.plan.hits").inc()
        spans.current_span().set_attr("shmap_plan", "hit")
    return fn


# communicator cache (ISSUE 8 satellite): one Comms per (mesh, axis).
# build_comms re-runs its axis/bootstrap checks on every call — cheap
# once, not per serving batch. Ladder-cached serving paths (and every
# distributed search below) reuse ONE frozen handle per mesh axis;
# callers holding a custom handle (split comms, non-default timeouts)
# pass it via the searches' `comms=` parameter instead.
_COMMS_CACHE: dict = {}


def get_comms(mesh: jax.sharding.Mesh, axis: str = "data"):
    """Cached :class:`~raft_tpu.comms.comms.Comms` over ``mesh[axis]``
    (the ``build_comms`` result, built once per mesh axis)."""
    key = (mesh, axis)
    c = _COMMS_CACHE.get(key)
    if c is None:
        c = _COMMS_CACHE[key] = build_comms(mesh, axis)
    return c


def _rank_spans(n_shards: int, t0: float, dt: float) -> None:
    """One rank-tagged child span per mesh shard, merged host-side into
    the current trace. The shard_map dispatch executes every rank
    inside ONE host call (SPMD), so the per-rank spans share the
    dispatch interval — they tag the trace with WHICH ranks served the
    request (EQuARX-style rank-level accounting), not independent
    per-rank walls. In Chrome-trace export the ``rank`` attribute maps
    to the event pid, so ranks render as separate rows."""
    for r in range(n_shards):
        spans.add_child_span("raft.parallel.ivf.shard", t0, dt, rank=r)


def _shard0(arr, mesh, axis):
    """Shard an array's leading (list) dimension over mesh[axis]."""
    spec = P(axis, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def shard_ivf_flat(index, mesh: jax.sharding.Mesh, axis: str = "data"):
    """Reshard an IVF-Flat index's lists over ``mesh[axis]`` (in place on
    a new Index). n_lists must divide evenly."""
    from raft_tpu.neighbors.ivf_flat import Index
    n_shards = mesh.shape[axis]
    expects(index.n_lists % n_shards == 0,
            f"shard_ivf_flat: n_lists={index.n_lists} not divisible by "
            f"{n_shards} shards")
    return Index(
        centers=_shard0(index.centers, mesh, axis),
        lists_data=_shard0(index.lists_data, mesh, axis),
        lists_indices=_shard0(index.lists_indices, mesh, axis),
        lists_norms=_shard0(index.lists_norms, mesh, axis),
        list_sizes=_shard0(index.list_sizes, mesh, axis),
        metric=index.metric, size=index.size, scale=index.scale)


def shard_ivf_pq(index, mesh: jax.sharding.Mesh, axis: str = "data"):
    """Reshard an IVF-PQ index's lists over ``mesh[axis]``. The bf16
    reconstruction cache is decoded first (sharded scans use it)."""
    from raft_tpu.neighbors.ivf_pq import (
        CodebookGen, Index, _code_norms, _code_norms_per_cluster,
        _decode_lists, _decode_lists_per_cluster)
    n_shards = mesh.shape[axis]
    expects(index.n_lists % n_shards == 0,
            f"shard_ivf_pq: n_lists={index.n_lists} not divisible by "
            f"{n_shards} shards")
    # shard the compact payload FIRST, then decode: the bf16 cache is the
    # one array sharding exists to split — it must never materialize on a
    # single device (the 100M north-star constraint)
    codes = _shard0(index.codes, mesh, axis)
    lists_indices = _shard0(index.lists_indices, mesh, axis)
    if index.codebook_kind == CodebookGen.PER_CLUSTER:
        # per-cluster books are list-aligned: shard them WITH the lists
        # and decode shard-locally
        pq_centers = _shard0(index.pq_centers, mesh, axis)
        decoded = _decode_lists_per_cluster(codes, pq_centers,
                                            lists_indices)
        norms_fn = _code_norms_per_cluster
    else:
        pq_centers = jax.device_put(index.pq_centers,
                                    NamedSharding(mesh, P()))
        decoded = _decode_lists(codes, pq_centers, lists_indices)
        norms_fn = _code_norms
    # build already holds the identical exact norms: shard them instead
    # of re-gathering every code slot; recompute only for older indexes
    decoded_norms = (_shard0(index.code_norms, mesh, axis)
                     if index.code_norms is not None
                     else norms_fn(codes, pq_centers, lists_indices))
    return Index(
        centers=_shard0(index.centers, mesh, axis),
        centers_rot=_shard0(index.centers_rot, mesh, axis),
        rotation_matrix=jax.device_put(index.rotation_matrix,
                                       NamedSharding(mesh, P())),
        pq_centers=pq_centers,
        codes=codes,
        lists_indices=lists_indices,
        list_sizes=_shard0(index.list_sizes, mesh, axis),
        metric=index.metric, pq_bits=index.pq_bits, size=index.size,
        codebook_kind=index.codebook_kind,
        decoded=decoded, decoded_norms=decoded_norms)


def _fine_scan(queries, get_probe, k: int, n_probes: int, axis: str):
    """Shared probe-rank scan with a shard-varying carry (plain
    ``_search_impl`` carries an unvarying init that shard_map's
    varying-manual-axes tracking rejects)."""
    nq = queries.shape[0]

    def probe_step(carry, p):
        best_d, best_i = carry
        d, ids = get_probe(p)
        cat_d = jnp.concatenate([best_d, d], axis=1)
        cat_i = jnp.concatenate([best_i, ids], axis=1)
        nd, sel = lax.top_k(-cat_d, k)
        return (-nd, jnp.take_along_axis(cat_i, sel, axis=1)), None

    init = (pcast_varying_compat(jnp.full((nq, k), jnp.inf, jnp.float32),
                                 (axis,)),
            pcast_varying_compat(jnp.full((nq, k), -1, jnp.int32),
                                 (axis,)))
    (d, i), _ = lax.scan(probe_step, init, jnp.arange(n_probes))
    return d, i


def _global_merge(comms, axis, d, i, k):
    gd = comms.allgather(d)                   # (n_shards, nq, k)
    gi = comms.allgather(i)
    cat_d = jnp.moveaxis(gd, 0, 1).reshape(d.shape[0], -1)
    cat_i = jnp.moveaxis(gi, 0, 1).reshape(d.shape[0], -1)
    nd, sel = lax.top_k(-cat_d, k)
    fd, fi = -nd, jnp.take_along_axis(cat_i, sel, axis=1)
    # identical on every rank; pmax proves replication to shard_map
    return lax.pmax(fd, axis), lax.pmax(fi, axis)


def _merge_topk(comms, axis, d, i, k, merge: str, size: int):
    """Cross-shard top-k merge at the selected wire format: the exact
    f32 allgather, or the int8 two-stage compressed merge
    (``serve/merge.py`` — EQuARX-style quantized collective; the
    ``RAFT_TPU_DIST_MERGE`` story lives there)."""
    if merge == "int8":
        from raft_tpu.serve.merge import compressed_merge
        return compressed_merge(comms, d, i, k, size)
    return _global_merge(comms, axis, d, i, k)


def _resolve_merge(merge):
    """Library-function default for the cross-shard merge wire format:
    exact f32 unless ``RAFT_TPU_DIST_MERGE`` (or the caller) opts into
    the int8 compressed merge. The serving tier (``serve/dist.py``)
    resolves its own default (int8) — see ``serve/merge.merge_mode``."""
    if merge is None:
        from raft_tpu.serve.merge import merge_mode
        merge = merge_mode(default="f32")
    expects(merge in ("f32", "int8"),
            "distributed search: merge must be 'f32' or 'int8', got %r",
            merge)
    return merge


def _flat_list_plan(mesh, axis: str, k: int, n_probes: int, kind: str,
                    sqrt: bool, scale: float, merge: str, size: int,
                    comms):
    """Cached shard_map program for the list-sharded IVF-Flat search —
    shared by :func:`distributed_ivf_flat_search` and the serving
    tier's pre-warmed distributed plan ladder (``serve/dist.py``)."""
    from raft_tpu.neighbors.ivf_flat import _coarse_scores, _score_probe

    def build():
        def local(centers, lists_data, lists_indices, lists_norms,
                  q_rep):
            qq = jnp.sum(q_rep * q_rep, axis=1)
            coarse = _coarse_scores(q_rep, centers, kind)
            _, probes = lax.top_k(-coarse, n_probes)

            def get_probe(p):
                return _score_probe(q_rep, qq, lists_data, lists_norms,
                                    lists_indices, probes[:, p],
                                    scale, kind=kind)

            d, i = _fine_scan(q_rep, get_probe, k, n_probes, axis)
            if sqrt:
                d = jnp.sqrt(jnp.maximum(d, 0.0))
            return _merge_topk(comms, axis, d, i, k, merge, size)

        return jax.jit(shard_map_compat(
            local, mesh,
            in_specs=(P(axis, None), P(axis, None, None), P(axis, None),
                      P(axis, None), P()),
            out_specs=(P(), P())))

    return _shmap_plan(
        ("flat_list", mesh, axis, k, n_probes, kind, sqrt, scale, merge,
         size, comms), build)


def distributed_ivf_flat_search(
    index, queries, k: int, params=None,
    mesh: jax.sharding.Mesh = None, axis: str = "data",
    comms=None, merge: str = None,
) -> Tuple[jax.Array, jax.Array]:
    """Search a list-sharded IVF-Flat index (see :func:`shard_ivf_flat`).

    ``comms`` — a pre-built communicator handle (default: the cached
    :func:`get_comms` handle, so repeated serving calls never re-run
    the bootstrap checks). ``merge`` — cross-shard merge wire format
    (``f32`` exact | ``int8`` compressed; default f32 unless
    ``RAFT_TPU_DIST_MERGE`` says otherwise)."""
    from raft_tpu.neighbors.ivf_flat import SearchParams
    params = params or SearchParams()
    expects(mesh is not None, "distributed ivf_flat: mesh is required")
    from raft_tpu.neighbors.ivf_flat import _metric_kind, _postprocess
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == index.dim, "distributed ivf_flat: dim mismatch")
    if index.metric == DistanceType.CosineExpanded:
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-30)
    n_shards = mesh.shape[axis]
    nl_local = index.n_lists // n_shards
    n_probes = min(params.n_probes, nl_local)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    kind = _metric_kind(index.metric)
    scale = float(index.scale)
    merge = _resolve_merge(merge)
    comms = comms if comms is not None else get_comms(mesh, axis)

    with spans.span("raft.parallel.ivf.search", family="ivf_flat",
                    nq=int(q.shape[0]), k=k, n_probes=n_probes,
                    axis=axis, n_shards=n_shards, merge=merge):
        shmapped = _flat_list_plan(mesh, axis, k, n_probes, kind, sqrt,
                                   scale, merge, int(index.size), comms)
        q_rep = jax.device_put(q, NamedSharding(mesh, P()))
        t0 = time.perf_counter()
        d, i = shmapped(index.centers, index.lists_data,
                        index.lists_indices, index.lists_norms, q_rep)
        _rank_spans(n_shards, t0, time.perf_counter() - t0)
    return _postprocess(d, index.metric), i


def _pq_list_plan(mesh, axis: str, k: int, n_probes: int, kind: str,
                  sqrt: bool, merge: str, size: int, comms):
    """Cached shard_map program for the list-sharded IVF-PQ
    (reconstruction-scan) search — shared by
    :func:`distributed_ivf_pq_search` and the serving tier's ladder."""
    from raft_tpu.neighbors.ivf_flat import _coarse_scores
    from raft_tpu.neighbors.ivf_pq import _score_probe_reconstruct

    def build():
        def local(centers, centers_rot, rot, decoded, decoded_norms,
                  lists_indices, q_rep):
            coarse = _coarse_scores(q_rep, centers, kind)
            _, probes = lax.top_k(-coarse, n_probes)
            q_rot = jnp.matmul(q_rep, rot.T,
                               precision=matmul_precision())

            def get_probe(p):
                return _score_probe_reconstruct(
                    q_rot, centers_rot, decoded, decoded_norms,
                    lists_indices, probes[:, p], kind=kind)

            d, i = _fine_scan(q_rep, get_probe, k, n_probes, axis)
            if sqrt:
                d = jnp.sqrt(jnp.maximum(d, 0.0))
            return _merge_topk(comms, axis, d, i, k, merge, size)

        return jax.jit(shard_map_compat(
            local, mesh,
            in_specs=(P(axis, None), P(axis, None), P(),
                      P(axis, None, None), P(axis, None), P(axis, None),
                      P()),
            out_specs=(P(), P())))

    return _shmap_plan(
        ("pq_list", mesh, axis, k, n_probes, kind, sqrt, merge, size,
         comms), build)


def distributed_ivf_pq_search(
    index, queries, k: int, params=None,
    mesh: jax.sharding.Mesh = None, axis: str = "data",
    comms=None, merge: str = None,
) -> Tuple[jax.Array, jax.Array]:
    """Search a list-sharded IVF-PQ index (see :func:`shard_ivf_pq`) via
    the bf16 reconstruction scan. ``comms``/``merge`` as in
    :func:`distributed_ivf_flat_search`."""
    from raft_tpu.neighbors.ivf_pq import SearchParams
    params = params or SearchParams()
    expects(mesh is not None, "distributed ivf_pq: mesh is required")
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == index.dim, "distributed ivf_pq: dim mismatch")
    expects(index.decoded is not None,
            "distributed ivf_pq: index not sharded via shard_ivf_pq")
    from raft_tpu.neighbors.ivf_flat import _metric_kind, _postprocess
    n_shards = mesh.shape[axis]
    nl_local = index.n_lists // n_shards
    n_probes = min(params.n_probes, nl_local)
    sqrt = index.metric in (DistanceType.L2SqrtExpanded,
                            DistanceType.L2SqrtUnexpanded)
    kind = _metric_kind(index.metric)
    merge = _resolve_merge(merge)
    comms = comms if comms is not None else get_comms(mesh, axis)

    with spans.span("raft.parallel.ivf.search", family="ivf_pq",
                    nq=int(q.shape[0]), k=k, n_probes=n_probes,
                    axis=axis, n_shards=n_shards, merge=merge):
        shmapped = _pq_list_plan(mesh, axis, k, n_probes, kind, sqrt,
                                 merge, int(index.size), comms)
        q_rep = jax.device_put(q, NamedSharding(mesh, P()))
        t0 = time.perf_counter()
        d, i = shmapped(index.centers, index.centers_rot,
                        index.rotation_matrix, index.decoded,
                        index.decoded_norms, index.lists_indices, q_rep)
        _rank_spans(n_shards, t0, time.perf_counter() - t0)
    return _postprocess(d, index.metric), i


# ---------------------------------------------------------------------------
# Distributed BUILD (VERDICT round-1 item 6 / reference ivf_pq_build.cuh:605
# extend + SURVEY.md §3.3 MNMG note): the dataset stays row-sharded on the
# mesh; coarse centers are trained with the MNMG kmeans; each shard encodes
# and buckets its OWN rows into partial lists with global ids. The global
# index never materializes on one device — it exists only as the collection
# of per-shard parts, the reference's own multi-part layout
# (brute_force.cuh:48 knn over parts + merge). Search probes the SAME
# global centers on every shard, scans the shard's partial lists, and
# merges — the scanned set equals the single-host index's, so results are
# numerically identical at matched probes.
# ---------------------------------------------------------------------------

from dataclasses import dataclass

from raft_tpu.cluster.kmeans_types import KMeansParams


@dataclass
class DistributedIvfFlat:
    """Row-sharded multi-part IVF-Flat index. ``parts_*`` lead with the
    shard axis and live sharded over ``mesh[axis]``; ``centers`` is
    replicated. ``parts_indices`` holds GLOBAL dataset row ids."""

    centers: jax.Array        # (n_lists, dim) replicated
    parts_data: jax.Array     # (n_shards, n_lists, ml, dim) P(axis,...)
    parts_indices: jax.Array  # (n_shards, n_lists, ml) int32, -1 pad
    parts_norms: jax.Array    # (n_shards, n_lists, ml)
    metric: "DistanceType"
    size: int
    mesh: jax.sharding.Mesh
    axis: str

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]


def _shard_rows(x, mesh, axis):
    """Pad + shard rows over mesh[axis]; returns (x_sharded,
    ids_sharded) with pad rows carrying id -1."""
    n = x.shape[0]
    n_shards = mesh.shape[axis]
    pad = (-n) % n_shards
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    ids = jnp.where(jnp.arange(n + pad) < n,
                    jnp.arange(n + pad, dtype=jnp.int32), -1)
    xs = jax.device_put(x, NamedSharding(mesh, P(axis, None)))
    ids_s = jax.device_put(ids, NamedSharding(mesh, P(axis)))
    return xs, ids_s


def _label_and_agree_width(xs, ids_s, centers, mesh, axis, n_lists: int,
                           kind: str):
    """Shared by both distributed builds: per-shard labels + per-list
    counts in one small jit, then one host sync agrees a static bucket
    width every shard uses (pad rows get the overflow label
    ``n_lists``, excluded from the counts)."""
    from raft_tpu.neighbors.ivf_flat import _coarse_scores

    def build():
        def count_local(x_loc, ids_loc, c):
            lbl = jnp.argmin(_coarse_scores(x_loc, c, kind), axis=1)
            lbl = jnp.where(ids_loc >= 0, lbl, n_lists)
            cnt = jax.ops.segment_sum(jnp.ones_like(lbl, jnp.int32), lbl,
                                      num_segments=n_lists + 1)[:n_lists]
            return lbl.astype(jnp.int32), cnt

        return jax.jit(shard_map_compat(
            count_local, mesh, in_specs=(P(axis, None), P(axis), P()),
            out_specs=(P(axis), P(axis))))

    # keyed on everything the closure bakes in (GL002: a fresh callable
    # per build re-traced the shard_map every call; amortized ≠ free —
    # repeated builds on one mesh now reuse ONE compiled program)
    counted = _shmap_plan(("count_agree", mesh, axis, n_lists, kind),
                          build)
    c_rep = jax.device_put(centers, NamedSharding(mesh, P()))
    labels_s, counts = counted(xs, ids_s, c_rep)
    ml = int(jax.device_get(jnp.max(counts.reshape(
        mesh.shape[axis], n_lists))))
    ml = max(8, -(-ml // 8) * 8)
    return labels_s, ml, c_rep


def distributed_ivf_flat_build(
    x, params=None, mesh: jax.sharding.Mesh = None, axis: str = "data",
) -> DistributedIvfFlat:
    """Build a row-sharded IVF-Flat index directly on the mesh: MNMG
    kmeans for the coarse centers, then per-shard label + bucketize of
    the shard's own rows (reference build = train + partition,
    ivf_flat_build.cuh:228, distributed per SURVEY.md §3.3)."""
    from raft_tpu.neighbors.ivf_flat import (IndexParams, _bucketize_static,
                                             _coarse_scores, _metric_kind)
    from raft_tpu.parallel.kmeans import distributed_kmeans_fit
    params = params or IndexParams()
    expects(mesh is not None, "distributed build: mesh is required")
    expects(params.metric in (DistanceType.L2Expanded,
                              DistanceType.L2SqrtExpanded,
                              DistanceType.L2Unexpanded,
                              DistanceType.L2SqrtUnexpanded,
                              DistanceType.InnerProduct,
                              DistanceType.CosineExpanded),
            "distributed ivf_flat build: unsupported metric %s",
            params.metric)
    expects(params.storage_dtype == "float32",
            "distributed ivf_flat build: narrow list storage (%s) is not "
            "implemented for sharded parts yet; use float32",
            params.storage_dtype)
    x = as_array(x).astype(jnp.float32)
    if params.metric == DistanceType.CosineExpanded:
        x = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True),
                            1e-30)
    n, dim = x.shape
    n_lists = params.n_lists
    expects(n_lists <= n, "distributed build: n_lists > n_samples")

    # 1) coarse centers: the MNMG Lloyd loop over the row-sharded data
    centers, _, _ = distributed_kmeans_fit(
        x, KMeansParams(n_clusters=n_lists,
                        max_iter=params.kmeans_n_iters), mesh, axis)

    xs, ids_s = _shard_rows(x, mesh, axis)
    kind = _metric_kind(params.metric)

    # 2) per-shard labels + one host sync agreeing the bucket width
    labels_s, ml, _ = _label_and_agree_width(xs, ids_s, centers, mesh,
                                             axis, n_lists, kind)

    # 3) per-shard bucketize with global ids (static shapes everywhere)
    def build_bucketed():
        def bucket_local(x_loc, lbl_loc, ids_loc):
            # overflow label n_lists went to pads; fold them to list 0
            # with id -1 (dropped by the id mask at search)
            lbl = jnp.where(lbl_loc < n_lists, lbl_loc, 0)
            safe_ids = jnp.where(lbl_loc < n_lists, ids_loc, -1)
            data, idx, norms, _ = _bucketize_static(
                x_loc, lbl, safe_ids, n_lists, ml)
            return data[None], idx[None], norms[None]

        return jax.jit(shard_map_compat(
            bucket_local, mesh,
            in_specs=(P(axis, None), P(axis), P(axis)),
            out_specs=(P(axis, None, None, None), P(axis, None, None),
                       P(axis, None, None))))

    bucketed = _shmap_plan(("flat_dbucket", mesh, axis, n_lists, ml),
                           build_bucketed)
    pdata, pidx, pnorms = bucketed(xs, labels_s, ids_s)
    return DistributedIvfFlat(
        centers=centers, parts_data=pdata, parts_indices=pidx,
        parts_norms=pnorms, metric=params.metric, size=n, mesh=mesh,
        axis=axis)


def distributed_ivf_flat_search_parts(
    dindex: DistributedIvfFlat, queries, k: int, params=None,
    comms=None,
) -> Tuple[jax.Array, jax.Array]:
    """Search a row-sharded multi-part index: every shard probes the
    same global centers, scans its partial probed lists, and the
    per-shard top-k merge runs over the comm axis. The scanned set
    equals the single-host index's at matched n_probes."""
    from raft_tpu.neighbors.ivf_flat import (SearchParams, _coarse_scores,
                                             _metric_kind, _postprocess,
                                             _score_probe)
    params = params or SearchParams()
    mesh, axis = dindex.mesh, dindex.axis
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == dindex.dim, "distributed search: dim mismatch")
    if dindex.metric == DistanceType.CosineExpanded:
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-30)
    kind = _metric_kind(dindex.metric)
    n_probes = min(params.n_probes, dindex.n_lists)
    sqrt = dindex.metric in (DistanceType.L2SqrtExpanded,
                             DistanceType.L2SqrtUnexpanded)

    comms = comms if comms is not None else get_comms(mesh, axis)

    def build():
        def local(centers, pdata, pidx, pnorms, q_rep):
            qq = jnp.sum(q_rep * q_rep, axis=1)
            coarse = _coarse_scores(q_rep, centers, kind)
            _, probes = lax.top_k(-coarse, n_probes)

            def get_probe(p):
                return _score_probe(q_rep, qq, pdata[0], pnorms[0],
                                    pidx[0], probes[:, p], 1.0,
                                    kind=kind)

            d, i = _fine_scan(q_rep, get_probe, k, n_probes, axis)
            if sqrt:
                d = jnp.sqrt(jnp.maximum(d, 0.0))
            return _global_merge(comms, axis, d, i, k)

        return jax.jit(shard_map_compat(
            local, mesh,
            in_specs=(P(), P(axis, None, None, None),
                      P(axis, None, None), P(axis, None, None), P()),
            out_specs=(P(), P())))

    n_shards = mesh.shape[axis]
    with spans.span("raft.parallel.ivf.search", family="ivf_flat_parts",
                    nq=int(q.shape[0]), k=k, n_probes=n_probes,
                    axis=axis, n_shards=n_shards):
        shmapped = _shmap_plan(
            ("flat_parts", mesh, axis, k, n_probes, kind, sqrt, comms),
            build)
        q_rep = jax.device_put(q, NamedSharding(mesh, P()))
        centers_rep = jax.device_put(dindex.centers,
                                     NamedSharding(mesh, P()))
        t0 = time.perf_counter()
        d, i = shmapped(centers_rep, dindex.parts_data,
                        dindex.parts_indices, dindex.parts_norms, q_rep)
        _rank_spans(n_shards, t0, time.perf_counter() - t0)
    return _postprocess(d, dindex.metric), i


@dataclass
class DistributedIvfPq:
    """Row-sharded multi-part IVF-PQ index: compressed codes are the
    only per-row payload, sharded over ``mesh[axis]``; centers,
    rotation, and codebooks are replicated (they are O(n_lists·dim),
    not O(n))."""

    centers: jax.Array        # (n_lists, dim) replicated
    centers_rot: jax.Array    # (n_lists, rot_dim) replicated
    rotation_matrix: jax.Array
    pq_centers: jax.Array     # (pq_dim, n_codes, pq_len) replicated
    parts_codes: jax.Array    # (n_shards, n_lists, ml, pq_dim) u8 sharded
    parts_indices: jax.Array  # (n_shards, n_lists, ml) int32 global ids
    parts_norms: jax.Array    # (n_shards, n_lists, ml) exact code norms
    metric: "DistanceType"
    pq_bits: int
    size: int
    mesh: jax.sharding.Mesh
    axis: str

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def pq_dim(self) -> int:
        return self.pq_centers.shape[0]


def distributed_ivf_pq_build(
    x, params=None, mesh: jax.sharding.Mesh = None, axis: str = "data",
    seed: int = 0,
) -> DistributedIvfPq:
    """Build a row-sharded IVF-PQ index on the mesh (reference
    ivf_pq_build.cuh:908/605 distributed per SURVEY.md §3.3): MNMG
    kmeans coarse centers; rotation + per-subspace codebooks trained on
    a BOUNDED subsample (≤ 2^15 rows — O(1) in the dataset size, the
    reference's own trainset-subsampling strategy); then each shard
    encodes and buckets its own rows. Codes never leave their shard."""
    from raft_tpu.neighbors.ivf_flat import (_bucketize_static,
                                             _coarse_scores, _metric_kind)
    from raft_tpu.neighbors.ivf_pq import (
        IndexParams, _encode, _train_codebooks_per_subspace,
        make_rotation_matrix)
    from raft_tpu.parallel.kmeans import distributed_kmeans_fit
    params = params or IndexParams()
    expects(mesh is not None, "distributed build: mesh is required")
    from raft_tpu.neighbors.ivf_pq import CodebookGen
    expects(params.codebook_kind == CodebookGen.PER_SUBSPACE,
            "distributed_ivf_pq_build: PER_CLUSTER codebooks are not "
            "supported on the distributed path yet — build single-host "
            "or use PER_SUBSPACE")
    expects(params.metric in (DistanceType.L2Expanded,
                              DistanceType.L2SqrtExpanded,
                              DistanceType.L2Unexpanded,
                              DistanceType.L2SqrtUnexpanded,
                              DistanceType.InnerProduct),
            "distributed ivf_pq build: L2-family and InnerProduct "
            "metrics are supported (got %s)", params.metric)
    x = as_array(x).astype(jnp.float32)
    n, dim = x.shape
    n_lists = params.n_lists
    expects(n_lists <= n, "distributed build: n_lists > n_samples")
    expects(n >= (1 << params.pq_bits),
            "distributed ivf_pq build: need at least 2^pq_bits (%d) "
            "training rows", 1 << params.pq_bits)
    pq_dim = params.pq_dim if params.pq_dim > 0 else max(1, dim // 4)
    rot_dim = ((dim + pq_dim - 1) // pq_dim) * pq_dim
    pq_len = rot_dim // pq_dim
    n_codes = 1 << params.pq_bits
    kind = _metric_kind(params.metric)

    # 1) coarse centers: MNMG Lloyd over the row-sharded data
    centers, _, _ = distributed_kmeans_fit(
        x, KMeansParams(n_clusters=n_lists,
                        max_iter=params.kmeans_n_iters), mesh, axis)
    rot = make_rotation_matrix(dim, rot_dim,
                               params.force_random_rotation,
                               seed=seed + 1)
    centers_rot = jnp.matmul(centers, rot.T,
                             precision=matmul_precision())

    # 2) codebooks on a bounded subsample (replicated training)
    m = min(n, 1 << 15)
    # host-side draw (util.host_sample): a traced choice(replace=False)
    # is an n-wide sort compile (minutes at 10M+ rows)
    sel = sample_rows(n, m, seed + 3) if m < n else jnp.arange(n)
    xs_cb = x[sel]
    lbl_cb = jnp.argmin(_coarse_scores(xs_cb, centers, kind), axis=1)
    resid_cb = jnp.matmul(xs_cb - centers[lbl_cb], rot.T,
                          precision=matmul_precision())
    pq_centers = _train_codebooks_per_subspace(
        resid_cb, pq_dim, pq_len, n_codes, params.kmeans_n_iters,
        seed + 2, reseed_threshold=params.reseed_threshold)

    xs, ids_s = _shard_rows(x, mesh, axis)

    # 3) per-shard labels + one host sync agreeing the bucket width
    labels_s, ml, c_rep = _label_and_agree_width(xs, ids_s, centers,
                                                 mesh, axis, n_lists,
                                                 kind)

    # 4) per-shard encode + bucketize the CODES (u8) with global ids
    def build_encoded():
        def encode_local(x_loc, lbl_loc, ids_loc, c, r, books):
            from raft_tpu.neighbors.ivf_pq import _code_norms
            lbl = jnp.where(lbl_loc < n_lists, lbl_loc, 0)
            safe_ids = jnp.where(lbl_loc < n_lists, ids_loc, -1)
            resid_rot = jnp.matmul(x_loc - c[lbl], r.T,
                                   precision=matmul_precision())
            codes = _encode(resid_rot, books).astype(jnp.float32)
            data, idx, _, _ = _bucketize_static(codes, lbl, safe_ids,
                                                n_lists, ml)
            codes_b = data.astype(jnp.uint8)
            norms = _code_norms(codes_b, books, idx)
            return codes_b[None], idx[None], norms[None]

        return jax.jit(shard_map_compat(
            encode_local, mesh,
            in_specs=(P(axis, None), P(axis), P(axis), P(), P(), P()),
            out_specs=(P(axis, None, None, None), P(axis, None, None),
                       P(axis, None, None))))

    encoded = _shmap_plan(("pq_dencode", mesh, axis, n_lists, ml),
                          build_encoded)
    rep = lambda a: jax.device_put(a, NamedSharding(mesh, P()))
    pcodes, pidx, pnorms = encoded(xs, labels_s, ids_s, c_rep,
                                   rep(rot), rep(pq_centers))
    return DistributedIvfPq(
        centers=centers, centers_rot=centers_rot, rotation_matrix=rot,
        pq_centers=pq_centers, parts_codes=pcodes, parts_indices=pidx,
        parts_norms=pnorms, metric=params.metric,
        pq_bits=params.pq_bits, size=n, mesh=mesh, axis=axis)


def distributed_ivf_pq_search_parts(
    dindex: DistributedIvfPq, queries, k: int, params=None,
    comms=None,
) -> Tuple[jax.Array, jax.Array]:
    """Search a row-sharded multi-part IVF-PQ index: per shard, probed
    code blocks decode on the fly (transient, probe-major) and score
    against the rotated query residual; shards merge over the comm
    axis. Codes stay compressed at rest on every shard.

    Decode is one-hot × codebook on the MXU (the ``_pq_scan_kernel``
    trick, probe-major form) — per-lane LUT gathers lower to the TPU
    scalar core and measured ~100× slower in rounds 1-2. The operand
    dtype follows ``params.lut_dtype`` (bf16 one-pass / f32 highest /
    float8_e4m3fn-quantized books computed in bf16)."""
    from raft_tpu.neighbors.ivf_flat import (_coarse_scores, _metric_kind,
                                             _postprocess)
    from raft_tpu.neighbors.ivf_pq import SearchParams
    params = params or SearchParams()
    mesh, axis = dindex.mesh, dindex.axis
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == dindex.dim, "distributed search: dim mismatch")
    kind = _metric_kind(dindex.metric)
    n_probes = min(params.n_probes, dindex.n_lists)
    sqrt = dindex.metric in (DistanceType.L2SqrtExpanded,
                             DistanceType.L2SqrtUnexpanded)
    comms = comms if comms is not None else get_comms(mesh, axis)
    pq_dim = dindex.pq_dim
    n_codes = 1 << dindex.pq_bits
    lut_dt = jnp.dtype(params.lut_dtype)
    expects(lut_dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
                       jnp.dtype(jnp.float8_e4m3fn)),
            "distributed ivf_pq search: lut_dtype must be "
            "float32|bfloat16|float8_e4m3fn")
    f32_lut = lut_dt == jnp.dtype(jnp.float32)
    op_dt = jnp.float32 if f32_lut else jnp.bfloat16
    op_prec = matmul_precision() if f32_lut else None

    def _local(centers, centers_rot, rot, books, pcodes, pidx, pnorms,
               q_rep, comms):
        coarse = _coarse_scores(q_rep, centers, kind)
        _, probes = lax.top_k(-coarse, n_probes)
        q_rot = jnp.matmul(q_rep, rot.T, precision=matmul_precision())
        if lut_dt == jnp.dtype(jnp.float8_e4m3fn):
            # NOTE: pnorms stay exact-over-f32-books here (recomputing
            # over quantized books would decode every shard's codes);
            # the resulting distance error is within the fp8 tier's own
            # quantization class, matching the reference fp8-LUT contract
            books_op = books.astype(jnp.float8_e4m3fn).astype(op_dt)
        else:
            books_op = books.astype(op_dt)

        def get_probe(p):
            list_id = probes[:, p]
            codes_p = pcodes[0][list_id].astype(jnp.int32)  # (nq, ml, s)
            ids = pidx[0][list_id]
            # transient decode of the probed blocks only: per subspace,
            # one-hot (nq, ml, C) × book (C, pl) rides the MXU
            import jax.nn as jnn
            strips = [
                jnp.einsum("qlc,cp->qlp",
                           jnn.one_hot(codes_p[..., s], n_codes,
                                       dtype=op_dt),
                           books_op[s], precision=op_prec,
                           preferred_element_type=jnp.float32)
                for s in range(pq_dim)]
            dec = jnp.concatenate(strips, axis=-1)        # (nq, ml, rot)
            if kind == "ip":
                full = dec + centers_rot[list_id][:, None, :]
                ip = jnp.einsum("qd,qld->ql", q_rot, full,
                                preferred_element_type=jnp.float32)
                return jnp.where(ids >= 0, -ip, jnp.inf), ids
            resid = q_rot - centers_rot[list_id]
            ip = jnp.einsum("qd,qld->ql", resid, dec,
                            preferred_element_type=jnp.float32)
            rr = jnp.sum(resid * resid, axis=1)
            d = rr[:, None] + pnorms[0][list_id] - 2.0 * ip
            return jnp.where(ids >= 0, jnp.maximum(d, 0.0), jnp.inf), ids

        d, i = _fine_scan(q_rep, get_probe, k, n_probes, axis)
        if sqrt:
            d = jnp.sqrt(jnp.maximum(d, 0.0))
        return _global_merge(comms, axis, d, i, k)

    def build():
        local = functools.partial(_local, comms=comms)
        return jax.jit(shard_map_compat(
            local, mesh,
            in_specs=(P(), P(), P(), P(), P(axis, None, None, None),
                      P(axis, None, None), P(axis, None, None), P()),
            out_specs=(P(), P())))

    n_shards = mesh.shape[axis]
    with spans.span("raft.parallel.ivf.search", family="ivf_pq_parts",
                    nq=int(q.shape[0]), k=k, n_probes=n_probes,
                    axis=axis, n_shards=n_shards):
        shmapped = _shmap_plan(
            ("pq_parts", mesh, axis, k, n_probes, kind, sqrt, pq_dim,
             n_codes, lut_dt.name, comms), build)
        rep = lambda a: jax.device_put(a, NamedSharding(mesh, P()))
        t0 = time.perf_counter()
        d, i = shmapped(rep(dindex.centers), rep(dindex.centers_rot),
                        rep(dindex.rotation_matrix),
                        rep(dindex.pq_centers), dindex.parts_codes,
                        dindex.parts_indices, dindex.parts_norms,
                        rep(q))
        _rank_spans(n_shards, t0, time.perf_counter() - t0)
    return _postprocess(d, dindex.metric), i


@dataclass
class DistributedIvfBq:
    """Row-sharded multi-part IVF-BQ index (the 1-bit tier of
    ``neighbors/ivf_bq.py``, sharded like :class:`DistributedIvfFlat`).
    ``raw`` optionally holds the FULL dataset host-side for exact
    rescoring after the global estimator merge."""

    centers: jax.Array        # (n_lists, dim) replicated
    centers_rot: jax.Array    # (n_lists, dim) replicated
    rotation_matrix: jax.Array
    parts_bits: jax.Array     # (n_shards, n_lists, ml, w) uint32
    parts_norms2: jax.Array   # (n_shards, n_lists, ml)
    parts_scales: jax.Array   # (n_shards, n_lists, ml)
    parts_indices: jax.Array  # (n_shards, n_lists, ml) global ids
    metric: "DistanceType"
    size: int
    mesh: jax.sharding.Mesh
    axis: str
    raw: "object" = None      # host numpy (n, dim) f32 or None
    # lazy device copy of `raw` (ivf_bq.resolve_raw_device contract);
    # replicated over the mesh by the rescore gather — the "auto" HBM
    # budget is the guard at multi-chip scale
    raw_dev: "object" = None

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]


def distributed_ivf_bq_build(
    x, params=None, mesh: jax.sharding.Mesh = None, axis: str = "data",
) -> DistributedIvfBq:
    """Row-sharded IVF-BQ build: MNMG kmeans coarse phase, then each
    shard sign-encodes and bucketizes its own rows — there is no
    codebook, so beyond the coarse phase the build is one shard-local
    jit (the binary tier's build-speed story survives sharding)."""
    from raft_tpu.neighbors.ivf_bq import IndexParams, _pack_bits
    from raft_tpu.neighbors.ivf_flat import _bucketize_static
    from raft_tpu.neighbors.ivf_pq import make_rotation_matrix
    from raft_tpu.parallel.kmeans import distributed_kmeans_fit
    params = params or IndexParams()
    expects(mesh is not None, "distributed build: mesh is required")
    expects(params.metric in (DistanceType.L2Expanded,
                              DistanceType.L2SqrtExpanded),
            "distributed ivf_bq build: L2 metrics only (got %s)",
            params.metric)
    x = as_array(x).astype(jnp.float32)
    n, dim = x.shape
    n_lists = params.n_lists
    expects(n_lists <= n, "distributed build: n_lists > n_samples")

    centers, _, _ = distributed_kmeans_fit(
        x, KMeansParams(n_clusters=n_lists,
                        max_iter=params.kmeans_n_iters), mesh, axis)
    rot = make_rotation_matrix(dim, dim, force_random=True)

    xs, ids_s = _shard_rows(x, mesh, axis)
    labels_s, ml, c_rep = _label_and_agree_width(
        xs, ids_s, centers, mesh, axis, n_lists, "l2")
    rot_rep = jax.device_put(rot, NamedSharding(mesh, P()))
    w = -(-dim // 32)

    def build_enc():
        def encode_local(x_loc, lbl_loc, ids_loc, c, rt):
            lbl = jnp.where(lbl_loc < n_lists, lbl_loc, 0)
            safe_ids = jnp.where(lbl_loc < n_lists, ids_loc, -1)
            # full-precision rotation, like ivf_bq.build: default-
            # precision TPU matmul flips signs of near-zero rotated
            # components
            r = jnp.matmul(x_loc - c[lbl], rt.T,
                           precision=matmul_precision())
            # int32 payload (see ivf_bq.build): bit words must not ride
            # as f32 bitcasts — NaN-pattern canonicalization hazard
            payload = jnp.concatenate(
                [lax.bitcast_convert_type(_pack_bits(r), jnp.int32),
                 lax.bitcast_convert_type(
                     jnp.sum(r * r, axis=1)[:, None], jnp.int32),
                 lax.bitcast_convert_type(
                     jnp.mean(jnp.abs(r), axis=1)[:, None], jnp.int32)],
                axis=1)
            data, idx, _, _ = _bucketize_static(payload, lbl, safe_ids,
                                                n_lists, ml,
                                                compute_norms=False)
            return data[None], idx[None]

        return jax.jit(shard_map_compat(
            encode_local, mesh,
            in_specs=(P(axis, None), P(axis), P(axis), P(), P()),
            out_specs=(P(axis, None, None, None), P(axis, None, None))))

    enc = _shmap_plan(("bq_dencode", mesh, axis, n_lists, ml), build_enc)
    payload, pidx = enc(xs, labels_s, ids_s, c_rep, rot_rep)
    bits = lax.bitcast_convert_type(payload[..., :w], jnp.uint32)
    raw = None
    if params.keep_raw:
        import numpy as _np
        raw = _np.asarray(jax.device_get(x))
    return DistributedIvfBq(
        centers=centers, centers_rot=centers @ rot.T,
        rotation_matrix=rot, parts_bits=bits,
        parts_norms2=lax.bitcast_convert_type(payload[..., w],
                                              jnp.float32),
        parts_scales=lax.bitcast_convert_type(payload[..., w + 1],
                                              jnp.float32),
        parts_indices=pidx, metric=params.metric, size=n, mesh=mesh,
        axis=axis, raw=raw)


def distributed_ivf_bq_search_parts(
    dindex: DistributedIvfBq, queries, k: int, params=None,
    comms=None,
) -> Tuple[jax.Array, jax.Array]:
    """Search the row-sharded binary index: every shard scans its
    partial probed lists with the 1-bit estimator, the per-shard
    candidates merge over the comm axis, and (when raw vectors exist)
    the merged survivors are exactly re-ranked host-side."""
    from raft_tpu.neighbors.ivf_bq import SearchParams, _unpack_pm1
    from raft_tpu.neighbors.ivf_flat import _coarse_scores
    params = params or SearchParams()
    mesh, axis = dindex.mesh, dindex.axis
    q = as_array(queries).astype(jnp.float32)
    expects(q.shape[1] == dindex.dim, "distributed search: dim mismatch")
    n_probes = min(params.n_probes, dindex.n_lists)
    rescore = params.rescore_factor > 0 and dindex.raw is not None
    kk = max(params.rescore_factor, 1) * k
    dim = dindex.dim
    comms = comms if comms is not None else get_comms(mesh, axis)

    def build():
        def local(centers, centers_rot, rot, pbits, pn2, psc, pidx,
                  q_rep):
            coarse = _coarse_scores(q_rep, centers, "l2")
            _, probes = lax.top_k(-coarse, n_probes)
            q_rot = q_rep @ rot.T

            def get_probe(p):
                list_id = probes[:, p]                     # (nq,)
                pm1 = _unpack_pm1(pbits[0][list_id], dim)  # (nq, ml, d)
                ql = q_rot - centers_rot[list_id]          # (nq, d)
                ip = jnp.einsum("qld,qd->ql", pm1,
                                ql.astype(jnp.bfloat16),
                                preferred_element_type=jnp.float32)
                qq = jnp.sum(ql * ql, axis=1)[:, None]
                est = qq + pn2[0][list_id] - 2.0 * psc[0][list_id] * ip
                ids = pidx[0][list_id]
                return jnp.where(ids >= 0, est, jnp.inf), ids

            d, i = _fine_scan(q_rep, get_probe, kk, n_probes, axis)
            return _global_merge(comms, axis, d, i, kk)

        return jax.jit(shard_map_compat(
            local, mesh,
            in_specs=(P(), P(), P(), P(axis, None, None, None),
                      P(axis, None, None), P(axis, None, None),
                      P(axis, None, None), P()),
            out_specs=(P(), P())))

    n_shards = mesh.shape[axis]
    with spans.span("raft.parallel.ivf.search", family="ivf_bq_parts",
                    nq=int(q.shape[0]), k=k, n_probes=n_probes,
                    axis=axis, n_shards=n_shards, rescore=rescore):
        shmapped = _shmap_plan(
            ("bq_parts", mesh, axis, kk, n_probes, dim, comms), build)
        rep = lambda a: jax.device_put(a, NamedSharding(mesh, P()))
        t0 = time.perf_counter()
        d_est, ids = shmapped(rep(dindex.centers),
                              rep(dindex.centers_rot),
                              rep(dindex.rotation_matrix),
                              dindex.parts_bits, dindex.parts_norms2,
                              dindex.parts_scales, dindex.parts_indices,
                              rep(q))
        _rank_spans(n_shards, t0, time.perf_counter() - t0)
        from raft_tpu.neighbors.ivf_bq import (finish_search,
                                               resolve_raw_device)
        raw_dev = (resolve_raw_device(dindex, params.rescore_on_device)
                   if rescore else None)
        return finish_search(d_est, ids, dindex.raw, q, k,
                             metric=dindex.metric, rescore=rescore,
                             raw_dev=raw_dev)


# ---------------------------------------------------------------------------
# Sharded BUILD into the SERVING (list-sharded) layout (ISSUE 4 tentpole):
# the multi-part builds above keep rows where they land (each shard serves
# its own partial lists); these builds go one step further and land the
# index DIRECTLY in the list-sharded layout that `shard_ivf_*` serves from
# (`distributed_ivf_flat_search` / `distributed_ivf_pq_search`). Coarse
# centers train data-parallel (`balanced_kmeans_sharded`: per-shard
# sufficient statistics + psum each EM sweep — the raft::comms MNMG
# pattern); every shard labels and encodes its OWN rows; then ONE
# all_to_all moves each list's encoded payload to the shard that serves
# it, where peers' partial buckets are compacted into the final padded
# list. No O(n) array ever materializes on a single device, and the build
# output needs no reshard step before serving.
# ---------------------------------------------------------------------------

import numpy as np


def _train_coarse_sharded(x, params, mesh, axis: str, seed: int):
    """Coarse-center phase shared by the list-layout sharded builds:
    build()'s trainset subsample (host-side draw, same seed policy) fed
    to the data-parallel balanced trainer."""
    from raft_tpu.cluster.kmeans_balanced import balanced_kmeans_sharded
    n = x.shape[0]
    n_train = max(params.n_lists, int(n * params.kmeans_trainset_fraction))
    if n_train < n:
        from raft_tpu.util.host_sample import take_rows
        trainset = take_rows(x, sample_rows(n, n_train, seed))
    else:
        trainset = x
    with obs.timed("raft.build.sharded.train"):
        if params.n_lists > 16384:
            # beyond the flat-EM compile ceiling the single-device
            # trainer's two-level hierarchy applies; the sharded flat EM
            # would be one giant compile (kmeans_balanced rationale)
            from raft_tpu.cluster.kmeans_balanced import build_hierarchical
            return build_hierarchical(
                trainset, params.n_lists, params.kmeans_n_iters,
                seed=seed,
                kernel_precision=params.kmeans_kernel_precision)
        return balanced_kmeans_sharded(
            trainset, params.n_lists, params.kmeans_n_iters, seed=seed,
            kernel_precision=params.kmeans_kernel_precision,
            mesh=mesh, axis=axis)


def _label_and_widths(xs, ids_s, centers, mesh, axis, n_lists: int,
                      kind: str):
    """`_label_and_agree_width` extended for list-layout builds: ONE
    host sync agrees both bucket widths — ``ml_shard`` bounds any single
    shard's per-list count (the pre-exchange bucket), ``ml_global`` any
    list's TOTAL count (the serving bucket) — and returns the global
    per-list totals (the index's ``list_sizes``)."""
    from raft_tpu.neighbors.ivf_flat import _coarse_scores

    def build():
        def count_local(x_loc, ids_loc, c):
            lbl = jnp.argmin(_coarse_scores(x_loc, c, kind), axis=1)
            lbl = jnp.where(ids_loc >= 0, lbl, n_lists)
            cnt = jax.ops.segment_sum(jnp.ones_like(lbl, jnp.int32), lbl,
                                      num_segments=n_lists + 1)[:n_lists]
            return lbl.astype(jnp.int32), cnt

        return jax.jit(shard_map_compat(
            count_local, mesh, in_specs=(P(axis, None), P(axis), P()),
            out_specs=(P(axis), P(axis))))

    counted = _shmap_plan(("count_widths", mesh, axis, n_lists, kind),
                          build)
    c_rep = jax.device_put(centers, NamedSharding(mesh, P()))
    labels_s, counts = counted(xs, ids_s, c_rep)
    c = np.asarray(jax.device_get(counts)).reshape(mesh.shape[axis],
                                                   n_lists)
    ml_shard = max(8, -(-int(c.max()) // 8) * 8)
    totals = c.sum(axis=0)
    ml_global = max(8, -(-int(totals.max()) // 8) * 8)
    return labels_s, ml_shard, ml_global, totals.astype(np.int32), c_rep


def _exchange_lists(data, idx, n_shards: int, axis: str, ml_global: int):
    """Inside shard_map: exchange per-shard partial buckets
    ((n_lists, ml_shard, D) + ids) into the list-sharded serving layout.
    Each shard receives every peer's buckets for ITS OWN lists (one
    all_to_all of exactly the encoded payload — the only O(n/shards)
    wire move of the build), concatenates them along the slot axis and
    compacts valid slots to the front, yielding
    (nl_local, ml_global, D). ``ml_global`` ≥ every list's true total,
    so compaction never drops a real row."""
    n_lists, ml_shard = idx.shape
    nl_local = n_lists // n_shards
    D = data.shape[-1]
    d2 = lax.all_to_all(data.reshape(n_shards, nl_local, ml_shard, D),
                        axis, 0, 0, tiled=False)
    i2 = lax.all_to_all(idx.reshape(n_shards, nl_local, ml_shard),
                        axis, 0, 0, tiled=False)
    # (src_shard, nl_local, ml_shard, ...) → (nl_local, src·ml_shard, ...)
    d2 = d2.transpose(1, 0, 2, 3).reshape(nl_local, n_shards * ml_shard,
                                          D)
    i2 = i2.transpose(1, 0, 2).reshape(nl_local, n_shards * ml_shard)
    # compact: valid slots (id ≥ 0) first — jnp.argsort is stable, so
    # within a list rows keep source-shard-major order
    order = jnp.argsort((i2 < 0).astype(jnp.int32), axis=1)[:, :ml_global]
    i2 = jnp.take_along_axis(i2, order, axis=1)
    d2 = jnp.take_along_axis(d2, order[:, :, None], axis=1)
    return d2, i2


def sharded_ivf_flat_build(
    x, params=None, mesh: jax.sharding.Mesh = None, axis: str = "data",
    seed: int = 0,
):
    """Build an IVF-Flat index DIRECTLY INTO the list-sharded serving
    layout (the :func:`shard_ivf_flat` layout): data-parallel balanced
    k-means for the coarse centers, per-shard label + bucketize of each
    shard's own rows, then one all_to_all lands every list on the shard
    that serves it — no single-device bucketize bottleneck. Returns a
    standard ``ivf_flat.Index`` whose arrays are sharded over
    ``mesh[axis]``, served as-is by :func:`distributed_ivf_flat_search`
    (or gathered for single-chip serving)."""
    from raft_tpu.neighbors.ivf_flat import (Index, IndexParams,
                                             _bucketize_static,
                                             _metric_kind)
    params = params or IndexParams()
    expects(mesh is not None, "sharded build: mesh is required")
    n_shards = mesh.shape[axis]
    n_lists = params.n_lists
    expects(n_lists % n_shards == 0,
            "sharded_ivf_flat_build: n_lists=%d not divisible by %d "
            "shards", n_lists, n_shards)
    expects(params.metric in (DistanceType.L2Expanded,
                              DistanceType.L2SqrtExpanded,
                              DistanceType.L2Unexpanded,
                              DistanceType.L2SqrtUnexpanded,
                              DistanceType.InnerProduct,
                              DistanceType.CosineExpanded),
            "sharded ivf_flat build: unsupported metric %s",
            params.metric)
    expects(params.storage_dtype == "float32",
            "sharded ivf_flat build: narrow list storage (%s) is not "
            "implemented for sharded lists yet; use float32",
            params.storage_dtype)
    x = as_array(x).astype(jnp.float32)
    if params.metric == DistanceType.CosineExpanded:
        x = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True),
                            1e-30)
    n, dim = x.shape
    expects(n_lists <= n, "sharded build: n_lists > n_samples")
    kind = _metric_kind(params.metric)

    with spans.span("raft.build.sharded", family="ivf_flat", rows=n,
                    n_lists=n_lists, n_shards=n_shards):
        obs.counter("raft.build.sharded.total", family="ivf_flat").inc()
        obs.counter("raft.build.sharded.rows", family="ivf_flat").inc(n)
        centers = _train_coarse_sharded(x, params, mesh, axis, seed)
        xs, ids_s = _shard_rows(x, mesh, axis)
        labels_s, ml_shard, ml_global, totals, _ = _label_and_widths(
            xs, ids_s, centers, mesh, axis, n_lists, kind)

        def build():
            def local(x_loc, lbl_loc, ids_loc):
                lbl = jnp.where(lbl_loc < n_lists, lbl_loc, 0)
                safe_ids = jnp.where(lbl_loc < n_lists, ids_loc, -1)
                data, idx, _, _ = _bucketize_static(
                    x_loc, lbl, safe_ids, n_lists, ml_shard,
                    compute_norms=False)
                d2, i2 = _exchange_lists(data, idx, n_shards, axis,
                                         ml_global)
                norms = jnp.sum(d2 * d2, axis=2)
                return d2, i2, jnp.where(i2 >= 0, norms, 0.0)

            return jax.jit(shard_map_compat(
                local, mesh,
                in_specs=(P(axis, None), P(axis), P(axis)),
                out_specs=(P(axis, None, None), P(axis, None),
                           P(axis, None))))

        with obs.timed("raft.build.sharded.encode", family="ivf_flat"):
            fn = _shmap_plan(("flat_lbuild", mesh, axis, n_lists,
                              ml_shard, ml_global, dim), build)
            data, idx, norms = fn(xs, labels_s, ids_s)
    return Index(centers=_shard0(centers, mesh, axis), lists_data=data,
                 lists_indices=idx, lists_norms=norms,
                 list_sizes=_shard0(jnp.asarray(totals), mesh, axis),
                 metric=params.metric, size=n, scale=1.0)


def sharded_ivf_pq_build(
    x, params=None, mesh: jax.sharding.Mesh = None, axis: str = "data",
    seed: int = 0,
):
    """Build an IVF-PQ index directly into the list-sharded serving
    layout (the :func:`shard_ivf_pq` layout, bf16 reconstruction cache
    included): data-parallel coarse centers, replicated rotation +
    codebooks trained on a bounded subsample, per-shard
    label→residual→encode, one all_to_all of the uint8 CODES (the
    compressed payload is the only per-row wire traffic), shard-local
    decode of the reconstruction cache. Served as-is by
    :func:`distributed_ivf_pq_search`."""
    from raft_tpu.neighbors.ivf_flat import (_bucketize_static,
                                             _coarse_scores,
                                             _metric_kind)
    from raft_tpu.neighbors.ivf_pq import (
        CodebookGen, Index, IndexParams, _code_norms, _decode_lists,
        _encode, _train_codebooks_per_subspace, make_rotation_matrix)
    params = params or IndexParams()
    expects(mesh is not None, "sharded build: mesh is required")
    expects(params.codebook_kind == CodebookGen.PER_SUBSPACE,
            "sharded_ivf_pq_build: PER_CLUSTER codebooks are not "
            "supported on the sharded path — build single-host or use "
            "PER_SUBSPACE")
    expects(params.metric in (DistanceType.L2Expanded,
                              DistanceType.L2SqrtExpanded,
                              DistanceType.L2Unexpanded,
                              DistanceType.L2SqrtUnexpanded,
                              DistanceType.InnerProduct),
            "sharded ivf_pq build: L2-family and InnerProduct metrics "
            "are supported (got %s)", params.metric)
    n_shards = mesh.shape[axis]
    n_lists = params.n_lists
    expects(n_lists % n_shards == 0,
            "sharded_ivf_pq_build: n_lists=%d not divisible by %d "
            "shards", n_lists, n_shards)
    x = as_array(x).astype(jnp.float32)
    n, dim = x.shape
    expects(n_lists <= n, "sharded build: n_lists > n_samples")
    expects(n >= (1 << params.pq_bits),
            "sharded ivf_pq build: need at least 2^pq_bits (%d) "
            "training rows", 1 << params.pq_bits)
    pq_dim = params.pq_dim if params.pq_dim > 0 else max(1, dim // 4)
    rot_dim = ((dim + pq_dim - 1) // pq_dim) * pq_dim
    pq_len = rot_dim // pq_dim
    n_codes = 1 << params.pq_bits
    kind = _metric_kind(params.metric)

    with spans.span("raft.build.sharded", family="ivf_pq", rows=n,
                    n_lists=n_lists, n_shards=n_shards):
        obs.counter("raft.build.sharded.total", family="ivf_pq").inc()
        obs.counter("raft.build.sharded.rows", family="ivf_pq").inc(n)
        centers = _train_coarse_sharded(x, params, mesh, axis, seed)
        rot = make_rotation_matrix(dim, rot_dim,
                                   params.force_random_rotation,
                                   seed=seed + 1)
        centers_rot = jnp.matmul(centers, rot.T,
                                 precision=matmul_precision())

        # codebooks on a bounded subsample (replicated training, same
        # O(1)-in-n strategy as the multi-part build)
        with obs.timed("raft.build.sharded.codebooks"):
            m = min(n, 1 << 15)
            sel = sample_rows(n, m, seed + 3) if m < n else jnp.arange(n)
            xs_cb = x[sel]
            lbl_cb = jnp.argmin(_coarse_scores(xs_cb, centers, kind),
                                axis=1)
            resid_cb = jnp.matmul(xs_cb - centers[lbl_cb], rot.T,
                                  precision=matmul_precision())
            pq_centers = _train_codebooks_per_subspace(
                resid_cb, pq_dim, pq_len, n_codes,
                params.kmeans_n_iters, seed + 2,
                kernel_precision=params.kmeans_kernel_precision,
                reseed_threshold=params.reseed_threshold)

        xs, ids_s = _shard_rows(x, mesh, axis)
        labels_s, ml_shard, ml_global, totals, c_rep = _label_and_widths(
            xs, ids_s, centers, mesh, axis, n_lists, kind)

        def build():
            def local(x_loc, lbl_loc, ids_loc, c, r, books):
                lbl = jnp.where(lbl_loc < n_lists, lbl_loc, 0)
                safe_ids = jnp.where(lbl_loc < n_lists, ids_loc, -1)
                resid_rot = jnp.matmul(x_loc - c[lbl], r.T,
                                       precision=matmul_precision())
                codes = _encode(resid_rot, books)        # (rows, s) u8
                data, idx, _, _ = _bucketize_static(
                    codes, lbl, safe_ids, n_lists, ml_shard,
                    compute_norms=False)
                d2, i2 = _exchange_lists(data, idx, n_shards, axis,
                                         ml_global)
                norms = _code_norms(d2, books, i2)
                dec = _decode_lists(d2, books, i2)
                return d2, i2, norms, dec

            return jax.jit(shard_map_compat(
                local, mesh,
                in_specs=(P(axis, None), P(axis), P(axis), P(), P(),
                          P()),
                out_specs=(P(axis, None, None), P(axis, None),
                           P(axis, None), P(axis, None, None))))

        with obs.timed("raft.build.sharded.encode", family="ivf_pq"):
            fn = _shmap_plan(("pq_lbuild", mesh, axis, n_lists, ml_shard,
                              ml_global, pq_dim, n_codes, kind), build)
            rep = lambda a: jax.device_put(a, NamedSharding(mesh, P()))
            codes_b, idx, norms, decoded = fn(xs, labels_s, ids_s, c_rep,
                                              rep(rot), rep(pq_centers))
    return Index(centers=_shard0(centers, mesh, axis),
                 centers_rot=_shard0(centers_rot, mesh, axis),
                 rotation_matrix=jax.device_put(
                     rot, NamedSharding(mesh, P())),
                 pq_centers=jax.device_put(
                     pq_centers, NamedSharding(mesh, P())),
                 codes=codes_b, lists_indices=idx,
                 list_sizes=_shard0(jnp.asarray(totals), mesh, axis),
                 metric=params.metric, pq_bits=params.pq_bits, size=n,
                 codebook_kind=CodebookGen.PER_SUBSPACE,
                 code_norms=norms, decoded=decoded, decoded_norms=norms,
                 raw=(np.asarray(jax.device_get(x))
                      if params.keep_raw else None))


def sharded_ivf_bq_build(
    x, params=None, mesh: jax.sharding.Mesh = None, axis: str = "data",
    seed: int = 0,
):
    """Build an IVF-BQ index into the list-sharded layout: data-parallel
    coarse phase, per-shard sign-encode (no codebook — one subtract +
    sign past the coarse phase), one all_to_all of the int32 bit
    payload. Returns a standard ``ivf_bq.Index``; at the 1-bit tier the
    whole payload usually fits one chip, so callers commonly gather the
    arrays for single-chip serving (the 100M-in-2.8GB story) — the
    sharded build is the BUILD-time scaling, the multi-part search is
    the serving-time one."""
    from raft_tpu.neighbors.ivf_bq import Index, IndexParams, _pack_bits
    from raft_tpu.neighbors.ivf_flat import _bucketize_static
    from raft_tpu.neighbors.ivf_pq import make_rotation_matrix
    params = params or IndexParams()
    expects(mesh is not None, "sharded build: mesh is required")
    expects(params.metric in (DistanceType.L2Expanded,
                              DistanceType.L2SqrtExpanded),
            "sharded ivf_bq build: L2 metrics only (got %s)",
            params.metric)
    n_shards = mesh.shape[axis]
    n_lists = params.n_lists
    expects(n_lists % n_shards == 0,
            "sharded_ivf_bq_build: n_lists=%d not divisible by %d "
            "shards", n_lists, n_shards)
    x = as_array(x).astype(jnp.float32)
    n, dim = x.shape
    expects(n_lists <= n, "sharded build: n_lists > n_samples")
    w = -(-dim // 32)

    with spans.span("raft.build.sharded", family="ivf_bq", rows=n,
                    n_lists=n_lists, n_shards=n_shards):
        obs.counter("raft.build.sharded.total", family="ivf_bq").inc()
        obs.counter("raft.build.sharded.rows", family="ivf_bq").inc(n)
        centers = _train_coarse_sharded(x, params, mesh, axis, seed)
        rot = make_rotation_matrix(dim, dim, force_random=True)
        xs, ids_s = _shard_rows(x, mesh, axis)
        labels_s, ml_shard, ml_global, totals, c_rep = _label_and_widths(
            xs, ids_s, centers, mesh, axis, n_lists, "l2")
        rot_rep = jax.device_put(rot, NamedSharding(mesh, P()))

        def build():
            def local(x_loc, lbl_loc, ids_loc, c, rt):
                lbl = jnp.where(lbl_loc < n_lists, lbl_loc, 0)
                safe_ids = jnp.where(lbl_loc < n_lists, ids_loc, -1)
                # full-precision rotation + int32 bit payload: the
                # ivf_bq.build contracts (sign stability, no f32
                # bitcast canonicalization)
                r = jnp.matmul(x_loc - c[lbl], rt.T,
                               precision=matmul_precision())
                payload = jnp.concatenate(
                    [lax.bitcast_convert_type(_pack_bits(r), jnp.int32),
                     lax.bitcast_convert_type(
                         jnp.sum(r * r, axis=1)[:, None], jnp.int32),
                     lax.bitcast_convert_type(
                         jnp.mean(jnp.abs(r), axis=1)[:, None],
                         jnp.int32)],
                    axis=1)
                data, idx, _, _ = _bucketize_static(
                    payload, lbl, safe_ids, n_lists, ml_shard,
                    compute_norms=False)
                return _exchange_lists(data, idx, n_shards, axis,
                                       ml_global)

            return jax.jit(shard_map_compat(
                local, mesh,
                in_specs=(P(axis, None), P(axis), P(axis), P(), P()),
                out_specs=(P(axis, None, None), P(axis, None))))

        with obs.timed("raft.build.sharded.encode", family="ivf_bq"):
            fn = _shmap_plan(("bq_lbuild", mesh, axis, n_lists, ml_shard,
                              ml_global, dim), build)
            payload, idx = fn(xs, labels_s, ids_s, c_rep, rot_rep)
        bits = lax.bitcast_convert_type(payload[..., :w], jnp.uint32)
        norms2 = lax.bitcast_convert_type(payload[..., w], jnp.float32)
        scales = lax.bitcast_convert_type(payload[..., w + 1],
                                          jnp.float32)
        raw = None
        if params.keep_raw:
            raw = np.asarray(jax.device_get(x))
    return Index(centers=_shard0(centers, mesh, axis),
                 centers_rot=_shard0(
                     jnp.matmul(centers, rot.T,
                                precision=matmul_precision()),
                     mesh, axis),
                 rotation_matrix=rot, bits=bits, norms2=norms2,
                 scales=scales, lists_indices=idx,
                 list_sizes=_shard0(jnp.asarray(totals), mesh, axis),
                 metric=params.metric, size=n, raw=raw)
