"""Distributed brute-force k-NN.

Design (SURVEY.md §5 "scale the big dimension"): shard the database rows
across the mesh's data axis; queries are replicated. Each shard computes
its local top-k with the scanned fused kernel, then shards merge — either
one all_gather + select (small k·n_shards) or a ring of
``collective_permute`` merge steps (constant memory, overlaps with ICI),
the sequence-parallel-style pattern this domain calls for. Index ids are
translated by shard offsets exactly like the reference's multi-part
``knn_merge_parts`` path (brute_force.cuh:48).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.core.mdarray import as_array
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.neighbors.brute_force import _knn_scan, _db_tile
from raft_tpu.comms.comms import build_comms
from raft_tpu.parallel.ivf import _shmap_plan


def _merge(d_a, i_a, d_b, i_b, k: int):
    cat_d = jnp.concatenate([d_a, d_b], axis=1)
    cat_i = jnp.concatenate([i_a, i_b], axis=1)
    nd, sel = lax.top_k(-cat_d, k)
    return -nd, jnp.take_along_axis(cat_i, sel, axis=1)


def distributed_knn(
    db,
    queries,
    k: int,
    mesh: jax.sharding.Mesh,
    axis: str = "data",
    metric: DistanceType = DistanceType.L2SqrtExpanded,
    merge: str = "ring",
    res=None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN with the database sharded over ``mesh[axis]``.

    ``merge``: "ring" = n-1 collective_permute merge steps (constant
    memory per step); "allgather" = one gather + final select.
    """
    db = as_array(db).astype(jnp.float32)
    q = as_array(queries).astype(jnp.float32)
    n_shards = mesh.shape[axis]
    n = db.shape[0]
    pad = (-n) % n_shards
    if pad:
        db = jnp.pad(db, ((0, pad), (0, 0)))
    rows_per = (n + pad) // n_shards
    tile = _db_tile(q.shape[0], rows_per)

    def build():
        from raft_tpu.parallel.mesh import (pcast_varying_compat,
                                            shard_map_compat)
        comms = build_comms(mesh, axis)

        def local(db_shard, q_rep):
            # local top-k over this shard's rows — inlined scan (the shared
            # _knn_scan creates an unvarying carry, which shard_map's
            # varying-manual-axes tracking rejects; here the init is cast
            # varying along the comm axis)
            nq = q_rep.shape[0]
            pad_t = (-rows_per) % tile
            dbp = (jnp.pad(db_shard, ((0, pad_t), (0, 0))) if pad_t else db_shard)
            n_tiles = (rows_per + pad_t) // tile
            db_tiles = dbp.reshape(n_tiles, tile, -1)
            offs = jnp.arange(n_tiles, dtype=jnp.int32) * tile

            from raft_tpu.distance.pairwise import _pairwise

            def step(carry, inp):
                best_d, best_i = carry
                dtile, off = inp
                dd = _pairwise(q_rep, dtile, metric, 2.0)
                col = jnp.arange(tile, dtype=jnp.int32)[None, :] + off
                dd = jnp.where(col < rows_per, dd, jnp.inf)
                td, tsel = lax.top_k(-dd, min(k, tile))
                ti = jnp.take_along_axis(jnp.broadcast_to(col, (nq, tile)),
                                         tsel, axis=1)
                return _merge(best_d, best_i, -td, ti, k), None

            init = (pcast_varying_compat(
                        jnp.full((nq, k), jnp.inf, jnp.float32), (axis,)),
                    pcast_varying_compat(
                        jnp.full((nq, k), -1, jnp.int32), (axis,)))
            (d, i), _ = lax.scan(step, init, (db_tiles, offs))
            # translate to global ids; mask pad rows (global id >= n)
            offset = lax.axis_index(axis) * rows_per
            gi = i + offset.astype(jnp.int32)
            d = jnp.where(gi < n, d, jnp.inf)
            gi = jnp.where(gi < n, gi, -1)

            if merge == "allgather":
                gd = comms.allgather(d)      # (n_shards, nq, k)
                gidx = comms.allgather(gi)
                cat_d = jnp.moveaxis(gd, 0, 1).reshape(q_rep.shape[0], -1)
                cat_i = jnp.moveaxis(gidx, 0, 1).reshape(q_rep.shape[0], -1)
                nd, sel = lax.top_k(-cat_d, k)
                fd, fi = -nd, jnp.take_along_axis(cat_i, sel, axis=1)
                # identical on every rank; a tiny pmax makes that provable to
                # shard_map's replication checker (no varying->invariant cast
                # exists)
                return lax.pmax(fd, axis), lax.pmax(fi, axis)

            # ring merge: circulate each rank's ORIGINAL candidate set around
            # the ring (merging the traveling set would duplicate candidates);
            # after n-1 hops every rank has merged every shard's set exactly
            # once
            def ring_step(carry, _):
                best_d, best_i, trav_d, trav_i = carry
                trav_d = comms.ring_permute(trav_d, 1)
                trav_i = comms.ring_permute(trav_i, 1)
                best_d, best_i = _merge(best_d, best_i, trav_d, trav_i, k)
                return (best_d, best_i, trav_d, trav_i), None

            (fd, fi, _, _), _ = lax.scan(ring_step, (d, gi, d, gi), None,
                                         length=n_shards - 1)
            # identical on every rank after n-1 hops; pmax proves replication
            return lax.pmax(fd, axis), lax.pmax(fi, axis)

        return jax.jit(shard_map_compat(
            local, mesh,
            in_specs=(P(axis, None), P()),
            out_specs=(P(), P())))

    shmapped = _shmap_plan(
        ("bf_knn", mesh, axis, k, int(metric), merge, rows_per, tile, n),
        build)
    db_sharded = jax.device_put(
        db, NamedSharding(mesh, P(axis, None)))
    q_rep = jax.device_put(q, NamedSharding(mesh, P()))
    return shmapped(db_sharded, q_rep)
