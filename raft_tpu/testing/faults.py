"""Deterministic fault injection for the serving/mutation stack.

The reference RAFT *acts* on failure — ``waitall``-with-timeout and
abort semantics in ``std_comms.hpp`` — but exercising those paths needs
failures on demand. This module is the chaos harness behind
``tests/test_faults.py``, ``tools/loadgen.py --chaos`` and
``bench_suite.bench_chaos``: production code carries named **injection
points** (:func:`inject` calls with labels) and a test/loadgen scope
activates **fault rules** against them — a stalled shard collective, a
compactor that dies on every fold, a failed device transfer, extra
latency in plan execution.

Design constraints (the tier-1 contract):

* **fault-free by default** — with no active rule, :func:`inject` is a
  single module-flag check; nothing is allocated, matched or locked.
  Rules only exist inside a scoped context manager, so no test can leak
  a fault into the next one (``reset()`` is the belt-and-braces
  teardown).
* **deterministic** — rules fire on exact label matches;
  probabilistic rules draw from a rule-local ``random.Random(seed)``,
  never the global RNG, so a chaos run replays bit-identically.
* **observable** — every fired rule counts under
  ``raft.testing.fault.injected{site}`` so a chaos report can show
  exactly which faults the run actually exercised.

Injection sites wired in this repo (labels in parentheses):

=========================  ==================================================
``serve.execute``          batcher dispatch, inside the watchdog scope
                           (``shape``) — delay here exercises the
                           ``dispatch_timeout_ms`` watchdog
``serve.dist.dispatch``    one mesh-wide dispatch (``ranks`` = the ranks the
                           plan needs alive, ``family``) — a rule matching a
                           rank in ``ranks`` simulates that shard stalling
``mutate.compact``         :meth:`MutableIndex.compact` entry (``epoch``)
``mutate.transfer``        the delta/tombstone host→device refresh
                           (``epoch``)
``fed.scrape``             one federator scrape of one instance
                           (``instance``) — delay/error here simulates a
                           dead or hung replica endpoint
``obs.blackbox.append``    between a black-box record's header and
                           payload writes (``kind``, ``box``) — an error
                           here manufactures the torn tail a kill -9
                           mid-write leaves, proving recovery truncates
=========================  ==================================================

Convenience scopes: :func:`stall_shard`, :func:`kill_compactor`,
:func:`fail_transfer`, :func:`delay_execute`. ``stall_shard``
additionally plays the :class:`~raft_tpu.comms.health.HealthMonitor`'s
role in-process: on the first hit it raises the per-rank
``raft.comms.health.suspect_rank`` gauge (and clears it on exit), so
the distributed serving tier's failover sees the same signal it would
get from stale heartbeats on real hardware (where detection latency is
tested separately in ``tests/test_comms.py``).
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

__all__ = [
    "FaultError",
    "FaultRule",
    "active",
    "delay_execute",
    "fail_transfer",
    "inject",
    "inject_fault",
    "kill_compactor",
    "reset",
    "stall_shard",
]


class FaultError(RuntimeError):
    """The default exception an ``action="error"`` rule raises — typed
    so tests can distinguish an injected failure from a real bug."""


_MISSING = object()

_lock = threading.Lock()
_rules: List["FaultRule"] = []
# fast path: flipped only while at least one rule is registered, read
# without the lock (a stale read costs one extra lock acquisition or
# skips a fault that was concurrently removed — both benign)
_enabled = False


class FaultRule:
    """One active fault: where it applies (``site`` + label ``match``),
    what it does (``action``: ``"delay"`` sleeps ``seconds``,
    ``"error"`` raises), and how often (``probability`` drawn from a
    rule-local seeded RNG; ``max_hits`` 0 = unlimited)."""

    def __init__(self, site: str, action: str = "error",
                 seconds: float = 0.0,
                 error: Optional[Callable[[], BaseException]] = None,
                 match: Optional[Dict[str, object]] = None,
                 probability: float = 1.0, max_hits: int = 0,
                 seed: int = 0,
                 on_hit: Optional[Callable[[dict], None]] = None):
        if action not in ("delay", "error"):
            raise ValueError(f"FaultRule: unknown action {action!r}")
        self.site = site
        self.action = action
        self.seconds = float(seconds)
        self.error = error
        self.match = dict(match or {})
        self.probability = float(probability)
        self.max_hits = int(max_hits)
        self.on_hit = on_hit
        self.hits = 0
        self._rng = random.Random(seed)

    def matches(self, labels: dict) -> bool:
        """Exact label match; a collection-valued label matches when
        the rule value is contained in it (so ``match={"ranks": 3}``
        trips any dispatch whose participating ``ranks`` include 3)."""
        for key, want in self.match.items():
            have = labels.get(key, _MISSING)
            if isinstance(have, (tuple, list, set, frozenset)):
                if want not in have:
                    return False
            elif have != want:
                return False
        return True

    def _make_error(self) -> BaseException:
        if self.error is None:
            return FaultError(f"injected fault at {self.site!r} "
                              f"(hit {self.hits})")
        err = self.error
        return err() if callable(err) else err


def active() -> bool:
    """True while any fault rule is registered (tier-1 must see
    False)."""
    return _enabled


def inject(site: str, **labels) -> None:
    """A named injection point. No-op (one flag read) unless a harness
    scope is active; otherwise fires every matching rule in
    registration order — delays first sleep, error rules raise."""
    if not _enabled:
        return
    fire: List[FaultRule] = []
    with _lock:
        for r in _rules:
            if r.site != site or not r.matches(labels):
                continue
            if r.max_hits > 0 and r.hits >= r.max_hits:
                continue
            if r.probability < 1.0 and r._rng.random() >= r.probability:
                continue
            r.hits += 1
            fire.append(r)
    if not fire:
        return
    from raft_tpu import obs
    for r in fire:
        obs.counter("raft.testing.fault.injected", site=site,
                    action=r.action).inc()
        if r.on_hit is not None:
            r.on_hit(labels)
        if r.action == "delay":
            time.sleep(r.seconds)
        else:
            raise r._make_error()


def reset() -> None:
    """Deactivate every fault (test teardown belt-and-braces)."""
    global _enabled
    with _lock:
        _rules.clear()
        _enabled = False


@contextmanager
def inject_fault(site: str, action: str = "error", seconds: float = 0.0,
                 error: Optional[Callable[[], BaseException]] = None,
                 match: Optional[Dict[str, object]] = None,
                 probability: float = 1.0, max_hits: int = 0,
                 seed: int = 0,
                 on_hit: Optional[Callable[[dict], None]] = None):
    """Scoped activation of one :class:`FaultRule`; yields the rule so
    the caller can read ``rule.hits``. The rule dies with the scope —
    faults cannot outlive the test/chaos window that asked for them."""
    global _enabled
    rule = FaultRule(site, action=action, seconds=seconds, error=error,
                     match=match, probability=probability,
                     max_hits=max_hits, seed=seed, on_hit=on_hit)
    with _lock:
        _rules.append(rule)
        _enabled = True
    try:
        yield rule
    finally:
        with _lock:
            if rule in _rules:
                _rules.remove(rule)
            _enabled = bool(_rules)


@contextmanager
def stall_shard(rank: int, seconds: float = 30.0,
                session: str = "default",
                site: str = "serve.dist.dispatch"):
    """Simulate shard ``rank`` stalling: every dispatch whose
    participating ``ranks`` include it hangs for ``seconds`` (long
    enough to trip ``dispatch_timeout_ms``). On the first hit the
    per-rank suspect gauge is raised — the harness standing in for the
    HealthMonitor's stale-heartbeat detection — and cleared on exit so
    the failover recovery probe sees the shard healthy again."""
    from raft_tpu import obs
    rank = int(rank)
    gauge = obs.gauge("raft.comms.health.suspect_rank",
                      session=session, rank=rank)
    seen = threading.Event()

    def on_hit(_labels):
        if not seen.is_set():
            seen.set()
            gauge.set(1)

    with inject_fault(site, action="delay", seconds=seconds,
                      match={"ranks": rank}, on_hit=on_hit) as rule:
        try:
            yield rule
        finally:
            gauge.set(0)


@contextmanager
def kill_compactor(times: int = 0):
    """Every :meth:`MutableIndex.compact` attempt raises (``times`` > 0
    bounds how many; 0 = for the whole scope) — the crash-looping
    compactor the :class:`~raft_tpu.mutate.Compactor` guard must
    survive."""
    with inject_fault("mutate.compact", action="error",
                      max_hits=times) as rule:
        yield rule


@contextmanager
def fail_transfer(times: int = 1):
    """The next ``times`` delta/tombstone device refreshes raise —
    a failed host→device transfer mid-mutation."""
    with inject_fault("mutate.transfer", action="error",
                      max_hits=times) as rule:
        yield rule


@contextmanager
def delay_execute(ms: float, max_hits: int = 0):
    """Add ``ms`` of latency to every batcher dispatch (inside the
    watchdog scope, so big enough values exercise the timeout path)."""
    with inject_fault("serve.execute", action="delay", seconds=ms / 1e3,
                      max_hits=max_hits) as rule:
        yield rule
