"""raft_tpu.testing — chaos/fault-injection support (ISSUE 10).

Deliberately tiny and dependency-light: :mod:`raft_tpu.testing.faults`
is imported by serving/mutation hot paths for its injection points, so
nothing here may pull in jax or any device runtime.
"""

from raft_tpu.testing.faults import (FaultError, FaultRule, inject,
                                     inject_fault, reset)

__all__ = [
    "FaultError",
    "FaultRule",
    "inject",
    "inject_fault",
    "reset",
]
