"""Mesh-wide distributed serving tier (ISSUE 8).

The raft::comms / raft-dask L7 layer rebuilt TPU-native as a *serving*
surface: one ``DistributedSearchServer.submit()`` front door over a
list-sharded IVF index spanning the whole mesh. It reuses the PR 5
micro-batcher wholesale — bounded-queue admission, request coalescing,
deadlines, the n_probes degradation ladder — and swaps the plan layer
underneath: every (shape, rung) of the ladder is ONE cached shard_map
program (``parallel/ivf._shmap_plan``) that fans the coalesced batch
out across every shard's lists and merges the per-shard top-k with the
quantized cross-shard codec (``serve/merge.py``,
``RAFT_TPU_DIST_MERGE=f32|int8``, int8 default here — exact re-rank or
the 0.005 recall budget absorbs the rounding).

Steady-state contract (same as the single-device server, asserted from
counters in ``tests/test_serve_dist.py`` and reported by
``bench_serve_sharded`` as ``steady_state_compiles``): after the
ladder prewarm, serving traffic performs ZERO compiles and zero
retraces anywhere on the mesh — ``raft.parallel.plan.misses``,
``raft.plan.cache.misses`` and ``raft.plan.build.total`` all stay
flat; every dispatch is a ``raft.parallel.plan.hits`` cache hit.

Observability: ``raft.serve.dist.*`` counters/gauges (batches, wire
bytes pre/post compression per rung, per-shard rows, shard count,
merge ratio), rank-tagged ``raft.parallel.ivf.shard`` child spans
under the batcher's ``raft.serve.batch`` root, and a ``/healthz``
``dist`` section folding the per-shard comms-health suspects
(docs/serving.md "Distributed serving").
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core.error import expects
from raft_tpu.core.logger import get_logger
from raft_tpu.obs import profiler, spans
from raft_tpu.serve.batcher import SearchServer
from raft_tpu.serve.ladder import PlanLadder
from raft_tpu.serve.merge import merge_mode, merge_wire_bytes
from raft_tpu.serve.types import ServeConfig, ShardFailedError
from raft_tpu.testing import faults

__all__ = [
    "DistSearchPlan",
    "DistributedSearchServer",
    "FailoverLadder",
    "build_dist_ladder",
    "build_failover_ladder",
]

# Compile-surface rung declarations (graftlint GL012–GL014): the
# distributed tier's key dimensions beyond the base ladder's — the
# fleet/dist audit of ISSUE 15.  `level` indexes the same rungs grid
# the ladder declares; k_fetch is the tail over-fetch width.
COMPILE_SURFACE_RUNGS = {
    "level": ("rungs", None,
              "degradation-rung index carried by DistSearchPlan "
              "(level 0 = full quality)"),
    "k_fetch": ("k_fetch", None,
                "mesh-wide over-fetch width (k + tombstone_slack) — "
                "fixed per server"),
    "rank": ("rank", None,
             "shard rank — bounded by the mesh shape, fixed per "
             "process"),
}


def _resolve_family(index) -> str:
    """Which distributed search serves this list-sharded index."""
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    if isinstance(index, ivf_flat.Index):
        return "ivf_flat"
    if isinstance(index, ivf_pq.Index):
        expects(index.decoded is not None,
                "serve.dist: IVF-PQ index has no reconstruction cache — "
                "shard it via shard_ivf_pq / sharded_ivf_pq_build first")
        return "ivf_pq"
    expects(False, "serve.dist: unsupported index type %s (want a "
            "list-sharded ivf_flat/ivf_pq Index)", type(index).__name__)


class DistSearchPlan:
    """Plan-like object (the :class:`PlanLadder` contract: ``search``,
    ``nq``, ``n_probes``) over one (nq, rung) operating point of a
    list-sharded index: each ``search`` is ONE cached shard_map
    dispatch over the whole mesh, merge wire format pinned at build."""

    def __init__(self, family: str, index, mesh, axis: str, nq: int,
                 k: int, params, merge: str, comms, level: int = 0,
                 sync_timeout_s: Optional[float] = None):
        self.family = family
        self.nq = int(nq)
        self.dim = int(index.dim)
        self.k = int(k)
        self.n_probes = int(min(
            params.n_probes, index.n_lists // mesh.shape[axis]))
        self.merge = merge
        self.mesh = mesh
        self.axis = axis
        self.level = int(level)
        self.n_shards = int(mesh.shape[axis])
        # the participants this plan needs alive — the chaos harness's
        # stall_shard matches against this set, and ShardFailedError
        # reports suspects out of it
        self.ranks = tuple(range(self.n_shards))
        self._index = index
        self._params = params
        self._comms = comms
        # when set, block=True waits through comms.sync_stream so a
        # device-side non-completion surfaces as a typed ABORT instead
        # of an indefinite block_until_ready hang (ISSUE 10)
        self._sync_timeout_s = sync_timeout_s
        # analytic per-dispatch wire accounting (serve/merge.py): the
        # trace-time collective counters fire once per program, these
        # fire per batch
        self._bytes_pre, self._bytes_post = merge_wire_bytes(
            self.nq, self.k, self.n_shards, merge, int(index.size))
        # profitability gate: at tiny shapes (nq < n_shards) the
        # two-stage codec's per-row metadata outweighs the f32
        # allgather it replaces — compressing would INFLATE the wire.
        # Those rungs serve f32; the ladder's saturated shapes carry
        # the compression (EQuARX gates quantization the same way)
        if merge == "int8" and 0 < self._bytes_pre <= self._bytes_post:
            self.merge = merge = "f32"
            self._bytes_post = self._bytes_pre

    @property
    def merge_ratio(self) -> float:
        return (self._bytes_post / self._bytes_pre
                if self._bytes_pre else 1.0)

    def search(self, queries, block: bool = False
               ) -> Tuple[object, object]:
        """Serve one batch of exactly ``plan.nq`` queries across the
        mesh → (dists, ids), both (nq, k), identical on every rank."""
        from raft_tpu.parallel import ivf as pivf
        q = np.asarray(queries, np.float32)
        expects(q.shape == (self.nq, self.dim),
                "dist plan.search: queries %s != plan shape (%d, %d)",
                q.shape, self.nq, self.dim)
        # chaos-harness site: a stall/drop rule matching any of this
        # plan's participating ranks fires here (no-op in production)
        faults.inject("serve.dist.dispatch", ranks=self.ranks,
                      family=self.family)
        obs.counter("raft.serve.dist.batches", level=self.level).inc()
        obs.counter("raft.serve.dist.queries").inc(self.nq)
        obs.counter("raft.serve.dist.merge.bytes_pre",
                    level=self.level).inc(self._bytes_pre)
        obs.counter("raft.serve.dist.merge.bytes_post",
                    level=self.level).inc(self._bytes_post)
        # per-shard row accounting: queries replicate, so every shard
        # scans its own lists for all nq rows (cardinality = mesh size)
        obs.counter("raft.serve.dist.shard.rows").inc(
            self.nq * self.n_shards)
        # resource profiler admission (one None read when off): a
        # sampled blocking dispatch splits host-enqueue vs device-wait
        # around the sync it was paying anyway
        prof = block and profiler.sampled()
        t0 = time.perf_counter()
        with spans.span("raft.serve.dist.dispatch", family=self.family,
                        nq=self.nq, k=self.k, n_probes=self.n_probes,
                        n_shards=self.n_shards, merge=self.merge,
                        level=self.level):
            if self.family == "ivf_flat":
                d, i = pivf.distributed_ivf_flat_search(
                    self._index, q, self.k, self._params,
                    mesh=self.mesh, axis=self.axis, comms=self._comms,
                    merge=self.merge)
            else:
                d, i = pivf.distributed_ivf_pq_search(
                    self._index, q, self.k, self._params,
                    mesh=self.mesh, axis=self.axis, comms=self._comms,
                    merge=self.merge)
        t_enq = time.perf_counter()
        if block:
            if self._sync_timeout_s:
                # comms-layer completion wait with failure semantics:
                # a lost participant makes the collective never
                # complete — sync_stream converts that into a typed
                # ABORT the dispatcher fails the batch with, instead of
                # an indefinite hang (reference waitall-with-timeout)
                st = self._comms.sync_stream(
                    d, i, timeout_s=self._sync_timeout_s)
                if getattr(st, "name", "SUCCESS") != "SUCCESS":
                    raise ShardFailedError(
                        f"cross-shard dispatch reported "
                        f"{getattr(st, 'name', st)}", ranks=self.ranks)
                if prof:
                    # sync_stream already blocked — result=None means
                    # "stamp now", no second sync
                    profiler.record_dispatch(
                        t0, t_enq, None, program="dist",
                        family=self.family, rung=self.level)
            elif prof:
                profiler.record_dispatch(
                    t0, t_enq, (d, i), program="dist",
                    family=self.family, rung=self.level)
            else:
                import jax
                jax.block_until_ready((d, i))
        return d, i


def build_dist_ladder(index, rep_queries, k: int, params=None,
                      mesh=None, axis: str = "data",
                      shapes: Tuple[int, ...] = (1, 8, 32, 128),
                      probes_ladder: Tuple[int, ...] = (),
                      prewarm: bool = True,
                      merge: Optional[str] = None,
                      sync_timeout_s: Optional[float] = None
                      ) -> PlanLadder:
    """Pre-warm the (shape × rung) grid of distributed plans over a
    list-sharded index → a :class:`PlanLadder` the micro-batcher serves
    from. With ``prewarm`` every grid point executes once at build, so
    steady-state traffic never compiles anywhere on the mesh."""
    expects(mesh is not None, "build_dist_ladder: mesh is required")
    from raft_tpu.neighbors import plan as plan_mod
    from raft_tpu.parallel import ivf as pivf
    family = _resolve_family(index)
    if params is None:
        params = plan_mod._default_params(family)
    merge = merge_mode(default="int8") if merge is None else merge
    expects(merge in ("f32", "int8"),
            "build_dist_ladder: merge must be 'f32' or 'int8', got %r",
            merge)
    comms = pivf.get_comms(mesh, axis)
    q = np.asarray(rep_queries, np.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "build_dist_ladder: rep_queries must be (nq, dim=%d), "
            "got %s", index.dim, q.shape)
    nl_local = index.n_lists // mesh.shape[axis]
    rungs = tuple(probes_ladder) or (min(params.n_probes, nl_local),)
    plans = {}
    for ri, n_probes in enumerate(rungs):
        p_r = dataclasses.replace(params, n_probes=n_probes)
        for s in shapes:
            plan = DistSearchPlan(family, index, mesh, axis, s, k, p_r,
                                  merge, comms, level=ri,
                                  sync_timeout_s=sync_timeout_s)
            if prewarm:
                # warm OUTSIDE the sync-timeout path: the first
                # dispatch pays the shard_map compile, which must not
                # read as a failed collective
                import jax
                reps = -(-s // q.shape[0])
                jax.block_until_ready(plan.search(
                    np.tile(q, (reps, 1))[:s], block=False))
            plans[(s, ri)] = plan
    return PlanLadder(shapes=tuple(shapes), rungs=rungs, plans=plans,
                      dim=index.dim, k=k)


# ---------------------------------------------------------------------------
# partial-mesh failover (ISSUE 10): degraded serving over healthy shards
# ---------------------------------------------------------------------------


def _shard_local_view(index, rank: int, nl_local: int, family: str,
                      device):
    """Shard ``rank``'s slice of a list-sharded index as a standalone
    single-device index on ``device`` — its own coarse centers and
    lists, global ids intact. This is what a healthy host still holds
    when a peer dies: its lists, searchable without any collective."""
    import jax
    sl = slice(rank * nl_local, (rank + 1) * nl_local)

    def put(a):
        return jax.device_put(np.asarray(a)[sl], device)

    if family == "ivf_flat":
        from raft_tpu.neighbors.ivf_flat import Index
        return Index(
            centers=put(index.centers), lists_data=put(index.lists_data),
            lists_indices=put(index.lists_indices),
            lists_norms=put(index.lists_norms),
            list_sizes=put(index.list_sizes), metric=index.metric,
            size=index.size, scale=index.scale)
    from raft_tpu.neighbors.ivf_pq import CodebookGen, Index
    per_cluster = index.codebook_kind == CodebookGen.PER_CLUSTER
    return Index(
        centers=put(index.centers), centers_rot=put(index.centers_rot),
        rotation_matrix=jax.device_put(
            np.asarray(index.rotation_matrix), device),
        pq_centers=(put(index.pq_centers) if per_cluster else
                    jax.device_put(np.asarray(index.pq_centers), device)),
        codes=put(index.codes), lists_indices=put(index.lists_indices),
        list_sizes=put(index.list_sizes), metric=index.metric,
        pq_bits=index.pq_bits, size=index.size,
        codebook_kind=index.codebook_kind,
        code_norms=(put(index.code_norms)
                    if index.code_norms is not None else None),
        decoded=(put(index.decoded)
                 if index.decoded is not None else None),
        decoded_norms=(put(index.decoded_norms)
                       if index.decoded_norms is not None else None))


class _PartialMeshPlan:
    """Plan-like handle serving one batch over the HEALTHY shard
    subset: each healthy shard's pre-warmed single-device
    :class:`~raft_tpu.neighbors.plan.SearchPlan` scans its own lists
    (no collective — a dead participant cannot hang what it is not part
    of), and the per-shard top-k merge happens host-side on the (nq, k)
    blocks. Results are explicitly partial: ``coverage`` is the row
    fraction of the corpus the surviving shards hold."""

    partial = True

    def __init__(self, ladder: "FailoverLadder", nq: int,
                 excluded: Tuple[int, ...]):
        self._ladder = ladder
        self.nq = int(nq)
        self.excluded = tuple(excluded)
        self.ranks = tuple(r for r in range(ladder.n_shards)
                           if r not in self.excluded)
        self.n_probes = ladder.n_probes
        self.coverage = ladder.coverage(self.excluded)
        self.k = ladder.k

    def search(self, queries, block: bool = False):
        lad = self._ladder
        faults.inject("serve.dist.dispatch", ranks=self.ranks,
                      family="failover")
        expects(self.ranks, "partial-mesh plan: every shard excluded")
        obs.counter("raft.serve.failover.batches.total").inc()
        with spans.span("raft.serve.dist.dispatch", mode="partial",
                        nq=self.nq, k=self.k,
                        healthy=len(self.ranks),
                        excluded=len(self.excluded),
                        coverage=round(self.coverage, 4)):
            # enqueue every healthy shard's dispatch before syncing any
            # (they run concurrently on their own devices)
            outs = [lad.plan(r, self.nq).search(queries, block=False)
                    for r in self.ranks]
            d = np.concatenate([np.asarray(o[0]) for o in outs], axis=1)
            i = np.concatenate([np.asarray(o[1]) for o in outs], axis=1)
            sel = np.argsort(-d if lad.descending else d, axis=1,
                             kind="stable")[:, :self.k]
            return (np.take_along_axis(d, sel, axis=1),
                    np.take_along_axis(i, sel, axis=1))


class FailoverLadder:
    """The pre-warmed degraded tier: per (rank, shape) single-device
    plans over each shard's local lists, built and warmed at SERVER
    construction so engaging failover never compiles (the zero-compile
    contract holds through the failure path — asserted from
    ``raft.plan.cache.*`` in tests/test_faults.py). One grid serves ANY
    suspect subset: exclusion is a host-side choice of which per-shard
    plans to run, not program structure."""

    def __init__(self, shapes: Tuple[int, ...],
                 plans: Dict[Tuple[int, int], object],
                 weights: Dict[int, float], n_shards: int, k: int,
                 n_probes: int, descending: bool):
        self.shapes = tuple(shapes)
        self._plans = dict(plans)
        self._weights = dict(weights)
        self.n_shards = int(n_shards)
        self.k = int(k)
        self.n_probes = int(n_probes)
        self.descending = bool(descending)

    def plan(self, rank: int, shape: int):
        return self._plans[(rank, shape)]

    def coverage(self, excluded: Tuple[int, ...]) -> float:
        return max(0.0, 1.0 - sum(self._weights.get(r, 0.0)
                                  for r in set(excluded)))

    def bind(self, rows: int, excluded: Tuple[int, ...]
             ) -> Tuple[int, _PartialMeshPlan]:
        """Smallest shape fitting ``rows`` → (shape, partial plan over
        the non-excluded shards) — the PlanLadder.plan_for contract."""
        expects(0 < rows <= self.shapes[-1],
                "FailoverLadder: %d rows exceed the largest shape %d",
                rows, self.shapes[-1])
        for s in self.shapes:
            if rows <= s:
                return s, _PartialMeshPlan(self, s, excluded)
        raise AssertionError("unreachable")


def build_failover_ladder(index, rep_queries, k: int, params=None,
                          mesh=None, axis: str = "data",
                          shapes: Tuple[int, ...] = (1, 8, 32, 128),
                          prewarm: bool = True) -> FailoverLadder:
    """Build + pre-warm the partial-mesh failover grid for a
    list-sharded index: one single-device
    :class:`~raft_tpu.neighbors.plan.SearchPlan` per (shard, shape),
    each over that shard's local lists on that shard's device. A single
    quality rung (the full ``n_probes`` clamped to the local list
    count) — degraded mode IS the quality reduction; the n_probes
    ladder stays a full-mesh concern."""
    from raft_tpu.distance.distance_types import DistanceType
    from raft_tpu.neighbors import plan as plan_mod
    expects(mesh is not None, "build_failover_ladder: mesh is required")
    family = _resolve_family(index)
    if params is None:
        params = plan_mod._default_params(family)
    n_shards = int(mesh.shape[axis])
    nl_local = index.n_lists // n_shards
    q = np.asarray(rep_queries, np.float32)
    sizes = np.asarray(index.list_sizes).reshape(-1).astype(np.float64)
    total = max(1.0, float(sizes.sum()))
    weights = {r: float(sizes[r * nl_local:(r + 1) * nl_local].sum())
               / total for r in range(n_shards)}
    devices = list(np.asarray(mesh.devices).reshape(-1))
    p_local = dataclasses.replace(
        params, n_probes=min(params.n_probes, nl_local))
    plans: Dict[Tuple[int, int], object] = {}
    for r in range(n_shards):
        sub = _shard_local_view(index, r, nl_local, family,
                                devices[r % len(devices)])
        for s in shapes:
            reps = -(-s // q.shape[0])
            plans[(r, s)] = plan_mod.build_plan(
                sub, np.tile(q, (reps, 1))[:s], k, p_local,
                warm=prewarm)
    descending = index.metric in (DistanceType.InnerProduct,
                                  DistanceType.CosineExpanded)
    return FailoverLadder(shapes=tuple(shapes), plans=plans,
                          weights=weights, n_shards=n_shards, k=k,
                          n_probes=p_local.n_probes,
                          descending=descending)


class DistributedSearchServer(SearchServer):
    """The mesh-wide serving front door: ``submit() -> Future`` with
    the full single-device robustness contract (bounded queue,
    deadlines, degradation ladder — all inherited), each coalesced
    batch dispatched as one cached shard_map program over the
    list-sharded index with the quantized cross-shard merge."""

    # same dispatcher/caller thread boundary as the base server (GL003
    # static race contract — redeclared because the rule is per-class):
    # this subclass adds NO cross-thread state; add a field another
    # thread writes and it belongs in this tuple AND under self._cond.
    # The failover fields (_failover/_excluded/_next_probe) are
    # dispatcher-thread-only, like the LoadController's.
    GUARDED_BY = ("_q", "_rows_queued", "_closed", "_shed_times")

    def __init__(self, ladder: PlanLadder,
                 config: Optional[ServeConfig] = None,
                 start: bool = True,
                 failover_ladder: Optional[FailoverLadder] = None):
        p0 = ladder.plan_for(ladder.shapes[0], 0)[1]
        expects(isinstance(p0, DistSearchPlan)
                or getattr(p0, "dist_like", False),
                "DistributedSearchServer: ladder must hold "
                "DistSearchPlans (build via build_dist_ladder /"
                " mutate.build_dist_serve_ladder)")
        # the ratio gauge reports the SATURATED operating point (the
        # largest ladder shape) — tiny shapes ride the profitability
        # fallback and would misstate the compression
        p_top = ladder.plan_for(ladder.max_shape, 0)[1]
        obs.gauge("raft.serve.dist.shards").set(p0.n_shards)
        obs.gauge("raft.serve.dist.merge.ratio").set(
            round(p_top.merge_ratio, 4))
        self._failover = failover_ladder
        self._excluded: Tuple[int, ...] = ()
        self._next_probe = 0.0
        obs.gauge("raft.serve.failover.engaged").set(0)
        super().__init__(ladder, config, start=start)

    # -- partial-mesh failover (ISSUE 10) ----------------------------------
    @property
    def excluded_ranks(self) -> Tuple[int, ...]:
        """Currently excluded (suspect) shard ranks — dispatcher-thread
        state; read-only snapshot for tests and status surfaces."""
        return self._excluded

    def _suspects(self) -> Tuple[int, ...]:
        from raft_tpu.comms.health import suspects_from_gauges
        return tuple(suspects_from_gauges(
            obs.snapshot().get("gauges", {})))

    def _engage_failover(self, suspects: Tuple[int, ...]) -> None:
        fresh = tuple(sorted(set(suspects)))
        if fresh == self._excluded:
            return
        first = not self._excluded
        self._excluded = fresh
        cov = self._failover.coverage(fresh)
        if first:
            obs.counter("raft.serve.failover.total").inc()
        obs.gauge("raft.serve.failover.engaged").set(1)
        obs.gauge("raft.serve.failover.coverage").set(round(cov, 4))
        self._next_probe = (time.monotonic()
                            + self._cfg.failover_probe_ms / 1e3)
        get_logger("serve").warn(
            "failover engaged: serving partial results over healthy "
            "shards (excluded ranks %s, coverage %.4f)", fresh, cov)

    def _maybe_recover(self) -> None:
        """While excluded, periodically re-read the suspect gauges; a
        clean bill of health clears the exclusion and the next batch
        rides the (still-warm) full-mesh ladder — recovery never
        compiles either."""
        now = time.monotonic()
        if now < self._next_probe:
            return
        self._next_probe = now + self._cfg.failover_probe_ms / 1e3
        suspects = self._suspects()
        if suspects:
            self._engage_failover(suspects)
            return
        self._excluded = ()
        obs.gauge("raft.serve.failover.engaged").set(0)
        obs.gauge("raft.serve.failover.coverage").set(1.0)
        obs.counter("raft.serve.failover.recovered.total").inc()
        get_logger("serve").warn(
            "failover recovered: suspect ranks cleared, back to the "
            "full mesh")

    def _plan_for_batch(self, rows: int, level: int):
        if self._excluded and self._failover is not None:
            self._maybe_recover()
            if self._excluded:
                return self._failover.bind(rows, self._excluded)
        return super()._plan_for_batch(rows, level)

    def _plan_after_failure(self, shape: int, level: int, err):
        if self._failover is None:
            return None
        suspects = self._suspects()
        if not suspects:
            return None     # nothing to exclude — retry the full mesh
        self._engage_failover(suspects)
        return self._failover.bind(shape, self._excluded)[1]

    def _quality_detail(self) -> str:
        """Shard attribution for coverage-flagged quality samples
        (ISSUE 11): while failover is engaged, sampled partial results
        carry the excluded ranks as a label, so a degraded
        ``raft.obs.quality.recall`` series names WHICH shards' rows
        were missing — explainable, not mysterious. Dispatcher-thread
        state, read on the dispatcher thread."""
        return ",".join(str(r) for r in self._excluded)

    @property
    def mesh(self):
        return self.ladder.plan_for(self.ladder.shapes[0], 0)[1].mesh

    @classmethod
    def from_sharded_index(cls, index, rep_queries, k: int, params=None,
                           mesh=None, axis: str = "data",
                           config: Optional[ServeConfig] = None,
                           merge: Optional[str] = None,
                           start: bool = True
                           ) -> "DistributedSearchServer":
        """Build + pre-warm the distributed plan ladder for a
        list-sharded ``index`` (``shard_ivf_*`` / ``sharded_*_build``
        layout) and start serving the mesh. With ``config.failover``
        the partial-mesh ladder is pre-warmed too, so a suspect shard
        degrades the server to flagged-partial results instead of
        errors — with zero failure-path compiles."""
        config = config if config is not None else ServeConfig()
        sync_timeout_s = (config.dispatch_timeout_ms / 1e3
                          if config.dispatch_timeout_ms > 0 else None)
        ladder = build_dist_ladder(
            index, rep_queries, k, params, mesh=mesh, axis=axis,
            shapes=config.batch_sizes,
            probes_ladder=config.probes_ladder,
            prewarm=config.prewarm, merge=merge,
            sync_timeout_s=sync_timeout_s)
        fol = None
        if config.failover:
            fol = build_failover_ladder(
                index, rep_queries, k, params, mesh=mesh, axis=axis,
                shapes=config.batch_sizes, prewarm=config.prewarm)
        srv = cls(ladder, config, start=start, failover_ladder=fol)
        srv._quality_meta = {"metric": getattr(index, "metric", None),
                             "family": type(index).__module__
                             .rsplit(".", 1)[-1]}
        return srv

    @classmethod
    def from_mutable(cls, mindex, rep_queries, mesh=None,
                     axis: str = "data",
                     config: Optional[ServeConfig] = None,
                     merge: Optional[str] = None,
                     start: bool = True) -> "DistributedSearchServer":
        """Serve a :class:`raft_tpu.mutate.MutableIndex` mesh-wide:
        each epoch's inner index is list-sharded and served through the
        cached shard_map grid, with the delta merge + tombstone filter
        composed as a compiled tail after the cross-shard merge (the
        delta segment replicates — it is orders of magnitude smaller
        than the sharded lists). Background compactions re-shard and
        pre-warm the next epoch off the serving path, then swap — the
        server never stops and never compiles in steady state
        (docs/mutability.md)."""
        from raft_tpu.mutate import build_dist_serve_ladder
        config = config if config is not None else ServeConfig()
        expects(not config.failover,
                "from_mutable: partial-mesh failover is not supported "
                "over a MutableIndex yet (the delta/tombstone tail "
                "would need per-shard recomposition) — serve with "
                "failover=False")
        ladder = build_dist_serve_ladder(
            mindex, rep_queries, mesh=mesh, axis=axis,
            shapes=config.batch_sizes,
            probes_ladder=config.probes_ladder, merge=merge)
        srv = cls(ladder, config, start=start)
        srv._quality_meta = {"metric": mindex.metric,
                             "family": mindex.family}
        srv._quality_src = mindex
        return srv
