"""Mesh-wide distributed serving tier (ISSUE 8).

The raft::comms / raft-dask L7 layer rebuilt TPU-native as a *serving*
surface: one ``DistributedSearchServer.submit()`` front door over a
list-sharded IVF index spanning the whole mesh. It reuses the PR 5
micro-batcher wholesale — bounded-queue admission, request coalescing,
deadlines, the n_probes degradation ladder — and swaps the plan layer
underneath: every (shape, rung) of the ladder is ONE cached shard_map
program (``parallel/ivf._shmap_plan``) that fans the coalesced batch
out across every shard's lists and merges the per-shard top-k with the
quantized cross-shard codec (``serve/merge.py``,
``RAFT_TPU_DIST_MERGE=f32|int8``, int8 default here — exact re-rank or
the 0.005 recall budget absorbs the rounding).

Steady-state contract (same as the single-device server, asserted from
counters in ``tests/test_serve_dist.py`` and reported by
``bench_serve_sharded`` as ``steady_state_compiles``): after the
ladder prewarm, serving traffic performs ZERO compiles and zero
retraces anywhere on the mesh — ``raft.parallel.plan.misses``,
``raft.plan.cache.misses`` and ``raft.plan.build.total`` all stay
flat; every dispatch is a ``raft.parallel.plan.hits`` cache hit.

Observability: ``raft.serve.dist.*`` counters/gauges (batches, wire
bytes pre/post compression per rung, per-shard rows, shard count,
merge ratio), rank-tagged ``raft.parallel.ivf.shard`` child spans
under the batcher's ``raft.serve.batch`` root, and a ``/healthz``
``dist`` section folding the per-shard comms-health suspects
(docs/serving.md "Distributed serving").
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core.error import expects
from raft_tpu.obs import spans
from raft_tpu.serve.batcher import SearchServer
from raft_tpu.serve.ladder import PlanLadder
from raft_tpu.serve.merge import merge_mode, merge_wire_bytes
from raft_tpu.serve.types import ServeConfig

__all__ = [
    "DistSearchPlan",
    "DistributedSearchServer",
    "build_dist_ladder",
]


def _resolve_family(index) -> str:
    """Which distributed search serves this list-sharded index."""
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    if isinstance(index, ivf_flat.Index):
        return "ivf_flat"
    if isinstance(index, ivf_pq.Index):
        expects(index.decoded is not None,
                "serve.dist: IVF-PQ index has no reconstruction cache — "
                "shard it via shard_ivf_pq / sharded_ivf_pq_build first")
        return "ivf_pq"
    expects(False, "serve.dist: unsupported index type %s (want a "
            "list-sharded ivf_flat/ivf_pq Index)", type(index).__name__)


class DistSearchPlan:
    """Plan-like object (the :class:`PlanLadder` contract: ``search``,
    ``nq``, ``n_probes``) over one (nq, rung) operating point of a
    list-sharded index: each ``search`` is ONE cached shard_map
    dispatch over the whole mesh, merge wire format pinned at build."""

    def __init__(self, family: str, index, mesh, axis: str, nq: int,
                 k: int, params, merge: str, comms, level: int = 0):
        self.family = family
        self.nq = int(nq)
        self.dim = int(index.dim)
        self.k = int(k)
        self.n_probes = int(min(
            params.n_probes, index.n_lists // mesh.shape[axis]))
        self.merge = merge
        self.mesh = mesh
        self.axis = axis
        self.level = int(level)
        self.n_shards = int(mesh.shape[axis])
        self._index = index
        self._params = params
        self._comms = comms
        # analytic per-dispatch wire accounting (serve/merge.py): the
        # trace-time collective counters fire once per program, these
        # fire per batch
        self._bytes_pre, self._bytes_post = merge_wire_bytes(
            self.nq, self.k, self.n_shards, merge, int(index.size))
        # profitability gate: at tiny shapes (nq < n_shards) the
        # two-stage codec's per-row metadata outweighs the f32
        # allgather it replaces — compressing would INFLATE the wire.
        # Those rungs serve f32; the ladder's saturated shapes carry
        # the compression (EQuARX gates quantization the same way)
        if merge == "int8" and 0 < self._bytes_pre <= self._bytes_post:
            self.merge = merge = "f32"
            self._bytes_post = self._bytes_pre

    @property
    def merge_ratio(self) -> float:
        return (self._bytes_post / self._bytes_pre
                if self._bytes_pre else 1.0)

    def search(self, queries, block: bool = False
               ) -> Tuple[object, object]:
        """Serve one batch of exactly ``plan.nq`` queries across the
        mesh → (dists, ids), both (nq, k), identical on every rank."""
        from raft_tpu.parallel import ivf as pivf
        q = np.asarray(queries, np.float32)
        expects(q.shape == (self.nq, self.dim),
                "dist plan.search: queries %s != plan shape (%d, %d)",
                q.shape, self.nq, self.dim)
        obs.counter("raft.serve.dist.batches", level=self.level).inc()
        obs.counter("raft.serve.dist.queries").inc(self.nq)
        obs.counter("raft.serve.dist.merge.bytes_pre",
                    level=self.level).inc(self._bytes_pre)
        obs.counter("raft.serve.dist.merge.bytes_post",
                    level=self.level).inc(self._bytes_post)
        # per-shard row accounting: queries replicate, so every shard
        # scans its own lists for all nq rows (cardinality = mesh size)
        obs.counter("raft.serve.dist.shard.rows").inc(
            self.nq * self.n_shards)
        with spans.span("raft.serve.dist.dispatch", family=self.family,
                        nq=self.nq, k=self.k, n_probes=self.n_probes,
                        n_shards=self.n_shards, merge=self.merge,
                        level=self.level):
            if self.family == "ivf_flat":
                d, i = pivf.distributed_ivf_flat_search(
                    self._index, q, self.k, self._params,
                    mesh=self.mesh, axis=self.axis, comms=self._comms,
                    merge=self.merge)
            else:
                d, i = pivf.distributed_ivf_pq_search(
                    self._index, q, self.k, self._params,
                    mesh=self.mesh, axis=self.axis, comms=self._comms,
                    merge=self.merge)
        if block:
            import jax
            jax.block_until_ready((d, i))
        return d, i


def build_dist_ladder(index, rep_queries, k: int, params=None,
                      mesh=None, axis: str = "data",
                      shapes: Tuple[int, ...] = (1, 8, 32, 128),
                      probes_ladder: Tuple[int, ...] = (),
                      prewarm: bool = True,
                      merge: Optional[str] = None) -> PlanLadder:
    """Pre-warm the (shape × rung) grid of distributed plans over a
    list-sharded index → a :class:`PlanLadder` the micro-batcher serves
    from. With ``prewarm`` every grid point executes once at build, so
    steady-state traffic never compiles anywhere on the mesh."""
    expects(mesh is not None, "build_dist_ladder: mesh is required")
    from raft_tpu.neighbors import plan as plan_mod
    from raft_tpu.parallel import ivf as pivf
    family = _resolve_family(index)
    if params is None:
        params = plan_mod._default_params(family)
    merge = merge_mode(default="int8") if merge is None else merge
    expects(merge in ("f32", "int8"),
            "build_dist_ladder: merge must be 'f32' or 'int8', got %r",
            merge)
    comms = pivf.get_comms(mesh, axis)
    q = np.asarray(rep_queries, np.float32)
    expects(q.ndim == 2 and q.shape[1] == index.dim,
            "build_dist_ladder: rep_queries must be (nq, dim=%d), "
            "got %s", index.dim, q.shape)
    nl_local = index.n_lists // mesh.shape[axis]
    rungs = tuple(probes_ladder) or (min(params.n_probes, nl_local),)
    plans = {}
    for ri, n_probes in enumerate(rungs):
        p_r = dataclasses.replace(params, n_probes=n_probes)
        for s in shapes:
            plan = DistSearchPlan(family, index, mesh, axis, s, k, p_r,
                                  merge, comms, level=ri)
            if prewarm:
                reps = -(-s // q.shape[0])
                plan.search(np.tile(q, (reps, 1))[:s], block=True)
            plans[(s, ri)] = plan
    return PlanLadder(shapes=tuple(shapes), rungs=rungs, plans=plans,
                      dim=index.dim, k=k)


class DistributedSearchServer(SearchServer):
    """The mesh-wide serving front door: ``submit() -> Future`` with
    the full single-device robustness contract (bounded queue,
    deadlines, degradation ladder — all inherited), each coalesced
    batch dispatched as one cached shard_map program over the
    list-sharded index with the quantized cross-shard merge."""

    # same dispatcher/caller thread boundary as the base server (GL003
    # static race contract — redeclared because the rule is per-class):
    # this subclass adds NO cross-thread state; add a field another
    # thread writes and it belongs in this tuple AND under self._cond
    GUARDED_BY = ("_q", "_rows_queued", "_closed", "_shed_times")

    def __init__(self, ladder: PlanLadder,
                 config: Optional[ServeConfig] = None,
                 start: bool = True):
        p0 = ladder.plan_for(ladder.shapes[0], 0)[1]
        expects(isinstance(p0, DistSearchPlan)
                or getattr(p0, "dist_like", False),
                "DistributedSearchServer: ladder must hold "
                "DistSearchPlans (build via build_dist_ladder /"
                " mutate.build_dist_serve_ladder)")
        # the ratio gauge reports the SATURATED operating point (the
        # largest ladder shape) — tiny shapes ride the profitability
        # fallback and would misstate the compression
        p_top = ladder.plan_for(ladder.max_shape, 0)[1]
        obs.gauge("raft.serve.dist.shards").set(p0.n_shards)
        obs.gauge("raft.serve.dist.merge.ratio").set(
            round(p_top.merge_ratio, 4))
        super().__init__(ladder, config, start=start)

    @property
    def mesh(self):
        return self.ladder.plan_for(self.ladder.shapes[0], 0)[1].mesh

    @classmethod
    def from_sharded_index(cls, index, rep_queries, k: int, params=None,
                           mesh=None, axis: str = "data",
                           config: Optional[ServeConfig] = None,
                           merge: Optional[str] = None,
                           start: bool = True
                           ) -> "DistributedSearchServer":
        """Build + pre-warm the distributed plan ladder for a
        list-sharded ``index`` (``shard_ivf_*`` / ``sharded_*_build``
        layout) and start serving the mesh."""
        config = config if config is not None else ServeConfig()
        ladder = build_dist_ladder(
            index, rep_queries, k, params, mesh=mesh, axis=axis,
            shapes=config.batch_sizes,
            probes_ladder=config.probes_ladder,
            prewarm=config.prewarm, merge=merge)
        return cls(ladder, config, start=start)

    @classmethod
    def from_mutable(cls, mindex, rep_queries, mesh=None,
                     axis: str = "data",
                     config: Optional[ServeConfig] = None,
                     merge: Optional[str] = None,
                     start: bool = True) -> "DistributedSearchServer":
        """Serve a :class:`raft_tpu.mutate.MutableIndex` mesh-wide:
        each epoch's inner index is list-sharded and served through the
        cached shard_map grid, with the delta merge + tombstone filter
        composed as a compiled tail after the cross-shard merge (the
        delta segment replicates — it is orders of magnitude smaller
        than the sharded lists). Background compactions re-shard and
        pre-warm the next epoch off the serving path, then swap — the
        server never stops and never compiles in steady state
        (docs/mutability.md)."""
        from raft_tpu.mutate import build_dist_serve_ladder
        config = config if config is not None else ServeConfig()
        ladder = build_dist_serve_ladder(
            mindex, rep_queries, mesh=mesh, axis=axis,
            shapes=config.batch_sizes,
            probes_ladder=config.probes_ladder, merge=merge)
        return cls(ladder, config, start=start)
