"""raft_tpu.serve — dynamic micro-batching serving runtime.

The first subsystem that makes ``raft_tpu`` a *service* rather than a
library: independent callers submit search requests; a bounded queue +
dispatcher thread coalesces them into the largest admissible compiled
shape from a pre-warmed :class:`~raft_tpu.serve.ladder.PlanLadder`
(``neighbors/plan.py`` AOT executables), pads ragged tails with
duplicated real rows, executes ONE plan per batch, and scatters
per-request slices back to caller futures — the chip runs at saturated
batch sizes however small the individual requests are.

Robustness contract (docs/serving.md):

* bounded queue → over-depth submissions fail NOW with
  :class:`RejectedError` (explicit backpressure);
* per-request deadlines → expired requests complete with
  :class:`DeadlineExceeded` instead of occupying batch slots;
* graceful degradation → above a queue-delay watermark, ``n_probes``
  steps down a configured ladder (p99 bounded at slightly reduced
  recall) and steps back up when the queue drains.

Quick use::

    from raft_tpu import serve
    from raft_tpu.neighbors import ivf_flat

    index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=1024))
    srv = serve.SearchServer.from_index(
        index, sample_queries, k=32,
        params=ivf_flat.SearchParams(n_probes=96),
        config=serve.ServeConfig(batch_sizes=(1, 8, 32, 128),
                                 probes_ladder=(96, 48, 24),
                                 default_deadline_ms=500.0))
    fut = srv.submit(queries, k=10)          # -> concurrent Future
    dists, ids = srv.search(queries, k=10)   # blocking convenience
    srv.close()

HTTP serving: pass the server to the obs debug endpoint and `POST
/search` is live (``obs.serve(searcher=srv)``); ``/healthz`` folds the
``raft.serve.*`` overload gauges into its verdict. Load-test with
``tools/loadgen.py``; capacity-plan from the ``raft.serve.*`` metrics
(docs/serving.md walkthrough).
"""

from raft_tpu.serve.batcher import (OCCUPANCY_BUCKETS,
                                    SERVE_LATENCY_BUCKETS, SearchServer)
from raft_tpu.serve.controller import LoadController
from raft_tpu.serve.ladder import PlanLadder
from raft_tpu.serve.types import (DeadlineExceeded, DispatchError,
                                  RejectedError, SearchResult,
                                  ServeConfig, ShardFailedError)

__all__ = [
    "DeadlineExceeded",
    "DispatchError",
    "DistSearchPlan",
    "DistributedSearchServer",
    "FailoverLadder",
    "LoadController",
    "OCCUPANCY_BUCKETS",
    "PlanLadder",
    "RejectedError",
    "SERVE_LATENCY_BUCKETS",
    "SearchResult",
    "SearchServer",
    "ServeConfig",
    "ShardFailedError",
    "build_dist_ladder",
    "build_failover_ladder",
]

# the distributed tier (serve/dist.py, ISSUE 8) pulls in jax through
# the merge codec; resolve it lazily so importing raft_tpu.serve for
# the error types (the obs endpoint does) stays dependency-light
_DIST_NAMES = ("DistSearchPlan", "DistributedSearchServer",
               "FailoverLadder", "build_dist_ladder",
               "build_failover_ladder")


def __getattr__(name):
    if name in _DIST_NAMES:
        from raft_tpu.serve import dist as _dist
        return getattr(_dist, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
