"""Quantized cross-shard merge codec (ISSUE 8).

The cross-shard top-k merge is the one wire payload distributed serving
moves per batch: every shard's ``(nq, k)`` (distance, id) candidates.
The f32 path (``parallel.ivf._global_merge``) allgathers both tensors
at full precision — 8 bytes per candidate received ``n_shards - 1``
times per rank. EQuARX (arxiv 2506.17615) shows XLA collectives
tolerate blockwise int8 wire formats at negligible quality loss; merge
traffic tolerates it even better than gradients do, because distances
only RANK candidates — exact re-rank (where the raw corpus is
resident) or the 0.005 recall budget absorbs the rounding.

The compressed merge here restructures the collective AND shrinks the
payload (both EQuARX moves):

* **two stages instead of one allgather** — stage A ``all_to_all``s
  each query block's candidates to one owner rank, which dequantizes
  and ``top_k``-merges its ``nq / n_shards`` slice; stage B allgathers
  the merged (re-quantized) slices so every rank holds the full result.
  Per-rank received bytes drop from ``(n-1)·nq·k`` candidates to
  ``2·(n-1)·nq·k / n`` — the 1/n factor does most of the compression.
* **int8 blockwise-scaled distances** — per-query max-abs scale (the
  block = one query's k candidates), distances on the wire as int8.
* **packed int32 words** — when ids fit 24 bits (``size`` <
  ``PACK_ID_SENTINEL``), each (distance, id) pair rides as ONE uint32
  word: biased dist byte high, 24-bit id low. Bigger corpora fall back
  to the split layout (int8 dists + int32 ids), still compressed.

Net wire ratio vs f32 ≈ ``1.03/n`` packed (``1.29/n`` split): 0.13 at
8 shards, measured by ``bench_serve_sharded`` as ``merge_bytes_ratio``
and counted under ``raft.serve.dist.merge.bytes_{pre,post}``.

Everything in this module except :func:`merge_mode` and
:func:`merge_wire_bytes` runs INSIDE ``shard_map`` (device code, no
obs calls — counters are emitted host-side by ``serve/dist.py`` from
the analytic byte accounting).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax.numpy as jnp
from jax import lax

__all__ = [
    "PACK_ID_SENTINEL",
    "compressed_merge",
    "dequantize_rows",
    "merge_mode",
    "merge_wire_bytes",
    "pack_pairs",
    "quantize_rows",
    "unpack_pairs",
]

_QMAX = 127.0
# 24-bit id space; the all-ones pattern is the invalid-slot sentinel
# (id -1), so packed layout requires ids < PACK_ID_SENTINEL
PACK_ID_SENTINEL = (1 << 24) - 1


def merge_mode(default: str = "int8") -> str:
    """Resolve the cross-shard merge wire format from
    ``RAFT_TPU_DIST_MERGE`` (``f32`` | ``int8``), host-side and OUTSIDE
    jit (the ``fused_mode`` pattern). ``default`` differs by caller:
    the serving tier (``serve/dist.py``) compresses by default; the
    library functions (``distributed_ivf_*_search``) default to the
    exact f32 merge so their bit-exactness contracts (dryrun
    exhaustive-probe == exact) hold unless an operator opts in."""
    v = os.environ.get("RAFT_TPU_DIST_MERGE", "").strip().lower()
    if v in ("f32", "int8"):
        return v
    return default


def quantize_rows(d, i) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Blockwise AFFINE int8 quantization, one block per query row:
    ``(nq, k) f32 -> (nq, k) int8 + (nq,) f32 scale + (nq,) f32 zero``.

    Affine (scale + zero-point), not max-abs symmetric: merge distances
    are concentrated far from zero (a query's cross-shard top-k spans a
    narrow band of the distance axis), so spending the 8 bits on
    ``[row_min, row_max]`` instead of ``[-|max|, |max|]`` cuts the
    rounding step to ``range/254`` — measured ~3× better recall at the
    rank-k boundary. Invalid slots (``i < 0`` — their distance is the
    +inf pad) are excluded from the range and quantize to the max code;
    :func:`dequantize_rows` restores their +inf from the id mask, so an
    all-invalid row round-trips."""
    valid = i >= 0
    hi = jnp.max(jnp.where(valid, d, -jnp.inf), axis=1)
    lo = jnp.min(jnp.where(valid, d, jnp.inf), axis=1)
    hi = jnp.where(jnp.isfinite(hi), hi, 0.0)
    lo = jnp.where(jnp.isfinite(lo), lo, 0.0)
    scale = jnp.where(hi > lo, (hi - lo) / (2.0 * _QMAX),
                      1.0).astype(jnp.float32)
    zero = lo.astype(jnp.float32)
    q = jnp.clip(jnp.round((d - zero[:, None]) / scale[:, None]) - _QMAX,
                 -_QMAX, _QMAX)
    q = jnp.where(valid, q, _QMAX).astype(jnp.int8)
    return q, scale, zero


def dequantize_rows(q, scale, zero, i):
    """Inverse of :func:`quantize_rows` (``scale``/``zero``
    broadcastable to ``q``): int8 codes back to f32 distances, invalid
    ids back to the +inf pad the merge sort expects."""
    d = (q.astype(jnp.float32) + _QMAX) * scale + zero
    return jnp.where(i >= 0, d, jnp.inf)


def pack_pairs(q, i):
    """One uint32 word per candidate: biased dist byte high, 24-bit id
    low. Invalid ids (< 0) carry :data:`PACK_ID_SENTINEL`."""
    b = (q.astype(jnp.int32) + 128).astype(jnp.uint32)
    idw = jnp.where(i >= 0, i, PACK_ID_SENTINEL).astype(jnp.uint32)
    return (b << 24) | (idw & jnp.uint32(PACK_ID_SENTINEL))


def unpack_pairs(w) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Inverse of :func:`pack_pairs` — bit-exact: the dist byte and the
    24-bit id round-trip unchanged, the sentinel maps back to -1."""
    q = ((w >> 24).astype(jnp.int32) - 128).astype(jnp.int8)
    idw = (w & jnp.uint32(PACK_ID_SENTINEL)).astype(jnp.int32)
    return q, jnp.where(idw == PACK_ID_SENTINEL, -1, idw)


def merge_wire_bytes(nq: int, k: int, n_shards: int, mode: str,
                     size: int = 0) -> Tuple[int, int]:
    """Analytic per-rank RECEIVED wire bytes of one cross-shard merge →
    ``(f32_bytes, mode_bytes)``. Host-side accounting for the
    ``raft.serve.dist.merge.bytes_{pre,post}`` counters and the
    ``merge_bytes_ratio`` bench figure (the trace-time
    ``raft.comms.collective.bytes`` counters only fire once per
    compiled program, never per execution)."""
    if n_shards <= 1:
        return 0, 0
    f32 = (n_shards - 1) * nq * k * 8          # allgather of f32 d + i32 i
    if mode == "f32":
        return f32, f32
    blk = -(-nq // n_shards)
    pair = 4 if 0 < size < PACK_ID_SENTINEL else 5   # packed | split
    # + 8 B/row: the f32 (scale, zero) affine metadata
    per_stage = (n_shards - 1) * blk * (k * pair + 8)
    return f32, 2 * per_stage


def compressed_merge(comms, d, i, k: int, size: int):
    """The int8 two-stage cross-shard top-k merge — runs inside
    ``shard_map``; every rank returns the identical full ``(nq, k)``
    result (same contract as ``_global_merge``).

    Per-query independence is a correctness property the serving tier
    leans on: scales are per-row and each query's candidate set is
    exactly the shards' top-k for that row, so a query's merged result
    does not depend on which batch (or padding) it rode in — asserted
    by the pad-row non-leakage test in ``tests/test_serve_dist.py``.
    """
    n = comms.get_size()
    axis = comms.axis_name
    nq = d.shape[0]
    blk = -(-nq // n)
    pad = blk * n - nq
    if pad:
        d = jnp.pad(d, ((0, pad), (0, 0)), constant_values=jnp.inf)
        i = jnp.pad(i, ((0, pad), (0, 0)), constant_values=-1)
    packed = 0 < size < PACK_ID_SENTINEL

    # stage A: ship each query block's candidates to its owner rank
    qz, s, z = quantize_rows(d, i)
    if packed:
        rw = comms.alltoall(pack_pairs(qz, i)).reshape(n, blk, k)
        rq, ri = unpack_pairs(rw)
    else:
        rq = comms.alltoall(qz).reshape(n, blk, k)
        ri = comms.alltoall(i).reshape(n, blk, k)
    meta = comms.alltoall(jnp.stack([s, z], axis=1)).reshape(n, blk, 2)
    rd = dequantize_rows(rq, meta[..., 0:1], meta[..., 1:2], ri)

    # owner-local merge of its nq/n slice: n·k candidates per query
    cat_d = jnp.moveaxis(rd, 0, 1).reshape(blk, n * k)
    cat_i = jnp.moveaxis(ri, 0, 1).reshape(blk, n * k)
    nd, sel = lax.top_k(-cat_d, k)
    md = -nd
    mi = jnp.take_along_axis(cat_i, sel, axis=1)      # (blk, k)

    # stage B: re-quantize the merged slice, allgather, dequantize
    qz2, s2, z2 = quantize_rows(md, mi)
    if packed:
        gq, gi = unpack_pairs(comms.allgather(pack_pairs(qz2, mi)))
    else:
        gq = comms.allgather(qz2)
        gi = comms.allgather(mi)
    gm = comms.allgather(jnp.stack([s2, z2], axis=1))  # (n, blk, 2)
    fd = dequantize_rows(gq, gm[..., 0:1], gm[..., 1:2],
                         gi).reshape(n * blk, k)[:nq]
    fi = gi.reshape(n * blk, k)[:nq]
    # identical on every rank; pmax proves replication to shard_map
    # (the _global_merge convention)
    return lax.pmax(fd, axis), lax.pmax(fi, axis)
