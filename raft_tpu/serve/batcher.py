"""Dynamic micro-batcher: independent requests → saturated plan shapes.

The serving gap this closes: every caller invoking ``plan.search``
alone runs the chip at per-request batch sizes — nq=1 dispatches on
hardware whose fixed per-dispatch cost was measured at ~9 ms
(docs/performance.md). The batcher is the standard TPU-runtime answer
(TPU-KNN, arxiv 2206.14286; continuous batching a la Ragged Paged
Attention, arxiv 2604.15464): a bounded queue, one dispatcher thread
that coalesces whatever is waiting into the largest admissible compiled
shape from the pre-warmed :class:`~raft_tpu.serve.ladder.PlanLadder`,
pads the ragged tail with duplicated REAL rows from the same batch
(pad results discarded — a pad row's neighbors can never leak into
another caller's results), executes the plan, and scatters per-request
slices back to caller futures.

Robustness is part of the contract, not an afterthought:

* **backpressure** — the queue is bounded (``ServeConfig.max_queue``);
  a submission over it fails NOW with :class:`RejectedError`.
* **deadlines** — an expired request completes with
  :class:`DeadlineExceeded` and never occupies a batch slot.
* **graceful degradation** — the :class:`LoadController` steps
  ``n_probes`` down the configured ladder above the queue-delay
  watermark and back up when drained (p99 bounded at slightly reduced
  recall instead of unbounded latency).
* **failure handling** (ISSUE 10, docs/robustness.md) — an optional
  dispatch **watchdog** (``ServeConfig.dispatch_timeout_ms``) abandons
  a hung dispatch (XLA collectives hang, not error, when a participant
  dies) and converts it into a typed :class:`ShardFailedError`; a
  comms-layer ``Status.ABORT``/``ERROR`` returned by a plan is
  converted the same way. Such failures are **retried** with
  exponential backoff under a ``max_retries`` budget, deadline-aware:
  a request whose deadline lands inside the backoff window fails NOW
  with :class:`DeadlineExceeded` rather than being retried past it.
  A **crash guard** around batch processing mirrors the compactor's:
  an unexpected dispatcher exception fails that batch's futures with
  a typed :class:`DispatchError` (counted under
  ``raft.serve.dispatcher.errors``) and the dispatcher keeps serving.

Every decision lands in ``raft.serve.*`` metrics and spans
(docs/serving.md has the taxonomy and a capacity-planning walkthrough).

Threading model: ONE dispatcher thread owns all device work; caller
threads only touch numpy and futures. With the watchdog enabled,
dispatch runs on a single helper thread the dispatcher waits on — an
abandoned (timed-out) helper drains its stuck program and exits, and a
fresh helper takes over, so at most one *live* dispatch exists at any
time (the overlap with a draining orphan mirrors real abort semantics:
a hung collective cannot be cancelled, only orphaned). Future
callbacks run on the dispatcher thread — keep them trivial.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from collections import deque
from typing import Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core.error import expects
from raft_tpu.core.logger import get_logger
from raft_tpu.obs import profiler, spans
from raft_tpu.serve.controller import LoadController
from raft_tpu.serve.ladder import PlanLadder
from raft_tpu.serve.types import (DeadlineExceeded, DispatchError,
                                  RejectedError, SearchResult,
                                  ServeConfig, ShardFailedError,
                                  _Request)
from raft_tpu.testing import faults

__all__ = ["SearchServer", "SERVE_LATENCY_BUCKETS", "OCCUPANCY_BUCKETS"]

# serving latency needs finer edges than the registry default around the
# tens-of-ms watermark region (p99-under-watermark is asserted from
# these buckets in tests/test_serve.py)
SERVE_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.15, 0.2,
    0.25, 0.3, 0.5, 1.0, 2.5, 5.0, 10.0)
OCCUPANCY_BUCKETS = (0.0625, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                     0.875, 1.0)

_SHED_RATE_WINDOW_S = 10.0


class _DispatchWorker:
    """The watchdog's helper thread: executes dispatches so the
    dispatcher can time one out and walk away. A timed-out worker is
    *abandoned* — it finishes (or hangs forever on) its stuck call,
    notices the flag, and exits without touching any shared serving
    state; the server spawns a replacement for the next dispatch."""

    def __init__(self, name: str):
        self._q: queue_mod.Queue = queue_mod.Queue()
        self.abandoned = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    def submit(self, fn) -> dict:
        box = {"done": threading.Event(), "out": None, "err": None}
        self._q.put((fn, box))
        return box

    def stop(self) -> None:
        self._q.put(None)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, box = item
            try:
                box["out"] = fn()
            except BaseException as e:  # delivered to the dispatcher
                box["err"] = e
            box["done"].set()
            if self.abandoned.is_set():
                return


class SearchServer:
    """The serving runtime over one index: ``submit() -> Future`` plus
    a blocking ``search()`` convenience. Construct via
    :meth:`from_index` (real plans) or directly from a
    :class:`PlanLadder` (tests inject fakes)."""

    # static race detector contract (tools/graftlint GL003): these
    # fields sit on the caller-thread/dispatcher-thread boundary and
    # must only be touched under `with self._cond` or inside a
    # `_locked`-suffix method
    GUARDED_BY = ("_q", "_rows_queued", "_closed", "_shed_times",
                  "_draining", "_inflight_rows")

    def __init__(self, ladder: PlanLadder,
                 config: Optional[ServeConfig] = None,
                 start: bool = True):
        self._ladder = ladder
        self._cfg = config if config is not None else ServeConfig()
        self._controller = LoadController(len(ladder.rungs), self._cfg)
        self._q: deque = deque()
        self._rows_queued = 0
        self._cond = threading.Condition()
        self._closed = False
        self._draining = False
        self._inflight_rows = 0
        self._thread: Optional[threading.Thread] = None
        self._shed_times: deque = deque()
        # watchdog helper (dispatcher-thread-only state, like the
        # LoadController: no lock because there is no sharing)
        self._worker: Optional[_DispatchWorker] = None
        # quality observability (ISSUE 11): None until enable_quality
        # attaches a monitor — with sampling off the hot path reads
        # exactly this one flag; _quality_src/_quality_meta carry the
        # mutable-epoch / family / metric context from_index learned
        self._quality = None
        self._quality_src = None
        self._quality_meta: dict = {}
        # resource profiler attribution tag (ISSUE 14): the fleet tier
        # names its replicas here so sampled device time folds into
        # router.report() per replica; dispatcher-thread-only read,
        # single plain-attr write at attach — no lock needed
        self._profile_tag = "server"
        obs.gauge("raft.serve.queue.max").set(self._cfg.max_queue)
        obs.gauge("raft.serve.queue.depth").set(0)
        obs.gauge("raft.serve.shed.rate").set(0.0)
        if start:
            self.start()

    @classmethod
    def from_index(cls, index, rep_queries, k: int, params=None,
                   config: Optional[ServeConfig] = None,
                   start: bool = True) -> "SearchServer":
        """Build + pre-warm the (shape × rung) plan ladder for
        ``index`` and start serving. ``rep_queries`` is the
        representative cap-measurement sample (same contract as
        ``plan.build_plan``). A :class:`raft_tpu.mutate.MutableIndex`
        is accepted too: its (shape × rung × delta-rung) grid is
        pre-warmed instead and the server keeps serving through every
        background compaction (the ladder handles re-resolve to the
        live epoch per call)."""
        config = config if config is not None else ServeConfig()
        from raft_tpu.mutate import MutableIndex, build_serve_ladder
        meta = {"metric": getattr(index, "metric", None)}
        if isinstance(index, MutableIndex):
            meta["family"] = index.family
            expects(k == index.k,
                    "serve.from_index: k=%d != MutableIndex k=%d "
                    "(fixed at its construction)", k, index.k)
            expects(params is None,
                    "serve.from_index: a MutableIndex carries its own "
                    "search params (set them at its construction)")
            ladder = build_serve_ladder(
                index, rep_queries, shapes=config.batch_sizes,
                probes_ladder=config.probes_ladder,
                prewarm=config.prewarm)
        else:
            # same resolver PlanLadder.build uses — an unsupported
            # index fails identically either way, so no guard needed
            from raft_tpu.neighbors import plan as plan_mod
            from raft_tpu.neighbors.tiered import TieredIndex
            if isinstance(index, TieredIndex):
                meta["family"] = "tiered_ivf_flat"
            else:
                meta["family"], _ = plan_mod._resolve_builder(index)
            ladder = PlanLadder.build(index, rep_queries, k, params,
                                      shapes=config.batch_sizes,
                                      probes_ladder=config.probes_ladder,
                                      prewarm=config.prewarm)
        srv = cls(ladder, config, start=start)
        srv._quality_meta = meta
        if isinstance(index, MutableIndex):
            srv._quality_src = index
        return srv

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "SearchServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="raft-serve-batcher")
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop admitting, fail everything still queued with
        :class:`RejectedError`, and join the dispatcher."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self._worker is not None:
            self._worker.stop()
            self._worker = None
        if self._quality is not None:
            self._quality.close()
        # a never-started server still owes its queue explicit errors
        self._drain_closed()

    def __enter__(self) -> "SearchServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def closed(self) -> bool:
        # benign racy read: a bool snapshot for status endpoints; the
        # admission decision re-checks under the lock in submit()
        return self._closed  # graftlint: disable=GL003

    @property
    def ladder(self) -> PlanLadder:
        return self._ladder

    @property
    def config(self) -> ServeConfig:
        return self._cfg

    # -- quality observability (ISSUE 11) ----------------------------------
    @property
    def quality(self):
        """The attached :class:`raft_tpu.obs.quality.QualityMonitor`
        (None while sampling is off)."""
        return self._quality

    def enable_quality(self, corpus, ids=None, metric=None,
                       estimator=None, qconfig=None, family=None):
        """Attach shadow-exact recall estimation: live queries are
        reservoir-sampled at ``ServeConfig.quality_sample_rate`` and
        replayed off the serving path through a pre-warmed exact
        scorer over ``corpus`` (the index's rows — or a representative
        bounded sample; ``raft_tpu.obs.quality`` docstring for the
        sampled-corpus caveat). Returns the monitor, or None when the
        configured rate is 0 (nothing is constructed — the hot path
        stays at one flag read). For a mutable index the compaction
        epoch listener is wired automatically, so recall is tracked
        per epoch and ``raft.obs.quality.drift`` fires on a degrading
        fold."""
        rate = self._cfg.quality_sample_rate
        if rate <= 0:
            get_logger("serve").info(
                "enable_quality: quality_sample_rate=0 — no monitor "
                "attached (set it on ServeConfig to sample)")
            return None
        from raft_tpu.obs import quality as _quality
        metric = metric if metric is not None \
            else self._quality_meta.get("metric")
        kwargs = {} if metric is None else {"metric": metric}
        qcfg = qconfig if qconfig is not None \
            else _quality.QualityConfig()
        scorer = _quality.ExactScorer(
            corpus, ids=ids, kmax=self._ladder.k,
            max_rows=qcfg.max_rows, chunk=qcfg.chunk,
            batch=qcfg.shadow_batch, seed=qcfg.seed, **kwargs)
        monitor = _quality.QualityMonitor(
            scorer, sample_rate=rate, config=qcfg,
            family=(family if family is not None
                    else self._quality_meta.get("family", "index")),
            estimator=estimator)
        return self.attach_quality(monitor)

    def attach_quality(self, monitor):
        """Attach an already-built monitor (tests inject fakes). Wires
        the mutable-epoch listener when the server fronts a
        :class:`~raft_tpu.mutate.MutableIndex`."""
        src = self._quality_src
        if src is not None:
            src.add_epoch_listener(monitor.note_epoch)
        self._quality = monitor
        return monitor

    def set_profile_tag(self, tag: str) -> None:
        """Name this server's sampled dispatches in the resource
        profiler's per-tag ledger (``raft_tpu.obs.profiler`` —
        :class:`~raft_tpu.fleet.Replica` passes its replica name so
        fleet utilization is attributable per replica)."""
        self._profile_tag = str(tag)

    def _quality_epoch(self) -> int:
        src = self._quality_src
        return int(src.epoch) if src is not None else 0

    def _quality_detail(self) -> str:
        """Shard attribution for coverage-flagged samples — the
        distributed tier returns its current exclusion so a degraded
        recall series names the missing shards."""
        return ""

    # -- admission ---------------------------------------------------------
    def submit(self, queries, k: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               trace_context: Optional[str] = None):
        """Enqueue one request → ``Future`` resolving to ``(dists,
        ids)``, each ``(nq, k)`` numpy arrays. Admission is decided NOW:
        a full queue or a closed server fails the future immediately
        with :class:`RejectedError` (explicit backpressure, never
        unbounded growth).

        ``trace_context`` is an optional ``traceparent`` value; when
        omitted it defaults to the caller thread's innermost open span
        (so a submit made under a router's ``raft.fleet.route`` span —
        or any other span — automatically parents this request's
        ``raft.serve.request`` root, which otherwise opens on the
        dispatcher thread with no trace of its own)."""
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        expects(q.ndim == 2 and q.shape[1] == self._ladder.dim,
                "serve.submit: queries must be (nq, dim=%d), got %s",
                self._ladder.dim, q.shape)
        nq = int(q.shape[0])
        expects(0 < nq <= self._ladder.max_shape,
                "serve.submit: nq=%d exceeds the largest ladder shape "
                "%d — split the request or widen the ladder", nq,
                self._ladder.max_shape)
        k = self._ladder.k if k is None else int(k)
        expects(0 < k <= self._ladder.k,
                "serve.submit: k=%d exceeds the plan k=%d", k,
                self._ladder.k)
        if deadline_ms is None:
            deadline_ms = self._cfg.default_deadline_ms
        now = time.perf_counter()
        if trace_context is None:
            trace_context = spans.current_traceparent()
        req = _Request(queries=q, nq=nq, k=k, t_enq=now,
                       deadline=(now + deadline_ms / 1e3
                                 if deadline_ms and deadline_ms > 0
                                 else None),
                       trace_ctx=trace_context)
        obs.counter("raft.serve.requests.total").inc()
        obs.counter("raft.serve.queries.total").inc(nq)
        with self._cond:
            if self._closed:
                self._shed_locked(req, "closed")
                return req.future
            if self._draining:
                # drain() stopped admission (rolling restart, ISSUE 13):
                # the queue flushes, new work goes to another replica
                self._shed_locked(req, "draining")
                return req.future
            if len(self._q) >= self._cfg.max_queue:
                self._shed_locked(req, "queue_full")
                return req.future
            self._q.append(req)
            self._rows_queued += nq
            obs.gauge("raft.serve.queue.depth").set(len(self._q))
            self._cond.notify()
        return req.future

    def search(self, queries, k: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               timeout: Optional[float] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(queries, k, deadline_ms).result(timeout)

    # -- load / drain (ISSUE 13: the fleet tier's per-replica view) --------
    def load(self) -> dict:
        """Cheap load snapshot for routing decisions (the fleet
        router's power-of-two-choices input) and for /debug surfaces:
        queued requests/rows, rows in the batch currently executing,
        the recent shed rate, and the admission state. One lock
        acquisition, no device work, no allocation beyond the dict."""
        with self._cond:
            self._update_shed_rate_locked()
            return {
                "queue_depth": len(self._q),
                "queued_rows": self._rows_queued,
                "inflight_rows": self._inflight_rows,
                "shed_rate": (len(self._shed_times)
                              / _SHED_RATE_WINDOW_S),
                "draining": self._draining,
                "closed": self._closed,
            }

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop admission and flush: new submissions fail NOW with
        :class:`RejectedError` (reason ``draining``) while every
        already-queued request still executes and every outstanding
        future resolves. Returns True once the queue is empty and no
        batch is in flight (False = timed out with work remaining).
        The dispatcher stays alive — :meth:`resume` re-opens admission
        (the rolling-restart rejoin path); :meth:`close` afterwards is
        a clean stop with nothing left to fail."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._q or self._inflight_rows:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(remaining, 0.25))
            return True

    def resume(self) -> None:
        """Re-open admission after :meth:`drain` (rolling-restart
        rejoin)."""
        with self._cond:
            self._draining = False
            self._cond.notify_all()

    # -- internals ---------------------------------------------------------
    def _shed_locked(self, req: _Request, reason: str) -> None:
        """Refuse admission (called under the queue lock). Counted AND
        span-attributed — the shed decision must be visible in both
        observability planes."""
        obs.counter("raft.serve.shed.total", reason=reason).inc()
        self._shed_times.append(time.monotonic())
        self._update_shed_rate_locked()
        with spans.span("raft.serve.request",
                        remote_parent=req.trace_ctx,
                        nq=req.nq, k=req.k,
                        outcome="shed", reason=reason):
            pass
        req.future.set_exception(RejectedError(
            f"request rejected ({reason}): queue depth "
            f"{len(self._q)}/{self._cfg.max_queue}"))

    def _update_shed_rate_locked(self) -> None:
        now = time.monotonic()
        while self._shed_times and now - self._shed_times[0] > \
                _SHED_RATE_WINDOW_S:
            self._shed_times.popleft()
        obs.gauge("raft.serve.shed.rate").set(
            len(self._shed_times) / _SHED_RATE_WINDOW_S)

    def _drain_closed(self) -> None:
        with self._cond:
            pending = list(self._q)
            self._q.clear()
            self._rows_queued = 0
            obs.gauge("raft.serve.queue.depth").set(0)
        for r in pending:
            if not r.future.done():
                obs.counter("raft.serve.shed.total", reason="closed").inc()
                r.future.set_exception(
                    RejectedError("server closed while queued"))

    def _fail_deadline(self, req: _Request, now: float) -> None:
        waited_ms = round((now - req.t_enq) * 1e3, 3)
        obs.counter("raft.serve.deadline.total").inc()
        with spans.span("raft.serve.request",
                        remote_parent=req.trace_ctx,
                        nq=req.nq, k=req.k,
                        outcome="deadline", waited_ms=waited_ms):
            spans.add_child_span("raft.serve.queue_wait", req.t_enq,
                                 now - req.t_enq)
        req.future.set_exception(DeadlineExceeded(
            f"deadline expired after {waited_ms} ms in queue"))

    def _take_batch_locked(self):
        """Pop whole requests up to the largest shape, dropping expired
        ones without letting them occupy a slot."""
        now = time.perf_counter()
        max_shape = self._ladder.max_shape
        batch, rows, expired = [], 0, []
        while self._q:
            r = self._q[0]
            if r.deadline is not None and now >= r.deadline:
                self._q.popleft()
                self._rows_queued -= r.nq
                expired.append(r)
                continue
            if batch and rows + r.nq > max_shape:
                break
            self._q.popleft()
            self._rows_queued -= r.nq
            batch.append(r)
            rows += r.nq
        depth = len(self._q)
        obs.gauge("raft.serve.queue.depth").set(depth)
        return batch, rows, expired, depth, now

    def _loop(self) -> None:
        cfg = self._cfg
        idle_s = max(cfg.degrade_cooldown_ms / 1e3, 0.02)
        wait_s = cfg.max_wait_ms / 1e3
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    if not self._cond.wait(timeout=idle_s):
                        # idle tick: the ladder steps back toward full
                        # quality, the overload verdict clears, the
                        # shed-rate window decays
                        self._controller.observe(0.0, 0)
                        self._update_shed_rate_locked()
                if self._closed:
                    break
                # batching window: let the head-of-line request wait up
                # to max_wait_ms for a fuller batch (or until the
                # largest shape is already covered)
                head_t = self._q[0].t_enq
                while (self._rows_queued < self._ladder.max_shape
                       and not self._closed and self._q):
                    remaining = wait_s - (time.perf_counter() - head_t)
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                if self._closed:
                    break
                batch, rows, expired, depth, now = \
                    self._take_batch_locked()
                self._inflight_rows = rows
            for r in expired:
                self._fail_deadline(r, now)
            if batch:
                # dispatcher crash guard (mirrors the compactor's,
                # ISSUE 10): one broken batch fails ITS futures with a
                # typed error; the dispatcher thread keeps serving —
                # previously any exception escaping _execute killed the
                # thread and hung every future behind it
                try:
                    self._execute(batch, rows, depth)
                except Exception as e:
                    obs.counter("raft.serve.dispatcher.errors").inc()
                    get_logger("serve").error(
                        "dispatcher: batch failed outside the dispatch "
                        "path (crash guard): %r", e)
                    err = (e if isinstance(e, DispatchError) else
                           DispatchError(f"dispatcher error: {e!r}"))
                    for r in batch:
                        if not r.future.done():
                            r.future.set_exception(err)
            with self._cond:
                # batch finished (or there was none): a drain() waiter
                # watches this reach zero together with an empty queue
                self._inflight_rows = 0
                self._cond.notify_all()
        self._drain_closed()

    # -- dispatch hooks (overridden by the distributed tier) ---------------
    def _plan_for_batch(self, rows: int, level: int):
        """(shape, plan) for a coalesced batch — the failover-aware
        distributed tier reroutes this to the partial-mesh ladder while
        shards are excluded."""
        return self._ladder.plan_for(rows, level)

    def _plan_after_failure(self, shape: int, level: int, err):
        """A replacement plan for the next attempt after a
        :class:`ShardFailedError` (the distributed tier returns its
        pre-warmed partial-mesh plan once suspects are known); None =
        retry the same plan."""
        return None

    def _watchdog_call(self, fn, timeout_s: float):
        if self._worker is None:
            self._worker = _DispatchWorker("raft-serve-watchdog")
        box = self._worker.submit(fn)
        if not box["done"].wait(timeout_s):
            # a hung XLA dispatch cannot be cancelled — orphan the
            # helper (it exits once its stuck program drains) and turn
            # the hang into a typed, retryable failure
            self._worker.abandoned.set()
            self._worker = None
            obs.counter("raft.serve.dispatch.timeouts.total").inc()
            raise ShardFailedError(
                f"dispatch exceeded dispatch_timeout_ms="
                f"{self._cfg.dispatch_timeout_ms:g}")
        if box["err"] is not None:
            raise box["err"]
        return box["out"]

    def _dispatch(self, plan, qb):
        """One plan execution with the failure conversions applied:
        a watchdog timeout and a comms ``ABORT``/``ERROR`` status both
        become :class:`ShardFailedError` — typed and retryable —
        instead of a silent hang or a bare exception that could kill
        the dispatcher thread."""
        def call():
            faults.inject("serve.execute", shape=plan.nq)
            return plan.search(qb, block=True)

        timeout_s = self._cfg.dispatch_timeout_ms / 1e3
        out = (self._watchdog_call(call, timeout_s) if timeout_s > 0
               else call())
        if not (isinstance(out, tuple) and len(out) == 2):
            # a comms-aware plan may surface sync_stream's verdict as a
            # Status instead of results (duck-typed — no comms import
            # on the serving path)
            status = getattr(out, "name", None) or repr(out)
            raise ShardFailedError(
                f"dispatch reported comms status {status}",
                ranks=getattr(out, "ranks", ()))
        return out

    def _execute(self, batch, rows: int, depth: int) -> None:
        cfg = self._cfg
        # profiler attribution: tag this dispatcher thread so a sampled
        # dispatch inside plan.search lands in this server's (replica's)
        # per-tag window — one None read when profiling is off
        profiler.tag_dispatch(self._profile_tag)
        t_start = time.perf_counter()
        head_wait = t_start - min(r.t_enq for r in batch)
        level = self._controller.observe(head_wait, depth)
        shape, plan = self._plan_for_batch(rows, level)
        qb = (batch[0].queries if len(batch) == 1
              else np.concatenate([r.queries for r in batch], axis=0))
        pad = shape - rows
        if pad:
            # duplicated-REAL-row padding (the pad_partial rule of
            # ann_types.batched_search): repeated real rows stay
            # in-distribution for the measured probe cap; their result
            # rows are sliced off before scatter
            obs.counter("raft.serve.batch.padded_rows").inc(pad)
            reps = -(-pad // rows)
            qb = np.concatenate([qb, np.tile(qb, (reps, 1))[:pad]],
                                axis=0)
        err = None
        dead: set = set()       # ids of requests failed during backoff
        attempt = 0
        with spans.span("raft.serve.batch", shape=shape, rows=rows,
                        requests=len(batch),
                        occupancy=round(rows / shape, 4),
                        n_probes=plan.n_probes, level=level) as bsp:
            for idx, r in enumerate(batch):
                spans.add_child_span("raft.serve.queue_wait", r.t_enq,
                                     t_start - r.t_enq, request=idx,
                                     rows=r.nq)
            while True:
                with spans.span("raft.serve.execute", shape=shape,
                                n_probes=plan.n_probes,
                                attempt=attempt):
                    try:
                        d, i = self._dispatch(plan, qb)
                        d, i = np.asarray(d), np.asarray(i)
                        err = None
                    except ShardFailedError as e:   # retryable
                        err = e
                    except Exception as e:  # scatter as-is, keep serving
                        err = e
                        bsp.set_attr("error", type(e).__name__)
                        break
                if err is None:
                    if attempt:
                        obs.counter("raft.serve.retry.success.total").inc()
                    break
                bsp.set_attr("error", type(err).__name__)
                # the failover-aware tier may hand back a degraded plan
                # for the next attempt (pre-warmed — never compiled on
                # the failure path)
                nxt = self._plan_after_failure(shape, level, err)
                if nxt is not None:
                    plan = nxt
                if attempt >= cfg.max_retries:
                    obs.counter("raft.serve.retry.exhausted.total").inc()
                    break
                attempt += 1
                backoff = (cfg.retry_backoff_ms / 1e3
                           * cfg.retry_backoff_mult ** (attempt - 1))
                # deadline-aware: a request whose deadline lands inside
                # the backoff window fails NOW with DeadlineExceeded —
                # a retry must never resolve after the caller stopped
                # waiting
                now = time.perf_counter()
                for r in batch:
                    if (id(r) not in dead and r.deadline is not None
                            and r.deadline <= now + backoff):
                        dead.add(id(r))
                        self._fail_deadline(r, now)
                if len(dead) == len(batch):
                    break       # nobody left waiting for the retry
                obs.counter("raft.serve.retry.total").inc()
                with spans.span("raft.serve.retry", attempt=attempt,
                                backoff_ms=round(backoff * 1e3, 3),
                                error=type(err).__name__):
                    if backoff > 0:
                        time.sleep(backoff)
            if attempt:
                bsp.set_attr("retries", attempt)
        t_done = time.perf_counter()
        exec_dur = t_done - t_start
        obs.counter("raft.serve.batch.total", level=level).inc()
        obs.counter("raft.serve.batch.rows").inc(rows)
        obs.counter("raft.serve.batch.slots").inc(shape)
        obs.histogram("raft.serve.batch.size",
                      buckets=obs.SIZE_BUCKETS).observe(rows)
        obs.histogram("raft.serve.batch.occupancy",
                      buckets=OCCUPANCY_BUCKETS).observe(rows / shape)
        partial = bool(getattr(plan, "partial", False))
        coverage = float(getattr(plan, "coverage", 1.0))
        # quality sampling (ISSUE 11): ONE flag read per batch — None
        # means sampling is off and nothing below allocates or runs
        qm = self._quality
        if qm is not None and err is None:
            q_epoch = self._quality_epoch()
            q_excl = self._quality_detail() if partial else ""
        off = 0
        for r in batch:
            if id(r) in dead:   # already failed with DeadlineExceeded
                off += r.nq
                continue
            wait_s = t_start - r.t_enq
            obs.histogram("raft.serve.queue.delay.seconds",
                          buckets=SERVE_LATENCY_BUCKETS).observe(wait_s)
            if err is not None:
                obs.counter("raft.serve.errors.total").inc()
                r.future.set_exception(err)
                continue
            d_r = d[off:off + r.nq, :r.k].copy()
            i_r = i[off:off + r.nq, :r.k].copy()
            off += r.nq
            lat = t_done - r.t_enq
            obs.histogram("raft.serve.request.seconds",
                          buckets=SERVE_LATENCY_BUCKETS).observe(lat)
            obs.counter("raft.serve.completed.total").inc()
            if partial:
                obs.counter("raft.serve.failover.partial.total").inc()
            # per-request root trace: queue-wait + (shared) execution
            # children under one raft.serve.request root — the flight
            # recorder shows each caller's story, batch sharing included
            with spans.span("raft.serve.request",
                            remote_parent=r.trace_ctx,
                            nq=r.nq, k=r.k,
                            outcome="partial" if partial else "ok",
                            level=level, batch_shape=shape,
                            latency_ms=round(lat * 1e3, 3)):
                spans.add_child_span("raft.serve.queue_wait", r.t_enq,
                                     wait_s)
                spans.add_child_span("raft.serve.execute", t_start,
                                     exec_dur, shape=shape,
                                     shared=len(batch) > 1)
            r.future.set_result(
                SearchResult(d_r, i_r, partial=True, coverage=coverage)
                if partial else (d_r, i_r))
            if qm is not None:
                # shadow-exact sampling: a Bernoulli draw + bounded
                # copy on this thread; the exact replay happens on the
                # monitor's background thread, never in a batch slot
                qm.offer(r.queries, i_r, r.k, epoch=q_epoch,
                         coverage=coverage, excluded=q_excl)
