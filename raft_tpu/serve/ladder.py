"""The plan-shape ladder: pre-warmed fixed-shape SearchPlans.

A serving batch must execute at one of a few compiled shapes — the
continuous-batching contract of every TPU inference runtime (Ragged
Paged Attention, arxiv 2604.15464, makes the same move for attention):
a small ladder of nq values covers any occupancy with bounded padding
waste, and every rung is AOT-compiled (``neighbors/plan.py``) before
traffic arrives, so steady-state serving performs ZERO compiles.

The ladder is two-dimensional: ``shapes`` (batch nq, ascending) ×
``rungs`` (``n_probes`` per degradation level, descending — rung 0 is
full quality). The load controller picks the rung; the batcher picks
the smallest shape that fits the coalesced rows.

The ladder holds DIRECT references to its plans: the LRU bound on
``index.plan_cache`` (``RAFT_TPU_PLAN_CACHE_MAX``) can evict the cache
entries without invalidating a running server.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import numpy as np

from raft_tpu.core.error import expects

__all__ = ["PlanLadder"]

# Compile-surface rung declarations (graftlint GL012–GL014,
# docs/static_analysis.md "The compile-surface manifest"): every
# dimension a serving-path compile key may draw from, with the grid it
# is bounded by.  A set name DIFFERENT from the dim name declares a
# pre-warmed grid (GL013 requires a warmup loop over it); values are
# the statically-known default grid, None when config-supplied.
COMPILE_SURFACE_RUNGS = {
    "nq": ("shapes", (1, 8, 32, 128),
           "PlanLadder batch shapes — the smallest shape that fits "
           "the coalesced rows serves the batch; one compiled "
           "program per shape"),
    "n_probes": ("rungs", None,
                 "the n_probes degradation ladder (rung 0 = full "
                 "quality); config-supplied via probes_ladder"),
    "rung": ("rungs", None,
             "a rung INDEX into the degradation ladder"),
}


class PlanLadder:
    """(shape, rung) → a plan-like object with ``.search(q, block=)``,
    ``.nq`` and ``.n_probes``. Build real ladders via :meth:`build`;
    tests may construct one directly from fake plans."""

    def __init__(self, shapes: Tuple[int, ...], rungs: Tuple[int, ...],
                 plans: Dict[Tuple[int, int], object], dim: int, k: int):
        expects(len(shapes) > 0 and len(rungs) > 0,
                "PlanLadder: need at least one shape and one rung")
        expects(list(shapes) == sorted(set(shapes)),
                "PlanLadder: shapes must be ascending and distinct")
        for s in shapes:
            for r in range(len(rungs)):
                expects((s, r) in plans,
                        "PlanLadder: missing plan for shape=%d rung=%d",
                        s, r)
        self.shapes = tuple(int(s) for s in shapes)
        self.rungs = tuple(int(r) for r in rungs)
        self.dim = int(dim)
        self.k = int(k)
        self._plans = dict(plans)

    @property
    def max_shape(self) -> int:
        return self.shapes[-1]

    def plan_for(self, rows: int, rung: int):
        """The smallest-shape plan that fits ``rows`` at ``rung`` →
        ``(shape, plan)``."""
        expects(0 < rows <= self.max_shape,
                "PlanLadder: %d rows exceed the largest shape %d",
                rows, self.max_shape)
        rung = min(max(rung, 0), len(self.rungs) - 1)
        for s in self.shapes:
            if rows <= s:
                return s, self._plans[(s, rung)]
        raise AssertionError("unreachable")  # guarded by expects above

    @classmethod
    def build(cls, index, rep_queries, k: int, params=None,
              shapes: Tuple[int, ...] = (1, 8, 32, 128),
              probes_ladder: Tuple[int, ...] = (),
              prewarm: bool = True) -> "PlanLadder":
        """AOT-compile the full (shape × rung) grid from one
        representative query batch (the cap-measurement sample —
        docs/performance.md). ``probes_ladder`` empty means a single
        rung at ``params.n_probes``."""
        from raft_tpu.neighbors import plan as plan_mod
        from raft_tpu.neighbors import tiered as tiered_mod

        if isinstance(index, tiered_mod.TieredIndex):
            # the tiered family builds its own (shape × rung) grid of
            # prepared TieredPlans — same ladder contract, pre-warmed
            # over the hot/stage capacity rungs instead of AOT-lowered
            return tiered_mod.build_ladder(
                index, rep_queries, k, params, shapes=shapes,
                probes_ladder=probes_ladder, prewarm=prewarm)
        family, _ = plan_mod._resolve_builder(index)
        if params is None:
            params = plan_mod._default_params(family)
        q = np.asarray(rep_queries, np.float32)
        expects(q.ndim == 2 and q.shape[1] == index.dim,
                "PlanLadder: rep_queries must be (nq, dim=%d), got %s",
                index.dim, q.shape)
        rungs = tuple(probes_ladder) or (min(params.n_probes,
                                             index.n_lists),)
        plans: Dict[Tuple[int, int], object] = {}
        for ri, n_probes in enumerate(rungs):
            p_r = dataclasses.replace(params, n_probes=n_probes)
            for s in shapes:
                reps = -(-s // q.shape[0])
                q_s = np.tile(q, (reps, 1))[:s]
                plans[(s, ri)] = plan_mod.build_plan(index, q_s, k, p_r,
                                                     warm=prewarm)
        return cls(shapes=tuple(shapes), rungs=rungs, plans=plans,
                   dim=index.dim, k=k)
